"""Load-management tests (ISSUE 15): the admission gate, the pressure
ladder and its rung effects, the bounded dispatcher queue, EWMA
latency-targeted micro-batching, streaming backpressure, and the
dead-letter drainer's seeded backoff jitter."""
import json
import os
import socket
import threading
import time
import urllib.error
import urllib.parse
import urllib.request

import pytest

from reporter_tpu.service import admission
from reporter_tpu.service.admission import (AdmissionGate, Overload,
                                            PressureLadder, RUNGS,
                                            WindowedQuantile,
                                            retry_after_s)
from reporter_tpu.service.dispatch import BatchDispatcher
from reporter_tpu.utils import metrics


@pytest.fixture(autouse=True)
def _fresh_admission():
    """Every test starts at pressure zero with no process-wide ladder
    (and leaves none behind for the rest of the suite)."""
    admission._reset_module()
    yield
    admission._reset_module()


class StubDispatcher:
    """Duck-typed dispatcher for gate unit tests."""

    def __init__(self, depth=0, ewma=None, queue_max=0, max_batch=32):
        self.depth = depth
        self.ewma = ewma
        self.queue_max = queue_max
        self.max_batch = max_batch

    def queue_depth(self):
        return self.depth

    def service_ewma_s(self):
        return self.ewma


class TestRetryAfter:
    def test_clamps(self):
        assert retry_after_s(0, None) == 1
        assert retry_after_s(100, None) == 1      # no estimate yet
        assert retry_after_s(10, 0.5) == 5
        assert retry_after_s(1, 0.001) == 1       # floor
        assert retry_after_s(10_000, 1.0) == 30   # cap


class TestWindowedQuantile:
    def test_breach_then_recovery(self):
        """The windowed p99 must FORGET a bad minute — the property the
        lifetime histogram p99 lacks and admission control needs."""
        r = metrics.Registry()
        w = WindowedQuantile(r)
        for _ in range(50):
            r.observe("stage", 0.9)
        p99 = w.update(["stage"])["stage"]
        assert p99 is not None and p99 > 0.5
        # idle window: no new observations -> None, never a breach
        assert w.update(["stage"])["stage"] is None
        # recovery window: fast observations only -> small p99, even
        # though the lifetime histogram still remembers the 0.9s tail
        for _ in range(50):
            r.observe("stage", 0.001)
        p99 = w.update(["stage"])["stage"]
        assert p99 is not None and p99 < 0.01
        lifetime = r.snapshot()["timers"]["stage"]["p99_s"]
        assert lifetime > 0.5  # the contrast the class exists for

    def test_unknown_stage_is_none(self):
        w = WindowedQuantile(metrics.Registry())
        assert w.update(["nope"])["nope"] is None


class TestPressureLadder:
    def test_hysteresis_and_rung_effects(self):
        from reporter_tpu.matcher import batchpad
        from reporter_tpu.matcher import matcher as matcher_mod
        from reporter_tpu.obs import profiler
        clk = [0.0]
        lad = PressureLadder(hold_s=1.0, clock=lambda: clk[0])
        assert lad.observe(True) == 0          # dwell 0 < hold
        clk[0] = 1.0
        assert lad.observe(True) == 1          # held for hold_s
        assert profiler.shadow_stats()["suspended"]
        clk[0] = 1.5
        assert lad.observe(True) == 1          # one rung per hold
        clk[0] = 2.0
        assert lad.observe(True) == 2
        assert not admission.allow_request_trace()
        clk[0] = 3.0
        assert lad.observe(True) == 3
        assert batchpad.bucket_ladder()[1] == 1.0  # splitter off
        clk[0] = 4.0
        assert lad.observe(True) == 4
        assert matcher_mod._pressure_oracle
        clk[0] = 5.0
        assert lad.observe(True) == 4          # capped at the top rung
        # calm: stepping back up needs 2x the hold
        assert lad.observe(False) == 4
        clk[0] = 6.5
        assert lad.observe(False) == 4         # 1.5 < 2.0
        clk[0] = 7.0
        assert lad.observe(False) == 3
        assert not matcher_mod._pressure_oracle   # oracle rung left
        assert batchpad.bucket_ladder()[1] == 1.0  # coarse still held
        clk[0] = 9.0
        assert lad.observe(False) == 2
        assert batchpad.bucket_ladder()[1] != 1.0
        assert not admission.allow_request_trace()  # trace still shed
        clk[0] = 11.0
        assert lad.observe(False) == 1
        assert admission.allow_request_trace()
        assert profiler.shadow_stats()["suspended"]  # last rung held
        clk[0] = 13.0
        assert lad.observe(False) == 0
        assert not profiler.shadow_stats()["suspended"]
        assert not matcher_mod._pressure_oracle
        assert lad.transitions == 8
        snap = lad.snapshot()
        assert snap["state"] == "normal" and snap["rungs"] == list(RUNGS)

    def test_flap_resistance(self):
        """Alternating pressure samples faster than the hold never
        move the ladder."""
        clk = [0.0]
        lad = PressureLadder(hold_s=1.0, clock=lambda: clk[0])
        for i in range(40):
            clk[0] += 0.3
            lad.observe(i % 2 == 0)
        assert lad.level == 0 and lad.transitions == 0


class TestAdmissionGate:
    def _gate(self, dispatcher, **kw):
        clk = kw.pop("clk", [0.0])
        return AdmissionGate(dispatcher, clock=lambda: clk[0], **kw), clk

    def test_queue_hard_bound(self):
        gate, _ = self._gate(StubDispatcher(depth=5, ewma=0.01,
                                            queue_max=5))
        before = metrics.default.counter("admission.shed.queue")
        verdict = gate.admit()
        assert isinstance(verdict, Overload)
        assert verdict.reason == "queue" and verdict.retry_after_s >= 1
        assert metrics.default.counter("admission.shed.queue") \
            == before + 1

    def test_deadline_shed(self, monkeypatch):
        monkeypatch.setenv("REPORTER_TPU_SLO_MS", "service.handle=100")
        # predicted wait 10 * 20ms = 200ms > 0.5 * 100ms budget
        gate, _ = self._gate(StubDispatcher(depth=10, ewma=0.02))
        verdict = gate.admit()
        assert verdict is not None and verdict.reason == "queue"
        # same depth, fast service: 10 * 1ms = 10ms -> admitted
        gate2, _ = self._gate(StubDispatcher(depth=10, ewma=0.001))
        assert gate2.admit() is None
        gate2.release()

    def test_windowed_slo_breach_and_recovery(self, monkeypatch):
        monkeypatch.setenv("REPORTER_TPU_SLO_MS", "service.handle=50")
        reg = metrics.Registry()
        clk = [0.0]
        gate = AdmissionGate(StubDispatcher(), clock=lambda: clk[0],
                             registry=reg)
        for _ in range(30):
            reg.observe("service.handle", 0.5)  # 10x over budget
        clk[0] = 1.0  # past the eval interval -> refresh
        verdict = gate.admit()
        assert verdict is not None and verdict.reason == "slo"
        # load drops: a fast window clears the breach (the lifetime
        # histogram still remembers — the windowed sensor must not)
        for _ in range(30):
            reg.observe("service.handle", 0.001)
        clk[0] = 2.0
        assert gate.admit() is None
        gate.release()

    def test_inflight_cap_and_release(self):
        gate, _ = self._gate(StubDispatcher(), inflight_max=1)
        assert gate.admit() is None
        verdict = gate.admit()
        assert verdict is not None and verdict.reason == "inflight"
        gate.release()
        assert gate.admit() is None
        gate.release()

    def test_snapshot_shape(self):
        gate, _ = self._gate(StubDispatcher(depth=3, ewma=0.004),
                             inflight_max=7)
        snap = gate.snapshot()
        assert snap["armed"] and snap["inflight_max"] == 7
        assert snap["queue_depth"] == 3
        assert set(snap["shed"]) == {"queue", "slo", "inflight"}

    def test_armed_reads_env(self, monkeypatch):
        monkeypatch.delenv("REPORTER_TPU_ADMISSION", raising=False)
        assert not admission.armed()
        monkeypatch.setenv("REPORTER_TPU_ADMISSION", "1")
        assert admission.armed()
        monkeypatch.setenv("REPORTER_TPU_ADMISSION", "off")
        assert not admission.armed()


def _results(batch):
    return [{"ok": True} for _ in batch]


class TestBoundedQueue:
    """Deterministic by construction: a "plug" batch occupies the
    dispatch loop (match_many blocks on an event), so the bounded
    queue can be filled EXACTLY — nothing drains until release."""

    def _plugged_dispatcher(self, **kw):
        release = threading.Event()

        def blocked(batch):
            release.wait(10.0)
            return _results(batch)

        d = BatchDispatcher(blocked, max_batch=2, max_wait_ms=5.0,
                            **kw)
        from reporter_tpu.service.dispatch import _Slot
        d._queue.put(_Slot({"uuid": "plug"}))
        deadline = time.monotonic() + 5.0
        while d._in_service == 0 and time.monotonic() < deadline:
            time.sleep(0.002)
        assert d._in_service == 1  # the loop is busy; fills are exact
        return d, release

    def test_reject_policy_sheds_new(self):
        from reporter_tpu.service.dispatch import _Slot
        d, release = self._plugged_dispatcher(queue_max=2,
                                              queue_policy="reject")
        try:
            fills = [_Slot({"uuid": f"q{i}"}) for i in range(2)]
            for slot in fills:
                d._enqueue_nowait(slot)
            assert d._queue.qsize() == 2
            before = metrics.default.counter("dispatch.queue.rejected")
            with pytest.raises(Overload) as exc:
                d.submit({"uuid": "overflow"}, timeout=1.0)
            assert exc.value.reason == "queue"
            assert exc.value.retry_after_s >= 1
            assert metrics.default.counter("dispatch.queue.rejected") \
                == before + 1
            assert d._queue.qsize() == 2  # the queued work survived
        finally:
            release.set()
            assert d.close()

    def test_oldest_policy_evicts_queued_waiter(self):
        from reporter_tpu.service.dispatch import _Slot
        d, release = self._plugged_dispatcher(queue_max=1,
                                              queue_policy="oldest")
        try:
            oldest = _Slot({"uuid": "old"})
            d._enqueue_nowait(oldest)
            before = metrics.default.counter("dispatch.queue.evicted")
            fresh = _Slot({"uuid": "fresh"})
            d._enqueue_nowait(fresh)  # full -> displaces "old"
            assert metrics.default.counter("dispatch.queue.evicted") \
                == before + 1
            # the displaced waiter was woken LOUDLY with the Overload
            assert oldest.event.is_set()
            assert isinstance(oldest.error, Overload)
            assert oldest.error.reason == "queue"
            assert fresh.error is None  # freshest work won the slot
        finally:
            release.set()
            assert d.close()
        assert fresh.event.wait(5.0)  # drained by close(), not lost
        assert fresh.result is not None

    def test_submit_many_blocking_backpressure(self):
        d, release = self._plugged_dispatcher(queue_max=2)
        try:
            with pytest.raises((Overload, TimeoutError)):
                d.submit_many([{"uuid": f"t{i}"} for i in range(6)],
                              timeout=0.3)
            assert metrics.default.counter("dispatch.queue.waits") >= 1
        finally:
            release.set()
            d.close()

    def test_unbounded_when_zero(self):
        d = BatchDispatcher(_results, max_batch=4, queue_max=0)
        try:
            out = d.submit_many([{"uuid": f"t{i}"} for i in range(64)],
                                timeout=10.0)
            assert len(out) == 64
        finally:
            d.close()


class TestLatencyBudget:
    def test_effective_cap(self):
        d = BatchDispatcher(_results, max_batch=64,
                            latency_budget_ms=100.0)
        try:
            assert d._effective_cap() == 64        # no EWMA yet
            d._ewma_per_trace = 0.01
            assert d._effective_cap() == 10        # 100ms / 10ms
            d._ewma_per_trace = 0.5
            assert d._effective_cap() == 1         # floor: progress
            d._ewma_per_trace = 0.0001
            assert d._effective_cap() == 64        # capped at max_batch
        finally:
            d.close()

    def test_budget_zero_keeps_fixed_batching(self):
        d = BatchDispatcher(_results, max_batch=64,
                            latency_budget_ms=0.0)
        try:
            d._ewma_per_trace = 10.0
            assert d._effective_cap() == 64
        finally:
            d.close()

    def test_ewma_updates_from_batches(self):
        d = BatchDispatcher(_results, max_batch=8)
        try:
            d._note_service_time(0.8, 8)
            first = d.service_ewma_s()
            assert first == pytest.approx(0.1)
            d._note_service_time(0.08, 8)
            assert d.service_ewma_s() < first  # EWMA moved toward fast
        finally:
            d.close()

    def test_batches_shrink_under_budget(self):
        """Integration: with a slow matcher and a budget, drained
        batches stay at the EWMA cap instead of max_batch."""
        sizes = []

        def slow(batch):
            sizes.append(len(batch))
            time.sleep(0.02 * len(batch))
            return _results(batch)

        d = BatchDispatcher(slow, max_batch=32, max_wait_ms=50.0,
                            latency_budget_ms=60.0)
        try:
            d.submit_many([{"uuid": f"w{i}"} for i in range(4)],
                          timeout=10.0)  # warm the EWMA (~20ms/trace)
            d.submit_many([{"uuid": f"t{i}"} for i in range(24)],
                          timeout=30.0)
            # after warm-up the cap is ~60/20 = 3 traces per batch
            assert max(sizes[1:]) <= 8
            assert metrics.default.counter(
                "batch.latency.capped_batches") > 0
        finally:
            d.close()


class TestQueueDepthGauges:
    def test_named_gauges_and_fork_reset(self):
        from reporter_tpu.obs import profiler
        profiler._reset_queue_depths()  # earlier tests' dispatchers
        profiler.note_queue_depth(4, name="svc-a")
        profiler.note_queue_depth(9, name="svc-b")
        assert profiler.queue_depth("svc-a") == 4
        assert profiler.queue_depth() == 9          # max across gauges
        assert profiler.queue_depths() == {"svc-a": 4, "svc-b": 9}
        snap = profiler.snapshot()
        assert snap["queue_depth"] == 9
        assert snap["queue_depths"]["svc-b"] == 9
        # the forksafe hook: a child must not inherit these
        profiler._reset_queue_depths()
        assert profiler.queue_depth() == 0
        assert profiler.queue_depths() == {}

    def test_dispatcher_notes_under_own_name(self):
        from reporter_tpu.obs import profiler
        profiler._reset_queue_depths()
        d = BatchDispatcher(_results, max_batch=4, name="gauge-test")
        try:
            d.submit({"uuid": "x"}, timeout=5.0)
            assert "gauge-test" in profiler.queue_depths()
        finally:
            d.close()


class TestServiceIntegration:
    @pytest.fixture(scope="class")
    def city(self):
        from reporter_tpu.synth import build_grid_city
        return build_grid_city(rows=8, cols=8, spacing_m=200.0, seed=7,
                               service_road_fraction=0.0,
                               internal_fraction=0.0)

    def _request(self, city, seed):
        import numpy as np

        from reporter_tpu.synth import generate_trace
        rng = np.random.default_rng(seed)
        tr = None
        while tr is None:
            tr = generate_trace(city, f"adm-{seed}", rng, noise_m=3.0)
        return {"uuid": tr.uuid, "trace": tr.points,
                "match_options": {"mode": "auto",
                                  "report_levels": [0, 1],
                                  "transition_levels": [0, 1]}}

    def test_armed_service_builds_gate_and_health_blocks(
            self, city, monkeypatch):
        from reporter_tpu.matcher import SegmentMatcher
        from reporter_tpu.service.server import ReporterService
        monkeypatch.setenv("REPORTER_TPU_ADMISSION", "1")
        service = ReporterService(SegmentMatcher(net=city),
                                  threshold_sec=15, max_batch=8)
        try:
            assert service.admission is not None
            code, body = service.handle(self._request(city, 1))
            assert code == 200
            code, body = service.health()
            health = json.loads(body)
            assert health["admission"]["armed"] is True
            assert health["pressure"]["state"] == "normal"
        finally:
            service.dispatcher.close()

    def test_unarmed_service_has_no_gate(self, city, monkeypatch):
        from reporter_tpu.matcher import SegmentMatcher
        from reporter_tpu.service.server import ReporterService
        monkeypatch.delenv("REPORTER_TPU_ADMISSION", raising=False)
        service = ReporterService(SegmentMatcher(net=city),
                                  threshold_sec=15, max_batch=8)
        try:
            assert service.admission is None
            health = json.loads(service.health()[1])
            assert health["admission"] == {"armed": False}
            assert health["pressure"]["level"] == 0
        finally:
            service.dispatcher.close()

    def test_http_429_carries_retry_after(self, city):
        from reporter_tpu.matcher import SegmentMatcher
        from reporter_tpu.service.server import ReporterService, serve

        class AlwaysShed:
            def admit(self):
                metrics.count("admission.shed.queue")
                return Overload("queue", 7.0)

            def release(self):
                pass

            def tick(self):
                pass

            def snapshot(self):
                return {"armed": True}

        service = ReporterService(SegmentMatcher(net=city),
                                  threshold_sec=15, max_batch=8)
        service.admission = AlwaysShed()
        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            port = s.getsockname()[1]
        httpd = serve(service, "127.0.0.1", port)
        try:
            q = urllib.parse.urlencode(
                {"json": json.dumps(self._request(city, 2))})
            with pytest.raises(urllib.error.HTTPError) as exc:
                urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/report?{q}")
            err = exc.value
            assert err.code == 429
            assert err.headers.get("Retry-After") == "7"
            body = json.loads(err.read())
            assert body["error"] == "overloaded"
            assert body["reason"] == "queue"
        finally:
            httpd.shutdown()
            service.dispatcher.close()

    def test_city_routed_requests_hit_their_own_gate(self, city):
        """A ``city=`` request must be shed by THAT city's gate — the
        front-door gate only watches the default dispatcher, and a
        city stack's overload would otherwise never shed at all."""
        from reporter_tpu.matcher import SegmentMatcher
        from reporter_tpu.service.server import ReporterService

        inner = ReporterService(SegmentMatcher(net=city),
                                threshold_sec=15, max_batch=8)

        class ShedGate:
            released = 0

            def admit(self):
                return Overload("queue", 11.0)

            def release(self):
                ShedGate.released += 1

        inner.admission = ShedGate()

        class Entry:
            service = inner

        class FakeRegistry:
            def acquire(self, name):
                assert name == "metro"
                return Entry()

            def release(self, entry):
                pass

        outer = ReporterService(SegmentMatcher(net=city),
                                threshold_sec=15, max_batch=8)
        outer.cities = FakeRegistry()
        try:
            req = dict(self._request(city, 9), city="metro")
            code, body = outer.handle(req)
            assert code == 429
            parsed = json.loads(body)
            assert parsed["reason"] == "queue"
            assert parsed["retry_after_s"] == 11.0
            assert ShedGate.released == 0  # shed never holds a slot
            # and an admitting city gate serves, releasing its slot
            inner.admission = None
            code, _body = outer.handle(req)
            assert code == 200
        finally:
            inner.dispatcher.close()
            outer.dispatcher.close()

    def test_pressure_oracle_rung_serves_identically(self, city):
        from reporter_tpu.matcher import SegmentMatcher
        from reporter_tpu.matcher import matcher as matcher_mod
        matcher = SegmentMatcher(net=city)
        req = self._request(city, 3)
        want = matcher.Match(json.dumps(req))
        before = metrics.default.counter("pressure.oracle_chunks")
        matcher_mod.set_pressure_oracle(True)
        try:
            got = matcher.Match(json.dumps(req))
        finally:
            matcher_mod.set_pressure_oracle(False)
        assert got == want
        assert metrics.default.counter("pressure.oracle_chunks") \
            > before


class TestBackpressure:
    def test_governor_thresholds_and_bounds(self):
        from reporter_tpu.streaming.backpressure import (
            SHED_FACTOR, BackpressureGovernor)
        g = BackpressureGovernor(latency_high_s=0.1, depth_high=4,
                                 max_delay_s=0.05)
        assert g.offer_delay() == 0.0 and not g.should_shed()
        g.note_flush(10, 0.5, 0, 0)          # 50ms/trace: calm
        assert g.offer_delay() == 0.0
        g.ewma_s = 0.2                        # 2x threshold
        assert 0.0 < g.offer_delay() <= 0.05
        assert not g.should_shed()
        g.ewma_s = 0.1 * SHED_FACTOR          # at the shed point
        assert g.offer_delay() == 0.05        # clamped at the bound
        assert g.should_shed()
        g.ewma_s = None
        g.note_flush(1, 0.0, 1, 20)           # depth 20 = 5x threshold
        assert g.should_shed()
        snap = g.snapshot()
        assert snap["shedding"] and snap["requeue_depth"] == 20

    def test_disabled_by_env(self, monkeypatch):
        from reporter_tpu.streaming.backpressure import \
            BackpressureGovernor
        monkeypatch.setenv("REPORTER_TPU_BACKPRESSURE", "0")
        g = BackpressureGovernor(latency_high_s=0.001)
        g.ewma_s = 100.0
        assert g.offer_delay() == 0.0 and not g.should_shed()

    def test_batcher_sheds_report_ready_sessions(self, tmp_path):
        from reporter_tpu.core.types import Point
        from reporter_tpu.streaming.backpressure import \
            BackpressureGovernor
        from reporter_tpu.streaming.batcher import PointBatcher
        g = BackpressureGovernor(latency_high_s=0.001, depth_high=1)
        g.ewma_s = 1.0  # pinned severe pressure
        assert g.should_shed()
        spool = str(tmp_path / ".traces")
        batcher = PointBatcher(lambda body: None, lambda k, s: None,
                               deadletter_dir=spool, governor=g)
        before = metrics.default.counter("backpressure.shed")
        t0 = 1700000000
        for i in range(12):
            batcher.process("veh-1", Point(lat=0.001 * i, lon=0.0,
                                           time=t0 + 30 * i,
                                           accuracy=5.0),
                            (t0 + 30 * i) * 1000)
        assert metrics.default.counter("backpressure.shed") \
            == before + 1
        assert not batcher.pending          # never queued
        # the shed session restarted from scratch (its spooled points
        # are gone; later points opened a fresh, small batch)
        assert len(batcher.store["veh-1"].points) < 10
        files = [f for f in os.listdir(spool) if f.endswith(".json")]
        assert len(files) == 1
        body = json.loads((tmp_path / ".traces" / files[0]).read_text())
        assert body["uuid"] == "veh-1" and len(body["trace"]) >= 10

    def test_requeue_depth_tracked(self):
        from reporter_tpu.streaming.batcher import PointBatcher
        batcher = PointBatcher(lambda body: None, lambda k, s: None,
                               retry_budget=2)
        from reporter_tpu.streaming.batcher import Batch
        from reporter_tpu.core.types import Point
        b = Batch(Point(lat=0.0, lon=0.0, time=1.0, accuracy=5.0))
        batcher.store["veh-2"] = b
        batcher._submit_failed("veh-2", b)
        assert len(batcher._retrying) == 1
        assert batcher.governor.requeue_depth == 0  # fed at flush time
        batcher._flush_due([])
        # empty flush does not feed the governor; simulate the real
        # path: a successful response clears the retry entry
        b.retries = 0
        batcher._retrying.pop("veh-2", None)
        assert len(batcher._retrying) == 0


class TestDrainerJitter:
    def _drainer(self, root, seed):
        from reporter_tpu.streaming.drainer import DeadLetterDrainer
        clk = [100.0]
        d = DeadLetterDrainer(
            str(root), trace_root=str(root / ".traces"),
            submit=lambda body: None,       # always fails -> backoff
            interval_s=0.0, max_attempts=10, base_backoff_s=1.0,
            max_backoff_s=60.0, jitter_seed=seed,
            clock=lambda: clk[0])
        return d, clk

    def _spool_one(self, root):
        from reporter_tpu.utils import spool
        os.makedirs(str(root / ".traces"), exist_ok=True)
        spool.write(str(root / ".traces"), "trace-1-000001.veh.json",
                    json.dumps({"uuid": "veh", "trace": []}))

    def test_deterministic_by_seed(self, tmp_path):
        delays = []
        for sub, seed in (("a", 42), ("b", 42), ("c", 43)):
            root = tmp_path / sub
            root.mkdir()
            self._spool_one(root)
            d, clk = self._drainer(root, seed)
            run = []
            for _ in range(4):
                d.maybe_drain()
                due = next(iter(d._due.values()))
                run.append(round(due - clk[0], 9))
                clk[0] = due + 0.001
            delays.append(run)
        assert delays[0] == delays[1]       # same seed, same schedule
        assert delays[0] != delays[2]       # different seed, different

    def test_jitter_bounds(self, tmp_path):
        self._spool_one(tmp_path)
        d, clk = self._drainer(tmp_path, 7)
        for attempt in range(1, 5):
            d.maybe_drain()
            due = next(iter(d._due.values()))
            base = min(1.0 * 2.0 ** (attempt - 1), 60.0)
            delay = due - clk[0]
            assert base <= delay <= base * 1.25
            clk[0] = due + 0.001

    def test_jitter_off(self, tmp_path):
        from reporter_tpu.streaming.drainer import DeadLetterDrainer
        self._spool_one(tmp_path)
        clk = [0.0]
        d = DeadLetterDrainer(
            str(tmp_path), trace_root=str(tmp_path / ".traces"),
            submit=lambda body: None, interval_s=0.0,
            base_backoff_s=1.0, backoff_jitter=0.0, jitter_seed=1,
            clock=lambda: clk[0])
        d.maybe_drain()
        assert next(iter(d._due.values())) == pytest.approx(1.0)
