"""reporter-lint suite tests: every pass fires on its known-bad fixture,
stays silent on the matching known-good one, the ABI cross-check catches
an injected mismatch against the LIVE pair, and a repo-wide run is clean
against the committed baseline (no new findings, no stale entries).
"""
import os
import re
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURES = os.path.join(REPO, "tests", "lint_fixtures")
sys.path.insert(0, REPO)

from reporter_tpu import analysis                      # noqa: E402
from reporter_tpu.analysis import abi, hotpath, jit_hygiene, locks  # noqa: E402
from reporter_tpu.analysis.core import SourceFile, parse_suppressions  # noqa: E402

LIVE_CPP = os.path.join(REPO, abi.DEFAULT_CPP)
LIVE_PY = os.path.join(REPO, abi.DEFAULT_PY)


def _fixture(name: str, relpath: str) -> SourceFile:
    """Load a fixture under a fake repo-relative path so the passes'
    module-scope filters apply."""
    sf = SourceFile.load(os.path.join(FIXTURES, name), REPO)
    sf.relpath = relpath
    return sf


def _run_pass(pass_mod, name: str, relpath: str):
    sf = _fixture(name, relpath)
    findings = analysis.filter_suppressed(pass_mod.run([sf], REPO), [sf])
    return sf, findings


def _expected_lines(sf: SourceFile, rule: str):
    """Lines whose trailing comment names the rule (fixture convention:
    ``# HP001: why`` / ``# JH001 (x2): why``)."""
    out = {}
    for i, line in enumerate(sf.text.splitlines(), start=1):
        m = re.search(rf"#\s*{rule}(?:\s*\(x(\d+)\))?:", line)
        if m:
            out[i] = int(m.group(1) or 1)
    return out


def _assert_matches_annotations(sf, findings, rules):
    got = {}
    for f in findings:
        got.setdefault(f.rule, {}).setdefault(f.line, 0)
        got[f.rule][f.line] += 1
    for rule in rules:
        assert got.get(rule, {}) == _expected_lines(sf, rule), \
            f"{rule} findings diverge from fixture annotations"


# ---- hot-path purity -------------------------------------------------------

def test_hotpath_fires_on_bad_fixture():
    sf, findings = _run_pass(hotpath, "hotpath_bad.py",
                             "reporter_tpu/matcher/fixture_bad.py")
    _assert_matches_annotations(sf, findings, ("HP001", "HP002", "HP003"))


def test_hotpath_silent_on_good_fixture():
    _, findings = _run_pass(hotpath, "hotpath_good.py",
                            "reporter_tpu/matcher/fixture_good.py")
    assert findings == []


def test_hotpath_scope_is_declared_module_set():
    # the same bad code OUTSIDE the declared hot-path set is not flagged
    _, findings = _run_pass(hotpath, "hotpath_bad.py",
                            "reporter_tpu/tools/fixture_bad.py")
    assert findings == []


# ---- jit hygiene -----------------------------------------------------------

def test_jit_fires_on_bad_fixture():
    sf, findings = _run_pass(jit_hygiene, "jit_bad.py",
                             "reporter_tpu/ops/fixture_bad.py")
    _assert_matches_annotations(sf, findings, ("JH001", "JH002", "JH003"))


def test_jit_silent_on_good_fixture():
    _, findings = _run_pass(jit_hygiene, "jit_good.py",
                            "reporter_tpu/ops/fixture_good.py")
    assert findings == []


def test_jit_reaches_called_helpers():
    # the while-loop branch lives in helper(), reached only through the
    # jitted entry_calls_helper — cross-function reachability must hold
    sf, findings = _run_pass(jit_hygiene, "jit_bad.py",
                             "reporter_tpu/ops/fixture_bad.py")
    helper_line = next(i for i, ln in
                       enumerate(sf.text.splitlines(), start=1)
                       if "while v > 0" in ln)
    assert any(f.rule == "JH003" and f.line == helper_line
               for f in findings)


# ---- lock discipline -------------------------------------------------------

def test_locks_fire_on_bad_fixture():
    sf, findings = _run_pass(locks, "locks_bad.py",
                             "reporter_tpu/streaming/fixture_bad.py")
    _assert_matches_annotations(sf, findings, ("LD001",))


def test_locks_silent_on_good_fixture():
    _, findings = _run_pass(locks, "locks_good.py",
                            "reporter_tpu/streaming/fixture_good.py")
    assert findings == []


# ---- suppressions ----------------------------------------------------------

def test_suppression_comment_silences_rule():
    src = ("def f(rows):\n"
           "    out = []\n"
           "    for r in rows:\n"
           "        out.append({'id': r})  # lint: ignore[HP002]\n"
           "    return out\n")
    import ast
    sf = SourceFile(path="x", relpath="reporter_tpu/matcher/x.py",
                    text=src, tree=ast.parse(src),
                    suppressions=parse_suppressions(src))
    findings = analysis.filter_suppressed(hotpath.run([sf], REPO), [sf])
    assert findings == []
    # without the suppression the same code fires
    bare = src.replace("  # lint: ignore[HP002]", "")
    sf2 = SourceFile(path="x", relpath="reporter_tpu/matcher/x.py",
                     text=bare, tree=ast.parse(bare),
                     suppressions=parse_suppressions(bare))
    assert any(f.rule == "HP002" for f in hotpath.run([sf2], REPO))


# ---- ABI cross-check -------------------------------------------------------

def _read(path):
    with open(path, encoding="utf-8") as f:
        return f.read()


def test_abi_good_fixture_pair_is_clean():
    findings = abi.check(_read(os.path.join(FIXTURES, "abi_good.cpp")),
                         _read(os.path.join(FIXTURES, "abi_good.py")),
                         "abi_good.cpp", "abi_good.py")
    assert findings == []


def test_abi_bad_fixture_catches_every_drift_class():
    findings = abi.check(_read(os.path.join(FIXTURES, "abi_good.cpp")),
                         _read(os.path.join(FIXTURES, "abi_bad.py")),
                         "abi_good.cpp", "abi_bad.py")
    rules = {f.rule for f in findings}
    assert rules == {"ABI001", "ABI002", "ABI003", "ABI004", "ABI005"}


def test_abi_live_pair_validates_at_version_11():
    cpp = _read(LIVE_CPP)
    exports, version = abi.parse_cpp(cpp)
    assert version == 11
    assert "rt_prepare_batch" in exports and "rt_assemble_batch" in exports
    findings = abi.check(cpp, _read(LIVE_PY))
    assert findings == [], [f.render() for f in findings]


def test_abi_injected_argtypes_mismatch_is_caught(tmp_path):
    """Satellite contract: inject a deliberate argtypes mismatch into a
    fixture COPY of the live binding and assert the checker fails it."""
    live = _read(LIVE_PY)
    # rt_route_matrices binds T as c_int64; narrow it to c_int32
    target = ("lib.rt_route_matrices.argtypes = [\n"
              "            ctypes.c_void_p, ctypes.c_int64,")
    assert target in live, "live binding drifted; update the injection"
    mutated = live.replace(
        target, target.replace("c_int64", "c_int32"), 1)
    bad_py = tmp_path / "native_init_mutated.py"
    bad_py.write_text(mutated, encoding="utf-8")
    findings = abi.run_paths(LIVE_CPP, str(bad_py),
                             abi.DEFAULT_CPP, "native_init_mutated.py")
    assert any(f.rule == "ABI003" and "rt_route_matrices" in f.message
               and "i32" in f.message for f in findings), \
        [f.render() for f in findings]


def test_abi_version_bump_is_caught(tmp_path):
    live = _read(LIVE_PY)
    mutated = re.sub(r"^ABI_VERSION = \d+", "ABI_VERSION = 999", live,
                     count=1, flags=re.MULTILINE)
    assert mutated != live
    bad_py = tmp_path / "native_init_ver.py"
    bad_py.write_text(mutated, encoding="utf-8")
    findings = abi.run_paths(LIVE_CPP, str(bad_py),
                             abi.DEFAULT_CPP, "native_init_ver.py")
    assert any(f.rule == "ABI004" for f in findings)


# ---- the driver ------------------------------------------------------------

def _lint(*args):
    return subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "lint.py"), *args],
        capture_output=True, text=True, cwd=REPO)


def test_repo_wide_run_is_clean_against_committed_baseline():
    """Acceptance gate: `python tools/lint.py` exits 0 — no new findings,
    no stale baseline entries."""
    proc = _lint()
    assert proc.returncode == 0, proc.stdout + proc.stderr


def test_abi_only_guard_passes_on_live_pair_and_fails_on_mismatch(tmp_path):
    assert _lint("--abi-only").returncode == 0
    mutated = _read(LIVE_PY).replace("ctypes.c_double, c_f32p]",
                                     "ctypes.c_double, c_f64p]", 1)
    bad_py = tmp_path / "native_guard.py"
    bad_py.write_text(mutated, encoding="utf-8")
    proc = _lint("--abi-only", "--abi-py", str(bad_py))
    assert proc.returncode == 1
    assert "ABI003" in proc.stdout


def test_stale_baseline_entry_fails_the_run(tmp_path):
    stale = tmp_path / "baseline.txt"
    stale.write_text("reporter_tpu/matcher/matcher.py:1: HP001 ghost\n",
                     encoding="utf-8")
    proc = _lint("--baseline", str(stale))
    assert proc.returncode == 1
    assert "stale baseline entry" in proc.stdout


def test_partial_run_does_not_report_unrelated_baseline_as_stale(tmp_path):
    # an entry for a file OUTSIDE the requested paths legitimately does
    # not fire on a partial run — it must not be called stale
    base = tmp_path / "baseline.txt"
    base.write_text("reporter_tpu/service/report.py:1: HP001 ghost\n",
                    encoding="utf-8")
    proc = _lint("reporter_tpu/matcher/matcher.py",
                 "--baseline", str(base))
    assert proc.returncode == 0, proc.stdout + proc.stderr
    # and --write-baseline refuses a partial run outright
    proc = _lint("reporter_tpu/matcher/matcher.py", "--write-baseline",
                 "--baseline", str(base))
    assert proc.returncode == 2


def test_jit_positional_dtype_not_flagged():
    import ast
    src = ("import jax\nimport jax.numpy as jnp\n"
           "@jax.jit\n"
           "def f(x):\n"
           "    a = jnp.arange(0, 10, 1, jnp.int32)\n"
           "    b = jnp.zeros(x.shape, jnp.float32)\n"
           "    c = jnp.arange(10)\n"                      # no dtype: flag
           "    return a + b + c\n")
    sf = SourceFile(path="x", relpath="reporter_tpu/ops/x.py", text=src,
                    tree=ast.parse(src), suppressions={})
    findings = jit_hygiene.run([sf], REPO)
    assert [f.line for f in findings if f.rule == "JH002"] == [7]


def test_abi_parses_plain_int_and_typed_pointer_returns():
    cpp = ('extern "C" {\n'
           "int32_t rt_abi_version(void) { return 1; }\n"
           "int rt_plain(int64_t n) { return 0; }\n"
           "double* rt_buf(void* h) { return 0; }\n"
           "}\n")
    exports, version = abi.parse_cpp(cpp)
    assert version == 1
    assert exports["rt_plain"] == (("val", "i32"), [("val", "i64")])
    assert exports["rt_buf"] == (("ptr", "f64"), [("ptr", "void")])
    # an unbound export of either shape raises ABI001, not silence
    py = "ABI_VERSION = 1\n"
    rules = {f.rule for f in abi.check(cpp, py, "c.cpp", "b.py")}
    assert "ABI001" in rules


def test_list_rules_covers_all_four_passes():
    proc = _lint("--list-rules")
    assert proc.returncode == 0
    for rule in ("HP001", "HP002", "HP003", "JH001", "JH002", "JH003",
                 "ABI001", "ABI004", "LD001"):
        assert rule in proc.stdout
