"""reporter-lint suite tests: every pass fires on its known-bad fixture,
stays silent on the matching known-good one, the ABI cross-check catches
an injected mismatch against the LIVE pair, and a repo-wide run is clean
against the committed baseline (no new findings, no stale entries).
"""
import os
import re
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURES = os.path.join(REPO, "tests", "lint_fixtures")
sys.path.insert(0, REPO)

from reporter_tpu import analysis                      # noqa: E402
from reporter_tpu.analysis import (abi, durability, fallback,  # noqa: E402
                                   fault_coverage, hotpath, jit_hygiene,
                                   lockgraph, locks, placement, registry,
                                   registry_drift, tensorcontract)
from reporter_tpu.analysis.core import SourceFile, parse_suppressions  # noqa: E402

LIVE_CPP = os.path.join(REPO, abi.DEFAULT_CPP)
LIVE_PY = os.path.join(REPO, abi.DEFAULT_PY)


def _fixture(name: str, relpath: str) -> SourceFile:
    """Load a fixture under a fake repo-relative path so the passes'
    module-scope filters apply."""
    sf = SourceFile.load(os.path.join(FIXTURES, name), REPO)
    sf.relpath = relpath
    return sf


def _run_pass(pass_mod, name: str, relpath: str):
    sf = _fixture(name, relpath)
    findings = analysis.filter_suppressed(pass_mod.run([sf], REPO), [sf])
    return sf, findings


def _expected_lines(sf: SourceFile, rule: str):
    """Lines whose trailing comment names the rule (fixture convention:
    ``# HP001: why`` / ``# JH001 (x2): why``)."""
    out = {}
    for i, line in enumerate(sf.text.splitlines(), start=1):
        m = re.search(rf"#\s*{rule}(?:\s*\(x(\d+)\))?:", line)
        if m:
            out[i] = int(m.group(1) or 1)
    return out


def _assert_matches_annotations(sf, findings, rules):
    got = {}
    for f in findings:
        got.setdefault(f.rule, {}).setdefault(f.line, 0)
        got[f.rule][f.line] += 1
    for rule in rules:
        assert got.get(rule, {}) == _expected_lines(sf, rule), \
            f"{rule} findings diverge from fixture annotations"


# ---- hot-path purity -------------------------------------------------------

def test_hotpath_fires_on_bad_fixture():
    sf, findings = _run_pass(hotpath, "hotpath_bad.py",
                             "reporter_tpu/matcher/fixture_bad.py")
    _assert_matches_annotations(sf, findings, ("HP001", "HP002", "HP003"))


def test_hotpath_silent_on_good_fixture():
    _, findings = _run_pass(hotpath, "hotpath_good.py",
                            "reporter_tpu/matcher/fixture_good.py")
    assert findings == []


def test_hotpath_scope_is_declared_module_set():
    # the same bad code OUTSIDE the declared hot-path set is not flagged
    _, findings = _run_pass(hotpath, "hotpath_bad.py",
                            "reporter_tpu/tools/fixture_bad.py")
    assert findings == []


# ---- jit hygiene -----------------------------------------------------------

def test_jit_fires_on_bad_fixture():
    sf, findings = _run_pass(jit_hygiene, "jit_bad.py",
                             "reporter_tpu/ops/fixture_bad.py")
    _assert_matches_annotations(sf, findings, ("JH001", "JH002", "JH003"))


def test_jit_silent_on_good_fixture():
    _, findings = _run_pass(jit_hygiene, "jit_good.py",
                            "reporter_tpu/ops/fixture_good.py")
    assert findings == []


def test_jit_reaches_called_helpers():
    # the while-loop branch lives in helper(), reached only through the
    # jitted entry_calls_helper — cross-function reachability must hold
    sf, findings = _run_pass(jit_hygiene, "jit_bad.py",
                             "reporter_tpu/ops/fixture_bad.py")
    helper_line = next(i for i, ln in
                       enumerate(sf.text.splitlines(), start=1)
                       if "while v > 0" in ln)
    assert any(f.rule == "JH003" and f.line == helper_line
               for f in findings)


# ---- lock discipline -------------------------------------------------------

def test_locks_fire_on_bad_fixture():
    sf, findings = _run_pass(locks, "locks_bad.py",
                             "reporter_tpu/streaming/fixture_bad.py")
    _assert_matches_annotations(sf, findings, ("LD001",))


def test_locks_silent_on_good_fixture():
    _, findings = _run_pass(locks, "locks_good.py",
                            "reporter_tpu/streaming/fixture_good.py")
    assert findings == []


# ---- suppressions ----------------------------------------------------------

def test_suppression_comment_silences_rule():
    src = ("def f(rows):\n"
           "    out = []\n"
           "    for r in rows:\n"
           "        out.append({'id': r})  # lint: ignore[HP002]\n"
           "    return out\n")
    import ast
    sf = SourceFile(path="x", relpath="reporter_tpu/matcher/x.py",
                    text=src, tree=ast.parse(src),
                    suppressions=parse_suppressions(src))
    findings = analysis.filter_suppressed(hotpath.run([sf], REPO), [sf])
    assert findings == []
    # without the suppression the same code fires
    bare = src.replace("  # lint: ignore[HP002]", "")
    sf2 = SourceFile(path="x", relpath="reporter_tpu/matcher/x.py",
                     text=bare, tree=ast.parse(bare),
                     suppressions=parse_suppressions(bare))
    assert any(f.rule == "HP002" for f in hotpath.run([sf2], REPO))


# ---- durability ------------------------------------------------------------

_DUR_FIXTURE_CONTRACTS = {
    f"reporter_tpu/streaming/fixture_bad.py::{fn}":
        ("punctuate", "commit_epoch")
    for fn in ("commit_before_ack", "commit_without_ack",
               "missing_commit")}
_DUR_GOOD_CONTRACTS = {
    "reporter_tpu/streaming/fixture_good.py::commit_after_ack":
        ("punctuate", "commit_epoch")}


def test_durability_fires_on_bad_fixture():
    sf = _fixture("durability_bad.py",
                  "reporter_tpu/streaming/fixture_bad.py")
    findings = analysis.filter_suppressed(
        durability.run([sf], REPO, modules=(sf.relpath,),
                       contracts=_DUR_FIXTURE_CONTRACTS), [sf])
    _assert_matches_annotations(sf, findings,
                                ("DUR001", "DUR002", "DUR003", "DUR004"))


def test_durability_silent_on_good_fixture():
    sf = _fixture("durability_good.py",
                  "reporter_tpu/streaming/fixture_good.py")
    findings = durability.run([sf], REPO, modules=(sf.relpath,),
                              contracts=_DUR_GOOD_CONTRACTS)
    assert findings == []


def test_durability_scope_is_declared_module_set():
    # the same bad writes OUTSIDE the durable-module set are not flagged
    sf = _fixture("durability_bad.py", "reporter_tpu/tools/fixture.py")
    findings = durability.run([sf], REPO, contracts={})
    assert findings == []


def test_durability_live_flush_contract_holds():
    """The shipped worker._flush_tiles satisfies the epoch-commit
    ordering, and reordering the marker before the egress is caught —
    the ABI live-pair pattern applied to the CFG contract."""
    live = _read(os.path.join(REPO, "reporter_tpu", "streaming",
                              "worker.py"))
    sf = SourceFile.load(
        os.path.join(REPO, "reporter_tpu", "streaming", "worker.py"),
        REPO)
    assert durability.run([sf], REPO) == []
    # mutate a copy: commit the epoch BEFORE punctuate
    target = "written = self.anonymiser.punctuate()"
    assert target in live, "worker flush drifted; update the injection"
    mutated = live.replace(
        target,
        "self.state.commit_epoch(epoch)\n        " + target, 1)
    import ast
    bad = SourceFile(path="x", relpath="reporter_tpu/streaming/worker.py",
                     text=mutated, tree=ast.parse(mutated),
                     suppressions={})
    findings = durability.run([bad], REPO)
    assert any(f.rule == "DUR004" for f in findings), \
        [f.render() for f in findings]


def test_durability_live_modules_are_clean():
    files = [SourceFile.load(os.path.join(REPO, rel), REPO)
             for rel in registry.DURABLE_MODULES]
    findings = analysis.filter_suppressed(
        durability.run(files, REPO), files)
    assert findings == [], [f.render() for f in findings]


# ---- lock graph ------------------------------------------------------------

def test_lockgraph_fires_on_bad_fixture():
    sf, findings = _run_pass(lockgraph, "lockgraph_bad.py",
                             "reporter_tpu/streaming/fixture_bad.py")
    _assert_matches_annotations(sf, findings, ("LD002", "LD003"))


def test_lockgraph_silent_on_good_fixture():
    _, findings = _run_pass(lockgraph, "lockgraph_good.py",
                            "reporter_tpu/streaming/fixture_good.py")
    assert findings == []


def test_lockgraph_native_build_lock_is_the_only_suppression():
    """The live package carries exactly one documented LD003 hold: the
    native once-only build lock (subprocess make + ABI handshake)."""
    files = analysis.collect_py_files(REPO)
    raw = lockgraph.run(files, REPO)
    native = [f for f in raw
              if f.path == "reporter_tpu/native/__init__.py"
              and f.rule == "LD003"]
    assert native, "the build-lock hold disappeared — update the test"
    kept = analysis.filter_suppressed(raw, files)
    assert kept == [], [f.render() for f in kept]


# ---- registry drift --------------------------------------------------------

_FIXTURE_KNOBS = {"REPORTER_TPU_KNOWN": "fixture knob"}
_FIXTURE_METRICS = {"known.metric": "fixture", "family.*": "fixture"}


def _run_registry(name, relpath):
    sf = _fixture(name, relpath)
    findings = analysis.filter_suppressed(
        registry_drift.run([sf], REPO, knobs=_FIXTURE_KNOBS,
                           metrics_reg=_FIXTURE_METRICS,
                           readme_text="", full_scope=False), [sf])
    return sf, findings


def test_registry_drift_fires_on_bad_fixture():
    sf, findings = _run_registry("registry_bad.py",
                                 "reporter_tpu/streaming/fixture_bad.py")
    _assert_matches_annotations(sf, findings, ("KN001", "MT001"))


def test_registry_drift_silent_on_good_fixture():
    _, findings = _run_registry("registry_good.py",
                                "reporter_tpu/streaming/fixture_good.py")
    assert findings == []


def test_registry_dead_knob_and_readme_drift_detected():
    """Full-scope reverse directions against the LIVE tree: dropping a
    knob from a registry copy fires KN001 nowhere but KN002+code drift
    where expected, and an unregistered README row fires KN002."""
    files = analysis.collect_py_files(
        REPO, [os.path.join(REPO, "reporter_tpu"),
               os.path.join(REPO, "tools"),
               os.path.join(REPO, "bench.py")])
    readme = _read(os.path.join(REPO, "README.md"))
    # a registered-but-never-mentioned knob is a dead entry (KN001)
    knobs = dict(registry.ENV_KNOBS, REPORTER_TPU_GHOST="never read")
    findings = registry_drift.run(files, REPO, knobs=knobs,
                                  readme_text=readme)
    assert any(f.rule == "KN001" and "REPORTER_TPU_GHOST" in f.message
               for f in findings)
    assert any(f.rule == "KN002" and "REPORTER_TPU_GHOST" in f.message
               for f in findings)
    # dropping a live knob from the registry: its read sites fire KN001
    # and its README row fires KN002
    knobs = dict(registry.ENV_KNOBS)
    del knobs["REPORTER_TPU_FAULTS"]
    findings = registry_drift.run(files, REPO, knobs=knobs,
                                  readme_text=readme)
    assert any(f.rule == "KN001" and "REPORTER_TPU_FAULTS" in f.message
               for f in findings)
    assert any(f.rule == "KN002" and f.path == "README.md"
               and "REPORTER_TPU_FAULTS" in f.message
               for f in findings)


def test_registry_dead_metric_detected():
    files = analysis.collect_py_files(REPO)
    metrics_reg = dict(registry.METRICS, **{"ghost.metric": "dead"})
    findings = registry_drift.run(files, REPO, metrics_reg=metrics_reg)
    assert any(f.rule == "MT002" and "ghost.metric" in f.message
               for f in findings)


def test_registry_unregistered_live_metric_detected():
    """Dropping a metric from a registry copy makes its live call site
    fire MT001 — the two-sided contract on the real tree."""
    files = analysis.collect_py_files(REPO)
    metrics_reg = dict(registry.METRICS)
    del metrics_reg["egress.deadletter"]
    findings = registry_drift.run(files, REPO, metrics_reg=metrics_reg,
                                  full_scope=False)
    assert any(f.rule == "MT001" and "egress.deadletter" in f.message
               and f.path == "reporter_tpu/streaming/anonymiser.py"
               for f in findings)


def test_readme_knob_table_parser_reads_full_names():
    readme = _read(os.path.join(REPO, "README.md"))
    table = registry_drift.parse_readme_knobs(readme)
    # the five knobs PR 6 closed the drift on are all table rows now
    for name in ("REPORTER_TPU_CHAOS_REQUIRE_NATIVE",
                 "REPORTER_TPU_NUM_PROCESSES",
                 "REPORTER_TPU_PROBE_TRIES",
                 "REPORTER_TPU_PROCESS_ID",
                 "REPORTER_TPU_ROUTE_CACHE_PAIRS"):
        assert name in table, f"{name} missing from README's knob table"


# ---- fault coverage --------------------------------------------------------

_FIXTURE_SITES = {"known.site": "fixture"}


def _run_faultcov(name, relpath):
    sf = _fixture(name, relpath)
    findings = analysis.filter_suppressed(
        fault_coverage.run([sf], REPO, sites=_FIXTURE_SITES,
                           full_scope=False), [sf])
    return sf, findings


def test_faultcov_fires_on_bad_fixture():
    sf, findings = _run_faultcov("faultcov_bad.py",
                                 "reporter_tpu/streaming/fixture_bad.py")
    _assert_matches_annotations(sf, findings, ("FP001",))


def test_faultcov_silent_on_good_fixture():
    _, findings = _run_faultcov("faultcov_good.py",
                                "reporter_tpu/streaming/fixture_good.py")
    assert findings == []


def test_faultcov_registry_mirrors_known_sites():
    import reporter_tpu.utils.faults as faults_mod
    assert set(registry.FAULT_SITES) == set(faults_mod.KNOWN_SITES)


def test_faultcov_live_drift_and_coverage_detected():
    """Against the LIVE tree: an extra registry site fires FP001 (KNOWN_
    SITES drift) + FP002 (no hook) + FP003 (no coverage); removing a
    real site fires FP001 at its call sites."""
    files = analysis.collect_py_files(REPO)
    sites = dict(registry.FAULT_SITES, **{"ghost.site": "nothing"})
    findings = fault_coverage.run(files, REPO, sites=sites)
    rules = {f.rule for f in findings if "ghost.site" in f.message}
    assert rules == {"FP001", "FP002", "FP003"}, \
        [f.render() for f in findings]
    sites = dict(registry.FAULT_SITES)
    del sites["worker.offer"]
    findings = fault_coverage.run(files, REPO, sites=sites)
    assert any(f.rule == "FP001" and "worker.offer" in f.message
               and f.path == "reporter_tpu/streaming/worker.py"
               for f in findings)


def test_faultcov_every_site_is_exercised():
    """FP003's contract directly: every registered site appears in a
    chaos scenario or a fault test (worker.post_egress was the gap this
    pass surfaced; tests/test_faults.py now pins it)."""
    files = analysis.collect_py_files(REPO)
    findings = fault_coverage.run(files, REPO)
    assert [f for f in findings if f.rule == "FP003"] == [], \
        [f.render() for f in findings]


# ---- tensor contracts ------------------------------------------------------

_TC_FIXTURE_CONTRACTS = {
    "reporter_tpu/ops/fixture_bad.py::contracted": "fixture",
    "reporter_tpu/ops/fixture_good.py::contracted": "fixture"}


def _run_tensor(name, relpath):
    sf = _fixture(name, relpath)
    findings = analysis.filter_suppressed(
        tensorcontract.run([sf], REPO, contracts=_TC_FIXTURE_CONTRACTS,
                           full_scope=False), [sf])
    return sf, findings


def test_tensorcontract_fires_on_bad_fixture():
    sf, findings = _run_tensor("tensorcontract_bad.py",
                               "reporter_tpu/ops/fixture_bad.py")
    _assert_matches_annotations(sf, findings, ("TC002", "TC003", "TC004"))


def test_tensorcontract_silent_on_good_fixture():
    _, findings = _run_tensor("tensorcontract_good.py",
                              "reporter_tpu/ops/fixture_good.py")
    assert findings == []


def test_tensorcontract_live_entries_are_all_contracted():
    """TC002 forward on the live tree: every enumerated jit/pallas entry
    has a KERNEL_CONTRACTS row (the acceptance gate's two-sided half
    that needs no eval harness)."""
    files = analysis.collect_py_files(REPO)
    findings = tensorcontract.run(files, REPO, full_scope=False)
    assert [f for f in findings if f.rule == "TC002"] == [], \
        [f.render() for f in findings]


def test_tensorcontract_signature_drift_detected():
    """Live injection: mutate a fresh-signature copy's output dtype
    (f32 -> f64 widening, the HBM-doubling class) — TC001 fires at the
    kernel's def line with the drift spelled out."""
    import copy
    import json
    with open(os.path.join(REPO, "tools", "kernel_contracts.json"),
              encoding="utf-8") as f:
        committed = json.load(f)
    fresh = copy.deepcopy(committed)
    key = "reporter_tpu/ops/route_relax.py::relax_csr"
    fresh["entries"][key]["cases"][0]["outputs"][0][1] = "float64"
    files = analysis.collect_py_files(REPO)
    findings = tensorcontract.run(files, REPO, signatures=fresh)
    assert any(f.rule == "TC001" and key in f.message
               and "float64" in f.message
               and f.path == "reporter_tpu/ops/route_relax.py"
               for f in findings), [f.render() for f in findings]
    # a dropped output is drift too, not silence
    fresh = copy.deepcopy(committed)
    fresh["entries"][key]["cases"][0]["outputs"].pop()
    findings = tensorcontract.run(files, REPO, signatures=fresh)
    assert any(f.rule == "TC001" and "output count" in f.message
               for f in findings)


def test_kernel_contracts_regen_containment():
    """Seed-containment (the LEDGER.jsonl pattern): every committed
    contract entry is contained in a fresh CPU-only regen, so hand
    edits to tools/kernel_contracts.json cannot drift from the live
    kernels — and the regen traces no entry the file lacks."""
    import json
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    fresh = tensorcontract.compute_signatures(REPO)
    with open(os.path.join(REPO, "tools", "kernel_contracts.json"),
              encoding="utf-8") as f:
        committed = json.load(f)
    assert set(committed["entries"]) == set(fresh["entries"])
    for key, entry in committed["entries"].items():
        diff = tensorcontract._diff_entry(entry, fresh["entries"][key])
        assert diff is None, f"{key}: {diff}"
    assert tensorcontract.LAST_EVAL_SECONDS is not None


# ---- placement -------------------------------------------------------------

_DP_ENTRIES = {"kernel_entry"}


def test_placement_fires_on_bad_fixture():
    sf = _fixture("placement_bad.py",
                  "reporter_tpu/matcher/fixture_bad.py")
    findings = analysis.filter_suppressed(placement.run(
        [sf], REPO,
        lanes=("reporter_tpu/matcher/fixture_bad.py::Lane.stage",),
        sync_points=("reporter_tpu/matcher/fixture_bad.py::Lane.drain",),
        entry_names=_DP_ENTRIES, full_scope=False), [sf])
    _assert_matches_annotations(sf, findings, ("DP001", "DP002", "DP003"))


def test_placement_silent_on_good_fixture():
    sf = _fixture("placement_good.py",
                  "reporter_tpu/matcher/fixture_good.py")
    findings = placement.run(
        [sf], REPO,
        lanes=("reporter_tpu/matcher/fixture_good.py::Lane.stage",),
        sync_points=("reporter_tpu/matcher/fixture_good.py::Lane.drain",),
        entry_names=_DP_ENTRIES, full_scope=False)
    assert findings == []


def test_placement_live_lanes_are_disciplined():
    """The declared lanes materialise only through SYNC_POINTS on the
    live tree — the PR 15 fill_prep tail now routes through
    DeferredRoutes.write_back instead of an inline np.asarray."""
    files = analysis.collect_py_files(REPO)
    findings = analysis.filter_suppressed(
        placement.run(files, REPO), files)
    assert findings == [], [f.render() for f in findings]


def test_placement_undeclared_sync_detected():
    """Live injection (the durability-worker pattern): re-introduce the
    inline materialisation this PR removed from fill_prep's synchronous
    tail — DP001 fires at the real line on the route prep lane."""
    import ast as _ast
    live = _read(os.path.join(REPO, "reporter_tpu", "graph",
                              "route_device.py"))
    target = "DeferredRoutes(route, dev_max, B, T).write_back(out)"
    assert target in live, "fill_prep tail drifted; update the injection"
    mutated = live.replace(
        target, 'out["route_m"][:B, :T - 1] = np.asarray(route)', 1)
    bad = SourceFile(path="x",
                     relpath="reporter_tpu/graph/route_device.py",
                     text=mutated, tree=_ast.parse(mutated),
                     suppressions={})
    files = [bad if sf.relpath == bad.relpath else sf
             for sf in analysis.collect_py_files(REPO)]
    findings = placement.run(files, REPO)
    assert any(f.rule == "DP001" and f.path == bad.relpath
               and "'route'" in f.message for f in findings), \
        [f.render() for f in findings]


# ---- fallback parity -------------------------------------------------------

_FB_FIXTURE_PAIRS = {"covered.circuit": {
    "fault_site": "native.prep", "knob": "REPORTER_TPU_NATIVE",
    "parity_test": "tests/test_faults.py::TestDecodeDomain"}}


def test_fallback_fires_on_bad_fixture():
    sf = _fixture("fallback_bad.py",
                  "reporter_tpu/service/fixture_bad.py")
    findings = analysis.filter_suppressed(
        fallback.run([sf], REPO, pairs=_FB_FIXTURE_PAIRS,
                     full_scope=False), [sf])
    _assert_matches_annotations(sf, findings, ("FB001",))


def test_fallback_silent_on_good_fixture():
    sf = _fixture("fallback_good.py",
                  "reporter_tpu/service/fixture_good.py")
    findings = fallback.run([sf], REPO, pairs=_FB_FIXTURE_PAIRS,
                            full_scope=False)
    assert findings == []


def test_fallback_live_pairs_are_fully_proven():
    """All four dual paths carry full pairs, every parity test resolves,
    and the one pairless breaker (matcher.circuit.assemble — quarantine,
    not a dual path) is a documented suppression."""
    files = analysis.collect_py_files(REPO)
    raw = fallback.run(files, REPO)
    assemble = [f for f in raw if f.rule == "FB001"
                and "matcher.circuit.assemble" in f.message]
    assert assemble, "the assemble suppression disappeared — update"
    kept = analysis.filter_suppressed(raw, files)
    assert kept == [], [f.render() for f in kept]


def test_fallback_missing_leg_detected_at_registry_line():
    """Live injection: drop the kill-switch leg from a FALLBACK_PAIRS
    copy — FB002 fires at the domain's real registry.py line."""
    import copy
    pairs = copy.deepcopy(dict(registry.FALLBACK_PAIRS))
    del pairs["matcher.circuit"]["knob"]
    files = analysis.collect_py_files(REPO)
    findings = fallback.run(files, REPO, pairs=pairs)
    hits = [f for f in findings if f.rule == "FB002"
            and "'knob'" in f.message]
    assert hits, [f.render() for f in findings]
    assert hits[0].path == "reporter_tpu/analysis/registry.py"
    assert hits[0].line > 1  # anchored at the real entry, not a stub


def test_fallback_dropped_pair_detected_at_breaker_site():
    """Drop a whole pair: FB001 fires at the real CircuitBreaker
    construction in matcher.py (the two-sided contract's code half)."""
    pairs = dict(registry.FALLBACK_PAIRS)
    del pairs["matcher.circuit.route"]
    files = analysis.collect_py_files(REPO)
    findings = analysis.filter_suppressed(
        fallback.run(files, REPO, pairs=pairs), files)
    assert any(f.rule == "FB001"
               and f.path == "reporter_tpu/matcher/matcher.py"
               and "matcher.circuit.route" in f.message
               for f in findings), [f.render() for f in findings]


def test_fallback_dangling_parity_test_detected():
    import copy
    pairs = copy.deepcopy(dict(registry.FALLBACK_PAIRS))
    pairs["wire.circuit"]["parity_test"] = \
        "tests/test_report_writer.py::test_gone_forever"
    files = analysis.collect_py_files(REPO)
    findings = fallback.run(files, REPO, pairs=pairs)
    assert any(f.rule == "FB003" and "test_gone_forever" in f.message
               for f in findings), [f.render() for f in findings]
    pairs["wire.circuit"]["parity_test"] = "tests/test_nowhere.py::t"
    findings = fallback.run(files, REPO, pairs=pairs)
    assert any(f.rule == "FB003" and "does not exist" in f.message
               for f in findings)


# ---- ABI cross-check -------------------------------------------------------

def _read(path):
    with open(path, encoding="utf-8") as f:
        return f.read()


def test_abi_good_fixture_pair_is_clean():
    findings = abi.check(_read(os.path.join(FIXTURES, "abi_good.cpp")),
                         _read(os.path.join(FIXTURES, "abi_good.py")),
                         "abi_good.cpp", "abi_good.py")
    assert findings == []


def test_abi_bad_fixture_catches_every_drift_class():
    findings = abi.check(_read(os.path.join(FIXTURES, "abi_good.cpp")),
                         _read(os.path.join(FIXTURES, "abi_bad.py")),
                         "abi_good.cpp", "abi_bad.py")
    rules = {f.rule for f in findings}
    assert rules == {"ABI001", "ABI002", "ABI003", "ABI004", "ABI005"}


def test_abi_live_pair_validates_at_version_14():
    # ABI 14: rt_prepare_batch gains prune_margin/skip_routes scalars and
    # the dt output tensor (ISSUE 16) — same export set, new signature
    cpp = _read(LIVE_CPP)
    exports, version = abi.parse_cpp(cpp)
    assert version == 14
    assert "rt_prepare_batch" in exports and "rt_assemble_batch" in exports
    # the ABI-13 route-memo profile surface (export + pre-warm)
    assert "rt_route_memo_export" in exports \
        and "rt_route_memo_warm" in exports
    # the ABI-12 wire writers are part of the checked surface
    assert "rt_report_json" in exports \
        and "rt_report_json_batch" in exports \
        and "rt_render_segments_json" in exports
    findings = abi.check(cpp, _read(LIVE_PY))
    assert findings == [], [f.render() for f in findings]


def test_abi_injected_argtypes_mismatch_is_caught(tmp_path):
    """Satellite contract: inject a deliberate argtypes mismatch into a
    fixture COPY of the live binding and assert the checker fails it."""
    live = _read(LIVE_PY)
    # rt_route_matrices binds T as c_int64; narrow it to c_int32
    target = ("lib.rt_route_matrices.argtypes = [\n"
              "            ctypes.c_void_p, ctypes.c_int64,")
    assert target in live, "live binding drifted; update the injection"
    mutated = live.replace(
        target, target.replace("c_int64", "c_int32"), 1)
    bad_py = tmp_path / "native_init_mutated.py"
    bad_py.write_text(mutated, encoding="utf-8")
    findings = abi.run_paths(LIVE_CPP, str(bad_py),
                             abi.DEFAULT_CPP, "native_init_mutated.py")
    assert any(f.rule == "ABI003" and "rt_route_matrices" in f.message
               and "i32" in f.message for f in findings), \
        [f.render() for f in findings]


def test_abi_version_bump_is_caught(tmp_path):
    live = _read(LIVE_PY)
    mutated = re.sub(r"^ABI_VERSION = \d+", "ABI_VERSION = 999", live,
                     count=1, flags=re.MULTILINE)
    assert mutated != live
    bad_py = tmp_path / "native_init_ver.py"
    bad_py.write_text(mutated, encoding="utf-8")
    findings = abi.run_paths(LIVE_CPP, str(bad_py),
                             abi.DEFAULT_CPP, "native_init_ver.py")
    assert any(f.rule == "ABI004" for f in findings)


# ---- the driver ------------------------------------------------------------

def _lint(*args):
    return subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "lint.py"), *args],
        capture_output=True, text=True, cwd=REPO)


def test_repo_wide_run_is_clean_against_committed_baseline():
    """Acceptance gate: `python tools/lint.py` exits 0 — no new findings,
    no stale baseline entries."""
    proc = _lint()
    assert proc.returncode == 0, proc.stdout + proc.stderr


def test_abi_only_guard_passes_on_live_pair_and_fails_on_mismatch(tmp_path):
    assert _lint("--abi-only").returncode == 0
    mutated = _read(LIVE_PY).replace("ctypes.c_double, c_f32p]",
                                     "ctypes.c_double, c_f64p]", 1)
    bad_py = tmp_path / "native_guard.py"
    bad_py.write_text(mutated, encoding="utf-8")
    proc = _lint("--abi-only", "--abi-py", str(bad_py))
    assert proc.returncode == 1
    assert "ABI003" in proc.stdout


def test_stale_baseline_entry_fails_the_run(tmp_path):
    stale = tmp_path / "baseline.txt"
    stale.write_text("reporter_tpu/matcher/matcher.py:1: HP001 ghost\n",
                     encoding="utf-8")
    proc = _lint("--baseline", str(stale))
    assert proc.returncode == 1
    assert "stale baseline entry" in proc.stdout


def test_partial_run_does_not_report_unrelated_baseline_as_stale(tmp_path):
    # an entry for a file OUTSIDE the requested paths legitimately does
    # not fire on a partial run — it must not be called stale
    base = tmp_path / "baseline.txt"
    base.write_text("reporter_tpu/service/report.py:1: HP001 ghost\n",
                    encoding="utf-8")
    proc = _lint("reporter_tpu/matcher/matcher.py",
                 "--baseline", str(base))
    assert proc.returncode == 0, proc.stdout + proc.stderr
    # and --write-baseline refuses a partial run outright
    proc = _lint("reporter_tpu/matcher/matcher.py", "--write-baseline",
                 "--baseline", str(base))
    assert proc.returncode == 2


def test_jit_positional_dtype_not_flagged():
    import ast
    src = ("import jax\nimport jax.numpy as jnp\n"
           "@jax.jit\n"
           "def f(x):\n"
           "    a = jnp.arange(0, 10, 1, jnp.int32)\n"
           "    b = jnp.zeros(x.shape, jnp.float32)\n"
           "    c = jnp.arange(10)\n"                      # no dtype: flag
           "    return a + b + c\n")
    sf = SourceFile(path="x", relpath="reporter_tpu/ops/x.py", text=src,
                    tree=ast.parse(src), suppressions={})
    findings = jit_hygiene.run([sf], REPO)
    assert [f.line for f in findings if f.rule == "JH002"] == [7]


def test_abi_parses_plain_int_and_typed_pointer_returns():
    cpp = ('extern "C" {\n'
           "int32_t rt_abi_version(void) { return 1; }\n"
           "int rt_plain(int64_t n) { return 0; }\n"
           "double* rt_buf(void* h) { return 0; }\n"
           "}\n")
    exports, version = abi.parse_cpp(cpp)
    assert version == 1
    assert exports["rt_plain"] == (("val", "i32"), [("val", "i64")])
    assert exports["rt_buf"] == (("ptr", "f64"), [("ptr", "void")])
    # an unbound export of either shape raises ABI001, not silence
    py = "ABI_VERSION = 1\n"
    rules = {f.rule for f in abi.check(cpp, py, "c.cpp", "b.py")}
    assert "ABI001" in rules


def test_list_rules_covers_all_passes():
    proc = _lint("--list-rules")
    assert proc.returncode == 0
    for rule in ("HP001", "HP002", "HP003", "JH001", "JH002", "JH003",
                 "ABI001", "ABI004", "LD001", "LD002", "LD003",
                 "DUR001", "DUR002", "DUR003", "DUR004",
                 "KN001", "KN002", "MT001", "MT002",
                 "FP001", "FP002", "FP003",
                 "TC001", "TC002", "TC003", "TC004",
                 "DP001", "DP002", "DP003",
                 "FB001", "FB002", "FB003"):
        assert rule in proc.stdout


def test_contracts_only_guard_is_clean_and_catches_drift(tmp_path):
    """--contracts-only passes on the live tree and fails loudly when
    README drops a knob row (the five-knob drift class, kept closed)."""
    assert _lint("--contracts-only").returncode == 0
    readme_path = os.path.join(REPO, "README.md")
    readme = _read(readme_path)
    target = "| `REPORTER_TPU_PROBE_TRIES` |"
    assert target in readme, "README knob table drifted; update the test"
    # simulate the drift in-process (the driver reads the real README,
    # so exercise the pass directly on a mutated copy)
    files = analysis.collect_py_files(
        REPO, [os.path.join(REPO, "reporter_tpu"),
               os.path.join(REPO, "tools"),
               os.path.join(REPO, "bench.py")])
    mutated = "\n".join(ln for ln in readme.splitlines()
                        if not ln.startswith(target))
    findings = registry_drift.run(files, REPO, readme_text=mutated)
    assert any(f.rule == "KN002"
               and "REPORTER_TPU_PROBE_TRIES" in f.message
               for f in findings)


def test_tensors_only_guard_is_clean_and_reports_eval_time():
    """--tensors-only exits 0 on the live tree and prints the eval_shape
    harness wall time (the CI budget guard's visibility hook)."""
    proc = _lint("--tensors-only")
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "eval_shape harness" in proc.stdout


def test_partial_run_skips_whole_package_contract_directions():
    # a single-file run must not call registry entries "dead" just
    # because their users are outside the requested paths
    proc = _lint("reporter_tpu/matcher/matcher.py")
    assert proc.returncode == 0, proc.stdout + proc.stderr


def _readme_rule_ids():
    """Every rule id documented in README's Static-analysis table,
    ranges expanded (``ABI001-005`` -> ABI001..ABI005)."""
    readme = _read(os.path.join(REPO, "README.md"))
    ids = set()
    in_table = False
    for line in readme.splitlines():
        if line.startswith("| rule |"):
            in_table = True
            continue
        if in_table:
            if not line.startswith("|"):
                break
            cell = line.split("|")[1].strip()
            m = re.match(r"^([A-Z]{2,3})(\d{3})(?:-(?:[A-Z]{2,3})?(\d{3}))?$",
                         cell)
            if not m:
                continue
            prefix, lo, hi = m.group(1), int(m.group(2)), m.group(3)
            for n in range(lo, (int(hi) if hi else lo) + 1):
                ids.add(f"{prefix}{n:03d}")
    return ids


def test_readme_rule_table_matches_the_suite():
    """lint_fixtures self-check (ISSUE 6 satellite): every rule id
    documented in README exists in the suite, and every implemented
    rule is documented."""
    documented = _readme_rule_ids()
    implemented = set(analysis.ALL_RULES)
    assert documented == implemented, (
        f"README-only: {sorted(documented - implemented)}; "
        f"undocumented: {sorted(implemented - documented)}")


def test_every_rule_id_has_a_fixture_test():
    """Every non-ABI rule id is exercised by a bad fixture annotation
    (the ABI rules pin through the fixture .cpp/.py pair instead)."""
    annotated = set()
    for name in os.listdir(FIXTURES):
        if not name.endswith(".py"):
            continue
        text = _read(os.path.join(FIXTURES, name))
        annotated.update(re.findall(r"#\s*([A-Z]{2,3}\d{3})(?:\s*\(x\d+\))?:",
                                    text))
    # whole-package reverse directions (dead entries, README drift,
    # coverage) are pinned by the live-tree tests above, not fixtures
    full_scope_only = {"KN002", "MT002", "FP002", "FP003",
                       "TC001", "FB002", "FB003"}
    # the RC rules are RUNTIME findings (the lock witness / guarded
    # audit, ISSUE 10): they pin through tests/test_racecheck.py
    # driving real threads, not through AST fixtures
    runtime = set(analysis.racecheck.RULES)
    missing = {r for r in analysis.ALL_RULES
               if not r.startswith("ABI")} \
        - full_scope_only - runtime - annotated
    assert missing == set(), f"rules with no bad-fixture line: {missing}"
