"""Behavioral tests for the Meili tuning knobs wired in round 3.

Round 3 plumbed ``turn_penalty_factor`` and the ``max_route_time_factor``
time-admissibility bound through both the native and numpy prep paths
(reference knobs: Dockerfile:14-17), but nothing observed them changing
output. These tests pin observable behavior:

- a fork trace whose matched edge FLIPS when turn_penalty_factor goes
  0 -> 500 (the sharp-turn interpretation wins on emission alone, loses
  once the heading change is priced);
- a slow-road transition PRUNED by the time bound when the
  min_time_bound_s floor is lowered, and kept at the 60 s default floor
  (the floor exists because at 1 Hz sampling factor*dt is ~2 s, which
  GPS noise alone overruns — so at defaults the bound only prunes
  routes that would take over a minute, i.e. sustained sub-30 km/h
  crawls within the ~500 m distance bound or large sampling gaps);
- native-vs-numpy parity of full match output at those non-default
  settings.
"""
import numpy as np
import pytest

from reporter_tpu import native
from reporter_tpu.core.geo import local_meters_projection
from reporter_tpu.graph.network import RoadNetwork
from reporter_tpu.graph.route import UNREACHABLE
from reporter_tpu.matcher import MatchParams, SegmentMatcher


def _net_from_meters(nodes_xy, edges, speeds=None):
    """Build a RoadNetwork from projected-meter node coords; each edge is
    its own OSMLR segment (id = edge index) so matched edges are directly
    observable in the output."""
    _to_xy, to_ll = local_meters_projection(0.0, 0.0)
    xs = np.array([x for x, _y in nodes_xy], dtype=np.float64)
    ys = np.array([y for _x, y in nodes_xy], dtype=np.float64)
    lat, lon = to_ll(xs, ys)
    starts = np.array([a for a, _b in edges], dtype=np.int32)
    ends = np.array([b for _a, b in edges], dtype=np.int32)
    lengths = np.hypot(xs[ends] - xs[starts],
                       ys[ends] - ys[starts]).astype(np.float32)
    if speeds is None:
        speeds = np.full(len(edges), 50.0, dtype=np.float32)
    seg_ids = np.arange(len(edges), dtype=np.int64)
    return RoadNetwork(
        node_lat=np.asarray(lat, dtype=np.float64),
        node_lon=np.asarray(lon, dtype=np.float64),
        edge_start=starts, edge_end=ends,
        edge_length_m=lengths,
        edge_speed_kph=np.asarray(speeds, dtype=np.float32),
        edge_segment_id=seg_ids,
        edge_segment_offset_m=np.zeros(len(edges), dtype=np.float32),
        edge_internal=np.zeros(len(edges), dtype=bool),
        segment_length_m={int(i): float(lengths[i])
                          for i in range(len(edges))},
    )


def _pts_from_meters(xy_times):
    _to_xy, to_ll = local_meters_projection(0.0, 0.0)
    pts = []
    for x, y, t in xy_times:
        lat, lon = to_ll(np.float64(x), np.float64(y))
        pts.append({"lat": float(lat), "lon": float(lon), "time": float(t)})
    return pts


# ---- turn penalty ---------------------------------------------------------

@pytest.fixture(scope="module")
def fork_city():
    """A -> X approach heading east, then a fork: a sharp ~150deg turn
    (edge 1) vs a mild ~10deg turn (edge 2)."""
    import math
    ax = (0.0, 0.0)
    xx = (400.0, 0.0)
    sharp = (400.0 + 400.0 * math.cos(math.radians(150.0)),
             400.0 * math.sin(math.radians(150.0)))
    mild = (400.0 + 400.0 * math.cos(math.radians(10.0)),
            400.0 * math.sin(math.radians(10.0)))
    return _net_from_meters([ax, xx, sharp, mild],
                            [(0, 1), (1, 2), (1, 3)])


def _fork_trace():
    """Two points on the approach, then one 20 m past the fork at bearing
    110deg — closer to the sharp edge (better emission) but requiring a
    ~150deg heading change to reach."""
    import math
    b = math.radians(110.0)
    return _pts_from_meters([
        (340.0, 0.5, 0.0),
        (380.0, -0.5, 3.0),
        (400.0 + 20.0 * math.cos(b), 20.0 * math.sin(b), 6.0),
    ])


def _matched_edges(match):
    return [w for seg in match["segments"] for w in seg["way_ids"]]


@pytest.mark.parametrize("use_native", [True, False])
def test_turn_penalty_flips_fork_choice(fork_city, use_native):
    if use_native and not native.available():
        pytest.skip("native toolchain unavailable")
    req = {"uuid": "fork", "trace": _fork_trace(),
           "match_options": {"mode": "auto", "report_levels": [0, 1, 2],
                             "transition_levels": [0, 1, 2]}}
    free = SegmentMatcher(
        net=fork_city, use_native=use_native,
        params=MatchParams(turn_penalty_factor=0.0))
    penal = SegmentMatcher(
        net=fork_city, use_native=use_native,
        params=MatchParams(turn_penalty_factor=500.0))
    edges_free = _matched_edges(free.match_many([req])[0])
    edges_penal = _matched_edges(penal.match_many([req])[0])
    # unpenalised: the sharp edge (1) wins on emission; penalised at 500 m
    # per U-turn-equivalent, the mild edge (2) wins
    assert 1 in edges_free and 2 not in edges_free, edges_free
    assert 2 in edges_penal and 1 not in edges_penal, edges_penal


def test_turn_penalty_native_numpy_parity(fork_city):
    if not native.available():
        pytest.skip("native toolchain unavailable")
    req = {"uuid": "fork", "trace": _fork_trace(),
           "match_options": {"mode": "auto", "report_levels": [0, 1, 2],
                             "transition_levels": [0, 1, 2]}}
    for factor in (0.0, 150.0, 500.0):
        params = MatchParams(turn_penalty_factor=factor)
        a = SegmentMatcher(net=fork_city, params=params).match_many([req])
        b = SegmentMatcher(net=fork_city, params=params,
                           use_native=False).match_many([req])
        assert a == b, f"turn_penalty_factor={factor}"


# ---- time-admissibility bound --------------------------------------------

@pytest.fixture(scope="module")
def slow_road():
    """One straight 400 m two-edge road at 10 km/h (2.78 m/s)."""
    return _net_from_meters([(0.0, 0.0), (200.0, 0.0), (400.0, 0.0)],
                            [(0, 1), (1, 2)],
                            speeds=np.array([10.0, 10.0], dtype=np.float32))


def _teleport_trace():
    """1 s between probes but ~185 m of road between them: the route's
    travel time at 10 km/h is ~67 s >> 1 s. (Points are > 10 m apart so
    the jitter filter keeps all three.)"""
    return _pts_from_meters([(2.0, 1.0, 0.0), (14.0, -1.0, 1.0),
                             (200.0, 1.0, 2.0)])


@pytest.mark.parametrize("use_native", [True, False])
def test_time_bound_prunes_impossible_transition(slow_road, use_native):
    if use_native and not native.available():
        pytest.skip("native toolchain unavailable")
    pts = _teleport_trace()
    # floor lowered: cap = max(5, 2*1s) = 5 s < ~68 s travel -> pruned
    tight = SegmentMatcher(
        net=slow_road, use_native=use_native,
        params=MatchParams(max_route_time_factor=2.0, min_time_bound_s=5.0))
    p = tight.prepare(pts)
    k2 = int(np.argmin(p.dist_m[2]))
    k1 = int(np.argmin(p.dist_m[1]))
    assert p.route_m[1, k1, k2] >= UNREACHABLE / 2

    # default 60 s floor: cap = 60 s < 68 s travel -> still pruned for
    # THIS crawl, proving the bound is live at defaults for sub-30 km/h
    # routes; a faster road (50 km/h, ~14 s travel) must pass
    dflt = SegmentMatcher(net=slow_road, use_native=use_native,
                          params=MatchParams())
    pd = dflt.prepare(pts)
    assert pd.route_m[1, k1, k2] >= UNREACHABLE / 2

    # bound disabled (factor <= 0): transition reachable again
    off = SegmentMatcher(
        net=slow_road, use_native=use_native,
        params=MatchParams(max_route_time_factor=0.0))
    po = off.prepare(pts)
    assert po.route_m[1, k1, k2] < UNREACHABLE / 2


@pytest.mark.parametrize("use_native", [True, False])
def test_time_bound_inert_on_fast_road(use_native):
    if use_native and not native.available():
        pytest.skip("native toolchain unavailable")
    fast = _net_from_meters([(0.0, 0.0), (200.0, 0.0), (400.0, 0.0)],
                            [(0, 1), (1, 2)],
                            speeds=np.array([50.0, 50.0], dtype=np.float32))
    pts = _teleport_trace()
    m = SegmentMatcher(net=fast, use_native=use_native,
                       params=MatchParams())  # defaults: factor 2, floor 60
    p = m.prepare(pts)
    k2 = int(np.argmin(p.dist_m[2]))
    k1 = int(np.argmin(p.dist_m[1]))
    # ~190 m at 50 km/h is ~14 s < the 60 s floor -> admissible
    assert p.route_m[1, k1, k2] < UNREACHABLE / 2


def test_time_bound_native_numpy_parity(slow_road):
    if not native.available():
        pytest.skip("native toolchain unavailable")
    req = {"uuid": "slow", "trace": _teleport_trace(),
           "match_options": {"mode": "auto", "report_levels": [0, 1, 2],
                             "transition_levels": [0, 1, 2]}}
    for factor, floor in ((2.0, 5.0), (2.0, 60.0), (0.0, 60.0),
                          (10.0, 1.0)):
        params = MatchParams(max_route_time_factor=factor,
                             min_time_bound_s=floor)
        a = SegmentMatcher(net=slow_road, params=params).match_many([req])
        b = SegmentMatcher(net=slow_road, params=params,
                           use_native=False).match_many([req])
        assert a == b, f"factor={factor} floor={floor}"
