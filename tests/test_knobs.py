"""Behavioral tests for the Meili tuning knobs wired in round 3.

Round 3 plumbed ``turn_penalty_factor`` and the ``max_route_time_factor``
time-admissibility bound through both the native and numpy prep paths
(reference knobs: Dockerfile:14-17), but nothing observed them changing
output. These tests pin observable behavior:

- a fork trace whose matched edge FLIPS when turn_penalty_factor goes
  0 -> 500 (the sharp-turn interpretation wins on emission alone, loses
  once the heading change is priced);
- a slow-road transition PRUNED by the time bound when the
  min_time_bound_s floor is lowered, and kept for noise-scale routes at
  the default floor (the floor exists because at 1 Hz sampling
  factor*dt is ~2 s, which GPS noise alone overruns — the 15 s default
  is sized to noise-scale projection hops, so the bound prunes
  teleports the 60 s floor of rounds 3-5 let through; see
  test_time_floor_prunes_teleport);
- native-vs-numpy parity of full match output at those non-default
  settings, including when the knobs arrive via per-request
  match_options overrides (which split native prep groups).
"""
import numpy as np
import pytest

from reporter_tpu import native
from reporter_tpu.core.geo import local_meters_projection
from reporter_tpu.graph.network import RoadNetwork
from reporter_tpu.graph.route import UNREACHABLE
from reporter_tpu.matcher import MatchParams, SegmentMatcher


def _net_from_meters(nodes_xy, edges, speeds=None):
    """Build a RoadNetwork from projected-meter node coords; each edge is
    its own OSMLR segment (id = edge index) so matched edges are directly
    observable in the output."""
    _to_xy, to_ll = local_meters_projection(0.0, 0.0)
    xs = np.array([x for x, _y in nodes_xy], dtype=np.float64)
    ys = np.array([y for _x, y in nodes_xy], dtype=np.float64)
    lat, lon = to_ll(xs, ys)
    starts = np.array([a for a, _b in edges], dtype=np.int32)
    ends = np.array([b for _a, b in edges], dtype=np.int32)
    lengths = np.hypot(xs[ends] - xs[starts],
                       ys[ends] - ys[starts]).astype(np.float32)
    if speeds is None:
        speeds = np.full(len(edges), 50.0, dtype=np.float32)
    seg_ids = np.arange(len(edges), dtype=np.int64)
    return RoadNetwork(
        node_lat=np.asarray(lat, dtype=np.float64),
        node_lon=np.asarray(lon, dtype=np.float64),
        edge_start=starts, edge_end=ends,
        edge_length_m=lengths,
        edge_speed_kph=np.asarray(speeds, dtype=np.float32),
        edge_segment_id=seg_ids,
        edge_segment_offset_m=np.zeros(len(edges), dtype=np.float32),
        edge_internal=np.zeros(len(edges), dtype=bool),
        segment_length_m={int(i): float(lengths[i])
                          for i in range(len(edges))},
    )


def _pts_from_meters(xy_times):
    _to_xy, to_ll = local_meters_projection(0.0, 0.0)
    pts = []
    for x, y, t in xy_times:
        lat, lon = to_ll(np.float64(x), np.float64(y))
        pts.append({"lat": float(lat), "lon": float(lon), "time": float(t)})
    return pts


# ---- turn penalty ---------------------------------------------------------

@pytest.fixture(scope="module")
def fork_city():
    """A -> X approach heading east, then a fork: a sharp ~150deg turn
    (edge 1) vs a mild ~10deg turn (edge 2)."""
    import math
    ax = (0.0, 0.0)
    xx = (400.0, 0.0)
    sharp = (400.0 + 400.0 * math.cos(math.radians(150.0)),
             400.0 * math.sin(math.radians(150.0)))
    mild = (400.0 + 400.0 * math.cos(math.radians(10.0)),
            400.0 * math.sin(math.radians(10.0)))
    return _net_from_meters([ax, xx, sharp, mild],
                            [(0, 1), (1, 2), (1, 3)])


def _fork_trace():
    """Two points on the approach, then one 20 m past the fork at bearing
    110deg — closer to the sharp edge (better emission) but requiring a
    ~150deg heading change to reach."""
    import math
    b = math.radians(110.0)
    return _pts_from_meters([
        (340.0, 0.5, 0.0),
        (380.0, -0.5, 3.0),
        (400.0 + 20.0 * math.cos(b), 20.0 * math.sin(b), 6.0),
    ])


def _matched_edges(match):
    return [w for seg in match["segments"] for w in seg["way_ids"]]


@pytest.mark.parametrize("use_native", [True, False])
def test_turn_penalty_flips_fork_choice(fork_city, use_native):
    if use_native and not native.available():
        pytest.skip("native toolchain unavailable")
    req = {"uuid": "fork", "trace": _fork_trace(),
           "match_options": {"mode": "auto", "report_levels": [0, 1, 2],
                             "transition_levels": [0, 1, 2]}}
    free = SegmentMatcher(
        net=fork_city, use_native=use_native,
        params=MatchParams(turn_penalty_factor=0.0))
    penal = SegmentMatcher(
        net=fork_city, use_native=use_native,
        params=MatchParams(turn_penalty_factor=500.0))
    edges_free = _matched_edges(free.match_many([req])[0])
    edges_penal = _matched_edges(penal.match_many([req])[0])
    # unpenalised: the sharp edge (1) wins on emission; penalised at 500 m
    # per U-turn-equivalent, the mild edge (2) wins
    assert 1 in edges_free and 2 not in edges_free, edges_free
    assert 2 in edges_penal and 1 not in edges_penal, edges_penal


def test_turn_penalty_native_numpy_parity(fork_city):
    if not native.available():
        pytest.skip("native toolchain unavailable")
    req = {"uuid": "fork", "trace": _fork_trace(),
           "match_options": {"mode": "auto", "report_levels": [0, 1, 2],
                             "transition_levels": [0, 1, 2]}}
    for factor in (0.0, 150.0, 500.0):
        params = MatchParams(turn_penalty_factor=factor)
        a = SegmentMatcher(net=fork_city, params=params).match_many([req])
        b = SegmentMatcher(net=fork_city, params=params,
                           use_native=False).match_many([req])
        assert a == b, f"turn_penalty_factor={factor}"


# ---- time-admissibility bound --------------------------------------------

@pytest.fixture(scope="module")
def slow_road():
    """One straight 400 m two-edge road at 10 km/h (2.78 m/s)."""
    return _net_from_meters([(0.0, 0.0), (200.0, 0.0), (400.0, 0.0)],
                            [(0, 1), (1, 2)],
                            speeds=np.array([10.0, 10.0], dtype=np.float32))


def _teleport_trace():
    """1 s between probes but ~185 m of road between them: the route's
    travel time at 10 km/h is ~67 s >> 1 s. (Points are > 10 m apart so
    the jitter filter keeps all three.)"""
    return _pts_from_meters([(2.0, 1.0, 0.0), (14.0, -1.0, 1.0),
                             (200.0, 1.0, 2.0)])


@pytest.mark.parametrize("use_native", [True, False])
def test_time_bound_prunes_impossible_transition(slow_road, use_native):
    if use_native and not native.available():
        pytest.skip("native toolchain unavailable")
    pts = _teleport_trace()
    # floor lowered: cap = max(5, 2*1s) = 5 s < ~68 s travel -> pruned
    tight = SegmentMatcher(
        net=slow_road, use_native=use_native,
        params=MatchParams(max_route_time_factor=2.0, min_time_bound_s=5.0))
    p = tight.prepare(pts)
    k2 = int(np.argmin(p.dist_m[2]))
    k1 = int(np.argmin(p.dist_m[1]))
    assert p.route_m[1, k1, k2] >= UNREACHABLE / 2

    # default floor: cap = max(15, 2*1s) = 15 s < 68 s travel -> still
    # pruned for this crawl; a noise-scale route on a faster road
    # (50 km/h, ~14 s travel) must pass (test_time_bound_inert_on_fast_road)
    dflt = SegmentMatcher(net=slow_road, use_native=use_native,
                          params=MatchParams())
    pd = dflt.prepare(pts)
    assert pd.route_m[1, k1, k2] >= UNREACHABLE / 2

    # bound disabled (factor <= 0): transition reachable again
    off = SegmentMatcher(
        net=slow_road, use_native=use_native,
        params=MatchParams(max_route_time_factor=0.0))
    po = off.prepare(pts)
    assert po.route_m[1, k1, k2] < UNREACHABLE / 2


@pytest.mark.parametrize("use_native", [True, False])
def test_time_bound_inert_on_fast_road(use_native):
    if use_native and not native.available():
        pytest.skip("native toolchain unavailable")
    fast = _net_from_meters([(0.0, 0.0), (200.0, 0.0), (400.0, 0.0)],
                            [(0, 1), (1, 2)],
                            speeds=np.array([50.0, 50.0], dtype=np.float32))
    pts = _teleport_trace()
    m = SegmentMatcher(net=fast, use_native=use_native,
                       params=MatchParams())  # defaults: factor 2, floor 15
    p = m.prepare(pts)
    k2 = int(np.argmin(p.dist_m[2]))
    k1 = int(np.argmin(p.dist_m[1]))
    # ~186 m at 50 km/h is ~13.4 s < the 15 s floor -> admissible: the
    # floor keeps noise-scale routes alive at moderate speeds
    assert p.route_m[1, k1, k2] < UNREACHABLE / 2


@pytest.mark.parametrize("use_native", [True, False])
def test_time_floor_prunes_teleport(use_native):
    """The 15 s default floor makes the time bound LIVE at defaults: a
    ~250 m stretch of 30 km/h road 'travelled' between 1 Hz probes takes
    ~30 s > 15 s -> pruned, while the 60 s floor of rounds 3-5 (the time
    analog of the 500 m distance floor, sized to the wrong scale) let
    exactly this teleport through. The distance bound alone cannot catch
    it (max(500, 5*gc) admits the ~250 m route)."""
    if use_native and not native.available():
        pytest.skip("native toolchain unavailable")
    road = _net_from_meters(
        [(0.0, 0.0), (300.0, 0.0), (600.0, 0.0)], [(0, 1), (1, 2)],
        speeds=np.array([30.0, 30.0], dtype=np.float32))
    pts = _pts_from_meters([(2.0, 1.0, 0.0), (14.0, -1.0, 1.0),
                            (260.0, 1.0, 2.0)])
    dflt = SegmentMatcher(net=road, use_native=use_native,
                          params=MatchParams())
    p = dflt.prepare(pts)
    k1 = int(np.argmin(p.dist_m[1]))
    k2 = int(np.argmin(p.dist_m[2]))
    assert p.route_m[1, k1, k2] >= UNREACHABLE / 2, \
        "teleport must be pruned at the default floor"
    # the old 60 s floor admits it — pinning exactly what the default
    # floor change buys
    old = SegmentMatcher(net=road, use_native=use_native,
                         params=MatchParams(min_time_bound_s=60.0))
    po = old.prepare(pts)
    assert po.route_m[1, k1, k2] < UNREACHABLE / 2


@pytest.mark.parametrize("use_native", [True, False])
def test_knobs_via_match_options_override(use_native):
    """Per-request match_options carrying non-default knob values must
    behave exactly like matcher-level params — the prep-param grouping
    (matcher._PREP_KEY_FIELDS) splits them into their own native prep
    call, and both paths agree."""
    if use_native and not native.available():
        pytest.skip("native toolchain unavailable")
    road = _net_from_meters(
        [(0.0, 0.0), (300.0, 0.0), (600.0, 0.0)], [(0, 1), (1, 2)],
        speeds=np.array([30.0, 30.0], dtype=np.float32))
    pts = _pts_from_meters([(2.0, 1.0, 0.0), (14.0, -1.0, 1.0),
                            (260.0, 1.0, 2.0)])
    base = {"mode": "auto", "report_levels": [0, 1, 2],
            "transition_levels": [0, 1, 2]}
    m = SegmentMatcher(net=road, use_native=use_native,
                       params=MatchParams())
    # one request at defaults (teleport pruned -> split match), one with
    # the bound disabled via match_options (teleport admitted -> joined)
    reqs = [
        {"uuid": "dflt", "trace": pts, "match_options": dict(base)},
        {"uuid": "loose", "trace": pts,
         "match_options": dict(base, max_route_time_factor=0.0)},
    ]
    out = m.match_many(reqs)
    ways_dflt = [w for s in out[0]["segments"] for w in s["way_ids"]]
    ways_loose = [w for s in out[1]["segments"] for w in s["way_ids"]]
    # with the bound off the decode routes through; at defaults the
    # pruned transition breaks the chain (fewer/shorter joined spans)
    assert ways_loose.count(0) >= 1
    assert out[0] != out[1]
    # parity with per-matcher params for the SAME knob values
    loose_params = SegmentMatcher(
        net=road, use_native=use_native,
        params=MatchParams(max_route_time_factor=0.0))
    want = loose_params.match_many([reqs[1]])[0]
    assert out[1] == want


def test_time_bound_native_numpy_parity(slow_road):
    if not native.available():
        pytest.skip("native toolchain unavailable")
    req = {"uuid": "slow", "trace": _teleport_trace(),
           "match_options": {"mode": "auto", "report_levels": [0, 1, 2],
                             "transition_levels": [0, 1, 2]}}
    for factor, floor in ((2.0, 5.0), (2.0, 60.0), (0.0, 60.0),
                          (10.0, 1.0)):
        params = MatchParams(max_route_time_factor=factor,
                             min_time_bound_s=floor)
        a = SegmentMatcher(net=slow_road, params=params).match_many([req])
        b = SegmentMatcher(net=slow_road, params=params,
                           use_native=False).match_many([req])
        assert a == b, f"factor={factor} floor={floor}"
