"""OSM XML importer (graph/osm.py): parsing, classification, OSMLR ids,
and end-to-end matching on an imported network."""
import io

import numpy as np
import pytest

from reporter_tpu.core.osmlr import segment_index, tile_level
from reporter_tpu.graph.osm import network_from_osm_xml

# A small real-shaped extract: a primary two-way street, a oneway
# residential, a reverse-oneway street, a motorway ramp (internal), a
# service alley (unassociated), a non-drivable footway, and a way with a
# node missing from the extract (clipped).
OSM_XML = """<?xml version="1.0" encoding="UTF-8"?>
<osm version="0.6" generator="test">
  <node id="1" lat="14.5800" lon="121.0000"/>
  <node id="2" lat="14.5810" lon="121.0000"/>
  <node id="3" lat="14.5820" lon="121.0000"/>
  <node id="4" lat="14.5810" lon="121.0010"/>
  <node id="5" lat="14.5820" lon="121.0010"/>
  <node id="6" lat="14.5800" lon="121.0010"/>
  <node id="7" lat="14.5830" lon="121.0000"/>
  <node id="8" lat="14.5840" lon="121.0010"/>
  <node id="9" lat="14.5850" lon="121.0010"/>
  <node id="10" lat="14.5860" lon="121.0010"/>
  <way id="100">
    <nd ref="1"/><nd ref="2"/><nd ref="3"/>
    <tag k="highway" v="primary"/>
    <tag k="maxspeed" v="50"/>
  </way>
  <way id="101">
    <nd ref="2"/><nd ref="4"/>
    <tag k="highway" v="residential"/>
    <tag k="oneway" v="yes"/>
  </way>
  <way id="102">
    <nd ref="4"/><nd ref="5"/>
    <tag k="highway" v="residential"/>
    <tag k="oneway" v="-1"/>
    <tag k="maxspeed" v="20 mph"/>
  </way>
  <way id="103">
    <nd ref="3"/><nd ref="5"/>
    <tag k="highway" v="motorway_link"/>
  </way>
  <way id="104">
    <nd ref="4"/><nd ref="6"/>
    <tag k="highway" v="service"/>
  </way>
  <way id="105">
    <nd ref="1"/><nd ref="6"/>
    <tag k="highway" v="footway"/>
  </way>
  <way id="106">
    <nd ref="7"/><nd ref="999"/>
    <tag k="highway" v="residential"/>
  </way>
  <way id="107">
    <nd ref="8"/><nd ref="9"/><nd ref="10"/>
    <tag k="highway" v="residential"/>
    <tag k="oneway" v="yes"/>
  </way>
</osm>
"""


@pytest.fixture(scope="module")
def net():
    return network_from_osm_xml(io.BytesIO(OSM_XML.encode()))


def _edges_between(net, a_osm_idx, b_osm_idx):
    return [e for e in range(net.num_edges)
            if net.edge_start[e] == a_osm_idx and net.edge_end[e] == b_osm_idx]


class TestImport:
    def test_counts(self, net):
        # way 100: 2 node pairs x 2 dirs = 4; way 101: 1; way 102: 1;
        # way 103: 2 dirs? no - _link is internal but still two-way: 2;
        # way 104 service two-way: 2; way 107 oneway 2 pairs: 2;
        # footway skipped; clipped way dropped
        assert net.num_edges == 4 + 1 + 1 + 2 + 2 + 2

    def test_two_way_and_oneway(self, net):
        s = net.edge_start.tolist()
        e = net.edge_end.tolist()
        pairs = set(zip(s, e))
        # primary is bidirectional between consecutive nodes
        assert (0, 1) in pairs and (1, 0) in pairs
        # oneway=yes: only forward
        assert (1, 3) in pairs and (3, 1) not in pairs
        # oneway=-1: only reverse
        assert (4, 3) in pairs and (3, 4) not in pairs

    def test_speeds(self, net):
        e_fwd = _edges_between(net, 0, 1)[0]
        assert net.edge_speed_kph[e_fwd] == pytest.approx(50.0)
        e_rev = _edges_between(net, 4, 3)[0]
        assert net.edge_speed_kph[e_rev] == pytest.approx(32.19, abs=0.01)

    def test_osmlr_levels_and_association(self, net):
        e_primary = _edges_between(net, 0, 1)[0]
        sid = int(net.edge_segment_id[e_primary])
        assert sid >= 0
        assert tile_level(sid) == 1  # primary -> arterial level
        assert sid in net.segment_length_m
        e_res = _edges_between(net, 1, 3)[0]
        assert tile_level(int(net.edge_segment_id[e_res])) == 2

    def test_internal_and_service_unassociated(self, net):
        e_ramp = _edges_between(net, 2, 4)[0]
        assert net.edge_internal[e_ramp]
        assert net.edge_segment_id[e_ramp] == -1
        e_svc = _edges_between(net, 3, 5)[0]
        assert not net.edge_internal[e_svc]
        assert net.edge_segment_id[e_svc] == -1

    def test_direction_segments_distinct(self, net):
        # each direction of a two-way associated way is its own segment
        e_fwd = _edges_between(net, 0, 1)[0]
        e_rev = _edges_between(net, 1, 0)[0]
        a, b = int(net.edge_segment_id[e_fwd]), int(net.edge_segment_id[e_rev])
        assert a != b and a >= 0 and b >= 0
        assert segment_index(a) != segment_index(b)

    def test_segments_split_at_junctions(self, net):
        # way 100 passes through node 2, which way 101 also uses — a
        # decision point, so the OSMLR segment SPLITS there (real OSMLR
        # breaks at intersections); each piece restarts its offsets and
        # carries its own length
        e1 = _edges_between(net, 0, 1)[0]
        e2 = _edges_between(net, 1, 2)[0]
        assert int(net.edge_segment_id[e1]) != int(net.edge_segment_id[e2])
        assert net.edge_segment_offset_m[e1] == pytest.approx(0.0)
        assert net.edge_segment_offset_m[e2] == pytest.approx(0.0)
        for e in (e1, e2):
            sid = int(net.edge_segment_id[e])
            assert net.segment_length_m[sid] == pytest.approx(
                float(net.edge_length_m[e]), rel=1e-5)

    def test_segment_offsets_cumulative_between_junctions(self, net):
        # way 107's interior node 9 belongs to no other way: NOT a
        # decision point, so both edges share one segment with
        # cumulative offsets
        import numpy as np
        lat9 = 14.5850
        n9 = int(np.argmin(np.abs(net.node_lat - lat9)))
        e1 = [e for e in range(net.num_edges) if net.edge_end[e] == n9][0]
        e2 = [e for e in range(net.num_edges) if net.edge_start[e] == n9][0]
        assert int(net.edge_segment_id[e1]) == int(net.edge_segment_id[e2])
        assert net.edge_segment_offset_m[e1] == pytest.approx(0.0)
        assert net.edge_segment_offset_m[e2] == pytest.approx(
            net.edge_length_m[e1], rel=1e-5)
        sid = int(net.edge_segment_id[e1])
        assert net.segment_length_m[sid] == pytest.approx(
            float(net.edge_length_m[e1] + net.edge_length_m[e2]), rel=1e-5)

    def test_no_drivable_ways_raises(self):
        xml = ('<?xml version="1.0"?><osm>'
               '<node id="1" lat="0" lon="0"/></osm>')
        with pytest.raises(ValueError):
            network_from_osm_xml(io.BytesIO(xml.encode()))

    def test_roundtrip_npz(self, net, tmp_path):
        from reporter_tpu.graph.network import RoadNetwork
        p = tmp_path / "osm.npz"
        net.save(str(p))
        back = RoadNetwork.load(str(p))
        assert back.num_edges == net.num_edges
        np.testing.assert_array_equal(back.edge_segment_id,
                                      net.edge_segment_id)


class TestMatchOnImported:
    def test_trace_matches_primary_street(self, net):
        """Probes along the primary way decode to its OSMLR segment."""
        from reporter_tpu.matcher import MatchParams, SegmentMatcher

        m = SegmentMatcher(net=net, params=MatchParams(max_candidates=4))
        rng = np.random.default_rng(0)
        # walk node 1 -> 3 (indices 0..2) at ~30 km/h with 3 m noise
        lats = np.linspace(14.5800, 14.5820, 12)
        pts = [{"lat": float(la + rng.normal(0, 3e-5)),
                "lon": float(121.0 + rng.normal(0, 3e-5)),
                "time": 1500000000 + i * 7} for i, la in enumerate(lats)]
        out = m.match_many([{"uuid": "osm-veh", "trace": pts}])[0]
        sids = {s.get("segment_id") for s in out["segments"]
                if "segment_id" in s}
        e_fwd = _edges_between(net, 0, 1)[0]
        assert int(net.edge_segment_id[e_fwd]) in sids


class TestQueueLength:
    """queue_length = slow tail measured from the segment end
    (reference README.md:283)."""

    def _match(self, net, pts):
        from reporter_tpu.matcher import MatchParams, SegmentMatcher
        m = SegmentMatcher(net=net, params=MatchParams(max_candidates=4))
        return m.match_many([{"uuid": "q", "trace": pts}])[0]

    def test_stalled_tail_reports_queue(self, net):
        # fast along the primary, then creep near the segment end
        pts, t = [], 1500000000
        for la in np.linspace(14.5800, 14.58145, 8):
            pts.append({"lat": float(la), "lon": 121.0, "time": t}); t += 3
        for i in range(4):  # ~1.6 m / 7 s ≈ 0.8 km/h
            pts.append({"lat": 14.58146 + i * 1.5e-5, "lon": 121.0,
                        "time": t}); t += 7
        out = self._match(net, pts)
        # the way splits into per-block OSMLR segments at node 2; the
        # stall sits on the 2->3 piece, so find the queued segment
        seg = max((s for s in out["segments"] if "segment_id" in s),
                  key=lambda s: s["queue_length"])
        assert seg["queue_length"] > 20
        sid = seg["segment_id"]
        assert seg["queue_length"] <= net.segment_length_m[sid]

    def test_free_flow_has_no_queue(self, net):
        pts = [{"lat": float(la), "lon": 121.0, "time": 1500000000 + i * 3}
               for i, la in enumerate(np.linspace(14.5800, 14.5818, 10))]
        out = self._match(net, pts)
        for s in out["segments"]:
            assert s["queue_length"] == 0

    def test_midsegment_slowdown_then_recovery_clears_queue(self, net):
        # slow in the MIDDLE of the 2->3 block-segment (the way splits at
        # node 2 now), fast again before its end: queue resets to 0
        pts, t = [], 1500000000
        for la in np.linspace(14.5810, 14.58125, 4):
            pts.append({"lat": float(la), "lon": 121.0, "time": t}); t += 3
        for i in range(3):  # crawl mid-segment
            pts.append({"lat": 14.5813 + i * 1.5e-5, "lon": 121.0,
                        "time": t}); t += 7
        for la in np.linspace(14.5815, 14.5819, 5):
            pts.append({"lat": float(la), "lon": 121.0, "time": t}); t += 3
        out = self._match(net, pts)
        for s in out["segments"]:
            assert s["queue_length"] == 0

    def test_far_from_end_stall_reports_no_queue(self, net):
        # stall early in a LONG segment (>100 m from its end): the
        # segment end was never observed, so no queue may be
        # extrapolated. Way 107 (nodes 8->10, ~222 m) has no interior
        # junction, so it stays one segment after splitting.
        pts, t = [], 1500000000
        for la in np.linspace(14.5840, 14.5843, 4):
            pts.append({"lat": float(la), "lon": 121.001, "time": t})
            t += 3
        for i in range(4):
            pts.append({"lat": 14.58432 + i * 1.5e-5, "lon": 121.001,
                        "time": t}); t += 7
        out = self._match(net, pts)
        for s in out["segments"]:
            assert s["queue_length"] == 0

    def test_offnetwork_tail_reports_no_queue(self, net):
        # trailing points with no candidates (vehicle left the mapped
        # network) must not be mistaken for a stalled queue
        pts, t = [], 1500000000
        for la in np.linspace(14.5800, 14.58145, 8):
            pts.append({"lat": float(la), "lon": 121.0, "time": t}); t += 3
        for i in range(4):  # far off any road, minutes of dwell
            pts.append({"lat": 14.60, "lon": 121.05, "time": t}); t += 60
        out = self._match(net, pts)
        for s in out["segments"]:
            assert s["queue_length"] == 0
