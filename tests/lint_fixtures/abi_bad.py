"""Deliberately MISMATCHED binding for abi_good.cpp — every drift class
the ABI pass must catch (parsed, never imported)."""
import ctypes

import numpy as np

ABI_VERSION = 8        # ABI004: cpp returns 11


def bind(lib):
    c_i32p = np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS")
    c_f32p = np.ctypeslib.ndpointer(np.float32, flags="C_CONTIGUOUS")
    c_i64p = np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS")
    lib.rt_abi_version.restype = ctypes.c_int32
    lib.rt_abi_version.argtypes = []
    # ABI003: arg 1 must be f64* (ndpointer float64), not f32*
    # ABI005: restype dropped — C returns void*
    lib.rt_thing_create.argtypes = [
        ctypes.c_int64, c_f32p, c_f32p, ctypes.c_double]
    lib.rt_thing_destroy.argtypes = [ctypes.c_void_p]
    # ABI002: out_scores missing (5 argtypes vs 6 C parameters)
    # and arg 4 is i32* where C wants i64* (masked by the arity error)
    lib.rt_thing_run.restype = ctypes.c_int64
    lib.rt_thing_run.argtypes = [
        ctypes.c_void_p, ctypes.c_int64, c_i32p, ctypes.c_char_p,
        c_i64p]
    # ABI001: no such export in the C++ fixture
    lib.rt_thing_missing.argtypes = [ctypes.c_void_p]
    return lib
