"""Known-BAD jit-hygiene snippets: every marked line must fire.

AST-only fixture (never imported); the imports below exist so the pass's
alias resolution sees the standard names.
"""
import functools

import jax
import jax.numpy as jnp
import numpy as np


@jax.jit
def host_sync(x):
    y = np.asarray(x)                   # JH001: numpy call on a tracer
    z = jax.device_get(y)               # JH001: device_get in jit
    z.block_until_ready()               # JH001: host sync
    return float(x) + x.item()          # JH001 (x2): float() cast + .item()


@jax.jit
def weak_types(x):
    bias = jnp.array(0.5)               # JH002: dtype-less constructor
    acc = jnp.zeros(x.shape[0])         # JH002: dtype-less constructor
    return (x + bias + acc).astype(float)   # JH002: builtin float dtype


@functools.partial(jax.jit, static_argnames=("flag",))
def branches(x, flag):
    if flag:                            # OK: static argument
        x = x * 2
    if x[0] > 0:                        # JH003: branch on traced values
        x = x + 1
    y = x - 1 if x.sum() > 0 else x     # JH003: ternary on traced values
    return y


def helper(v):
    while v > 0:                        # JH003: reached from the entry below
        v = v - 1
    return v


@jax.jit
def entry_calls_helper(x):
    return helper(x)
