"""Lock-graph fixture: a cycle and blocking calls under locks.

Findings anchor at the ``with`` acquisition line; the LD002 cycle is
reported once, at the acquisition that closes it (Right -> Left).
"""
import subprocess
import threading
import urllib.request


class Left:
    def __init__(self, right):
        self._lock = threading.Lock()
        self.right = right

    def poke(self):
        with self._lock:  # (records the Left -> Right edge)
            self.right.look()

    def peek(self):
        with self._lock:
            return 1


class Right:
    def __init__(self, left):
        self._lock = threading.Lock()
        self.left = left

    def poke(self):
        with self._lock:  # LD002: closes the Left->Right->Left cycle
            self.left.peek()

    def look(self):
        with self._lock:
            return 2


class Fetcher:
    def __init__(self):
        self._lock = threading.Lock()
        self.lib = None

    def fetch(self, url):
        with self._lock:  # LD003: HTTP under a lock
            return urllib.request.urlopen(url)

    def rebuild(self):
        with self._lock:  # LD003: subprocess under a lock
            subprocess.run(["make"], check=True)

    def native(self, handle):
        with self._lock:  # LD003: rt_* native under a lock
            return self.lib.rt_prepare_batch(handle)

    def indirect(self, url):
        with self._lock:  # LD003: HTTP via a resolvable helper
            return self._do_fetch(url)

    def _do_fetch(self, url):
        return urllib.request.urlopen(url)


class Amber:
    """Locks reached through executor.submit / Thread(target=...): the
    pre-ISSUE 10 blind spot — the callback runs on a pool thread, but
    the submit-then-result()/join() idiom couples the held lock to
    everything the callback acquires."""

    def __init__(self, pool, blue):
        self._lock = threading.Lock()
        self.pool = pool
        self.blue = blue

    def go(self):
        with self._lock:  # (records the Amber -> Blue edge via submit)
            return self.pool.submit(self.blue.grab_blue).result()

    def peek_amber(self):
        with self._lock:
            return 1


class Blue:
    def __init__(self, amber):
        self._lock = threading.Lock()
        self.amber = amber

    def grab_blue(self):
        with self._lock:
            return 2

    def back(self):
        with self._lock:  # LD002: closes Amber->Blue->Amber (Thread target)
            thread = threading.Thread(target=self.amber.peek_amber)
            thread.start()
            thread.join()


class PoolFetcher:
    def __init__(self, pool):
        self._lock = threading.Lock()
        self.pool = pool

    def kick(self):
        with self._lock:  # LD003: HTTP via a submitted callback
            return self.pool.submit(self._work).result()

    def spawn(self):
        with self._lock:  # LD003: HTTP via a Thread target
            thread = threading.Thread(target=self._work)
            thread.start()
            thread.join()

    def _work(self):
        return urllib.request.urlopen("http://example.com")
