"""Lock-graph fixture: a cycle and blocking calls under locks.

Findings anchor at the ``with`` acquisition line; the LD002 cycle is
reported once, at the acquisition that closes it (Right -> Left).
"""
import subprocess
import threading
import urllib.request


class Left:
    def __init__(self, right):
        self._lock = threading.Lock()
        self.right = right

    def poke(self):
        with self._lock:  # (records the Left -> Right edge)
            self.right.look()

    def peek(self):
        with self._lock:
            return 1


class Right:
    def __init__(self, left):
        self._lock = threading.Lock()
        self.left = left

    def poke(self):
        with self._lock:  # LD002: closes the Left->Right->Left cycle
            self.left.peek()

    def look(self):
        with self._lock:
            return 2


class Fetcher:
    def __init__(self):
        self._lock = threading.Lock()
        self.lib = None

    def fetch(self, url):
        with self._lock:  # LD003: HTTP under a lock
            return urllib.request.urlopen(url)

    def rebuild(self):
        with self._lock:  # LD003: subprocess under a lock
            subprocess.run(["make"], check=True)

    def native(self, handle):
        with self._lock:  # LD003: rt_* native under a lock
            return self.lib.rt_prepare_batch(handle)

    def indirect(self, url):
        with self._lock:  # LD003: HTTP via a resolvable helper
            return self._do_fetch(url)

    def _do_fetch(self, url):
        return urllib.request.urlopen(url)
