"""Known-GOOD hot-path snippets: the pass must stay silent here.

The matching negatives for hotpath_bad.py — the approved columnar idioms
for the same jobs.
"""
import numpy as np


def ingest(traces):
    # columnar: one bulk conversion, no per-point statement loop
    counts = [len(r["trace"]) for r in traces]
    lat = np.fromiter(
        (p["lat"] for r in traces for p in r["trace"]),
        np.float64, sum(counts))
    return lat


def rebuild_columnar(lat):
    return float(np.sum(lat))


def format_rows(rows):
    # bulk convert ONCE, in the loop header (runs once) — then index
    doubled = (rows * 2)
    out = []
    for r, v in zip(rows.tolist(), doubled.tolist()):
        out.append((r, v))
    return out


def chunk_indices(idxs, chunk):
    # loops over index ranges are structure, not trace data
    parts = []
    for lo in range(0, len(idxs), chunk):
        parts.append(idxs[lo:lo + chunk])
    return parts


def suppressed_edge(rows):
    results = []
    for r in rows:
        # a documented boundary may opt out explicitly:
        entry = {"id": r}  # lint: ignore[HP002]
        results.append(entry)
    return results
