"""Lock-graph fixture: ordered nesting and lock-free blocking calls."""
import subprocess
import threading
import urllib.request


class Outer:
    """Consistent one-way nesting (outer -> inner) is not a cycle."""

    def __init__(self, inner):
        self._lock = threading.Lock()
        self.inner = inner

    def poke(self):
        with self._lock:
            self.inner.observe()


class Inner:
    def __init__(self):
        self._lock = threading.Lock()

    def observe(self):
        with self._lock:
            return 1


class Fetcher:
    """Blocking work runs OUTSIDE the lock; the lock guards the cache."""

    def __init__(self):
        self._lock = threading.Lock()
        self.cache = {}

    def fetch(self, url):
        body = urllib.request.urlopen(url)
        with self._lock:
            self.cache[url] = body
        return body

    def rebuild(self):
        proc = subprocess.run(["make"], check=True)
        with self._lock:
            self.cache.clear()
        return proc


class GoodPool:
    """Submit and join OUTSIDE the lock; the lock only guards the
    cache — the callback's blocking work never runs under it."""

    def __init__(self, pool):
        self._lock = threading.Lock()
        self.pool = pool
        self.cache = {}

    def kick(self, url):
        future = self.pool.submit(self._fetch, url)
        body = future.result()
        with self._lock:
            self.cache[url] = body
        return body

    def _fetch(self, url):
        return urllib.request.urlopen(url)
