"""Fallback fixture: a breaker whose domain has no FALLBACK_PAIRS entry
(against injected pairs covering only ``covered.circuit``)."""
from reporter_tpu.utils.circuit import CircuitBreaker

covered = CircuitBreaker("covered.circuit", threshold=3, cooldown_s=1.0)
orphan = CircuitBreaker("orphan.circuit", threshold=3, cooldown_s=1.0)  # FB001: domain not in FALLBACK_PAIRS
