"""Durability fixture: every DUR rule fires where annotated."""
import json
import os


def bare_write(root, name, payload):
    path = os.path.join(root, name)
    with open(path, "w") as f:  # DUR001: torn-file window
        f.write(payload)


def unsynced_replace(root, manifest):
    tmp = os.path.join(root, ".m.tmp")
    with open(tmp, "w") as f:
        json.dump(manifest, f)
    os.replace(tmp, os.path.join(root, "m"))  # DUR002: no fsync  # DUR003: no dir fsync


def no_dir_fsync(root, manifest):
    tmp = os.path.join(root, ".m.tmp")
    with open(tmp, "w") as f:
        json.dump(manifest, f)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, os.path.join(root, "m"))  # DUR003: rename not durable


def commit_before_ack(state, anonymiser):
    epoch = anonymiser.flush_epoch
    state.commit_epoch(epoch)  # DUR004: marker before the sink ack
    anonymiser.punctuate()


def commit_without_ack(state, anonymiser):  # (never acks at all)
    state.commit_epoch(anonymiser.flush_epoch)  # DUR004: marker before the sink ack


def missing_commit(state, anonymiser):  # DUR004: contract, no commit call
    anonymiser.punctuate()
