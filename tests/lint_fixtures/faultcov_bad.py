"""Fault-coverage fixture: unknown and unauditable failpoint sites
(against an injected registry of ``{"known.site"}``)."""
from reporter_tpu.utils import faults


def hooked(site_var):
    faults.failpoint("known.site")
    faults.failpoint("not.a.site")  # FP001: site unknown to the registry
    faults.failpoint(site_var)  # FP001: non-literal site name
