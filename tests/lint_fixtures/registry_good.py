"""Registry-drift fixture: registered names and unresolvable-by-design
dynamic sites stay silent (against the same injected registry as the
bad fixture)."""
import os

from reporter_tpu.utils import metrics


def read_known_knob():
    return os.environ.get("REPORTER_TPU_KNOWN")


def emit_known_metrics(code, name):
    metrics.count("known.metric")
    metrics.count(f"family.{code}")
    metrics.observe("known.metric", 0.5)
    # dynamic from the first character: unauditable, skipped (register
    # the instantiated family as a pattern instead)
    metrics.count(f"{name}.opened")
    metrics.count(name)
