"""Known-GOOD jit-hygiene snippets: the pass must stay silent here."""
import functools

import jax
import jax.numpy as jnp
import numpy as np


@jax.jit
def device_pure(x):
    z = x / jnp.float32(2.0)
    w = jnp.where(x > 0, z, -z)         # data-dependence via jnp.where
    return jnp.zeros(x.shape, dtype=jnp.float32) + w


@jax.jit
def shape_branches(x, y):
    # shape/dtype branches are static under trace — the sanctioned
    # pattern (matcher/hmm.py trim_time_pad)
    if x.shape[-1] == y.shape[-1] + 1:
        x = x[..., :-1]
    if x.dtype == jnp.float16:
        x = x.astype(jnp.float32)
    return x + y


@functools.partial(jax.jit, static_argnames=("interpret",))
def static_branch(x, interpret=False):
    if interpret:
        return x
    return x * 2


def host_prep(x):
    # NOT reachable from any jit entry: numpy is fine on the host side
    arr = np.asarray(x)
    if arr[0] > 0:
        arr = arr + 1
    return float(arr.sum())


def entry_builder():
    # jitting a named function by call-site also marks it (the pass
    # resolves jax.jit(f) assignments); device_pure is already clean
    return jax.jit(device_pure.__wrapped__)
