"""Tensor-contract fixture: an uncontracted jit entry, a both-weak
``jnp.where``, and array-valued statics (against injected
``contracts={"reporter_tpu/ops/fixture_bad.py::contracted": ...}``,
``full_scope=False``)."""
from functools import partial

import jax
import jax.numpy as jnp

NEG_INF = -1.0e30


@jax.jit
def uncontracted(x):  # TC002: jit entry with no KERNEL_CONTRACTS row
    return x * 2.0


@partial(jax.jit, static_argnames=("table", "missing"))
def contracted(x, table):  # TC004: static 'missing' names no parameter
    gap = jnp.where(x > 0, 0.0, NEG_INF)  # TC003: both branches weak
    row = table[0]  # TC004: static 'table' subscripted like an array
    return gap + row + x
