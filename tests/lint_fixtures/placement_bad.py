"""Placement fixture: undisciplined host materialisation on a device
lane (against injected ``lanes=(...::Lane.stage,)``,
``sync_points=(...::Lane.drain,)``, ``entry_names={"kernel_entry"}``)."""
import numpy as np


def kernel_entry(x):
    return x


class Lane:
    def stage(self, batch):
        out = kernel_entry(batch)
        host = np.asarray(out)  # DP001: d2h materialisation outside SYNC_POINTS
        for _ in range(3):
            y = kernel_entry(batch)
            val = float(y)  # DP002: host cast inside a dispatching loop
        arr = np.zeros(4)
        res = kernel_entry(arr)  # DP003: bare numpy array into a jit entry
        return self.helper(), host, val, res

    def helper(self):
        d = kernel_entry(np.ones(2))
        return d.item()  # DP001: reachable helper materialises its dispatch

    def drain(self, out):
        return np.asarray(out)  # the declared sync point: legal site
