"""Known-BAD lock-discipline snippets: every marked line must fire."""
import threading

pending = {}
_state_lock = threading.Lock()


def enqueue(key, value):
    with _state_lock:
        pending[key] = value


def drop_unlocked(key):
    pending.pop(key, None)              # LD001: locked in enqueue, not here


class Counter:
    def __init__(self):
        self._lock = threading.Lock()
        self.total = 0                  # construction writes are fine
        self.events = []

    def add(self, n):
        with self._lock:
            self.total += n
            self.events.append(n)

    def reset_unlocked(self):
        self.total = 0                  # LD001: written under lock in add
        self.events.clear()             # LD001: written under lock in add
