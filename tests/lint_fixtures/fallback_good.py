"""Fallback fixture: every constructed breaker domain carries a full
FALLBACK_PAIRS entry (fault site + kill switch + parity test)."""
from reporter_tpu.utils.circuit import CircuitBreaker

covered = CircuitBreaker("covered.circuit", threshold=3, cooldown_s=1.0)
