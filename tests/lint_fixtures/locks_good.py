"""Known-GOOD lock-discipline snippets: the pass must stay silent here."""
import threading

pending = {}
_state_lock = threading.Lock()


def enqueue(key, value):
    with _state_lock:
        pending[key] = value


def drop(key):
    with _state_lock:
        pending.pop(key, None)          # every write under the lock


class Counter:
    def __init__(self):
        self._lock = threading.Lock()
        self.total = 0
        self.events = []

    def add(self, n):
        with self._lock:
            self.total += n
            self.events.append(n)

    def reset(self):
        with self._lock:
            self.total = 0
            self.events.clear()


class LockFree:
    """No lock anywhere: a single-threaded or queue-mediated design is
    not a LD001 violation (nothing established a locking convention)."""

    def __init__(self):
        self.seen = 0

    def bump(self):
        self.seen += 1
