"""Fault-coverage fixture: registered sites, both hook positions
(against an injected registry of ``{"known.site"}``)."""
from reporter_tpu.utils import faults


def hooked(effect):
    faults.failpoint("known.site")
    result = effect()
    faults.failpoint("known.site", after=True)
    return result
