"""Registry-drift fixture: unregistered knob reads and metric names.

The fixture tests run this against an injected registry of
``{REPORTER_TPU_KNOWN}`` / ``{"known.metric", "family.*"}``.
"""
import os

from reporter_tpu.utils import metrics


def read_unknown_knob():
    os.environ.get("REPORTER_TPU_KNOWN")
    return os.environ.get("REPORTER_TPU_NOT_REGISTERED")  # KN001: unregistered knob


def emit_unknown_metric(code):
    metrics.count("known.metric")
    metrics.count(f"family.{code}")
    metrics.count("rogue.metric")  # MT001: unregistered literal
    with metrics.timer(f"other.family.{code}"):  # MT001: unregistered f-string family
        pass
