"""Known-BAD hot-path snippets: every marked line must fire.

Parsed by reporter_tpu.analysis.hotpath under a fake hot-path relpath —
never imported or executed (numpy-ish names are just names to the AST).
"""


def ingest(traces):
    out = []
    for req in traces:
        for p in req["trace"]:          # HP001: per-element loop over trace
            out.append(p["lat"])
    return out


def rebuild(points):
    total = 0.0
    for p in points:                    # HP001: per-element loop over points
        total += p.lat
    return total


def format_rows(rows):
    results = []
    for r in rows:
        entry = {"id": r, "v": r * 2}   # HP002: dict built inside a loop
        results.append(entry)
    return results


def collect(arrs):
    vals = []
    for a in arrs:
        vals.append(a.tolist())         # HP003: .tolist() in a loop body
    return vals


def scalarise(arr):
    return arr[0].item()                # HP003: .item() extraction
