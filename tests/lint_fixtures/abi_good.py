"""Matched ctypes binding for abi_good.cpp (parsed, never imported)."""
import ctypes

import numpy as np

ABI_VERSION = 11


def bind(lib):
    c_i32p = np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS")
    c_f32p = np.ctypeslib.ndpointer(np.float32, flags="C_CONTIGUOUS")
    c_f64p = np.ctypeslib.ndpointer(np.float64, flags="C_CONTIGUOUS")
    c_i64p = np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS")
    lib.rt_abi_version.restype = ctypes.c_int32
    lib.rt_abi_version.argtypes = []
    lib.rt_thing_create.restype = ctypes.c_void_p
    lib.rt_thing_create.argtypes = [
        ctypes.c_int64, c_f64p, c_f32p, ctypes.c_double]
    lib.rt_thing_destroy.argtypes = [ctypes.c_void_p]
    lib.rt_thing_run.restype = ctypes.c_int64
    lib.rt_thing_run.argtypes = [
        ctypes.c_void_p, ctypes.c_int64, c_i32p, ctypes.c_char_p,
        c_i64p, c_f32p]
    return lib
