"""Placement fixture: the disciplined lane — dispatch stays device-side,
the only materialisation lives in the declared sync point, and scalar
``bool()`` convergence syncs stay legal (not a DP sink)."""
import numpy as np


def kernel_entry(x):
    return x


class Lane:
    def stage(self, batch):
        out = kernel_entry(batch)
        if bool(out):
            return self.drain(out)
        return out

    def drain(self, out):
        return np.asarray(out)  # the declared SYNC_POINTS site
