"""Durability fixture: the disciplined patterns stay silent."""
import json
import os

from reporter_tpu.utils import fsio


def atomic_helper_write(root, name, payload):
    # routed through the verified commit helper: no local discipline
    fsio.atomic_write_text(os.path.join(root, name), payload)


def full_protocol(root, manifest):
    tmp = os.path.join(root, ".m.tmp")
    with open(tmp, "w") as f:
        json.dump(manifest, f)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, os.path.join(root, "m"))
    fsio.fsync_dir(root)


def read_paths_are_fine(root):
    with open(os.path.join(root, "m")) as f:
        return json.load(f)


def quarantine_rename(root, name):
    # renaming an already-committed file is not a tmp-commit: exempt
    os.replace(os.path.join(root, name),
               os.path.join(root, f".{name}.failed"))


def commit_after_ack(state, anonymiser):
    epoch = anonymiser.flush_epoch
    written = anonymiser.punctuate()
    if written > 0:
        state.commit_epoch(epoch)
