// Mini host-runtime fixture: a matched extern "C" surface for the ABI
// cross-check tests (never compiled — parsed by analysis/abi.py).
#include <cstdint>

extern "C" {

int32_t rt_abi_version(void) { return 11; }

void* rt_thing_create(int64_t n, const double* xs, const float* ws,
                      double scale) {
  (void)n; (void)xs; (void)ws; (void)scale;
  return nullptr;
}

void rt_thing_destroy(void* handle) { (void)handle; }

// multi-line signatures and 8-bit/64-bit pointer classes
int64_t rt_thing_run(void* handle, int64_t count, const int32_t* ids,
                     const uint8_t* flags, int64_t* out_vals,
                     float* out_scores) {
  (void)handle; (void)count; (void)ids; (void)flags;
  (void)out_vals; (void)out_scores;
  return 0;
}

}  // extern "C"
