"""Tensor-contract fixture: a contracted entry using only the sanctioned
weak-scalar idioms (one weak branch against an array operand, explicit
jnp dtypes) and shape-only statics."""
from functools import partial

import jax
import jax.numpy as jnp

NEG_INF = -1.0e30


@partial(jax.jit, static_argnames=("n",))
def contracted(x, n):
    pad = jnp.zeros((n,), dtype=jnp.float32)
    one_weak = jnp.where(x > 0, -0.5 * x, NEG_INF)
    pinned = jnp.where(x > 0, jnp.float32(0.0), jnp.float32(NEG_INF))
    return one_weak + pinned + pad
