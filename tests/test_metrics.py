"""Metrics registry + /stats endpoint (the tracing/profiling subsystem
SURVEY.md §5 lists as absent in the reference and built fresh here)."""
import threading
import time

from reporter_tpu.utils.metrics import (BUCKET_BOUNDS_S, Registry,
                                        bucket_index, device_trace,
                                        snapshot_rounded)


class TestRegistry:
    def test_counters_accumulate(self):
        r = Registry()
        assert r.count("a") == 1
        assert r.count("a", 5) == 6
        assert r.count("b") == 1
        assert r.snapshot()["counters"] == {"a": 6, "b": 1}

    def test_timer_records_count_total_max(self):
        r = Registry()
        with r.timer("stage"):
            time.sleep(0.01)
        with r.timer("stage"):
            pass
        t = r.snapshot()["timers"]["stage"]
        assert t["count"] == 2
        assert t["total_s"] >= 0.01
        assert t["max_s"] >= 0.01
        assert t["mean_s"] <= t["max_s"]

    def test_timer_records_on_exception(self):
        r = Registry()
        try:
            with r.timer("boom"):
                raise ValueError
        except ValueError:
            pass
        assert r.snapshot()["timers"]["boom"]["count"] == 1

    def test_observe_external_duration(self):
        r = Registry()
        r.observe("x", 1.5)
        assert r.snapshot()["timers"]["x"]["total_s"] == 1.5

    def test_thread_safety(self):
        r = Registry()

        def work():
            for _ in range(1000):
                r.count("n")

        threads = [threading.Thread(target=work) for _ in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert r.snapshot()["counters"]["n"] == 8000

    def test_reset(self):
        r = Registry()
        r.count("a")
        r.observe("t", 1.0)
        r.reset()
        assert r.snapshot() == {"counters": {}, "timers": {}}

    def test_reset_timers_keeps_counters(self):
        """Bench legs isolate one stage's histogram without dropping
        cache-hit/egress counters accumulated across legs."""
        r = Registry()
        r.count("egress.ok", 7)
        r.observe("stage", 0.5)
        r.reset_timers()
        snap = r.snapshot()
        assert snap["timers"] == {}
        assert snap["counters"] == {"egress.ok": 7}


class TestHistogramTimers:
    def test_sub_microsecond_mean_not_collapsed(self):
        """The old snapshot() rounded to 6 decimals, flattening sub-µs
        timers to 0.0 — raw floats now, rounding is the wire's job."""
        r = Registry()
        for _ in range(4):
            r.observe("tiny", 5e-7)
        t = r.snapshot()["timers"]["tiny"]
        assert t["mean_s"] == 5e-7
        assert t["total_s"] == 2e-6
        # the /stats writer rounds at nanosecond resolution: still visible
        rounded = snapshot_rounded(r)["timers"]["tiny"]
        assert rounded["mean_s"] == 5e-7

    def test_bucket_index_log2(self):
        assert bucket_index(0.0) == 0
        assert bucket_index(1e-12) == 0  # below the smallest bound
        # a value lands in a bucket whose bound is >= the value
        for v in (3e-6, 0.004, 0.7, 10.0):
            idx = bucket_index(v)
            assert BUCKET_BOUNDS_S[idx] >= v
            if idx > 0:
                assert BUCKET_BOUNDS_S[idx - 1] <= v * 2
        # past the largest bound: the overflow bucket
        assert bucket_index(1e6) == len(BUCKET_BOUNDS_S)

    def test_percentiles_ordered_and_bounded(self):
        r = Registry()
        for ms in (1, 2, 3, 4, 5, 6, 7, 8, 9, 200):
            r.observe("stage", ms / 1000.0)
        t = r.snapshot()["timers"]["stage"]
        assert 0.0 < t["p50_s"] <= t["p95_s"] <= t["p99_s"] <= t["max_s"]
        # the one 200 ms outlier must pull p99 well above p50: this is
        # exactly the tail count/total/max could not see
        assert t["p99_s"] > 0.05
        assert t["p50_s"] < 0.02

    def test_percentiles_single_observation(self):
        r = Registry()
        r.observe("once", 0.01)
        t = r.snapshot()["timers"]["once"]
        assert t["p50_s"] == t["p99_s"] == t["max_s"] == 0.01

    def test_export_state_buckets_sum_to_count(self):
        r = Registry()
        for v in (1e-7, 1e-3, 0.3, 50.0, 1e4):
            r.observe("s", v)
        _counters, timers = r.export_state()
        count, total, max_s, buckets = timers["s"]
        assert count == 5 and sum(buckets) == 5
        assert max_s == 1e4 and abs(total - 10050.3011) < 1e-3
        # one overflow landed past the largest bound
        assert buckets[-1] == 1


class TestDeviceTrace:
    def test_trace_context_produces_profile(self, tmp_path):
        import jax
        import jax.numpy as jnp
        with device_trace(str(tmp_path)):
            jnp.ones(8).sum().block_until_ready()
        # jax writes trace events under plugins/profile/<run>/
        produced = list(tmp_path.rglob("*"))
        assert produced, "no profiler output written"
