"""Metrics registry + /stats endpoint (the tracing/profiling subsystem
SURVEY.md §5 lists as absent in the reference and built fresh here)."""
import threading
import time

from reporter_tpu.utils.metrics import Registry, device_trace


class TestRegistry:
    def test_counters_accumulate(self):
        r = Registry()
        assert r.count("a") == 1
        assert r.count("a", 5) == 6
        assert r.count("b") == 1
        assert r.snapshot()["counters"] == {"a": 6, "b": 1}

    def test_timer_records_count_total_max(self):
        r = Registry()
        with r.timer("stage"):
            time.sleep(0.01)
        with r.timer("stage"):
            pass
        t = r.snapshot()["timers"]["stage"]
        assert t["count"] == 2
        assert t["total_s"] >= 0.01
        assert t["max_s"] >= 0.01
        assert t["mean_s"] <= t["max_s"]

    def test_timer_records_on_exception(self):
        r = Registry()
        try:
            with r.timer("boom"):
                raise ValueError
        except ValueError:
            pass
        assert r.snapshot()["timers"]["boom"]["count"] == 1

    def test_observe_external_duration(self):
        r = Registry()
        r.observe("x", 1.5)
        assert r.snapshot()["timers"]["x"]["total_s"] == 1.5

    def test_thread_safety(self):
        r = Registry()

        def work():
            for _ in range(1000):
                r.count("n")

        threads = [threading.Thread(target=work) for _ in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert r.snapshot()["counters"]["n"] == 8000

    def test_reset(self):
        r = Registry()
        r.count("a")
        r.observe("t", 1.0)
        r.reset()
        assert r.snapshot() == {"counters": {}, "timers": {}}


class TestDeviceTrace:
    def test_trace_context_produces_profile(self, tmp_path):
        import jax
        import jax.numpy as jnp
        with device_trace(str(tmp_path)):
            jnp.ones(8).sum().block_until_ready()
        # jax writes trace events under plugins/profile/<run>/
        produced = list(tmp_path.rglob("*"))
        assert produced, "no profiler output written"
