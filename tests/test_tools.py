"""Ops tooling: replay producer, print-consumer rendering, tiles CLI,
umbrella entry point."""
import http.server
import io
import json
import os
import tarfile
import threading

import pytest

from reporter_tpu.core.types import Point, Segment
from reporter_tpu.tools.print_consumer import render
from reporter_tpu.tools.replay import bbox_send_if, replay
from reporter_tpu.tools.tiles_cli import download_tiles, list_tiles


class TestReplay:
    def test_lambdas_applied(self):
        lines = ["a|1", "b|2", "skip|3"]
        sent = []
        n_sent, n_total = replay(
            lines, lambda k, v: sent.append((k, v)),
            key_with=lambda l: l.split("|")[0],
            value_with=lambda l: l.upper(),
            send_if=lambda l: not l.startswith("skip"))
        assert n_sent == 2 and n_total == 3
        assert sent == [("a", "A|1"), ("b", "B|2")]

    def test_bad_line_skipped_not_fatal(self):
        # reference: cat_to_kafka.py:62-65 — per-line failure logged, loop
        # continues
        lines = ["good", "bad", "good"]

        def key_with(l):
            if l == "bad":
                raise ValueError("boom")
            return l

        sent = []
        n_sent, n_total = replay(lines, lambda k, v: sent.append(k),
                                 key_with=key_with)
        assert n_sent == 2 and n_total == 3

    def test_bbox_filter(self):
        # reference: make_requests.sh:38-44
        send_if = bbox_send_if([120.0, 14.0, 122.0, 16.0], "|", 1, 2)
        assert send_if("uuid|15.0|121.0|0|10")
        assert not send_if("uuid|17.0|121.0|0|10")
        assert not send_if("uuid|not_a_number|121.0|0|10")

    def test_cli_stdout_sink(self, capsys, tmp_path):
        from reporter_tpu.tools.replay import main
        src = tmp_path / "in.sv"
        src.write_text("u1|15.0|121.0|0|10\nu2|99.0|121.0|0|10\n")
        assert main([str(src), "--bbox", "120,14,122,16",
                     "--lat-index", "1", "--lon-index", "2"]) == 0
        out = capsys.readouterr().out
        assert "u1|15.0" in out and "u2|99.0" not in out


class TestPrintConsumer:
    def test_renders_point(self):
        p = Point(lat=14.6, lon=121.0, accuracy=10, time=1500000000)
        assert "14.6" in render("formatted", "veh-1", p.to_bytes())

    def test_renders_segment_list(self):
        segs = [Segment(1, 2, 10.0, 20.0, 100, 0),
                Segment(3, None, 20.0, 30.0, 50, 5)]
        raw = b"".join(s.to_bytes() for s in segs)
        text = render("segments", "1 2", raw)
        assert "Segment" in text and "100" in text

    def test_renders_utf8_and_binary(self):
        assert render("raw", None, b"hello") == "None=hello"
        assert render("raw", None, b"\xff\xfe") == "None=fffe"


class TestTilesCli:
    def test_list_matches_library(self):
        from reporter_tpu.core.tiles import tiles_for_bbox
        bbox = [120.9, 14.5, 121.1, 14.7]
        assert list_tiles(bbox) == list(tiles_for_bbox(bbox))

    def test_download_and_tar(self, tmp_path):
        # serve fake tiles from a local dir over HTTP; one path 404s
        bbox = [120.99, 14.59, 121.01, 14.61]
        paths = list_tiles(bbox)
        assert len(paths) >= 3  # one per level
        src = tmp_path / "src"
        for p in paths[:-1]:
            f = src / p
            f.parent.mkdir(parents=True, exist_ok=True)
            f.write_bytes(b"tile:" + p.encode())

        handler = lambda *a, **kw: http.server.SimpleHTTPRequestHandler(
            *a, directory=str(src), **kw)
        httpd = http.server.ThreadingHTTPServer(("127.0.0.1", 0), handler)
        threading.Thread(target=httpd.serve_forever, daemon=True).start()
        try:
            url = f"http://127.0.0.1:{httpd.server_port}"
            out = tmp_path / "out"
            missing = download_tiles(bbox, url, str(out), processes=3,
                                     tar_output=True)
            assert missing == [paths[-1]]
            for p in paths[:-1]:
                assert (out / p).read_bytes() == b"tile:" + p.encode()
            tars = [f for f in os.listdir(out) if f.endswith(".tar")]
            assert len(tars) == 1
            with tarfile.open(out / tars[0]) as tar:
                assert sorted(tar.getnames()) == sorted(paths[:-1])
        finally:
            httpd.shutdown()


class TestDatastoreCli:
    def _flush(self, root, segs):
        tile_dir = os.path.join(root, "1483344000_1483347599", "2", "756425")
        os.makedirs(tile_dir, exist_ok=True)
        with open(os.path.join(tile_dir, "t.abc"), "w") as f:
            f.write("\n".join([Segment.column_layout()]
                              + [s.csv_row("AUTO", "t") for s in segs]))

    def test_ingest_compact_query_stats(self, capsys, tmp_path):
        from reporter_tpu.core.osmlr import make_segment_id
        from reporter_tpu.tools.datastore_cli import main
        sid = make_segment_id(2, 756425, 10)
        segs = [Segment(sid, None, 1483344000 + i * 30,
                        1483344000 + i * 30 + 10, 100, 0) for i in range(8)]
        results = tmp_path / "results"
        store = str(tmp_path / "store")
        self._flush(str(results), segs)

        assert main(["ingest", store, str(results), "--delete"]) == 0
        out = json.loads(capsys.readouterr().out)
        assert out["files"] == 1 and out["rows"] == 8
        assert "datastore.ingest.parse" in out["metrics"]
        # --delete consumed the tile file (replay-safe)
        assert not any(files for _r, _d, files in os.walk(results))

        assert main(["compact", store]) == 0
        assert json.loads(capsys.readouterr().out)["partitions"] == 1

        assert main(["query", store, "--segment", str(sid),
                     "--hours", "7-9", "--percentiles", "50"]) == 0
        q = json.loads(capsys.readouterr().out)
        assert q["count"] == 8 and q["mean_kph"] == pytest.approx(36.0)
        assert list(q["percentiles"]) == ["p50"]

        assert main(["stats", store]) == 0
        s = json.loads(capsys.readouterr().out)
        assert s["partitions"] == 1 and s["rows"] == 8


class TestUmbrella:
    def test_unknown_command(self, capsys):
        from reporter_tpu.__main__ import main
        assert main(["nope"]) == 2
        assert "unknown command" in capsys.readouterr().err

    def test_help(self, capsys):
        from reporter_tpu.__main__ import main
        assert main(["--help"]) == 0
        assert "stream" in capsys.readouterr().out

    def test_dispatch_tiles(self, capsys):
        from reporter_tpu.__main__ import main
        assert main(["tiles", "list", "--bbox", "120.9,14.5,121.1,14.7"]) == 0
        assert "2/" in capsys.readouterr().out


class TestSynthCli:
    def test_sv_and_json_output(self, capsys):
        from reporter_tpu.tools.synth_cli import main
        assert main(["--traces", "2", "--rows", "6", "--cols", "6",
                     "--format", "sv"]) == 0
        sv = capsys.readouterr().out.strip().splitlines()
        assert len(sv) >= 4
        assert all(len(line.split("|")) == 5 for line in sv)
        uuids = {line.split("|")[0] for line in sv}
        assert uuids == {"synth-0", "synth-1"}

        assert main(["--traces", "1", "--rows", "6", "--cols", "6",
                     "--format", "json"]) == 0
        body = json.loads(capsys.readouterr().out)
        assert body["uuid"] == "synth-0"
        assert len(body["trace"]) >= 2
        assert body["match_options"]["report_levels"] == [0, 1]


class TestAccuracyCli:
    def test_gate_passes_on_clean_city(self, capsys):
        from reporter_tpu.tools.accuracy_cli import main
        assert main(["--traces", "8", "--rows", "10", "--cols", "10",
                     "--noise-m", "3.0", "--min-agreement", "0.99"]) == 0
        out = json.loads(capsys.readouterr().out)
        assert out["traces"] == 8
        assert out["agreement"] >= 0.99
        assert out["segment_precision"] >= 0.99
        assert 0.9 <= out["point_agreement"] <= 1.0

    def test_gate_fails_below_threshold(self, capsys):
        from reporter_tpu.tools.accuracy_cli import main
        # an impossible bar guarantees the failure path
        assert main(["--traces", "4", "--rows", "8", "--cols", "8",
                     "--noise-m", "12.0", "--min-agreement", "1.01"]) == 1
