"""Umbrella CLI: ``python -m reporter_tpu <command> [args...]``.

One binary front door for every service and tool in the framework — the
analog of the reference's scattered entry points (reporter-kafka jar,
reporter_service.py, simple_reporter.py, cat_to_kafka.py, get_tiles.py,
PrintConsumer):

  serve            matcher HTTP service (/report)           [reporter_service]
  stream           streaming worker (format/batch/anonymise) [reporter-kafka]
  pipeline         batched 3-stage historical pipeline      [simple_reporter]
  replay           flat file/stdin -> topic/stdout producer [cat_to_kafka]
  print-consumer   debug-print a topic                      [PrintConsumer]
  tiles            list/download graph tiles for a bbox     [get_tiles et al]
  graph            build/tile/inspect road networks   [valhalla build tools]
  synth            synthetic GPS trace generator      [generate_test_trace]
  datastore        histogram datastore: ingest/compact/query/stats
                   over flushed tiles                 [datastore service]
"""
from __future__ import annotations

import sys

COMMANDS = {}


def _cmd(name):
    def register(loader):
        COMMANDS[name] = loader
        return loader
    return register


@_cmd("serve")
def _serve():
    from .service.server import main
    return main


@_cmd("stream")
def _stream():
    from .streaming.worker import main
    return main


@_cmd("pipeline")
def _pipeline():
    from .pipeline.simple_reporter import main
    return main


@_cmd("replay")
def _replay():
    from .tools.replay import main
    return main


@_cmd("print-consumer")
def _print_consumer():
    from .tools.print_consumer import main
    return main


@_cmd("tiles")
def _tiles():
    from .tools.tiles_cli import main
    return main


@_cmd("synth")
def _synth():
    from .tools.synth_cli import main
    return main


@_cmd("graph")
def _graph():
    from .tools.graph_cli import main
    return main


@_cmd("accuracy")
def _accuracy():
    from .tools.accuracy_cli import main
    return main


@_cmd("datastore")
def _datastore():
    from .tools.datastore_cli import main
    return main


def main(argv=None):
    argv = sys.argv[1:] if argv is None else argv
    if not argv or argv[0] in ("-h", "--help"):
        print(__doc__)
        return 0 if argv else 2
    name, rest = argv[0], argv[1:]
    if name not in COMMANDS:
        print(f"unknown command {name!r}; one of: "
              + ", ".join(sorted(COMMANDS)), file=sys.stderr)
        return 2
    return COMMANDS[name]()(rest)


if __name__ == "__main__":
    sys.exit(main())
