"""Multi-host bootstrap: the framework's distributed backbone glue.

The reference scales across machines manually — operators split days of
data across N instances (reference: load-historical-data/README.md) and
Kafka partitions spread uuids across worker processes
(reference: tests/circle.sh:58). The TPU-native equivalents:

- **process bootstrap**: JAX's multi-controller runtime.
  :func:`init_multihost` wraps ``jax.distributed.initialize`` with env-var
  configuration so every entry point (serve/stream/pipeline) can join a
  multi-host job without code changes; after it runs, ``jax.devices()``
  spans all hosts and meshes built by :func:`reporter_tpu.parallel.make_mesh`
  are global — in-pod collectives ride ICI, cross-host legs ride DCN.
- **work partitioning**: :func:`partition_for_host` assigns uuids to hosts
  by stable hash — the Kafka keyed-partition contract (all of one uuid's
  points to one host, preserving per-uuid point order) without Kafka.
"""
from __future__ import annotations

import hashlib
import os
from typing import Optional, Sequence

# env names follow the framework's REPORTER_TPU_* convention; the standard
# JAX cluster envs (coordinator via JAX_COORDINATOR_ADDRESS etc.) also work
ENV_COORDINATOR = "REPORTER_TPU_COORDINATOR"
ENV_NUM_PROCESSES = "REPORTER_TPU_NUM_PROCESSES"
ENV_PROCESS_ID = "REPORTER_TPU_PROCESS_ID"


def init_multihost(coordinator_address: Optional[str] = None,
                   num_processes: Optional[int] = None,
                   process_id: Optional[int] = None) -> bool:
    """Join a multi-host JAX job; no-op for single-host runs.

    Arguments default to ``REPORTER_TPU_COORDINATOR`` /
    ``REPORTER_TPU_NUM_PROCESSES`` / ``REPORTER_TPU_PROCESS_ID``. Returns
    True when distributed initialisation ran, False for the (default)
    single-host path. On TPU pods with standard metadata the address/count
    arguments may all be absent and JAX discovers them; setting only the
    coordinator env is then enough to opt in.
    """
    coordinator_address = coordinator_address \
        or os.environ.get(ENV_COORDINATOR) or None
    if num_processes is None and os.environ.get(ENV_NUM_PROCESSES):
        num_processes = int(os.environ[ENV_NUM_PROCESSES])
    if process_id is None and os.environ.get(ENV_PROCESS_ID):
        process_id = int(os.environ[ENV_PROCESS_ID])

    # no coordinator -> no JAX multi-controller job. NUM_PROCESSES /
    # PROCESS_ID alone still partition the uuid space (host_uuid_filter):
    # N *independent* workers splitting one stream need no collectives and
    # no coordinator. The standard JAX cluster envs opt in too —
    # jax.distributed.initialize auto-detects them when called.
    if coordinator_address is None \
            and not os.environ.get("JAX_COORDINATOR_ADDRESS"):
        return False

    import jax
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id)
    return True


def host_hash(uuid: str) -> int:
    """Stable across processes and runs (unlike builtin hash with
    PYTHONHASHSEED randomisation)."""
    return int.from_bytes(
        hashlib.sha1(uuid.encode("utf-8")).digest()[:8], "big")


def owned_by_host(uuid: str, num_processes: int, process_id: int) -> bool:
    return host_hash(uuid) % num_processes == process_id


def partition_for_host(uuids: Sequence[str], num_processes: int,
                       process_id: int) -> list:
    """Indices of the traces this host owns.

    Same contract as Kafka's uuid-keyed partitions (reference:
    tests/circle.sh:58, README "Kafka stream configuration"): every trace
    of a given uuid lands on exactly one host, hosts partition the uuid
    space disjointly, and the assignment is stable across runs.
    """
    if not 0 <= process_id < num_processes:
        raise ValueError(
            f"process_id {process_id} not in [0, {num_processes})")
    return [i for i, u in enumerate(uuids)
            if owned_by_host(u, num_processes, process_id)]


def host_uuid_filter(num_processes: Optional[int] = None,
                     process_id: Optional[int] = None):
    """Ownership predicate for this host's uuids, or None for single-host.

    Defaults from the REPORTER_TPU_NUM_PROCESSES / REPORTER_TPU_PROCESS_ID
    env. Entry points pass the result to their ingest stage so a shared
    (unpartitioned) input stream is processed exactly once across a
    multi-host job; with a uuid-keyed Kafka topic the broker already
    partitions and this stays None.
    """
    if num_processes is None and os.environ.get(ENV_NUM_PROCESSES):
        num_processes = int(os.environ[ENV_NUM_PROCESSES])
    if process_id is None and os.environ.get(ENV_PROCESS_ID):
        process_id = int(os.environ[ENV_PROCESS_ID])
    if not num_processes or num_processes <= 1:
        return None
    if process_id is None or not 0 <= process_id < num_processes:
        raise ValueError(
            f"process_id {process_id} not in [0, {num_processes})")
    return lambda u: owned_by_host(u, num_processes, process_id)
