"""Sharded batched decode: pjit over the decode mesh.

Two mesh shapes, two contracts:

- the 1-D ``("data",)`` mesh (the serving default, parallel/mesh.py
  ``decode_mesh``): pure batch parallelism — every tensor shards along
  its leading batch axis, params replicate, and NO collective runs in
  the decode, so every backend shards, including the sequential scan.
  Each device runs the identical per-row program it would run alone,
  which is why the sharded scan decode is *bit-identical* to the
  single-device scan decode (the contract tests/test_sharded_decode.py
  pins at 1/2/8 forced host devices).
- the 2-D ``(data, seq)`` mesh (REPORTER_TPU_SEQ_SHARDS > 1): time
  additionally shards along ``seq`` and XLA's GSPMD partitioner inserts
  the max-plus scan's cross-shard combines over ICI — associative
  backend only.
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ops.assoc_viterbi import viterbi_assoc_batch


def shard_batch(mesh: Mesh, dist_m, valid, route_m, gc_m, case):
    """Device-put one padded batch with (data, seq) shardings.

    The batch axis must divide the ``data`` mesh axis and T the ``seq``
    axis (callers pad batches/buckets to multiples — batchpad's
    ``pad_batch_to`` exists for this).

    ``route_m`` is the dominant tensor by a factor of K (B, T-1, K, K);
    its ragged T-1 time axis is padded to T with one dead trailing step
    so it shards along ``seq`` like everything else — per-device bytes
    and h2d for the largest input drop by the seq factor (the round-3
    weakness: it used to replicate along seq). The dead step is sliced
    off inside the jitted decode and never scored.
    """
    def put(x, spec):
        return jax.device_put(x, NamedSharding(mesh, spec))

    B, Tm1 = route_m.shape[0], route_m.shape[1]
    T = dist_m.shape[1]
    if Tm1 == T - 1:
        route_m = np.concatenate(
            [route_m, np.zeros((B, 1) + route_m.shape[2:],
                               dtype=route_m.dtype)], axis=1)
        gc_m = np.concatenate(
            [gc_m, np.zeros((B, 1), dtype=gc_m.dtype)], axis=1)

    return (
        put(dist_m, P("data", "seq", None)),
        put(valid, P("data", "seq", None)),
        put(route_m, P("data", "seq", None, None)),
        put(gc_m, P("data", "seq")),
        put(case, P("data", "seq")),
    )


def shard_batch_data(mesh: Mesh, dist_m, valid, route_m, gc_m, case):
    """Device-put one padded chunk onto a 1-D ``("data",)`` mesh: every
    tensor shards along its leading batch axis (which must divide the
    mesh size — callers pad rows to a multiple, counted in the
    ``padded_cells`` wide event), emission/transition params replicate
    inside the jitted call. No time-axis padding is needed: route's
    ragged T-1 rows only matter to ``seq`` sharding."""
    def put(x):
        spec = P("data", *([None] * (np.ndim(x) - 1)))
        return jax.device_put(x, NamedSharding(mesh, spec))

    return (put(dist_m), put(valid), put(route_m), put(gc_m), put(case))


def sharded_data_viterbi(mesh: Mesh, kernel):
    """A decode callable running ``kernel`` (an unjitted batch decode —
    scan or assoc) data-parallel over a 1-D ``("data",)`` mesh, with
    sharded in/out specs so the (B, T) paths stay device-sharded until
    the drain lane's d2h gather."""
    out_sharding = (NamedSharding(mesh, P("data")),
                    NamedSharding(mesh, P("data")))
    decode = jax.jit(kernel, out_shardings=out_sharding)

    def run(dist_m, valid, route_m, gc_m, case, sigma, beta):
        args = shard_batch_data(mesh, dist_m, valid, route_m, gc_m, case)
        return decode(*args, jnp.float32(sigma), jnp.float32(beta))

    return run


def sharded_viterbi(mesh: Mesh):
    """Return a decode callable fixed to ``mesh``.

    out_shardings keep paths on ``data`` so the host gathers only (B, T)
    int32 — the K-width intermediates never leave the devices.
    """
    out_sharding = (NamedSharding(mesh, P("data", "seq")),
                    NamedSharding(mesh, P("data")))

    # route/gc arrive padded to T time rows (dead trailing step) so they
    # shard along seq; the kernel itself sheds the dead step inside jit
    # (matcher/hmm.py trim_time_pad) and GSPMD partitions the slice
    decode = jax.jit(viterbi_assoc_batch.__wrapped__,
                     out_shardings=out_sharding)

    def run(dist_m, valid, route_m, gc_m, case, sigma, beta):
        args = shard_batch(mesh, dist_m, valid, route_m, gc_m, case)
        return decode(*args, jnp.float32(sigma), jnp.float32(beta))

    return run
