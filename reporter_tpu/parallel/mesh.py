"""Device mesh construction for the sharded matcher.

The reference scales by Kafka partitions across worker processes and
machines (reference: SURVEY.md §2.4 — uuid-keyed partitions, manual
multi-instance backfill). The TPU equivalent is a ``jax.sharding.Mesh``
with two axes:

  ``data`` — traces (the uuid/partition axis reborn): pure data
             parallelism, no cross-device traffic in the decode
  ``seq``  — the time axis of each trace (sequence parallelism): the
             associative-scan decode composes step matrices across devices
             via GSPMD-inserted collectives over ICI

Multi-host runs get the same mesh over all processes' devices (JAX's
standard multi-controller setup); ``data`` should map to the DCN-connected
dimension and ``seq`` stay within a pod slice so the scan's collectives
ride ICI.
"""
from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh


def make_mesh(shape: Optional[Tuple[int, int]] = None,
              axis_names: Sequence[str] = ("data", "seq"),
              devices=None) -> Mesh:
    """Build a 2D (data, seq) mesh over the available devices.

    Default shape puts everything on ``data`` (n, 1) — the right default
    for throughput serving; pass e.g. (n//2, 2) to shard long traces.
    """
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    if shape is None:
        shape = (n, 1)
    if shape[0] * shape[1] != n:
        raise ValueError(f"mesh shape {shape} != device count {n}")
    arr = np.asarray(devices).reshape(shape)
    return Mesh(arr, axis_names=tuple(axis_names))
