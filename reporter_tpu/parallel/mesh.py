"""Device mesh construction for the sharded matcher.

The reference scales by Kafka partitions across worker processes and
machines (reference: SURVEY.md §2.4 — uuid-keyed partitions, manual
multi-instance backfill). The TPU equivalent is a ``jax.sharding.Mesh``
with two axes:

  ``data`` — traces (the uuid/partition axis reborn): pure data
             parallelism, no cross-device traffic in the decode
  ``seq``  — the time axis of each trace (sequence parallelism): the
             associative-scan decode composes step matrices across devices
             via GSPMD-inserted collectives over ICI

Multi-host runs get the same mesh over all processes' devices (JAX's
standard multi-controller setup); ``data`` should map to the DCN-connected
dimension and ``seq`` stay within a pod slice so the scan's collectives
ride ICI.
"""
from __future__ import annotations

import logging
import os
import re
from typing import List, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh

from ..utils import locks as _locks

logger = logging.getLogger("reporter_tpu.parallel")

#: decode sharding: "auto" (default — shard when >1 local device is
#: visible), "0"/"off" never, "1"/"on" always (a 1-device mesh is a
#: no-op). REPORTER_TPU_SHARD=0, the original kill switch, still wins.
ENV_DECODE_SHARD = "REPORTER_TPU_DECODE_SHARD"
#: which slice of jax.local_devices() this process decodes on:
#: "<slot>/<procs>" (slot-derived contiguous block — what the pre-fork
#: supervisor sets per worker so N processes x M devices never contend
#: on one device queue) or "<lo>:<hi>" (explicit range). Empty = all.
ENV_DEVICE_SLICE = "REPORTER_TPU_DEVICE_SLICE"

_SLICE_RE = re.compile(r"^\s*(?:(\d+)\s*/\s*(\d+)|(\d+)?\s*:\s*(\d+)?)\s*$")

# the process-global decode mesh, built once per (shard, slice, seq)
# env state — a sentinel distinguishes "not built" from "built: None"
_UNSET = object()
_mesh_lock = _locks.new_lock("parallel.mesh")
_decode_mesh = _UNSET


def make_mesh(shape: Optional[Tuple[int, int]] = None,
              axis_names: Sequence[str] = ("data", "seq"),
              devices=None) -> Mesh:
    """Build a 2D (data, seq) mesh over the available devices.

    Default shape puts everything on ``data`` (n, 1) — the right default
    for throughput serving; pass e.g. (n//2, 2) to shard long traces.
    """
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    if shape is None:
        shape = (n, 1)
    if shape[0] * shape[1] != n:
        raise ValueError(f"mesh shape {shape} != device count {n}")
    arr = np.asarray(devices).reshape(shape)
    return Mesh(arr, axis_names=tuple(axis_names))


def make_data_mesh(devices) -> Mesh:
    """A 1-D ``("data",)`` mesh: pure batch parallelism, no collective
    traffic in the decode — every backend (including the sequential
    scan, the bit-identity oracle) shards on it."""
    return Mesh(np.asarray(list(devices)), axis_names=("data",))


def shard_enabled() -> bool:
    """Both kill switches consulted: the original REPORTER_TPU_SHARD
    and the decode knob REPORTER_TPU_DECODE_SHARD (default auto)."""
    if os.environ.get("REPORTER_TPU_SHARD", "1").strip().lower() in (
            "0", "off", "false"):
        return False
    val = os.environ.get(ENV_DECODE_SHARD, "auto").strip().lower()
    return val not in ("0", "off", "false")


def device_slice(devices: Sequence) -> List:
    """This process's subset of ``devices`` per REPORTER_TPU_DEVICE_SLICE.

    ``"s/p"`` — contiguous block ``s`` of ``p`` (slot-derived: prefork
    worker ``s`` of ``p`` owns ``devices[s*n//p:(s+1)*n//p]``; with more
    processes than devices each process falls back to the single device
    ``s % n``, so every worker always owns at least one).
    ``"lo:hi"`` — an explicit half-open range. Empty/absent = all.
    A malformed spec logs and returns all devices (mis-typed slicing
    must degrade to the safe single-mesh default, never to an empty
    mesh)."""
    devices = list(devices)
    spec = os.environ.get(ENV_DEVICE_SLICE, "").strip()
    if not spec or not devices:
        return devices
    m = _SLICE_RE.match(spec)
    if not m:
        logger.warning("%s=%r not understood (want 'slot/procs' or "
                       "'lo:hi'); using all %d local devices",
                       ENV_DEVICE_SLICE, spec, len(devices))
        return devices
    n = len(devices)
    if m.group(1) is not None:
        slot, procs = int(m.group(1)), int(m.group(2))
        if procs <= 0 or slot >= procs:
            logger.warning("%s=%r out of range; using all devices",
                           ENV_DEVICE_SLICE, spec)
            return devices
        lo, hi = slot * n // procs, (slot + 1) * n // procs
        if lo >= hi:
            # more processes than devices: empty block -> the same
            # proportional index the block math uses, so slots spread
            # evenly (slot % n would pile the empty-block slots onto
            # the low devices: n=2, procs=4 put 3 workers on device 0)
            return [devices[slot * n // procs]]
        return devices[lo:hi]
    lo = int(m.group(3)) if m.group(3) else 0
    hi = int(m.group(4)) if m.group(4) is not None else n
    picked = devices[lo:hi]
    if not picked:
        logger.warning("%s=%r selects no device; using all",
                       ENV_DEVICE_SLICE, spec)
        return devices
    return picked


def _build_decode_mesh() -> Optional[Mesh]:
    if not shard_enabled():
        return None
    # local devices only: in a multi-host job the decode inputs are
    # host-local numpy arrays, and a device_put onto a global mesh's
    # non-addressable devices would throw — each process shards over
    # its own chips; cross-host scale-out stays uuid-partitioned
    # (parallel/multihost.py), exactly the reference's partition axis
    devices = device_slice(jax.local_devices())
    n = len(devices)
    if n <= 1:
        return None
    from ..utils.runtime import _env_int
    seq = max(1, _env_int("REPORTER_TPU_SEQ_SHARDS", 1))
    seq = min(seq, n)
    while n % seq:  # largest feasible seq <= requested
        seq -= 1
    if seq > 1:
        return make_mesh((n // seq, seq), devices=devices)
    return make_data_mesh(devices)


def decode_mesh() -> Optional[Mesh]:
    """The process-global decode mesh: a 1-D ``("data",)`` mesh over
    this process's device slice (2-D ``(data, seq)`` when
    REPORTER_TPU_SEQ_SHARDS > 1), or None when sharding is off or only
    one device is visible. Built once; :func:`reset_decode_mesh` drops
    it (tests, post-fork)."""
    global _decode_mesh
    if _decode_mesh is _UNSET:
        with _mesh_lock:
            if _decode_mesh is _UNSET:
                _decode_mesh = _build_decode_mesh()
                if _decode_mesh is not None:
                    logger.info(
                        "decode mesh: %s over %d local device(s)",
                        dict(zip(_decode_mesh.axis_names,
                                 _decode_mesh.devices.shape)),
                        _decode_mesh.devices.size)
    return _decode_mesh


def mesh_axes(mesh: Optional[Mesh]) -> Tuple[int, int]:
    """(data, seq) axis sizes of a decode mesh (1, 1) when unsharded."""
    if mesh is None:
        return 1, 1
    shape = dict(zip(mesh.axis_names, mesh.devices.shape))
    return shape.get("data", 1), shape.get("seq", 1)


def decode_mesh_size() -> int:
    """The data-axis width of the process decode mesh (1 = unsharded) —
    what chunk sizing and the dispatcher's in-flight depth scale by."""
    return mesh_axes(decode_mesh())[0]


def reset_decode_mesh() -> None:
    """Forget the cached decode mesh (tests re-read the env; forked
    workers re-derive their slice)."""
    global _decode_mesh
    with _mesh_lock:
        _decode_mesh = _UNSET
