from .mesh import make_mesh
from .sharded import sharded_viterbi, shard_batch

__all__ = ["make_mesh", "sharded_viterbi", "shard_batch"]
