from .mesh import make_mesh
from .multihost import host_uuid_filter, init_multihost, partition_for_host
from .sharded import sharded_viterbi, shard_batch

__all__ = ["make_mesh", "sharded_viterbi", "shard_batch",
           "init_multihost", "partition_for_host", "host_uuid_filter"]
