"""Micro-batching dispatcher: many concurrent requests -> one device batch.

The reference serves one trace per HTTP request with one C++ matcher per
thread (reference: py/reporter_service.py:32-64). The TPU inverts that
economy: the device wants *large* batches. This dispatcher is the bridge —
request threads enqueue traces and block; a single dispatch loop drains the
queue into a batch (flushing on ``max_batch`` or ``max_wait_ms`` since the
first pending trace, whichever first), runs the batched matcher, and wakes
each requester with its own result.

This is the micro-batch buffer SURVEY.md §2.4 calls the north-star addition.
"""
from __future__ import annotations

import itertools
import os
import queue
import threading
import time
from typing import Callable, List, Optional, Sequence

from ..core.tracebatch import TraceBatch
from ..obs import profiler
from ..obs import trace as obs_trace
from ..utils import locks as _locks
from ..utils import metrics
from .admission import Overload, retry_after_s

#: dispatcher queue bound in traces (0 = unbounded, the pre-ISSUE-15
#: behaviour). Bounded by default: an unbounded queue under overload is
#: latency debt every later request pays — better to say no at the door
ENV_QUEUE_MAX = "REPORTER_TPU_QUEUE_MAX"
DEFAULT_QUEUE_MAX = 4096
#: what happens when the bounded queue is full: "reject" sheds the NEW
#: submit (Overload -> HTTP 429 upstream), "oldest" sheds the oldest
#: queued slot to make room (its waiter gets the Overload — freshest
#: work wins). Both are counted; nothing is ever dropped silently.
ENV_QUEUE_POLICY = "REPORTER_TPU_QUEUE_POLICY"
#: per-batch latency budget in ms driving the EWMA flush model
#: (0 = fixed count/interval flushing, the pre-ISSUE-15 behaviour)
ENV_BATCH_LATENCY = "REPORTER_TPU_BATCH_LATENCY_MS"
#: EWMA smoothing for the per-trace service-time model
_EWMA_ALPHA = 0.2

_dispatcher_seq = itertools.count(1)

#: queue sentinel close() enqueues AFTER the closed flag flips: every
#: real slot precedes it, so the loop drains all in-flight work, then
#: exits — shutdown is a drain, not an abandonment
_STOP = object()


class _Slot:
    __slots__ = ("trace", "columns", "event", "result", "error", "ctx")

    def __init__(self, trace, columns: Optional[tuple] = None):
        self.trace = trace
        # (uuid, lat, lon, time, accuracy, options) column arrays, built
        # by the submitting request thread (so columnarisation fans out
        # across the handler pool); None for callers that submit plain
        # dicts — a whole-batch of columnar slots reaches the matcher as
        # ONE TraceBatch with zero per-point Python in the dispatch loop
        self.columns = columns
        # the submitter's trace context: the dispatch loop runs on its
        # own thread, so request causality must ride the slot (None —
        # one flag check — when tracing is disarmed)
        self.ctx = obs_trace.current()
        self.event = threading.Event()
        self.result: Optional[dict] = None
        self.error: Optional[Exception] = None


class BatchDispatcher:
    """Accumulates traces and runs ``match_many`` over the accumulated batch.

    ``match_many``: callable taking a list of trace dicts and returning a
    list of match results (dicts, or the matcher's lazy ``MatchRuns``
    column views — e.g. ``SegmentMatcher.match_many``).
    """

    def __init__(self, match_many: Callable[[Sequence[dict]], List[dict]],
                 max_batch: int = 256, max_wait_ms: float = 20.0,
                 idle_grace_ms: float = 2.0,
                 queue_max: Optional[int] = None,
                 queue_policy: Optional[str] = None,
                 latency_budget_ms: Optional[float] = None,
                 name: Optional[str] = None):
        from ..utils.runtime import _env_float, _env_int
        self._match_many = match_many
        self.max_batch = max_batch
        self.max_wait = max_wait_ms / 1000.0
        # flush early once the queue has stayed empty this long: callers
        # that were going to batch enqueue within a moment of each other,
        # so an idle queue means waiting out the full max_wait would add
        # latency without adding batch — max_wait stays the hard bound
        # for a steady trickle of arrivals
        self.idle_grace = min(idle_grace_ms / 1000.0, self.max_wait)
        # named so the per-dispatcher queue-depth gauges (profiler) and
        # a multi-dispatcher process (city stacks) stay distinguishable
        self.name = name or f"dispatch{next(_dispatcher_seq)}"
        # bounded queue (ISSUE 15): full sheds loudly instead of
        # growing latency debt without bound; 0 keeps it unbounded
        self.queue_max = queue_max if queue_max is not None \
            else _env_int(ENV_QUEUE_MAX, DEFAULT_QUEUE_MAX)
        self.queue_policy = (queue_policy
                             or os.environ.get(ENV_QUEUE_POLICY,
                                               "reject")).strip().lower()
        if self.queue_policy not in ("reject", "oldest"):
            self.queue_policy = "reject"
        self._queue: "queue.Queue[_Slot]" = queue.Queue(
            maxsize=max(0, self.queue_max))
        # latency-targeted micro-batching: an EWMA of per-trace service
        # time turns the flush decision into "how many traces fit the
        # REPORTER_TPU_BATCH_LATENCY_MS budget" — batch size shrinks
        # under load (service time inflates) and grows back when idle.
        # 0 disables: fixed max_batch/max_wait flushing.
        self.latency_budget = (latency_budget_ms
                               if latency_budget_ms is not None
                               else _env_float(ENV_BATCH_LATENCY,
                                               0.0)) / 1000.0
        # written only by the dispatch loop thread; read cross-thread
        # by the admission gate (a torn read of a float cannot happen
        # in CPython, and the gate only wants an estimate)
        self._ewma_per_trace: Optional[float] = None
        # traces in the batch currently being matched: queue_depth()
        # includes them — a drained-but-in-service batch is wait a new
        # arrival pays just like queued slots, and hiding it from the
        # gate's deadline check under-predicts by a whole batch wall
        self._in_service = 0
        self._batches = 0  # batch sequence, stamped on batch spans
        self._closed = False
        self._stopping = False  # loop consumed the _STOP sentinel
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="match-dispatch")
        self._thread.start()

    # ---- load-management sensors ----------------------------------------
    def queue_depth(self) -> int:
        """Live backlog in traces — queued slots PLUS the batch in
        service (the admission gate's DEADLINE sensor: both are wait a
        new arrival pays before its own batch dispatches)."""
        return self._queue.qsize() + self._in_service

    def queued_depth(self) -> int:
        """Queued slots only — the gate's HARD-BOUND sensor. The batch
        in service must not count against ``queue_max`` (a max_batch
        larger than the bound would read as permanently full and shed
        everything for every batch wall)."""
        return self._queue.qsize()

    def service_ewma_s(self) -> Optional[float]:
        """EWMA per-trace service time (None before the first batch)."""
        return self._ewma_per_trace

    def _effective_cap(self) -> int:
        """Traces the latency budget allows per batch: min(max_batch,
        budget / per-trace EWMA), floored at 1 so the dispatcher always
        makes progress even when one trace alone busts the budget."""
        if self.latency_budget <= 0.0 or not self._ewma_per_trace:
            return self.max_batch
        return max(1, min(self.max_batch,
                          int(self.latency_budget
                              / self._ewma_per_trace)))

    # ---- request side ----------------------------------------------------
    def submit(self, trace: dict, timeout: float = 60.0,
               columns: Optional[tuple] = None) -> dict:
        """Block until the trace's match result is ready. ``columns`` is
        the trace's pre-built (uuid, lat, lon, time, accuracy, options)
        column tuple when the caller already columnarised the wire."""
        if self._closed:
            raise RuntimeError("dispatcher is closed")
        slot = _Slot(trace, columns)
        _locks.fuzz_point("dispatch.queue.put")
        self._enqueue_nowait(slot)
        if not slot.event.wait(timeout):
            raise TimeoutError("match result not ready in time")
        if slot.error is not None:
            raise slot.error
        return slot.result  # type: ignore[return-value]

    def submit_many(self, traces: Sequence[dict], timeout: float = 60.0,
                    return_exceptions: bool = False) -> List[dict]:
        """Enqueue a whole list, then wait: the dispatch loop drains them
        into ONE device batch (up to max_batch; a longer list spans
        several batches back-to-back). This is the streaming worker's
        eviction path — N uuids flushed by one punctuate cycle decode as
        one padded batch of N, not N batches of 1 (reference being
        beaten: one C++ call per trace, Batch.java:66-68).

        ``timeout`` is per device batch; the aggregate deadline scales
        with how many batches the list needs, so a huge end-of-stream
        flush cannot time out merely for being large. With
        ``return_exceptions`` failures come back in-place (the exception
        object in that trace's slot) instead of raising — a one-batch
        failure then costs only that batch's traces, not the whole list.
        """
        if self._closed:
            raise RuntimeError("dispatcher is closed")
        if isinstance(traces, TraceBatch):
            acc = traces.accuracy
            off = traces.offsets
            slots = [
                _Slot(traces[i], (traces.uuid(i), *traces.trace_columns(i),
                                  acc[off[i]:off[i + 1]]
                                  if acc is not None else None,
                                  traces.option(i)))
                for i in range(len(traces))]
        else:
            slots = [_Slot(tr) for tr in traces]
        for slot in slots:  # enqueue ALL before waiting on any
            _locks.fuzz_point("dispatch.queue.put")
            self._enqueue_blocking(slot, timeout)
        # deadline scales with the batches the list will ACTUALLY need:
        # under a latency budget the drain loop flushes at the EWMA-
        # shrunk cap, not max_batch — sizing by max_batch would time
        # out large streaming flushes exactly when the model kicks in
        n_batches = max(1, -(-len(slots) // self._effective_cap()))
        deadline = time.monotonic() + timeout * n_batches
        results: List = []
        for slot in slots:
            if not slot.event.wait(max(0.0, deadline - time.monotonic())):
                err: Exception = TimeoutError(
                    "match result not ready in time")
                if not return_exceptions:
                    raise err
                results.append(err)
                continue
            if slot.error is not None:
                if not return_exceptions:
                    raise slot.error
                results.append(slot.error)
                continue
            results.append(slot.result)
        return results

    # ---- bounded enqueue -------------------------------------------------
    def _overload(self) -> Overload:
        return Overload("queue", retry_after_s(self._queue.qsize(),
                                               self._ewma_per_trace))

    def _enqueue_nowait(self, slot: _Slot) -> None:
        """The request-path enqueue: a full bounded queue sheds — the
        NEW slot under the "reject" policy, the OLDEST queued slot
        under "oldest" (freshest work wins; the displaced waiter gets
        the Overload). Every shed is counted; nothing silent."""
        while True:
            try:
                self._queue.put_nowait(slot)
                return
            except queue.Full:
                pass
            if self.queue_policy != "oldest":
                metrics.count("dispatch.queue.rejected")
                raise self._overload()
            try:
                old = self._queue.get_nowait()
            except queue.Empty:
                continue  # the loop drained it first — retry the put
            if old is _STOP:
                # close() raced us: restore the sentinel, refuse ours
                self._queue.put(old)
                metrics.count("dispatch.queue.rejected")
                raise self._overload()
            old.error = self._overload()
            old.event.set()
            metrics.count("dispatch.queue.evicted")

    def _enqueue_blocking(self, slot: _Slot, timeout: float) -> None:
        """The streaming-flush enqueue: a full queue BLOCKS (bounded by
        ``timeout``) — this is the end-to-end backpressure, the queue
        bound propagating to the producer instead of shedding its
        flush. A wait that times out raises Overload; the batcher's
        requeue/dead-letter budget absorbs it."""
        try:
            self._queue.put_nowait(slot)
            return
        except queue.Full:
            metrics.count("dispatch.queue.waits")
        try:
            self._queue.put(slot, timeout=timeout)
        except queue.Full:
            metrics.count("dispatch.queue.rejected")
            raise self._overload() from None

    # ---- dispatch loop ---------------------------------------------------
    # the drain loop is single-thread-owned (the match-dispatch thread);
    # @thread_affine turns a second thread draining the queue — exactly
    # the bug a future pre-fork refactor could introduce — into a named
    # racecheck RC004 finding when the witness is armed
    @_locks.thread_affine
    def _drain_batch(self) -> List[_Slot]:
        """Block for the first trace, then collect until a flush
        condition: the effective batch cap reached (``max_batch``, or
        fewer when the latency budget's EWMA model says a full batch
        would bust ``REPORTER_TPU_BATCH_LATENCY_MS``), ``max_wait``
        elapsed since the first trace, the queue stayed empty for
        ``idle_grace``, or the close() sentinel surfaced (every slot
        before it still flushes)."""
        _locks.fuzz_point("dispatch.queue.get")
        first = self._queue.get()
        if first is _STOP:
            self._stopping = True
            return []
        slots = [first]
        cap = self._effective_cap()
        if cap < self.max_batch:
            metrics.count("batch.latency.capped_batches")
        t0 = time.monotonic()
        while len(slots) < cap:
            remaining = self.max_wait - (time.monotonic() - t0)
            if remaining <= 0:
                break
            try:
                _locks.fuzz_point("dispatch.queue.get")
                got = self._queue.get(
                    timeout=min(remaining, self.idle_grace))
            except queue.Empty:
                break  # idle past the grace window — flush what we have
            if got is _STOP:
                self._stopping = True
                break
            slots.append(got)
        return slots

    def _loop(self):
        while not self._stopping:
            slots = self._drain_batch()
            if not slots:
                continue  # woke on the close() sentinel alone
            self._batches += 1
            metrics.count("dispatch.batches")
            metrics.count("dispatch.traces", len(slots))
            # backlog left behind after this drain — "queue depth at
            # dispatch" stamped into the profiler's wide events, under
            # THIS dispatcher's name (a pre-fork child resets the gauge
            # registry, so it never inherits the parent's stale depth)
            profiler.note_queue_depth(self._queue.qsize(),
                                      name=self.name)
            # adopt one submitter's trace context so the batch's stage
            # spans parent to that request (a merged batch can only
            # follow one requester; the batch attrs record the merge)
            ctx = None
            for s in slots:
                if s.ctx is not None:
                    ctx = s.ctx
                    break
            self._in_service = len(slots)
            try:
                with obs_trace.attach(ctx), \
                        obs_trace.span("dispatch.batch",
                                       batch=self._batches,
                                       traces=len(slots)):
                    # a batch of columnar slots concatenates into ONE
                    # TraceBatch (flat arrays, no per-point Python);
                    # plain dict submissions fall back to the
                    # request-dict path
                    if all(s.columns is not None for s in slots):
                        batch = TraceBatch.concat(
                            [s.columns for s in slots])
                    else:
                        batch = [s.trace for s in slots]
                    t_match = time.monotonic()
                    with metrics.timer("dispatch.match_many"):
                        results = self._match_many(batch)
                    self._note_service_time(
                        time.monotonic() - t_match, len(slots))
                    for slot, res in zip(slots, results):
                        slot.result = res
            except Exception as e:  # propagate to every waiter in the batch
                metrics.count("dispatch.errors")
                for slot in slots:
                    slot.error = e
            finally:
                self._in_service = 0
                for slot in slots:
                    slot.event.set()

    def _note_service_time(self, elapsed_s: float, n: int) -> None:
        """Feed one batch's wall into the per-trace EWMA service-time
        model (dispatch-loop thread only). The EWMA drives both the
        latency-budget flush cap and the gate's Retry-After estimate."""
        if n <= 0:
            return
        per_trace = elapsed_s / n
        prev = self._ewma_per_trace
        self._ewma_per_trace = per_trace if prev is None else \
            (1.0 - _EWMA_ALPHA) * prev + _EWMA_ALPHA * per_trace
        metrics.observe("batch.latency.per_trace", per_trace)
        if self.latency_budget > 0.0 and elapsed_s > self.latency_budget:
            metrics.count("batch.latency.over_budget")

    def close(self, timeout: float = 30.0) -> bool:
        """Shut down by DRAINING, not abandoning: refuse new submits,
        let the loop flush every slot already enqueued (waiters wake
        with real results), then join the dispatch thread — the
        shutdown-ordering contract (ISSUE 10): no dispatch thread may
        outlive the matcher/datastore handles its batches touch. Any
        slot that raced past the closed check after the sentinel is
        woken with an error rather than left to hit its wait timeout.
        Idempotent; returns True when the loop thread fully stopped."""
        if not self._closed:
            self._closed = True
            self._queue.put(_STOP)
        self._thread.join(timeout)
        stopped = not self._thread.is_alive()
        if stopped:
            while True:
                try:
                    slot = self._queue.get_nowait()
                except queue.Empty:
                    break
                if slot is _STOP:
                    continue
                slot.error = RuntimeError("dispatcher is closed")
                slot.event.set()
        return stopped
