"""Datastore report generation from matcher output.

Behavioral port of the reference's ``report()``
(reference: py/reporter_service.py:79-179) — this is the compatibility
contract between the matcher and every downstream consumer (the Java
worker's ``forward()`` at BatchingProcessor.java:108-141 and the batch
pipeline at simple_reporter.py:168-177). Preserved semantics:

- trailing holdback: segments whose start_time is within ``threshold_sec``
  of the trace end are withheld (the vehicle may still be on them), and
  ``shape_used`` marks how much of the trace may be trimmed
- emission is *pairwise*: a segment is reported only once its successor is
  known; ``t1`` is the successor's start time when the successor's level is
  in ``transition_levels``, else the segment's own end time
- internal segments (turn channels, roundabouts) never clear the pending
  prior segment — they are bridged over
- validity: positive finite dt and speed <= 160 km/h
- the stats block (successful/unreported counts, discontinuities, invalid
  times/speeds, unassociated segments)

One deliberate deviation: the reference *assigns* the last segment's km to
the stats ``length`` fields instead of accumulating
(reporter_service.py:138,142); here lengths are summed, which is the
evident intent of the telemetry.

The emission state machine itself is **columnar**: it scans parallel
per-segment value lists and accumulates parallel report lists — no dict
is built per segment or per report inside the scan. :func:`report` is
the structured-dict compatibility surface (tests, the worker's trimming
logic); :func:`report_json` is the hot serving path, serialising the
whole response straight from a :class:`~..matcher.matcher.MatchRuns`'s
run columns to JSON — byte-identical to ``json.dumps`` over
:func:`report`'s output (pinned by tests/test_report_writer.py).
"""
from __future__ import annotations

import json
import math
from typing import Iterable, List, Optional, Tuple


class _Scan:
    """Output of one pass of the emission state machine: the holdback
    cut, the datastore reports as parallel lists, and the stats."""

    __slots__ = ("last_idx", "shape_used", "r_id", "r_t0", "r_t1",
                 "r_len", "r_queue", "r_next", "successful",
                 "successful_km", "unreported", "unreported_km",
                 "discontinuities", "invalid_times", "invalid_speeds",
                 "unassociated")


_matcher_mod = None


def _matcher():
    """reporter_tpu.matcher.matcher, bound once (a per-call ``from``
    import costs importlib machinery on every request)."""
    global _matcher_mod
    if _matcher_mod is None:
        from ..matcher import matcher as _matcher_mod_  # noqa: F401
        _matcher_mod = _matcher_mod_
    return _matcher_mod


def _segment_columns(match) -> Tuple[list, ...]:
    """(seg_id, internal, start, end, length, queue, begin_idx, end_idx)
    parallel lists for the scan — straight slices of a MatchRuns's run
    columns (zero per-segment work), or one comprehension pass per field
    over plain segment dicts (the numpy-fallback / hand-built path).
    Absent segment ids are None (dict path) or -1 (column path); the
    scan treats both as unassociated."""
    if isinstance(match, _matcher().MatchRuns):
        c, lo, hi = match.cols, match.lo, match.hi
        return (c.seg_id[lo:hi], c.internal[lo:hi], c.start[lo:hi],
                c.end[lo:hi], c.length[lo:hi], c.queue[lo:hi],
                c.begin_idx[lo:hi], c.end_idx[lo:hi])
    segs = match["segments"]
    return ([s.get("segment_id") for s in segs],
            [s.get("internal", False) for s in segs],
            [s.get("start_time") for s in segs],
            [s.get("end_time") for s in segs],
            [s.get("length") for s in segs],
            [s.get("queue_length") for s in segs],
            [s.get("begin_shape_index") for s in segs],
            [s.get("end_shape_index") for s in segs])


def _scan_segments(seg_id: list, internal: list, start: list, end: list,
                   length: list, queue: list, begin_idx: list,
                   end_idx: list, trace_end, threshold_sec: float,
                   report_levels: set, transition_levels: set) -> _Scan:
    """The reference's pairwise emission state machine
    (reporter_service.py:79-179) over columnar inputs."""
    n = len(seg_id)

    # ---- trailing holdback (reference: reporter_service.py:83-92) --------
    last_idx = n - 1
    while last_idx >= 0 and trace_end - start[last_idx] < threshold_sec:
        last_idx -= 1
    shape_used: Optional[int] = None
    if last_idx >= 0:
        # keep the boundary-straddling probe: the reference trims at the
        # in-progress segment's first point (reporter_service.py:92), but
        # without the last probe of the PRECEDING segment the next window
        # can never interpolate this segment's entry time, so every
        # window-boundary segment would be reported partial (length -1)
        # and dropped — a systematic hole in the datastore stream at
        # every batch trim. The preceding run's end_shape_index is the
        # straddling probe even when jitter-dropped points sit between
        # the runs.
        if last_idx > 0:
            shape_used = end_idx[last_idx - 1]
        else:
            shape_used = max(begin_idx[0] - 1, 0)

    out = _Scan()
    out.last_idx = last_idx
    out.shape_used = shape_used
    r_id: List = []
    r_t0: List = []
    r_t1: List = []
    r_len: List = []
    r_queue: List = []
    r_next: List = []
    successful = unreported = 0
    successful_km = unreported_km = 0.0
    discontinuities = invalid_times = invalid_speeds = unassociated = 0

    # the pending segment awaiting its successor before being reported
    have_pending = False
    p_sid = p_start = p_end = p_len = p_queue = None
    p_level = -1
    first = True
    for idx in range(last_idx + 1):
        sid = seg_id[idx]
        if sid is not None and sid < 0:
            sid = None  # column sentinel for "no OSMLR id"
        intern = internal[idx]
        start_time = start[idx]

        # a partial end followed by a partial start marks a discontinuity
        # (reference: reporter_service.py:114-116)
        if idx > 0 and start_time == -1 and end[idx - 1] == -1:
            discontinuities += 1

        level = (sid & 0x7) if sid is not None else -1

        # emit the pending segment now that its successor is visible;
        # an internal successor defers emission (reference: :122-127)
        if have_pending and p_sid is not None and p_len is not None \
                and p_len > 0 and not intern:
            if p_level in report_levels:
                t1 = start_time if level in transition_levels else p_end
                dt = float(t1) - float(p_start)
                if dt <= 0 or math.isinf(dt) or math.isnan(dt):
                    invalid_times += 1
                elif (p_len / dt) * 3.6 > 160:
                    invalid_speeds += 1
                else:
                    r_id.append(p_sid)
                    r_t0.append(p_start)
                    r_t1.append(t1)
                    r_len.append(p_len)
                    r_queue.append(p_queue)
                    r_next.append(sid if (level in transition_levels
                                          and sid is not None) else None)
                    successful += 1
                    successful_km += round(p_len * 0.001, 3)
            else:
                unreported += 1
                unreported_km += round(p_len * 0.001, 3)

        # internal segments bridge: keep the pending prior
        # (reference: :144-156)
        if not (intern and not first):
            p_sid = sid
            p_start = start_time
            p_end = end[idx]
            p_len = length[idx]
            p_queue = queue[idx]
            p_level = level
            have_pending = True
        first = False

        # service roads etc: matched edges with no OSMLR id
        # (reference: :159-162)
        if sid is None and not intern:
            unassociated += 1

    out.r_id, out.r_t0, out.r_t1 = r_id, r_t0, r_t1
    out.r_len, out.r_queue, out.r_next = r_len, r_queue, r_next
    out.successful, out.successful_km = successful, successful_km
    out.unreported, out.unreported_km = unreported, unreported_km
    out.discontinuities = discontinuities
    out.invalid_times = invalid_times
    out.invalid_speeds = invalid_speeds
    out.unassociated = unassociated
    return out


def report(match: dict, trace: dict, threshold_sec: float,
           report_levels: Iterable[int],
           transition_levels: Iterable[int]) -> dict:
    """Turn a match result into datastore reports + stats (structured
    dicts — the worker's trimming logic and tests consume these; the
    serving path uses :func:`report_json` and never builds them)."""
    scan = _scan_segments(
        *_segment_columns(match), trace["trace"][-1]["time"],
        threshold_sec, set(report_levels), set(transition_levels))
    match["mode"] = "auto"
    reports = [
        {"id": i, "t0": t0, "t1": t1, "length": ln, "queue_length": q,
         **({"next_id": nx} if nx is not None else {})}
        for i, t0, t1, ln, q, nx in zip(scan.r_id, scan.r_t0, scan.r_t1,
                                        scan.r_len, scan.r_queue,
                                        scan.r_next)]
    out = {
        "stats": {
            "successful_matches": {
                "count": scan.successful,
                "length": round(scan.successful_km, 3),
            },
            "unreported_matches": {
                "count": scan.unreported,
                "length": round(scan.unreported_km, 3),
            },
            "match_errors": {
                "discontinuities": scan.discontinuities,
                "invalid_speeds": scan.invalid_speeds,
                "invalid_times": scan.invalid_times,
            },
            "unassociated_segments": scan.unassociated,
        },
    }
    # reference quirk preserved: shape_used omitted when falsy (index 0)
    if scan.shape_used:
        out["shape_used"] = scan.shape_used
    out["segment_matcher"] = match
    out["datastore"] = {"mode": "auto", "reports": reports}
    return out


_wire_mod = None


def _wire():
    """service.wire, bound once (like :func:`_matcher`: a per-call
    ``from`` import costs importlib machinery on every request)."""
    global _wire_mod
    if _wire_mod is None:
        from . import wire as _wire_mod_  # noqa: F401
        _wire_mod = _wire_mod_
    return _wire_mod


def _try_native_wire(match, trace: dict, threshold_sec: float,
                     report_levels, transition_levels):
    """The C-level writer's bytes for a MatchRuns, or None (backend
    off / circuit open / writer fault — the caller falls back to the
    Python columnar writer, byte-identical)."""
    arrays = getattr(match.cols, "arrays", None)
    if arrays is None:
        return None
    wire = _wire_mod if _wire_mod is not None else _wire()
    out = wire.maybe_native_report(
        arrays, match.lo, match.hi, trace["trace"][-1]["time"],
        threshold_sec, report_levels, transition_levels)
    if out is not None:
        match["mode"] = "auto"  # same side effect as the writers below
    return out


def report_wire(match, trace: dict, threshold_sec: float,
                report_levels: Iterable[int],
                transition_levels: Iterable[int]):
    """The ``/report`` response body as BYTES — the serving path's
    entry point (service/server.py hands the returned buffer to the
    socket with no re-encode). A thin dispatcher over the wire backend
    knob: the native C writer emits the whole body into one contiguous
    buffer (memoryview, zero-copy); otherwise the Python writer's
    string is encoded. All paths are byte-identical (pinned by
    tests/test_report_writer.py)."""
    mm = _matcher()
    if isinstance(match, mm.MatchRuns):
        out = _try_native_wire(match, trace, threshold_sec,
                               report_levels, transition_levels)
        if out is not None:
            return out
        from ..utils import metrics
        metrics.count("wire.fallback")
        # straight to the Python writer: report_json would re-attempt
        # the native path this call just watched fail
        return _report_json_py(match, trace, threshold_sec, report_levels,
                               transition_levels).encode("utf-8")
    return report_json(match, trace, threshold_sec, report_levels,
                       transition_levels).encode("utf-8")


def report_json(match, trace: dict, threshold_sec: float,
                report_levels: Iterable[int],
                transition_levels: Iterable[int]) -> str:
    """The whole ``/report`` response serialised straight from run
    columns to JSON, as a string — a thin dispatcher over the wire
    backend knob (``REPORTER_TPU_WIRE_NATIVE``): native C writer when
    armed, else the Python columnar writer. Byte-identical to
    ``json.dumps(report(...), separators=(",", ":"))`` (pinned by
    tests/test_report_writer.py); a plain-dict match (numpy fallback or
    hand-built) takes exactly that dict route."""
    mm = _matcher()
    if not isinstance(match, mm.MatchRuns):
        return json.dumps(
            report(match, trace, threshold_sec, report_levels,
                   transition_levels), separators=(",", ":"))
    out = _try_native_wire(match, trace, threshold_sec, report_levels,
                           transition_levels)
    if out is not None:
        return bytes(out).decode("utf-8")
    return _report_json_py(match, trace, threshold_sec, report_levels,
                           transition_levels)


def _report_json_py(match, trace: dict, threshold_sec: float,
                    report_levels: Iterable[int],
                    transition_levels: Iterable[int]) -> str:
    """The Python columnar writer — the wire dispatcher's fallback
    backend and the oracle the native writer is pinned against."""
    mm = _matcher()
    scan = _scan_segments(
        *_segment_columns(match), trace["trace"][-1]["time"],
        threshold_sec, set(report_levels), set(transition_levels))
    match["mode"] = "auto"  # same side effect as report()
    r_t0, r_t1 = scan.r_t0, scan.r_t1
    parts = []
    for i in range(len(scan.r_id)):
        # t0/t1 are columnar start/end values — always finite floats on
        # this path, so bare repr matches json.dumps byte for byte
        nx = scan.r_next[i]
        parts.append(
            f'{{"id":{scan.r_id[i]},"t0":{r_t0[i]!r},'
            f'"t1":{r_t1[i]!r},"length":{scan.r_len[i]},'
            f'"queue_length":{scan.r_queue[i]}'
            + (f',"next_id":{nx}}}' if nx is not None else "}"))
    body = (
        '{"stats":{"successful_matches":{"count":%d,"length":%s},'
        '"unreported_matches":{"count":%d,"length":%s},'
        '"match_errors":{"discontinuities":%d,"invalid_speeds":%d,'
        '"invalid_times":%d},"unassociated_segments":%d}'
        % (scan.successful, mm._jnum(round(scan.successful_km, 3)),
           scan.unreported, mm._jnum(round(scan.unreported_km, 3)),
           scan.discontinuities, scan.invalid_speeds, scan.invalid_times,
           scan.unassociated))
    if scan.shape_used:
        body += f',"shape_used":{scan.shape_used}'
    # the holdback cut is over REPORTED segments only; the echoed
    # segment_matcher carries every run, like the dict path. The _py
    # writer explicitly (not the dispatcher): this path IS the Python
    # backend, and must stay pure-Python end to end
    body += (',"segment_matcher":'
             + mm.render_segments_json_py(match.cols, match.lo, match.hi,
                                          "auto")
             + ',"datastore":{"mode":"auto","reports":['
             + ",".join(parts) + "]}}")
    return body
