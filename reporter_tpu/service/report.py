"""Datastore report generation from matcher output.

Behavioral port of the reference's ``report()``
(reference: py/reporter_service.py:79-179) — this is the compatibility
contract between the matcher and every downstream consumer (the Java
worker's ``forward()`` at BatchingProcessor.java:108-141 and the batch
pipeline at simple_reporter.py:168-177). Preserved semantics:

- trailing holdback: segments whose start_time is within ``threshold_sec``
  of the trace end are withheld (the vehicle may still be on them), and
  ``shape_used`` marks how much of the trace may be trimmed
- emission is *pairwise*: a segment is reported only once its successor is
  known; ``t1`` is the successor's start time when the successor's level is
  in ``transition_levels``, else the segment's own end time
- internal segments (turn channels, roundabouts) never clear the pending
  prior segment — they are bridged over
- validity: positive finite dt and speed <= 160 km/h
- the stats block (successful/unreported counts, discontinuities, invalid
  times/speeds, unassociated segments)

One deliberate deviation: the reference *assigns* the last segment's km to
the stats ``length`` fields instead of accumulating
(reporter_service.py:138,142); here lengths are summed, which is the
evident intent of the telemetry.
"""
from __future__ import annotations

import math
from typing import Iterable, Optional


class _Pending:
    """The prior segment awaiting its successor before being reported."""

    __slots__ = ("segment_id", "start_time", "end_time", "length",
                 "queue_length", "level", "internal")

    def __init__(self, seg: dict, level: int):
        self.segment_id = seg.get("segment_id")
        self.start_time = seg.get("start_time")
        self.end_time = seg.get("end_time")
        self.length = seg.get("length")
        self.queue_length = seg.get("queue_length")
        self.level = level
        self.internal = seg.get("internal", False)


def report(match: dict, trace: dict, threshold_sec: float,
           report_levels: Iterable[int],
           transition_levels: Iterable[int]) -> dict:
    """Turn a match result into datastore reports + stats."""
    report_levels = set(report_levels)
    transition_levels = set(transition_levels)
    segs = match["segments"]
    trace_end = trace["trace"][-1]["time"]

    # ---- trailing holdback (reference: reporter_service.py:83-92) --------
    last_idx = len(segs) - 1
    while last_idx >= 0 and \
            trace_end - segs[last_idx]["start_time"] < threshold_sec:
        last_idx -= 1
    shape_used: Optional[int] = None
    if last_idx >= 0:
        # keep the boundary-straddling probe: the reference trims at the
        # in-progress segment's first point (reporter_service.py:92), but
        # without the last probe of the PRECEDING segment the next window
        # can never interpolate this segment's entry time, so every
        # window-boundary segment would be reported partial (length -1)
        # and dropped — a systematic hole in the datastore stream at
        # every batch trim. The preceding run's end_shape_index is the
        # straddling probe even when jitter-dropped points sit between
        # the runs.
        if last_idx > 0:
            shape_used = segs[last_idx - 1]["end_shape_index"]
        else:
            shape_used = max(segs[0]["begin_shape_index"] - 1, 0)

    match["mode"] = "auto"
    reports = []
    stats = {
        "successful": 0, "successful_km": 0.0,
        "unreported": 0, "unreported_km": 0.0,
        "discontinuities": 0, "invalid_times": 0, "invalid_speeds": 0,
        "unassociated": 0,
    }

    pending: Optional[_Pending] = None
    first = True
    for idx in range(last_idx + 1):
        seg = segs[idx]
        seg_id = seg.get("segment_id")
        internal = seg.get("internal", False)
        start_time = seg.get("start_time")

        # a partial end followed by a partial start marks a discontinuity
        # (reference: reporter_service.py:114-116)
        if idx > 0 and start_time == -1 and segs[idx - 1]["end_time"] == -1:
            stats["discontinuities"] += 1

        level = (seg_id & 0x7) if seg_id is not None else -1

        # emit the pending segment now that its successor is visible;
        # an internal successor defers emission (reference: :122-127)
        if pending is not None and pending.segment_id is not None \
                and pending.length is not None \
                and pending.length > 0 and not internal:
            if pending.level in report_levels:
                t1 = start_time if level in transition_levels \
                    else pending.end_time
                entry = {
                    "id": pending.segment_id,
                    "t0": pending.start_time,
                    "t1": t1,
                    "length": pending.length,
                    "queue_length": pending.queue_length,
                }
                if level in transition_levels and seg_id is not None:
                    entry["next_id"] = seg_id
                dt = float(entry["t1"]) - float(entry["t0"])
                if dt <= 0 or math.isinf(dt) or math.isnan(dt):
                    stats["invalid_times"] += 1
                elif (pending.length / dt) * 3.6 > 160:
                    stats["invalid_speeds"] += 1
                else:
                    reports.append(entry)
                    stats["successful"] += 1
                    stats["successful_km"] += round(pending.length * 0.001, 3)
            else:
                stats["unreported"] += 1
                stats["unreported_km"] += round(pending.length * 0.001, 3)

        # internal segments bridge: keep the pending prior
        # (reference: :144-156)
        if internal and not first:
            if pending is not None:
                pending.internal = True
        else:
            pending = _Pending(seg, level)
        first = False

        # service roads etc: matched edges with no OSMLR id
        # (reference: :159-162)
        if seg_id is None and not internal:
            stats["unassociated"] += 1

    out = {
        "stats": {
            "successful_matches": {
                "count": stats["successful"],
                "length": round(stats["successful_km"], 3),
            },
            "unreported_matches": {
                "count": stats["unreported"],
                "length": round(stats["unreported_km"], 3),
            },
            "match_errors": {
                "discontinuities": stats["discontinuities"],
                "invalid_speeds": stats["invalid_speeds"],
                "invalid_times": stats["invalid_times"],
            },
            "unassociated_segments": stats["unassociated"],
        },
    }
    # reference quirk preserved: shape_used omitted when falsy (index 0)
    if shape_used:
        out["shape_used"] = shape_used
    out["segment_matcher"] = match
    out["datastore"] = {"mode": "auto", "reports": reports}
    return out
