"""The /report wire backend: C-level writer vs Python columnar writer.

PR 4 moved /report serialisation from per-run dicts to a Python
columnar writer; this module finishes the wire path (ISSUE 11): the
response bytes for a whole run-column slice are emitted by ONE
GIL-released C call (native/src/host_runtime.cpp ``rt_report_json``,
ABI 12) into one contiguous buffer that goes to the socket with no
re-encode. The Python writer stays behind the same interface as the
fallback backend and the byte-parity oracle.

Backend knob — ``REPORTER_TPU_WIRE_NATIVE``:

- unset / ``auto`` (default): native whenever the library loads
- ``0`` / ``off`` / ``false`` / ``python``: always the Python writer

Failure domain: the writer gets the PR 9 circuit-breaker treatment — a
native writer fault (or an armed ``wire.native`` failpoint) counts a
``wire.circuit`` failure and THAT response falls back to the Python
writer byte-identically; enough consecutive failures open the circuit
and later responses skip the native attempt until a half-open probe
re-closes it. A writer fault therefore degrades, never 500s.

Metrics: ``wire.native`` / ``wire.fallback`` responses and
``wire.errors`` faults, plus the breaker's ``wire.circuit.*`` family.
"""
from __future__ import annotations

import json
import logging
import math
import numbers
import os
from typing import Optional

from .. import native
from ..utils import faults, metrics
from ..utils.circuit import CircuitBreaker
from ..utils.runtime import _env_float, _env_int

logger = logging.getLogger("reporter_tpu.wire")

ENV_VAR = "REPORTER_TPU_WIRE_NATIVE"
_OFF_VALUES = ("0", "off", "false", "python")

#: the writer's failure domain (same threshold/cooldown knobs as the
#: matcher's breakers): open = every response takes the Python writer
circuit = CircuitBreaker(
    "wire.circuit",
    threshold=_env_int("REPORTER_TPU_CIRCUIT_THRESHOLD", 5),
    cooldown_s=_env_float("REPORTER_TPU_CIRCUIT_COOLDOWN_S", 30.0))

# knob-parse memo keyed on the raw env value: this runs once per
# /report response, and the parse (and even ``os.environ.get`` itself,
# ~1.4 us through os._Environ's key encoding) was measurable next to a
# writer whose whole job is a handful of microseconds. The raw value
# is read from os.environ's backing dict when the implementation
# exposes it (CPython; ~0.1 us) — setenv/monkeypatch write through
# that same dict, so tests stay free to flip the knob mid-process.
try:
    _env_data = os.environ._data  # type: ignore[attr-defined]
    _ENV_KEY_RAW = os.environ.encodekey(ENV_VAR)  # type: ignore
except AttributeError:  # pragma: no cover - non-CPython fallback
    _env_data, _ENV_KEY_RAW = None, ENV_VAR
_knob_memo = (b"\0unset", True)


def use_native() -> bool:
    """Resolve the backend knob: opted out, or native library absent,
    means the Python writer; otherwise (default auto) the C writer."""
    global _knob_memo
    raw = _env_data.get(_ENV_KEY_RAW) if _env_data is not None \
        else os.environ.get(ENV_VAR)
    memo = _knob_memo
    if raw != memo[0]:
        val = raw.decode() if isinstance(raw, bytes) else raw
        on = val is None or val.strip().lower() not in _OFF_VALUES
        memo = _knob_memo = (raw, on)
    if not memo[1]:
        return False
    return native.available()


def level_mask(levels) -> Optional[int]:
    """Levels as a 0..7 bitmask, or None when a mask cannot reproduce
    the Python scan's SET-MEMBERSHIP semantics (the caller then takes
    the Python writer — the semantic oracle). The scan tests
    ``level in levels`` where level is an int in -1..7 (-1 = no
    segment id), so:

    - integral numbers in 0..7 become mask bits (bools and x.0 floats
      compare equal to int levels in a set, so they coerce safely);
    - non-integral / non-numeric values (2.5, "0", None) can never
      equal an int level — they are DROPPED, never coerced (int("0")
      would invent a match the Python writer does not make);
    - a value equal to -1 CAN match the no-id level in the set test,
      which no 0..7 mask expresses — that forces the fallback;
    - integral values past 7 can never match (level = sid & 7): drop.
    """
    m = 0
    for v in levels:
        if isinstance(v, numbers.Integral):  # bool, int, numpy ints
            iv = int(v)
        elif isinstance(v, numbers.Real):  # float, numpy floats
            f = float(v)
            # inf/nan/2.5 can never equal an int level (and int(inf)
            # raises — this runs BEFORE the degrade-never-500 try)
            if not math.isfinite(f) or f != int(f):
                continue
            iv = int(f)
        elif v is None or isinstance(v, (str, bytes)):
            continue  # can never compare equal to an int level
        else:
            # an exotic numeric (Decimal, a user type with __eq__)
            # MIGHT match in the set test — only the oracle knows
            return None
        if iv == -1:
            return None
        if 0 <= iv <= 7:
            m |= 1 << iv
    return m


def maybe_native_report(arrays: dict, lo: int, hi: int, trace_end,
                        threshold_sec, report_levels,
                        transition_levels) -> Optional[memoryview]:
    """The whole /report body from the C writer, or None when the
    backend is off, the circuit is open, or the writer faulted (the
    caller then takes the Python writer — byte-identical, pinned).

    Chunk memo: when the batched assembler attached the chunk layout
    (``_run_off``/``_trace_end``), the FIRST response serialised from
    this chunk emits EVERY trace's body in one GIL-released C call
    into one contiguous buffer, and later responses — including the
    other requests micro-batched into the same decode — are zero-copy
    memoryview slices of it. The memo is keyed on (threshold, masks)
    and each slice is guarded by its trace's recorded end time, so a
    caller with different options or a doctored trace falls back to
    the exact per-trace C call instead of serving stale bytes. The
    plain-dict memo write is GIL-atomic; two racing builders produce
    byte-identical buffers and the last one wins (benign)."""
    if not use_native() or not circuit.allow():
        return None
    rep_m = level_mask(report_levels)
    trans_m = level_mask(transition_levels)
    if rep_m is None or trans_m is None:
        return None  # mask can't mirror the set semantics: Python path
    threshold_sec = float(threshold_sec)
    trace_end = float(trace_end)
    key = (threshold_sec, rep_m, trans_m)
    memo = arrays.get("_wire_chunk")
    if memo is not None and memo[0] == key:
        hit = memo[1].get((lo, hi))
        if hit is not None and (hit[0] == trace_end or lo == hi):
            metrics.count("wire.native")
            return hit[1]
    try:
        faults.failpoint("wire.native")
        out = None
        # build the whole-chunk buffer only when this chunk has NO memo
        # yet: with requests alternating two option sets in one chunk, a
        # rebuild per mismatch would re-serialise the chunk per REQUEST
        # (O(N^2) trace bodies) — mismatches take the per-trace call
        if memo is None and "_run_off" in arrays:
            buf, offsets = native.write_report_json_batch(
                arrays, threshold_sec, rep_m, trans_m)
            ro = arrays["_run_off"].tolist()
            ends = arrays["_trace_end"].tolist()
            mv = buf.data
            slices = {}
            for t in range(len(offsets) - 1):
                slices[(ro[t], ro[t + 1])] = (
                    ends[t], mv[offsets[t]:offsets[t + 1]])
            arrays["_wire_chunk"] = (key, slices)
            hit = slices.get((lo, hi))
            if hit is not None and (hit[0] == trace_end or lo == hi):
                out = hit[1]
        if out is None:
            out = native.write_report_json(
                arrays, lo, hi, trace_end, threshold_sec, rep_m,
                trans_m)
    except Exception as e:
        circuit.record_failure()
        metrics.count("wire.errors")
        logger.warning("native /report writer failed (%s); serving via "
                       "the Python writer", e)
        return None
    circuit.record_success()
    metrics.count("wire.native")
    return out


def maybe_native_segments(arrays: dict, lo: int, hi: int,
                          mode: str) -> Optional[memoryview]:
    """``{"segments":...,"mode":...}`` from the C writer, or None (same
    degradation contract as :func:`maybe_native_report`)."""
    if not use_native() or not circuit.allow():
        return None
    try:
        faults.failpoint("wire.native")
        mode_json = b'"auto"' if mode == "auto" \
            else json.dumps(mode).encode("utf-8")
        out = native.write_segments_json(arrays, lo, hi, mode_json)
    except Exception as e:
        circuit.record_failure()
        metrics.count("wire.errors")
        logger.warning("native segments writer failed (%s); serving via "
                       "the Python writer", e)
        return None
    circuit.record_success()
    metrics.count("wire.native")
    return out
