"""Pre-fork ``SO_REUSEPORT`` multi-process serving.

One ``ThreadingHTTPServer`` process is GIL-bound: every handler thread,
the dispatch loop and the Python halves of match/serialise share one
interpreter, so a multi-core box serves at roughly one core's
throughput. This module is the process-per-core multiplier
(``REPORTER_TPU_SERVICE_PROCS`` / ``--procs N``): the parent forks N
workers, each binds the SAME ``(host, port)`` with ``SO_REUSEPORT``
(server.ReusePortThreadingHTTPServer) and the kernel spreads accepted
connections across them — no shared accept lock, no proxy hop, and each
worker owns a whole interpreter, dispatcher and device handle.

Fork discipline — everything heavyweight happens POST-fork:

- the parent calls :func:`serve_prefork` with a ``make_service``
  thunk and never builds a matcher, device handle or dispatcher itself;
  each worker runs the thunk after the fork, so no child ever inherits
  a native WorkerPool, a JAX client or a live dispatcher thread
  (native/__init__.py's ``_check_owner`` makes the inherited-handle
  mistake loud rather than a condvar hang);
- module singletons that DO predate the fork (metrics registry,
  TrackedLock internals, flight-recorder ring, spool caches, racecheck
  graphs) are reset in the child by the :mod:`..utils.forksafe` hooks,
  so each worker's /metrics and postmortems describe its own work.

Per-process writer identity: each worker slot extends
``REPORTER_TPU_WRITER_ID`` with ``p<slot>`` before building its
service, so every epoch tile file name (streaming/anonymiser.py
``{source}.{writer}.e{epoch:08d}``) and therefore every ingest-ledger
key (datastore/ingest.py) is process-unique — tee/egress stays
exactly-once across workers exactly as it does across bigreplay's
multi-writer topology. A restarted worker reuses its slot's id: PR 9's
committed-epoch markers make the re-emit overwrite byte-identically
instead of colliding.

Supervision: the parent is a dumb waitpid loop — restart a dead worker
in the same slot, forever, with exponential backoff against crash
loops. An rc-137 exit (SIGKILL, or the crash failpoint's ``os._exit
(137)``) is logged as such and the worker's flight-recorder dumps
(``flightrec-<pid>-*``, named by the DEAD pid) are enumerated, never
touched: the postmortem outlives the process it describes. SIGTERM /
SIGINT to the parent TERMs every worker and reaps them; worker exits
during shutdown do not restart.
"""
from __future__ import annotations

import errno
import glob
import logging
import os
import signal
import sys
import time
from typing import Callable, Dict, Optional

logger = logging.getLogger("reporter_tpu.prefork")

ENV_PROCS = "REPORTER_TPU_SERVICE_PROCS"

#: consecutive fast-crash backoff ceiling (seconds); the first restart
#: in a slot is immediate, a crash-looping slot converges to this pace
MAX_BACKOFF_S = 5.0
#: a worker that lived at least this long resets its slot's crash count
HEALTHY_AGE_S = 10.0


def writer_id_for_slot(slot: int, base: Optional[str] = None) -> str:
    """The slot's writer identity: the inherited ``REPORTER_TPU_
    WRITER_ID`` (multihost deployments already tag each host) extended
    with ``p<slot>`` — stable across restarts of the slot, distinct
    across slots, so epoch tile names and ingest-ledger keys never
    collide between workers sharing a sink."""
    if base is None:
        base = os.environ.get("REPORTER_TPU_WRITER_ID", "")
    return f"{base}.p{slot}" if base else f"p{slot}"


def worker_main(slot: int, make_service: Callable[[], object],
                host: str, port: int,
                procs: Optional[int] = None) -> int:
    """One worker's whole life, run just after the fork: adopt the
    slot's writer identity AND device slice, build the service (device
    handle, native runtime, dispatcher — all POST-fork), bind the
    shared port with ``SO_REUSEPORT`` and serve until TERMed. Returns
    an exit code (the caller ``os._exit``\\ s it — a worker must never
    fall back into the parent's stack)."""
    os.environ["REPORTER_TPU_WRITER_ID"] = writer_id_for_slot(slot)
    # slot-derived device ownership (the writer-identity pattern, for
    # devices): worker s of P claims its contiguous block of
    # jax.local_devices() via REPORTER_TPU_DEVICE_SLICE, so N processes
    # x M devices compose — every worker's decode mesh spans ITS
    # devices and no two workers contend on one device queue. An
    # operator-set slice wins (heterogeneous pinning); single-proc mode
    # claims nothing.
    if procs and procs > 1 and not os.environ.get(
            "REPORTER_TPU_DEVICE_SLICE"):
        os.environ["REPORTER_TPU_DEVICE_SLICE"] = f"{slot}/{procs}"
    # the parent's supervisor handlers are not ours: TERM must close
    # the listener and exit this process, not set the parent's flag
    httpd_box: Dict[str, object] = {}

    def _term(signum, frame):
        srv = httpd_box.get("httpd")
        if srv is not None:
            # shutdown() BLOCKS until serve_forever exits — and this
            # handler runs in the very thread serve_forever occupies,
            # so calling it inline would deadlock the worker against
            # itself. A helper thread lets the handler return, the
            # loop notice the flag, and in-flight requests finish.
            import threading
            threading.Thread(target=srv.shutdown,  # type: ignore
                             daemon=True).start()
        else:
            os._exit(0)

    signal.signal(signal.SIGTERM, _term)
    signal.signal(signal.SIGINT, signal.SIG_IGN)  # parent owns ^C

    from ..utils import metrics
    from .server import make_server
    service = make_service()
    # response identity header + chaos-harness observability
    service.proc_tag = f"p{slot}:{os.getpid()}"  # type: ignore[attr-defined]
    metrics.count("service.procs.worker_start")
    httpd = make_server(service, host, port, reuse_port=True)
    httpd_box["httpd"] = httpd
    logger.info("prefork worker p%d (pid %d) serving on %s:%d",
                slot, os.getpid(), host, port)
    try:
        httpd.serve_forever()
    except KeyboardInterrupt:
        pass
    finally:
        try:
            httpd.server_close()
        except Exception:
            pass
    return 0


def _exit_code(status: int) -> int:
    """waitpid status -> shell-style exit code (signal n => 128+n)."""
    if os.WIFSIGNALED(status):
        return 128 + os.WTERMSIG(status)
    if os.WIFEXITED(status):
        return os.WEXITSTATUS(status)
    return 1


def _flightrec_dumps(pid: int) -> list:
    """The dead worker's preserved flight-recorder postmortems (named
    by ITS pid — obs/flightrec.py ``flightrec-<pid>-<seq>-*``)."""
    from ..obs import flightrec
    root = flightrec.dump_dir()
    if not root:
        return []
    try:
        return sorted(glob.glob(os.path.join(root, f"flightrec-{pid}-*")))
    except Exception:
        return []


def serve_prefork(make_service: Callable[[], object], host: str,
                  port: int, procs: int,
                  max_total_restarts: Optional[int] = None) -> int:
    """Fork ``procs`` workers sharing (host, port) via ``SO_REUSEPORT``
    and supervise them: restart-on-crash with per-slot backoff, rc-137
    aware logging, flight-recorder dumps preserved and enumerated.
    Blocks until SIGTERM/SIGINT, then TERMs and reaps every worker.
    ``max_total_restarts`` bounds the restart budget (tests/CI; None =
    supervise forever). Returns a process exit code."""
    procs = max(1, int(procs))
    shutting_down = {"flag": False}

    def _stop(signum, frame):
        shutting_down["flag"] = True

    old_term = signal.signal(signal.SIGTERM, _stop)
    old_int = signal.signal(signal.SIGINT, _stop)

    slot_of: Dict[int, int] = {}           # pid -> slot
    started_at: Dict[int, float] = {}      # pid -> monotonic start
    crashes: Dict[int, int] = {}           # slot -> consecutive fast crashes
    respawn_at: Dict[int, float] = {}      # slot -> earliest respawn time
    restarts = 0

    def _spawn(slot: int) -> int:
        pid = os.fork()
        if pid == 0:
            # child: never unwind into the supervisor's stack
            code = 1
            try:
                code = worker_main(slot, make_service, host, port,
                                   procs=procs)
            except BaseException:
                logger.exception("prefork worker p%d died in startup",
                                 slot)
            finally:
                os._exit(code)
        slot_of[pid] = slot
        started_at[pid] = time.monotonic()
        logger.info("prefork: started worker p%d as pid %d", slot, pid)
        return pid

    from ..utils import metrics
    for slot in range(procs):
        _spawn(slot)
        metrics.count("service.procs.spawned")

    rc = 0
    try:
        # WNOHANG poll rather than a blocking waitpid: PEP 475 restarts
        # a blocking waitpid after the SIGTERM handler returns, so the
        # shutdown flag would never be seen until a child happened to die
        while (slot_of or respawn_at) and not shutting_down["flag"]:
            # due backed-off respawns first: the backoff is a DEADLINE,
            # never an inline sleep — a crash-looping slot must not
            # stall reaping of other workers or SIGTERM shutdown
            now = time.monotonic()
            for slot in [s for s, at in respawn_at.items() if at <= now]:
                del respawn_at[slot]
                restarts += 1
                metrics.count("service.procs.restarts")
                _spawn(slot)
            try:
                pid, status = os.waitpid(-1, os.WNOHANG)
            except OSError as e:
                if e.errno == errno.ECHILD:
                    if not respawn_at:
                        break
                    time.sleep(0.05)
                    continue
                raise
            if pid == 0:
                time.sleep(0.05)
                continue
            slot = slot_of.pop(pid, None)
            if slot is None:
                continue  # transient fork-exec child (subprocess etc.)
            age = time.monotonic() - started_at.pop(pid, time.monotonic())
            code = _exit_code(status)
            if shutting_down["flag"]:
                logger.info("prefork: worker p%d (pid %d) exited rc %d "
                            "during shutdown", slot, pid, code)
                continue
            metrics.count("service.procs.deaths")
            dumps = _flightrec_dumps(pid)
            if code == 137:
                # SIGKILL-grade: OOM killer, chaos harness, operator.
                # The postmortem is the flight recorder's, not ours.
                logger.error(
                    "prefork: worker p%d (pid %d) SIGKILLed (rc 137) "
                    "after %.1fs; %d flight-recorder dump(s) preserved%s",
                    slot, pid, age, len(dumps),
                    ": " + ", ".join(dumps) if dumps else "")
            else:
                logger.error(
                    "prefork: worker p%d (pid %d) exited rc %d after "
                    "%.1fs%s", slot, pid, code, age,
                    "; dumps: " + ", ".join(dumps) if dumps else "")
            if max_total_restarts is not None \
                    and restarts >= max_total_restarts:
                logger.error("prefork: restart budget exhausted; "
                             "shutting down")
                rc = 1
                shutting_down["flag"] = True
                continue
            # backoff against a crash-looping slot; a worker that
            # served healthily resets its slot's streak. The respawn is
            # SCHEDULED (picked up at the top of the loop), keeping the
            # supervisor responsive for other deaths and for shutdown
            crashes[slot] = 0 if age >= HEALTHY_AGE_S \
                else crashes.get(slot, 0) + 1
            delay = min(MAX_BACKOFF_S, 0.1 * (2 ** crashes[slot])) \
                if crashes[slot] else 0.0
            respawn_at[slot] = time.monotonic() + delay
    finally:
        # TERM + reap every survivor, restore the old handlers
        for pid in list(slot_of):
            try:
                os.kill(pid, signal.SIGTERM)
            except ProcessLookupError:
                pass
        deadline = time.monotonic() + 10.0
        for pid in list(slot_of):
            try:
                while time.monotonic() < deadline:
                    done, _ = os.waitpid(pid, os.WNOHANG)
                    if done == pid:
                        break
                    time.sleep(0.05)
                else:
                    os.kill(pid, signal.SIGKILL)
                    os.waitpid(pid, 0)
            except (ChildProcessError, ProcessLookupError):
                pass
            slot_of.pop(pid, None)
        signal.signal(signal.SIGTERM, old_term)
        signal.signal(signal.SIGINT, old_int)
    logger.info("prefork: supervisor exiting rc %d (%d restarts)",
                rc, restarts)
    return rc


__all__ = ["serve_prefork", "worker_main", "writer_id_for_slot",
           "ENV_PROCS"]
