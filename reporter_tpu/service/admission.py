"""SLO-driven admission control + the graceful-degradation ladder.

Nothing in the serving tier said "no" before this module: every request
was admitted no matter how far p99 had blown past ``REPORTER_TPU_SLO_MS``,
the dispatcher queue grew without bound, and overload meant collapse —
every request slow — instead of a bounded number of requests shed. This
is the overload-control layer ROADMAP's "millions of users at peak, and
it bends instead of breaking" direction calls for, using the PR 7 SLO
budgets and the PR 8 queue-depth gauges as *sensors*:

- **AdmissionGate** — the front door. Before a /report request is even
  parsed, the gate reads three live sensors and sheds with HTTP 429 +
  a computed ``Retry-After`` (utils/http.py clients already honour it)
  rather than queueing work that cannot meet its deadline:

  * ``queue``     the dispatcher backlog, both as a hard bound (the
                  ``REPORTER_TPU_QUEUE_MAX`` queue is full) and as a
                  *deadline* check — predicted queue wait (depth x the
                  dispatcher's EWMA per-trace service time) exceeding
                  ``DEADLINE_FRACTION`` of the SLO budget means the
                  request would breach before it even dispatched;
  * ``slo``       the *windowed* p99 of each budgeted stage breaching
                  its ``REPORTER_TPU_SLO_MS`` target — windowed via
                  bucket-count deltas of the cumulative histograms, so
                  the sensor recovers when load drops (a lifetime p99
                  never forgets one bad minute);
  * ``inflight``  admitted-but-unanswered requests over
                  ``REPORTER_TPU_INFLIGHT_MAX``.

  Every shed is counted per reason (``admission.shed.{queue,slo,
  inflight}``); an admission-path failure (the ``admission.gate``
  failpoint, a sensor exception) FAILS OPEN — admit, count
  ``admission.errors`` — because a broken gate must degrade to PR-13
  behaviour (serve everything), never to shedding everything.

- **PressureLadder** — under *sustained* pressure the service steps
  down feature-by-feature instead of dying, one named rung at a time
  with hysteresis (a rung must hold for ``REPORTER_TPU_PRESSURE_HOLD_S``
  before the next step; stepping back up needs twice that calm, so the
  ladder cannot flap):

      normal -> shed_shadow -> shed_trace -> coarse_buckets -> oracle_decode

  * ``shed_shadow``    shadow-accuracy sampling suspended (the oracle
                       thread's CPU goes back to serving);
  * ``shed_trace``     per-request ``?trace=1`` tracing refused
                       (span/export overhead shed);
  * ``coarse_buckets`` the adaptive bucket splitter disabled — fewer,
                       larger decode shapes, no split dispatches and no
                       fresh compile episodes mid-storm;
  * ``oracle_decode``  the last rung: decode serves via the numpy
                       oracle path (the PR 9 circuit fallback), keeping
                       the device queue free for the drain backlog.

  Transitions are logged, counted (``pressure.transitions`` +
  ``pressure.enter.<rung>``), and surfaced as the ``pressure`` block on
  ``/health`` and the worker heartbeat.

Both halves arm via ``REPORTER_TPU_ADMISSION=1`` (default off: the gate
is a serving-fleet policy, not a test-suite default). The module state
is process-wide by design — one ladder per process, like the profiler —
and resets in forked pre-fork workers via the ``utils.forksafe`` hook
(a child must not inherit the parent's pressure level).
"""
from __future__ import annotations

import logging
import math
import os
import time
from typing import Callable, Dict, List, Optional, Tuple

from ..obs import slo
from ..utils import faults, metrics
from ..utils import forksafe as _forksafe
from ..utils import locks as _locks
from ..utils.runtime import _env_float, _env_int

logger = logging.getLogger("reporter_tpu.admission")

ENV_ADMISSION = "REPORTER_TPU_ADMISSION"
ENV_INFLIGHT = "REPORTER_TPU_INFLIGHT_MAX"
ENV_HOLD = "REPORTER_TPU_PRESSURE_HOLD_S"

#: fraction of the tightest SLO budget the predicted queue wait may
#: consume before the gate sheds on the deadline check — the remaining
#: half covers the admitted request's own batch (gather + service):
#: with REPORTER_TPU_BATCH_LATENCY_MS at ~budget/4 the worst case sums
#: comfortably inside the budget
DEADLINE_FRACTION = 0.5

#: Retry-After clamp: at least 1 s (sub-second retries re-arrive inside
#: the same overload), at most 30 s (a misestimated EWMA must not park
#: honest clients for minutes)
RETRY_AFTER_MIN_S = 1
RETRY_AFTER_MAX_S = 30

#: how often the gate refreshes its windowed-p99 sensor; between
#: refreshes admit() costs two integer reads and a couple of compares
EVAL_INTERVAL_S = 0.25


class Overload(RuntimeError):
    """A request shed by load management (admission gate or the bounded
    dispatcher queue). ``reason`` is the counted shed family; the
    serving layer maps this to HTTP 429 with ``Retry-After:
    ceil(retry_after_s)``."""

    def __init__(self, reason: str, retry_after_s: float):
        super().__init__(f"overloaded ({reason}); retry after "
                         f"{retry_after_s:.0f}s")
        self.reason = reason
        self.retry_after_s = retry_after_s


def armed() -> bool:
    """Whether admission control is armed (``REPORTER_TPU_ADMISSION``);
    read per service build, not cached — a test or operator flips it
    between constructions."""
    return os.environ.get(ENV_ADMISSION, "").strip().lower() \
        not in ("", "0", "off", "false", "no")


def retry_after_s(depth: int, ewma_s: Optional[float]) -> int:
    """The computed back-off a shed response carries: the expected time
    for the current backlog to drain (depth x per-trace EWMA), clamped.
    With no service-time estimate yet, the floor — an honest "soon"."""
    if not ewma_s or depth <= 0:
        return RETRY_AFTER_MIN_S
    return int(min(max(math.ceil(depth * ewma_s), RETRY_AFTER_MIN_S),
                   RETRY_AFTER_MAX_S))


# ---- windowed p99 ----------------------------------------------------------

class WindowedQuantile:
    """p99 over a sliding window of a cumulative stage histogram.

    The metrics timers are monotone (they never forget), so a lifetime
    p99 that breached once stays breached forever — useless as an
    admission sensor, which must notice *recovery*. This helper diffs
    the fixed log-bucket counts between evaluations: the diff IS the
    window's histogram, and its p99 is the window's p99. An idle window
    (no new observations) reports None — an idle stage is not a slow
    one, matching obs/slo.py's posture.
    """

    def __init__(self, registry: Optional[metrics.Registry] = None):
        self._registry = registry if registry is not None \
            else metrics.default
        self._prev: Dict[str, Tuple[int, List[int]]] = {}

    def update(self, stages: List[str]) -> Dict[str, Optional[float]]:
        """One evaluation: {stage: windowed p99 seconds or None}."""
        _counters, timers = self._registry.export_state()
        out: Dict[str, Optional[float]] = {}
        for stage in stages:
            got = timers.get(stage)
            if got is None:
                out[stage] = None
                continue
            count, _total, max_s, buckets = got
            prev_count, prev_buckets = self._prev.get(stage, (0, None))
            self._prev[stage] = (count, buckets)
            window = count - prev_count
            if window <= 0:
                out[stage] = None
                continue
            if prev_buckets is None:
                diff = buckets
            else:
                diff = [b - p for b, p in zip(buckets, prev_buckets)]
            out[stage] = self._quantile(diff, window, 0.99, max_s)
        return out

    @staticmethod
    def _quantile(diff: List[int], total: int, q: float,
                  max_s: float) -> float:
        """Within-bucket linear interpolation, the same scheme as
        metrics._Timer.quantile — the raw log2 bucket UPPER bound
        would overestimate by up to 2x, and a 2x-high p99 sensor
        sheds traffic that is actually inside budget."""
        bounds = metrics.BUCKET_BOUNDS_S
        target = q * total
        cum = 0
        for idx, n in enumerate(diff):
            below = cum
            cum += n
            if cum >= target:
                lo = bounds[idx - 1] if 0 < idx <= len(bounds) else 0.0
                hi = bounds[idx] if idx < len(bounds) else max_s
                frac = (target - below) / n if n else 1.0
                return min(lo + frac * (hi - lo), max_s)
        return max_s


# ---- the degradation ladder ------------------------------------------------

#: the named rungs, mildest first; index == pressure level
RUNGS = ("normal", "shed_shadow", "shed_trace", "coarse_buckets",
         "oracle_decode")


class PressureLadder:
    """Sustained-pressure step-down with hysteresis.

    :meth:`observe` feeds one boolean pressure sample (typically "did
    the gate shed / breach this evaluation window"). A condition must
    hold continuously for ``hold_s`` before the ladder steps DOWN one
    rung (toward degradation), and for ``2 * hold_s`` of calm before it
    steps back UP — and at most one rung moves per hold interval, so a
    spike cannot slam the service to the oracle path and a lull cannot
    snap every feature back at once.
    """

    def __init__(self, hold_s: Optional[float] = None,
                 clock: Callable[[], float] = time.monotonic):
        self.hold_s = hold_s if hold_s is not None \
            else _env_float(ENV_HOLD, 2.0)
        self.clock = clock
        self._lock = _locks.new_lock("admission.ladder")
        self.level = 0
        now = clock()
        self._cond = False      # last observed pressure condition
        self._cond_t = now      # when that condition began
        self._trans_t = now     # last transition time
        self.transitions = 0

    def observe(self, pressured: bool) -> int:
        """Feed one pressure sample; returns the (possibly new) level.
        Transitions apply their rung effects outside the ladder lock."""
        new_level = None
        with self._lock:
            now = self.clock()
            if pressured != self._cond:
                self._cond = pressured
                self._cond_t = now
            dwell = now - self._cond_t
            since_trans = now - self._trans_t
            if pressured and self.level < len(RUNGS) - 1 \
                    and dwell >= self.hold_s \
                    and since_trans >= self.hold_s:
                self.level += 1
                self._trans_t = now
                self.transitions += 1
                new_level = self.level
            elif not pressured and self.level > 0 \
                    and dwell >= 2.0 * self.hold_s \
                    and since_trans >= 2.0 * self.hold_s:
                self.level -= 1
                self._trans_t = now
                self.transitions += 1
                new_level = self.level
            level = self.level
        if new_level is not None:
            _apply_level(new_level)
            metrics.count("pressure.transitions")
            metrics.count(f"pressure.enter.{RUNGS[new_level]}")
            logger.warning("pressure ladder -> %s (level %d/%d)",
                           RUNGS[new_level], new_level, len(RUNGS) - 1)
        return level

    def snapshot(self) -> dict:
        with self._lock:
            return {"level": self.level,
                    "state": RUNGS[self.level],
                    "rungs": list(RUNGS),
                    "transitions": self.transitions,
                    "hold_s": self.hold_s}


def _apply_level(level: int) -> None:
    """Push the rung effects into their owning modules (cold path: runs
    only on a transition). Each effect is a module flag the hot path
    reads with one global load; lazy imports keep this module free of
    matcher/profiler import cycles."""
    from ..obs import profiler
    profiler.set_shadow_suspended(level >= 1)
    global _trace_shed
    with _module_lock:
        _trace_shed = level >= 2
    from ..matcher import incremental
    incremental.set_pressure_shed(level >= 2)
    from ..matcher import batchpad
    batchpad.set_pressure_coarse(level >= 3)
    from ..matcher import matcher as matcher_mod
    matcher_mod.set_pressure_oracle(level >= 4)


# ---- process-wide ladder state ---------------------------------------------

_module_lock = _locks.new_lock("admission.module")
_ladder: Optional[PressureLadder] = None
_trace_shed = False


def ladder(hold_s: Optional[float] = None,
           clock: Callable[[], float] = time.monotonic
           ) -> PressureLadder:
    """The process-wide ladder, created on first use (one ladder per
    process: every gate in the process feeds it, every consumer —
    /health, the heartbeat, the rung flags — reads it)."""
    global _ladder
    with _module_lock:
        if _ladder is None:
            _ladder = PressureLadder(hold_s=hold_s, clock=clock)
        return _ladder


def current_level() -> int:
    lad = _ladder
    return lad.level if lad is not None else 0


def allow_request_trace() -> bool:
    """Whether per-request ``?trace=1`` tracing is currently allowed
    (the ``shed_trace`` rung refuses it under pressure)."""
    return not _trace_shed


def pressure_snapshot() -> dict:
    """The /health "pressure" block (also carried by the worker
    heartbeat): current ladder state, or the quiescent shape when no
    ladder was ever armed."""
    lad = _ladder
    if lad is None:
        return {"level": 0, "state": RUNGS[0], "transitions": 0}
    return lad.snapshot()


def _reset_module() -> None:
    """Forksafe / test reset: a forked worker (or the next test) must
    start at pressure zero with every rung effect withdrawn. The rung
    effects are only withdrawn when a ladder actually existed — the
    hook runs on EVERY fork in the process (subprocess's transient
    fork-exec children included) and must not import the matcher stack
    into a child that never armed admission."""
    global _ladder, _trace_shed
    with _module_lock:
        had = _ladder is not None
        _ladder = None
        _trace_shed = False
    if had:
        _apply_level(0)


_forksafe.register(_reset_module)


# ---- the admission gate ----------------------------------------------------

class AdmissionGate:
    """The /report front door: admit (and track in-flight) or shed.

    ``dispatcher`` duck-types :class:`..service.dispatch.BatchDispatcher`
    (``queue_depth()``, ``service_ewma_s()``, ``queue_max``). The gate
    is built per service (pre-fork workers each build their own post-
    fork) but feeds the ONE process-wide pressure ladder.
    """

    def __init__(self, dispatcher,
                 inflight_max: Optional[int] = None,
                 hold_s: Optional[float] = None,
                 clock: Callable[[], float] = time.monotonic,
                 registry: Optional[metrics.Registry] = None):
        self.dispatcher = dispatcher
        if inflight_max is None:
            inflight_max = _env_int(ENV_INFLIGHT, 0)
        if inflight_max <= 0:
            # default: four full device batches of admitted work — the
            # dispatcher pipeline stays fed without the handler pool
            # itself becoming an unbounded queue
            inflight_max = 4 * max(1, getattr(dispatcher, "max_batch",
                                              64))
        self.inflight_max = inflight_max
        self.clock = clock
        self._lock = _locks.new_lock("admission.gate")
        self._inflight = 0
        self._window = WindowedQuantile(registry)
        self._last_eval = 0.0
        self._slo_breaches: List[str] = []
        self.ladder = ladder(hold_s=hold_s, clock=clock)
        self._shed_in_window = False

    # -- sensors ----------------------------------------------------------
    def _maybe_refresh(self, now: float) -> None:
        """Rate-limited sensor refresh: ONE thread per interval wins
        the locked check-and-set and recomputes the windowed p99s —
        an unlocked check would let a second refresher consume an
        empty bucket window and wipe the first's breach verdict (and
        clobber a concurrent shed sample). The winner also feeds the
        ladder one pressure sample."""
        with self._lock:
            if now - self._last_eval < EVAL_INTERVAL_S:
                return
            self._last_eval = now
            shed_seen = self._shed_in_window
            self._shed_in_window = False
        targets = slo.thresholds()
        breaches: List[str] = []
        if targets:
            p99s = self._window.update(sorted(targets))
            breaches = [stage for stage, budget in targets.items()
                        if p99s.get(stage) is not None
                        and p99s[stage] > budget]
        self._slo_breaches = breaches
        self.ladder.observe(bool(breaches) or shed_seen)

    def _evaluate(self) -> Optional[Overload]:
        self._maybe_refresh(self.clock())
        depth = self.dispatcher.queue_depth()
        ewma = self.dispatcher.service_ewma_s()
        qmax = getattr(self.dispatcher, "queue_max", 0)
        # the hard bound watches QUEUED work only (queued_depth):
        # queue_depth() also counts the batch in service, and a
        # max_batch larger than the bound would then read as
        # permanently full — shedding everything for every batch wall
        queued = getattr(self.dispatcher, "queued_depth",
                         self.dispatcher.queue_depth)()
        if qmax and queued >= qmax:
            return Overload("queue", retry_after_s(depth, ewma))
        targets = slo.thresholds()
        if targets and ewma and depth:
            budget = min(targets.values())
            if depth * ewma > DEADLINE_FRACTION * budget:
                # the deadline check: this request would spend its SLO
                # budget waiting in the queue — shed it NOW, while the
                # 429 is cheap, instead of serving a guaranteed breach
                return Overload("queue", retry_after_s(depth, ewma))
        if self._slo_breaches:
            return Overload("slo", retry_after_s(depth, ewma))
        return None

    # -- the gate ---------------------------------------------------------
    def admit(self) -> Optional[Overload]:
        """None = admitted (in-flight slot held until :meth:`release`);
        an :class:`Overload` = shed, counted per reason. A gate-path
        failure fails OPEN: a broken sensor serves everything."""
        try:
            faults.failpoint("admission.gate")
            verdict = self._evaluate()
            if verdict is None:
                # atomic compare-and-increment: a check in _evaluate
                # followed by a separate increment would let N
                # concurrent admits all pass at inflight_max - 1 and
                # overshoot the cap — the exact race the cap exists
                # to close
                with self._lock:
                    if self._inflight >= self.inflight_max:
                        verdict = Overload(
                            "inflight",
                            retry_after_s(
                                self.dispatcher.queue_depth(),
                                self.dispatcher.service_ewma_s()))
                    else:
                        self._inflight += 1
        except Exception as e:
            metrics.count("admission.errors")
            logger.error("admission gate failed open: %s", e)
            # fail-open admits still hold a slot: the caller WILL call
            # release(), and an unpaired decrement would leak capacity
            # out of the cap's books
            with self._lock:
                self._inflight += 1
            metrics.count("admission.admitted")
            return None
        if verdict is None:
            metrics.count("admission.admitted")
            return None
        metrics.count(f"admission.shed.{verdict.reason}")
        with self._lock:
            self._shed_in_window = True
        return verdict

    def release(self) -> None:
        """The admitted request answered (any status): free its slot."""
        with self._lock:
            if self._inflight > 0:
                self._inflight -= 1

    def tick(self) -> None:
        """Sensor/ladder heartbeat for idle periods: /health calls this
        so a service that stopped receiving traffic still steps the
        ladder back up (observe() only runs on admissions otherwise)."""
        self._maybe_refresh(self.clock())

    def snapshot(self) -> dict:
        with self._lock:
            inflight = self._inflight
        reg = metrics.default
        return {
            "armed": True,
            "inflight": inflight,
            "inflight_max": self.inflight_max,
            "queue_depth": self.dispatcher.queue_depth(),
            "queue_max": getattr(self.dispatcher, "queue_max", 0),
            "service_ewma_ms": round(
                (self.dispatcher.service_ewma_s() or 0.0) * 1000.0, 3),
            "slo_breaches": list(self._slo_breaches),
            "admitted": reg.counter("admission.admitted"),
            "shed": {reason: reg.counter(f"admission.shed.{reason}")
                     for reason in ("queue", "slo", "inflight")},
            "errors": reg.counter("admission.errors"),
        }


__all__ = ["AdmissionGate", "PressureLadder", "WindowedQuantile",
           "Overload", "RUNGS", "armed", "retry_after_s", "ladder",
           "current_level", "allow_request_trace", "pressure_snapshot"]
