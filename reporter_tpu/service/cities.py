"""Multi-tenant city residency: several cities hot in one fleet.

One serving process used to mean one graph + one datastore. The
multi-city tier routes every ``city=``-tagged request through a
:class:`CityRegistry`: a byte-budgeted LRU (Hermes-style memory-budgeted
residency) of fully wired per-city stacks — graph, matcher (with its
native runtime), dispatcher and datastore. A request for a non-resident
city LOADS it (evicting the least-recently-used city once the budget is
exceeded) and pre-warms the native route-pair memo from the city's
committed ``.profile`` artifact (datastore/profile.py), so the first
request batch of a newly resident city hits a warm memo instead of
paying every pair's Dijkstra cold.

Configuration is the service config's ``cities`` map::

    {"cities": {"metro-a": {"graph": "a.npz", "datastore": "/data/a",
                            "profile": "/data/a/.profile"}}}

(``profile`` defaults to ``<datastore>/.profile``; either key may be
omitted — a city can serve /report without a datastore and vice versa.)

``REPORTER_TPU_CITY_BUDGET_MB`` bounds resident graph bytes (default
512 MB; the most recently used city is never evicted, so one oversized
city still serves). Counters surface as ``datastore.city.*``; /health
and /profile carry the residency table.
"""
from __future__ import annotations

import logging
from collections import OrderedDict
from typing import Callable, Dict, Optional

import numpy as np

from ..utils import metrics
from ..utils import locks as _locks

logger = logging.getLogger("reporter_tpu.service")


def city_budget_bytes() -> int:
    from ..utils.runtime import _env_float
    return int(_env_float("REPORTER_TPU_CITY_BUDGET_MB", 512.0)
               * 1024 * 1024)


def _graph_bytes(net) -> int:
    """Resident-size estimate of one city: the graph's numpy columns
    (the dominant term; the native handle mirrors the same columns, so
    this undercounts by a small constant factor — the budget is a
    residency bound, not an allocator)."""
    total = 0
    for v in vars(net).values():
        if isinstance(v, np.ndarray):
            total += v.nbytes
    return total


class CityEntry:
    """One resident city's wired stack."""

    def __init__(self, name: str, service, size_bytes: int,
                 warmed_pairs: int = 0):
        self.name = name
        self.service = service
        self.size_bytes = size_bytes
        self.warmed_pairs = warmed_pairs
        # in-flight request pins (registry._reslock guards both): an
        # evicted entry with live pins defers its close to the last
        # release — eviction must never stop the dispatcher under a
        # request another handler thread is still serving through it
        self._refs = 0
        self._evicted = False

    def close(self) -> None:
        """Release on eviction: stop the dispatcher's drain thread so
        the evicted stack cannot outlive its handles; graph/native/mmap
        memory frees with the last reference."""
        try:
            self.service.dispatcher.close()
        except Exception as e:
            logger.warning("evicting %s: dispatcher close failed: %s",
                           self.name, e)

    def snapshot(self) -> dict:
        m = self.service.matcher
        memo = m.runtime.route_memo_stats() if m.runtime is not None \
            else None
        return {"size_bytes": self.size_bytes,
                # the cold-start counter pair: warmed_pairs > 0 with
                # memo hits climbing on the first batch is the pre-warm
                # working; a cold load shows 0 / all-miss
                "warmed_pairs": self.warmed_pairs,
                "route_memo": memo,
                "datastore": self.service.datastore is not None}


class CityRegistry:
    """Byte-budgeted LRU of :class:`CityEntry` (see module docstring).

    ``loader`` (tests, harnesses) overrides the config-driven build:
    ``loader(name) -> (service, size_bytes_or_None)``.
    """

    def __init__(self, config: Optional[Dict[str, dict]] = None,
                 budget_bytes: Optional[int] = None,
                 loader: Optional[Callable] = None):
        self.config = dict(config or {})
        self._budget = budget_bytes
        self.loader = loader
        # long_hold_ok: a miss loads a whole city (graph parse + native
        # build + memo pre-warm — seconds) under the lock by design;
        # residency swaps must be serialised, and concurrent requests
        # for the loading city want exactly this wait
        self._lock = _locks.new_lock("datastore.cities",
                                     long_hold_ok=True)
        # the resident MAP has its own tiny lock so /health and
        # /profile snapshots (and pin/release) never wait out a
        # multi-second city load; order is always _lock -> _reslock
        self._reslock = _locks.new_lock("datastore.cities.resident")
        self._resident: "OrderedDict[str, CityEntry]" = OrderedDict()

    @property
    def budget_bytes(self) -> int:
        return self._budget if self._budget is not None \
            else city_budget_bytes()

    def known(self) -> list:
        names = set(self.config)
        if self.loader is not None:
            with self._reslock:
                names |= set(self._resident)
        return sorted(names)

    # -- residency ---------------------------------------------------------
    def _hit(self, name: str, pin: bool) -> Optional[CityEntry]:
        """Resident-map lookup under the TINY lock only: a request for
        an already-loaded city must never wait out another city's
        multi-second load. The pin increments INSIDE the same critical
        section — a pin taken after the lock drops could race an
        eviction closing the entry first."""
        with self._reslock:
            got = self._resident.get(name)
            if got is not None:
                # LD001 reads the big registry lock as this map's
                # guard (most writes sit inside both); the map's real
                # guard is _reslock, which THIS block holds — the hot
                # hit path skipping _lock is the whole point (a
                # resident city must not wait out another's load)
                self._resident.move_to_end(name)  # lint: ignore[LD001]
                if pin:
                    got._refs += 1
        return got

    def get(self, name: str, pin: bool = False) -> CityEntry:
        """The city's entry, loading (and pre-warming) on a miss. A
        miss loads the whole city UNDER the registry lock (LD003-style
        hold by design — see the lock's long_hold_ok note above:
        residency swaps must serialise, and concurrent requests for
        the loading city want exactly this wait); resident HITS take
        only the tiny map lock; evicted stacks are closed after the
        locks drop."""
        got = self._hit(name, pin)
        if got is not None:
            metrics.count("datastore.city.hits")
            return got
        evicted = []
        try:
            with self._lock:  # lint: ignore[LD003]
                got = self._hit(name, pin)  # loaded while we waited
                if got is not None:
                    metrics.count("datastore.city.hits")
                    return got
                if self.loader is None and name not in self.config:
                    raise KeyError(
                        f"unknown city {name!r}; configured: "
                        f"{sorted(self.config)}")
                metrics.count("datastore.city.misses")
                entry = self._load(name)
                with self._reslock:
                    self._resident[name] = entry
                    if pin:
                        entry._refs += 1
                    # drop LRU cities until resident bytes fit the
                    # budget; the most recent stays regardless (one
                    # oversized city must still serve)
                    budget = self.budget_bytes
                    while len(self._resident) > 1 and \
                            sum(e.size_bytes for e
                                in self._resident.values()) > budget:
                        ename, e = self._resident.popitem(last=False)
                        e._evicted = True
                        metrics.count("datastore.city.evictions")
                        if e._refs <= 0:
                            evicted.append((ename, e))
                        # else: a handler is mid-request through this
                        # entry — release() closes it at the last unpin
                return entry
        finally:
            for ename, e in evicted:
                logger.info("evicting city %s (%.1f MB) over the "
                            "residency budget", ename,
                            e.size_bytes / 1e6)
                e.close()

    def acquire(self, name: str) -> CityEntry:
        """``get`` plus a pin taken under the map lock: the entry
        cannot be closed (only unmapped) until the matching
        :meth:`release` — the request-routing spelling
        (server._route)."""
        return self.get(name, pin=True)

    def release(self, entry: CityEntry) -> None:
        """Unpin; closes an entry the LRU evicted mid-request once the
        last in-flight request drains off it."""
        with self._reslock:
            entry._refs -= 1
            close_now = entry._evicted and entry._refs <= 0
        if close_now:
            logger.info("closing evicted city %s after its last "
                        "in-flight request", entry.name)
            entry.close()

    def _load(self, name: str) -> CityEntry:
        with metrics.timer("datastore.city.load"):
            if self.loader is not None:
                service, size = self.loader(name)
                if size is None:
                    size = _graph_bytes(service.matcher.net)
                entry = CityEntry(name, service, size)
            else:
                entry = self._load_from_config(name)
            # pre-warm AFTER the stack is wired: the profile artifact's
            # resident pairs land in the fresh native memo so the first
            # request batch hits instead of running every Dijkstra cold
            from ..datastore import load_profile, warm_matcher
            from ..datastore.profile import profile_path
            conf = self.config.get(name, {})
            ppath = conf.get("profile")
            if ppath is None and conf.get("datastore"):
                ppath = profile_path(conf["datastore"])
            if ppath is None and entry.service.datastore is not None:
                ppath = profile_path(entry.service.datastore.root)
            if ppath:
                try:
                    entry.warmed_pairs = warm_matcher(
                        entry.service.matcher, load_profile(ppath))
                except Exception as e:
                    # the pre-warm is an optimisation: it must never
                    # cost the city load
                    logger.warning("profile pre-warm of %s failed "
                                   "(loading cold): %s", name, e)
            metrics.count("datastore.city.loads")
            logger.info("city %s resident: %.1f MB, %d memo pairs "
                        "pre-warmed", name, entry.size_bytes / 1e6,
                        entry.warmed_pairs)
            return entry

    def _load_from_config(self, name: str) -> CityEntry:
        from ..graph.network import RoadNetwork
        from ..matcher import SegmentMatcher
        from .server import ReporterService
        conf = self.config[name]
        if not conf.get("graph"):
            raise ValueError(f"city {name!r} has no 'graph' configured")
        net = RoadNetwork.load(conf["graph"])
        datastore = None
        if conf.get("datastore"):
            from ..datastore import LocalDatastore
            datastore = LocalDatastore(conf["datastore"])
        service = ReporterService(SegmentMatcher(net=net),
                                  datastore=datastore)
        return CityEntry(name, service, _graph_bytes(net))

    def evict(self, name: str) -> bool:
        """Explicit eviction (tests, admin); pinned entries close at
        their last release like LRU-evicted ones. Takes the registry
        lock too (same _lock -> _reslock order as get), so an explicit
        eviction serialises with in-progress loads."""
        with self._lock, self._reslock:
            entry = self._resident.pop(name, None)
            if entry is not None:
                entry._evicted = True
                close_now = entry._refs <= 0
        if entry is None:
            return False
        metrics.count("datastore.city.evictions")
        if close_now:
            entry.close()
        return True

    # -- introspection -----------------------------------------------------
    def snapshot(self) -> dict:
        # tiny lock only: /health and /profile must never wait out a
        # city load; per-entry stats (a quick C counter read) happen
        # on the copied list
        with self._reslock:
            entries = list(self._resident.items())
        resident = {name: e.snapshot() for name, e in entries}
        return {"budget_bytes": self.budget_bytes,
                "resident_bytes": sum(e["size_bytes"]
                                      for e in resident.values()),
                "configured": sorted(self.config),
                "resident": resident}


__all__ = ["CityRegistry", "CityEntry", "city_budget_bytes"]
