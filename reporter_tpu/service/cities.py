"""Multi-tenant city residency: several cities hot in one fleet.

One serving process used to mean one graph + one datastore. The
multi-city tier routes every ``city=``-tagged request through a
:class:`CityRegistry`: a byte-budgeted LRU (Hermes-style memory-budgeted
residency) of fully wired per-city stacks — graph, matcher (with its
native runtime), dispatcher and datastore. A request for a non-resident
city LOADS it (evicting the least-recently-used city once the budget is
exceeded) and pre-warms the native route-pair memo from the city's
committed ``.profile`` artifact (datastore/profile.py), so the first
request batch of a newly resident city hits a warm memo instead of
paying every pair's Dijkstra cold.

Configuration is the service config's ``cities`` map::

    {"cities": {"metro-a": {"graph": "a.npz", "datastore": "/data/a",
                            "profile": "/data/a/.profile"}}}

(``profile`` defaults to ``<datastore>/.profile``; either key may be
omitted — a city can serve /report without a datastore and vice versa.)

``REPORTER_TPU_CITY_BUDGET_MB`` bounds resident graph bytes (default
512 MB; the most recently used city is never evicted, so one oversized
city still serves). Counters surface as ``datastore.city.*``; /health
and /profile carry the residency table.
"""
from __future__ import annotations

import json
import logging
import os
from collections import OrderedDict, deque
from typing import Callable, Dict, Optional

import numpy as np

from ..utils import metrics
from ..utils import locks as _locks

logger = logging.getLogger("reporter_tpu.service")


def city_budget_bytes() -> int:
    from ..utils.runtime import _env_float
    return int(_env_float("REPORTER_TPU_CITY_BUDGET_MB", 512.0)
               * 1024 * 1024)


def swap_sample_fraction() -> float:
    """Fraction of admitted /report traffic sampled into a resident
    city's capture ring — the dual-version shadow gate's corpus."""
    from ..utils.runtime import _env_float
    return max(0.0, min(1.0,
                        _env_float("REPORTER_TPU_SWAP_SAMPLE", 0.25)))


def swap_agreement_floor() -> float:
    """Minimum segment-id agreement (old vs candidate graph over the
    capture ring) below which :meth:`CityRegistry.swap` refuses to
    flip."""
    from ..utils.runtime import _env_float
    return _env_float("REPORTER_TPU_SWAP_AGREEMENT", 0.99)


def swap_window() -> int:
    """Capture-ring capacity: how many sampled requests the shadow
    gate re-scores at swap time."""
    from ..utils.runtime import _env_int
    return max(1, _env_int("REPORTER_TPU_SWAP_WINDOW", 64))


def swap_force() -> bool:
    """Operator override: flip even below the agreement floor (an
    intentional map change legitimately rewrites segment ids)."""
    from ..utils.runtime import _env_int
    return bool(_env_int("REPORTER_TPU_SWAP_FORCE", 0))


def _graph_bytes(net) -> int:
    """Resident-size estimate of one city: the graph's numpy columns
    (the dominant term; the native handle mirrors the same columns, so
    this undercounts by a small constant factor — the budget is a
    residency bound, not an allocator)."""
    total = 0
    for v in vars(net).values():
        if isinstance(v, np.ndarray):
            total += v.nbytes
    return total


class CityEntry:
    """One resident city's wired stack."""

    def __init__(self, name: str, service, size_bytes: int,
                 warmed_pairs: int = 0,
                 map_version: Optional[str] = None):
        self.name = name
        self.service = service
        self.size_bytes = size_bytes
        self.warmed_pairs = warmed_pairs
        # content-derived graph identity (graph/version.py), stamped by
        # the registry load; swap() compares it across versions and
        # /health surfaces it per resident city
        self.map_version = map_version
        # in-flight request pins (registry._reslock guards both): an
        # evicted entry with live pins defers its close to the last
        # release — eviction must never stop the dispatcher under a
        # request another handler thread is still serving through it
        self._refs = 0
        self._evicted = False
        # swap shadow capture: a bounded ring of recently admitted
        # /report requests (deterministic accumulator sampling, same
        # family as the obs/profiler shadow sampler); swap() re-scores
        # the ring on BOTH the serving and the candidate graph off the
        # hot path — the dual-version shadow gate's evidence
        self._capture: deque = deque(maxlen=swap_window())
        self._cap_acc = 0.0
        self._cap_lock = _locks.new_lock("datastore.cities.capture")

    def observe(self, req: dict) -> None:
        """Sample one admitted /report request into the capture ring
        (hot-path cost: one accumulator add; the occasional sampled
        request appends to a bounded deque)."""
        frac = swap_sample_fraction()
        if frac <= 0.0:
            return
        with self._cap_lock:
            self._cap_acc += frac
            if self._cap_acc < 1.0:
                return
            self._cap_acc -= 1.0
            self._capture.append(req)
        metrics.count("swap.shadow.sampled")

    def capture_samples(self) -> list:
        with self._cap_lock:
            return list(self._capture)

    def close(self) -> None:
        """Release on eviction: stop the dispatcher's drain thread so
        the evicted stack cannot outlive its handles; graph/native/mmap
        memory frees with the last reference. Carried decode state
        (matcher/incremental.py) built against this graph is flushed
        first — an evicted or swapped-out city must not leave per-trace
        Viterbi state keyed to a dead graph."""
        try:
            table = getattr(self.service.matcher,
                            "_incremental_table", None)
            if table is not None:
                table.clear()
        except Exception as e:
            logger.warning("evicting %s: incremental-state flush "
                           "failed: %s", self.name, e)
        try:
            self.service.dispatcher.close()
        except Exception as e:
            logger.warning("evicting %s: dispatcher close failed: %s",
                           self.name, e)

    def snapshot(self) -> dict:
        m = self.service.matcher
        memo = m.runtime.route_memo_stats() if m.runtime is not None \
            else None
        return {"size_bytes": self.size_bytes,
                # the cold-start counter pair: warmed_pairs > 0 with
                # memo hits climbing on the first batch is the pre-warm
                # working; a cold load shows 0 / all-miss
                "warmed_pairs": self.warmed_pairs,
                "route_memo": memo,
                "map_version": self.map_version,
                "datastore": self.service.datastore is not None}


class CityRegistry:
    """Byte-budgeted LRU of :class:`CityEntry` (see module docstring).

    ``loader`` (tests, harnesses) overrides the config-driven build:
    ``loader(name) -> (service, size_bytes_or_None)``.
    """

    def __init__(self, config: Optional[Dict[str, dict]] = None,
                 budget_bytes: Optional[int] = None,
                 loader: Optional[Callable] = None):
        self.config = dict(config or {})
        self._budget = budget_bytes
        self.loader = loader
        # long_hold_ok: a miss loads a whole city (graph parse + native
        # build + memo pre-warm — seconds) under the lock by design;
        # residency swaps must be serialised, and concurrent requests
        # for the loading city want exactly this wait
        self._lock = _locks.new_lock("datastore.cities",
                                     long_hold_ok=True)
        # the resident MAP has its own tiny lock so /health and
        # /profile snapshots (and pin/release) never wait out a
        # multi-second city load; order is always _lock -> _reslock
        self._reslock = _locks.new_lock("datastore.cities.resident")
        self._resident: "OrderedDict[str, CityEntry]" = OrderedDict()
        # swap bookkeeping (guarded by _reslock): the last swap record
        # per city plus flip/refusal totals — /health's swap block
        self._swap_last: Dict[str, dict] = {}
        self._swap_flips = 0
        self._swap_refusals = 0

    @property
    def budget_bytes(self) -> int:
        return self._budget if self._budget is not None \
            else city_budget_bytes()

    def known(self) -> list:
        names = set(self.config)
        if self.loader is not None:
            with self._reslock:
                names |= set(self._resident)
        return sorted(names)

    # -- residency ---------------------------------------------------------
    def _hit(self, name: str, pin: bool) -> Optional[CityEntry]:
        """Resident-map lookup under the TINY lock only: a request for
        an already-loaded city must never wait out another city's
        multi-second load. The pin increments INSIDE the same critical
        section — a pin taken after the lock drops could race an
        eviction closing the entry first."""
        with self._reslock:
            got = self._resident.get(name)
            if got is not None:
                # LD001 reads the big registry lock as this map's
                # guard (most writes sit inside both); the map's real
                # guard is _reslock, which THIS block holds — the hot
                # hit path skipping _lock is the whole point (a
                # resident city must not wait out another's load)
                self._resident.move_to_end(name)  # lint: ignore[LD001]
                if pin:
                    got._refs += 1
        return got

    def get(self, name: str, pin: bool = False) -> CityEntry:
        """The city's entry, loading (and pre-warming) on a miss. A
        miss loads the whole city UNDER the registry lock (LD003-style
        hold by design — see the lock's long_hold_ok note above:
        residency swaps must serialise, and concurrent requests for
        the loading city want exactly this wait); resident HITS take
        only the tiny map lock; evicted stacks are closed after the
        locks drop."""
        got = self._hit(name, pin)
        if got is not None:
            metrics.count("datastore.city.hits")
            return got
        evicted = []
        try:
            with self._lock:  # lint: ignore[LD003]
                got = self._hit(name, pin)  # loaded while we waited
                if got is not None:
                    metrics.count("datastore.city.hits")
                    return got
                if self.loader is None and name not in self.config:
                    raise KeyError(
                        f"unknown city {name!r}; configured: "
                        f"{sorted(self.config)}")
                metrics.count("datastore.city.misses")
                entry = self._load(name)
                with self._reslock:
                    self._resident[name] = entry
                    if pin:
                        entry._refs += 1
                    # drop LRU cities until resident bytes fit the
                    # budget; the most recent stays regardless (one
                    # oversized city must still serve)
                    budget = self.budget_bytes
                    while len(self._resident) > 1 and \
                            sum(e.size_bytes for e
                                in self._resident.values()) > budget:
                        ename, e = self._resident.popitem(last=False)
                        e._evicted = True
                        metrics.count("datastore.city.evictions")
                        if e._refs <= 0:
                            evicted.append((ename, e))
                        # else: a handler is mid-request through this
                        # entry — release() closes it at the last unpin
                return entry
        finally:
            for ename, e in evicted:
                logger.info("evicting city %s (%.1f MB) over the "
                            "residency budget", ename,
                            e.size_bytes / 1e6)
                e.close()

    def acquire(self, name: str) -> CityEntry:
        """``get`` plus a pin taken under the map lock: the entry
        cannot be closed (only unmapped) until the matching
        :meth:`release` — the request-routing spelling
        (server._route)."""
        return self.get(name, pin=True)

    def release(self, entry: CityEntry) -> None:
        """Unpin; closes an entry the LRU evicted mid-request once the
        last in-flight request drains off it."""
        with self._reslock:
            entry._refs -= 1
            close_now = entry._evicted and entry._refs <= 0
        if close_now:
            logger.info("closing evicted city %s after its last "
                        "in-flight request", entry.name)
            entry.close()

    def _load(self, name: str) -> CityEntry:
        with metrics.timer("datastore.city.load"):
            if self.loader is not None:
                service, size = self.loader(name)
                if size is None:
                    size = _graph_bytes(service.matcher.net)
                entry = CityEntry(name, service, size)
            else:
                entry = self._load_from_config(name)
            self._finish_load(name, entry)
            return entry

    def _finish_load(self, name: str, entry: CityEntry) -> None:
        """Wire-up common to every load path (config, loader, swap
        candidate): profile pre-warm, map-version stamping, counters."""
        # pre-warm AFTER the stack is wired: the profile artifact's
        # resident pairs land in the fresh native memo so the first
        # request batch hits instead of running every Dijkstra cold
        from ..datastore import load_profile, warm_matcher
        from ..datastore.profile import profile_path
        conf = self.config.get(name, {})
        ppath = conf.get("profile")
        if ppath is None and conf.get("datastore"):
            ppath = profile_path(conf["datastore"])
        if ppath is None and entry.service.datastore is not None:
            ppath = profile_path(entry.service.datastore.root)
        if ppath:
            try:
                entry.warmed_pairs = warm_matcher(
                    entry.service.matcher, load_profile(ppath))
            except Exception as e:
                # the pre-warm is an optimisation: it must never
                # cost the city load
                logger.warning("profile pre-warm of %s failed "
                               "(loading cold): %s", name, e)
        # content-derived map version (graph/version.py): the graph's
        # persisted columns plus the committed profile artifact — two
        # builds with identical bytes share a version, any change
        # mints a new epoch
        try:
            from ..graph.version import map_version as _map_version
            extra = None
            if ppath and os.path.exists(ppath):
                with open(ppath, "rb") as fh:
                    extra = fh.read()
            entry.map_version = _map_version(entry.service.matcher.net,
                                             extra=extra)
        except Exception as e:
            logger.warning("map version of %s unavailable: %s", name, e)
        # the version stamps the city's datastore: epoch-qualified
        # ledger keys and manifest epoch tags (datastore/store.py)
        # keep histograms from mixing map builds across a swap
        if entry.map_version is not None \
                and entry.service.datastore is not None:
            try:
                entry.service.datastore.set_map_version(
                    entry.map_version)
            except Exception as e:
                logger.warning("stamping %s datastore with map %s "
                               "failed: %s", name, entry.map_version, e)
        metrics.count("datastore.city.loads")
        logger.info("city %s resident: %.1f MB, %d memo pairs "
                    "pre-warmed, map %s", name, entry.size_bytes / 1e6,
                    entry.warmed_pairs, entry.map_version)

    def _load_from_config(self, name: str) -> CityEntry:
        from ..graph.network import RoadNetwork
        from ..matcher import SegmentMatcher
        from .server import ReporterService
        conf = self.config[name]
        if not conf.get("graph"):
            raise ValueError(f"city {name!r} has no 'graph' configured")
        net = RoadNetwork.load(conf["graph"])
        datastore = None
        if conf.get("datastore"):
            from ..datastore import LocalDatastore
            datastore = LocalDatastore(conf["datastore"])
        service = ReporterService(SegmentMatcher(net=net),
                                  datastore=datastore)
        return CityEntry(name, service, _graph_bytes(net))

    def evict(self, name: str) -> bool:
        """Explicit eviction (tests, admin); pinned entries close at
        their last release like LRU-evicted ones. Takes the registry
        lock too (same _lock -> _reslock order as get), so an explicit
        eviction serialises with in-progress loads."""
        with self._lock, self._reslock:
            entry = self._resident.pop(name, None)
            if entry is not None:
                entry._evicted = True
                close_now = entry._refs <= 0
        if entry is None:
            return False
        metrics.count("datastore.city.evictions")
        if close_now:
            entry.close()
        return True

    # -- zero-downtime map swap --------------------------------------------
    def swap(self, name: str, new_source=None,
             force: Optional[bool] = None) -> dict:
        """Hot-swap city ``name`` to a new map build with zero downtime.

        ``new_source`` is the next version's source: a config dict
        (replaces ``self.config[name]``) or a zero-arg callable
        returning ``(service, size_bytes_or_None)`` (the loader-style
        spelling tests and harnesses use); ``None`` reloads from the
        current config/loader. The candidate stack loads and pre-warms
        BESIDE the serving one — both versions count against the
        residency budget for the duration — then the dual-version
        shadow gate re-scores the capture ring on both graphs and the
        flip happens at a request boundary: in-flight requests finish
        on vN through their pins (release() closes vN's stack at the
        last unpin), new requests route to vN+1.

        The swap REFUSES (returns a ``refused_*`` record, old version
        keeps serving) rather than evict an unrelated PINNED city for
        room, and when shadow agreement falls below
        ``REPORTER_TPU_SWAP_AGREEMENT`` — unless ``force=True`` /
        ``REPORTER_TPU_SWAP_FORCE=1`` (an intentional map change
        legitimately rewrites segment ids). Every outcome is counted
        (``swap.flips`` / ``swap.refusals``) and surfaced on /health's
        swap block; the returned record carries ``result`` =
        ``flipped`` / ``loaded`` / ``refused_budget`` /
        ``refused_shadow``."""
        from ..utils import faults
        forced = swap_force() if force is None else bool(force)
        with self._lock:  # lint: ignore[LD003]
            prev_conf = self.config.get(name)
            if new_source is not None and not callable(new_source):
                self.config[name] = dict(new_source)
            cand = None
            try:
                if callable(new_source):
                    service, size = new_source()
                    if size is None:
                        size = _graph_bytes(service.matcher.net)
                    cand = CityEntry(name, service, size)
                    with metrics.timer("datastore.city.load"):
                        self._finish_load(name, cand)
                else:
                    if self.loader is None and name not in self.config:
                        raise KeyError(
                            f"unknown city {name!r}; configured: "
                            f"{sorted(self.config)}")
                    cand = self._load(name)
                old = self._hit(name, pin=False)
                if old is None:
                    # nothing resident to shadow against: a plain
                    # (budgeted) load of the new version
                    record = {"city": name, "from": None,
                              "to": cand.map_version,
                              "agreement": None, "checks": 0,
                              "forced": forced, "result": "loaded"}
                    self._admit(name, cand, record)
                    return record
                record = {"city": name, "from": old.map_version,
                          "to": cand.map_version, "forced": forced}
                # residency: both versions are resident through the
                # shadow window and both count against the budget.
                # Unpinned unrelated LRU cities are evicted for room;
                # a PINNED unrelated city refuses the swap instead
                # (it is mid-request — the swap is the optional party
                # here). old+candidate alone over budget still
                # proceeds: the swapping city must serve (the same
                # one-oversized-city rule as get()), and the overshoot
                # ends when vN closes at the flip.
                evicted = []
                refused_for = None
                with self._reslock:
                    budget = self.budget_bytes
                    for ename in [n for n in list(self._resident)
                                  if n != name]:
                        total = cand.size_bytes + sum(
                            e.size_bytes
                            for e in self._resident.values())
                        if total <= budget:
                            break
                        e = self._resident[ename]
                        if e._refs > 0:
                            continue  # pinned: a swap never evicts it
                        del self._resident[ename]
                        e._evicted = True
                        metrics.count("datastore.city.evictions")
                        evicted.append((ename, e))
                    total = cand.size_bytes + sum(
                        e.size_bytes for e in self._resident.values())
                    if total > budget:
                        pinned = sorted(
                            n for n in self._resident if n != name
                            and self._resident[n]._refs > 0)
                        if pinned:
                            refused_for = pinned
                for ename, e in evicted:
                    logger.info("evicting city %s (%.1f MB) for the "
                                "swap of %s", ename,
                                e.size_bytes / 1e6, name)
                    e.close()
                if refused_for is not None:
                    record["pinned"] = refused_for
                    self._restore_conf(name, prev_conf, new_source)
                    return self._refuse(name, cand, record,
                                        "refused_budget")
                # dual-version shadow gate: re-score the serving
                # entry's capture ring on BOTH stacks (off the hot
                # path — the handler threads keep routing to vN) and
                # compare segment-id sequences. An empty ring passes
                # vacuously: a city with no sampled traffic has
                # nothing to disagree about.
                checks = agree = 0
                for sub in old.capture_samples():
                    va = self._shadow_score(old.service, sub)
                    vb = self._shadow_score(cand.service, sub)
                    checks += 1
                    metrics.count("swap.shadow.checks")
                    if va == vb:
                        agree += 1
                        metrics.count("swap.shadow.agree")
                    else:
                        metrics.count("swap.shadow.mismatch")
                agreement = (agree / checks) if checks else 1.0
                record["agreement"] = round(agreement, 4)
                record["checks"] = checks
                floor = swap_agreement_floor()
                if agreement < floor and not forced:
                    record["floor"] = floor
                    self._restore_conf(name, prev_conf, new_source)
                    return self._refuse(name, cand, record,
                                        "refused_shadow")
                # the widest chaos window: candidate loaded, warmed
                # and gated; vN still serving; nothing flipped yet
                faults.failpoint("city.swap")
                with self._reslock:
                    self._resident[name] = cand
                    # lint: ignore[LD001] — same _reslock-guards-the-
                    # map rule as _hit
                    self._resident.move_to_end(name)
                    old._evicted = True
                    close_old_now = old._refs <= 0
                    record["result"] = "flipped"
                    self._swap_last[name] = record
                    self._swap_flips += 1
                metrics.count("swap.flips")
                logger.info(
                    "city %s swapped map %s -> %s (agreement %.4f "
                    "over %d checks%s)", name, record["from"],
                    record["to"], agreement, checks,
                    ", FORCED" if forced and agreement < floor else "")
                if close_old_now:
                    old.close()
                # an explicit epoch event on the new version's change
                # feed: /feed subscribers learn the map changed (and
                # must resync) even before any vN+1 deltas land
                ds = cand.service.datastore
                if ds is not None \
                        and getattr(ds, "freshness", None) is not None:
                    try:
                        ds.freshness.feed.publish_epoch(
                            cand.map_version)
                    except Exception as e:
                        logger.warning("epoch feed event for %s "
                                       "failed: %s", name, e)
                return record
            except BaseException:
                self._restore_conf(name, prev_conf, new_source)
                if cand is not None:
                    try:
                        cand.close()
                    except Exception:
                        pass
                raise

    def _restore_conf(self, name: str, prev_conf, new_source) -> None:
        # swap() (the only caller) holds _lock — the config guard —
        # for this whole call; the per-function pass can't see that
        if new_source is None or callable(new_source):
            return
        if prev_conf is None:
            self.config.pop(name, None)  # lint: ignore[LD001]
        else:
            self.config[name] = prev_conf  # lint: ignore[LD001]

    def _refuse(self, name: str, cand: CityEntry, record: dict,
                result: str) -> dict:
        record["result"] = result
        # _reslock guards the swap bookkeeping (the caller additionally
        # holds _lock; the lint reads neither through the call)
        with self._reslock:
            self._swap_last[name] = record  # lint: ignore[LD001]
            self._swap_refusals += 1
        metrics.count("swap.refusals")
        logger.warning("swap of city %s REFUSED (%s); map %s keeps "
                       "serving: %s", name, result, record.get("from"),
                       record)
        try:
            cand.close()
        except Exception:
            pass
        return record

    def _admit(self, name: str, entry: CityEntry, record: dict) -> None:
        """Insert a swap-loaded entry for a non-resident city with the
        same budget policy as get()."""
        evicted = []
        # _reslock guards the resident map here exactly as in get()
        # (the caller, swap(), additionally holds _lock)
        with self._reslock:
            self._resident[name] = entry  # lint: ignore[LD001]
            budget = self.budget_bytes
            while len(self._resident) > 1 and \
                    sum(e.size_bytes for e
                        in self._resident.values()) > budget:
                # lint: ignore[LD001] — same _reslock critical section
                ename, e = self._resident.popitem(last=False)
                e._evicted = True
                metrics.count("datastore.city.evictions")
                if e._refs <= 0:
                    evicted.append((ename, e))
            self._swap_last[name] = record  # lint: ignore[LD001]
        for ename, e in evicted:
            logger.info("evicting city %s (%.1f MB) over the "
                        "residency budget", ename, e.size_bytes / 1e6)
            e.close()

    @staticmethod
    def _shadow_score(service, sub: dict):
        """The segment-id sequence one version's stack reports for a
        captured request — the shadow gate's comparison key. Non-200
        outcomes compare by status (both versions rejecting a request
        the same way is agreement)."""
        try:
            status, body = service.handle(dict(sub))
        except Exception as e:
            return ("error", str(e))
        if status != 200:
            return (status,)
        if isinstance(body, (bytes, bytearray, memoryview)):
            body = bytes(body).decode("utf-8")
        try:
            doc = json.loads(body)
        except Exception:
            return ("unparseable",)
        segs = (doc.get("segment_matcher") or {}).get("segments") or []
        return (200, tuple(s.get("segment_id") for s in segs))

    # -- introspection -----------------------------------------------------
    def snapshot(self) -> dict:
        # tiny lock only: /health and /profile must never wait out a
        # city load; per-entry stats (a quick C counter read) happen
        # on the copied list
        with self._reslock:
            entries = list(self._resident.items())
            swap = {"flips": self._swap_flips,
                    "refusals": self._swap_refusals,
                    "last": {c: dict(r)
                             for c, r in self._swap_last.items()}}
        resident = {name: e.snapshot() for name, e in entries}
        return {"budget_bytes": self.budget_bytes,
                "resident_bytes": sum(e["size_bytes"]
                                      for e in resident.values()),
                "configured": sorted(self.config),
                "resident": resident,
                # map-lifecycle view: flip/refusal totals plus the
                # last swap record per city (/health's swap block)
                "swap": swap}


__all__ = ["CityRegistry", "CityEntry", "city_budget_bytes",
           "swap_sample_fraction", "swap_agreement_floor",
           "swap_window", "swap_force"]
