from .report import report
from .dispatch import BatchDispatcher

__all__ = ["report", "BatchDispatcher"]
