"""The /report HTTP service.

Drop-in replacement for the reference matcher service
(reference: py/reporter_service.py): same URL surface
(``GET /report?json=...`` and ``POST /report`` with a JSON body), same
request validation and error bodies, same response schema — so the Java
streaming worker (Batch.java:56-72) and the test harnesses work unchanged.

What changed underneath: instead of a thread pool with one C++ matcher per
thread, request threads hand their trace to a :class:`BatchDispatcher`
which batches concurrent requests into single vmapped TPU decodes.

Environment knobs honoured from the reference deployment:
  THRESHOLD_SEC            trailing holdback (reference: :55-58)
  THREAD_POOL_COUNT /      server thread count
  THREAD_POOL_MULTIPLIER   (reference: :37-40)
plus new batching knobs MATCH_BATCH_MAX (traces per device batch) and
MATCH_BATCH_WAIT_MS (flush latency bound).

Run:  python -m reporter_tpu.service.server <config.json> <host:port>
"""
from __future__ import annotations

import json
import multiprocessing
import os
import sys
import threading
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from ..core.tracebatch import points_to_columns
from ..matcher import Configure, SegmentMatcher
from ..obs import trace as obs_trace
from ..utils import metrics
from . import admission
from .dispatch import BatchDispatcher
from .report import report, report_wire

# /report is the reference's only action (reporter_service.py:26);
# /stats is new — a metrics snapshot (counters + stage-timer
# histograms: count/total/mean/max + p50/p95/p99);
# /metrics is the same registry in Prometheus exposition text;
# /histogram is the datastore query surface (datastore/query.py), live
# when the service was built with a datastore attached;
# /health is the failure-domain probe: graph, native runtime vs numpy
# fallback, circuit state, SLO breaches, datastore reachability —
# 200 or 503;
# /profile is the device-level profiler (obs/profiler.py): per-shape
# compile telemetry, per-chunk bucket-occupancy wide events, shadow-
# accuracy verdicts;
# /feed is the change-feed long-poll (datastore/feed.py): bbox
# subscribers block on a monotone cursor instead of polling /histogram
ACTIONS = {"report", "stats", "metrics", "histogram", "health",
           "profile", "feed"}

#: pressure-ladder rung at which /feed sheds subscribers (429 +
#: Retry-After): rung 2 (shed_trace) — one rung BEFORE the ladder
#: starts degrading the match path itself (coarse_buckets), so feed
#: fan-out is always the first load dropped
FEED_SHED_LEVEL = 2


class ReporterService:
    """Owns the matcher + dispatcher; shared by all handler threads."""

    def __init__(self, matcher: SegmentMatcher,
                 threshold_sec: int | None = None,
                 max_batch: int | None = None,
                 max_wait_ms: float | None = None,
                 datastore=None, cities=None):
        self.matcher = matcher
        # optional LocalDatastore serving /histogram (None = 503 there)
        self.datastore = datastore
        # optional CityRegistry (service/cities.py): requests carrying
        # a ``city`` key route to that city's resident stack (loaded
        # through the byte-budgeted LRU with route-memo pre-warm);
        # requests without one serve this default matcher/datastore
        self.cities = cities
        # optional BackgroundCompactor attached by the owning harness/
        # worker — /health surfaces its delta-pressure backlog gauge
        self.compactor = None
        from ..utils.runtime import _env_float, _env_int
        self.threshold_sec = threshold_sec if threshold_sec is not None else \
            _env_int("THRESHOLD_SEC", 15)
        # MATCH_BATCH_MAX default scales with the decode mesh
        # (matcher.match_batch_default: >=2 decode chunks per drained
        # batch, so N devices never sit idle behind a half-chunk flush)
        from ..matcher.matcher import match_batch_default
        self.dispatcher = BatchDispatcher(
            matcher.match_many,
            max_batch=max_batch or _env_int("MATCH_BATCH_MAX", 0)
            or match_batch_default(),
            max_wait_ms=max_wait_ms if max_wait_ms is not None else
            _env_float("MATCH_BATCH_WAIT_MS", 20.0),
            idle_grace_ms=_env_float("MATCH_BATCH_GRACE_MS", 2.0))
        # pre-fork identity ("p<index>:<pid>", set by service/prefork.py
        # worker_main): stamped on responses as X-Reporter-Proc so load
        # tests and the chaos harness can see which worker answered;
        # None (single-process mode) adds no header
        self.proc_tag: str | None = None
        # SLO-driven admission control (service/admission.py, ISSUE 15):
        # armed by REPORTER_TPU_ADMISSION — the /report front door sheds
        # with 429 + Retry-After before work is queued, and feeds the
        # process-wide pressure ladder. None = admit everything (the
        # pre-ISSUE-15 behaviour; the bounded dispatcher queue is still
        # the loud backstop).
        self.admission = admission.AdmissionGate(self.dispatcher) \
            if admission.armed() else None

    def handle(self, trace: dict) -> "tuple[int, str | bytes | memoryview]":
        """Validate + match + report; (status, body). The 200 body is
        BYTES (a memoryview of the chunk buffer on the native wire
        path) — _respond writes it to the socket as is; error bodies
        stay str. Validation messages mirror the reference
        (reporter_service.py:209-245)."""
        routed = self._route(trace, "handle")
        if routed is not None:
            return routed
        if trace.get("uuid") is None:
            return 400, '{"error":"uuid is required"}'
        try:
            trace["trace"][1]
        except Exception:
            return 400, ('{"error":"trace must be a non zero length array of '
                         'object each of which must have at least lat, lon '
                         'and time"}')
        try:
            report_levels = set(trace["match_options"]["report_levels"])
        except Exception:
            return 400, '{"error":"match_options must include report_levels array"}'
        try:
            transition_levels = set(trace["match_options"]["transition_levels"])
        except Exception:
            return 400, '{"error":"match_options must include transition_levels array"}'
        try:
            # columnarise the wire ONCE, in this request thread — the
            # dispatch loop and matcher never touch point dicts again
            lat, lon, tm, acc = points_to_columns(trace["trace"])
            match = self.dispatcher.submit(
                trace, columns=(trace.get("uuid"), lat, lon, tm, acc,
                                trace.get("match_options")))
            # wire writer: the whole response body as bytes, straight
            # from the match's run columns — ONE GIL-released C call on
            # the native backend (memoryview handed to the socket with
            # no re-encode), the Python columnar writer otherwise; the
            # per-trace report/segment dicts never exist on this path
            with obs_trace.span("report.serialise"):
                return 200, report_wire(match, trace, self.threshold_sec,
                                        report_levels, transition_levels)
        except admission.Overload as e:
            # the bounded dispatcher queue shed this request (the
            # backstop behind the admission gate): 429, with the
            # computed back-off in the body — the HTTP handler lifts
            # it into the Retry-After header
            return 429, json.dumps({"error": "overloaded",
                                    "reason": e.reason,
                                    "retry_after_s": e.retry_after_s})
        except Exception as e:
            return 500, json.dumps({"error": str(e)})

    def _route(self, req: dict, method: str):
        """City routing (service/cities.py): a ``city`` key sends this
        request to that city's resident stack — loading it through the
        LRU (with route-memo pre-warm) on a miss. Returns the routed
        (status, body), an error response for an unknown city, or None
        to serve from this default stack."""
        city = req.get("city")
        if city is None:
            return None
        if self.cities is None:
            return 400, json.dumps(
                {"error": "no city registry attached; this fleet "
                          "serves a single city"})
        try:
            # acquire/release pin: the LRU may evict this city while
            # the request is in flight — the entry's dispatcher then
            # closes at our release, never underneath us
            entry = self.cities.acquire(str(city))
        except KeyError as e:
            return 400, json.dumps({"error": str(e).strip("'\"")})
        except Exception as e:
            return 500, json.dumps({"error": f"city load failed: {e}"})
        try:
            sub = {k: v for k, v in req.items() if k != "city"}
            # the routed city's OWN admission gate guards its /report
            # path: the front-door gate only watches THIS service's
            # dispatcher, and a city stack's bounded queue filling up
            # must shed city traffic — not ride on the default stack's
            # idle sensors. (The city key lives in the parsed body, so
            # city sheds are necessarily post-parse; they still happen
            # before any work is queued on the city's dispatcher.)
            gate = getattr(entry.service, "admission", None) \
                if method == "handle" else None
            if gate is not None:
                shed = gate.admit()
                if shed is not None:
                    return 429, json.dumps(
                        {"error": "overloaded", "reason": shed.reason,
                         "retry_after_s": shed.retry_after_s})
                try:
                    status, body = entry.service.handle(sub)
                finally:
                    gate.release()
            elif method == "handle":
                status, body = entry.service.handle(sub)
            else:
                return getattr(entry.service, method)(sub)
            if status == 200:
                # swap shadow capture (service/cities.py): sampled
                # admitted traffic is the corpus the dual-version
                # gate re-scores on a candidate graph at swap time.
                # getattr: registries are duck-typed (tests stub them)
                # and capture is best-effort, never request-fatal.
                observe = getattr(entry, "observe", None)
                if observe is not None:
                    observe(sub)
            return status, body
        finally:
            self.cities.release(entry)

    def histogram(self, params: dict) -> tuple[int, str]:
        """Answer a /histogram query; (status, body). ``params`` carries
        ONE of ``segment_id`` (single), ``segments`` (batched: answered
        through one ``query_many`` sweep) or ``bbox`` + ``level``
        (every resident segment of that level inside the lon/lat box),
        plus optional ``hours`` (list of hour-of-week ints),
        ``time_range`` ([t0, t1) epoch seconds, converted to the hour
        set it covers), ``percentiles``, ``window`` (freshness tier:
        ``5m``/``300s``/``inf`` — see datastore/freshness.py),
        ``viewport`` (with bbox+level: the materialised tile summaries,
        one read per covered tile), and ``city`` (multi-tenant
        routing)."""
        routed = self._route(params, "histogram")
        if routed is not None:
            return routed
        if self.datastore is None:
            return 503, ('{"error":"no datastore attached; serve with a '
                         '--datastore directory"}')
        from ..datastore import DEFAULT_PERCENTILES, hours_for_range
        if params.get("viewport"):
            if params.get("bbox") is None or params.get("level") is None:
                return 400, ('{"error":"viewport queries need bbox '
                             'and level"}')
            tier = self.datastore.enable_freshness()
            if tier is None:
                return 503, ('{"error":"freshness tier disabled '
                             '(REPORTER_TPU_FRESHNESS=0)"}')
            try:
                result = tier.viewports.summarise(
                    params["bbox"], int(params["level"]))
            except (TypeError, ValueError) as e:
                return 400, json.dumps({"error": str(e)})
            return 200, json.dumps(result, separators=(",", ":"))
        seg = params.get("segment_id")
        segs = params.get("segments")
        bbox = params.get("bbox")
        if seg is None and segs is None and bbox is None:
            return 400, ('{"error":"one of segment_id, segments or '
                         'bbox (+level) is required"}')
        hours = params.get("hours")
        if hours is None and params.get("time_range") is not None:
            try:
                t0, t1 = params["time_range"]
            except Exception:
                return 400, ('{"error":"time_range must be a [start, end) '
                             'epoch-seconds pair"}')
            hours = hours_for_range(int(t0), int(t1)).tolist()
        pcts = tuple(params.get("percentiles") or DEFAULT_PERCENTILES)
        # window=: served through the freshness overlay's store view
        # (enable the tier on demand so window=inf works in a serving
        # process that never ingests); window-less requests take the
        # exact pre-freshness path — byte-identical answers
        window = params.get("window")
        if window is not None:
            self.datastore.enable_freshness()
        # epoch pin/merge (datastore/__init__.py): map_version= pins
        # the sweep to one map build, merge=1 explicitly mixes epochs;
        # the default pins to the store's active version
        mv = params.get("map_version")
        mv = str(mv) if mv is not None else None
        merge = bool(params.get("merge"))
        try:
            if bbox is not None:
                if params.get("level") is None:
                    return 400, ('{"error":"bbox queries need a level '
                                 '(0, 1 or 2)"}')
                result = self.datastore.query_bbox(
                    bbox, int(params["level"]), hours=hours,
                    percentiles=pcts,
                    max_segments=params.get("max_segments"),
                    window=window, map_version=mv, merge=merge)
            elif segs is not None:
                result = {"results": self.datastore.query_many(
                    [int(s) for s in segs], hours=hours,
                    percentiles=pcts, window=window,
                    map_version=mv, merge=merge)}
            else:
                result = self.datastore.query(int(seg), hours=hours,
                                              percentiles=pcts,
                                              window=window,
                                              map_version=mv,
                                              merge=merge)
        except (TypeError, ValueError) as e:
            return 400, json.dumps({"error": str(e)})
        return 200, json.dumps(result, separators=(",", ":"))

    def feed(self, params: dict) -> tuple[int, str]:
        """Answer one /feed long-poll; (status, body). Sheds BEFORE
        registering a waiter — on the pressure ladder (rung >=
        ``FEED_SHED_LEVEL``: subscriber fan-out is dropped one rung
        before the match path degrades) and on the feed's own bounded
        waiter table — with 429 bodies carrying ``retry_after_s`` (the
        handler lifts it into Retry-After: PR 14's explicit-retry
        contract; a subscriber is never silently dropped)."""
        routed = self._route(params, "feed")
        if routed is not None:
            return routed
        if self.datastore is None:
            return 503, ('{"error":"no datastore attached; serve with a '
                         '--datastore directory"}')
        tier = self.datastore.enable_freshness()
        if tier is None:
            return 503, ('{"error":"freshness tier disabled '
                         '(REPORTER_TPU_FRESHNESS=0)"}')
        from ..datastore.feed import FEED_RETRY_AFTER_S, FeedOverload
        if admission.current_level() >= FEED_SHED_LEVEL:
            metrics.count("feed.shed.pressure")
            return 429, json.dumps(
                {"error": "overloaded", "reason": "pressure",
                 "retry_after_s": FEED_RETRY_AFTER_S})
        try:
            out = tier.feed.poll(
                bbox=params.get("bbox"),
                level=int(params["level"])
                if params.get("level") is not None else None,
                cursor=int(params.get("cursor", -1)),
                timeout_s=min(float(params.get("timeout", 25.0)), 60.0),
                max_events=int(params.get("max_events", 256)))
        except FeedOverload as e:
            return 429, json.dumps(
                {"error": "overloaded", "reason": e.reason,
                 "retry_after_s": e.retry_after_s})
        except (TypeError, ValueError) as e:
            return 400, json.dumps({"error": str(e)})
        return 200, json.dumps(out, separators=(",", ":"))

    def health(self) -> tuple[int, str]:
        """Liveness + degradation probe; (status, JSON body).

        200 means fully serving: graph loaded and the datastore (when
        attached) reachable. 503 flags a degraded domain a load balancer
        should rotate away from: the native-prep circuit OPEN (still
        serving, via the numpy fallback, but slower), a stage whose p99
        breaches its ``REPORTER_TPU_SLO_MS`` budget (working, but over
        latency budget), or the datastore erroring. The body always
        enumerates every domain either way.
        """
        from ..obs import profiler, slo
        from ..utils import faults, spool
        m = self.matcher
        circuit = m.circuit.snapshot()
        open_domains = m.open_domains()
        try:
            from ..graph.version import map_version as _map_version
            graph_version = _map_version(m.net) if m.net is not None \
                else None
        except Exception:
            graph_version = None
        body = {
            "graph": {"loaded": m.net is not None,
                      "nodes": int(m.net.num_nodes),
                      "edges": int(m.net.num_edges),
                      # content-derived map identity (graph/version.py)
                      # of the DEFAULT stack; per-city versions live in
                      # the cities block below
                      "map_version": graph_version},
            "native": {"status": "native" if m.runtime is not None
                       else "fallback"},
            "circuit": circuit,
            # every guarded hot-path domain by name (ISSUE 9): which
            # breakers are open (serving via their fallback) and each
            # domain's full breaker state — a load balancer rotates on
            # "open", an operator reads "domains" to see which stage
            "degraded": {"open": open_domains,
                         "domains": m.circuit_snapshots()},
            # dead-letter backlog gauges (worker-registered spool roots;
            # zeros when this process runs no worker): a drain stall is
            # visible here long before the disk fills
            "deadletter": spool.backlog_snapshot(),
            "faults": faults.active_spec(),
            # shadow-decode verdicts (informational here; budget the
            # decode.shadow.mismatch_ratio histogram via
            # REPORTER_TPU_SLO_MS to make a mismatch rate flip 503)
            "shadow": profiler.shadow_stats(),
        }
        # carried-state gauge (matcher/incremental.py): table occupancy
        # vs its byte budget, lag bound, eviction/fallback/reset
        # counters — zeros until the first incremental report builds the
        # table (batch-only deployments never pay for it)
        from ..matcher import incremental as _inc
        body["incremental"] = {
            "enabled": _inc.incremental_enabled()
            and not _inc.pressure_shed()}
        if m._incremental_table is not None:
            body["incremental"].update(m._incremental_table.gauge())
        # load-management view (ISSUE 15): the degradation-ladder state
        # plus — when the gate is armed — its live sensors and per-
        # reason shed counters. Informational: a shedding service is
        # doing its job, not failing; the ladder's rungs each have
        # their own degraded signals above. health() doubles as the
        # idle-period ladder tick so a service that stopped receiving
        # traffic still steps back up.
        if self.admission is not None:
            self.admission.tick()
        body["pressure"] = admission.pressure_snapshot()
        body["admission"] = self.admission.snapshot() \
            if self.admission is not None else {"armed": False}
        healthy = True
        if open_domains:
            healthy = False
        slo_check = slo.check()
        body["slo"] = {"targets": {k: round(v * 1000.0, 3) for k, v
                                   in slo_check["targets"].items()},
                       "breaches": slo_check["breaches"]}
        if slo_check["breaches"]:
            healthy = False
        if self.datastore is None:
            body["datastore"] = {"status": "absent"}
        else:
            try:
                stats = self.datastore.stats()
                body["datastore"] = {"status": "ok",
                                     "partitions": stats["partitions"],
                                     "rows": stats["rows"],
                                     # writer-lease holder view: which
                                     # pid owns mutations on this store
                                     # root right now (multi-process
                                     # serving shares the root)
                                     "lease": self.datastore.lease
                                     .snapshot()}
            except Exception as e:
                body["datastore"] = {"status": "error", "error": str(e)}
                healthy = False
        if self.compactor is not None:
            # delta-pressure backlog gauge (cached last sweep): a
            # growing backlog means compaction is falling behind the
            # tee — visible here long before queries slow down
            body["compaction"] = self.compactor.pending()
        if self.datastore is not None \
                and getattr(self.datastore, "freshness", None) is not None:
            # freshness-tier gauges: overlay occupancy vs its byte
            # budget (evictions here mean the window is effectively
            # shorter than configured), feed waiters/sheds, viewport
            # materialisation counts
            body["freshness"] = self.datastore.freshness.snapshot()
        if self.cities is not None:
            body["cities"] = self.cities.snapshot()
        body["status"] = "ok" if healthy else "degraded"
        return (200 if healthy else 503,
                json.dumps(body, separators=(",", ":")))

    def report_incremental(self, traces) -> list:
        """:meth:`report_many` with the carried-state fast path: traces
        the incremental matcher serves (O(K) device work per appended
        point) skip the whole-window dispatcher round trip; every slot
        it declines — no uuid, kill switch, pressure shed, open
        circuit, parity fallback, eviction — rides ONE batched
        :meth:`report_many` call instead. The per-slot reports are
        byte-identical either way (the incremental path's match dicts
        are pinned to the batch oracle), so callers cannot tell which
        path served them except by latency and the
        ``match.incremental.*`` counters."""
        import logging
        from ..core.tracebatch import as_trace_batch
        log = logging.getLogger("reporter_tpu.service")
        tb = as_trace_batch(traces)
        try:
            matches = self.matcher.match_incremental(tb)
        except Exception as e:   # defensive: match_incremental degrades
            log.error("incremental match failed (%s); the batch path "
                      "serves this flush", e)
            matches = [None] * len(tb)
        unserved = [i for i, mt in enumerate(matches) if mt is None]
        if len(unserved) == len(tb):
            return self.report_many(tb)
        out: list = [None] * len(tb)
        if unserved:
            for j, rep in zip(unserved, self.report_many(tb.gather(unserved))):
                out[j] = rep
        for i, mt in enumerate(matches):
            if mt is None:
                continue
            trace = tb[i]
            try:
                opts = trace["match_options"]
                out[i] = report(mt, trace, self.threshold_sec,
                                set(opts["report_levels"]),
                                set(opts["transition_levels"]))
            except Exception as e:
                log.error("report build failed for %s: %s",
                          trace.get("uuid"), e)
        return out

    def report_many(self, traces) -> list:
        """Match + report a whole list — or a columnar
        :class:`TraceBatch` — in ONE dispatcher round trip (one device
        batch up to MATCH_BATCH_MAX); returns parsed report dicts, None
        for a trace that failed — a one-batch failure costs only that
        batch's traces, and the cause is logged. The streaming worker's
        in-process flush path — no per-trace HTTP, no per-trace JSON, no
        point dicts."""
        import logging
        log = logging.getLogger("reporter_tpu.service")
        matches = self.dispatcher.submit_many(traces,
                                              return_exceptions=True)
        out = []
        for trace, match in zip(traces, matches):
            if isinstance(match, Exception):
                log.error("batched match failed for %s: %s",
                          trace.get("uuid"), match)
                out.append(None)
                continue
            try:
                opts = trace["match_options"]
                out.append(report(match, trace, self.threshold_sec,
                                  set(opts["report_levels"]),
                                  set(opts["transition_levels"])))
            except Exception as e:
                log.error("report build failed for %s: %s",
                          trace.get("uuid"), e)
                out.append(None)
        return out


def make_handler(service: ReporterService):
    class Handler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def _parse(self, post: bool) -> dict:
            split = urllib.parse.urlsplit(self.path)
            if split.path.split("/")[-1] not in ACTIONS:
                raise ValueError("Try a valid action: " + str(sorted(ACTIONS)))
            if post:
                length = int(self.headers.get("Content-Length", 0))
                return json.loads(self.rfile.read(length).decode("utf-8"))
            params = urllib.parse.parse_qs(split.query)
            if "json" in params:
                return json.loads(params["json"][0])
            raise ValueError("No json provided")

        def _respond(self, code: int, body,
                     content_type: str = "application/json;charset=utf-8",
                     headers=None):
            # str bodies encode here; bytes/memoryview bodies (the
            # native wire writer's buffer) go to the socket AS IS —
            # the zero-copy handoff the C writer exists for
            raw = body.encode("utf-8") if isinstance(body, str) else body
            # one request per connection, like the reference's HTTP/1.0
            # service — keep-alive would pin a bounded pool slot idle
            self.close_connection = True
            self.send_response(code)
            self.send_header("Access-Control-Allow-Origin", "*")
            if service.proc_tag is not None:
                self.send_header("X-Reporter-Proc", service.proc_tag)
            for key, value in (headers or {}).items():
                self.send_header(key, value)
            self.send_header("Content-type", content_type)
            self.send_header("Content-length", str(len(raw)))
            self.end_headers()
            self.wfile.write(raw)

        def _respond_shed(self, code: int, body, retry_after_s=None):
            """A load-shed response: every 429 carries the computed
            ``Retry-After`` — the contract utils/http.py clients
            already honour. Callers that hold the Overload pass the
            seconds directly (the front-door shed path is HOT under
            overload); only bodies built deeper in the stack (the
            dispatcher backstop, a routed city's gate) pay the parse."""
            retry = retry_after_s
            if retry is None:
                try:
                    retry = json.loads(body).get("retry_after_s")
                except Exception:
                    pass
            headers = {"Retry-After": str(int(retry))} \
                if retry is not None else None
            self._respond(code, body, headers=headers)

        def _parse_histogram(self, post: bool) -> dict:
            """Histogram params: JSON body / ``json=`` like /report, or
            bare GET query params (``segment_id=…&hours=7-9``)."""
            params = urllib.parse.parse_qs(
                urllib.parse.urlsplit(self.path).query)
            if post or "json" in params:
                return self._parse(post)
            out: dict = {}
            if "segment_id" in params:
                out["segment_id"] = int(params["segment_id"][0])
            # repeated segment params: ?segment=A&segment=B&... —
            # served through ONE query_many sweep
            if "segment" in params:
                out["segments"] = [int(s) for s in params["segment"]]
            # ?bbox=min_lon,min_lat,max_lon,max_lat&level=L
            if "bbox" in params:
                out["bbox"] = [float(v) for v
                               in params["bbox"][0].split(",")]
            if "level" in params:
                out["level"] = int(params["level"][0])
            if "max_segments" in params:
                out["max_segments"] = int(params["max_segments"][0])
            if "city" in params:
                out["city"] = params["city"][0]
            if "hours" in params:
                from ..datastore import parse_hours_spec
                out["hours"] = parse_hours_spec(params["hours"][0])
            if "t0" in params and "t1" in params:
                out["time_range"] = [int(params["t0"][0]),
                                     int(params["t1"][0])]
            if "percentiles" in params:
                out["percentiles"] = [
                    float(p) for p in params["percentiles"][0].split(",") if p]
            # ?window=5m|300s|inf — freshness-tier staleness bound
            if "window" in params:
                out["window"] = params["window"][0]
            # ?map_version=abc123def456 — pin the sweep to one map
            # epoch; ?merge=1 — explicit opt-in to sweep every epoch
            # (default pins to the store's active version)
            if "map_version" in params:
                out["map_version"] = params["map_version"][0]
            if "merge" in params:
                out["merge"] = params["merge"][0].lower() \
                    not in ("", "0", "off", "false")
            # ?viewport=1 — materialised tile summaries for bbox+level
            if "viewport" in params:
                out["viewport"] = params["viewport"][0].lower() \
                    not in ("", "0", "off", "false")
            return out

        def _parse_feed(self, post: bool) -> dict:
            """Feed params: JSON body / ``json=`` like /report, or bare
            GET query params (``bbox=…&level=L&cursor=N&timeout=S``)."""
            params = urllib.parse.parse_qs(
                urllib.parse.urlsplit(self.path).query)
            if post or "json" in params:
                return self._parse(post)
            out: dict = {}
            if "bbox" in params:
                out["bbox"] = [float(v) for v
                               in params["bbox"][0].split(",")]
            for key in ("level", "cursor", "max_events"):
                if key in params:
                    out[key] = int(params[key][0])
            if "timeout" in params:
                out["timeout"] = float(params["timeout"][0])
            if "city" in params:
                out["city"] = params["city"][0]
            return out

        def _do(self, post: bool):
            split = urllib.parse.urlsplit(self.path)
            action = split.path.split("/")[-1]
            if action == "stats":
                # the wire writer owns the rounding (snapshot() reports
                # raw floats so sub-µs stages don't collapse to 0.0)
                self._respond(200, json.dumps(metrics.snapshot_rounded()))
                return
            if action == "metrics":
                from ..obs import prom
                self._respond(200, prom.render(),
                              content_type=prom.CONTENT_TYPE)
                return
            if action == "profile":
                from ..obs import profiler
                prof = profiler.snapshot()
                # the load-management view rides /profile too: sheds
                # per reason, in-flight, per-dispatcher queue gauges
                # (prof["queue_depths"]) and the ladder state
                prof["pressure"] = admission.pressure_snapshot()
                if service.admission is not None:
                    prof["admission"] = service.admission.snapshot()
                if service.cities is not None:
                    # the residency table with each city's route-memo
                    # counters + warmed_pairs: the cold-start pair a
                    # pre-warm assertion reads (serve_smoke)
                    prof["cities"] = service.cities.snapshot()
                self._respond(200, json.dumps(prof,
                                              separators=(",", ":")))
                return
            if action == "health":
                code, body = service.health()
                if code != 200:
                    metrics.count(f"service.errors.{code}")
                self._respond(code, body)
                return
            if action == "histogram":
                try:
                    params = self._parse_histogram(post)
                except Exception as e:
                    self._respond(400, json.dumps({"error": str(e)}))
                    return
                metrics.count("service.requests.histogram")
                with metrics.timer("service.histogram"):
                    code, body = service.histogram(params)
                if code != 200:
                    metrics.count(f"service.errors.{code}")
                self._respond(code, body)
                return
            if action == "feed":
                try:
                    params = self._parse_feed(post)
                except Exception as e:
                    self._respond(400, json.dumps({"error": str(e)}))
                    return
                metrics.count("service.requests.feed")
                code, body = service.feed(params)
                if code != 200:
                    metrics.count(f"service.errors.{code}")
                if code == 429:
                    # _respond_shed lifts retry_after_s from the body
                    # into Retry-After: every shed subscriber gets the
                    # explicit retry signal (PR 14 contract)
                    self._respond_shed(code, body)
                else:
                    self._respond(code, body)
                return
            # the admission gate (ISSUE 15): shed BEFORE the body is
            # even parsed — a 429 must cost headers, not work. The
            # in-flight slot an admit holds is released when the
            # response is written, whatever its status.
            gate = service.admission
            if gate is not None:
                shed = gate.admit()
                if shed is not None:
                    metrics.count("service.errors.429")
                    self._respond_shed(
                        429, json.dumps(
                            {"error": "overloaded",
                             "reason": shed.reason,
                             "retry_after_s": shed.retry_after_s}),
                        retry_after_s=shed.retry_after_s)
                    return
            # ?trace=1 debug flag: arm tracing for this request and ship
            # the request's span tree (Chrome/Perfetto trace-event JSON)
            # alongside the report body. The pressure ladder's
            # shed_trace rung refuses the flag under sustained overload
            # (the report still serves — only the debug tree is shed).
            qs = urllib.parse.parse_qs(split.query)
            # same falsy spellings as REPORTER_TPU_TRACE env parsing
            want_trace = qs.get("trace", ["0"])[0].lower() \
                not in ("", "0", "off", "false")
            if want_trace and not admission.allow_request_trace():
                metrics.count("pressure.trace_suppressed")
                want_trace = False
            if want_trace:
                obs_trace.force_begin()
            try:
                # the root span: one per /report request, covering parse
                # -> dispatch -> match -> serialisation, so every stage
                # span below it shares the request's trace_id
                with obs_trace.span("service.request") as root:
                    try:
                        with obs_trace.span("service.parse"):
                            trace = self._parse(post)
                    except Exception as e:
                        self._respond(400, json.dumps({"error": str(e)}))
                        return
                    metrics.count("service.requests")
                    with metrics.timer("service.handle"):
                        code, body = service.handle(trace)
                if want_trace and code == 200:
                    if not isinstance(body, str):  # native wire bytes
                        body = bytes(body).decode("utf-8")
                    body = ('{"report":' + body + ',"trace":'
                            + json.dumps(obs_trace.export_trace(root),
                                         separators=(",", ":")) + "}")
            finally:
                if want_trace:
                    obs_trace.force_end()
                if gate is not None:
                    gate.release()
            if code != 200:
                metrics.count(f"service.errors.{code}")
            if code == 429:
                self._respond_shed(code, body)
            else:
                self._respond(code, body)

        def do_GET(self):
            self._do(False)

        def do_POST(self):
            self._do(True)

        def log_message(self, fmt, *args):  # quiet by default
            pass

    return Handler


class BoundedThreadingHTTPServer(ThreadingHTTPServer):
    """ThreadingHTTPServer with a cap on concurrent handler threads.

    The reference sizes its pool at THREAD_POOL_COUNT or
    THREAD_POOL_MULTIPLIER x cpus because each of its threads runs a
    CPU-heavy C++ matcher (reference: reporter_service.py:37-40). Both
    env knobs are honoured here, but the DEFAULT is a flat 64: in this
    architecture handler threads only parse JSON and then *wait* on the
    micro-batching dispatcher — they are IO-bound, and sizing them by
    cpu count serialises requests on small hosts (measured on one core:
    a pool of 1 turned every batch into a batch of ONE and added the
    full dispatcher wait to every request — 44 req/s where the matcher
    itself does thousands/s). Excess connections queue in the listen
    backlog until a slot frees."""

    daemon_threads = True
    # accepts queue here while all pool slots are busy
    request_queue_size = 128

    def __init__(self, addr, handler, pool_size: int | None = None):
        if pool_size is None:
            from ..utils.runtime import _env_int
            count = _env_int("THREAD_POOL_COUNT", 0)
            mult = _env_int("THREAD_POOL_MULTIPLIER", 0)
            pool_size = count or \
                (mult * multiprocessing.cpu_count() if mult else 64)
        self._slots = threading.BoundedSemaphore(max(1, pool_size))
        super().__init__(addr, handler)

    def process_request(self, request, client_address):
        self._slots.acquire()
        super().process_request(request, client_address)

    def process_request_thread(self, request, client_address):
        try:
            super().process_request_thread(request, client_address)
        finally:
            self._slots.release()


def make_server(service: ReporterService, host: str, port: int,
                pool_size: int | None = None,
                reuse_port: bool = False) -> BoundedThreadingHTTPServer:
    """The ONE server constructor every entry point goes through, so
    the THREAD_POOL_COUNT/_MULTIPLIER knobs apply uniformly (the old
    ``__main__`` path constructed the server directly and silently
    ignored them). ``reuse_port`` binds with SO_REUSEPORT — the
    pre-fork multi-process mode's shared-port primitive."""
    cls = ReusePortThreadingHTTPServer if reuse_port \
        else BoundedThreadingHTTPServer
    return cls((host, port), make_handler(service), pool_size)


class ReusePortThreadingHTTPServer(BoundedThreadingHTTPServer):
    """BoundedThreadingHTTPServer binding with ``SO_REUSEPORT``: N
    processes each bind the same (host, port) and the kernel spreads
    accepted connections across them — the pre-fork serving mode's
    listener (service/prefork.py). Manual setsockopt: socketserver only
    grew ``allow_reuse_port`` in Python 3.11."""

    def server_bind(self):
        import socket
        self.socket.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEPORT, 1)
        super().server_bind()


def serve(service: ReporterService, host: str, port: int,
          pool_size: int | None = None) -> BoundedThreadingHTTPServer:
    httpd = make_server(service, host, port, pool_size)
    thread = threading.Thread(target=httpd.serve_forever, daemon=True)
    thread.start()
    return httpd


def main(argv=None):
    argv = list(argv if argv is not None else sys.argv[1:])
    # --procs N: pre-fork multi-process serving (SO_REUSEPORT); the
    # REPORTER_TPU_SERVICE_PROCS env knob is the no-CLI spelling
    procs = None
    if "--procs" in argv:
        i = argv.index("--procs")
        try:
            procs = int(argv[i + 1])
        except (IndexError, ValueError):
            sys.stderr.write("--procs needs an integer\n")
            return 1
        del argv[i:i + 2]
    if procs is None:
        from ..utils.runtime import _env_int
        procs = _env_int("REPORTER_TPU_SERVICE_PROCS", 1)
    if len(argv) < 2:
        sys.stderr.write(
            "usage: python -m reporter_tpu.service.server <config.json> "
            "<host:port> [--procs N]\n")
        return 1
    try:
        with open(argv[0]) as f:
            conf = json.load(f)
        Configure(conf)
        host, port = argv[1].split("/")[-1].split(":")
        port = int(port)
    except Exception as e:
        sys.stderr.write(f"Problem with config file: {e}\n")
        return 1

    def make_service() -> ReporterService:
        """Everything heavyweight — backend init, graph load, native
        build, datastore mount — happens HERE, which in multi-process
        mode runs post-fork in each worker: children never inherit
        device handles, native worker pools or dispatcher threads."""
        # a "datastore" key in the config (or REPORTER_TPU_DATASTORE)
        # mounts a local histogram store under /histogram
        datastore = None
        ds_root = os.environ.get("REPORTER_TPU_DATASTORE") \
            or conf.get("datastore")
        if ds_root:
            from ..datastore import LocalDatastore
            datastore = LocalDatastore(ds_root)

        # pin the JAX platform before any decode can block on a chip
        # tunnel (REPORTER_TPU_PLATFORM=cpu|accel|auto)
        from ..utils.runtime import ensure_backend
        ensure_backend()

        # joins a multi-host JAX job when REPORTER_TPU_COORDINATOR etc.
        # are set; single-host no-op otherwise
        from ..parallel import init_multihost
        init_multihost()
        # a "cities" map in the config mounts the multi-tenant registry
        # (service/cities.py): city=-tagged requests route through the
        # byte-budgeted residency LRU with route-memo pre-warm
        cities = None
        if conf.get("cities"):
            from .cities import CityRegistry
            cities = CityRegistry(conf["cities"])
        service = ReporterService(SegmentMatcher(), datastore=datastore,
                                  cities=cities)
        # stamp the default stack's store with its graph epoch, the
        # same contract as a CityRegistry load (cities.py): the
        # /histogram default pin must track the graph THIS process
        # serves — without the stamp a restart forgets the active
        # epoch and the default query silently mixes map builds
        if datastore is not None \
                and service.matcher.net is not None:
            from ..graph.version import map_version as _mv
            try:
                datastore.set_map_version(_mv(service.matcher.net))
            except Exception as e:
                sys.stderr.write(f"map version stamp failed: {e}\n")
        return service

    if procs > 1:
        from .prefork import serve_prefork
        return serve_prefork(make_service, host, port, procs)

    service = make_service()
    httpd = make_server(service, host, port)
    try:
        httpd.serve_forever()
    except KeyboardInterrupt:
        httpd.server_close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
