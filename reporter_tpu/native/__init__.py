"""ctypes binding for the C++ host runtime.

Builds ``libreporter_host.so`` on demand with the repo's Makefile (g++ is
baked into the image; pybind11 is not, hence the flat C ABI + ctypes).
``available()`` gates callers: when the toolchain or build is missing the
framework silently stays on the numpy implementations in
:mod:`reporter_tpu.graph` — same contract, slower.

ctypes releases the GIL during calls, so multiple Python threads can
prepare traces through one NativeRuntime concurrently; the C++ route
cache is lock-striped per source node (host_runtime.cpp), so concurrent
rt_route_matrices calls on one shared handle are safe and scale across
threads (SegmentMatcher owns one handle and preps on a thread pool).
"""
from __future__ import annotations

import ctypes
import logging
import os
import subprocess
from typing import Optional

import numpy as np

from ..utils import locks as _locks

logger = logging.getLogger("reporter_tpu.native")

_DIR = os.path.dirname(os.path.abspath(__file__))
_LIB_PATH = os.path.join(_DIR, "libreporter_host.so")
# Must equal host_runtime.cpp's rt_abi_version(). The handshake in
# _get_lib() turns a half-landed ABI change (library and binding updated
# in different commits) into a loud numpy fallback instead of a segfault.
ABI_VERSION = 14
_lib = None
# long_hold_ok: the once-only init hold (subprocess make + ABI
# handshake, bounded by the 180 s build timeout) is the design — both
# the static pass (LD003 suppression below) and the runtime witness
# (RC002 exemption here) document the same exception
_build_lock = _locks.new_lock("native.build", long_hold_ok=True)
_build_failed = False


def _try_build() -> Optional[ctypes.CDLL]:
    """Build (if stale) and load the library. Caller holds _build_lock."""
    global _build_failed
    if _build_failed:
        return None
    # sanitizer/CI override: load a pre-built library (e.g. the asan/ubsan
    # targets of the Makefile) instead of the default build product; the
    # ABI handshake below still applies to it
    override = os.environ.get("REPORTER_TPU_NATIVE_LIB")
    src = os.path.join(_DIR, "src", "host_runtime.cpp")

    def build():
        subprocess.run(["make", "-C", _DIR], check=True,
                       capture_output=True, timeout=180)

    try:
        if override:
            return ctypes.CDLL(override)
        if not (os.path.exists(_LIB_PATH) and os.path.exists(src)
                and os.path.getmtime(_LIB_PATH) >= os.path.getmtime(src)):
            build()
        try:
            return ctypes.CDLL(_LIB_PATH)
        except OSError:
            # a stale or foreign-platform .so can look up to date by
            # mtime yet fail to load — rebuild once and retry
            build()
            return ctypes.CDLL(_LIB_PATH)
    except Exception as e:
        _build_failed = True
        logger.warning("native host runtime unavailable (%s); "
                       "falling back to numpy", e)
        return None


def _get_lib() -> Optional[ctypes.CDLL]:
    # lock-free fast path: a published _lib is immutable from then on.
    # The whole init — build, handshake, signature setup, publication —
    # runs under _build_lock: the old flow published _lib and the sticky
    # _build_failed flag OUTSIDE the lock while _try_build wrote the same
    # flag inside it, so two first-callers could race a half-checked
    # handle into the process (found by reporter-lint LD001).
    if _lib is not None:
        return _lib
    # LD003 false-positive by design: _build_lock IS the once-only init
    # serialiser — the subprocess make + ABI handshake must complete
    # under it exactly once (publishing outside it was the LD001 race
    # PR 2 fixed). Bounded (180 s build timeout), never on a hot path.
    with _build_lock:  # lint: ignore[LD003]
        return _init_locked()


def _init_locked() -> Optional[ctypes.CDLL]:
    """Build + handshake + signature setup; _build_lock held."""
    global _lib, _build_failed
    if _lib is None:
        lib = _try_build()
        if lib is None:
            return None
        # ABI handshake before any signature is trusted: a library built
        # from a different revision of host_runtime.cpp must not be called
        # through these argtypes (ctypes would happily pass the wrong
        # argument list and segfault — that is exactly what took down
        # round 2's snapshot).
        try:
            lib.rt_abi_version.restype = ctypes.c_int32
            lib.rt_abi_version.argtypes = []
            got = int(lib.rt_abi_version())
        except AttributeError:
            got = -1
        if got != ABI_VERSION:
            _build_failed = True
            logger.error(
                "native host runtime ABI mismatch (library=%d, binding=%d);"
                " falling back to numpy — rebuild with `make -C %s clean"
                " && make -C %s`", got, ABI_VERSION, _DIR, _DIR)
            return None
        c_i32p = np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS")
        c_f32p = np.ctypeslib.ndpointer(np.float32, flags="C_CONTIGUOUS")
        c_f64p = np.ctypeslib.ndpointer(np.float64, flags="C_CONTIGUOUS")
        lib.rt_graph_create.restype = ctypes.c_void_p
        lib.rt_graph_create.argtypes = [
            ctypes.c_int64, ctypes.c_int64, c_f64p, c_f64p, c_i32p, c_i32p,
            c_f32p, c_f32p, ctypes.c_double]
        lib.rt_graph_destroy.argtypes = [ctypes.c_void_p]
        lib.rt_cache_clear.argtypes = [ctypes.c_void_p]
        lib.rt_cache_size.argtypes = [ctypes.c_void_p]
        lib.rt_cache_size.restype = ctypes.c_int64
        c_i64arr = np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS")
        lib.rt_route_memo_stats.argtypes = [ctypes.c_void_p, c_i64arr]
        # profile export / pre-warm of the route-pair memo (ABI 13):
        # export dumps resident (edge_from, edge_to) pairs, warm
        # recomputes and inserts their node kernels bit-identically to
        # the serving path's miss (datastore/profile.py)
        lib.rt_route_memo_export.restype = ctypes.c_int64
        lib.rt_route_memo_export.argtypes = [
            ctypes.c_void_p, ctypes.c_int64, c_i32p, c_i32p]
        lib.rt_route_memo_warm.restype = ctypes.c_int64
        lib.rt_route_memo_warm.argtypes = [
            ctypes.c_void_p, ctypes.c_int64, c_i32p, c_i32p,
            ctypes.c_double]
        lib.rt_candidates.argtypes = [
            ctypes.c_void_p, ctypes.c_int64, c_f64p, c_f64p, ctypes.c_int32,
            ctypes.c_double, c_i32p, c_f32p, c_f32p, c_f32p, c_f32p]
        # dt is nullable (no time bound), so it binds as a raw pointer
        # rather than an ndpointer
        lib.rt_route_matrices.argtypes = [
            ctypes.c_void_p, ctypes.c_int64, ctypes.c_int32, c_i32p, c_f32p,
            c_f32p, ctypes.POINTER(ctypes.c_double), ctypes.c_double,
            ctypes.c_double, ctypes.c_double, ctypes.c_double,
            ctypes.c_double, ctypes.c_double, c_f32p]
        c_i64p = np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS")
        c_u16p = np.ctypeslib.ndpointer(np.uint16, flags="C_CONTIGUOUS")
        c_u8p = np.ctypeslib.ndpointer(np.uint8, flags="C_CONTIGUOUS")
        lib.rt_f32_to_f16.argtypes = [c_f32p, c_u16p, ctypes.c_int64]
        lib.rt_assemble_batch.restype = ctypes.c_int64
        lib.rt_assemble_batch.argtypes = [
            ctypes.c_void_p, ctypes.c_int64, ctypes.c_int32, ctypes.c_int32,
            c_i32p, c_i32p, c_f32p, c_f32p, c_i32p, c_i32p, c_i32p, c_f32p,
            c_i64p, c_f64p, c_u8p,
            c_i64p, c_f32p, c_u8p, c_i64p, c_f64p, ctypes.c_int64,
            ctypes.c_double, ctypes.c_double, ctypes.c_double,
            ctypes.c_double, ctypes.c_int64,
            c_i64p, c_i64p, c_u8p, c_f64p, c_f64p, c_i32p, c_i32p,
            c_i32p, c_i32p, c_i64p, c_i64p]
        lib.rt_prepare_batch.argtypes = [
            ctypes.c_void_p, ctypes.c_int64, c_i64p, c_f64p, c_f64p, c_f64p,
            ctypes.c_double, ctypes.c_double, ctypes.c_int32, ctypes.c_int32,
            ctypes.c_double, ctypes.c_double, ctypes.c_double,
            ctypes.c_double, ctypes.c_double, ctypes.c_double,
            ctypes.c_double, ctypes.c_double, ctypes.c_double,
            ctypes.c_double, ctypes.c_int32, ctypes.c_int32,
            c_i32p, c_f32p, c_f32p, c_f32p, c_f32p, c_i32p, c_i32p, c_i32p,
            c_f32p, c_u8p, c_f32p, c_i64p, c_f64p]
        # columnar /report wire writer (ABI 12): pure functions over
        # borrowed run columns, no handle — see write_report_json below.
        # The ten column base addresses travel as ONE packed int64
        # array (built and cached per chunk by _writer_args), and every
        # pointer binds as raw c_void_p: these are per-TRACE calls over
        # a chunk-shared RunColumns, and ndpointer's per-call
        # from_param validation of 10 arrays — then even ten plain
        # pointer conversions — cost more than the serialisation
        # itself (measured 2x the Python writer before the repack)
        lib.rt_json_double.restype = ctypes.c_int64
        lib.rt_json_double.argtypes = [ctypes.c_double, c_u8p]
        lib.rt_render_segments_json.restype = ctypes.c_int64
        lib.rt_render_segments_json.argtypes = [
            ctypes.c_void_p, ctypes.c_int64, ctypes.c_int64,
            ctypes.c_char_p, ctypes.c_int64, ctypes.c_void_p,
            ctypes.c_int64]
        lib.rt_report_json.restype = ctypes.c_int64
        lib.rt_report_json.argtypes = [
            ctypes.c_void_p, ctypes.c_int64, ctypes.c_int64,
            ctypes.c_double, ctypes.c_double, ctypes.c_int32,
            ctypes.c_int32, ctypes.c_void_p, ctypes.c_int64]
        lib.rt_report_json_batch.restype = ctypes.c_int64
        lib.rt_report_json_batch.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
            ctypes.c_int64, ctypes.c_double, ctypes.c_int32,
            ctypes.c_int32, ctypes.c_void_p, ctypes.c_int64,
            ctypes.c_void_p]
        i64ref = ctypes.POINTER(ctypes.c_int64)
        lib.rt_tile_counts.restype = ctypes.c_int32
        lib.rt_tile_counts.argtypes = [
            ctypes.c_char_p, ctypes.c_int64, i64ref, i64ref, i64ref]
        lib.rt_tile_parse.restype = ctypes.c_int32
        lib.rt_tile_parse.argtypes = [
            ctypes.c_char_p, ctypes.c_int64, c_i64p, c_f64p, c_f64p,
            c_i32p, c_i32p, c_f32p, c_f32p, c_i64p, c_f32p, c_u8p,
            c_i64p, c_f32p]
        _lib = lib
    return _lib


def parse_tile(raw: bytes):
    """Parse an RGT1 graph-tile blob with the C++ parser; returns the
    column dict (tilestore layout) or None when the library is missing or
    the blob is malformed (caller falls back to the numpy parser for the
    error message)."""
    lib = _get_lib()
    if lib is None:
        return None
    n_nodes = ctypes.c_int64()
    n_edges = ctypes.c_int64()
    n_segs = ctypes.c_int64()
    if lib.rt_tile_counts(raw, len(raw), ctypes.byref(n_nodes),
                          ctypes.byref(n_edges), ctypes.byref(n_segs)) != 0:
        return None
    N, E, S = n_nodes.value, n_edges.value, n_segs.value
    out = {
        "node_gid": np.empty(N, np.int64),
        "node_lat": np.empty(N, np.float64),
        "node_lon": np.empty(N, np.float64),
        "edge_start": np.empty(E, np.int32),
        "edge_end": np.empty(E, np.int32),
        "edge_length_m": np.empty(E, np.float32),
        "edge_speed_kph": np.empty(E, np.float32),
        "edge_segment_id": np.empty(E, np.int64),
        "edge_segment_offset_m": np.empty(E, np.float32),
        "edge_internal": np.empty(E, np.uint8),
        "seg_ids": np.empty(S, np.int64),
        "seg_lens": np.empty(S, np.float32),
    }
    rc = lib.rt_tile_parse(
        raw, len(raw), out["node_gid"], out["node_lat"], out["node_lon"],
        out["edge_start"], out["edge_end"], out["edge_length_m"],
        out["edge_speed_kph"], out["edge_segment_id"],
        out["edge_segment_offset_m"], out["edge_internal"],
        out["seg_ids"], out["seg_lens"])
    if rc != 0:
        return None
    out["edge_internal"] = out["edge_internal"].astype(bool)
    return out


def available() -> bool:
    return _get_lib() is not None


# ---- columnar /report wire writer (ABI 12) --------------------------------
# Free functions over a chunk's run-column arrays (matcher.RunColumns
# .arrays) — no graph handle, no shared state; ctypes releases the GIL,
# so concurrent request threads serialise responses truly in parallel.

_WRITER_COLS = ("seg_id", "internal", "start", "end", "length", "queue",
                "begin_idx", "end_idx", "way_off", "ways")
#: the wire ABI's expected dtypes, column-for-column with _WRITER_COLS
_WIRE_DTYPES = (np.int64, np.uint8, np.float64, np.float64, np.int32,
                np.int32, np.int32, np.int32, np.int64, np.int64)


def _writer_args(arrays: dict) -> tuple:
    """Per-chunk wire-call state, cached ON the arrays dict: every
    trace in a chunk serialises from the same chunk-wide RunColumns,
    so dtype/contiguity coercion AND pointer packing happen once per
    CHUNK here, not once per trace in ctypes marshalling (which made
    the C writer 2x slower than the Python one). Returns
    ``(col_addrs_ptr, way_off_list)``: the address of a packed int64
    array of the ten column base addresses (the C side's
    ``unpack_cols`` order) and the way-offset column as a plain list
    for the O(1) buffer sizing in the callers. The coerced arrays ride
    along in the cache entry so the pointers stay alive."""
    cached = arrays.get("_wire_ptrs")
    if cached is None:
        cols = tuple(np.ascontiguousarray(arrays[k], dtype=dt)
                     for k, dt in zip(_WRITER_COLS, _WIRE_DTYPES))
        addrs = np.array([c.ctypes.data for c in cols], dtype=np.int64)
        cached = (addrs.ctypes.data, cols[8].tolist(), cols, addrs)
        arrays["_wire_ptrs"] = cached
    return cached


def json_double(v: float) -> bytes:
    """repr(float) bytes from the native writer — the formatting-parity
    test surface (tests/test_report_writer.py pins it against repr)."""
    lib = _get_lib()
    if lib is None:
        raise RuntimeError("native host runtime unavailable")
    out = np.empty(32, np.uint8)
    n = int(lib.rt_json_double(float(v), out))
    return out[:n].tobytes()


def write_segments_json(arrays: dict, lo: int, hi: int,
                        mode_json: bytes) -> memoryview:
    """``{"segments":[...],"mode":...}`` bytes for run columns [lo, hi)
    — byte-identical to matcher.render_segments_json (pinned)."""
    lib = _get_lib()
    if lib is None:
        raise RuntimeError("native host runtime unavailable")
    col_addrs, way_off = _writer_args(arrays)[:2]
    # generous first-try buffer: fixed keys + digits per run and per
    # way id, grown on the (rare) -1 retry below
    cap = 320 * (hi - lo + 1) + 24 * (way_off[hi] - way_off[lo]) + 1024
    fn = lib.rt_render_segments_json
    while True:
        out = np.empty(cap, np.uint8)
        n = fn(col_addrs, lo, hi, mode_json, len(mode_json),
               out.ctypes.data, cap)
        if n >= 0:
            return out.data[:n]
        cap *= 4


def write_report_json_batch(arrays: dict, threshold_sec: float,
                            report_mask: int, transition_mask: int):
    """The whole CHUNK's /report bodies in ONE C call and one
    contiguous buffer. Needs the chunk layout the batched assembler
    attaches to its RunColumns (``_run_off``: per-trace run spans,
    ``_trace_end``: per-trace last point times); returns ``(buffer,
    offsets)`` where trace ``t``'s body is ``buffer[offsets[t]:
    offsets[t+1]]`` — the per-trace slicing the parity tests pin
    against the per-trace writer byte-for-byte."""
    lib = _get_lib()
    if lib is None:
        raise RuntimeError("native host runtime unavailable")
    run_off = arrays["_run_off"]
    trace_ends = arrays["_trace_end"]
    n = len(run_off) - 1
    col_addrs, way_off = _writer_args(arrays)[:2]
    offsets = np.empty(n + 1, np.int64)
    # size from the meaningful prefixes ONLY: the assembler over-
    # allocates the way_off column to the ways capacity, so entries
    # past run_off[-1] are uninitialised — way_off[-1] is garbage
    n_runs = int(run_off[-1])
    cap = 320 * (n_runs + n) + 24 * way_off[n_runs] + 448 * n + 1024
    fn = lib.rt_report_json_batch
    while True:
        out = np.empty(cap, np.uint8)
        total = fn(col_addrs, run_off.ctypes.data,
                   trace_ends.ctypes.data, n, threshold_sec,
                   report_mask, transition_mask, out.ctypes.data, cap,
                   offsets.ctypes.data)
        if total >= 0:
            return out, offsets.tolist()
        cap *= 4


def write_report_json(arrays: dict, lo: int, hi: int, trace_end: float,
                      threshold_sec: float, report_mask: int,
                      transition_mask: int) -> memoryview:
    """The whole /report response body for run columns [lo, hi) in ONE
    contiguous caller-owned buffer — byte-identical to
    service.report.report_json (pinned). The returned memoryview goes
    to the socket with no re-encode (service/server.py)."""
    lib = _get_lib()
    if lib is None:
        raise RuntimeError("native host runtime unavailable")
    col_addrs, way_off = _writer_args(arrays)[:2]
    cap = 320 * (hi - lo + 1) + 24 * (way_off[hi] - way_off[lo]) + 1024
    fn = lib.rt_report_json
    while True:
        out = np.empty(cap, np.uint8)
        n = fn(col_addrs, lo, hi, trace_end, threshold_sec,
               report_mask, transition_mask, out.ctypes.data, cap)
        if n >= 0:
            return out.data[:n]
        cap *= 4


class NativeRuntime:
    """C++-backed candidate lookup + route matrices for one RoadNetwork.

    Drop-in for (SpatialGrid.candidates, candidate_route_matrices) — same
    padding sentinels, same bounds semantics, same cache behavior.
    """

    def __init__(self, net, cell_m: float = 250.0):
        lib = _get_lib()
        if lib is None:
            raise RuntimeError("native host runtime unavailable")
        self._lib = lib
        self.net = net
        # fork guard: the handle's C++ WorkerPool threads (and any mid-
        # call state) do NOT survive os.fork() — a forked child calling
        # through an inherited handle would hang on a condvar no thread
        # will ever signal. _check_owner turns that hang into a loud
        # error the matcher's circuit breaker degrades around; pre-fork
        # serving (service/prefork.py) builds its runtimes post-fork.
        self._owner_pid = os.getpid()
        # rt_graph_create copies everything into C++ vectors, so the
        # contiguous staging arrays only need to live for this call
        nx, ny = net.node_xy()
        self._handle = lib.rt_graph_create(
            net.num_nodes, net.num_edges,
            np.ascontiguousarray(nx, dtype=np.float64),
            np.ascontiguousarray(ny, dtype=np.float64),
            np.ascontiguousarray(net.edge_start, dtype=np.int32),
            np.ascontiguousarray(net.edge_end, dtype=np.int32),
            np.ascontiguousarray(net.edge_length_m, dtype=np.float32),
            np.ascontiguousarray(net.edge_speed_kph, dtype=np.float32),
            float(cell_m))

    def __del__(self):
        try:
            if getattr(self, "_handle", None):
                # never destroy a parent's handle from a forked child:
                # the pool threads the destructor joins exist only in
                # the owning process (the child would hang; the memory
                # is the parent's to free)
                if os.getpid() == getattr(self, "_owner_pid", os.getpid()):
                    self._lib.rt_graph_destroy(self._handle)
                self._handle = None
        except Exception:
            pass

    def _check_owner(self) -> None:
        if os.getpid() != self._owner_pid:
            raise RuntimeError(
                "NativeRuntime used across fork (its C++ worker-pool "
                "threads did not survive); build a new SegmentMatcher "
                "in the child process")

    # -- SpatialGrid-compatible candidate lookup ---------------------------
    def candidates(self, lat, lon, k: int, search_radius_m: float = 50.0):
        from ..graph.spatial import CandidateSet

        self._check_owner()
        to_xy, _ = self.net.projection()
        px, py = to_xy(np.asarray(lat, dtype=np.float64),
                       np.asarray(lon, dtype=np.float64))
        px = np.ascontiguousarray(np.atleast_1d(px), dtype=np.float64)
        py = np.ascontiguousarray(np.atleast_1d(py), dtype=np.float64)
        T = len(px)
        edge = np.empty((T, k), dtype=np.int32)
        dist = np.empty((T, k), dtype=np.float32)
        off = np.empty((T, k), dtype=np.float32)
        qx = np.empty((T, k), dtype=np.float32)
        qy = np.empty((T, k), dtype=np.float32)
        self._lib.rt_candidates(self._handle, T, px, py, k,
                                float(search_radius_m),
                                edge, dist, off, qx, qy)
        return CandidateSet(edge, dist, off, qx, qy)

    # -- candidate_route_matrices-compatible -------------------------------
    def route_matrices(self, cands, gc_dist,
                       max_route_distance_factor: float = 5.0,
                       min_bound_m: float = 500.0,
                       backward_tolerance_m: float = 0.0,
                       dt=None,
                       max_route_time_factor: float = 0.0,
                       min_time_bound_s: float = 15.0,
                       turn_penalty_factor: float = 0.0) -> np.ndarray:
        """(T-1, K, K) route distances; Meili's admissibility bounds.

        ``dt`` is the (T-1,) probe time deltas in seconds; together with
        ``max_route_time_factor`` > 0 it prunes transitions whose travel
        time at edge speeds exceeds max(min_time_bound_s, factor*dt)
        (reference knob ``max-route-time-factor``, Dockerfile:14-17; the
        floor parallels min_bound_m on the distance side).
        ``turn_penalty_factor`` adds meters scaled by the heading change
        between candidate edges. Semantics mirror
        graph.route.candidate_route_matrices exactly.
        """
        self._check_owner()
        T, K = cands.edge_ids.shape
        out = np.empty((max(T - 1, 0), K, K), dtype=np.float32)
        if T < 2:
            return out
        edge = np.ascontiguousarray(cands.edge_ids, dtype=np.int32)
        off = np.ascontiguousarray(cands.offset_m, dtype=np.float32)
        gc = np.ascontiguousarray(gc_dist, dtype=np.float32)
        if dt is not None:
            dt_arr = np.ascontiguousarray(dt, dtype=np.float64)
            if dt_arr.shape != (T - 1,):
                raise ValueError(f"dt must be (T-1,)={T-1}, got {dt_arr.shape}")
            dt_ptr = dt_arr.ctypes.data_as(ctypes.POINTER(ctypes.c_double))
        else:
            dt_ptr = None
        self._lib.rt_route_matrices(
            self._handle, T, K, edge, off, gc, dt_ptr,
            float(max_route_distance_factor), float(min_bound_m),
            float(backward_tolerance_m), float(max_route_time_factor),
            float(min_time_bound_s), float(turn_penalty_factor), out)
        return out

    # -- whole-batch prep (the hot path) -----------------------------------
    def prepare_batch(self, pt_off, lat, lon, times, T: int, K: int,
                      search_radius: float, interpolation_distance: float,
                      breakage_distance: float,
                      max_route_distance_factor: float = 5.0,
                      min_bound_m: float = 500.0,
                      backward_tolerance_m: float = 0.0,
                      max_route_time_factor: float = 0.0,
                      min_time_bound_s: float = 15.0,
                      turn_penalty_factor: float = 0.0,
                      prune_margin_m: float = 0.0,
                      skip_routes: bool = False,
                      n_threads: int = 0, n_rows: int | None = None):
        """Prepare B traces in ONE native call, straight into padded
        (rows, T, ...) batch tensors — candidates, jitter filtering, case
        codes and route matrices per matcher/batchpad.py prepare_trace
        semantics, fanned out across C++ threads (no GIL, no per-trace
        Python). ``pt_off`` is (B+1,) int64 offsets into the flat
        lat/lon/times point arrays; ``n_rows`` >= B allocates extra
        all-SKIP filler rows (mesh/pow2 batch padding).

        ``prune_margin_m`` > 0 arms FLASH-style candidate pruning after
        kept selection: each row's distance-sorted candidates are cut
        where dist > dist[0] + margin, shrinking K before any route is
        requested (the best candidate always survives). ``skip_routes``
        skips ONLY the route_step stage — the device route kernel
        (graph/route_device.py) then owns route rows [0, n-1) of every
        live trace; all other tensors (including the ``dt`` deltas the
        device time cap needs) are computed as usual.

        Returns a dict of the filled tensors: edge_ids (rows,T,K) i32,
        dist_m/offset_m (rows,T,K) f32, route_m (rows,T,K,K) f32,
        gc_m (rows,T) f32, case (rows,T) i32, kept_idx (rows,T) i32
        (-1 pad), num_kept (rows,) i32, dwell (rows,) f32, dt (rows,T)
        f64 kept-point probe time deltas (-1 where the time bound must
        not arm: no next kept point, or the bound is off).

        route_m/gc_m carry T time rows — the final row is a dead step
        left at its pre-fill — so the dominant tensor ships to the
        device already shardable along the seq mesh axis, with no pad
        copy anywhere on the path (parallel/sharded.py; the decode
        kernels slice the dead step off inside jit).
        """
        self._check_owner()
        pt_off = np.ascontiguousarray(pt_off, dtype=np.int64)
        lat = np.ascontiguousarray(lat, dtype=np.float64)
        lon = np.ascontiguousarray(lon, dtype=np.float64)
        times = np.ascontiguousarray(times, dtype=np.float64)
        B = len(pt_off) - 1
        rows = n_rows if n_rows is not None else B
        if rows < B:
            raise ValueError(f"n_rows={rows} < B={B}")
        from ..graph.spatial import PAD_DIST, PAD_EDGE
        from ..graph.route import UNREACHABLE
        from ..matcher.hmm import SKIP
        # np.empty, not np.full: the C++ call writes every row of its B
        # traces (live prefixes AND pad sentinels, in the worker threads)
        # — pre-filling 8-16 MB per chunk from Python was measured host
        # time for bytes the callee immediately overwrites. Only filler
        # rows beyond B (mesh/pow2 batch padding) are filled here.
        out = {
            "edge_ids": np.empty((rows, T, K), np.int32),
            "dist_m": np.empty((rows, T, K), np.float32),
            "offset_m": np.empty((rows, T, K), np.float32),
            "route_m": np.empty((rows, T, K, K), np.float32),
            "gc_m": np.empty((rows, T), np.float32),
            "case": np.empty((rows, T), np.int32),
            "kept_idx": np.empty((rows, T), np.int32),
            "num_kept": np.zeros(rows, np.int32),
            "dwell": np.zeros(rows, np.float32),
            # kept-point probe time deltas (f64: the device route kernel
            # re-derives the exact time cap from them); -1 sentinel
            "dt": np.empty((rows, T), np.float64),
            # per RAW point: had any candidate edge (flat over pt_off) —
            # distinguishes jitter drops from off-network drops in the
            # assembler's span attribution
            "has_cands": np.zeros(max(int(pt_off[-1]), 1), np.uint8),
            # max finite distance written anywhere (dist/gc/route) — the
            # wire-dtype decision reads this scalar instead of re-scanning
            # the tensors
            "max_finite": np.zeros(1, np.float32),
            # phase split {candidates, select_pack, routes} ns — folded
            # into utils.metrics below so the bench artifact can show
            # where prep time went without rerunning under a profiler
            "phase_ns": np.zeros(3, np.int64),
        }
        if rows > B:
            out["edge_ids"][B:] = PAD_EDGE
            out["dist_m"][B:] = PAD_DIST
            out["offset_m"][B:] = 0.0
            out["route_m"][B:] = UNREACHABLE
            out["gc_m"][B:] = 0.0
            out["case"][B:] = SKIP
            out["kept_idx"][B:] = -1
            out["dt"][B:] = -1.0
        lat0, lon0 = self.net.projection_anchor()
        self._lib.rt_prepare_batch(
            self._handle, B, pt_off, lat, lon, times,
            float(lat0), float(lon0), T, K,
            float(search_radius), float(interpolation_distance),
            float(breakage_distance), float(max_route_distance_factor),
            float(min_bound_m), float(backward_tolerance_m),
            float(max_route_time_factor), float(min_time_bound_s),
            float(turn_penalty_factor), float(prune_margin_m),
            int(bool(skip_routes)), int(n_threads),
            out["edge_ids"], out["dist_m"], out["offset_m"],
            out["route_m"], out["gc_m"], out["case"], out["kept_idx"],
            out["num_kept"], out["dwell"], out["has_cands"],
            out["max_finite"], out["phase_ns"], out["dt"])
        from ..utils import metrics
        phase_ns = out["phase_ns"].tolist()
        for name, ns in zip(("candidates", "select", "routes"), phase_ns):
            if ns > 0:
                metrics.count(f"prep.phase.{name}_ns", ns)
        # the same split as child spans of the enclosing matcher.prep
        # span (no-op unless request tracing is armed): the ABI-11
        # phase export doubles as the trace's prep breakdown
        from ..obs import trace as obs_trace
        obs_trace.phase_spans(
            ("prep.candidates", "prep.select", "prep.routes"), phase_ns)
        return out

    def to_f16(self, arr: np.ndarray) -> np.ndarray:
        """f32 -> f16 wire cast via F16C (bit-identical to numpy astype;
        round-to-nearest-even, overflow to inf). The numpy cast was the
        largest single host cost after batching (round-4 profile)."""
        src = np.ascontiguousarray(arr, dtype=np.float32)
        out = np.empty(src.shape, dtype=np.float16)
        self._lib.rt_f32_to_f16(src.reshape(-1), out.view(np.uint16).reshape(-1),
                                src.size)
        return out

    def _assembly_columns(self):
        """Graph columns the native assembler needs, staged contiguous once
        per runtime (sorted segment-length table for the C++ binary
        search)."""
        cols = getattr(self, "_asm_cols", None)
        if cols is None:
            net = self.net
            seg_ids = np.array(sorted(net.segment_length_m), dtype=np.int64)
            seg_lens = np.array(
                [net.segment_length_m[int(s)] for s in seg_ids],
                dtype=np.float64)
            cols = {
                "edge_seg_id": np.ascontiguousarray(
                    net.edge_segment_id, dtype=np.int64),
                "edge_seg_off": np.ascontiguousarray(
                    net.edge_segment_offset_m, dtype=np.float32),
                "edge_internal": np.ascontiguousarray(
                    net.edge_internal, dtype=np.uint8),
                "seg_ids": seg_ids,
                "seg_lens": seg_lens,
            }
            self._asm_cols = cols
        return cols

    def assemble_batch(self, path, prep: dict, pt_off, times,
                       queue_threshold_kph: float,
                       interpolation_distance_m: float,
                       backward_tolerance_m: float = 25.0,
                       turn_penalty_factor: float = 0.0):
        """Walk B decoded paths into segment runs in ONE native call.

        ``path`` (B, T) decoded candidate indices (live rows only);
        ``prep`` the dict from :meth:`prepare_batch`. Returns the flat run
        columns: (run_off, seg_id, internal, start, end, length, queue,
        begin_idx, end_idx, way_off, ways) — Python formats these into the
        reference-schema segment dicts (matcher/assemble.py semantics,
        pinned by parity tests).
        """
        self._check_owner()
        cols = self._assembly_columns()
        path = np.ascontiguousarray(path, dtype=np.int32)
        B, T = path.shape
        K = prep["edge_ids"].shape[2]
        num_kept = prep["num_kept"][:B]
        cap = max(int(num_kept.sum()), 1)
        run_off = np.empty(B + 1, dtype=np.int64)
        out = {
            "seg_id": np.empty(cap, np.int64),
            "internal": np.empty(cap, np.uint8),
            "start": np.empty(cap, np.float64),
            "end": np.empty(cap, np.float64),
            "length": np.empty(cap, np.int32),
            "queue": np.empty(cap, np.int32),
            "begin_idx": np.empty(cap, np.int32),
            "end_idx": np.empty(cap, np.int32),
            "way_off": np.empty(cap + 1, np.int64),
            "ways": np.empty(cap, np.int64),
        }
        pt_off = np.ascontiguousarray(pt_off, dtype=np.int64)
        has_cands = prep.get("has_cands")
        if has_cands is None:  # hand-built preps: treat all drops as jitter
            has_cands = np.ones(max(int(pt_off[-1]), 1), np.uint8)
        n = self._lib.rt_assemble_batch(
            self._handle, B, T, K, path,
            prep["edge_ids"][:B], prep["offset_m"][:B],
            prep["route_m"][:B], prep["case"][:B], prep["kept_idx"][:B],
            np.ascontiguousarray(num_kept, dtype=np.int32),
            prep["dwell"][:B],
            pt_off,
            np.ascontiguousarray(times, dtype=np.float64),
            np.ascontiguousarray(has_cands, dtype=np.uint8),
            cols["edge_seg_id"], cols["edge_seg_off"],
            cols["edge_internal"], cols["seg_ids"], cols["seg_lens"],
            len(cols["seg_ids"]),
            float(queue_threshold_kph), float(interpolation_distance_m),
            float(backward_tolerance_m), float(turn_penalty_factor),
            cap, run_off, out["seg_id"], out["internal"], out["start"],
            out["end"], out["length"], out["queue"], out["begin_idx"],
            out["end_idx"], out["way_off"], out["ways"])
        if n < 0:
            raise RuntimeError("rt_assemble_batch capacity overflow "
                               f"(cap={cap}) — capacity invariant broken")
        out["run_off"] = run_off
        out["n_runs"] = int(n)
        return out

    def cache_clear(self):
        self._lib.rt_cache_clear(self._handle)

    def cache_size(self) -> int:
        return int(self._lib.rt_cache_size(self._handle))

    def route_memo_stats(self) -> dict:
        """Counters of the cross-call (edge_from, edge_to) route-pair
        memo (host_runtime.cpp PairMemo; capacity via
        REPORTER_TPU_ROUTE_MEMO, read at runtime construction)."""
        out = np.zeros(4, np.int64)
        self._lib.rt_route_memo_stats(self._handle, out)
        return {"hits": int(out[0]), "misses": int(out[1]),
                "size": int(out[2]), "evictions": int(out[3])}

    def route_memo_export(self, cap: int = 1 << 16):
        """Resident (edge_from, edge_to) pairs of the route memo as two
        int32 arrays — the per-city profile artifact's payload. The
        clock eviction keeps residents biased hot, so a post-replay
        export is the city's top route pairs."""
        self._check_owner()
        ea = np.empty(cap, dtype=np.int32)
        eb = np.empty(cap, dtype=np.int32)
        n = int(self._lib.rt_route_memo_export(self._handle, cap, ea, eb))
        return ea[:n].copy(), eb[:n].copy()

    def route_memo_warm(self, edge_from, edge_to,
                        bound_m: float = 500.0) -> int:
        """Insert the given pairs' node kernels into the route memo
        (computed with the same bounded Dijkstra the serving path runs
        on a miss — bit-identical admissibility on later hits). Pairs
        are sorted by from-edge first so consecutive pairs share one
        search. Returns pairs inserted; 0 when the memo is disabled."""
        self._check_owner()
        ea = np.ascontiguousarray(edge_from, dtype=np.int32)
        eb = np.ascontiguousarray(edge_to, dtype=np.int32)
        if ea.shape != eb.shape:
            raise ValueError("edge_from/edge_to must share a shape")
        if ea.size == 0:
            return 0
        order = np.lexsort((eb, ea))
        ea = np.ascontiguousarray(ea[order])
        eb = np.ascontiguousarray(eb[order])
        return int(self._lib.rt_route_memo_warm(
            self._handle, ea.shape[0], ea, eb, float(bound_m)))
