// Native host runtime for reporter_tpu: spatial candidate lookup and
// bounded-Dijkstra route-distance matrices.
//
// This is the framework's replacement for the native layer the reference
// gets from Valhalla (reference: SURVEY.md §2.3 — tile reading, candidate
// search and route distances all live in external C++ behind the `valhalla`
// python module). Here the same responsibilities sit behind a flat C ABI
// consumed via ctypes (no pybind11 in the image), emitting the fixed-width
// tensors the JAX matcher wants.
//
// Graph model: directed edges between projected-meter node coordinates,
// straight-segment geometry (matching reporter_tpu.graph.network). All
// arrays are borrowed from numpy; the handle owns only its derived
// structures (CSR, grid, caches).

#include <algorithm>
#include <array>
#include <cmath>
#include <cstdint>
#include <cstring>
#include <mutex>
#include <queue>
#include <unordered_map>
#include <vector>

namespace {

constexpr float kUnreachable = 1.0e9f;
constexpr int32_t kPadEdge = -1;
constexpr float kPadDist = 1.0e9f;

struct Graph {
  int64_t n_nodes = 0;
  int64_t n_edges = 0;
  std::vector<double> node_x, node_y;
  std::vector<int32_t> edge_start, edge_end;
  std::vector<float> edge_len;
  std::vector<float> edge_speed;       // kph; for route travel time
  std::vector<float> head_x, head_y;   // unit heading per edge; turn costs

  // CSR out-adjacency
  std::vector<int64_t> csr_off;
  std::vector<int32_t> csr_edge;

  // uniform spatial grid over projected meters
  double cell = 250.0;
  std::unordered_map<int64_t, std::vector<int32_t>> cells;

  // travel seconds along edge e for `meters` of it
  float edge_secs(int32_t e, float meters) const {
    const float v = std::max(edge_speed[e], 1.0f) * (1.0f / 3.6f);  // m/s
    return meters / v;
  }

  // per-source-node bounded dijkstra cache: node -> (bound, dists).
  // Lock-STRIPED: ctypes releases the GIL, so many Python threads
  // prepare traces through one handle concurrently; a whole-cache mutex
  // would serialise them (it did, round 1). A search from src touches
  // only src's entry, so striping by src keeps contention to threads
  // racing on the same source node — where waiting is the right call
  // anyway (the winner's cache entry saves the loser the search).
  static constexpr int kStripes = 64;
  // per-target (network distance m, travel time s) along the
  // shortest-DISTANCE path — time rides along for the
  // max_route_time_factor admissibility bound, it does not drive the
  // search (matching Meili: the matcher routes by distance, then bounds
  // the route's travel time against the probes' elapsed time)
  struct DistTime {
    float d, t;
  };
  struct CacheStripe {
    std::unordered_map<
        int32_t, std::pair<float, std::unordered_map<int32_t, DistTime>>>
        map;
    std::mutex mu;
  };
  std::array<CacheStripe, kStripes> route_stripes;

  CacheStripe& stripe_for(int32_t src) {
    return route_stripes[static_cast<uint32_t>(src) % kStripes];
  }

  static int64_t cell_key(int64_t i, int64_t j) {
    // shift on the unsigned representation: << on negative values is UB
    return static_cast<int64_t>((static_cast<uint64_t>(i) << 32) ^
                                (static_cast<uint64_t>(j) & 0xffffffffULL));
  }

  void build(double cell_m) {
    cell = cell_m;
    // unit headings (straight-segment geometry)
    head_x.resize(n_edges);
    head_y.resize(n_edges);
    for (int64_t e = 0; e < n_edges; ++e) {
      const double dx = node_x[edge_end[e]] - node_x[edge_start[e]];
      const double dy = node_y[edge_end[e]] - node_y[edge_start[e]];
      const double n = std::max(std::hypot(dx, dy), 1e-9);
      head_x[e] = static_cast<float>(dx / n);
      head_y[e] = static_cast<float>(dy / n);
    }
    // CSR
    csr_off.assign(n_nodes + 1, 0);
    for (int64_t e = 0; e < n_edges; ++e) csr_off[edge_start[e] + 1]++;
    for (int64_t v = 0; v < n_nodes; ++v) csr_off[v + 1] += csr_off[v];
    csr_edge.assign(n_edges, 0);
    std::vector<int64_t> fill(csr_off.begin(), csr_off.end() - 1);
    for (int64_t e = 0; e < n_edges; ++e)
      csr_edge[fill[edge_start[e]]++] = static_cast<int32_t>(e);
    // grid: every cell an edge's bbox touches
    for (int64_t e = 0; e < n_edges; ++e) {
      double ax = node_x[edge_start[e]], ay = node_y[edge_start[e]];
      double bx = node_x[edge_end[e]], by = node_y[edge_end[e]];
      int64_t i0 = static_cast<int64_t>(std::floor(std::min(ax, bx) / cell));
      int64_t i1 = static_cast<int64_t>(std::floor(std::max(ax, bx) / cell));
      int64_t j0 = static_cast<int64_t>(std::floor(std::min(ay, by) / cell));
      int64_t j1 = static_cast<int64_t>(std::floor(std::max(ay, by) / cell));
      for (int64_t i = i0; i <= i1; ++i)
        for (int64_t j = j0; j <= j1; ++j)
          cells[cell_key(i, j)].push_back(static_cast<int32_t>(e));
    }
  }

  // bounded single-source dijkstra over nodes; reuses/extends cache
  // entries. Caller must hold stripe_for(src).mu for the whole call AND
  // for as long as it reads the returned map (an extension to a larger
  // bound move-assigns the mapped value, invalidating concurrent reads).
  const std::unordered_map<int32_t, DistTime>& dists_from(int32_t src,
                                                          float bound) {
    auto& route_cache = stripe_for(src).map;
    auto it = route_cache.find(src);
    if (it != route_cache.end() && it->second.first >= bound)
      return it->second.second;
    std::unordered_map<int32_t, DistTime> dist;
    using QE = std::pair<float, int32_t>;
    std::priority_queue<QE, std::vector<QE>, std::greater<QE>> heap;
    dist[src] = {0.0f, 0.0f};
    heap.push({0.0f, src});
    while (!heap.empty()) {
      auto [d, u] = heap.top();
      heap.pop();
      auto du = dist.find(u);
      if (du != dist.end() && d > du->second.d) continue;
      if (d > bound) break;
      const float tu = dist[u].t;
      for (int64_t k = csr_off[u]; k < csr_off[u + 1]; ++k) {
        int32_t e = csr_edge[k];
        int32_t v = edge_end[e];
        float nd = d + edge_len[e];
        if (nd > bound) continue;
        auto dv = dist.find(v);
        if (dv == dist.end() || nd < dv->second.d) {
          dist[v] = {nd, tu + edge_secs(e, edge_len[e])};
          heap.push({nd, v});
        }
      }
    }
    auto& slot = route_cache[src];
    slot.first = bound;
    slot.second = std::move(dist);
    return route_cache[src].second;
  }
};

}  // namespace

extern "C" {

// ABI handshake: the ctypes loader (native/__init__.py) refuses to use a
// library whose version differs from its expectation, falling back to the
// numpy path loudly instead of calling through a stale signature. BUMP
// THIS on ANY change to the signatures below, in the same commit as the
// Python-side constant.
int32_t rt_abi_version(void) { return 3; }

void* rt_graph_create(int64_t n_nodes, int64_t n_edges,
                      const double* node_x, const double* node_y,
                      const int32_t* edge_start, const int32_t* edge_end,
                      const float* edge_len, const float* edge_speed_kph,
                      double cell_m) {
  auto* g = new Graph();
  g->n_nodes = n_nodes;
  g->n_edges = n_edges;
  g->node_x.assign(node_x, node_x + n_nodes);
  g->node_y.assign(node_y, node_y + n_nodes);
  g->edge_start.assign(edge_start, edge_start + n_edges);
  g->edge_end.assign(edge_end, edge_end + n_edges);
  g->edge_len.assign(edge_len, edge_len + n_edges);
  g->edge_speed.assign(edge_speed_kph, edge_speed_kph + n_edges);
  g->build(cell_m);
  return g;
}

void rt_graph_destroy(void* handle) { delete static_cast<Graph*>(handle); }

void rt_cache_clear(void* handle) {
  auto* g = static_cast<Graph*>(handle);
  for (auto& s : g->route_stripes) {
    std::lock_guard<std::mutex> lock(s.mu);
    s.map.clear();
  }
}

int64_t rt_cache_size(void* handle) {
  auto* g = static_cast<Graph*>(handle);
  int64_t n = 0;
  for (auto& s : g->route_stripes) {
    std::lock_guard<std::mutex> lock(s.mu);
    n += static_cast<int64_t>(s.map.size());
  }
  return n;
}

// K nearest edges within radius for each of T projected points.
// Outputs are (T, K) row-major, padded with kPadEdge / kPadDist / 0.
void rt_candidates(void* handle, int64_t n_points, const double* px,
                   const double* py, int32_t k, double radius,
                   int32_t* out_edge, float* out_dist, float* out_off,
                   float* out_px, float* out_py) {
  auto* g = static_cast<Graph*>(handle);
  const double cell = g->cell;
  const int64_t reach = static_cast<int64_t>(std::ceil(radius / cell));
  struct Cand {
    double d;  // double so tie-ordering matches the numpy float64 sort
    int32_t e;
    float off, qx, qy;
  };
  std::vector<Cand> cands;
  std::vector<char> seen(g->n_edges, 0);
  std::vector<int32_t> seen_list;
  for (int64_t t = 0; t < n_points; ++t) {
    cands.clear();
    for (int32_t s : seen_list) seen[s] = 0;
    seen_list.clear();
    const double x = px[t], y = py[t];
    const int64_t ci = static_cast<int64_t>(std::floor(x / cell));
    const int64_t cj = static_cast<int64_t>(std::floor(y / cell));
    for (int64_t i = ci - reach; i <= ci + reach; ++i) {
      for (int64_t j = cj - reach; j <= cj + reach; ++j) {
        auto it = g->cells.find(Graph::cell_key(i, j));
        if (it == g->cells.end()) continue;
        for (int32_t e : it->second) {
          if (seen[e]) continue;
          seen[e] = 1;
          seen_list.push_back(e);
          const double ax = g->node_x[g->edge_start[e]];
          const double ay = g->node_y[g->edge_start[e]];
          const double bx = g->node_x[g->edge_end[e]];
          const double by = g->node_y[g->edge_end[e]];
          const double dx = bx - ax, dy = by - ay;
          const double len2 = std::max(dx * dx + dy * dy, 1e-9);
          double f = ((x - ax) * dx + (y - ay) * dy) / len2;
          f = std::min(1.0, std::max(0.0, f));
          const double qx = ax + f * dx, qy = ay + f * dy;
          const double d = std::hypot(x - qx, y - qy);
          if (d <= radius) {
            cands.push_back({d, e, static_cast<float>(f * g->edge_len[e]),
                             static_cast<float>(qx), static_cast<float>(qy)});
          }
        }
      }
    }
    const int32_t n = static_cast<int32_t>(
        std::min<size_t>(cands.size(), static_cast<size_t>(k)));
    // stable top-K by distance, ties by edge id (matches numpy stable sort
    // over edge-id-ordered input)
    std::stable_sort(cands.begin(), cands.end(), [](const Cand& a,
                                                    const Cand& b) {
      return a.d < b.d || (a.d == b.d && a.e < b.e);
    });
    for (int32_t s = 0; s < k; ++s) {
      const int64_t o = t * k + s;
      if (s < n) {
        out_edge[o] = cands[s].e;
        out_dist[o] = static_cast<float>(cands[s].d);
        out_off[o] = cands[s].off;
        out_px[o] = cands[s].qx;
        out_py[o] = cands[s].qy;
      } else {
        out_edge[o] = kPadEdge;
        out_dist[o] = kPadDist;
        out_off[o] = 0.0f;
        out_px[o] = 0.0f;
        out_py[o] = 0.0f;
      }
    }
  }
}

// (T-1, K, K) route-distance tensor between consecutive candidate sets.
// edge_ids/offsets are (T, K) row-major; gc is (T-1); dt is (T-1) probe
// time deltas in seconds (may be null: no time bound).
//
// Admissibility mirrors Meili's two bounds (reference: Dockerfile:14-17):
// distance — route fits within max(min_bound, factor * gc);
// time     — the route's travel time at edge speeds fits within
//            time_factor * dt (skipped when either is <= 0).
// turn_penalty_factor adds meters for the heading change between the two
// candidate edges: factor * 0.5 * (1 - cos(theta)) — 0 when straight,
// `factor` for a full U-turn — the penalised route distance Meili feeds
// its transition cost.
void rt_route_matrices(void* handle, int64_t T, int32_t K,
                       const int32_t* edge_ids, const float* offsets,
                       const float* gc, const double* dt, double factor,
                       double min_bound, double backward_tol,
                       double time_factor, double min_time_bound,
                       double turn_penalty_factor, float* out) {
  auto* g = static_cast<Graph*>(handle);
  for (int64_t t = 0; t + 1 < T; ++t) {
    const float bound = static_cast<float>(
        std::max(min_bound, factor * static_cast<double>(gc[t])));
    // min_time_bound floors the cap the way min_bound floors the distance
    // bound: at 1 Hz sampling factor*dt is ~2 s, which GPS noise alone
    // overruns — without the floor the time bound prunes honest
    // transitions instead of absurd detours.
    const float time_cap =
        (dt != nullptr && time_factor > 0 && dt[t] > 0)
            ? static_cast<float>(std::max(min_time_bound, time_factor * dt[t]))
            : -1.0f;  // no bound
    for (int32_t i = 0; i < K; ++i) {
      const int32_t ea = edge_ids[t * K + i];
      float* row = out + (t * K + i) * K;
      if (ea == kPadEdge) {
        for (int32_t j = 0; j < K; ++j) row[j] = kUnreachable;
        continue;
      }
      const float oa = offsets[t * K + i];
      const float remaining = g->edge_len[ea] - oa;
      const int32_t src = g->edge_end[ea];
      // one bounded search from ea's end node covers every target j.
      // The stripe lock is held across compute AND the row fill below:
      // a concurrent bound-extension on the same src move-assigns the
      // cached map, so reads must stay inside the critical section.
      std::lock_guard<std::mutex> lock(g->stripe_for(src).mu);
      const auto& dist = g->dists_from(src, bound);
      for (int32_t j = 0; j < K; ++j) {
        const int32_t eb = edge_ids[(t + 1) * K + j];
        if (eb == kPadEdge) {
          row[j] = kUnreachable;
          continue;
        }
        const float ob = offsets[(t + 1) * K + j];
        if (eb == ea && ob >= oa) {
          row[j] = (time_cap >= 0 && g->edge_secs(ea, ob - oa) > time_cap)
                       ? kUnreachable
                       : ob - oa;
          continue;
        }
        // forgive small apparent backward movement on the same directed
        // edge (along-track GPS noise) — see graph/route.py route_distance
        if (eb == ea && oa - ob <= backward_tol) {
          row[j] = 0.0f;
          continue;
        }
        const float via = remaining + ob;
        if (via > bound) {
          row[j] = kUnreachable;
          continue;
        }
        auto it = dist.find(g->edge_start[eb]);
        // reachable only if the whole route fits inside the bound, matching
        // the python fallback's max_dist semantics (graph/route.py)
        if (it == dist.end() || via + it->second.d > bound) {
          row[j] = kUnreachable;
          continue;
        }
        if (time_cap >= 0) {
          const float secs = g->edge_secs(ea, remaining) +
                             g->edge_secs(eb, ob) + it->second.t;
          if (secs > time_cap) {
            row[j] = kUnreachable;
            continue;
          }
        }
        float d = via + it->second.d;
        if (turn_penalty_factor > 0) {
          const float cos_th = g->head_x[ea] * g->head_x[eb] +
                               g->head_y[ea] * g->head_y[eb];
          d += static_cast<float>(turn_penalty_factor) * 0.5f *
               (1.0f - cos_th);
        }
        row[j] = d;
      }
    }
  }
}

}  // extern "C"

// ---- RGT1 graph-tile parser (reporter_tpu/graph/tilestore.py layout) ----
// The native analog of the reference's C++ tile reader (SURVEY.md §2.3):
// header "RGT1" + u32 version + i64 n_nodes/n_edges/n_segments, then the
// column arrays little-endian in declaration order.

namespace {
constexpr int64_t kRgtHeaderSize = 4 + 4 + 3 * 8;

template <typename T>
bool rgt_copy(const uint8_t* buf, int64_t len, int64_t& off, T* out,
              int64_t count) {
  const int64_t bytes = count * static_cast<int64_t>(sizeof(T));
  if (off + bytes > len) return false;
  std::memcpy(out, buf + off, bytes);
  off += bytes;
  return true;
}
}  // namespace

extern "C" {

// Fills counts from the header. Returns 0 on success, nonzero on a
// malformed tile. Counts are validated against the blob length so a
// corrupt header can neither drive huge allocations in the caller nor
// overflow the per-column size math below.
int32_t rt_tile_counts(const uint8_t* buf, int64_t len, int64_t* n_nodes,
                       int64_t* n_edges, int64_t* n_segs) {
  if (len < kRgtHeaderSize || std::memcmp(buf, "RGT1", 4) != 0) return 1;
  uint32_t version;
  std::memcpy(&version, buf + 4, 4);
  if (version != 1) return 2;
  std::memcpy(n_nodes, buf + 8, 8);
  std::memcpy(n_edges, buf + 16, 8);
  std::memcpy(n_segs, buf + 24, 8);
  if (*n_nodes < 0 || *n_edges < 0 || *n_segs < 0) return 3;
  // each count also fits in the blob on its own, so the exact-size sum
  // below cannot overflow int64
  if (*n_nodes > len || *n_edges > len || *n_segs > len) return 3;
  const int64_t expect = kRgtHeaderSize + *n_nodes * (8 + 8 + 8) +
                         *n_edges * (4 + 4 + 4 + 4 + 8 + 4 + 1) +
                         *n_segs * (8 + 4);
  if (expect != len) return 3;
  return 0;
}

// Copies every column into caller-allocated arrays sized from
// rt_tile_counts. Returns 0 on success, nonzero on truncation/trailing
// bytes.
int32_t rt_tile_parse(const uint8_t* buf, int64_t len, int64_t* node_gid,
                      double* node_lat, double* node_lon,
                      int32_t* edge_start, int32_t* edge_end,
                      float* edge_length_m, float* edge_speed_kph,
                      int64_t* edge_segment_id, float* edge_segment_offset_m,
                      uint8_t* edge_internal, int64_t* seg_ids,
                      float* seg_lens) {
  int64_t N, E, S;
  const int32_t rc = rt_tile_counts(buf, len, &N, &E, &S);
  if (rc != 0) return rc;
  int64_t off = kRgtHeaderSize;
  if (!rgt_copy(buf, len, off, node_gid, N)) return 4;
  if (!rgt_copy(buf, len, off, node_lat, N)) return 4;
  if (!rgt_copy(buf, len, off, node_lon, N)) return 4;
  if (!rgt_copy(buf, len, off, edge_start, E)) return 4;
  if (!rgt_copy(buf, len, off, edge_end, E)) return 4;
  if (!rgt_copy(buf, len, off, edge_length_m, E)) return 4;
  if (!rgt_copy(buf, len, off, edge_speed_kph, E)) return 4;
  if (!rgt_copy(buf, len, off, edge_segment_id, E)) return 4;
  if (!rgt_copy(buf, len, off, edge_segment_offset_m, E)) return 4;
  if (!rgt_copy(buf, len, off, edge_internal, E)) return 4;
  if (!rgt_copy(buf, len, off, seg_ids, S)) return 4;
  if (!rgt_copy(buf, len, off, seg_lens, S)) return 4;
  return off == len ? 0 : 5;
}

}  // extern "C"
