// Native host runtime for reporter_tpu: spatial candidate lookup and
// bounded-Dijkstra route-distance matrices.
//
// This is the framework's replacement for the native layer the reference
// gets from Valhalla (reference: SURVEY.md §2.3 — tile reading, candidate
// search and route distances all live in external C++ behind the `valhalla`
// python module). Here the same responsibilities sit behind a flat C ABI
// consumed via ctypes (no pybind11 in the image), emitting the fixed-width
// tensors the JAX matcher wants.
//
// Graph model: directed edges between projected-meter node coordinates,
// straight-segment geometry (matching reporter_tpu.graph.network). All
// arrays are borrowed from numpy; the handle owns only its derived
// structures (CSR, grid, caches).

#include <algorithm>
#include <array>
#include <atomic>
#include <cfenv>
#include <chrono>
#include <cmath>
#include <condition_variable>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <functional>
#include <memory>
#include <mutex>
#include <queue>
#include <thread>
#include <unordered_map>
#include <vector>

#ifdef __F16C__
#include <immintrin.h>
#endif

namespace {

constexpr float kUnreachable = 1.0e9f;
constexpr int32_t kPadEdge = -1;
constexpr float kPadDist = 1.0e9f;

// Persistent worker pool, one per Graph handle. rt_prepare_batch used to
// spawn-and-join fresh std::threads every call; at service chunk sizes
// that is two thread births per worker per chunk (candidate sweep +
// trace phase) of pure overhead. Pool threads park on a condvar between
// calls. run() is serialised (run_mu): concurrent rt_prepare_batch
// callers on one handle queue up rather than corrupt the epoch state.
class WorkerPool {
 public:
  ~WorkerPool() {
    {
      std::lock_guard<std::mutex> lk(mu_);
      stop_ = true;
    }
    cv_work_.notify_all();
    for (auto& t : threads_) t.join();
  }

  // Run fn on `extra` pool threads plus the calling thread; fn must be an
  // atomic-cursor loop (every participant pulls items until exhausted),
  // so output never depends on which thread ran what. Blocks until all
  // participants return.
  void run(int extra, const std::function<void()>& fn) {
    std::lock_guard<std::mutex> outer(run_mu_);
    if (extra <= 0) {
      fn();
      return;
    }
    {
      std::unique_lock<std::mutex> lk(mu_);
      while (static_cast<int>(threads_.size()) < extra)
        threads_.emplace_back([this] { worker_main(); });
      job_ = &fn;
      wanted_ = extra;
      claimed_ = 0;
      pending_ = extra;
      ++epoch_;
    }
    cv_work_.notify_all();
    fn();  // the caller is a participant too
    std::unique_lock<std::mutex> lk(mu_);
    cv_done_.wait(lk, [this] { return pending_ == 0; });
  }

 private:
  void worker_main() {
    uint64_t seen = 0;
    std::unique_lock<std::mutex> lk(mu_);
    for (;;) {
      cv_work_.wait(lk, [&] { return stop_ || epoch_ != seen; });
      if (stop_) return;
      seen = epoch_;
      if (claimed_ >= wanted_) continue;  // over quota for this epoch
      ++claimed_;
      const std::function<void()>* fn = job_;
      lk.unlock();
      (*fn)();
      lk.lock();
      if (--pending_ == 0) cv_done_.notify_all();
    }
  }

  std::mutex run_mu_;  // serialises whole run() calls
  std::mutex mu_;
  std::condition_variable cv_work_, cv_done_;
  std::vector<std::thread> threads_;
  const std::function<void()>* job_ = nullptr;
  uint64_t epoch_ = 0;
  int wanted_ = 0, claimed_ = 0, pending_ = 0;
  bool stop_ = false;
};

// REPORTER_TPU_PREP_THREADS fallback when the caller passes n_threads<=0
// (the ctypes binding passes its own resolved count; other callers get
// the same env contract without a Python layer in between).
int env_prep_threads() {
  const char* v = std::getenv("REPORTER_TPU_PREP_THREADS");
  if (v != nullptr && v[0] != '\0') {
    const long n = std::strtol(v, nullptr, 10);
    if (n > 0) return static_cast<int>(n);
  }
  const unsigned hw = std::thread::hardware_concurrency();
  return hw ? static_cast<int>(hw) : 1;
}

// ---- route-pair memo ----------------------------------------------------
// The (edge_from, edge_to) node-route kernel — distance and travel time
// from edge_from's end node to edge_to's start node along the
// shortest-DISTANCE path — is bound-independent once found: a bounded
// Dijkstra settles exact shortest distances for every node it returns
// (relaxation never inserts past the bound), so a finite cached value is
// reusable at ANY query bound, and an unreachable verdict is reusable at
// any bound its search already covered. Offsets, turn penalties and the
// time-admissibility check are reapplied per query — mirroring the
// Python RouteCache pair level (graph/route.py), whose key deliberately
// carries no dt. Consecutive trace steps and co-located traces repeat
// the same candidate-edge pairs constantly; a memo hit skips the stripe
// lock and the whole Dijkstra-map probe.
struct PairVal {
  float d;      // node distance m; >= kUnreachable means "not reachable"
  float t;      // node travel seconds (valid when d finite)
  float bound;  // search bound the verdict is proven to (unreachable case)
};

// In-call memo, one per worker thread per native call: keyed by the
// FROM edge, holding that edge's known (to-edge -> kernel) pairs as two
// small parallel vectors. A route block row shares one ea across all K
// targets, so the row does ONE hash probe and then K linear scans of a
// vector that is 1-2 cache lines hot — measured faster than a flat
// pair-keyed table, whose per-(i,j) probes each took a cold cache miss
// on a table that grows with the whole chunk's pair set.
struct EaMemo {
  std::vector<int32_t> ebs;
  std::vector<PairVal> vals;

  int find(int32_t eb) const {
    const size_t n = ebs.size();
    for (size_t i = 0; i < n; ++i)
      if (ebs[i] == eb) return static_cast<int>(i);
    return -1;
  }

  void push(int32_t eb, const PairVal& v) {
    ebs.push_back(eb);
    vals.push_back(v);
  }
};

struct PairLocal {
  // node-based map: EaMemo references stay valid across other inserts
  std::unordered_map<int32_t, EaMemo> by_ea;
  int64_t n_pairs = 0;

  EaMemo& row(int32_t ea) { return by_ea[ea]; }

  void clear() {
    by_ea.clear();
    n_pairs = 0;
  }
};

// Bounded cross-call route-pair memo, lock-striped by the FROM edge —
// the C++ analog of the Python pair cache (REPORTER_TPU_ROUTE_MEMO
// entries across all stripes; 0 disables). Pairs are stored as per-ea
// rows of (eb, kernel) parallel vectors: a route block row shares one
// ea across its K targets, so route_step batches the whole row's
// lookups (and later its inserts) under ONE stripe lock and scans a
// vector that is a cache line or two hot. Recency is clock/second-
// chance per row (a `hot` flag set on lookup, no per-get list splicing
// — the splice writes were measured as cross-thread cache-line
// ping-pong costing more than the memo saved); eviction drops whole
// cold rows. Hit/miss/eviction counters feed rt_route_memo_stats.
class PairMemo {
 public:
  static constexpr int kStripes = 64;

  // same row representation (and linear scan) as the in-call EaMemo,
  // plus the clock bit
  struct Row : EaMemo {
    bool hot = false;
  };

  struct Stripe {
    std::mutex mu;
    std::unordered_map<int32_t, Row> rows;
    std::vector<int32_t> ring;  // clock ring of row keys
    size_t hand = 0;
    int64_t pairs = 0, hits = 0, misses = 0, evictions = 0;
  };

  explicit PairMemo(int64_t capacity) {
    cap_per_stripe_ = capacity > 0 ? (capacity + kStripes - 1) / kStripes : 0;
  }

  bool enabled() const { return cap_per_stripe_ > 0; }

  int64_t capacity() const { return cap_per_stripe_ * kStripes; }

  Stripe& stripe(int32_t ea) {
    return stripes_[static_cast<uint32_t>(ea) % kStripes];
  }

  // Insert/update `n` kernels of one ea row; caller holds stripe.mu.
  void put_row_locked(Stripe& s, int32_t ea, size_t n, const int32_t* ebs,
                      const PairVal* vals) {
    auto it = s.rows.find(ea);
    if (it == s.rows.end()) {
      it = s.rows.emplace(ea, Row{}).first;
      s.ring.push_back(ea);
    }
    Row& r = it->second;
    for (size_t i = 0; i < n; ++i) {
      const int pos = r.find(ebs[i]);
      if (pos >= 0) {
        r.vals[pos] = vals[i];  // deepened verdict replaces the stale one
      } else {
        r.ebs.push_back(ebs[i]);
        r.vals.push_back(vals[i]);
        ++s.pairs;
      }
    }
    r.hot = true;
    // clock eviction: sweep the ring, demoting hot rows, dropping cold
    // ones, until the stripe fits its share of the bound
    while (s.pairs > cap_per_stripe_ && !s.ring.empty()) {
      if (s.hand >= s.ring.size()) s.hand = 0;
      const int32_t key = s.ring[s.hand];
      auto vit = s.rows.find(key);
      if (vit == s.rows.end()) {  // stale ring slot
        s.ring[s.hand] = s.ring.back();
        s.ring.pop_back();
        continue;
      }
      if (vit->second.hot) {
        vit->second.hot = false;
        ++s.hand;
        continue;
      }
      s.pairs -= static_cast<int64_t>(vit->second.ebs.size());
      s.evictions += static_cast<int64_t>(vit->second.ebs.size());
      s.rows.erase(vit);
      s.ring[s.hand] = s.ring.back();
      s.ring.pop_back();
    }
  }

  void clear() {
    for (auto& s : stripes_) {
      std::lock_guard<std::mutex> lk(s.mu);
      s.rows.clear();
      s.ring.clear();
      s.hand = 0;
      s.pairs = 0;
    }
  }

  // Dump up to `cap` resident (edge_from, edge_to) pairs, stripe
  // order; returns the count written. The clock eviction keeps the
  // memo's residents biased hot, so a post-replay dump IS the city's
  // top route pairs — the per-city profile artifact the serving tier
  // pre-warms a freshly loaded city from (datastore/profile.py).
  int64_t export_pairs(int64_t cap, int32_t* ea_out, int32_t* eb_out) {
    int64_t n = 0;
    for (auto& s : stripes_) {
      std::lock_guard<std::mutex> lk(s.mu);
      for (auto& kv : s.rows) {
        for (size_t i = 0; i < kv.second.ebs.size(); ++i) {
          if (n >= cap) return n;
          ea_out[n] = kv.first;
          eb_out[n] = kv.second.ebs[i];
          ++n;
        }
      }
    }
    return n;
  }

  // out[4] = {hits, misses, size, evictions}
  void stats(int64_t out[4]) {
    out[0] = out[1] = out[2] = out[3] = 0;
    for (auto& s : stripes_) {
      std::lock_guard<std::mutex> lk(s.mu);
      out[0] += s.hits;
      out[1] += s.misses;
      out[2] += s.pairs;
      out[3] += s.evictions;
    }
  }

 private:
  std::array<Stripe, kStripes> stripes_;
  int64_t cap_per_stripe_ = 0;
};

// per-worker route scratch: the local pair memo plus per-row work lists
// (reused so no per-row allocation). rt_prepare_batch keeps one of
// these per worker SLOT on the graph handle, persistent across calls —
// the pipeline preps in 128-trace chunks, and rebuilding a ~30k-pair
// local memo (plus its allocations and the re-consults of the shared
// store) four times per 512 traces measured as the whole memo win given
// back. The slot's memo is cleared when it outgrows the configured
// bound, or every call when the shared memo is disabled (env 0 must
// mean no cross-call memoisation at all).
struct RouteScratch {
  PairLocal local;
  std::vector<int32_t> miss;      // js awaiting the shared memo / search
  std::vector<int32_t> hit_js;    // shared-memo hits, emitted post-lock
  std::vector<PairVal> hit_vals;
  std::vector<int32_t> put_ebs;   // freshly computed kernels to publish
  std::vector<PairVal> put_vals;
};

struct Graph {
  int64_t n_nodes = 0;
  int64_t n_edges = 0;
  std::vector<double> node_x, node_y;
  std::vector<int32_t> edge_start, edge_end;
  std::vector<float> edge_len;
  std::vector<float> edge_speed;       // kph; for route travel time
  std::vector<float> head_x, head_y;   // unit heading per edge; turn costs
  // SoA segment geometry for the candidate projection hot loop: one
  // contiguous stream per operand instead of two node-table indirections
  // per endpoint per edge per probe point. e_len2 keeps the DIVIDE
  // (f = dot / len2) — a precomputed reciprocal would drift a ulp from
  // the numpy path (graph/spatial.py) and flip distance ties.
  std::vector<double> e_ax, e_ay, e_dx, e_dy, e_len2;

  // CSR out-adjacency
  std::vector<int64_t> csr_off;
  std::vector<int32_t> csr_edge;

  // uniform spatial grid over projected meters
  double cell = 250.0;
  std::unordered_map<int64_t, std::vector<int32_t>> cells;

  // travel seconds along edge e for `meters` of it
  float edge_secs(int32_t e, float meters) const {
    const float v = std::max(edge_speed[e], 1.0f) * (1.0f / 3.6f);  // m/s
    return meters / v;
  }

  // per-source-node bounded dijkstra cache: node -> (bound, dists).
  // Lock-STRIPED: ctypes releases the GIL, so many Python threads
  // prepare traces through one handle concurrently; a whole-cache mutex
  // would serialise them (it did, round 1). A search from src touches
  // only src's entry, so striping by src keeps contention to threads
  // racing on the same source node — where waiting is the right call
  // anyway (the winner's cache entry saves the loser the search).
  static constexpr int kStripes = 64;
  // per-target (network distance m, travel time s) along the
  // shortest-DISTANCE path — time rides along for the
  // max_route_time_factor admissibility bound, it does not drive the
  // search (matching Meili: the matcher routes by distance, then bounds
  // the route's travel time against the probes' elapsed time)
  struct DistTime {
    float d, t;
  };
  // open-addressing node->DistTime map (linear probing, pow2 capacity,
  // key -1 = empty; node ids are >= 0). The K*K admissibility lookups per
  // step — millions per batch — were bound on std::unordered_map's
  // bucket-chain finds; a flat probe sequence is one cache line most of
  // the time.
  struct FlatMap {
    std::vector<int32_t> keys;
    std::vector<DistTime> vals;
    size_t mask = 0, count = 0;

    explicit FlatMap(size_t cap_pow2 = 16) { init(cap_pow2); }

    void init(size_t cap_pow2) {
      keys.assign(cap_pow2, -1);
      vals.resize(cap_pow2);
      mask = cap_pow2 - 1;
      count = 0;
    }

    static size_t slot_hash(int32_t k) {
      return static_cast<size_t>(static_cast<uint32_t>(k) * 2654435761u);
    }

    const DistTime* find(int32_t k) const {
      size_t i = slot_hash(k) & mask;
      for (;;) {
        if (keys[i] == k) return &vals[i];
        if (keys[i] == -1) return nullptr;
        i = (i + 1) & mask;
      }
    }

    DistTime& slot_for(int32_t k) {
      size_t i = slot_hash(k) & mask;
      while (keys[i] != -1 && keys[i] != k) i = (i + 1) & mask;
      if (keys[i] == -1) {
        keys[i] = k;
        ++count;
      }
      return vals[i];
    }

    DistTime& insert(int32_t k) {
      if ((count + 1) * 10 >= (mask + 1) * 7) {  // load factor 0.7
        FlatMap bigger((mask + 1) * 2);
        for (size_t i = 0; i <= mask; ++i)
          if (keys[i] != -1) bigger.slot_for(keys[i]) = vals[i];
        *this = std::move(bigger);
      }
      return slot_for(k);
    }
  };
  struct CacheStripe {
    std::unordered_map<int32_t, std::pair<float, FlatMap>> map;
    std::mutex mu;
  };
  std::array<CacheStripe, kStripes> route_stripes;

  CacheStripe& stripe_for(int32_t src) {
    return route_stripes[static_cast<uint32_t>(src) % kStripes];
  }

  // cross-call (edge_from, edge_to) route-pair memo + the persistent
  // prep worker pool (both per handle; see the class docs above)
  PairMemo pair_memo{[] {
    const char* v = std::getenv("REPORTER_TPU_ROUTE_MEMO");
    if (v != nullptr && v[0] != '\0') {
      const long long n = std::strtoll(v, nullptr, 10);
      return static_cast<int64_t>(n < 0 ? 0 : n);
    }
    return static_cast<int64_t>(1) << 18;  // ~260k pairs
  }()};
  WorkerPool pool;

  // rt_prepare_batch state, serialised by prep_mu (the matcher preps
  // from one thread; concurrent direct callers queue): per-worker-slot
  // route scratches (see RouteScratch) and the whole-batch candidate
  // staging buffers, both reused across calls so a 128-trace pipeline
  // chunk doesn't pay fresh multi-MB allocations per call.
  std::mutex prep_mu;
  std::vector<std::unique_ptr<RouteScratch>> prep_slots;
  std::vector<double> sc_px, sc_py;
  std::vector<int32_t> sc_edge;
  std::vector<float> sc_dist, sc_off;

  static int64_t cell_key(int64_t i, int64_t j) {
    // shift on the unsigned representation: << on negative values is UB
    return static_cast<int64_t>((static_cast<uint64_t>(i) << 32) ^
                                (static_cast<uint64_t>(j) & 0xffffffffULL));
  }

  void build(double cell_m) {
    cell = cell_m;
    // unit headings (straight-segment geometry) + SoA projection columns
    head_x.resize(n_edges);
    head_y.resize(n_edges);
    e_ax.resize(n_edges);
    e_ay.resize(n_edges);
    e_dx.resize(n_edges);
    e_dy.resize(n_edges);
    e_len2.resize(n_edges);
    for (int64_t e = 0; e < n_edges; ++e) {
      const double dx = node_x[edge_end[e]] - node_x[edge_start[e]];
      const double dy = node_y[edge_end[e]] - node_y[edge_start[e]];
      const double n = std::max(std::hypot(dx, dy), 1e-9);
      head_x[e] = static_cast<float>(dx / n);
      head_y[e] = static_cast<float>(dy / n);
      e_ax[e] = node_x[edge_start[e]];
      e_ay[e] = node_y[edge_start[e]];
      e_dx[e] = dx;
      e_dy[e] = dy;
      e_len2[e] = std::max(dx * dx + dy * dy, 1e-9);
    }
    // CSR
    csr_off.assign(n_nodes + 1, 0);
    for (int64_t e = 0; e < n_edges; ++e) csr_off[edge_start[e] + 1]++;
    for (int64_t v = 0; v < n_nodes; ++v) csr_off[v + 1] += csr_off[v];
    csr_edge.assign(n_edges, 0);
    std::vector<int64_t> fill(csr_off.begin(), csr_off.end() - 1);
    for (int64_t e = 0; e < n_edges; ++e)
      csr_edge[fill[edge_start[e]]++] = static_cast<int32_t>(e);
    // grid: every cell an edge's bbox touches
    for (int64_t e = 0; e < n_edges; ++e) {
      double ax = node_x[edge_start[e]], ay = node_y[edge_start[e]];
      double bx = node_x[edge_end[e]], by = node_y[edge_end[e]];
      int64_t i0 = static_cast<int64_t>(std::floor(std::min(ax, bx) / cell));
      int64_t i1 = static_cast<int64_t>(std::floor(std::max(ax, bx) / cell));
      int64_t j0 = static_cast<int64_t>(std::floor(std::min(ay, by) / cell));
      int64_t j1 = static_cast<int64_t>(std::floor(std::max(ay, by) / cell));
      for (int64_t i = i0; i <= i1; ++i)
        for (int64_t j = j0; j <= j1; ++j)
          cells[cell_key(i, j)].push_back(static_cast<int32_t>(e));
    }
  }

  // bounded single-source dijkstra over nodes; reuses/extends cache
  // entries. Caller must hold stripe_for(src).mu for the whole call AND
  // for as long as it reads the returned map (an extension to a larger
  // bound move-assigns the mapped value, invalidating concurrent reads).
  // ``covered`` (optional) reports the bound the returned map actually
  // covers — a cached entry may have been searched at a larger bound
  // than requested, which makes its absence-verdicts proven further out
  // (the pair memo records that so future queries reuse them).
  const FlatMap& dists_from(int32_t src, float bound,
                            float* covered = nullptr) {
    auto& route_cache = stripe_for(src).map;
    auto it = route_cache.find(src);
    if (it != route_cache.end() && it->second.first >= bound) {
      if (covered) *covered = it->second.first;
      return it->second.second;
    }
    if (covered) *covered = bound;
    // pre-size from the entry being extended (if any): a bound extension
    // revisits at least as many nodes as the cached search found
    size_t cap = 16;
    if (it != route_cache.end())
      while (cap * 7 <= it->second.second.count * 10) cap *= 2;
    FlatMap dist(cap);
    using QE = std::pair<float, int32_t>;
    std::priority_queue<QE, std::vector<QE>, std::greater<QE>> heap;
    dist.insert(src) = {0.0f, 0.0f};
    heap.push({0.0f, src});
    while (!heap.empty()) {
      auto [d, u] = heap.top();
      heap.pop();
      const DistTime* du = dist.find(u);
      if (du != nullptr && d > du->d) continue;
      if (d > bound) break;
      const float tu = du != nullptr ? du->t : 0.0f;
      for (int64_t k = csr_off[u]; k < csr_off[u + 1]; ++k) {
        int32_t e = csr_edge[k];
        int32_t v = edge_end[e];
        float nd = d + edge_len[e];
        if (nd > bound) continue;
        const DistTime* dv = dist.find(v);
        if (dv == nullptr || nd < dv->d) {
          dist.insert(v) = {nd, tu + edge_secs(e, edge_len[e])};
          heap.push({nd, v});
        }
      }
    }
    auto& slot = route_cache[src];
    slot.first = bound;
    slot.second = std::move(dist);
    return route_cache[src].second;
  }
};

// ---- shared per-point / per-step helpers --------------------------------
// The single-call APIs (rt_candidates, rt_route_matrices) and the batched
// rt_prepare_batch funnel through these so semantics cannot drift.

struct Cand {
  double d;  // double so tie-ordering matches the numpy float64 sort
  int32_t e;
  float off, qx, qy;
};

// per-thread scratch for candidate search (seen is n_edges bytes; reused
// across points so the clear is O(|touched|), not O(E)). The deduped
// neighborhood is cached until the centre cell (or reach) changes AND
// gathered into compact SoA columns, so the per-point distance loop runs
// contiguous and branch-light (auto-vectorisable) instead of chasing
// per-edge indices through the graph tables. Points arrive sorted into
// grid-cell order (candidates_batch below), so the neighborhood rebuild
// amortises over every point of a cell, not just consecutive ones.
struct CandScratch {
  std::vector<Cand> cands;
  std::vector<char> seen;
  std::vector<int32_t> nbr_edges;  // deduped; doubles as the seen-clear list
  // gathered neighborhood columns (one entry per nbr edge)
  std::vector<double> nbr_ax, nbr_ay, nbr_dx, nbr_dy, nbr_len2;
  std::vector<float> nbr_len;
  std::vector<double> sc_f, sc_d2;  // per-point projection scratch
  int64_t nbr_ci = INT64_MIN, nbr_cj = INT64_MIN, nbr_reach = -1;
  explicit CandScratch(int64_t n_edges) : seen(n_edges, 0) {}
};

// K nearest edges within radius of projected point (x, y); writes one
// (K,) row of each output, padded with kPadEdge / kPadDist / 0.
void candidates_for_point(const Graph* g, double x, double y, int32_t k,
                          double radius, CandScratch& s, int32_t* out_edge,
                          float* out_dist, float* out_off, float* out_px,
                          float* out_py) {
  const double cell = g->cell;
  const int64_t reach = static_cast<int64_t>(std::ceil(radius / cell));
  s.cands.clear();
  const int64_t ci = static_cast<int64_t>(std::floor(x / cell));
  const int64_t cj = static_cast<int64_t>(std::floor(y / cell));
  if (ci != s.nbr_ci || cj != s.nbr_cj || reach != s.nbr_reach) {
    // rebuild the deduped neighborhood edge list for this centre cell
    s.nbr_ci = ci;
    s.nbr_cj = cj;
    s.nbr_reach = reach;
    for (int32_t e : s.nbr_edges) s.seen[e] = 0;
    s.nbr_edges.clear();
    for (int64_t i = ci - reach; i <= ci + reach; ++i) {
      for (int64_t j = cj - reach; j <= cj + reach; ++j) {
        auto it = g->cells.find(Graph::cell_key(i, j));
        if (it == g->cells.end()) continue;
        for (int32_t e : it->second) {
          if (s.seen[e]) continue;
          s.seen[e] = 1;
          s.nbr_edges.push_back(e);
        }
      }
    }
    // gather the neighborhood's SoA columns once; every point in this
    // cell then runs a contiguous distance loop over them
    const size_t m = s.nbr_edges.size();
    s.nbr_ax.resize(m);
    s.nbr_ay.resize(m);
    s.nbr_dx.resize(m);
    s.nbr_dy.resize(m);
    s.nbr_len2.resize(m);
    s.nbr_len.resize(m);
    for (size_t i = 0; i < m; ++i) {
      const int32_t e = s.nbr_edges[i];
      s.nbr_ax[i] = g->e_ax[e];
      s.nbr_ay[i] = g->e_ay[e];
      s.nbr_dx[i] = g->e_dx[e];
      s.nbr_dy[i] = g->e_dy[e];
      s.nbr_len2[i] = g->e_len2[e];
      s.nbr_len[i] = g->edge_len[e];
    }
  }
  const size_t m = s.nbr_edges.size();
  s.sc_f.resize(m);
  s.sc_d2.resize(m);
  // pass 1: branch-free projection + squared distance over contiguous
  // columns (the compiler vectorises this; same double math as the
  // numpy path, so tie-order parity holds)
  for (size_t i = 0; i < m; ++i) {
    double f = ((x - s.nbr_ax[i]) * s.nbr_dx[i] +
                (y - s.nbr_ay[i]) * s.nbr_dy[i]) / s.nbr_len2[i];
    f = std::min(1.0, std::max(0.0, f));
    const double ex = x - (s.nbr_ax[i] + f * s.nbr_dx[i]);
    const double ey = y - (s.nbr_ay[i] + f * s.nbr_dy[i]);
    s.sc_f[i] = f;
    s.sc_d2[i] = ex * ex + ey * ey;
  }
  // pass 2: the exact but slow hypot — which must match numpy's np.hypot
  // for tie-order parity (graph/spatial.py:125) — only for edges the
  // squared-distance prefilter (with ulp slack) kept
  const double lim = radius * radius * 1.0000001;
  for (size_t i = 0; i < m; ++i) {
    if (s.sc_d2[i] > lim) continue;
    const double f = s.sc_f[i];
    const double qx = s.nbr_ax[i] + f * s.nbr_dx[i];
    const double qy = s.nbr_ay[i] + f * s.nbr_dy[i];
    const double d = std::hypot(x - qx, y - qy);
    if (d <= radius) {
      s.cands.push_back({d, s.nbr_edges[i],
                         static_cast<float>(f * s.nbr_len[i]),
                         static_cast<float>(qx), static_cast<float>(qy)});
    }
  }
  const int32_t n = static_cast<int32_t>(
      std::min<size_t>(s.cands.size(), static_cast<size_t>(k)));
  // top-K by distance, ties by edge id (matches numpy stable sort over
  // edge-id-ordered input; plain sort is safe — (d, e) pairs are unique
  // since each edge appears once — and does not allocate)
  std::sort(s.cands.begin(), s.cands.end(),
            [](const Cand& a, const Cand& b) {
              return a.d < b.d || (a.d == b.d && a.e < b.e);
            });
  for (int32_t q = 0; q < k; ++q) {
    if (q < n) {
      out_edge[q] = s.cands[q].e;
      out_dist[q] = static_cast<float>(s.cands[q].d);
      out_off[q] = s.cands[q].off;
      if (out_px) out_px[q] = s.cands[q].qx;
      if (out_py) out_py[q] = s.cands[q].qy;
    } else {
      out_edge[q] = kPadEdge;
      out_dist[q] = kPadDist;
      out_off[q] = 0.0f;
      if (out_px) out_px[q] = 0.0f;
      if (out_py) out_py[q] = 0.0f;
    }
  }
}

// Batch-sorted candidate sweep over points [lo, hi): sort the span into
// grid-cell order, sweep it (a cell's neighborhood is built +
// SoA-gathered once per run of points that landed in it — CandScratch's
// cache), and scatter each point's (K,) result rows back by original
// index — output is identical to a per-point scan, position for
// position, regardless of how callers span the points. ``order`` is
// caller scratch, reused across spans. This is THE candidate kernel:
// rt_candidates chunks flat queries through it, and rt_prepare_batch's
// span workers run it per trace span before routing those traces.
// Spans stay cache-sized and small: a serial whole-batch sort measured
// as long as the sweep it was meant to help, and under the device lanes
// a coarse span turns into a straggler tail on a descheduled worker.
constexpr int64_t kCandChunk = 1024;

void sweep_span(const Graph* g, int64_t lo, int64_t hi, const double* px,
                const double* py, int32_t k, double radius,
                CandScratch& scratch,
                std::vector<std::pair<int64_t, int64_t>>& order,
                int32_t* out_edge, float* out_dist, float* out_off,
                float* out_px, float* out_py) {
  const double cell = g->cell;
  order.clear();
  for (int64_t p = lo; p < hi; ++p) {
    const int64_t ci = static_cast<int64_t>(std::floor(px[p] / cell));
    const int64_t cj = static_cast<int64_t>(std::floor(py[p] / cell));
    order.emplace_back(Graph::cell_key(ci, cj), p);
  }
  std::sort(order.begin(), order.end());
  for (const auto& kp : order) {
    const int64_t idx = kp.second;
    const int64_t o = idx * k;
    candidates_for_point(g, px[idx], py[idx], k, radius, scratch,
                         out_edge + o, out_dist + o, out_off + o,
                         out_px ? out_px + o : nullptr,
                         out_py ? out_py + o : nullptr);
  }
}

void candidates_batch(const Graph* g, int64_t n_pts, const double* px,
                      const double* py, int32_t k, double radius,
                      int32_t* out_edge, float* out_dist, float* out_off,
                      float* out_px, float* out_py) {
  CandScratch scratch(g->n_edges);
  std::vector<std::pair<int64_t, int64_t>> order;
  order.reserve(static_cast<size_t>(std::min(n_pts, kCandChunk)));
  for (int64_t lo = 0; lo < n_pts; lo += kCandChunk)
    sweep_span(g, lo, std::min(lo + kCandChunk, n_pts), px, py, k, radius,
               scratch, order, out_edge, out_dist, out_off, out_px,
               out_py);
}

// One (K, K) route-distance block between consecutive candidate rows.
// Admissibility mirrors Meili's two bounds (reference: Dockerfile:14-17):
// distance — route fits within max(min_bound, factor * gc);
// time     — the route's travel time at edge speeds fits within
//            max(min_time_bound, time_factor * dt) (skipped unless
//            have_dt && time_factor > 0 && dt > 0).
// turn_penalty_factor adds meters for the heading change between the two
// candidate edges: factor * 0.5 * (1 - cos(theta)).
//
// Each general (ea, eb) pair consults the in-call table, then the shared
// cross-call LRU; only rows with memo misses take the stripe lock and
// probe the Dijkstra map. Admissibility is reapplied per query from the
// cached node kernel, so a memo hit is bit-identical to a recompute.
//
// Returns the largest finite distance written (0 when none): the wire-
// dtype decision needs the batch max, and computing it here — while the
// values are in registers — replaces a second cold pass over the 16 MB
// route tensor per chunk.
float route_step(Graph* g, const int32_t* ea_row, const float* oa_row,
                 const int32_t* eb_row, const float* ob_row, int32_t K,
                 float gc_t, double dt_t, bool have_dt, double factor,
                 double min_bound, double backward_tol, double time_factor,
                 double min_time_bound, double turn_penalty_factor,
                 RouteScratch& rs, float* out) {
  const float bound = static_cast<float>(
      std::max(min_bound, factor * static_cast<double>(gc_t)));
  // min_time_bound floors the cap the way min_bound floors the distance
  // bound: at 1 Hz sampling factor*dt is ~2 s, which GPS noise alone
  // overruns — without the floor the time bound prunes honest
  // transitions instead of absurd detours.
  const float time_cap =
      (have_dt && time_factor > 0 && dt_t > 0)
          ? static_cast<float>(std::max(min_time_bound, time_factor * dt_t))
          : -1.0f;  // no bound
  float mx = 0.0f;
  for (int32_t i = 0; i < K; ++i) {
    const int32_t ea = ea_row[i];
    float* row = out + static_cast<int64_t>(i) * K;
    if (ea == kPadEdge) {
      for (int32_t j = 0; j < K; ++j) row[j] = kUnreachable;
      continue;
    }
    const float oa = oa_row[i];
    const float remaining = g->edge_len[ea] - oa;
    const int32_t src = g->edge_end[ea];

    // one admissibility emitter shared by the memo-hit and recompute
    // paths so the two cannot drift: dn/tn are the node kernel
    // (dn >= kUnreachable: not reachable within a bound >= bound - via)
    auto emit = [&](int32_t j, int32_t eb, float ob, float via, float dn,
                    float tn) {
      // reachable only if the whole route fits inside the bound, matching
      // the python fallback's max_dist semantics (graph/route.py)
      if (dn >= kUnreachable || via + dn > bound) {
        row[j] = kUnreachable;
        return;
      }
      if (time_cap >= 0) {
        const float secs = g->edge_secs(ea, remaining) +
                           g->edge_secs(eb, ob) + tn;
        if (secs > time_cap) {
          row[j] = kUnreachable;
          return;
        }
      }
      float d = via + dn;
      if (turn_penalty_factor > 0) {
        const float cos_th =
            g->head_x[ea] * g->head_x[eb] + g->head_y[ea] * g->head_y[eb];
        d += static_cast<float>(turn_penalty_factor) * 0.5f * (1.0f - cos_th);
      }
      row[j] = d;
      if (d > mx) mx = d;
    };

    // ONE in-call memo probe per row: every target j of this row shares
    // ea, so the row's known kernels live in one small hot vector
    EaMemo& em = rs.local.row(ea);
    rs.miss.clear();
    for (int32_t j = 0; j < K; ++j) {
      const int32_t eb = eb_row[j];
      if (eb == kPadEdge) {
        row[j] = kUnreachable;
        continue;
      }
      const float ob = ob_row[j];
      if (eb == ea && ob >= oa) {
        if (time_cap >= 0 && g->edge_secs(ea, ob - oa) > time_cap) {
          row[j] = kUnreachable;
        } else {
          row[j] = ob - oa;
          if (ob - oa > mx) mx = ob - oa;
        }
        continue;
      }
      // forgive small apparent backward movement on the same directed
      // edge (along-track GPS noise) — see graph/route.py route_distance
      if (eb == ea && oa - ob <= backward_tol) {
        row[j] = 0.0f;
        continue;
      }
      const float via = remaining + ob;
      if (via > bound) {
        row[j] = kUnreachable;
        continue;
      }
      // a finite kernel is exact at any bound; an unreachable verdict
      // only proves depths its search covered (bound - via needed here)
      const int pos = em.find(eb);
      if (pos >= 0 && (em.vals[pos].d < kUnreachable ||
                       em.vals[pos].bound >= bound - via)) {
        emit(j, eb, ob, via, em.vals[pos].d, em.vals[pos].t);
        continue;
      }
      rs.miss.push_back(j);
    }
    if (rs.miss.empty()) continue;

    // shared memo consult for the whole row under ONE stripe(ea) lock;
    // hits are copied out and emitted after the lock drops
    if (g->pair_memo.enabled()) {
      rs.hit_js.clear();
      rs.hit_vals.clear();
      size_t w = 0;
      {
        auto& sp = g->pair_memo.stripe(ea);
        std::lock_guard<std::mutex> lk(sp.mu);
        auto it = sp.rows.find(ea);
        PairMemo::Row* rp = it != sp.rows.end() ? &it->second : nullptr;
        if (rp != nullptr) rp->hot = true;
        for (const int32_t j : rs.miss) {
          const int32_t eb = eb_row[j];
          const float via = remaining + ob_row[j];
          const int pos = rp != nullptr ? rp->find(eb) : -1;
          if (pos >= 0 && (rp->vals[pos].d < kUnreachable ||
                           rp->vals[pos].bound >= bound - via)) {
            ++sp.hits;
            rs.hit_js.push_back(j);
            rs.hit_vals.push_back(rp->vals[pos]);
          } else {
            ++sp.misses;
            rs.miss[w++] = j;  // compact: still needs the search
          }
        }
      }
      rs.miss.resize(w);
      for (size_t i = 0; i < rs.hit_js.size(); ++i) {
        const int32_t j = rs.hit_js[i];
        const int32_t eb = eb_row[j];
        const PairVal& pv = rs.hit_vals[i];
        const int lp = em.find(eb);
        if (lp >= 0) {
          em.vals[lp] = pv;
        } else {
          em.push(eb, pv);
          ++rs.local.n_pairs;
        }
        emit(j, eb, ob_row[j], remaining + ob_row[j], pv.d, pv.t);
      }
      if (rs.miss.empty()) continue;
    }

    rs.put_ebs.clear();
    rs.put_vals.clear();
    {
      // one bounded search from ea's end node covers every missed j.
      // The stripe lock is held across compute AND the fills below: a
      // concurrent bound-extension on the same src move-assigns the
      // cached map, so reads must stay inside the critical section.
      std::lock_guard<std::mutex> lock(g->stripe_for(src).mu);
      float covered = bound;
      const auto& dist = g->dists_from(src, bound, &covered);
      for (const int32_t j : rs.miss) {
        const int32_t eb = eb_row[j];
        const float ob = ob_row[j];
        const float via = remaining + ob;
        const Graph::DistTime* it = dist.find(g->edge_start[eb]);
        // every map entry is a settled exact shortest distance (the
        // relaxation never inserts past the search bound), so a find
        // miss proves dist(dst) > covered and a hit is final — both
        // cacheable
        const PairVal pv = it == nullptr
                               ? PairVal{kUnreachable, 0.0f, covered}
                               : PairVal{it->d, it->t, covered};
        const int pos = em.find(eb);
        if (pos >= 0) {
          em.vals[pos] = pv;  // deepen a stale unreachable verdict
        } else {
          em.push(eb, pv);
          ++rs.local.n_pairs;
        }
        rs.put_ebs.push_back(eb);
        rs.put_vals.push_back(pv);
        emit(j, eb, ob, via, pv.d, pv.t);
      }
    }
    // publish the freshly computed kernels in one batched insert
    if (g->pair_memo.enabled() && !rs.put_ebs.empty()) {
      auto& sp = g->pair_memo.stripe(ea);
      std::lock_guard<std::mutex> lk(sp.mu);
      g->pair_memo.put_row_locked(sp, ea, rs.put_ebs.size(),
                                  rs.put_ebs.data(), rs.put_vals.data());
    }
  }
  return mx;
}

// equirectangular distance in meters, matching core/geo.py exactly
// (double math; per-pair midpoint cosine — NOT the projection's fixed
// anchor cosine, so kept-selection parity with the numpy path holds)
constexpr double kMetersPerDeg = 20037581.187 / 180.0;
constexpr double kRadPerDeg = 3.14159265358979323846 / 180.0;

double equirect_m(double lat_a, double lon_a, double lat_b, double lon_b) {
  const double x =
      (lon_a - lon_b) * kMetersPerDeg * std::cos(0.5 * (lat_a + lat_b) *
                                                 kRadPerDeg);
  const double y = (lat_a - lat_b) * kMetersPerDeg;
  // sqrt(x*x + y*y), NOT hypot: geo.py computes np.sqrt(x*x + y*y), and
  // this value feeds strict threshold compares (interpolation_distance,
  // breakage_distance) where a last-ulp divergence flips a decision
  return std::sqrt(x * x + y * y);
}

}  // namespace

extern "C" {

// ABI handshake: the ctypes loader (native/__init__.py) refuses to use a
// library whose version differs from its expectation, falling back to the
// numpy path loudly instead of calling through a stale signature. BUMP
// THIS on ANY change to the signatures below, in the same commit as the
// Python-side constant.
int32_t rt_abi_version(void) { return 14; }

void* rt_graph_create(int64_t n_nodes, int64_t n_edges,
                      const double* node_x, const double* node_y,
                      const int32_t* edge_start, const int32_t* edge_end,
                      const float* edge_len, const float* edge_speed_kph,
                      double cell_m) {
  auto* g = new Graph();
  g->n_nodes = n_nodes;
  g->n_edges = n_edges;
  g->node_x.assign(node_x, node_x + n_nodes);
  g->node_y.assign(node_y, node_y + n_nodes);
  g->edge_start.assign(edge_start, edge_start + n_edges);
  g->edge_end.assign(edge_end, edge_end + n_edges);
  g->edge_len.assign(edge_len, edge_len + n_edges);
  g->edge_speed.assign(edge_speed_kph, edge_speed_kph + n_edges);
  g->build(cell_m);
  return g;
}

void rt_graph_destroy(void* handle) { delete static_cast<Graph*>(handle); }

void rt_cache_clear(void* handle) {
  auto* g = static_cast<Graph*>(handle);
  for (auto& s : g->route_stripes) {
    std::lock_guard<std::mutex> lock(s.mu);
    s.map.clear();
  }
  g->pair_memo.clear();
  std::lock_guard<std::mutex> lock(g->prep_mu);
  for (auto& slot : g->prep_slots) slot->local.clear();
}

// {hits, misses, size, evictions} of the cross-call route-pair memo
void rt_route_memo_stats(void* handle, int64_t* out4) {
  static_cast<Graph*>(handle)->pair_memo.stats(out4);
}

// Dump up to `cap` resident route-memo pairs into ea/eb (profile
// export); returns the count written.
int64_t rt_route_memo_export(void* handle, int64_t cap, int32_t* ea_out,
                             int32_t* eb_out) {
  return static_cast<Graph*>(handle)->pair_memo.export_pairs(cap, ea_out,
                                                             eb_out);
}

// Pre-warm the cross-call route-pair memo from a profile artifact's
// (edge_from, edge_to) pairs: each pair's node kernel is computed
// exactly like route_step's miss path — a bounded Dijkstra from
// edge_from's end node under the same stripe lock — so a warmed entry
// is bit-identical to what the serving path would compute and cache on
// first contact. Consecutive same-ea pairs (the export order) share
// one search and one batched memo insert. Out-of-range edge ids (a
// profile from a different graph build) are skipped, not fatal.
// Returns pairs inserted; 0 when the memo is disabled.
int64_t rt_route_memo_warm(void* handle, int64_t n, const int32_t* ea,
                           const int32_t* eb, double bound_m) {
  auto* g = static_cast<Graph*>(handle);
  if (!g->pair_memo.enabled()) return 0;
  const float bound = static_cast<float>(bound_m);
  int64_t warmed = 0;
  int64_t i = 0;
  std::vector<int32_t> ebs;
  std::vector<PairVal> vals;
  while (i < n) {
    const int32_t a = ea[i];
    if (a < 0 || a >= g->n_edges) {
      ++i;
      continue;
    }
    ebs.clear();
    vals.clear();
    const int32_t src = g->edge_end[a];
    {
      // lock held across compute AND reads of the returned map — same
      // contract as route_step's miss path (a concurrent bound
      // extension move-assigns the cached map)
      std::lock_guard<std::mutex> lock(g->stripe_for(src).mu);
      float covered = bound;
      const auto& dist = g->dists_from(src, bound, &covered);
      for (; i < n && ea[i] == a; ++i) {
        const int32_t b = eb[i];
        if (b < 0 || b >= g->n_edges) continue;
        const Graph::DistTime* it = dist.find(g->edge_start[b]);
        vals.push_back(it == nullptr
                           ? PairVal{kUnreachable, 0.0f, covered}
                           : PairVal{it->d, it->t, covered});
        ebs.push_back(b);
      }
    }
    if (!ebs.empty()) {
      auto& sp = g->pair_memo.stripe(a);
      std::lock_guard<std::mutex> lk(sp.mu);
      g->pair_memo.put_row_locked(sp, a, ebs.size(), ebs.data(),
                                  vals.data());
      warmed += static_cast<int64_t>(ebs.size());
    }
  }
  return warmed;
}

int64_t rt_cache_size(void* handle) {
  auto* g = static_cast<Graph*>(handle);
  int64_t n = 0;
  for (auto& s : g->route_stripes) {
    std::lock_guard<std::mutex> lock(s.mu);
    n += static_cast<int64_t>(s.map.size());
  }
  return n;
}

// K nearest edges within radius for each of T projected points.
// Outputs are (T, K) row-major, padded with kPadEdge / kPadDist / 0.
void rt_candidates(void* handle, int64_t n_points, const double* px,
                   const double* py, int32_t k, double radius,
                   int32_t* out_edge, float* out_dist, float* out_off,
                   float* out_px, float* out_py) {
  auto* g = static_cast<Graph*>(handle);
  candidates_batch(g, n_points, px, py, k, radius, out_edge, out_dist,
                   out_off, out_px, out_py);
}

// (T-1, K, K) route-distance tensor between consecutive candidate sets.
// edge_ids/offsets are (T, K) row-major; gc is (T-1); dt is (T-1) probe
// time deltas in seconds (may be null: no time bound).
//
// Admissibility mirrors Meili's two bounds (reference: Dockerfile:14-17):
// distance — route fits within max(min_bound, factor * gc);
// time     — the route's travel time at edge speeds fits within
//            time_factor * dt (skipped when either is <= 0).
// turn_penalty_factor adds meters for the heading change between the two
// candidate edges: factor * 0.5 * (1 - cos(theta)) — 0 when straight,
// `factor` for a full U-turn — the penalised route distance Meili feeds
// its transition cost.
void rt_route_matrices(void* handle, int64_t T, int32_t K,
                       const int32_t* edge_ids, const float* offsets,
                       const float* gc, const double* dt, double factor,
                       double min_bound, double backward_tol,
                       double time_factor, double min_time_bound,
                       double turn_penalty_factor, float* out) {
  auto* g = static_cast<Graph*>(handle);
  RouteScratch rs;
  for (int64_t t = 0; t + 1 < T; ++t) {
    route_step(g, edge_ids + t * K, offsets + t * K, edge_ids + (t + 1) * K,
               offsets + (t + 1) * K, K, gc[t], dt ? dt[t] : 0.0,
               dt != nullptr, factor, min_bound, backward_tol, time_factor,
               min_time_bound, turn_penalty_factor, rs,
               out + t * static_cast<int64_t>(K) * K);
  }
}

// Whole-batch trace preparation: projection, candidate search, jitter/
// no-candidate point selection, case codes, and route matrices for B
// traces in ONE call, writing rows straight into the caller's padded
// (B, T, ...) batch tensors. This is the framework's answer to the
// reference's one-C++-Match-per-trace architecture
// (reference: py/reporter_service.py:240) — per-trace Python and
// per-trace ctypes round-trips were the measured end-to-end ceiling
// (BENCH_r03: device decode ~4% of the leg).
//
// Inputs: flat per-point lat/lon/times (degrees / epoch secs) with
// pt_off (B+1) trace offsets; (lat0, lon0) is the network projection
// anchor (graph/network.py projection()). Semantics per trace mirror
// matcher/batchpad.py prepare_trace exactly: points with no candidates
// and points within interpolation_distance of the last kept point are
// excluded; kept sequences cap at T (bucket truncation); case codes are
// RESTART at t=0 and after breakage-sized gaps, NORMAL otherwise, SKIP
// in the padding tail (pre-filled by the caller); route matrices and
// time/turn bounds via route_step above. dt derives from times over
// kept points when time_factor > 0.
//
// This call writes EVERY row of its n_traces traces — live prefixes and
// pad sentinels (SKIP case, kPadEdge, kPadDist, kUnreachable, kept=-1)
// — so the caller may hand in uninitialised (np.empty) tensors; only
// filler rows beyond n_traces (mesh/pow2 batch padding) remain the
// caller's to fill. out_dwell gets the trailing jitter dwell
// (batchpad.py:109-123 semantics). n_threads <= 0 falls back to
// REPORTER_TPU_PREP_THREADS, then hardware_concurrency; work fans out
// over the handle's persistent WorkerPool in two phases — the batch-
// sorted candidate sweep (cell-granular) then the per-trace
// select/route phase (trace-granular) — with deterministic output
// either way (the route cache is lock-striped and the pair memo stores
// exact kernels; ctypes releases the GIL for the whole call).
// ``out_phase_ns`` (nullable, 3 slots) reports the phase split:
// {candidates, select_pack, routes} in nanoseconds, each summed across
// worker threads. The ctypes side folds these into utils.metrics so the
// BENCH artifact can attribute prep time without a profiler;
// REPORTER_TPU_PREP_TIMINGS=1 additionally prints one stderr line per
// call.
//
// ABI 14 additions for the device route kernel (graph/route_device.py):
// ``out_dt`` (B, T) doubles gets the kept-point probe time deltas the
// route stage would bound against — dt_b[t] = times[kept[t+1]] -
// times[kept[t]] for t < n-1 when the time bound is armed, -1.0
// everywhere else — always written, so a skip_routes caller can apply
// the identical time cap off-host. ``skip_routes`` != 0 skips ONLY the
// route_step loop (candidates, selection, gc, case codes, dt and the
// tail fill are unchanged; route rows [0, n-1) are then the caller's to
// write — the device kernel fills every one of them). ``prune_margin``
// > 0 arms FLASH-style candidate pruning after selection: each kept
// row's candidates (sorted ascending by projection distance) are cut
// where dist > dist[0] + prune_margin, shrinking K before any route is
// requested; the best candidate always survives.
void rt_prepare_batch(void* handle, int64_t n_traces, const int64_t* pt_off,
                      const double* lat, const double* lon,
                      const double* times, double lat0, double lon0,
                      int32_t T, int32_t K, double search_radius,
                      double interpolation_distance,
                      double breakage_distance, double factor,
                      double min_bound, double backward_tol,
                      double time_factor, double min_time_bound,
                      double turn_penalty_factor, double prune_margin,
                      int32_t skip_routes, int32_t n_threads,
                      int32_t* out_edge, float* out_dist, float* out_off,
                      float* out_route, float* out_gc, int32_t* out_case,
                      int32_t* out_kept, int32_t* out_num_kept,
                      float* out_dwell, uint8_t* out_has_cands,
                      float* out_max_finite, int64_t* out_phase_ns,
                      double* out_dt) {
  auto* g = static_cast<Graph*>(handle);
  // one prepare call at a time per handle: the per-slot scratches and
  // candidate staging buffers below are reused across calls
  std::lock_guard<std::mutex> prep_lock(g->prep_mu);
  const double coslat0 = std::cos(lat0 * kRadPerDeg);
  const int64_t TK = static_cast<int64_t>(T) * K;
  // route/gc rows are T per trace (not T-1): the final row is a dead
  // step the caller pre-fills, so the (B, T, K, K) tensor shards along
  // the seq mesh axis with no host-side pad copy (parallel/sharded.py)
  const int64_t TKK = static_cast<int64_t>(T) * K * K;
  const int64_t n_pts = n_traces > 0 ? pt_off[n_traces] : 0;

  // running max of every finite distance written (candidate dists, gc,
  // reachable route entries) — the wire-dtype decision (f16 iff the max
  // fits) used to re-scan the 10x-larger tensors in numpy
  std::atomic<float> max_finite{0.0f};
  auto bump_max = [&max_finite](float v) {
    float cur = max_finite.load(std::memory_order_relaxed);
    while (v > cur &&
           !max_finite.compare_exchange_weak(cur, v,
                                             std::memory_order_relaxed)) {
    }
  };

  static const bool timings = [] {
    const char* v = std::getenv("REPORTER_TPU_PREP_TIMINGS");
    return v != nullptr && v[0] != '\0' && v[0] != '0';
  }();
  using clk = std::chrono::steady_clock;
  std::atomic<int64_t> ns_cand{0}, ns_select{0}, ns_route{0};

  int workers = n_threads > 0 ? n_threads : env_prep_threads();
  workers = std::max(1, std::min<int>(
                            workers, static_cast<int>(
                                         std::max<int64_t>(n_traces, 1))));

  // Flat (n_pts, K) candidate staging buffers, persistent on the handle
  // — a 128-trace pipeline chunk must not pay multi-MB allocations per
  // call. Every trace reads its rows out of them by point index, so
  // per-trace copies of the raw candidate rows are gone.
  g->sc_px.resize(n_pts);
  g->sc_py.resize(n_pts);
  double* px = g->sc_px.data();
  double* py = g->sc_py.data();
  for (int64_t p = 0; p < n_pts; ++p) {
    px[p] = (lon[p] - lon0) * kMetersPerDeg * coslat0;
    py[p] = (lat[p] - lat0) * kMetersPerDeg;
  }
  g->sc_edge.resize(n_pts * K);
  g->sc_dist.resize(n_pts * K);
  g->sc_off.resize(n_pts * K);
  int32_t* edge_all = g->sc_edge.data();
  float* dist_all = g->sc_dist.data();
  float* off_all = g->sc_off.data();

  // ---- per-trace selection, packing and route matrices -----------------
  auto prepare_one = [&](int64_t b, RouteScratch& rscratch,
                         std::vector<int32_t>& kept) {
    float local_max = 0.0f;
    const int64_t p0 = pt_off[b], p1 = pt_off[b + 1];
    const int64_t n_raw = p1 - p0;
    const int32_t* edge_raw = edge_all + p0 * K;
    const float* dist_raw = dist_all + p0 * K;
    const float* off_raw = off_all + p0 * K;
    int32_t* edge_b = out_edge + b * TK;
    float* dist_b = out_dist + b * TK;
    float* off_b = out_off + b * TK;
    float* route_b = out_route + b * TKK;
    float* gc_b = out_gc + b * T;
    int32_t* case_b = out_case + b * T;
    int32_t* kept_b = out_kept + b * T;
    double* dt_b = out_dt + b * T;
    out_num_kept[b] = 0;
    out_dwell[b] = 0.0f;
    // pad sentinels for rows beyond the live prefix — written HERE (in
    // the worker threads, one pass, only the dead region) instead of a
    // caller-side np.full over the whole 8-16 MB batch that the live
    // rows immediately overwrite
    auto fill_tail = [&](int32_t live_t, int32_t live_route) {
      for (int32_t t = live_t; t < T; ++t) {
        int32_t* er = edge_b + static_cast<int64_t>(t) * K;
        float* dr = dist_b + static_cast<int64_t>(t) * K;
        float* fr = off_b + static_cast<int64_t>(t) * K;
        for (int32_t q = 0; q < K; ++q) {
          er[q] = kPadEdge;
          dr[q] = kPadDist;
          fr[q] = 0.0f;
        }
        case_b[t] = 2;  // SKIP
        kept_b[t] = -1;
      }
      std::fill_n(route_b + static_cast<int64_t>(live_route) * K * K,
                  static_cast<int64_t>(T - live_route) * K * K,
                  kUnreachable);
      std::fill_n(gc_b + live_route, T - live_route, 0.0f);
      std::fill_n(dt_b + live_route, T - live_route, -1.0);
    };
    if (n_raw <= 0) {
      fill_tail(0, 0);
      return;
    }

    clk::time_point tp;
    if (timings || out_phase_ns) tp = clk::now();

    // kept selection: drop candidate-less points and jitter points within
    // interpolation_distance of the last kept point (batchpad._select_kept)
    kept.clear();
    for (int64_t p = 0; p < n_raw; ++p) {
      bool has = false;
      for (int32_t q = 0; q < K; ++q)
        if (edge_raw[p * K + q] != kPadEdge) {
          has = true;
          break;
        }
      out_has_cands[p0 + p] = has ? 1 : 0;
      if (!has) continue;
      if (!kept.empty()) {
        const int64_t lk = kept.back();
        if (equirect_m(lat[p0 + lk], lon[p0 + lk], lat[p0 + p],
                       lon[p0 + p]) < interpolation_distance)
          continue;
      }
      kept.push_back(static_cast<int32_t>(p));
    }
    const bool truncated = kept.size() > static_cast<size_t>(T);
    const int32_t n =
        static_cast<int32_t>(std::min<size_t>(kept.size(), T));
    out_num_kept[b] = n;
    if (n == 0) {
      fill_tail(0, 0);
      return;
    }

    // trailing jitter dwell: every raw point after the last kept one has
    // candidates and sits within interpolation_distance of it — the
    // vehicle verifiably stayed put (batchpad.py:109-123)
    if (!truncated && kept[n - 1] < n_raw - 1) {
      const int64_t lk = kept[n - 1];
      bool all_jitter = true;
      for (int64_t p = lk + 1; p < n_raw && all_jitter; ++p) {
        bool has = false;
        for (int32_t q = 0; q < K; ++q)
          if (edge_raw[p * K + q] != kPadEdge) {
            has = true;
            break;
          }
        if (!has ||
            equirect_m(lat[p0 + lk], lon[p0 + lk], lat[p0 + p],
                       lon[p0 + p]) >= interpolation_distance)
          all_jitter = false;
      }
      if (all_jitter)
        out_dwell[b] =
            static_cast<float>(times[p1 - 1] - times[p0 + lk]);
    }

    // gather kept rows into the padded outputs; gc + case codes
    for (int32_t t = 0; t < n; ++t) {
      const int64_t p = kept[t];
      std::memcpy(edge_b + t * K, edge_raw + p * K, K * sizeof(int32_t));
      std::memcpy(dist_b + t * K, dist_raw + p * K, K * sizeof(float));
      std::memcpy(off_b + t * K, off_raw + p * K, K * sizeof(float));
      for (int32_t q = 0; q < K; ++q) {
        const float d = dist_b[t * K + q];
        if (d < kUnreachable / 2 && d > local_max) local_max = d;
      }
      kept_b[t] = static_cast<int32_t>(p);
      if (t > 0) {
        const int64_t pp = kept[t - 1];
        const double gc = equirect_m(lat[p0 + pp], lon[p0 + pp],
                                     lat[p0 + p], lon[p0 + p]);
        gc_b[t - 1] = static_cast<float>(gc);
        if (gc_b[t - 1] > local_max) local_max = gc_b[t - 1];
        // compare the FLOAT32 gc, as batchpad.prepare_trace does (it
        // casts gc to f32 before the breakage test) — a gap within one
        // f32 ulp of the threshold must split identically on both paths
        case_b[t] = static_cast<double>(gc_b[t - 1]) > breakage_distance
                        ? 1 /*RESTART*/
                        : 0 /*NORMAL*/;
      } else {
        case_b[t] = 1;  // RESTART at the first kept point
      }
    }

    // FLASH-style candidate pruning: each kept row is sorted ascending
    // by projection distance (candidates_for_point), so cutting the
    // suffix past dist[0] + margin keeps the emission-dominant
    // candidates and shrinks K before any route is requested. Row 0's
    // best candidate always survives, so selection is unchanged.
    if (prune_margin > 0) {
      for (int32_t t = 0; t < n; ++t) {
        int32_t* er = edge_b + static_cast<int64_t>(t) * K;
        float* dr = dist_b + static_cast<int64_t>(t) * K;
        float* fr = off_b + static_cast<int64_t>(t) * K;
        if (er[0] == kPadEdge) continue;
        const float cut = dr[0] + static_cast<float>(prune_margin);
        for (int32_t q = 1; q < K; ++q) {
          if (er[q] == kPadEdge) break;
          if (dr[q] > cut) {
            for (int32_t w = q; w < K && er[w] != kPadEdge; ++w) {
              er[w] = kPadEdge;
              dr[w] = kPadDist;
              fr[w] = 0.0f;
            }
            break;
          }
        }
      }
    }

    if (timings || out_phase_ns) {
      const auto t2 = clk::now();
      ns_select += (t2 - tp).count();
      tp = t2;
    }
    // kept-point probe time deltas: always recorded (the device route
    // kernel applies the identical time cap from them); -1 marks steps
    // the time bound must not arm on
    const bool have_dt = time_factor > 0 && n > 1;
    for (int32_t t = 0; t + 1 < n; ++t)
      dt_b[t] = have_dt
                    ? times[p0 + kept[t + 1]] - times[p0 + kept[t]]
                    : -1.0;
    // route matrices between consecutive kept candidate rows; dt feeds
    // the time-admissibility bound. skip_routes leaves rows [0, n-1)
    // for the device kernel (the tail fill below still covers the rest)
    if (!skip_routes) {
      for (int32_t t = 0; t + 1 < n; ++t) {
        const double dt_t = have_dt ? dt_b[t] : 0.0;
        const float step_max = route_step(
            g, edge_b + t * K, off_b + t * K, edge_b + (t + 1) * K,
            off_b + (t + 1) * K, K, gc_b[t], dt_t, have_dt, factor,
            min_bound, backward_tol, time_factor, min_time_bound,
            turn_penalty_factor, rscratch,
            route_b + static_cast<int64_t>(t) * K * K);
        if (step_max > local_max) local_max = step_max;
      }
    }
    fill_tail(n, n - 1);
    bump_max(local_max);
    if (timings || out_phase_ns) ns_route += (clk::now() - tp).count();
  };

  // per-worker-slot route scratches, persistent across calls: the
  // slot's local pair memo survives between pipeline chunks (cleared
  // when it outgrows the shared memo's configured bound, or every call
  // when REPORTER_TPU_ROUTE_MEMO=0 disables cross-call memoisation)
  while (g->prep_slots.size() < static_cast<size_t>(workers))
    g->prep_slots.emplace_back(new RouteScratch());
  // Work unit: a SPAN of consecutive traces. The worker first runs the
  // batch-sorted candidate kernel over the span's points (sort into
  // grid-cell order, sweep with the gathered-SoA loops, scatter by
  // index), then immediately selects/packs/routes those traces — no
  // barrier between the candidate and route phases. The two-phase
  // variant (whole-batch candidate pass, then traces) measured badly
  // under the device lanes: with decode/assemble threads contending for
  // the same cores, every barrier waited out a descheduled straggler.
  constexpr int64_t kSpanTraces = 8;
  const int64_t n_units = (n_traces + kSpanTraces - 1) / kSpanTraces;
  const bool memo_on = g->pair_memo.enabled();
  const int64_t local_cap = g->pair_memo.capacity();
  std::atomic<int> slot{0};
  std::atomic<int64_t> next{0};
  auto span_worker = [&]() {
    RouteScratch& rscratch = *g->prep_slots[slot.fetch_add(1)];
    if (!memo_on || rscratch.local.n_pairs > local_cap)
      rscratch.local.clear();
    CandScratch cscratch(g->n_edges);
    std::vector<std::pair<int64_t, int64_t>> order;
    std::vector<int32_t> kept;
    for (;;) {
      const int64_t u = next.fetch_add(1);
      if (u >= n_units) return;
      const int64_t b0 = u * kSpanTraces;
      const int64_t b1 = std::min(b0 + kSpanTraces, n_traces);
      clk::time_point tp;
      if (timings || out_phase_ns) tp = clk::now();
      sweep_span(g, pt_off[b0], pt_off[b1], px, py, K, search_radius,
                 cscratch, order, edge_all, dist_all, off_all, nullptr,
                 nullptr);
      if (timings || out_phase_ns)
        ns_cand += (clk::now() - tp).count();
      for (int64_t b = b0; b < b1; ++b) prepare_one(b, rscratch, kept);
    }
  };
  g->pool.run(static_cast<int>(std::min<int64_t>(workers - 1,
                                                 n_units - 1)),
              span_worker);
  *out_max_finite = max_finite.load();
  if (out_phase_ns) {
    out_phase_ns[0] = ns_cand.load();
    out_phase_ns[1] = ns_select.load();
    out_phase_ns[2] = ns_route.load();
  }
  if (timings)
    std::fprintf(stderr,
                 "[prep_timings] traces=%lld workers=%d "
                 "candidates=%.3fms select_pack=%.3fms "
                 "routes=%.3fms (thread-summed)\n",
                 static_cast<long long>(n_traces), workers,
                 ns_cand.load() / 1e6, ns_select.load() / 1e6,
                 ns_route.load() / 1e6);
}

// f32 -> f16 (IEEE half) bulk conversion for the wire tensors
// (matcher/batchpad.py). Round-to-nearest-even with overflow to +/-inf —
// bit-identical to numpy.astype(float16). The numpy cast was the single
// largest host cost after batching (BENCH round-4 profile: ~43% of
// match_many); with F16C this is one instruction per 8 floats.
void rt_f32_to_f16(const float* src, uint16_t* dst, int64_t n) {
  int64_t i = 0;
#ifdef __F16C__
  for (; i + 8 <= n; i += 8) {
    __m256 v = _mm256_loadu_ps(src + i);
    _mm_storeu_si128(reinterpret_cast<__m128i*>(dst + i),
                     _mm256_cvtps_ph(v, _MM_FROUND_TO_NEAREST_INT));
  }
#endif
  for (; i < n; ++i) {
    // scalar fallback: round-to-nearest-even via float bit manipulation
    uint32_t x;
    std::memcpy(&x, src + i, 4);
    const uint32_t sign = (x >> 16) & 0x8000u;
    x &= 0x7fffffffu;
    uint16_t h;
    if (x >= 0x47800000u) {                  // overflow / inf / nan
      h = x > 0x7f800000u ? 0x7e00u : 0x7c00u;
    } else if (x < 0x38800000u) {            // subnormal / zero
      const float f = std::fabs(src[i]) * 0x1.0p+24f;  // scale into int range
      uint32_t m = static_cast<uint32_t>(f);
      const float r = f - static_cast<float>(m);
      m += (r > 0.5f || (r == 0.5f && (m & 1u))) ? 1u : 0u;
      h = static_cast<uint16_t>(m);
    } else {
      const uint32_t mant = x & 0xfffu;
      x += 0xfffu + ((x >> 13) & 1u);        // round to nearest even
      (void)mant;
      h = static_cast<uint16_t>(((x - 0x38000000u) >> 13) & 0x7fffu);
    }
    dst[i] = h | sign;
  }
}

}  // extern "C"

// ---- batched segment assembly (matcher/assemble.py in C++) --------------
// The decoded (B, T) candidate indices -> per-trace OSMLR segment runs,
// walked entirely in native code; Python only formats the run records
// into the reference-schema dicts (reference: py/reporter_service.py:103-162
// consumes them). Semantics mirror matcher/assemble.py line for line; the
// parity is pinned by tests (native batch vs pure-python assemble).

namespace {

constexpr double kBoundaryEps = 1.0;          // assemble.py _BOUNDARY_EPS
constexpr double kQueueEndProximity = 100.0;  // _QUEUE_END_PROXIMITY_M
constexpr int32_t kCaseRestart = 1;

double interp_time(double pos, double pos_a, double pos_b, double ta,
                   double tb) {
  if (pos_b <= pos_a) return ta;
  double frac = (pos - pos_a) / (pos_b - pos_a);
  frac = std::min(std::max(frac, 0.0), 1.0);
  return ta + frac * (tb - ta);
}

// segment length lookup over the sorted (seg_ids, seg_lens) columns;
// returns fallback when absent (assemble.py uses .get(id, 0.0) for
// interpolation and .get(id, -1.0) for output)
double seg_len_of(const int64_t* ids, const double* lens, int64_t n,
                  int64_t key, double fallback) {
  const int64_t* it = std::lower_bound(ids, ids + n, key);
  if (it != ids + n && *it == key) return lens[it - ids];
  return fallback;
}

struct Run {
  int64_t segment_id;  // -1 = unassociated stretch
  bool internal;
  int32_t first_idx, last_idx;
  double first_pos, last_pos;
  double first_time, last_time;
  double first_cum, last_cum;
  double start_time = -1.0, end_time = -1.0;
  double queue_start;  // NaN while traffic is moving
  bool has_queue_start = false;
  std::vector<int64_t> edges;
};

}  // namespace

extern "C" {

// Returns total runs written (<= cap), or -1 if cap would overflow (the
// caller sizes cap = sum(num_kept), which is a strict upper bound — each
// chain element starts at most one run — so -1 indicates a caller bug).
// Outputs: run_off (B+1) per-trace run ranges; per-run columns; way_off
// (cap+1) + out_ways flat way-id lists (capacity also sum(num_kept)).
int64_t rt_assemble_batch(
    void* handle, int64_t B, int32_t T, int32_t K, const int32_t* path,
    const int32_t* edge_ids, const float* offset_m, const float* route_m,
    const int32_t* case_codes, const int32_t* kept_idx,
    const int32_t* num_kept, const float* dwell, const int64_t* pt_off,
    const double* times, const uint8_t* has_cands, const int64_t* edge_seg_id,
    const float* edge_seg_off, const uint8_t* edge_internal,
    const int64_t* seg_ids_sorted, const double* seg_lens_sorted,
    int64_t n_segs, double queue_threshold_kph,
    double interpolation_distance_m, double backward_tolerance_m,
    double turn_penalty_factor, int64_t cap, int64_t* run_off,
    int64_t* out_seg_id, uint8_t* out_internal, double* out_start,
    double* out_end, int32_t* out_length, int32_t* out_queue,
    int32_t* out_begin_idx, int32_t* out_end_idx, int64_t* way_off,
    int64_t* out_ways) {
  const auto* g = static_cast<const Graph*>(handle);
  const int64_t TK = static_cast<int64_t>(T) * K;
  // route rows are T per trace (dead trailing step) — see rt_prepare_batch
  const int64_t TKK = static_cast<int64_t>(T) * K * K;
  int64_t r_total = 0;  // runs written
  int64_t w_total = 0;  // way ids written
  way_off[0] = 0;
  std::vector<Run> runs;
  // chain element: (orig_idx, edge, seg_id, seg_pos, time, cum, internal)
  struct Elem {
    int32_t idx;
    int64_t edge, seg_id;
    double seg_pos, time, cum;
    bool internal;
  };
  std::vector<Elem> chain;

  for (int64_t b = 0; b < B; ++b) {
    run_off[b] = r_total;
    const int32_t n = num_kept[b];
    if (n == 0) continue;
    const int32_t* path_b = path + b * T;
    const int32_t* edge_b_rows = edge_ids + b * TK;
    const float* off_b = offset_m + b * TK;
    const float* route_b = route_m + b * TKK;
    const int32_t* case_b = case_codes + b * T;
    const int32_t* kept_b = kept_idx + b * T;
    const double* times_b = times + pt_off[b];
    const double trailing_dwell = dwell[b];

    runs.clear();
    chain.clear();

    // emit the accumulated chain as runs (assemble.py _chain_to_segments)
    auto flush_chain = [&](bool final_flush) {
      if (chain.empty()) return;
      const size_t first_run = runs.size();
      // re-entry splits a run, but backward movement within the
      // matcher's backward tolerance is along-track GPS noise, not a
      // loop (matcher/assemble.py _chain_to_segments has the rationale)
      const double reentry_tol =
          std::max(kBoundaryEps, backward_tolerance_m);
      for (const Elem& e : chain) {
        const int64_t sid = e.seg_id >= 0 ? e.seg_id : -1;
        bool same = false;
        if (runs.size() > first_run) {
          Run& last = runs.back();
          same = last.segment_id == sid && last.internal == e.internal &&
                 !(sid >= 0 && e.seg_pos < last.last_pos - reentry_tol);
        }
        if (same) {
          Run& r = runs.back();
          const double dt = e.time - r.last_time;
          if (dt > 0.0) {
            const double speed_kph = (e.seg_pos - r.last_pos) / dt * 3.6;
            if (speed_kph < queue_threshold_kph) {
              if (!r.has_queue_start) {
                r.queue_start = r.last_pos;
                r.has_queue_start = true;
              }
            } else {
              r.has_queue_start = false;
            }
          }
          r.last_idx = e.idx;
          r.last_pos = e.seg_pos;
          r.last_time = e.time;
          r.last_cum = e.cum;
          if (r.edges.back() != e.edge) r.edges.push_back(e.edge);
        } else {
          Run r;
          r.segment_id = sid;
          r.internal = e.internal;
          r.first_idx = r.last_idx = e.idx;
          r.first_pos = r.last_pos = e.seg_pos;
          r.first_time = r.last_time = e.time;
          r.first_cum = r.last_cum = e.cum;
          r.edges.push_back(e.edge);
          runs.push_back(std::move(r));
        }
      }
      // trailing raw-point dwell: the dropped tail stayed within
      // interpolation_distance for dwell seconds — if even the
      // upper-bound speed (disc diameter / dwell) is below the queue
      // threshold, the vehicle is queued at its last decoded position
      if (final_flush && trailing_dwell > 0.0 && runs.size() > first_run) {
        Run& last = runs.back();
        const double bound_kph =
            2.0 * interpolation_distance_m / trailing_dwell * 3.6;
        if (bound_kph < queue_threshold_kph && !last.has_queue_start) {
          last.queue_start = last.last_pos;
          last.has_queue_start = true;
        }
      }
      // interpolate boundary times between adjacent runs of this chain.
      // The crossing must lie ON the route between the straddling probes
      // (matcher/assemble.py has the full rationale: a clamped interp
      // would read a one-point intersection flicker as a complete
      // traversal of the crossing segment) — unobserved exits/entries
      // keep their -1 sentinel.
      for (size_t ri = first_run; ri + 1 < runs.size(); ++ri) {
        Run& a = runs[ri];
        Run& b2 = runs[ri + 1];
        const double pos_a = a.last_cum, pos_b = b2.first_cum;
        const double ta = a.last_time, tb = b2.first_time;
        if (a.segment_id >= 0) {
          const double seg_len = seg_len_of(seg_ids_sorted, seg_lens_sorted,
                                            n_segs, a.segment_id, 0.0);
          const double exit_cum =
              a.last_cum + std::max(seg_len - a.last_pos, 0.0);
          if (exit_cum <= pos_b + kBoundaryEps)
            a.end_time = interp_time(exit_cum, pos_a, pos_b, ta, tb);
          // else: exit unobserved; end_time stays -1
        } else {
          a.end_time = ta;
        }
        if (b2.segment_id >= 0) {
          const double entry_cum = b2.first_cum - b2.first_pos;
          if (entry_cum >= pos_a - kBoundaryEps)
            b2.start_time = interp_time(entry_cum, pos_a, pos_b, ta, tb);
          // else: entry unobserved; start_time stays -1
        } else {
          b2.start_time = tb;
        }
      }
      // chain endpoints: partial entry/exit => -1 sentinels. Boundary
      // proximity tolerates one interpolation distance of GPS noise
      // (matcher/assemble.py has the rationale)
      const double end_tol =
          std::max(kBoundaryEps, 3.0 * interpolation_distance_m);
      if (runs.size() > first_run) {
        // a single-point run that is BOTH chain endpoints gets no
        // grants — one probe cannot witness a traversal
        // (matcher/assemble.py has the window-boundary rationale)
        const bool lone_point =
            runs.size() == first_run + 1 &&
            runs[first_run].first_idx == runs[first_run].last_idx;
        Run& first = runs[first_run];
        if (first.segment_id >= 0) {
          if (first.first_pos <= end_tol && !lone_point)
            first.start_time = first.first_time;
          // else stays -1 (got on mid-segment)
        } else {
          first.start_time = first.first_time;
        }
        Run& last = runs.back();
        if (last.segment_id >= 0) {
          const double seg_len = seg_len_of(seg_ids_sorted, seg_lens_sorted,
                                            n_segs, last.segment_id, 0.0);
          if (last.last_pos >= seg_len - end_tol && !lone_point)
            last.end_time = last.last_time;
          // else stays -1 (still on the segment when the trace ended)
        } else {
          last.end_time = last.last_time;
        }
      }
      chain.clear();
    };

    double cum = 0.0;
    bool prev_ok = false;
    for (int32_t t = 0; t < n; ++t) {
      if (case_b[t] == kCaseRestart) {
        flush_chain(false);
        cum = 0.0;
        prev_ok = false;
      }
      const int32_t k = path_b[t];
      const int64_t e = edge_b_rows[t * K + k];
      if (e == kPadEdge) {
        flush_chain(false);
        prev_ok = false;
        continue;
      }
      if (prev_ok) {
        float step =
            route_b[static_cast<int64_t>(t - 1) * K * K +
                    static_cast<int64_t>(path_b[t - 1]) * K + k];
        if (step >= kUnreachable / 2) {
          // decoder was forced through an unroutable pair; break here
          flush_chain(false);
          cum = 0.0;
        } else {
          if (turn_penalty_factor > 0) {
            // strip the ranking-only turn penalty: cumulative route
            // positions must be geometric meters, not penalty meters
            // (matcher/assemble.py has the rationale)
            const int64_t e_prev =
                edge_b_rows[static_cast<int64_t>(t - 1) * K +
                            path_b[t - 1]];
            const float cos_th = g->head_x[e_prev] * g->head_x[e] +
                                 g->head_y[e_prev] * g->head_y[e];
            step = std::max(
                step - static_cast<float>(turn_penalty_factor) * 0.5f *
                           (1.0f - cos_th),
                0.0f);
          }
          cum += static_cast<double>(step);
        }
      }
      chain.push_back(Elem{
          kept_b[t], e, edge_seg_id[e],
          static_cast<double>(edge_seg_off[e]) +
              static_cast<double>(off_b[t * K + k]),
          times_b[kept_b[t]], cum, edge_internal[e] != 0});
      prev_ok = true;
    }
    flush_chain(true);

    // attribute HMM-excluded points: jitter gap points between runs
    // join the FOLLOWING run — but only back to the last candidate-less
    // (off-network) point, which stays unattributed along with anything
    // before it (spans are contiguous ranges and cannot hole-punch) —
    // and a verifiably-jitter trailing tail joins the final run
    // (matcher/assemble.py has the contract rationale)
    for (size_t ri = 1; ri < runs.size(); ++ri) {
      const int32_t lo = runs[ri - 1].last_idx + 1;
      const int32_t hi = runs[ri].first_idx;
      int32_t start = lo;
      for (int32_t j = hi - 1; j >= lo; --j)
        if (!has_cands[pt_off[b] + j]) {
          start = j + 1;
          break;
        }
      runs[ri].first_idx = start;
    }
    if (!runs.empty() && trailing_dwell > 0.0)
      runs.back().last_idx =
          static_cast<int32_t>(pt_off[b + 1] - pt_off[b]) - 1;

    // write this trace's runs to the flat outputs
    if (r_total + static_cast<int64_t>(runs.size()) > cap) return -1;
    std::fesetround(FE_TONEAREST);
    for (const Run& r : runs) {
      const bool complete =
          r.segment_id >= 0 && r.start_time != -1.0 && r.end_time != -1.0;
      const double seg_len =
          r.segment_id >= 0
              ? seg_len_of(seg_ids_sorted, seg_lens_sorted, n_segs,
                           r.segment_id, -1.0)
              : -1.0;
      out_seg_id[r_total] = r.segment_id;
      out_internal[r_total] = r.internal ? 1 : 0;
      out_start[r_total] = r.start_time;
      out_end[r_total] = r.end_time;
      // rint (round-half-even) matches python round()
      out_length[r_total] =
          complete ? static_cast<int32_t>(std::rint(seg_len)) : -1;
      int32_t q = 0;
      if (r.segment_id >= 0 && r.has_queue_start) {
        const double sl = std::max(seg_len, 0.0);
        // only extrapolate to the segment end when the queue was actually
        // observed near it (assemble.py _Run.queue_length)
        if (sl > 0.0 && sl - r.last_pos <= kQueueEndProximity)
          q = static_cast<int32_t>(
              std::rint(std::max(sl - r.queue_start, 0.0)));
      }
      out_queue[r_total] = q;
      out_begin_idx[r_total] = r.first_idx;
      out_end_idx[r_total] = r.last_idx;
      if (w_total + static_cast<int64_t>(r.edges.size()) > cap) return -1;
      for (int64_t e : r.edges) out_ways[w_total++] = e;
      way_off[r_total + 1] = w_total;
      ++r_total;
    }
  }
  run_off[B] = r_total;
  return r_total;
}

}  // extern "C"

// ---- columnar /report wire writer (ABI 12) -------------------------------
// Emits the whole /report UTF-8 JSON response for one trace's run-column
// slice [lo, hi) into a single caller-owned buffer — the native twin of
// service/report.py's Python columnar writer, pinned byte-identical to it
// (and therefore to json.dumps over the legacy dict path) by
// tests/test_report_writer.py. Pure functions over borrowed numpy columns:
// no handle, no allocation, no shared state — concurrent calls from many
// GIL-released request threads are trivially safe (TSan leg drives them).

namespace jsonwire {

inline char* put_u64_dec(char* p, uint64_t v) {
  char tmp[20];
  int n = 0;
  do {
    tmp[n++] = static_cast<char>('0' + v % 10);
    v /= 10;
  } while (v);
  while (n) *p++ = tmp[--n];
  return p;
}

inline char* put_i64_dec(char* p, int64_t v) {
  uint64_t u = static_cast<uint64_t>(v);
  if (v < 0) {
    *p++ = '-';
    u = 0ull - u;
  }
  return put_u64_dec(p, u);
}

// CPython round(x, 3): correctly-rounded DECIMAL rounding with ties to
// even — NOT rint(x*1000)/1000 (that is numpy's np.round, which the
// Python side applies to the start/end columns before they reach this
// writer). glibc's printf is correctly rounded with the same tie rule,
// so %.3f + strtod reproduces the builtin bit-for-bit. Magnitudes past
// 1e13 are already coarser than 1e-3 (ulp > 2e-3): round() returns the
// input there, and the guard also bounds the %.3f output length.
inline double py_round3(double x) {
  if (!std::isfinite(x) || std::fabs(x) >= 1e13) return x;
  char buf[64];
  snprintf(buf, sizeof buf, "%.3f", x);
  return strtod(buf, nullptr);
}

// Python float-repr formatting over extracted digits: dig[0..p) with the
// first digit worth 10^e. Mirrors CPython's format_float_short: fixed
// notation for -4 <= e < 16 (integer values gain ".0"), scientific
// otherwise with a sign and >= 2 exponent digits.
inline int format_repr(bool neg, const char* dig, int p, int e,
                       char* out) {
  char* q = out;
  if (neg) *q++ = '-';
  if (-4 <= e && e < 16) {
    if (e >= p - 1) {
      std::memcpy(q, dig, p);
      q += p;
      for (int i = 0; i < e - (p - 1); ++i) *q++ = '0';
      *q++ = '.';
      *q++ = '0';
    } else if (e >= 0) {
      std::memcpy(q, dig, e + 1);
      q += e + 1;
      *q++ = '.';
      std::memcpy(q, dig + e + 1, p - e - 1);
      q += p - e - 1;
    } else {
      *q++ = '0';
      *q++ = '.';
      for (int i = 0; i < -e - 1; ++i) *q++ = '0';
      std::memcpy(q, dig, p);
      q += p;
    }
  } else {
    *q++ = dig[0];
    if (p > 1) {
      *q++ = '.';
      std::memcpy(q, dig + 1, p - 1);
      q += p - 1;
    }
    *q++ = 'e';
    *q++ = e < 0 ? '-' : '+';
    int a = e < 0 ? -e : e;
    if (a < 10) *q++ = '0';  // repr pads the exponent to two digits
    q = put_u64_dec(q, static_cast<uint64_t>(a));
  }
  return static_cast<int>(q - out);
}

// repr(float) bytes, CPython-identical, with json.dumps's Infinity/NaN
// spellings (matcher._jnum). `out` must hold >= 32 bytes. Two fast
// paths cover every value this wire actually carries (integer-valued
// doubles and 3-decimal-rounded times/kms below 1e12, where a
// round-tripping stripped "%.3f" is provably the shortest repr); the
// general path finds the smallest precision whose correctly-rounded
// "%.*e" round-trips — the grisu-style shortest-digits contract,
// delegated to glibc's correctly-rounded conversions.
inline int json_double(double v, char* out) {
  if (std::isnan(v)) {
    std::memcpy(out, "NaN", 3);
    return 3;
  }
  if (std::isinf(v)) {
    if (v < 0) {
      std::memcpy(out, "-Infinity", 9);
      return 9;
    }
    std::memcpy(out, "Infinity", 8);
    return 8;
  }
  const bool neg = std::signbit(v);
  const double a = neg ? -v : v;
  char* q = out;
  if (a == 0.0) {
    if (neg) *q++ = '-';
    *q++ = '0';
    *q++ = '.';
    *q++ = '0';
    return static_cast<int>(q - out);
  }
  if (a < 1e16 && a == std::floor(a)) {
    if (neg) *q++ = '-';
    q = put_u64_dec(q, static_cast<uint64_t>(a));
    *q++ = '.';
    *q++ = '0';
    return static_cast<int>(q - out);
  }
  char buf[40];
  if (a < 1e12) {
    // 3-decimal fast path: below 1e12 a double's half-ulp is < 5e-4,
    // so at most one 3-decimal string round-trips and no shorter
    // string can (beyond trailing-zero stripping) — if the 3-decimal
    // form round-trips, it IS repr. All in integer math: m is the
    // correctly-rounded (ties-even, llrint) milli-value, and
    // double(m)/1000.0 — one exact int64->double conversion, one
    // correctly-rounded division — equals strtod of the 3-decimal
    // string by IEEE-754, so the snprintf/strtod pair this path used
    // to lean on (~2 us per float, most of the writer's wall) is
    // byte-for-byte replaced by a division and a compare.
    const int64_t m = std::llrint(a * 1000.0);
    if (m > 0 && static_cast<double>(m) / 1000.0 == a) {
      if (neg) *q++ = '-';
      q = put_u64_dec(q, static_cast<uint64_t>(m / 1000));
      // m % 1000 > 0: an integer-valued a took the floor path above
      const int frac = static_cast<int>(m % 1000);
      const char d2 = static_cast<char>('0' + frac / 100);
      const char d1 = static_cast<char>('0' + (frac / 10) % 10);
      const char d0 = static_cast<char>('0' + frac % 10);
      *q++ = '.';
      *q++ = d2;
      if (d1 != '0' || d0 != '0') *q++ = d1;
      if (d0 != '0') *q++ = d0;
      return static_cast<int>(q - out);
    }
  }
  // general path (rare on this wire): smallest p in 1..17 whose
  // correctly-rounded p-digit form round-trips = shortest repr digits
  int p = 17;
  for (int t = 1; t <= 17; ++t) {
    snprintf(buf, sizeof buf, "%.*e", t - 1, a);
    if (strtod(buf, nullptr) == a) {
      p = t;
      break;
    }
  }
  snprintf(buf, sizeof buf, "%.*e", p - 1, a);
  char dig[20];
  int np = 0;
  const char* s = buf;
  dig[np++] = *s++;
  // collect mantissa digits up to 'e', skipping the radix mark
  // WHATEVER the host process's LC_NUMERIC renders it as (an embedding
  // application may have setlocale'd to a comma — or multibyte —
  // decimal point; the strtod round-trip checks above formatted and
  // parsed under that same locale, so they stay self-consistent, and
  // the emitted JSON gets its '.' from format_repr, never from here)
  while (*s != 'e') {
    if (*s >= '0' && *s <= '9') dig[np++] = *s;
    ++s;
  }
  ++s;  // 'e'
  const int esign = (*s++ == '-') ? -1 : 1;
  int e = 0;
  while (*s) e = e * 10 + (*s++ - '0');
  e *= esign;
  while (np > 1 && dig[np - 1] == '0') --np;  // belt + braces
  return format_repr(neg, dig, np, e, out);
}

// Bounds-checked append buffer: overflow latches `of` and stops writing;
// the caller grows its buffer and retries (returns -1 at the ABI edge).
struct JBuf {
  char* p;
  int64_t cap;
  int64_t n = 0;
  bool of = false;
  void raw(const void* s, int64_t k) {
    if (of || n + k > cap) {
      of = true;
      return;
    }
    std::memcpy(p + n, s, k);
    n += k;
  }
  template <size_t N>
  void lit(const char (&s)[N]) {
    raw(s, static_cast<int64_t>(N - 1));
  }
  void ch(char c) {
    if (of || n + 1 > cap) {
      of = true;
      return;
    }
    p[n++] = c;
  }
  void i64(int64_t v) {
    char t[24];
    raw(t, put_i64_dec(t, v) - t);
  }
  void f(double v) {
    char t[40];
    raw(t, json_double(v, t));
  }
};

// matcher.render_segments_json: the reference-schema
// {"segments":[...],"mode":...} block straight from run columns.
inline void render_segments(JBuf& b, const int64_t* seg_id,
                            const uint8_t* internal, const double* start,
                            const double* end_, const int32_t* length,
                            const int32_t* queue, const int32_t* begin_idx,
                            const int32_t* end_idx, const int64_t* way_off,
                            const int64_t* ways, int64_t lo, int64_t hi,
                            const char* mode_json, int64_t mode_len) {
  b.lit("{\"segments\":[");
  for (int64_t r = lo; r < hi; ++r) {
    if (r > lo) b.ch(',');
    b.lit("{\"way_ids\":[");
    for (int64_t w = way_off[r]; w < way_off[r + 1]; ++w) {
      if (w > way_off[r]) b.ch(',');
      b.i64(ways[w]);
    }
    b.lit("],\"start_time\":");
    b.f(start[r]);
    b.lit(",\"end_time\":");
    b.f(end_[r]);
    b.lit(",\"length\":");
    b.i64(length[r]);
    b.lit(",\"queue_length\":");
    b.i64(queue[r]);
    b.lit(",\"internal\":");
    if (internal[r])
      b.lit("true");
    else
      b.lit("false");
    b.lit(",\"begin_shape_index\":");
    b.i64(begin_idx[r]);
    b.lit(",\"end_shape_index\":");
    b.i64(end_idx[r]);
    if (seg_id[r] >= 0) {
      b.lit(",\"segment_id\":");
      b.i64(seg_id[r]);
    }
    b.ch('}');
  }
  b.lit("],\"mode\":");
  b.raw(mode_json, mode_len);
  b.ch('}');
}

struct ScanStats {
  int64_t successful = 0, unreported = 0;
  double successful_km = 0.0, unreported_km = 0.0;
  int64_t discontinuities = 0, invalid_times = 0, invalid_speeds = 0,
          unassociated = 0;
  int64_t last_idx = -1;    // relative to lo
  int64_t shape_used = -1;  // -1 = None (omitted)
};

// The reference's pairwise emission state machine — a line-for-line
// port of service/report.py _scan_segments over the ROUNDED columns
// (the Python side applies np.round(.., 3) before handing them over,
// so holdback comparisons and emitted bytes see identical doubles).
// With `emit` set, report objects stream into it; the machine runs
// twice per response — once to size the stats block that precedes the
// reports, once to emit — so the caller must hand the second pass a
// throwaway ScanStats (the km sums accumulate per pass).
inline void scan_segments(const int64_t* seg_id, const uint8_t* internal,
                          const double* start, const double* end_,
                          const int32_t* length, const int32_t* queue,
                          const int32_t* begin_idx, const int32_t* end_idx,
                          int64_t lo, int64_t hi, double trace_end,
                          double threshold_sec, uint32_t report_mask,
                          uint32_t transition_mask, ScanStats* st,
                          JBuf* emit) {
  const int64_t n = hi - lo;
  int64_t last = n - 1;
  while (last >= 0 && trace_end - start[lo + last] < threshold_sec) --last;
  st->last_idx = last;
  if (last > 0)
    st->shape_used = end_idx[lo + last - 1];
  else if (last == 0)
    st->shape_used = std::max<int64_t>(
        static_cast<int64_t>(begin_idx[lo]) - 1, 0);
  bool have_pending = false, first = true, emitted_any = false;
  bool p_has_sid = false;
  int64_t p_sid = 0;
  double p_start = 0.0, p_end = 0.0;
  int32_t p_len = 0, p_queue = 0;
  int p_level = -1;
  for (int64_t i = 0; i <= last; ++i) {
    const int64_t sid = seg_id[lo + i];
    const bool has_sid = sid >= 0;  // -1 = column sentinel for no id
    const bool intern = internal[lo + i] != 0;
    const double start_time = start[lo + i];
    if (i > 0 && start_time == -1.0 && end_[lo + i - 1] == -1.0)
      ++st->discontinuities;
    const int level = has_sid ? static_cast<int>(sid & 7) : -1;
    if (have_pending && p_has_sid && p_len > 0 && !intern) {
      if (p_level >= 0 && ((report_mask >> p_level) & 1u)) {
        const bool trans =
            level >= 0 && ((transition_mask >> level) & 1u);
        const double t1 = trans ? start_time : p_end;
        const double dt = t1 - p_start;
        if (dt <= 0.0 || std::isinf(dt) || std::isnan(dt)) {
          ++st->invalid_times;
        } else if ((static_cast<double>(p_len) / dt) * 3.6 > 160.0) {
          ++st->invalid_speeds;
        } else {
          ++st->successful;
          // == py_round3(p_len * 0.001): for integer meters the
          // 3-decimal rounding of len*0.001 is exactly the correctly-
          // rounded division len/1000 (validated exhaustively against
          // CPython round() in the parity tests) — no snprintf here
          st->successful_km += static_cast<double>(p_len) / 1000.0;
          if (emit) {
            if (emitted_any) emit->ch(',');
            emitted_any = true;
            emit->lit("{\"id\":");
            emit->i64(p_sid);
            emit->lit(",\"t0\":");
            emit->f(p_start);
            emit->lit(",\"t1\":");
            emit->f(t1);
            emit->lit(",\"length\":");
            emit->i64(p_len);
            emit->lit(",\"queue_length\":");
            emit->i64(p_queue);
            if (trans && has_sid) {
              emit->lit(",\"next_id\":");
              emit->i64(sid);
            }
            emit->ch('}');
          }
        }
      } else {
        ++st->unreported;
        st->unreported_km += static_cast<double>(p_len) / 1000.0;
      }
    }
    if (!(intern && !first)) {
      p_has_sid = has_sid;
      p_sid = sid;
      p_start = start_time;
      p_end = end_[lo + i];
      p_len = length[lo + i];
      p_queue = queue[lo + i];
      p_level = level;
      have_pending = true;
    }
    first = false;
    if (!has_sid && !intern) ++st->unassociated;
  }
}

// One trace's column set, unpacked from the packed base-address array
// the Python side caches per CHUNK (native._writer_args). Order is the
// wire contract with _WRITER_COLS/_WIRE_DTYPES: [0]=seg_id(i64)
// [1]=internal(u8) [2]=start(f64) [3]=end(f64) [4]=length(i32)
// [5]=queue(i32) [6]=begin_idx(i32) [7]=end_idx(i32) [8]=way_off(i64)
// [9]=ways(i64). Ten separate pointer params would be marshalled by
// ctypes on EVERY per-trace call — measured at more than the
// serialisation itself — so the addresses travel as one array whose
// storage the caller owns for the duration of the call.
struct WireCols {
  const int64_t* seg_id;
  const uint8_t* internal;
  const double* start;
  const double* end_;
  const int32_t* length;
  const int32_t* queue;
  const int32_t* begin_idx;
  const int32_t* end_idx;
  const int64_t* way_off;
  const int64_t* ways;
};

inline WireCols unpack_cols(const int64_t* a) {
  return WireCols{reinterpret_cast<const int64_t*>(a[0]),
                  reinterpret_cast<const uint8_t*>(a[1]),
                  reinterpret_cast<const double*>(a[2]),
                  reinterpret_cast<const double*>(a[3]),
                  reinterpret_cast<const int32_t*>(a[4]),
                  reinterpret_cast<const int32_t*>(a[5]),
                  reinterpret_cast<const int32_t*>(a[6]),
                  reinterpret_cast<const int32_t*>(a[7]),
                  reinterpret_cast<const int64_t*>(a[8]),
                  reinterpret_cast<const int64_t*>(a[9])};
}

}  // namespace jsonwire

extern "C" {

// repr(float) bytes into out (>= 32 bytes); returns the length. The
// formatting-parity unit-test surface for the two writers below.
int64_t rt_json_double(double v, uint8_t* out) {
  return jsonwire::json_double(v, reinterpret_cast<char*>(out));
}

// {"segments":[...],"mode":<mode_json>} for run columns [lo, hi).
// Returns bytes written, or -1 when cap is too small (caller grows and
// retries). mode_json is the pre-encoded JSON token for the mode value.
int64_t rt_render_segments_json(
    const void* col_addrs, int64_t lo, int64_t hi,
    const char* mode_json, int64_t mode_len, void* out, int64_t cap) {
  const jsonwire::WireCols c = jsonwire::unpack_cols(
      static_cast<const int64_t*>(col_addrs));
  jsonwire::JBuf b{reinterpret_cast<char*>(out), cap};
  jsonwire::render_segments(b, c.seg_id, c.internal, c.start, c.end_,
                            c.length, c.queue, c.begin_idx, c.end_idx,
                            c.way_off, c.ways, lo, hi, mode_json,
                            mode_len);
  return b.of ? -1 : b.n;
}

}  // extern "C"

namespace jsonwire {

// One trace's whole /report response body for run columns [lo, hi):
// stats + optional shape_used + segment_matcher echo + datastore
// reports, in service/report.py report_json's exact byte layout —
// shared by the per-trace ABI call and the whole-chunk batch call.
inline void emit_report(JBuf& b, const WireCols& c, int64_t lo,
                        int64_t hi, double trace_end,
                        double threshold_sec, uint32_t report_mask,
                        uint32_t transition_mask) {
  const int64_t* seg_id = c.seg_id;
  const uint8_t* internal = c.internal;
  const double* start = c.start;
  const double* end_ = c.end_;
  const int32_t* length = c.length;
  const int32_t* queue = c.queue;
  const int32_t* begin_idx = c.begin_idx;
  const int32_t* end_idx = c.end_idx;
  const int64_t* way_off = c.way_off;
  const int64_t* ways = c.ways;
  ScanStats st;
  scan_segments(seg_id, internal, start, end_, length, queue,
                begin_idx, end_idx, lo, hi, trace_end, threshold_sec,
                report_mask, transition_mask, &st, nullptr);
  b.lit("{\"stats\":{\"successful_matches\":{\"count\":");
  b.i64(st.successful);
  b.lit(",\"length\":");
  b.f(jsonwire::py_round3(st.successful_km));
  b.lit("},\"unreported_matches\":{\"count\":");
  b.i64(st.unreported);
  b.lit(",\"length\":");
  b.f(jsonwire::py_round3(st.unreported_km));
  b.lit("},\"match_errors\":{\"discontinuities\":");
  b.i64(st.discontinuities);
  b.lit(",\"invalid_speeds\":");
  b.i64(st.invalid_speeds);
  b.lit(",\"invalid_times\":");
  b.i64(st.invalid_times);
  b.lit("},\"unassociated_segments\":");
  b.i64(st.unassociated);
  b.ch('}');
  if (st.shape_used > 0) {  // falsy-omitted, like report() (index 0 too)
    b.lit(",\"shape_used\":");
    b.i64(st.shape_used);
  }
  b.lit(",\"segment_matcher\":");
  render_segments(b, seg_id, internal, start, end_, length, queue,
                  begin_idx, end_idx, way_off, ways, lo, hi,
                  "\"auto\"", 6);
  b.lit(",\"datastore\":{\"mode\":\"auto\",\"reports\":[");
  ScanStats st2;
  scan_segments(seg_id, internal, start, end_, length, queue, begin_idx,
                end_idx, lo, hi, trace_end, threshold_sec, report_mask,
                transition_mask, &st2, &b);
  b.lit("]}}");
}

}  // namespace jsonwire

extern "C" {

// One trace's /report body for run columns [lo, hi). Returns bytes
// written, or -1 when cap is too small (caller grows and retries).
// report/transition masks carry levels 0..7 as bits (level =
// segment_id & 7).
int64_t rt_report_json(
    const void* col_addrs, int64_t lo, int64_t hi,
    double trace_end, double threshold_sec, int32_t report_mask,
    int32_t transition_mask, void* out, int64_t cap) {
  const jsonwire::WireCols c = jsonwire::unpack_cols(
      static_cast<const int64_t*>(col_addrs));
  jsonwire::JBuf b{reinterpret_cast<char*>(out), cap};
  jsonwire::emit_report(b, c, lo, hi, trace_end, threshold_sec,
                        static_cast<uint32_t>(report_mask),
                        static_cast<uint32_t>(transition_mask));
  return b.of ? -1 : b.n;
}

// The whole CHUNK's /report bodies in one call and one contiguous
// buffer: trace t (of n_traces, in run_off order) covers run columns
// [run_off[t], run_off[t+1]) with its own trace_ends[t]; its body is
// out[offsets[t], offsets[t+1]) — the per-trace slices the service
// hands to sockets zero-copy (service/wire.py memoises the buffer per
// chunk, so concurrent requests batched into one decode also share
// ONE serialisation call). Returns total bytes, or -1 when cap is too
// small (offsets[] contents are then unspecified; caller retries).
int64_t rt_report_json_batch(
    const void* col_addrs, const void* run_off_p,
    const void* trace_ends_p, int64_t n_traces, double threshold_sec,
    int32_t report_mask, int32_t transition_mask, void* out,
    int64_t cap, void* offsets_p) {
  const jsonwire::WireCols c = jsonwire::unpack_cols(
      static_cast<const int64_t*>(col_addrs));
  const int64_t* run_off = static_cast<const int64_t*>(run_off_p);
  const double* trace_ends = static_cast<const double*>(trace_ends_p);
  int64_t* offsets = static_cast<int64_t*>(offsets_p);
  jsonwire::JBuf b{reinterpret_cast<char*>(out), cap};
  for (int64_t t = 0; t < n_traces; ++t) {
    offsets[t] = b.n;
    jsonwire::emit_report(b, c, run_off[t], run_off[t + 1],
                          trace_ends[t], threshold_sec,
                          static_cast<uint32_t>(report_mask),
                          static_cast<uint32_t>(transition_mask));
    if (b.of) return -1;
  }
  offsets[n_traces] = b.n;
  return b.n;
}

}  // extern "C"

// ---- RGT1 graph-tile parser (reporter_tpu/graph/tilestore.py layout) ----
// The native analog of the reference's C++ tile reader (SURVEY.md §2.3):
// header "RGT1" + u32 version + i64 n_nodes/n_edges/n_segments, then the
// column arrays little-endian in declaration order.

namespace {
constexpr int64_t kRgtHeaderSize = 4 + 4 + 3 * 8;

template <typename T>
bool rgt_copy(const uint8_t* buf, int64_t len, int64_t& off, T* out,
              int64_t count) {
  const int64_t bytes = count * static_cast<int64_t>(sizeof(T));
  if (off + bytes > len) return false;
  std::memcpy(out, buf + off, bytes);
  off += bytes;
  return true;
}
}  // namespace

extern "C" {

// Fills counts from the header. Returns 0 on success, nonzero on a
// malformed tile. Counts are validated against the blob length so a
// corrupt header can neither drive huge allocations in the caller nor
// overflow the per-column size math below.
int32_t rt_tile_counts(const uint8_t* buf, int64_t len, int64_t* n_nodes,
                       int64_t* n_edges, int64_t* n_segs) {
  if (len < kRgtHeaderSize || std::memcmp(buf, "RGT1", 4) != 0) return 1;
  uint32_t version;
  std::memcpy(&version, buf + 4, 4);
  if (version != 1) return 2;
  std::memcpy(n_nodes, buf + 8, 8);
  std::memcpy(n_edges, buf + 16, 8);
  std::memcpy(n_segs, buf + 24, 8);
  if (*n_nodes < 0 || *n_edges < 0 || *n_segs < 0) return 3;
  // each count also fits in the blob on its own, so the exact-size sum
  // below cannot overflow int64
  if (*n_nodes > len || *n_edges > len || *n_segs > len) return 3;
  const int64_t expect = kRgtHeaderSize + *n_nodes * (8 + 8 + 8) +
                         *n_edges * (4 + 4 + 4 + 4 + 8 + 4 + 1) +
                         *n_segs * (8 + 4);
  if (expect != len) return 3;
  return 0;
}

// Copies every column into caller-allocated arrays sized from
// rt_tile_counts. Returns 0 on success, nonzero on truncation/trailing
// bytes.
int32_t rt_tile_parse(const uint8_t* buf, int64_t len, int64_t* node_gid,
                      double* node_lat, double* node_lon,
                      int32_t* edge_start, int32_t* edge_end,
                      float* edge_length_m, float* edge_speed_kph,
                      int64_t* edge_segment_id, float* edge_segment_offset_m,
                      uint8_t* edge_internal, int64_t* seg_ids,
                      float* seg_lens) {
  int64_t N, E, S;
  const int32_t rc = rt_tile_counts(buf, len, &N, &E, &S);
  if (rc != 0) return rc;
  int64_t off = kRgtHeaderSize;
  if (!rgt_copy(buf, len, off, node_gid, N)) return 4;
  if (!rgt_copy(buf, len, off, node_lat, N)) return 4;
  if (!rgt_copy(buf, len, off, node_lon, N)) return 4;
  if (!rgt_copy(buf, len, off, edge_start, E)) return 4;
  if (!rgt_copy(buf, len, off, edge_end, E)) return 4;
  if (!rgt_copy(buf, len, off, edge_length_m, E)) return 4;
  if (!rgt_copy(buf, len, off, edge_speed_kph, E)) return 4;
  if (!rgt_copy(buf, len, off, edge_segment_id, E)) return 4;
  if (!rgt_copy(buf, len, off, edge_segment_offset_m, E)) return 4;
  if (!rgt_copy(buf, len, off, edge_internal, E)) return 4;
  if (!rgt_copy(buf, len, off, seg_ids, S)) return 4;
  if (!rgt_copy(buf, len, off, seg_lens, S)) return 4;
  return off == len ? 0 : 5;
}

}  // extern "C"
