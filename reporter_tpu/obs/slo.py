"""Per-stage latency SLOs: p99 targets that flip /health degraded.

``REPORTER_TPU_SLO_MS`` declares targets as a comma-separated spec::

    service.handle=250,matcher.prep=50,dispatch.match_many=120

Each entry names a stage timer and its p99 budget in milliseconds. The
/health probe calls :func:`check`; a stage whose histogram p99 exceeds
its budget is a breach, and any breach turns /health 503 — the same
load-balancer rotate-away signal an open circuit sends, but driven by
the latency distribution instead of hard failures (a stage can be
"working" and still 10x over budget).

A malformed spec is reported in the check result and logged, but never
degrades health by itself — a typo'd SLO string must not rotate a
healthy fleet out of service (the same fail-open posture as a typo'd
``REPORTER_TPU_FAULTS`` spec staying disarmed).
"""
from __future__ import annotations

import logging
import os
from typing import Dict, Optional

from ..utils import metrics

logger = logging.getLogger("reporter_tpu.obs")

ENV_VAR = "REPORTER_TPU_SLO_MS"

_cache_spec: Optional[str] = None
_cache_parsed: Dict[str, float] = {}


def parse_spec(spec: str) -> Dict[str, float]:
    """``stage=ms[,stage=ms...]`` -> {stage: budget seconds}; raises
    ValueError on any malformed entry."""
    out: Dict[str, float] = {}
    for entry in spec.split(","):
        entry = entry.strip()
        if not entry:
            continue
        stage, sep, ms = entry.partition("=")
        if not sep or not stage.strip():
            raise ValueError(f"bad SLO entry {entry!r} (want stage=ms)")
        try:
            budget_ms = float(ms)
        except ValueError:
            raise ValueError(f"bad SLO budget in {entry!r} "
                             "(want milliseconds)") from None
        if budget_ms <= 0:
            raise ValueError(f"SLO budget must be > 0 in {entry!r}")
        out[stage.strip()] = budget_ms / 1000.0
    return out


def thresholds() -> Dict[str, float]:
    """The armed targets from the environment ({} when unset); the
    parse is cached per spec string (health probes are frequent)."""
    global _cache_spec, _cache_parsed
    spec = os.environ.get(ENV_VAR, "")
    if spec == _cache_spec:
        return _cache_parsed
    try:
        parsed = parse_spec(spec) if spec else {}
    except ValueError as e:
        # fail open AND counted: once per new spec value (this branch
        # is the cache-miss path), so the warning is visible on
        # /metrics without a health probe inflating it per call
        metrics.count("slo.malformed")
        logger.error("ignoring malformed %s=%r: %s", ENV_VAR, spec, e)
        parsed = {}
    _cache_spec, _cache_parsed = spec, parsed
    return parsed


def check(registry: Optional[metrics.Registry] = None) -> dict:
    """{"targets": {stage: budget_s}, "breaches": [...]} — a breach is
    a stage whose histogram p99 exceeds its budget. Stages with no
    observations yet never breach (an idle stage is not a slow one)."""
    targets = thresholds()
    if not targets:
        return {"targets": {}, "breaches": []}
    snap = (registry if registry is not None
            else metrics.default).snapshot()["timers"]
    breaches = [
        {"stage": stage,
         "p99_s": round(snap[stage]["p99_s"], 6),
         "slo_s": budget_s,
         "count": snap[stage]["count"]}
        for stage, budget_s in sorted(targets.items())
        if stage in snap and snap[stage]["count"]
        and snap[stage]["p99_s"] > budget_s]
    return {"targets": targets, "breaches": breaches}
