"""Observability: request tracing, latency histograms, Prometheus
exposition, and the crash-surviving flight recorder.

The reference's only telemetry is a 10k-message throughput log line
(KeyedFormattingProcessor.java:36-38); SURVEY.md §5 lists
tracing/profiling as an absent subsystem to build fresh. This package
is that subsystem's second half (utils/metrics.py grew the histogram
timers): per-request causality and a postmortem you can read after a
crash.

- :mod:`trace` — ``trace_id``/``span_id`` contexts (contextvar
  propagated, ONE module-flag check when disarmed — the same discipline
  as :mod:`..utils.faults`), spans threaded through the service,
  dispatcher, matcher lanes, native prep phases and tile egress, and a
  Chrome/Perfetto trace-event exporter.
- :mod:`flightrec` — a bounded in-memory ring of recent span events,
  dumped atomically (utils/fsio.py) to ``<deadletter>/.flightrec`` on
  circuit-open, dead-letter spool, unhandled worker exceptions and
  ``faults`` crash sites, so the postmortem names the exact span that
  was in flight at SIGKILL.
- :mod:`prom` — ``/metrics`` Prometheus text exposition rendered
  straight from the metrics registry (counters -> ``_total``,
  histogram timers -> ``_bucket``/``_sum``/``_count``).
- :mod:`slo` — per-stage p99 targets (``REPORTER_TPU_SLO_MS``) that
  flip ``/health`` degraded on breach.
- :mod:`profiler` — the device-facing half (ISSUE 8): per-shape XLA
  compile telemetry with recompile-storm detection, per-chunk
  bucket-occupancy/padding-waste wide events served on ``/profile``,
  and sampled shadow decoding against the numpy oracle
  (``REPORTER_TPU_SHADOW_SAMPLE``).
- :mod:`ledger` — the perf-ledger library normalising every committed
  bench artifact into ``LEDGER.jsonl`` entries (ratios + stage
  shares, never absolutes) for ``tools/perf_gate.py``'s CI
  regression gate.

Import order matters: only the metrics-free modules load eagerly here
(utils.metrics itself imports :mod:`trace` so every ``metrics.timer``
site doubles as a span site); :mod:`prom` and :mod:`slo` depend on
utils.metrics and are imported where used.
"""
from . import flightrec, trace  # noqa: F401

__all__ = ["trace", "flightrec"]
