"""The perf ledger: normalised bench history as one entry schema.

The repo accumulated perf history as loose artifacts — ``BENCH_r0*``
(driver rounds), ``BENCH_DEV_r0*`` (developer-recorded runs, sometimes
several legs per file), ``MULTICHIP_r0*`` (mesh harness verdicts) — in
four different JSON shapes that no CI stage read. This module is the
library half of the fix (``tools/perf_ledger.py`` is the CLI,
``tools/perf_gate.py`` the CI regression gate): every artifact
flattens to one line of ``LEDGER.jsonl``:

    {"source": file, "label": round, "kind": bench|bench_dev|multichip,
     "scope": full|smoke, "platform": cpu|tpu|None,
     "decode": scan|assoc|None, "pipelined": bool|None,
     "vs_baseline": ratio|None, "traces_per_sec": N|None,
     "baseline_tps": N|None, "stage_shares": {stage: s/total}|None,
     "n_devices": N|None, "ok": bool|None, "context": note|None}

Three rules the gate depends on:

- **Ratios, never absolutes.** Bench boxes drift ~2x between rounds
  (BENCH_DEV_r06's context block measured it), so entries carry
  ``vs_baseline`` (batched/baseline on the SAME box) and per-stage
  *shares* of wall — the numbers that survive a box change.
- **Like scope only.** A bench_smoke-sized run (tiny batch, one
  repeat) has a structurally lower ratio than a 512-trace run —
  batching amortisation hasn't kicked in (measured: 0.57 at 48 traces
  on a 2-core CI box vs 18+ at 512 on dev boxes) — so entries carry a
  ``scope`` and the gate never cross-compares. Likewise a stage whose
  *measurement scope* changed (PR 4 folded response serialisation
  into ``report``) drops its legacy share rather than comparing two
  different quantities.
- **Context rides along.** Each artifact's box-drift note is carried
  into the entry verbatim, so a future reader of a surprising ratio
  sees the caveat next to the number.
"""
from __future__ import annotations

import glob
import json
import os
import re
from typing import List, Optional

DEFAULT_LEDGER = "LEDGER.jsonl"

#: stages whose share of total wall the gate compares (the bench
#: breakdown's stable subset; prep phase sub-splits are diagnostic)
SHARE_STAGES = ("prep", "decode_dispatch", "decode_wait", "assemble",
                "report")

_METRIC_RE = re.compile(r"platform=(\w+), decode=(\w+)")


def stage_shares(stages: Optional[dict]) -> Optional[dict]:
    """Per-stage share of total wall from a bench ``stages`` block;
    None when the block is missing or carries no total. Shares on a
    pipelined run can sum past 1.0 (stages overlap) — the gate only
    compares like-pipelined entries."""
    if not stages:
        return None
    total = stages.get("total")
    if not total:
        return None
    out = {}
    for name in SHARE_STAGES:
        val = stages.get(name)
        if isinstance(val, (int, float)):
            out[name] = round(val / total, 4)
    return out or None


def entry_from_bench(parsed: dict, source: str, label: str, kind: str,
                     context: Optional[str] = None) -> dict:
    """One ledger entry from a bench.py output object."""
    metric = parsed.get("metric") or ""
    m = _METRIC_RE.search(metric)
    stages = parsed.get("stages") or {}
    baseline = parsed.get("baseline") or {}
    pipelined = stages.get("pipelined")
    shares = stage_shares(stages)
    # PR 4 widened the bench's ``report`` stage to include full
    # response serialisation (the metric string says
    # "report-serialise" since). A legacy entry's report share is a
    # DIFFERENT measurement — gating the new scope against it reads as
    # a 4x regression that never happened — so it is dropped, not
    # compared. Every other stage kept its scope.
    if shares and "report-serialise" not in metric:
        shares.pop("report", None)
    # run scale: tiny runs gate only against tiny-run history (see
    # module doc)
    base_n = (baseline.get("n_traces")
              if isinstance(baseline.get("n_traces"), int) else None)
    scope = "smoke" if base_n is not None and base_n < 64 else "full"
    entry = {
        "source": source,
        "label": label,
        "kind": kind,
        "scope": scope,
        "platform": m.group(1) if m else None,
        "decode": m.group(2) if m else None,
        "pipelined": pipelined if isinstance(pipelined, bool) else None,
        "vs_baseline": parsed.get("vs_baseline"),
        "traces_per_sec": parsed.get("value"),
        "baseline_tps": baseline.get("traces_per_sec"),
        "stage_shares": shares,
        "n_devices": None,
        "ok": parsed.get("vs_baseline") is not None,
        "context": context,
    }
    # the adaptive-bucket before/after pair (ISSUE 13): fixed-ladder vs
    # adaptive padding waste over the same mixed-length batch — a true
    # same-box ratio pair, gated by perf_gate --max-padding-waste
    if isinstance(parsed.get("bucketing"), dict):
        entry["bucketing"] = parsed["bucketing"]
    # the serving-tier batched-query pair (ISSUE 14): query_many(256)
    # vs 256 single queries over the same store — another same-box
    # ratio pair, gated by perf_gate --min-query-ratio
    if isinstance(parsed.get("query"), dict):
        entry["query"] = parsed["query"]
    # the route-kernel triple (ISSUE 16): device relax vs host Dijkstra
    # vs native memo on identical pairs (parity asserted before timing)
    # — the device/host ratio is the prep_routes speedup the pipelined
    # shares should reflect
    if isinstance(parsed.get("routes"), dict):
        entry["routes"] = parsed["routes"]
    return entry


def _failed_entry(source: str, label: str, kind: str, tail: str) -> dict:
    return {"source": source, "label": label, "kind": kind,
            "scope": "full", "platform": None, "decode": None,
            "pipelined": None, "vs_baseline": None,
            "traces_per_sec": None, "baseline_tps": None,
            "stage_shares": None, "n_devices": None, "ok": False,
            "context": ("run failed: "
                        + (tail.strip().splitlines() or ["?"])[-1][:200])}


def _multichip_entry(source: str, d: dict) -> dict:
    """One ledger entry from a MULTICHIP artifact. r01-r05 carry only
    ``ok: true`` — a liveness verdict with no measurement — and are
    tagged ``scope: legacy`` so no gate median ever pools them with
    measured runs (the like-for-like pool starts at the first artifact
    whose legs assert ``devices_seen``); tools/multichip_bench.py
    artifacts add per-device-count legs and throughput ratios —
    ``vs_baseline`` then holds the max-device-count ratio over the
    1-device leg (a true same-box ratio, like every other entry) and
    ``traces_per_sec`` the max-device leg's absolute, with the full
    ratio curve in context. A measured artifact whose legs never saw
    their requested device count (the r06 failure mode) is also tagged
    legacy: its ratios compare nothing. Gate with ``tools/perf_gate.py
    --multichip`` (the kind is excluded from the bench comparable pool,
    so these ratios never bleed into the vs_baseline medians)."""
    ratios = d.get("ratios") or {}
    legs = d.get("legs") or []
    top = max((leg for leg in legs
               if leg.get("traces_per_sec")),
              key=lambda leg: leg["n_devices"], default=None)
    vs = ratios.get(str(d.get("n_devices"))) if ratios else None
    measured = bool(ratios) and all(
        leg.get("devices_seen") == leg.get("n_devices") for leg in legs)
    context = None
    if ratios:
        context = "device ratios vs 1: " + ",".join(
            f"{k}x={v}" for k, v in sorted(ratios.items(),
                                           key=lambda kv: int(kv[0])))
        if not measured:
            context += ("; LEGACY: legs disagree with their requested "
                        "device counts (devices_seen) — ratios compare "
                        "nothing")
    elif not d.get("ok"):
        context = f"rc={d.get('rc')}; harness leg failed or timed out"
    return {"source": source,
            "label": source.replace("MULTICHIP_", "").replace(".json",
                                                              ""),
            "kind": "multichip",
            "scope": "full" if measured else "legacy",
            "platform": None, "decode": None, "pipelined": None,
            "vs_baseline": vs if measured else None,
            "traces_per_sec": top["traces_per_sec"] if top else None,
            "baseline_tps": None, "stage_shares": None,
            "n_devices": d.get("n_devices"), "ok": bool(d.get("ok")),
            "context": context}


def _bigreplay_entry(source: str, d: dict) -> dict:
    """One ledger entry from a tools/bigreplay.py artifact (the ISSUE
    15 scaled-probe legs). ``vs_baseline`` holds the chaos-over-clean
    throughput ratio — a true same-process, same-box ratio like every
    other entry — and the context carries the oracle agreement and
    probe scale. Kind ``bigreplay`` is excluded from the bench
    comparable pool (tools/perf_gate.py ``comparable_pool``), so these
    ratios never bleed into vs_baseline medians; gate them with
    ``perf_gate --bigreplay --min-fault-ratio`` instead. Scope follows
    the probe count: the 100k+ local leg is ``full``, CI-scale runs
    are ``smoke`` (never cross-compared, same rule as bench)."""
    probes = d.get("probes") or 0
    ratio = d.get("fault_throughput_ratio")
    clean = d.get("clean") or {}
    return {"source": source,
            "label": source.replace("BIGREPLAY_", "")
            .replace(".json", ""),
            "kind": "bigreplay",
            "scope": "full" if probes >= 100_000 else "smoke",
            "platform": "cpu", "decode": None, "pipelined": None,
            "vs_baseline": ratio,
            "traces_per_sec": clean.get("probes_per_s"),
            "baseline_tps": None, "stage_shares": None,
            "n_devices": None, "ok": bool(ratio),
            "context": f"probes={probes} agreement={d.get('agreement')}"
                       f" writers={d.get('writers')}"}


def _feed_entry(source: str, d: dict) -> dict:
    """One ledger entry from a tools/feed_fanout_bench.py artifact
    (the ISSUE 18 freshness-tier fan-out leg). ``vs_baseline`` holds
    the fanout ratio — delivered subscribers over subscribers, 1.0
    when every long-poll received the measured commit — and the
    context carries delivery p99 and the shed/loss accounting. Kind
    ``feed_fanout`` is excluded from the bench comparable pool
    (tools/perf_gate.py ``comparable_pool``); gate with ``perf_gate
    --feed`` instead. Scope follows subscriber count: the >= 1000
    acceptance leg is ``full``, CI-scale runs are ``smoke``."""
    subs = d.get("subscribers") or 0
    return {"source": source,
            "label": source.replace("BENCH_", "").replace(".json", ""),
            "kind": "feed_fanout",
            "scope": "full" if subs >= 1000 else "smoke",
            "platform": "cpu", "decode": None, "pipelined": None,
            "vs_baseline": d.get("fanout_ratio"),
            "traces_per_sec": None,
            "baseline_tps": None, "stage_shares": None,
            "n_devices": None,
            "ok": d.get("silent_lost") == 0 and not d.get("errors"),
            "context": f"subscribers={subs} procs={d.get('procs')}"
                       f" delivered={d.get('delivered')}"
                       f" shed={d.get('shed')}"
                       f" shed_events={d.get('shed_events')}"
                       f" errors={d.get('errors')}"
                       f" silent_lost={d.get('silent_lost')}"
                       f" p99_ms={d.get('delivery_p99_ms')}"}


def _stream_entry(source: str, d: dict) -> dict:
    """One ledger entry from a tools/stream_bench.py artifact (the
    ISSUE 19 incremental-matcher leg). ``vs_baseline`` holds the
    flatness ratio — per-appended-point decode p99 at the longest
    window over the shortest, <= 1.5 meaning the carried-state cost is
    flat in T while the context's ``growth`` shows the whole-window
    path scaling with it — and ``ok`` pins the zero-parity-mismatch
    contract. Kind ``streaming`` is excluded from the bench comparable
    pool (tools/perf_gate.py ``comparable_pool``); gate with
    ``perf_gate --streaming`` instead. Scope follows the longest
    window: the T=256 acceptance leg is ``full``, shorter smoke runs
    are ``smoke``."""
    legs = d.get("legs") or {}
    t_max = max((int(t) for t in legs), default=0)
    big = legs.get(str(t_max), {})
    return {"source": source,
            "label": source.replace("BENCH_", "").replace(".json", ""),
            "kind": "streaming",
            "scope": "full" if t_max >= 256 else "smoke",
            "platform": "cpu", "decode": "incremental",
            "pipelined": None,
            "vs_baseline": d.get("flatness_ratio"),
            "traces_per_sec": None,
            "baseline_tps": None, "stage_shares": None,
            "n_devices": None,
            "ok": d.get("parity_mismatches") == 0,
            "context": f"windows={sorted(int(t) for t in legs)}"
                       f" lag={d.get('lag')}"
                       f" dec_p99_ms={big.get('dec_p99_ms')}"
                       f" match_p99_ms={big.get('inc_p99_ms')}"
                       f" growth={d.get('batch_growth')}"
                       f" speedup_p50={d.get('speedup_p50_at_256')}"
                       f" mismatches={d.get('parity_mismatches')}"}


def seed_entries(repo: str) -> List[dict]:
    """Normalise every checked-in perf artifact into ledger entries."""
    entries: List[dict] = []

    # driver rounds: {"n", "cmd", "rc", "tail", "parsed"}
    for path in sorted(glob.glob(os.path.join(repo, "BENCH_r*.json"))):
        name = os.path.basename(path)
        label = name.replace("BENCH_", "").replace(".json", "")
        with open(path, encoding="utf-8") as f:
            d = json.load(f)
        parsed = d.get("parsed")
        if parsed:
            entries.append(entry_from_bench(parsed, name, label, "bench"))
        else:
            entries.append(_failed_entry(name, label, "bench",
                                         d.get("tail", "")))

    # developer rounds: heterogeneous; handle each recorded shape
    dev4 = os.path.join(repo, "BENCH_DEV_r04.json")
    if os.path.exists(dev4):
        with open(dev4, encoding="utf-8") as f:
            d = json.load(f)
        note = (d.get("note") or "")[:300]
        if d.get("result"):
            entries.append(entry_from_bench(
                d["result"], "BENCH_DEV_r04.json", "dev_r04",
                "bench_dev", context=note))
        cont = d.get("continuation_session") or {}
        if cont.get("result"):
            entries.append(entry_from_bench(
                cont["result"], "BENCH_DEV_r04.json", "dev_r04_cont",
                "bench_dev", context=(cont.get("note") or "")[:300]))

    dev4t = os.path.join(repo, "BENCH_DEV_r04_tpu.json")
    if os.path.exists(dev4t):
        with open(dev4t, encoding="utf-8") as f:
            d = json.load(f)
        note = (d.get("note") or "")[:300]
        for leg in ("pre_pipeline", "post_pipeline"):
            if d.get(leg):
                entries.append(entry_from_bench(
                    d[leg], "BENCH_DEV_r04_tpu.json", f"dev_r04_{leg}",
                    "bench_dev", context=note))

    # r06 onward share one shape: {"parsed": <bench artifact>,
    # "serialized_breakdown": {"value", "stages"}, "context": {"box"}}
    # — two entries per file: the pipelined headline and the
    # serialized stage breakdown (whose ratio shares the parsed leg's
    # baseline run — same box, so it is the r05-comparable number)
    for path in sorted(glob.glob(os.path.join(repo,
                                              "BENCH_DEV_r*.json"))):
        name = os.path.basename(path)
        label_n = name.replace("BENCH_DEV_", "").replace(".json", "")
        if label_n in ("r04", "r04_tpu"):
            continue  # the heterogeneous legacy shapes handled above
        with open(path, encoding="utf-8") as f:
            d = json.load(f)
        box_note = (d.get("context") or {}).get("box")
        # same-session drift control (r10+): the prior configuration
        # re-benched on the same box; perf_gate uses it to tell box
        # drift from a code regression when the ratio lands below the
        # cross-box floor
        ctrl_vs = (d.get("control") or {}).get("vs_baseline") \
            if isinstance(d.get("control"), dict) else None
        if d.get("parsed"):
            e = entry_from_bench(
                d["parsed"], name, f"dev_{label_n}", "bench_dev",
                context=box_note)
            if ctrl_vs is not None:
                e["control_vs_baseline"] = ctrl_vs
            entries.append(e)
        ser = d.get("serialized_breakdown") or {}
        parsed = d.get("parsed") or {}
        base = (parsed.get("baseline") or {}).get("traces_per_sec")
        if ser.get("value") and base:
            shares = stage_shares(ser.get("stages"))
            if shares and "report-serialise" not in \
                    (parsed.get("metric") or ""):
                shares.pop("report", None)  # pre-PR-4 report scope
            # a handful of checked-in artifacts at seed time, not a
            # serving path
            se = {  # lint: ignore[HP002]
                "source": name,
                "label": f"dev_{label_n}_serialized",
                "kind": "bench_dev",
                "scope": "full",
                "platform": "cpu", "decode": "scan",
                "pipelined": False,
                "vs_baseline": round(ser["value"] / base, 2),
                "traces_per_sec": ser["value"],
                "baseline_tps": base,
                "stage_shares": shares,
                "n_devices": None, "ok": True,
                "context": box_note,
            }
            if ctrl_vs is not None:
                se["control_vs_baseline"] = ctrl_vs
            entries.append(se)

    # multichip harness verdicts: {"n_devices", "rc", "ok", ...}
    for path in sorted(glob.glob(os.path.join(repo,
                                              "MULTICHIP_r*.json"))):
        with open(path, encoding="utf-8") as f:
            d = json.load(f)
        entries.append(_multichip_entry(os.path.basename(path), d))

    # bigreplay scaled-probe verdicts (ISSUE 15): the chaos/clean
    # throughput ratio + agreement at production-fidelity scale
    for path in sorted(glob.glob(os.path.join(repo,
                                              "BIGREPLAY_r*.json"))):
        with open(path, encoding="utf-8") as f:
            d = json.load(f)
        entries.append(_bigreplay_entry(os.path.basename(path), d))

    # change-feed fan-out verdicts (ISSUE 18): subscriber delivery
    # accounting + latency through the pre-fork fleet
    for path in sorted(glob.glob(os.path.join(repo,
                                              "BENCH_FEED_r*.json"))):
        with open(path, encoding="utf-8") as f:
            d = json.load(f)
        entries.append(_feed_entry(os.path.basename(path), d))

    # incremental-matcher streaming verdicts (ISSUE 19): per-appended-
    # point decode flatness + parity against the whole-window oracle
    for path in sorted(glob.glob(os.path.join(repo,
                                              "BENCH_STREAM_r*.json"))):
        with open(path, encoding="utf-8") as f:
            d = json.load(f)
        entries.append(_stream_entry(os.path.basename(path), d))
    return entries


def load_ledger(path: str) -> List[dict]:
    entries = []
    with open(path, encoding="utf-8") as f:
        for i, line in enumerate(f, start=1):
            line = line.strip()
            if not line:
                continue
            try:
                entries.append(json.loads(line))
            except json.JSONDecodeError as e:
                raise ValueError(f"{path}:{i}: not JSON: {e}") from None
    return entries


def write_ledger(path: str, entries: List[dict]) -> None:
    with open(path, "w", encoding="utf-8") as f:
        for e in entries:
            f.write(json.dumps(e, separators=(",", ":")) + "\n")
