"""Flight recorder: a bounded ring of recent span events, dumped on
failure so a crash leaves a postmortem.

PR 5 built a whole chaos harness around crashing the worker, but the
only evidence a SIGKILL'd process leaves is its state snapshot — what
the process was *doing* at death is gone. This module keeps the last
``RING_EVENTS`` closed spans and every still-open span in memory
(populated by :mod:`.trace` whenever tracing is armed, zero cost
otherwise) and dumps them atomically (:mod:`..utils.fsio` — a torn
postmortem is worse than none) to ``<dump dir>/flightrec-<pid>-<seq>-
<reason>.json`` at the failure sites that matter:

- ``faults`` crash failpoints, immediately before ``os._exit`` — the
  dump's ``in_flight`` list names the exact span the SIGKILL landed in
  (asserted by the chaos harness's kill/restore scenario)
- circuit-breaker open transitions (utils/circuit.py)
- dead-letter spools (a tile body or trace JSON headed for the spool
  means an outage worth a postmortem)
- unhandled streaming-worker exceptions

The dump directory defaults to ``.flightrec`` under the worker's
dead-letter spool (set by :class:`~..streaming.worker.StreamWorker`);
``REPORTER_TPU_FLIGHTREC`` overrides it with an explicit directory, or
disables dumping outright with ``0``. With no directory resolved,
dumps are skipped — the ring still serves the ``?trace=1`` exporter.
"""
from __future__ import annotations

import collections
import json
import os
import time
from typing import Dict, List, Optional

from ..utils import fsio
from ..utils import locks as _locks

ENV_VAR = "REPORTER_TPU_FLIGHTREC"

#: closed-span ring capacity; at ~14 spans per request this is the last
#: ~290 requests of context — enough to see what led up to a failure,
#: and enough that one ``?trace=1`` request's own spans survive a busy
#: server's concurrent traffic until export (the ring is process-global;
#: a request overlapped by more than ~290 others exports best-effort)
RING_EVENTS = 4096

#: ring and open-table writes AND reads hold the lock: a lone deque
#: append is atomic, but iterating a deque while a concurrent append
#: lands raises RuntimeError — the same race the profiler ring fixed in
#: PR 8, audited here by the Guarded wrappers (racecheck RC003)
_lock = _locks.new_lock("flightrec")
_ring = _locks.Guarded(collections.deque(maxlen=RING_EVENTS), _lock,
                       "flightrec.ring")
_open = _locks.Guarded({}, _lock, "flightrec.open")
_dump_dir: Optional[str] = None
_dir_from_env = False
_disabled = False
_seq = 0


def _configure_env() -> None:
    global _dump_dir, _dir_from_env, _disabled
    val = os.environ.get(ENV_VAR, "").strip()
    with _lock:
        if val.lower() in ("0", "off", "false"):
            _disabled = True
        elif val:
            _dump_dir = val
            _dir_from_env = True


def set_dump_dir(path: str) -> None:
    """Adopt a dump directory (the worker's ``<deadletter>/.flightrec``)
    unless the environment already pinned one — an operator override
    must win over the derived default."""
    global _dump_dir
    with _lock:
        if not _dir_from_env:
            _dump_dir = path


def dump_dir() -> Optional[str]:
    with _lock:
        return None if _disabled else _dump_dir


# ---- ring maintenance (called by trace.py, armed only) ---------------------

def span_opened(span_id: int, record: dict) -> None:
    with _lock:
        _open[span_id] = record


def span_closed(span_id: int, dur_ns: int) -> None:
    with _lock:
        record = _open.pop(span_id, None)
        if record is not None:
            record["dur_ns"] = dur_ns
            _ring.append(record)


def record_closed(records: List[dict]) -> None:
    """Append already-closed span records (synthetic phase spans)."""
    with _lock:
        _ring.extend(records)


def events() -> List[dict]:
    """Closed spans, oldest first (a snapshot copy)."""
    with _lock:
        return list(_ring)


def in_flight() -> List[dict]:
    """Open spans right now, with their age stamped in."""
    now_ns = time.time_ns()
    with _lock:
        open_now = list(_open.values())
    return [{**r, "age_ns": max(0, now_ns - r["t0_ns"])} for r in open_now]


def reset() -> None:
    """Drop ring + open table (tests)."""
    with _lock:
        _open.clear()
        _ring.clear()


# ---- the postmortem --------------------------------------------------------

def dump(reason: str, extra: Optional[dict] = None) -> Optional[str]:
    """Write the postmortem; returns its path, or None when disabled or
    no dump directory is resolved. Never raises — every caller is
    already on a failure path (one of them is about to ``os._exit``)."""
    global _seq
    try:
        with _lock:
            if _disabled or _dump_dir is None:
                return None
            _seq += 1
            seq = _seq
            out_dir = _dump_dir
        safe = "".join(c if c.isalnum() or c in "._-" else "_"
                       for c in reason)[:80]
        payload = {
            "reason": reason,
            "pid": os.getpid(),
            "ts_ns": time.time_ns(),
            "in_flight": in_flight(),
            "spans": events(),
        }
        if extra:
            payload["extra"] = extra
        from ..utils import metrics  # lazy: metrics imports obs.trace
        # export_state's counter copy, not snapshot(): no percentile
        # math on a failure path that may be racing an os._exit
        payload["counters"] = metrics.default.export_state()[0]
        # the last few decode chunks' wide events (occupancy, compile,
        # queue depth) next to the span ring: what the device was
        # chewing on when the process died. Best-effort in its OWN
        # guard: an enrichment failure (e.g. module globals already
        # torn down at interpreter exit) must cost the wide events,
        # never the whole postmortem.
        try:
            from . import profiler  # lazy: profiler imports metrics
            payload["wide_events"] = profiler.recent_events(16)
        except Exception:
            payload["wide_events"] = []
        os.makedirs(out_dir, exist_ok=True)
        path = os.path.join(out_dir,
                            f"flightrec-{os.getpid()}-{seq:04d}-{safe}.json")
        fsio.atomic_write_text(path, json.dumps(payload,
                                                separators=(",", ":")))
        metrics.count("flightrec.dumps")
        return path
    except Exception:  # pragma: no cover - postmortem must never kill
        return None


_configure_env()

# fork safety: a forked worker's postmortem must carry ITS spans — the
# inherited ring and open-span table describe work the parent did. The
# dump-dir config survives (forked workers share the deployment's spool;
# dump names are pid-qualified, so files never collide across workers).
from ..utils import forksafe as _forksafe  # noqa: E402

_forksafe.register(reset)
