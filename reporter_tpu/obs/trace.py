"""Request tracing: trace_id/span_id contexts and trace-event export.

A span is one timed stage of one request's life: the service opens a
root span per ``/report`` request, and every ``metrics.timer`` site
(dispatch, prep, decode, assemble, report serialisation, tile egress)
nests a child span under it automatically, so the existing stage-timer
discipline IS the span tree. Spans propagate through a contextvar;
thread hops (the dispatcher queue, the matcher's device lanes) carry
the context explicitly via :func:`current`/:func:`attach` because a
queue handoff does not copy contexts.

Cost discipline (same as :mod:`..utils.faults`): when disarmed, every
span site pays ONE module-flag load — :func:`span` returns a shared
no-op context manager, :func:`current` returns None without touching
the contextvar. Arming is either persistent (``REPORTER_TPU_TRACE=1``
in the environment, or :func:`configure`) or per-request
(:func:`force_begin`/:func:`force_end`, the ``?trace=1`` debug flag —
the flag arms the whole process for the request's lifetime, so spans
on worker threads record too, and the exporter filters by trace id).

Completed spans land in :mod:`flightrec`'s bounded ring — the same
ring the crash postmortem dumps — and :func:`export_trace` renders one
trace's spans as Chrome/Perfetto trace-event JSON (``ph:"X"`` complete
events, epoch-microsecond timestamps, so they line up with an XLA
profile captured by ``metrics.device_trace``).
"""
from __future__ import annotations

import contextvars
import itertools
import os
import threading
import time
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

from ..utils import locks as _locks
from . import flightrec

ENV_VAR = "REPORTER_TPU_TRACE"

_ENABLED = False   # the one flag every disarmed span site loads
_ARMED = False     # persistent arming (env / configure)
_FORCED = 0        # ?trace=1 requests currently in flight
_lock = _locks.new_lock("trace.arm")

#: (trace_id, span_id) of the innermost open span in this context
_ctx: "contextvars.ContextVar[Optional[Tuple[str, int]]]" = \
    contextvars.ContextVar("reporter_tpu_trace", default=None)

#: process-unique span ids (itertools.count is atomic under the GIL)
_ids = itertools.count(1)

#: maps perf_counter_ns timestamps onto wall-clock epoch ns, so span
#: timestamps are comparable across processes and with an XLA profile
_EPOCH_OFFSET_NS = time.time_ns() - time.perf_counter_ns()


def _recompute_locked() -> None:
    global _ENABLED
    _ENABLED = _ARMED or _FORCED > 0


def configure(on: bool) -> None:
    """Persistently arm/disarm tracing (the env flag's in-process twin)."""
    global _ARMED
    with _lock:
        _ARMED = bool(on)
        _recompute_locked()


def force_begin() -> None:
    """Arm tracing for one in-flight request (``?trace=1``)."""
    global _FORCED
    with _lock:
        _FORCED += 1
        _recompute_locked()


def force_end() -> None:
    global _FORCED
    with _lock:
        _FORCED = max(0, _FORCED - 1)
        _recompute_locked()


def enabled() -> bool:
    return _ENABLED


class _Noop:
    """Shared do-nothing span/attach: the disarmed fast path allocates
    nothing and enters/exits in two attribute calls."""

    __slots__ = ()
    trace_id = None
    span_id = 0

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NOOP = _Noop()


def new_trace_id() -> str:
    """Process-qualified trace id (pid keeps ids unique across the
    worker fleet without any coordination)."""
    return f"{os.getpid():x}-{next(_ids):012x}"


class _Span:
    __slots__ = ("name", "attrs", "trace_id", "span_id", "parent_id",
                 "_token", "_t0", "dur_ns")

    def __init__(self, name: str, attrs: Optional[dict]):
        self.name = name
        self.attrs = attrs
        self.dur_ns = 0

    def __enter__(self):
        cur = _ctx.get()
        if cur is None:
            self.trace_id = new_trace_id()
            self.parent_id = 0
        else:
            self.trace_id, self.parent_id = cur
        self.span_id = next(_ids)
        self._token = _ctx.set((self.trace_id, self.span_id))
        self._t0 = time.perf_counter_ns()
        flightrec.span_opened(self.span_id, {
            "name": self.name, "trace_id": self.trace_id,
            "span_id": self.span_id, "parent_id": self.parent_id,
            "t0_ns": self._t0 + _EPOCH_OFFSET_NS,
            "tid": threading.get_ident(),
            **({"attrs": self.attrs} if self.attrs else {})})
        return self

    def __exit__(self, *exc):
        self.dur_ns = time.perf_counter_ns() - self._t0
        _ctx.reset(self._token)
        flightrec.span_closed(self.span_id, self.dur_ns)
        return False


def span(name: str, **attrs):
    """A timed span context. Disarmed: one flag check, a shared no-op."""
    if not _ENABLED:
        return _NOOP
    return _Span(name, attrs or None)


def current() -> Optional[Tuple[str, int]]:
    """The (trace_id, span_id) context to carry across a thread hop;
    None when disarmed or outside any span."""
    if not _ENABLED:
        return None
    return _ctx.get()


class _Attach:
    __slots__ = ("ctx", "_token")

    def __init__(self, ctx: Tuple[str, int]):
        self.ctx = ctx

    def __enter__(self):
        self._token = _ctx.set(self.ctx)
        return self

    def __exit__(self, *exc):
        _ctx.reset(self._token)
        return False


def attach(ctx: Optional[Tuple[str, int]]):
    """Adopt a context captured by :func:`current` on another thread
    (the dispatcher loop, the matcher's device lanes)."""
    if ctx is None:
        return _NOOP
    return _Attach(ctx)


def phase_spans(names: Sequence[str], ns_list: Sequence[int]) -> None:
    """Synthesize back-to-back child spans ending now from phase
    durations measured inside an opaque call — the ABI-11 native prep
    ``phase_ns`` split becomes ``prep.candidates``/``select``/``routes``
    child spans without a second timing source. Phases overlap across
    prep worker threads, so the reconstruction is the serialised view
    (flagged ``synthetic`` in the attrs)."""
    if not _ENABLED:
        return
    cur = _ctx.get()
    if cur is None:
        return
    pairs = [(n, int(ns)) for n, ns in zip(names, ns_list) if ns > 0]
    if not pairs:
        return
    trace_id, parent_id = cur
    tid = threading.get_ident()
    end_ns = time.perf_counter_ns() + _EPOCH_OFFSET_NS
    offsets = list(itertools.accumulate(ns for _, ns in pairs))
    base_ns = end_ns - offsets[-1]
    flightrec.record_closed([
        {"name": name, "trace_id": trace_id, "span_id": next(_ids),
         "parent_id": parent_id, "t0_ns": base_ns + off - ns,
         "dur_ns": ns, "tid": tid, "attrs": {"synthetic": True}}
        for (name, ns), off in zip(pairs, offsets)])


# ---- export ----------------------------------------------------------------

def events_for(trace_id: str) -> List[dict]:
    """Closed span records for one trace, oldest first, from the ring."""
    return [r for r in flightrec.events() if r["trace_id"] == trace_id]


def to_trace_events(records: Iterable[dict],
                    in_flight: Iterable[dict] = ()) -> Dict[str, object]:
    """Chrome/Perfetto trace-event JSON object: completed spans as
    ``ph:"X"`` events (epoch-µs timestamps, µs durations), still-open
    spans as ``ph:"B"`` begin events — load the dict's JSON in
    ``chrome://tracing`` or https://ui.perfetto.dev."""
    pid = os.getpid()
    events = [
        {"name": r["name"], "ph": "X", "cat": "reporter_tpu",
         "pid": r.get("pid", pid), "tid": r["tid"],
         "ts": r["t0_ns"] / 1e3, "dur": r["dur_ns"] / 1e3,
         "args": {"trace_id": r["trace_id"], "span_id": r["span_id"],
                  "parent_id": r["parent_id"], **r.get("attrs", {})}}
        for r in records]
    events += [
        {"name": r["name"], "ph": "B", "cat": "reporter_tpu",
         "pid": r.get("pid", pid), "tid": r["tid"],
         "ts": r["t0_ns"] / 1e3,
         "args": {"trace_id": r["trace_id"], "span_id": r["span_id"],
                  "parent_id": r["parent_id"], "in_flight": True,
                  **r.get("attrs", {})}}
        for r in in_flight]
    return {"traceEvents": events, "displayTimeUnit": "ms"}


def export_trace(root) -> Dict[str, object]:
    """The trace-event JSON for the trace a root span belongs to (the
    ``?trace=1`` response payload); empty when the span never armed."""
    if root is None or getattr(root, "trace_id", None) is None:
        return {"traceEvents": [], "displayTimeUnit": "ms"}
    return to_trace_events(events_for(root.trace_id))


def _configure_env() -> None:
    val = os.environ.get(ENV_VAR, "").strip().lower()
    if val and val not in ("0", "off", "false"):
        configure(True)


_configure_env()
