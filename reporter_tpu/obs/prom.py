"""Prometheus text exposition rendered straight from the metrics
registry.

``/metrics`` serves exposition-format 0.0.4 text: every counter becomes
``reporter_tpu_<name>_total`` and every histogram timer becomes the
``reporter_tpu_<name>_seconds`` ``_bucket``/``_sum``/``_count`` family,
with the power-of-2 bucket bounds from :mod:`..utils.metrics` as the
``le`` labels. No client library, no collectors: the registry's one
``export_state()`` copy is the scrape, so a scrape can never observe a
half-updated histogram.

Every metric name this framework emits is declared in
``analysis/registry.py`` (two-sided MT001/MT002 lint), so a dashboard
built on the names here cannot silently rot when code renames one.
"""
from __future__ import annotations

import re
from typing import List, Optional

from ..utils import metrics

PREFIX = "reporter_tpu"

_INVALID = re.compile(r"[^a-zA-Z0-9_:]")


def sanitize(name: str) -> str:
    """A metric-registry name as a Prometheus metric name component
    (dots and dashes become underscores)."""
    return _INVALID.sub("_", name)


def _fmt(v: float) -> str:
    """A float sample value in exposition format (repr round-trips,
    which is all Prometheus asks)."""
    return repr(float(v))


def render(registry: Optional[metrics.Registry] = None) -> str:
    """The full exposition body for one registry (default: the process
    registry). Deterministic ordering — sorted by name — so scrapes
    diff cleanly and the golden test can pin the format."""
    reg = registry if registry is not None else metrics.default
    counters, timers = reg.export_state()
    lines: List[str] = []
    for name in sorted(counters):
        base = f"{PREFIX}_{sanitize(name)}_total"
        lines.append(f"# TYPE {base} counter")
        lines.append(f"{base} {counters[name]}")
    for name in sorted(timers):
        count, total_s, _max_s, buckets = timers[name]
        base = f"{PREFIX}_{sanitize(name)}_seconds"
        lines.append(f"# TYPE {base} histogram")
        cum = 0
        for bound, n in zip(metrics.BUCKET_BOUNDS_S, buckets):
            cum += n
            lines.append(f'{base}_bucket{{le="{_fmt(bound)}"}} {cum}')
        lines.append(f'{base}_bucket{{le="+Inf"}} {count}')
        lines.append(f"{base}_sum {_fmt(total_s)}")
        lines.append(f"{base}_count {count}")
    return "\n".join(lines) + "\n"


CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"
