"""Device-level profiling: compile telemetry, bucket-occupancy wide
events, and shadow-accuracy sampling.

PR 7 gave the pipeline request-level eyes (spans, histograms, the
flight recorder); the compute layer below it stayed dark. This module
is the device-facing half:

- **Compile telemetry.** Every decode dispatch runs under
  :func:`dispatch_span`, which attributes ``jax.monitoring`` backend-
  compile events to the dispatching shape ``(B, T, K, platform)``. A
  dispatch during which any compile fired is a *compile episode*:
  counted (``decode.compile.count``), timed (``decode.compile``), and
  — when the SAME shape compiles a second time — flagged as a
  recompile storm (``decode.compile.recompiles`` + a log warning: a
  steady-state service recompiling a known shape is losing whole
  seconds to XLA, usually a jit-cache eviction or a drifting aux
  input). Dispatch wall time splits into ``decode.dispatch.first``
  (episodes that paid a compile) and ``decode.dispatch.steady``.
- **Wide events.** One bounded ring of per-chunk records (the
  "everything about this chunk on one line" discipline): bucket T, K,
  real traces vs padded rows, kept points vs padded ``rows*T`` point
  cells, the padding-waste ratio the fixed LENGTH_BUCKETS pay (the
  number that decides bucket tuning and the FLASH variable-length
  work), queue depth at dispatch, route-memo/cache hit snapshots, and
  the PR 7 ``trace_id`` when tracing is armed — so a slow traced
  request joins to the exact chunks that served it. Served by the
  service's ``/profile`` action; per-bucket occupancy histograms ride
  the metrics registry (``decode.occupancy.t<T>``) onto ``/stats``
  and ``/metrics``.
- **Shadow-accuracy sampling.** ``REPORTER_TPU_SHADOW_SAMPLE=0.05``
  re-decodes ~5% of chunks through the numpy oracle
  (matcher/cpu_ref.py) on ONE background thread, off the hot path, and
  compares *path quality* (f64 re-score — the device and the oracle
  may break exact score ties differently, which is agreement, not
  error). ``decode.shadow.{sampled,mismatch}`` counters export the
  verdicts; the per-chunk mismatch ratio lands in the
  ``decode.shadow.mismatch_ratio`` histogram so a
  ``REPORTER_TPU_SLO_MS`` budget on it flips ``/health`` 503 through
  the PR 7 machinery (the ratio rides the timer histogram: a budget of
  ``1000`` "ms" = ratio 1.0).

Cost discipline: chunk accounting is per *chunk* (hundreds of traces),
not per trace — a handful of scalar ops and one deque append. The
compile listener registers once, lazily, on the first dispatch; when
jax.monitoring is absent the telemetry degrades to the first-call
timing split (an episode is then inferred from nothing — compile
counts stay 0 — rather than guessed).
"""
from __future__ import annotations

import collections
import logging
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..utils import locks as _locks
from ..utils import metrics
from ..utils.runtime import _env_float, _env_int
from . import trace as obs_trace

logger = logging.getLogger("reporter_tpu.obs")

ENV_SHADOW = "REPORTER_TPU_SHADOW_SAMPLE"
ENV_RING = "REPORTER_TPU_PROFILE_EVENTS"

#: score agreement tolerance for the shadow oracle, in f64 log-score
#: units — the same bound the device/oracle equivalence tests use
#: (ties may break differently; equal-quality paths are agreement)
SHADOW_SCORE_TOL = 1e-2

#: shadow chunks allowed in flight before sampling sheds load (the
#: sampler must never become its own backlog)
_SHADOW_MAX_PENDING = 4

_lock = _locks.new_lock("profiler")

#: (B, T, K, platform) -> per-shape stats dict (see dispatch_span)
_shapes: Dict[Tuple[int, int, int, str], dict] = {}

#: the wide-event ring; writes AND reads hold _lock (iterating a deque
#: mid-append raises), audited by the Guarded wrapper (racecheck RC003).
#: Sized once from the env at import, resizable via reset() for tests.
_events = _locks.Guarded(
    collections.deque(maxlen=max(16, _env_int(ENV_RING, 512))),
    _lock, "profiler.events")

_tls = threading.local()  # .active: [compile_calls, compile_s] or None

_listener_registered = False
_platform_cache: Optional[str] = None
#: per-dispatcher queue-depth gauges, keyed by dispatcher name. A
#: process can run several dispatchers (city stacks, tests); one
#: last-writer-wins scalar made them overwrite each other, and a
#: pre-fork child inherited the parent's stale depth — the registry is
#: cleared by the forksafe hook below so each worker gauges ITS queues
_queue_depths: Dict[str, int] = {}
_total_kept = 0           # running occupancy totals (point slots)
_total_cells = 0
#: per-bucket-T running [kept, cells] — the recorded waste the adaptive
#: bucket splitter acts on (SegmentMatcher._split_bucket)
_bucket_totals: Dict[int, list] = {}
_compile_episodes = 0

_shadow_acc = 0.0         # deterministic sampling accumulator
_shadow_pending = 0
_shadow_pool: Optional[ThreadPoolExecutor] = None
_shadow_sampled = 0
_shadow_mismatch = 0
#: pressure-ladder rung (service/admission.py "shed_shadow"): sampling
#: suspended under sustained overload — the oracle thread's CPU goes
#: back to serving. Suspensions are counted, never silent.
_shadow_suspended = False


# ---- compile telemetry -----------------------------------------------------

def _on_event_duration(name: str, dur_s: float, **_kw) -> None:
    """jax.monitoring listener: credit backend compiles to whichever
    dispatch is active on this thread (compilation is synchronous in
    the dispatching thread, so thread-local attribution is exact)."""
    if not name.endswith("backend_compile_duration"):
        return
    acc = getattr(_tls, "active", None)
    if acc is not None:
        acc[0] += 1
        acc[1] += dur_s


def _ensure_listener() -> None:
    global _listener_registered
    if _listener_registered:
        return
    with _lock:
        if _listener_registered:
            return
        try:
            import jax.monitoring
            jax.monitoring.register_event_duration_secs_listener(
                _on_event_duration)
        except Exception:  # pragma: no cover - jax is baked in
            logger.warning("jax.monitoring unavailable; compile "
                           "telemetry degrades to dispatch timing only")
        _listener_registered = True


def _platform() -> str:
    global _platform_cache
    if _platform_cache is None:
        try:
            import jax
            p = jax.default_backend()
        except Exception:  # pragma: no cover
            p = "unknown"
        with _lock:
            _platform_cache = p
    return _platform_cache


class _DispatchSpan:
    """Times one decode dispatch and attributes compile events to its
    shape; updates the shape table and the decode.* metrics on exit."""

    __slots__ = ("B", "T", "K", "_acc", "_t0")

    def __init__(self, B: int, T: int, K: int):
        self.B = B
        self.T = T
        self.K = K

    def __enter__(self):
        _ensure_listener()
        self._acc = [0, 0.0]
        _tls.active = self._acc
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, exc_type, exc, tb):
        elapsed = time.perf_counter() - self._t0
        _tls.active = None
        if exc_type is not None:
            # an aborted dispatch's wall is time-to-failure, not
            # latency: recording it would pollute the steady-state
            # histograms and seed shape entries with failure timings
            return False
        calls, compile_s = self._acc
        compiled = calls > 0
        # the backend AND the mesh width are part of the compiled-shape
        # identity: switching REPORTER_TPU_DECODE (bench's pallas leg,
        # an operator A/B) — or a (B, T, K) that recompiles because the
        # decode mesh changed (device slice, DECODE_SHARD flip) — is a
        # new shape, not a recompile storm
        try:
            from ..ops import decode_backend, shard_width
            backend = decode_backend(self.T, self.K)
            mesh = shard_width(self.B, self.T, backend)
        except Exception:  # pragma: no cover - ops is always importable
            backend, mesh = "?", 1
        key = (self.B, self.T, self.K, _platform(), backend, mesh)
        global _compile_episodes
        with _lock:
            st = _shapes.get(key)
            if st is None:
                st = _shapes[key] = {
                    "B": self.B, "T": self.T, "K": self.K,
                    "platform": key[3], "backend": backend,
                    "mesh": mesh,
                    "dispatches": 0, "compiles": 0,
                    "compile_calls": 0, "compile_s": 0.0,
                    "first_s": elapsed, "steady_n": 0,
                    "steady_total_s": 0.0, "steady_max_s": 0.0}
            st["dispatches"] += 1
            recompiled = False
            if compiled:
                recompiled = st["compiles"] >= 1
                st["compiles"] += 1
                st["compile_calls"] += calls
                st["compile_s"] += compile_s
                _compile_episodes += 1
            else:
                st["steady_n"] += 1
                st["steady_total_s"] += elapsed
                if elapsed > st["steady_max_s"]:
                    st["steady_max_s"] = elapsed
        # metrics outside the lock (the registry has its own)
        if compiled:
            metrics.count("decode.compile.count")
            metrics.observe("decode.compile", compile_s)
            metrics.observe("decode.dispatch.first", elapsed)
            if recompiled:
                metrics.count("decode.compile.recompiles")
                logger.warning(
                    "recompile storm: decode shape B=%d T=%d K=%d "
                    "(%s/%s mesh=%d) compiled again (%d episodes, "
                    "%.0f ms this time) — a steady-state service "
                    "should compile each shape once", self.B, self.T,
                    self.K, key[3], backend, mesh, st["compiles"],
                    compile_s * 1e3)
        else:
            metrics.observe("decode.dispatch.steady", elapsed)
        return False


def dispatch_span(B: int, T: int, K: int) -> _DispatchSpan:
    """Wrap one decode dispatch (the matcher's dispatch lane)."""
    return _DispatchSpan(B, T, K)


# ---- wide events -----------------------------------------------------------

def note_queue_depth(depth: int, name: str = "dispatch") -> None:
    """Dispatcher backlog after draining a batch, per NAMED dispatcher
    — sampled into each wide event as "queue depth at dispatch"."""
    with _lock:
        _queue_depths[name] = int(depth)


def queue_depth(name: Optional[str] = None) -> int:
    """One dispatcher's last-noted depth, or — with no name — the max
    across every registered gauge (the wide events' scalar: the worst
    backlog is the one that matters under pressure)."""
    with _lock:
        if name is not None:
            return _queue_depths.get(name, 0)
        return max(_queue_depths.values(), default=0)


def queue_depths() -> Dict[str, int]:
    """Every named gauge (the /profile per-dispatcher view)."""
    with _lock:
        return dict(_queue_depths)


def _reset_queue_depths() -> None:
    """Forksafe hook: a pre-fork child starts with an empty gauge
    registry — the parent's dispatcher depths describe queues the
    child does not own (its own dispatchers re-note after their first
    drain)."""
    with _lock:
        _queue_depths.clear()


def chunk_event(bucket_T: int, K: int, traces: int, rows: int,
                kept_points: int, raw_points: int,
                cache: Optional[dict] = None,
                path: str = "native") -> None:
    """Record one decode chunk's wide event (called once per chunk by
    the matcher's dispatch paths — a handful of scalars, one append).

    ``rows`` is the padded batch dimension (mesh/pow2 filler included),
    so ``rows * bucket_T`` is the point-slot grid the device actually
    decodes; ``kept_points`` is how many of those slots carry a real
    (kept) probe point. The waste ratio is what adaptive/variable
    bucketing (FLASH) would reclaim.
    """
    # the ONE occupancy formula, shared with the pinning tests (lazy
    # import: batchpad sits under matcher/, which imports this module)
    from ..matcher.batchpad import occupancy_stats
    global _total_kept, _total_cells
    cells, occupancy, waste = occupancy_stats(kept_points, rows,
                                              bucket_T)
    ctx = obs_trace.current()
    event = {
        "ts_ms": int(time.time() * 1000),
        "trace_id": ctx[0] if ctx is not None else None,
        "path": path,
        "bucket_T": int(bucket_T),
        "K": int(K),
        "traces": int(traces),
        "rows": int(rows),
        "raw_points": int(raw_points),
        "kept_points": int(kept_points),
        "padded_cells": int(cells),
        "occupancy": round(occupancy, 6),
        "padding_waste": round(waste, 6),
        "queue_depth": queue_depth(),
    }
    if cache:
        event["cache"] = cache
    with _lock:
        # ring writes AND reads hold the lock: a lone deque append is
        # atomic, but iterating a deque raises RuntimeError when a
        # concurrent append lands mid-iteration — and recent_events()
        # feeds both /profile and the flight-recorder crash dump.
        # (extend, not append: the lockgraph pass resolves bare-name
        # calls package-wide, and `append` under a lock reads as
        # HistogramStore.append — a builtin deque method is invisible
        # to it either way, so use the spelling with no collision)
        _events.extend((event,))
        _total_kept += int(kept_points)
        _total_cells += int(cells)
        tot = _bucket_totals.get(int(bucket_T))
        if tot is None:
            tot = _bucket_totals[int(bucket_T)] = [0, 0]
        tot[0] += int(kept_points)
        tot[1] += int(cells)
    metrics.count("profile.chunks")
    # per-bucket occupancy histogram: the ratio rides the fixed
    # log-bucket timer machinery (units are ratio, not seconds) so
    # /stats gets p50/p95/p99 occupancy per bucket and /metrics a
    # scrapeable histogram family per bucket
    metrics.observe(f"decode.occupancy.t{int(bucket_T)}", occupancy)


def recent_events(n: Optional[int] = 16) -> List[dict]:
    """The last ``n`` wide events, oldest first (a snapshot copy).
    ``n=0`` means none, ``None`` means the whole ring."""
    with _lock:
        evs = list(_events)
    if n is None:
        return evs
    return evs[-n:] if n > 0 else []


def padding_waste() -> Optional[float]:
    """Lifetime padding-waste ratio across every recorded chunk; None
    before the first chunk."""
    with _lock:
        if not _total_cells:
            return None
        return 1.0 - _total_kept / _total_cells


def bucket_waste(bucket_T: int) -> Optional[float]:
    """Recorded padding-waste ratio for one bucket shape — what the
    dispatcher's adaptive splitter consults before breaking a chunk
    into finer sub-buckets; None before the first chunk of that T."""
    with _lock:
        tot = _bucket_totals.get(int(bucket_T))
        if not tot or not tot[1]:
            return None
        return 1.0 - tot[0] / tot[1]


def compile_count() -> int:
    with _lock:
        return _compile_episodes


# ---- shadow-accuracy sampling ----------------------------------------------

def shadow_fraction() -> float:
    return max(0.0, _env_float(ENV_SHADOW, 0.0))


def set_shadow_suspended(on: bool) -> None:
    """Pressure-ladder rung (service/admission.py): suspend / resume
    shadow-accuracy sampling. Under the lock only for write-discipline
    consistency with reset(); readers take one global load."""
    global _shadow_suspended
    with _lock:
        _shadow_suspended = bool(on)


def _ensure_shadow_pool() -> ThreadPoolExecutor:
    global _shadow_pool
    with _lock:
        if _shadow_pool is None:
            _shadow_pool = ThreadPoolExecutor(
                max_workers=1, thread_name_prefix="shadow-decode")
        return _shadow_pool


def maybe_shadow(batch, decoded: np.ndarray, n_real: int,
                 sigma: float, beta: float) -> None:
    """Sample this chunk for shadow decoding (deterministic accumulator
    — a fraction of 0.25 samples exactly every 4th chunk). The oracle
    runs on one background thread; when it falls behind, chunks are
    shed (counted) rather than queued without bound."""
    frac = shadow_fraction()
    if frac <= 0.0 or n_real <= 0:
        return
    if _shadow_suspended:
        # the shed_shadow pressure rung: sampling paused, accounted —
        # the accumulator does not advance, so easing pressure resumes
        # the configured cadence, not a burst of catch-up chunks
        metrics.count("decode.shadow.suppressed")
        return
    global _shadow_acc, _shadow_pending
    with _lock:
        _shadow_acc += min(frac, 1.0)
        if _shadow_acc < 1.0:
            return
        _shadow_acc -= 1.0
        if _shadow_pending >= _SHADOW_MAX_PENDING:
            shed = True
        else:
            shed = False
            _shadow_pending += 1
    if shed:
        metrics.count("decode.shadow.dropped")
        return
    try:
        pool = _ensure_shadow_pool()
        pool.submit(_shadow_job, batch.dist_m, batch.valid,
                    batch.route_m, batch.gc_m, batch.case,
                    np.asarray(decoded), n_real, float(sigma),
                    float(beta))
    except Exception as e:
        # submit itself can fail (thread exhaustion, interpreter
        # shutdown); the sampler must never take down serving, and the
        # reserved pending slot must not leak (4 leaks would shed every
        # future chunk and hang drain_shadow)
        with _lock:
            _shadow_pending -= 1
        metrics.count("decode.shadow.errors")
        logger.error("shadow submit failed (chunk skipped): %s", e)


def _path_score_f64(dist_row, route_row, gc_row, case_row, path,
                    sigma: float, beta: float, n: int,
                    normal_code: int, unreachable: float) -> float:
    """Re-score a decoded path in f64, independent of either decoder's
    accumulation order (vectorised twin of the equivalence tests'
    scorer). Returns -inf when the path crosses an unroutable
    transition — always a mismatch."""
    if n <= 0:
        return 0.0
    p = np.asarray(path[:n], dtype=np.int64)
    d = dist_row[np.arange(n), p].astype(np.float64)
    total = float((-0.5 * (d / sigma) ** 2).sum())
    if n > 1:
        steps = np.arange(1, n)
        normal = np.asarray(case_row[1:n]) == normal_code
        r = route_row[steps - 1, p[:-1], p[1:]].astype(np.float64)
        if bool((r[normal] >= unreachable).any()):
            return float("-inf")
        dev = np.abs(r - np.asarray(gc_row[:n - 1], dtype=np.float64))
        total += float(np.where(normal, -dev / beta, 0.0).sum())
    return total


def _shadow_job(dist, valid, route, gc, case, decoded, n_real: int,
                sigma: float, beta: float) -> None:
    global _shadow_sampled, _shadow_mismatch, _shadow_pending
    try:
        # lazy: cpu_ref sits under matcher/, which imports this module
        from ..matcher.cpu_ref import viterbi_decode_numpy
        from ..matcher.hmm import NORMAL, SKIP, UNREACHABLE_THRESHOLD
        T = dist.shape[1]
        # native batches carry a dead trailing time row (seq sharding);
        # the oracle's contract is (T-1, K, K)
        route = route[:, :max(T - 1, 0)]
        gc = gc[:, :max(T - 1, 0)]
        case = np.asarray(case)
        mismatches = 0
        for b in range(n_real):
            n = int(np.count_nonzero(case[b] != SKIP))
            if n == 0:
                continue
            oracle_path, _ = viterbi_decode_numpy(
                dist[b], valid[b], route[b], gc[b], case[b], sigma, beta)
            s_dev = _path_score_f64(dist[b], route[b], gc[b], case[b],
                                    decoded[b], sigma, beta, n, NORMAL,
                                    UNREACHABLE_THRESHOLD)
            s_np = _path_score_f64(dist[b], route[b], gc[b], case[b],
                                   oracle_path, sigma, beta, n, NORMAL,
                                   UNREACHABLE_THRESHOLD)
            # path QUALITY comparison: a differently-broken exact tie
            # is agreement; a worse-scoring device path is the bug the
            # sampler exists to catch
            if not (abs(s_dev - s_np) <= SHADOW_SCORE_TOL):
                mismatches += 1
        metrics.count("decode.shadow.chunks")
        metrics.count("decode.shadow.sampled", n_real)
        if mismatches:
            metrics.count("decode.shadow.mismatch", mismatches)
            logger.warning(
                "shadow decode: %d/%d traces in a sampled chunk scored "
                "differently from the numpy oracle", mismatches, n_real)
        metrics.observe("decode.shadow.mismatch_ratio",
                        mismatches / n_real)
        with _lock:
            _shadow_sampled += n_real
            _shadow_mismatch += mismatches
    except Exception as e:  # the sampler must never take down serving
        metrics.count("decode.shadow.errors")
        logger.error("shadow decode failed (chunk skipped): %s", e)
    finally:
        with _lock:
            _shadow_pending -= 1


def shadow_stats() -> dict:
    with _lock:
        return {"fraction": shadow_fraction(),
                "sampled": _shadow_sampled,
                "mismatch": _shadow_mismatch,
                "pending": _shadow_pending,
                "suspended": _shadow_suspended}


def shadow_mismatches() -> int:
    with _lock:
        return _shadow_mismatch


def drain_shadow(timeout_s: float = 30.0) -> bool:
    """Block until no shadow chunk is in flight (tests / smoke gates);
    True when drained, False on timeout."""
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        with _lock:
            if _shadow_pending == 0:
                return True
        time.sleep(0.005)
    return False


def shutdown_shadow_pool(timeout_s: float = 30.0) -> bool:
    """Drain in-flight shadow chunks, then JOIN the sampler thread —
    the worker's shutdown-ordering contract (ISSUE 10): no oracle job
    may outlive the spool/datastore handles the final flush is about to
    release. A later :func:`maybe_shadow` lazily recreates the pool
    (multi-worker processes share it). True when the drain completed."""
    global _shadow_pool
    drained = drain_shadow(timeout_s)
    with _lock:
        pool, _shadow_pool = _shadow_pool, None
    if pool is not None:
        pool.shutdown(wait=True)
    return drained


# ---- export ----------------------------------------------------------------

def _shape_view(st: dict) -> dict:
    """One shape-table row as the /profile wire form (first-call vs
    steady-state split folded into a ``steady`` sub-object)."""
    n = st["steady_n"]
    return {
        "B": st["B"], "T": st["T"], "K": st["K"],
        "platform": st["platform"],
        "backend": st["backend"],
        "mesh": st.get("mesh", 1),
        "dispatches": st["dispatches"],
        "compiles": st["compiles"],
        "compile_calls": st["compile_calls"],
        "compile_s": round(st["compile_s"], 6),
        "first_s": round(st["first_s"], 6),
        "steady": {"n": n,
                   "mean_s": round(st["steady_total_s"] / n, 6)
                   if n else 0.0,
                   "max_s": round(st["steady_max_s"], 6)},
    }


def snapshot(n_events: int = 64) -> dict:
    """The ``/profile`` payload: per-shape compile/dispatch stats, the
    last ``n_events`` wide events, lifetime occupancy totals, shadow
    verdicts, and the last-seen dispatcher queue depth."""
    with _lock:
        raw = [dict(st) for st in _shapes.values()]
        kept, cells = _total_kept, _total_cells
        depths = dict(_queue_depths)
        episodes = _compile_episodes
    shapes = [_shape_view(st) for st in raw]
    shapes.sort(key=lambda s: (s["T"], s["K"], s["B"]))
    return {
        "shapes": shapes,
        "compile_episodes": episodes,
        "events": recent_events(n_events),
        "totals": {
            "kept_points": kept,
            "padded_cells": cells,
            "padding_waste": round(1.0 - kept / cells, 6) if cells
            else None},
        "shadow": shadow_stats(),
        "routes": route_kernel_stats(),
        "queue_depth": max(depths.values(), default=0),
        "queue_depths": depths,
    }


def route_kernel_stats() -> dict:
    """Device-vs-host route-stage split for ``/profile``: chunks the
    device kernel served vs chunks that fell back to (or never left)
    the host Dijkstra path, so a prep_routes regression is attributable
    at a glance — a sick device shows up as fallback/error counts, a
    disabled knob as device_chunks == 0."""
    from ..utils import metrics
    c = metrics.default.counter
    return {
        "device_chunks": c("route.device.chunks"),
        "device_pairs": c("route.device.pairs"),
        "device_sources": c("route.device.sources"),
        "sharded_chunks": c("route.device.sharded_chunks"),
        "deferred_chunks": c("route.device.deferred_chunks"),
        "async_dispatch_chunks": c("route.device.async_dispatch_chunks"),
        "cache_hit_rows": c("route.device.cache_hit_rows"),
        "cache_miss_rows": c("route.device.cache_miss_rows"),
        "empty_chunks": c("route.device.empty_chunks"),
        "fallback_chunks": c("route.device.fallback_chunks"),
        "circuit_skipped_chunks": c("route.device.circuit_skipped_chunks"),
        "errors": c("route.device.errors"),
    }


def reset() -> None:
    """Drop every table/ring/total (tests). Re-reads the ring-size env
    so a test can shrink the ring."""
    global _total_kept, _total_cells, _compile_episodes, \
        _shadow_acc, _shadow_pending, _shadow_sampled, _shadow_mismatch, \
        _shadow_suspended, _events
    with _lock:
        _shapes.clear()
        _bucket_totals.clear()
        _queue_depths.clear()
        _shadow_suspended = False
        _total_kept = 0
        _total_cells = 0
        _compile_episodes = 0
        _shadow_acc = 0.0
        _shadow_pending = 0
        _shadow_sampled = 0
        _shadow_mismatch = 0
        _events = _locks.Guarded(
            collections.deque(maxlen=max(16, _env_int(ENV_RING, 512))),
            _lock, "profiler.events")


# fork safety: a pre-fork child must never inherit the parent's
# dispatcher queue-depth gauges (they describe queues the child does
# not own; its own dispatchers re-note after their first drain)
from ..utils import forksafe as _forksafe  # noqa: E402

_forksafe.register(_reset_queue_depths)
