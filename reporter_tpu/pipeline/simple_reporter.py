"""The batched (historical) pipeline: raw probe archives -> traffic tiles.

Capability-parity rebuild of the reference's script pipeline
(reference: py/simple_reporter.py) with the matching stage redesigned for
the TPU. Same three stages, same artifacts:

1. **gather_traces** — list + download part files (S3 via boto3 when
   configured and available, else a local directory), parse each line with
   a user-supplied ``--src-valuer`` lambda, bbox-filter, cap accuracy at
   1000 m, and shard lines into files by ``sha1(uuid)[:3]``
   (reference: :87-129, :256-276). IO-bound -> process fan-out.

2. **match_traces** — per shard: group by uuid, sort by time, split into
   windows at ``--inactivity`` gaps (reference: :149-163). THE redesign:
   instead of one C++ ``Match`` call per window (reference: :164-168 — the
   hot loop), *all windows in a shard go to the device as one padded
   batch* via ``SegmentMatcher.match_many``; ``report()`` post-processing
   and the usable-segment filter are unchanged (:176-177), and rows land
   in ``{bucket_start}_{bucket_end}/{level}/{tile_index}`` files (:178-196).

3. **report_tiles** — per tile file: sort, privacy-cull (id, next_id) runs
   below ``--privacy`` observations, prepend the CSV header, upload/write
   (reference: :211-254).

Stage-level resume via --trace-dir / --match-dir is preserved
(reference: :350-363).
"""
from __future__ import annotations

import argparse
import functools
import glob
import gzip
import hashlib
import logging
import math
import multiprocessing

# worker processes are spawned, not forked: the parent may already run
# JAX's (and jax.distributed's) native threads, and forking a
# multi-threaded process can deadlock the children mid-mutex. Spawned
# children re-import this module, which initialises no XLA backend.
_MP = multiprocessing.get_context("spawn")
import os
import re
import shutil
import sys
import tempfile
import time
from typing import Callable, Iterable, List

from ..core.osmlr import INVALID_SEGMENT_ID, tile_index, tile_level
from ..core.types import Segment

logger = logging.getLogger("reporter_tpu.pipeline")

MAX_ACCURACY_M = 1000  # reference: simple_reporter.py:112


# --------------------------------------------------------------------------
# stage 1: gather
# --------------------------------------------------------------------------

def _parse_part_file(path: str, valuer: Callable, time_pattern: str,
                     bbox: List[float], dest_dir: str) -> int:
    """Parse one downloaded part file into uuid-sharded trace files."""
    # multi-host backfill: when REPORTER_TPU_NUM_PROCESSES/PROCESS_ID are
    # set, each host keeps only its share of the uuid space, so N hosts
    # pointed at the same --src partition the work instead of repeating it
    # (the reference splits days across instances by hand,
    # load-historical-data/README.md)
    from ..parallel import host_uuid_filter
    uuid_filter = host_uuid_filter()
    fast_time = time_pattern == "%Y-%m-%d %H:%M:%S"
    opener = gzip.open if path.endswith(".gz") else open
    shards: dict[str, list[str]] = {}
    count = 0
    with opener(path, "rt") as f:
        for line in f:
            try:
                uuid, tm, lat, lon, acc = valuer(line)
                if uuid_filter is not None and \
                        not uuid_filter(str(uuid)):
                    continue
                lat = float(lat)
                lon = float(lon)
                if lat < bbox[0] or lat > bbox[2] or \
                        lon < bbox[1] or lon > bbox[3]:
                    continue
                if isinstance(tm, str) and not tm.isdigit():
                    if fast_time:
                        st = time.struct_time((
                            int(tm[0:4]), int(tm[5:7]), int(tm[8:10]),
                            int(tm[11:13]), int(tm[14:16]), int(tm[17:19]),
                            0, 0, 0))
                    else:
                        st = time.strptime(tm, time_pattern)
                    import calendar
                    epoch = calendar.timegm(st)
                else:
                    epoch = int(tm)
                acc = min(int(math.ceil(float(acc))), MAX_ACCURACY_M)
            except Exception:
                continue
            shard = hashlib.sha1(str(uuid).encode()).hexdigest()[:3]
            shards.setdefault(shard, []).append(
                f"{uuid},{epoch},{lat},{lon},{acc}\n")
            count += 1
    # one shard file per worker process (suffix = pid): concurrent gather
    # workers never share a file, so no interleaved/torn rows — stage 2
    # walks every file in the directory regardless of suffix
    pid = os.getpid()
    for shard, rows in shards.items():
        with open(os.path.join(dest_dir, f"{shard}.{pid}"), "a") as f:
            f.write("".join(rows))
    return count


def _gather_worker(paths: List[str], valuer_src: str, time_pattern: str,
                   bbox: List[float], dest_dir: str) -> None:
    valuer = eval(valuer_src)  # user-supplied lambda, like the reference
    for path in paths:
        try:
            n = _parse_part_file(path, valuer, time_pattern, bbox, dest_dir)
            logger.info("Gathered %d probes from %s", n, path)
        except Exception as e:
            logger.error("%s was not processed: %s", path, e)


def gather_traces(src: str, key_regex: str, valuer_src: str,
                  time_pattern: str, bbox: List[float],
                  concurrency: int) -> str:
    """Stage 1 driver. ``src`` is a local directory of part files, or an
    ``s3://bucket/prefix`` URL (requires boto3 + credentials)."""
    dest_dir = tempfile.mkdtemp(prefix="traces_", dir=".")
    if src.startswith("s3://"):
        paths = _download_s3(src, key_regex)
    else:
        rx = re.compile(key_regex)
        paths = sorted(
            p for p in glob.glob(os.path.join(src, "**", "*"), recursive=True)
            if os.path.isfile(p) and rx.match(os.path.relpath(p, src)))
    logger.info("Gathering %d part files into %s", len(paths), dest_dir)
    chunks = [paths[i::concurrency] for i in range(concurrency)]
    procs = []
    for chunk in chunks:
        if not chunk:
            continue
        p = _MP.Process(
            target=_gather_worker,
            args=(chunk, valuer_src, time_pattern, bbox, dest_dir))
        p.start()
        procs.append(p)
    for p in procs:
        p.join()
    return dest_dir


def _download_s3(url: str, key_regex: str) -> List[str]:
    try:
        import boto3
    except ImportError:
        raise RuntimeError("s3 source requires boto3, which is unavailable")
    bucket, _, prefix = url[len("s3://"):].partition("/")
    client = boto3.client("s3")
    rx = re.compile(key_regex)
    keys = []
    token = None
    while True:
        kw = {"Bucket": bucket, "Prefix": prefix}
        if token:
            kw["ContinuationToken"] = token
        resp = client.list_objects_v2(**kw)
        keys.extend(o["Key"] for o in resp.get("Contents", []))
        token = resp.get("NextContinuationToken")
        if not token:
            break
    keys = [k for k in keys if rx.match(k)]
    paths = []
    dl_dir = tempfile.mkdtemp(prefix="parts_", dir=".")
    for key in keys:
        path = os.path.join(dl_dir, hashlib.sha1(key.encode()).hexdigest())
        client.download_file(bucket, key, path)
        paths.append(path)
    return paths


# --------------------------------------------------------------------------
# stage 2: match (batched on device)
# --------------------------------------------------------------------------

# longest window sent to the matcher in one request: the largest padding
# bucket (batchpad.LENGTH_BUCKETS[-1]); longer active windows are chunked
# with a trailing-holdback overlap instead of being truncated
MAX_WINDOW_POINTS = 1024


def _window_spans(times, inactivity: int,
                  max_window: int = MAX_WINDOW_POINTS,
                  holdback_s: int = 15) -> Iterable[tuple]:
    """Index spans ``(lo, hi)`` of matcher windows over a sorted times
    array: split at gaps > ``inactivity`` seconds (reference:
    simple_reporter.py:149-163), then chunk long windows with a
    trailing-holdback overlap (see :func:`_windows_of`). Operating on
    index spans keeps the columnar pipeline zero-copy: each window is a
    slice of the uuid's coordinate arrays, never a list of point dicts.
    """
    import numpy as np

    times = np.asarray(times, dtype=np.float64)
    n = len(times)

    def chunked(start: int, end: int) -> Iterable[tuple]:
        while end - start > max_window:
            yield (start, start + max_window)
            end_t = times[start + max_window - 1]
            j = max_window - 1
            while j > 0 and end_t - times[start + j] <= holdback_s:
                j -= 1
            # progress floor: a pathological burst (>max_window points
            # inside one holdback span) must not degrade to 1-point steps
            # and ~N chunks; advancing at least half a window caps the
            # re-presented overlap at 2x total work
            j = max(max_window // 2, min(j, max_window - 1))
            start += j
        if end - start >= 2:
            yield (start, end)

    gap_at = np.flatnonzero(np.diff(times) > inactivity) + 1
    lo = 0
    for g in gap_at.tolist() + [n]:
        if g - lo >= 2:
            yield from chunked(lo, g)
        lo = g


def _windows_of(points: List[dict], inactivity: int,
                max_window: int = MAX_WINDOW_POINTS,
                holdback_s: int = 15) -> Iterable[List[dict]]:
    """Split a uuid's points at gaps > ``inactivity`` seconds
    (reference: simple_reporter.py:149-163).

    Windows longer than ``max_window`` (the device's largest padding
    bucket) are further split into chunks whose overlap covers the last
    ``holdback_s`` seconds of the previous chunk — the same consumed-prefix
    overlap the streaming path gets from ``shape_used`` trimming
    (reference: Batch.java:73-76, reporter_service.py:89-92): report()
    withholds segments inside the trailing holdback, and the next chunk
    re-presents those points, so pairs at the seam are reported exactly
    once with match context preserved. (Dict-list convenience wrapper
    over :func:`_window_spans`, which the columnar stage uses directly.)
    """
    times = [p["time"] for p in points]
    for lo, hi in _window_spans(times, inactivity, max_window, holdback_s):
        yield points[lo:hi]


def match_traces(trace_dir: str, matcher, mode: str,
                 report_levels: set, transition_levels: set,
                 quantisation: int, inactivity: int, source: str,
                 threshold_sec: int = 15,
                 device_batch: int = 512) -> str:
    """Stage 2 driver: shard files -> batched device matching -> tile rows.

    ``matcher`` is a SegmentMatcher (or anything with ``match_many``).
    """
    import numpy as np

    from ..core.tracebatch import TraceBatch
    from ..service.report import report as make_report

    dest_dir = tempfile.mkdtemp(prefix="matches_", dir=".")
    # gather workers write one file per (shard, worker pid); all files with
    # the same sha1-prefix shard belong together so a uuid's points are
    # consolidated no matter which worker parsed them
    by_shard: dict[str, list[str]] = {}
    for r, _d, files in os.walk(trace_dir):
        for f in files:
            by_shard.setdefault(f.split(".")[0], []).append(
                os.path.join(r, f))
    total_traces = 0
    shared_opts = {"mode": mode}
    for shard, paths in sorted(by_shard.items()):
        # columnar parse: per-uuid coordinate LISTS (one append per row,
        # never a point dict), then arrays + argsort per uuid
        by_uuid: dict[str, tuple] = {}
        for path in paths:
            with open(path) as f:
                for line in f:
                    try:
                        uuid, tm, lat, lon, acc = line.strip().split(",")
                        cols = by_uuid.get(uuid)
                        if cols is None:
                            cols = by_uuid[uuid] = ([], [], [], [])
                        cols[0].append(int(tm))
                        cols[1].append(float(lat))
                        cols[2].append(float(lon))
                        cols[3].append(int(acc))
                    except ValueError:
                        continue

        # build every window request in this shard up front, as columnar
        # parts (uuid, lat, lon, time, accuracy, options) over array
        # slices. The chunker's holdback must equal report()'s threshold:
        # report withholds segments starting within threshold_sec of a
        # chunk's end, and the next chunk re-presents exactly that span
        parts = []
        for uuid, (tms, lats, lons, accs) in by_uuid.items():
            tm = np.asarray(tms, dtype=np.float64)
            order = np.argsort(tm, kind="stable")
            tm = tm[order]
            la = np.asarray(lats, dtype=np.float64)[order]
            lo_ = np.asarray(lons, dtype=np.float64)[order]
            ac = np.asarray(accs, dtype=np.float32)[order]
            for a, b in _window_spans(tm, inactivity,
                                      holdback_s=threshold_sec):
                parts.append((uuid, la[a:b], lo_[a:b], tm[a:b], ac[a:b],
                              shared_opts))

        tiles: dict[str, list[str]] = {}
        # exactly-once across chunk seams: a uuid's windows are processed
        # in time order, and pair start times are strictly increasing along
        # a trace, so dropping reports at or below the uuid's
        # highest-emitted t0 removes seam duplicates (and nothing else)
        last_t0: dict[str, float] = {}
        for lo in range(0, len(parts), device_batch):
            tb = TraceBatch.concat(parts[lo:lo + device_batch])
            try:
                matches = matcher.match_many(tb)
            except Exception as e:
                logger.error("Batch match failed for %s: %s", shard, e)
                continue
            for trace, match in zip(tb, matches):
                uuid = trace["uuid"]
                try:
                    rep = make_report(match, trace, threshold_sec,
                                      report_levels, transition_levels)
                except Exception:
                    logger.error("Failed to report trace with uuid %s "
                                 "from file %s", uuid, shard)
                    continue
                floor = last_t0.get(uuid)
                reports = rep["datastore"]["reports"]
                if floor is not None:
                    reports = [r for r in reports if r["t0"] > floor]
                    rep["datastore"]["reports"] = reports
                if reports:
                    last_t0[uuid] = max(r["t0"] for r in reports)
                _emit_rows(rep, trace, quantisation, source, mode, tiles)
        for tile_file, rows in tiles.items():
            path = os.path.join(dest_dir, tile_file)
            os.makedirs(os.path.dirname(path), exist_ok=True)
            with open(path, "a") as f:
                f.writelines(rows)
        total_traces += len(parts)
        logger.info("Finished matching %d windows in %s",
                    len(parts), shard)
    logger.info("Matched %d windows total", total_traces)
    return dest_dir


def _emit_rows(rep: dict, trace: dict, quantisation: int, source: str,
               mode: str, tiles: dict) -> None:
    """Usable reports -> per-bucket tile rows
    (reference: simple_reporter.py:176-196)."""
    points = trace["trace"]
    max_buckets = (points[-1]["time"] - points[0]["time"]) // quantisation + 1
    for r in rep["datastore"]["reports"]:
        if not (r["t0"] > 0 and r["t1"] > 0 and r["t1"] - r["t0"] > 0.5
                and r["length"] > 0 and r["queue_length"] >= 0):
            continue
        duration = int(round(r["t1"] - r["t0"]))
        start = int(math.floor(r["t0"]))
        end = int(math.ceil(r["t1"]))
        lo_b, hi_b = start // quantisation, end // quantisation
        if hi_b - lo_b > max_buckets:
            logger.error("Segment spans %d buckets but should be <= %d",
                         hi_b - lo_b, max_buckets)
            continue
        for b in range(lo_b, hi_b + 1):
            tile_file = os.path.join(
                f"{b * quantisation}_{(b + 1) * quantisation - 1}",
                str(tile_level(r["id"])), str(tile_index(r["id"])))
            row = ",".join([
                str(r["id"]), str(r.get("next_id", INVALID_SEGMENT_ID)),
                str(duration), "1", str(r["length"]),
                str(r["queue_length"]), str(start), str(end),
                source, mode.upper()]) + "\n"
            tiles.setdefault(tile_file, []).append(row)


# --------------------------------------------------------------------------
# stage 3: report
# --------------------------------------------------------------------------

def _report_worker(files: List[str], match_dir: str, dest: str,
                   privacy: int) -> None:
    for path in files:
        with open(path) as f:
            rows = f.readlines()
        rows.sort()
        # cull rows below the privacy threshold on (segment, next) runs
        kept: list[str] = []
        i = 0
        while i < len(rows):
            ki = rows[i].split(",")[:2]
            j = i
            while j < len(rows) and rows[j].split(",")[:2] == ki:
                j += 1
            if j - i >= privacy:
                kept.extend(rows[i:j])
            i = j
        rel = os.path.relpath(path, match_dir)
        if not kept:
            logger.info("No segments for %s after anonymising", rel)
            continue
        name = hashlib.sha1(path.encode()).hexdigest()
        payload = Segment.column_layout() + "\n" + "".join(kept)
        key = rel + "/" + name
        logger.info("Writing %d segments to %s", len(kept), key)
        if _is_remote(dest):
            _put_remote(dest, key, payload)
        else:
            out_path = os.path.join(dest, key)
            os.makedirs(os.path.dirname(out_path), exist_ok=True)
            with open(out_path, "w") as f:
                f.write(payload)


def _is_remote(dest: str) -> bool:
    return dest.startswith(("s3://", "http://", "https://"))


def _put_remote(dest: str, key: str, payload: str) -> None:
    if not dest.startswith("s3://"):
        # signed PUT / boto3 for AWS endpoints, plain POST otherwise —
        # same routing as the streaming TileSink
        from ..utils import http as http_egress
        http_egress.egress_tile(dest, key, payload)
        return
    try:
        import boto3
    except ImportError:
        logger.error("s3 destination requires boto3, which is unavailable")
        return
    bucket, _, prefix = dest[len("s3://"):].partition("/")
    full_key = (prefix.rstrip("/") + "/" + key) if prefix else key
    boto3.client("s3").put_object(Bucket=bucket, Key=full_key,
                                  Body=payload.encode())


def report_tiles(match_dir: str, dest: str, privacy: int,
                 concurrency: int) -> None:
    files = sorted(
        os.path.join(r, f)
        for r, _d, fs in os.walk(match_dir) for f in fs)
    logger.info("Reporting %d anonymised time tiles", len(files))
    if not _is_remote(dest):
        os.makedirs(dest, exist_ok=True)
    chunks = [files[i::concurrency] for i in range(concurrency)]
    procs = []
    for chunk in chunks:
        if not chunk:
            continue
        p = _MP.Process(
            target=_report_worker, args=(chunk, match_dir, dest, privacy))
        p.start()
        procs.append(p)
    for p in procs:
        p.join()


# --------------------------------------------------------------------------
# CLI
# --------------------------------------------------------------------------

def _bbox(arg: str) -> List[float]:
    b = [float(x) for x in arg.split(",")]
    if b[0] < -90 or b[1] < -180 or b[2] > 90 or b[3] > 180 \
            or b[0] >= b[2] or b[1] >= b[3]:
        raise argparse.ArgumentTypeError(f"{arg} is not a valid bbox")
    return b


def _int_set(arg: str) -> set:
    return {int(x) for x in arg.split(",")}


DEFAULT_VALUER = ("lambda l: functools.partial(lambda c: "
                  "[c[1], c[0], c[9], c[10], c[5]], l.split('|'))()")


def main(argv=None):
    parser = argparse.ArgumentParser(prog="simple-reporter")
    parser.add_argument("--src", help="local dir of part files or s3://bucket/prefix")
    parser.add_argument("--src-key-regex", default=".*")
    parser.add_argument("--src-valuer", default=DEFAULT_VALUER,
                        help="lambda extracting (uuid, time, lat, lon, accuracy)")
    parser.add_argument("--src-time-pattern", default="%Y-%m-%d %H:%M:%S")
    parser.add_argument("--match-config", required=True,
                        help="matcher config json (graph path + knobs)")
    parser.add_argument("--mode", default="auto")
    parser.add_argument("--report-levels", type=_int_set, default={0, 1})
    parser.add_argument("--transition-levels", type=_int_set, default={0, 1})
    parser.add_argument("--quantisation", type=int, default=3600)
    parser.add_argument("--inactivity", type=int, default=120)
    parser.add_argument("--privacy", type=int, default=2)
    parser.add_argument("--source-id", default="smpl_rprt")
    parser.add_argument("--dest", help="output dir or s3://bucket[/prefix]")
    parser.add_argument("--concurrency", type=int,
                        default=multiprocessing.cpu_count())
    parser.add_argument("--bbox", type=_bbox,
                        default=[-90.0, -180.0, 90.0, 180.0])
    parser.add_argument("--trace-dir", help="resume: pre-gathered traces")
    parser.add_argument("--match-dir", help="resume: pre-matched segments")
    parser.add_argument("--device-batch", type=int, default=512)
    parser.add_argument("--cleanup", action=argparse.BooleanOptionalAction,
                        default=True)
    args = parser.parse_args(argv)

    logging.basicConfig(level=logging.INFO,
                        format="%(asctime)s %(levelname)s %(message)s")

    from ..matcher import Configure, SegmentMatcher

    from ..utils import metrics

    trace_dir = args.trace_dir
    match_dir = args.match_dir
    if not trace_dir and not match_dir:
        if not args.src:
            parser.error("--src is required unless resuming")
        with metrics.timer("pipeline.gather"):
            trace_dir = gather_traces(args.src, args.src_key_regex,
                                      args.src_valuer, args.src_time_pattern,
                                      args.bbox, args.concurrency)
    if not match_dir:
        # joins a multi-host JAX job when a coordinator is configured;
        # single-host no-op otherwise. Deliberately AFTER the gather stage
        # (which needs no devices) so the coordinator rendezvous doesn't
        # gate pure-IO work; worker processes are spawned (_MP above), so
        # jax.distributed's threads are never inherited mid-state either.
        from ..parallel import init_multihost
        from ..utils.runtime import ensure_backend
        ensure_backend()
        init_multihost()
        Configure(args.match_config)
        matcher = SegmentMatcher()
        with metrics.timer("pipeline.match"):
            match_dir = match_traces(
                trace_dir, matcher, args.mode, args.report_levels,
                args.transition_levels, args.quantisation, args.inactivity,
                args.source_id, device_batch=args.device_batch)
    if args.dest:
        with metrics.timer("pipeline.report"):
            report_tiles(match_dir, args.dest, args.privacy, args.concurrency)
    timers = metrics.snapshot()["timers"]
    logging.info("Stage timings: %s", {
        k: v["total_s"] for k, v in timers.items()
        if k.startswith("pipeline.")})
    if args.cleanup:
        for d in (trace_dir, match_dir):
            if d and not (d == args.trace_dir or d == args.match_dir):
                shutil.rmtree(d, ignore_errors=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
