from .simple_reporter import gather_traces, match_traces, report_tiles

__all__ = ["gather_traces", "match_traces", "report_tiles"]
