"""Associative-scan Viterbi: the sequence-parallel decode.

The sequential Viterbi (matcher/hmm.py) is a ``lax.scan`` over T — correct
and cheap in FLOPs, but its critical path is T dependent steps of tiny
(K,K) work, and it cannot shard the time axis. This module reformulates
the decode over the **max-plus semiring**, where a Viterbi step is a matrix
"product":

    (A @ B)[i, j] = max_k (A[i, k] + B[k, j])

Step matrices ``M_t[i, j] = transition[t][i, j] + emission[t][j]`` compose
associatively, so all prefix score vectors come out of one
``jax.lax.associative_scan``: O(log T) depth, and the T axis becomes
shardable across devices — the framework's sequence parallelism for
long traces (the analog of ring attention's role in SURVEY.md's brief:
splitting one long sequence across chips, here via GSPMD collectives
instead of explicit ppermute).

The RESTART/SKIP case encoding composes cleanly: a RESTART step's matrix
is ``M[i, j] = em[j]`` (constant over i — resets the chain up to an
argmax-invariant offset), a SKIP step's is the max-plus identity (0 on the
diagonal). Both are exactly what ``transition_scores`` already emits, so
``M = tr + em[1:, None, :]`` holds uniformly.

Backpointers are *recomputed in parallel* from the prefix scores
(bp_t[j] = argmax_i(scores[t-1, i] + tr[t-1, i, j])) — only the final
backtrace is a sequential scan, and it is O(T) gathers of width K.

Work: O(T K^3) vs the sequential O(T K^2) — for K=8..16 the extra FLOPs
are noise next to the latency of T sequential dispatches, and the K^3
inner op is a dense (K,K)x(K,K) reduction the TPU vector unit eats.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from ..matcher.hmm import (
    NEG_INF, RESTART, emission_scores, transition_scores, trim_time_pad)


def _maxplus_matmul(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """(..., K, K) max-plus product: (a @ b)[i,j] = max_k a[i,k] + b[k,j].

    The broadcast sum is indexed (..., i, k, j); the contraction axis k is
    axis -2.
    """
    return jnp.max(a[..., :, :, None] + b[..., None, :, :], axis=-2)


def step_matrices(em: jnp.ndarray, tr: jnp.ndarray) -> jnp.ndarray:
    """(T-1, K, K) composable step matrices from emission/transition scores."""
    return tr + em[1:, None, :]


def _viterbi_assoc_single(em: jnp.ndarray, tr: jnp.ndarray,
                          case: jnp.ndarray):
    """Associative-scan decode for one trace; same contract as
    matcher.hmm._viterbi_single."""
    T, K = em.shape
    M = step_matrices(em, tr)                       # (T-1, K, K)
    # prefix products P[t] = M_0 ∘ ... ∘ M_t  (in max-plus)
    P = jax.lax.associative_scan(_maxplus_matmul, M, axis=0)
    # forward score vectors for every prefix: scores[t] = init maxplus P[t-1]
    init = em[0]                                    # (K,)
    prefix = jnp.max(init[None, :, None] + P, axis=1)   # (T-1, K)
    scores = jnp.concatenate([init[None], prefix])      # (T, K)

    # parallel backpointer reconstruction from prefix scores
    cand = scores[:-1, :, None] + tr                # (T-1, K, K)
    bps = jnp.argmax(cand, axis=1).astype(jnp.int32)    # (T-1, K)
    prev_bests = jnp.argmax(scores[:-1], axis=1).astype(jnp.int32)  # (T-1,)

    last = jnp.argmax(scores[-1]).astype(jnp.int32)

    def backward(cur, inp):
        bp_t, prev_best_t, case_t = inp
        prev = jnp.where(case_t == RESTART, prev_best_t, bp_t[cur])
        return prev, cur

    first, rest = jax.lax.scan(
        backward, last, (bps, prev_bests, case[1:]), reverse=True)
    path = jnp.concatenate([first[None], rest])
    return path, jnp.max(scores[-1])


@jax.jit
def viterbi_assoc_batch(dist_m: jnp.ndarray, valid: jnp.ndarray,
                        route_m: jnp.ndarray, gc_m: jnp.ndarray,
                        case: jnp.ndarray, sigma: jnp.ndarray,
                        beta: jnp.ndarray):
    """Batch decode with the associative formulation; drop-in replacement
    for matcher.hmm.viterbi_decode_batch — same shapes, same path quality
    and total score (both accumulate across RESTART chains), with possible
    differences only where f32 ordering flips exact score ties."""
    route_m, gc_m = trim_time_pad(dist_m, route_m, gc_m)

    def one(d, v, r, g, c):
        em = emission_scores(d, v, c, sigma)
        tr = transition_scores(r, g, c[1:], beta)
        return _viterbi_assoc_single(em, tr, c)

    return jax.vmap(one)(dist_m, valid, route_m, gc_m, case)
