"""Fused Pallas TPU kernel for the Viterbi forward recurrence.

The other two decode paths pay for generality: the ``lax.scan`` version
(matcher/hmm.py) launches T tiny dependent steps through XLA, and the
associative-scan version (ops/assoc_viterbi.py) does O(K^3) work per step
to buy log-depth. This kernel does the minimal O(T K^2) work in ONE fused
program per batch block: the whole recurrence runs out of VMEM with the
batch laid across vector lanes, so the T-step dependence chain never
leaves the chip.

Layout: the batch dimension B is the *lane* axis (128-wide) and K sits on
sublanes — for the service's K=8..16 and f32 this is exactly the TPU's
native (8, 128) tile. Per grid step the kernel owns a (T, K, 128) emission
block, a (T-1, K, K, 128) transition block, and the recurrence

    scores[t+1, j, b] = max_i(scores[t, i, b] + tr[t, i, j, b]) + em[t+1, j, b]
    bps[t, j, b]      = argmax_i(...)

is uniform across NORMAL/RESTART/SKIP because ``transition_scores``
already encodes the case semantics into ``tr`` (identity for SKIP, zeros
for RESTART — matcher/hmm.py:57-72). The backtrace is O(T K) gathers,
done outside the kernel in XLA where gathers are cheap.

VMEM budget gates dispatch: a (T, K) bucket needs roughly
(T*K + 2*T*K + (T-1)*K*K) * 128 * 4 bytes resident; buckets beyond the
budget fall back to the associative path (ops/__init__.decode_batch).

The kernel stays opt-in via REPORTER_TPU_DECODE=pallas rather than the
default: no RECORDED hardware run has shown it beating the assoc backend
(and only assoc shards along seq). bench.py measures a pallas leg on
every TPU run and records it in the artifact (the "pallas" field of
BENCH_r*.json) — performance claims for this kernel live there, not
here.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..matcher.hmm import emission_scores, transition_scores

LANES = 128
# stay well under the ~16MB/core VMEM
VMEM_BUDGET_BYTES = 12 * 1024 * 1024


def vmem_bytes_estimate(T: int, K: int) -> int:
    """Resident bytes per grid step: em + tr in, final + bps out — times
    two, because pallas_call double-buffers every block for pipelining."""
    per_lane = (T * K + (T - 1) * K * K + K + (T - 1) * K) * 4
    return per_lane * LANES * 2


def _forward_kernel(em_ref, tr_ref, final_ref, bps_ref):
    T = em_ref.shape[0]

    def body(t, prev):
        # prev: (K, LANES) running scores; tr_ref[t]: (K, K, LANES)
        cand = prev[:, None, :] + tr_ref[t]          # (K_prev, K_cur, LANES)
        bps_ref[t] = jnp.argmax(cand, axis=0).astype(jnp.int32)
        return jnp.max(cand, axis=0) + em_ref[t + 1]

    # only the final timestep's scores leave the kernel — the backtrace
    # needs just the backpointers
    final_ref[:] = jax.lax.fori_loop(0, T - 1, body, em_ref[0])


def _forward_pallas(emT: jnp.ndarray, trT: jnp.ndarray, interpret: bool):
    """emT (T, K, Bp), trT (T-1, K, K, Bp) with Bp % LANES == 0.
    Returns final scores (K, Bp), bps (T-1, K, Bp)."""
    T, K, Bp = emT.shape
    grid = (Bp // LANES,)
    return pl.pallas_call(
        _forward_kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((T, K, LANES), lambda b: (0, 0, b),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((T - 1, K, K, LANES), lambda b: (0, 0, 0, b),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((K, LANES), lambda b: (0, b),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((T - 1, K, LANES), lambda b: (0, 0, b),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((K, Bp), jnp.float32),
            jax.ShapeDtypeStruct((T - 1, K, Bp), jnp.int32),
        ],
        interpret=interpret,
    )(emT, trT)


@functools.partial(jax.jit, static_argnames=("interpret",))
def viterbi_pallas_batch(dist_m: jnp.ndarray, valid: jnp.ndarray,
                         route_m: jnp.ndarray, gc_m: jnp.ndarray,
                         case: jnp.ndarray, sigma: jnp.ndarray,
                         beta: jnp.ndarray, interpret: bool = False):
    """Drop-in replacement for matcher.hmm.viterbi_decode_batch with the
    forward recurrence fused into one Pallas program per batch block.
    ``interpret=True`` runs the kernel in the Pallas interpreter
    (CPU-testable, same numerics)."""
    from ..matcher.hmm import trim_time_pad
    route_m, gc_m = trim_time_pad(dist_m, route_m, gc_m)
    B, T, K = dist_m.shape

    em = jax.vmap(lambda d, v, c: emission_scores(d, v, c, sigma))(
        dist_m, valid, case)                              # (B, T, K)
    tr = jax.vmap(lambda r, g, c: transition_scores(r, g, c[1:], beta))(
        route_m, gc_m, case)                              # (B, T-1, K, K)

    pad = (-B) % LANES
    emT = jnp.pad(em, ((0, pad), (0, 0), (0, 0))).transpose(1, 2, 0)
    trT = jnp.pad(tr, ((0, pad), (0, 0), (0, 0), (0, 0))).transpose(1, 2, 3, 0)

    final, bps = _forward_pallas(emT, trT, interpret)
    final = final.transpose(1, 0)[:B]                     # (B, K)
    bps = bps.transpose(2, 0, 1)[:B]                      # (B, T-1, K)

    last = jnp.argmax(final, axis=-1).astype(jnp.int32)   # (B,)

    def backtrace(last_b, bps_b):
        # RESTART steps need no special case: their tr rows are constant
        # over i, so bp_t[cur] is already argmax(prev_scores)
        def backward(cur, bp_t):
            return bp_t[cur], cur

        first, rest = jax.lax.scan(backward, last_b, bps_b, reverse=True)
        return jnp.concatenate([first[None], rest])

    paths = jax.vmap(backtrace)(last, bps)                # (B, T)
    return paths, jnp.max(final, axis=-1)
