"""Device route kernels: batched bounded relaxation + pair-cost assembly.

The transition-cost stage used to be the host's job: one bounded Dijkstra
per (candidate-edge, candidate-edge) pair miss, fanned across C++ threads
(native/src/host_runtime.cpp route_step). BENCH_DEV_r09 measured that
stage — ``prep_routes`` — as the pipeline's dominant line item. These two
jitted kernels move it onto the device:

``relax_csr``
    One padded multi-source bounded relaxation over the road graph's edge
    columns (the same CSR-backing arrays the native runtime loads): a
    Bellman-Ford-style gather/scatter sweep that settles, for every
    source node in the chunk at once, the exact shortest network distance
    to every node within ``bound`` meters — and the travel time *along
    that shortest-distance path* (time rides along, it never drives the
    search, matching Meili and route_step). All arithmetic is float32 in
    path order, mirroring the C++ node kernel (``nd = d + edge_len[e]``,
    ``secs = meters / (max(kph, 1) / 3.6)``), so a settled distance is
    bit-identical to the host Dijkstra's value for the same path.

``pair_costs``
    The vectorised twin of route_step's admissibility emitter: gathers
    the relaxed node kernels into the padded (B, T-1, K, K) route tensor,
    applying the same-edge forward/backward cases, the distance bound
    ``max(min_bound, factor * gc)``, the time cap
    ``max(min_time_bound, time_factor * dt)`` and the turn penalty in the
    exact float32 expression order of the C++ emitter. Padding candidates
    (edge -1) and steps at/after ``num_kept - 1`` emit the UNREACHABLE
    sentinel — identical bytes to what the host path's tail fill writes.

Bound semantics are exactness-safe under batching: the relaxation runs at
the CHUNK-global bound (the max over every live step's bound). A bounded
search at a larger bound settles a superset of exact distances and never
changes a settled value, and ``pair_costs`` re-applies each step's own
bound — so an entry is finite iff the per-pair host search would have
found it, with the same value. Equal-distance ties are the one accepted
divergence: the host Dijkstra keeps the first-settled path's travel time
(heap order), the relaxation keeps the minimum — which can flip a
time-cap verdict only on exact float ties, the same class of divergence
the native/numpy pair already exhibits (and the report-byte parity tests
pin to be inert).

Convergence is explicit: the sweep stops when neither distances nor times
changed (times keep relaxing along the shortest-path DAG after distances
settle, so both must be quiet), or at ``max_iters`` — in which case the
``converged`` flag is False and the caller must fall back to the host
path rather than trust a partially-relaxed tensor.
"""
from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

#: the unreachable sentinel, identical to graph/route.py UNREACHABLE and
#: the C++ kUnreachable (1.0e9f)
UNREACHABLE = 1.0e9


@partial(jax.jit, static_argnames=("n_nodes", "max_iters"))
def relax_csr(edge_start, edge_end, edge_len, edge_secs, src_nodes,
              bound, *, n_nodes: int, max_iters: int):
    """Multi-source bounded relaxation over the edge columns.

    Args:
      edge_start, edge_end: (E,) int32 directed edge endpoints.
      edge_len:  (E,) float32 edge lengths, meters.
      edge_secs: (E,) float32 full-edge travel seconds
                 (``edge_len / (max(kph, 1) / 3.6)`` in float32).
      src_nodes: (S,) int32 source node ids (duplicates allowed — padding
                 rows repeat a real source and are simply redundant).
      bound:     float32 scalar; paths whose running distance exceeds it
                 stop relaxing (the chunk-global route bound).
      n_nodes:   static node count N.
      max_iters: static sweep cap (>= longest bounded path in hops + 1).

    Returns ``(dist, time, iters, converged)``: (S, N) float32 exact
    bounded shortest distances (inf = not reachable within ``bound``),
    (S, N) float32 travel seconds along those shortest-distance paths
    (min over equal-distance ties), the sweep count actually run, and
    whether the sweep reached a fixpoint before ``max_iters``.
    """
    S = src_nodes.shape[0]
    inf = jnp.float32(jnp.inf)
    rows = jnp.arange(S, dtype=jnp.int32)
    dist0 = jnp.full((S, n_nodes), inf, jnp.float32)
    dist0 = dist0.at[rows, src_nodes].set(jnp.float32(0.0))
    time0 = jnp.full((S, n_nodes), inf, jnp.float32)
    time0 = time0.at[rows, src_nodes].set(jnp.float32(0.0))

    def body(state):
        dist, time, it, _ = state
        # gather: candidate relaxations through every edge at once
        cd = dist[:, edge_start] + edge_len[None, :]
        ct = time[:, edge_start] + edge_secs[None, :]
        ok = cd <= bound  # the Dijkstra admission rule (nd > bound skips)
        cd = jnp.where(ok, cd, inf)
        ct = jnp.where(ok, ct, inf)
        # scatter-min distances (duplicate targets reduce correctly)
        nd = dist.at[:, edge_end].min(cd)
        # lexicographic (d, t): among arcs achieving the (possibly
        # unchanged) new distance at their target, keep the minimum
        # time; nodes whose distance improved reset their time first
        tie = jnp.where(cd == nd[:, edge_end], ct, inf)
        nt = jnp.where(nd == dist, time, inf)
        nt = nt.at[:, edge_end].min(tie)
        changed = jnp.any(nd != dist) | jnp.any(nt != time)
        return nd, nt, it + 1, changed

    def cond(state):
        _, _, it, changed = state
        return changed & (it < max_iters)

    dist, time, iters, changed = jax.lax.while_loop(
        cond, body, (dist0, time0, jnp.int32(0), jnp.bool_(True)))
    return dist, time, iters, jnp.logical_not(changed)


@jax.jit
def pair_costs(edge, offset, nk, bounds, caps, dist_sn, time_sn,
               node_row, edge_start, edge_end, edge_len, edge_v,
               head_x, head_y, backward_tol, turn_penalty_factor):
    """Assemble the (B, T-1, K, K) route tensor from relaxed kernels.

    Args:
      edge, offset: (B, T, K) int32 / float32 candidate tensors (pad -1).
      nk:        (B,) int32 kept point counts (steps >= nk-1 are dead).
      bounds:    (B, T-1) float32 per-step distance bound.
      caps:      (B, T-1) float32 per-step time cap; < 0 disables it.
      dist_sn, time_sn: (S, N) float32 relaxed node kernels.
      node_row:  (N,) int32 node id -> relaxation row (-1 = not a source).
      edge_start, edge_end: (E,) int32; edge_len (E,) float32.
      edge_v:    (E,) float32 edge speed in m/s (``max(kph, 1) / 3.6``).
      head_x, head_y: (E,) float32 unit headings (turn penalty).
      backward_tol, turn_penalty_factor: float32 scalars.

    Returns ``(route, max_finite)``: the route tensor (UNREACHABLE where
    inadmissible / padded / dead) and the largest finite cost written
    (0 when none) — the wire-dtype decision input.
    """
    unreach = jnp.float32(UNREACHABLE)
    ea = edge[:, :-1, :][..., :, None]       # (B, T-1, K, 1)
    eb = edge[:, 1:, :][..., None, :]        # (B, T-1, 1, K)
    oa = offset[:, :-1, :][..., :, None]
    ob = offset[:, 1:, :][..., None, :]
    sa = jnp.maximum(ea, 0)
    sb = jnp.maximum(eb, 0)

    remaining = edge_len[sa] - oa            # (B, T-1, K, 1)
    via = remaining + ob                     # (B, T-1, K, K)
    row = node_row[edge_end[sa]]             # (B, T-1, K, 1)
    dn = dist_sn[jnp.maximum(row, 0), edge_start[sb]]
    tn = time_sn[jnp.maximum(row, 0), edge_start[sb]]

    b_ = bounds[:, :, None, None]
    cap = caps[:, :, None, None]
    via_dn = via + dn
    # general pair: the emit() ladder of route_step, in its order
    bad = (via > b_) | (row < 0) | jnp.logical_not(jnp.isfinite(dn)) \
        | (via_dn > b_)
    secs = remaining / edge_v[sa] + ob / edge_v[sb] + tn
    bad = bad | ((cap >= 0) & (secs > cap))
    cos_th = head_x[sa] * head_x[sb] + head_y[sa] * head_y[sb]
    pen = (turn_penalty_factor * jnp.float32(0.5)) \
        * (jnp.float32(1.0) - cos_th)
    d_gen = jnp.where(turn_penalty_factor > 0, via_dn + pen, via_dn)
    general = jnp.where(bad, unreach, d_gen)

    # same directed edge: forward progress prices the along-edge meters
    # (time-capped); small apparent backward motion prices as staying put
    same = eb == ea
    fwd = same & (ob >= oa)
    d_fwd = ob - oa
    fwd_val = jnp.where((cap >= 0) & (d_fwd / edge_v[sa] > cap),
                        unreach, d_fwd)
    back = same & (ob < oa) & ((oa - ob) <= backward_tol)
    val = jnp.where(fwd, fwd_val,
                    jnp.where(back, jnp.float32(0.0), general))

    steps = jnp.arange(edge.shape[1] - 1, dtype=nk.dtype)
    dead = (ea < 0) | (eb < 0) \
        | (steps[None, :, None, None] >= (nk[:, None, None, None] - 1))
    out = jnp.where(dead, unreach, val)
    max_finite = jnp.max(jnp.where(out < unreach, out, jnp.float32(0.0)),
                         initial=jnp.float32(0.0))
    return out, max_finite


@partial(jax.jit, static_argnames=("B", "T", "K", "N"))
def pair_costs_packed(ints, f32s, dist_sn, time_sn,
                      edge_start, edge_end, edge_len, edge_v,
                      head_x, head_y, *, B, T, K, N):
    """pair_costs with the six small per-chunk tensors packed into two
    1-D blobs so a warm dispatch pays two host->device transfers
    instead of eight. Pure repacking — slices/reshapes inside the jit
    are free and the assembled bytes match pair_costs exactly.

    Layouts (see DeviceRouteKernel._run, the only caller):
      ints: [edge (B*T*K) | nk (B) | node_row (N)]            int32
      f32s: [offset (B*T*K) | bounds (B*(T-1)) | caps (B*(T-1))
             | backward_tol | turn_penalty_factor]            float32
    """
    btk = B * T * K
    edge = ints[:btk].reshape(B, T, K)
    nk = ints[btk:btk + B]
    node_row = ints[btk + B:btk + B + N]
    offset = f32s[:btk].reshape(B, T, K)
    bt1 = B * (T - 1)
    bounds = f32s[btk:btk + bt1].reshape(B, T - 1)
    caps = f32s[btk + bt1:btk + 2 * bt1].reshape(B, T - 1)
    backward_tol = f32s[btk + 2 * bt1]
    turn_penalty_factor = f32s[btk + 2 * bt1 + 1]
    return pair_costs(edge, offset, nk, bounds, caps, dist_sn, time_sn,
                      node_row, edge_start, edge_end, edge_len, edge_v,
                      head_x, head_y, backward_tol, turn_penalty_factor)
