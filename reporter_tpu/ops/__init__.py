from .assoc_viterbi import viterbi_assoc_batch, step_matrices

__all__ = ["viterbi_assoc_batch", "step_matrices"]
