"""Device decode kernels and backend dispatch.

Three implementations of the batched Viterbi decode, one contract:

  scan    lax.scan over T (matcher/hmm.py) — simplest, T dependent steps
  assoc   associative scan over max-plus step matrices — log-depth,
          shardable along T (sequence parallelism)
  pallas  fused single-program forward recurrence in VMEM — minimal
          work and launches on TPU hardware

``decode_batch`` picks per call: honours REPORTER_TPU_DECODE
(scan|assoc|pallas) when set; otherwise assoc. Measured on one TPU chip at
(B=512, T=64, K=8): end-to-end service throughput is identical across the
three (~2250 traces/s — host-side segment assembly dominates); device-
resident decode favours assoc (~26 ms vs ~64 ms for scan/pallas per 512
traces), so assoc is the default and pallas stays opt-in until it wins.
"""
import os

import jax

from .assoc_viterbi import step_matrices, viterbi_assoc_batch
from .pallas_viterbi import (
    VMEM_BUDGET_BYTES,
    viterbi_pallas_batch,
    vmem_bytes_estimate,
)

__all__ = ["viterbi_assoc_batch", "viterbi_pallas_batch", "step_matrices",
           "decode_batch"]


def decode_backend(T: int, K: int) -> str:
    forced = os.environ.get("REPORTER_TPU_DECODE", "").strip().lower()
    if forced == "pallas" and vmem_bytes_estimate(T, K) > VMEM_BUDGET_BYTES:
        return "assoc"  # bucket too large for the fused kernel's VMEM
    if forced in ("scan", "assoc", "pallas"):
        return forced
    return "assoc"


def decode_batch(dist_m, valid, route_m, gc_m, case, sigma, beta):
    """Backend-dispatched batched Viterbi decode; same contract as
    matcher.hmm.viterbi_decode_batch.

    Accepts f32 tensors or the f16 wire format (built by
    matcher.batchpad.pack_batches, the single owner of the wire policy) —
    the scoring kernels upcast on device either way."""
    backend = decode_backend(T=dist_m.shape[1], K=dist_m.shape[2])
    if backend == "pallas":
        interpret = jax.default_backend() != "tpu"
        return viterbi_pallas_batch(dist_m, valid, route_m, gc_m, case,
                                    sigma, beta, interpret=interpret)
    if backend == "assoc":
        return viterbi_assoc_batch(dist_m, valid, route_m, gc_m, case,
                                   sigma, beta)
    from ..matcher.hmm import viterbi_decode_batch
    return viterbi_decode_batch(dist_m, valid, route_m, gc_m, case,
                                sigma, beta)
