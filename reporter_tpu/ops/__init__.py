"""Device decode kernels and backend dispatch.

Three implementations of the batched Viterbi decode, one contract:

  scan    lax.scan over T (matcher/hmm.py) — simplest, T dependent steps
  assoc   associative scan over max-plus step matrices — log-depth,
          shardable along T (sequence parallelism)
  pallas  fused single-program forward recurrence in VMEM — minimal
          work and launches on TPU hardware

``decode_batch`` picks per call: honours REPORTER_TPU_DECODE
(scan|assoc|pallas) when set; otherwise the default is platform-aware —
assoc on accelerators and device meshes (the only backend that is both
log-depth and seq-shardable), scan on a lone CPU device where assoc's
O(K^3) work is a measured ~4x decode loss and the T-step dependence
chain costs nothing. bench.py records whichever default its platform
resolves (the artifact's ``decode=`` field). pallas stays opt-in until
a recorded run shows it winning on hardware.
"""
import os

import jax

from .assoc_viterbi import step_matrices, viterbi_assoc_batch
from .incremental import incremental_step_batch
from .pallas_viterbi import (
    VMEM_BUDGET_BYTES,
    viterbi_pallas_batch,
    vmem_bytes_estimate,
)

__all__ = ["viterbi_assoc_batch", "viterbi_pallas_batch", "step_matrices",
           "incremental_step_batch", "decode_batch", "batch_pad_multiple",
           "decode_mesh_size", "shard_width"]

# a forked worker must re-derive its device slice and jitted runs (the
# parent's mesh names devices the child's slice may not own); prefork
# builds everything post-fork anyway — this keeps it true even for a
# parent that decoded before forking
from ..utils import forksafe as _forksafe  # noqa: E402

_forksafe.register(lambda: reset_sharded_cache())


def decode_backend(T: int, K: int) -> str:
    forced = os.environ.get("REPORTER_TPU_DECODE", "").strip().lower()
    if forced == "pallas" and vmem_bytes_estimate(T, K) > VMEM_BUDGET_BYTES:
        return "assoc"  # bucket too large for the fused kernel's VMEM
    if forced in ("scan", "assoc", "pallas"):
        return forced
    # default is platform-aware: assoc's max-plus matmuls buy log-depth
    # and seq-shardability at O(K^3) work — the right trade on an
    # accelerator, or on any mesh that shards the time axis. On CPU the
    # T-step dependence chain costs nothing and assoc is a measured ~4x
    # decode loss (512 traces: ~59 ms scan vs ~244 ms assoc on one
    # core) — and since the 1-D ("data",) decode mesh shards scan rows
    # with zero collectives (parallel/sharded.py), a multi-device CPU
    # mesh keeps scan too; that is also what makes the sharded decode
    # bit-identical to the single-device oracle.
    if jax.default_backend() == "cpu":
        _mesh, _data, seq = _mesh_state()
        if seq <= 1:
            return "scan"
    return "assoc"


# process-default sharded decode, built lazily on first use:
# (mesh, data, seq, {backend: run}) — (None, 1, 1, {}) when unsharded
_sharded_cache = None


def _mesh_state():
    """(mesh, data_size, seq_size) of the process decode mesh
    (parallel/mesh.py decode_mesh; (None, 1, 1) when single-device or
    disabled)."""
    global _sharded_cache
    if _sharded_cache is None:
        from ..parallel import mesh as pmesh
        mesh = pmesh.decode_mesh()
        data, seq = pmesh.mesh_axes(mesh)
        _sharded_cache = (mesh, data, seq, {})
    return _sharded_cache[:3]


def _sharded_run(backend: str):
    """The mesh decode callable for ``backend``, or None when this
    backend can't shard on the process mesh (no mesh; pallas; scan on a
    seq-sharded mesh — the sequential scan has no cross-shard combine)."""
    global _sharded_cache
    _mesh_state()  # ensure the cache tuple exists
    mesh, data, seq, runs = _sharded_cache
    if mesh is None or backend == "pallas":
        return None
    if backend == "scan" and seq > 1:
        return None
    run = runs.get(backend)
    if run is None:
        from ..parallel.sharded import (sharded_data_viterbi,
                                        sharded_viterbi)
        if seq > 1:  # (data, seq) mesh: assoc only (checked above)
            run = sharded_viterbi(mesh)
        elif backend == "assoc":
            run = sharded_data_viterbi(mesh,
                                       viterbi_assoc_batch.__wrapped__)
        else:
            from ..matcher.hmm import viterbi_decode_batch
            run = sharded_data_viterbi(mesh,
                                       viterbi_decode_batch.__wrapped__)
        runs[backend] = run
    return run


def batch_pad_multiple():
    """Batch-dim multiple callers should pad to so ``decode_batch`` can
    take the sharded path (the mesh's data-axis size); None when decode
    is single-device. match_many feeds this to
    pack_batches(pad_batch_to=...) / padded_batch_rows.

    scan and assoc both shard along ``data`` (parallel/sharded.py);
    only a forced pallas backend — and scan under a seq-sharded mesh —
    can't, so padding would buy nothing there and None skips it."""
    forced = os.environ.get("REPORTER_TPU_DECODE", "").strip().lower()
    if forced == "pallas":
        return None
    _mesh, data, seq = _mesh_state()
    if data <= 1:
        return None
    if forced == "scan" and seq > 1:
        return None
    return data


def shard_width(B: int, T: int, backend: str) -> int:
    """How many devices a (B, T) decode of ``backend`` actually spans —
    the compile-shape key's mesh dimension (obs/profiler.py): a
    recompile because the mesh changed is a new shape, not a storm."""
    mesh, data, seq = _mesh_state()
    if _sharded_run(backend) is None or B % data or T % seq:
        return 1
    return data * seq


def decode_mesh_size() -> int:
    """Data-axis width of the process decode mesh (1 = unsharded) —
    what _decode_chunk and the dispatcher's in-flight depth scale by."""
    _mesh, data, _seq = _mesh_state()
    return data


def reset_sharded_cache() -> None:
    """Drop the cached mesh + jitted runs (tests re-read the env;
    forked workers re-derive their device slice)."""
    global _sharded_cache
    _sharded_cache = None
    from ..parallel import mesh as pmesh
    pmesh.reset_decode_mesh()


def decode_batch(dist_m, valid, route_m, gc_m, case, sigma, beta):
    """Backend-dispatched batched Viterbi decode; same contract as
    matcher.hmm.viterbi_decode_batch.

    Accepts f32 tensors or the f16 wire format (matcher.batchpad owns
    the wire policy — pack_batches on the fallback path, prepare_batch
    on the native path) — the scoring kernels upcast on device either
    way.

    With more than one visible device, batches whose dims divide the
    process mesh run sharded — data-parallel over traces for scan and
    assoc (bit-identical rows, no collectives), optionally sequence-
    parallel over time for assoc — and the returned paths stay
    device-sharded until the caller's d2h gather. Others fall through
    to single-device."""
    from ..utils import metrics
    backend = decode_backend(T=dist_m.shape[1], K=dist_m.shape[2])
    if backend in ("scan", "assoc"):
        run = _sharded_run(backend)
        _mesh, data, seq = _mesh_state()
        B, T = dist_m.shape[0], dist_m.shape[1]
        if run is not None and B % data == 0 and T % seq == 0:
            # decode.shard.* is the fan-out sensor pair: chunks through
            # the mesh path, and rows placed across the data axis
            metrics.count("decode.shard.chunks")
            metrics.count("decode.shard.rows", B)
            return run(dist_m, valid, route_m, gc_m, case, sigma, beta)
        if backend == "assoc":
            return viterbi_assoc_batch(dist_m, valid, route_m, gc_m,
                                       case, sigma, beta)
        from ..matcher.hmm import viterbi_decode_batch
        return viterbi_decode_batch(dist_m, valid, route_m, gc_m, case,
                                    sigma, beta)
    interpret = jax.default_backend() != "tpu"
    return viterbi_pallas_batch(dist_m, valid, route_m, gc_m, case,
                                sigma, beta, interpret=interpret)
