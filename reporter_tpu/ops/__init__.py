"""Device decode kernels and backend dispatch.

Three implementations of the batched Viterbi decode, one contract:

  scan    lax.scan over T (matcher/hmm.py) — simplest, T dependent steps
  assoc   associative scan over max-plus step matrices — log-depth,
          shardable along T (sequence parallelism)
  pallas  fused single-program forward recurrence in VMEM — minimal
          work and launches on TPU hardware

``decode_batch`` picks per call: honours REPORTER_TPU_DECODE
(scan|assoc|pallas) when set; otherwise the default is platform-aware —
assoc on accelerators and device meshes (the only backend that is both
log-depth and seq-shardable), scan on a lone CPU device where assoc's
O(K^3) work is a measured ~4x decode loss and the T-step dependence
chain costs nothing. bench.py records whichever default its platform
resolves (the artifact's ``decode=`` field). pallas stays opt-in until
a recorded run shows it winning on hardware.
"""
import os

import jax

from .assoc_viterbi import step_matrices, viterbi_assoc_batch
from .pallas_viterbi import (
    VMEM_BUDGET_BYTES,
    viterbi_pallas_batch,
    vmem_bytes_estimate,
)

__all__ = ["viterbi_assoc_batch", "viterbi_pallas_batch", "step_matrices",
           "decode_batch", "batch_pad_multiple"]


def decode_backend(T: int, K: int) -> str:
    forced = os.environ.get("REPORTER_TPU_DECODE", "").strip().lower()
    if forced == "pallas" and vmem_bytes_estimate(T, K) > VMEM_BUDGET_BYTES:
        return "assoc"  # bucket too large for the fused kernel's VMEM
    if forced in ("scan", "assoc", "pallas"):
        return forced
    # default is platform-aware: assoc's max-plus matmuls buy log-depth
    # and seq-shardability at O(K^3) work — the right trade on an
    # accelerator or a device mesh, and a 4x throughput LOSS on a lone
    # CPU device where the T-step dependence chain costs nothing
    # (measured: 512 traces decode ~59 ms scan vs ~244 ms assoc on one
    # CPU core). Single-device CPU -> scan; everything else -> assoc.
    if jax.default_backend() == "cpu" and len(jax.local_devices()) == 1:
        return "scan"
    return "assoc"


# process-default sharded decode, built lazily on first use: (run, data, seq)
# or (None, 1, 1) on a single device / when disabled
_sharded_cache = None


def _sharded_run():
    """The process-default mesh decode, the production multi-device path.

    Built once from the visible devices: a (data, seq) mesh — data shards
    the trace batch (the reference's uuid-partition scale-out axis,
    SURVEY.md §2.4), seq optionally shards the time axis
    (REPORTER_TPU_SEQ_SHARDS, default 1). REPORTER_TPU_SHARD=0 disables.
    """
    global _sharded_cache
    if _sharded_cache is None:
        if os.environ.get("REPORTER_TPU_SHARD", "1").lower() in (
                "0", "off", "false"):
            _sharded_cache = (None, 1, 1)
            return _sharded_cache
        # local devices only: in a multi-host job the decode inputs are
        # host-local numpy arrays, and a device_put onto a global mesh's
        # non-addressable devices would throw — each process shards over
        # its own chips; cross-host scale-out stays uuid-partitioned
        # (parallel/multihost.py), exactly the reference's partition axis
        n = len(jax.local_devices())
        if n <= 1:
            _sharded_cache = (None, 1, 1)
            return _sharded_cache
        from ..utils.runtime import _env_int
        seq = max(1, _env_int("REPORTER_TPU_SEQ_SHARDS", 1))
        seq = min(seq, n)
        while n % seq:  # largest feasible seq <= requested
            seq -= 1
        data = n // seq
        from ..parallel.mesh import make_mesh
        from ..parallel.sharded import sharded_viterbi
        mesh = make_mesh((data, seq), devices=jax.local_devices())
        _sharded_cache = (sharded_viterbi(mesh), data, seq)
    return _sharded_cache


def batch_pad_multiple():
    """Batch-dim multiple callers should pad to so ``decode_batch`` can
    take the sharded path (the mesh's data-axis size); None when decode is
    single-device. match_many feeds this to pack_batches(pad_batch_to=...).

    Only the assoc backend shards, so a forced scan/pallas backend means
    padding would buy nothing — report None and skip it."""
    forced = os.environ.get("REPORTER_TPU_DECODE", "").strip().lower()
    if forced in ("scan", "pallas"):
        return None
    run, data, _seq = _sharded_run()
    return data if run is not None else None


def decode_batch(dist_m, valid, route_m, gc_m, case, sigma, beta):
    """Backend-dispatched batched Viterbi decode; same contract as
    matcher.hmm.viterbi_decode_batch.

    Accepts f32 tensors or the f16 wire format (matcher.batchpad owns
    the wire policy — pack_batches on the fallback path, prepare_batch
    on the native path) — the scoring kernels upcast on device either
    way.

    With more than one visible device, batches whose dims divide the
    process mesh run sharded (data-parallel over traces, optionally
    sequence-parallel over time); others fall through to single-device."""
    backend = decode_backend(T=dist_m.shape[1], K=dist_m.shape[2])
    if backend == "assoc":
        run, data, seq = _sharded_run()
        B, T = dist_m.shape[0], dist_m.shape[1]
        if run is not None and B % data == 0 and T % seq == 0:
            return run(dist_m, valid, route_m, gc_m, case, sigma, beta)
        return viterbi_assoc_batch(dist_m, valid, route_m, gc_m, case,
                                   sigma, beta)
    if backend == "pallas":
        interpret = jax.default_backend() != "tpu"
        return viterbi_pallas_batch(dist_m, valid, route_m, gc_m, case,
                                    sigma, beta, interpret=interpret)
    from ..matcher.hmm import viterbi_decode_batch
    return viterbi_decode_batch(dist_m, valid, route_m, gc_m, case,
                                sigma, beta)
