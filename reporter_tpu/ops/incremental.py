"""Batched incremental Viterbi step: advance N carried traces by one point.

The windowed decode (``matcher/hmm.py``) re-runs the full ``lax.scan``
over T kept points every time a window is matched — for a long-lived
streaming uuid that is O(T·K^2) per report forever (ISSUE 19; the batcher
trims only the consumed prefix, so windows overlap). This kernel is the
device half of the incremental path: it advances the carried per-trace
decode state — last-step log-scores (K,) per trace — by exactly one
appended kept point, for N active traces in a single dispatch.

One step of ``_viterbi_single``'s forward scan, vmapped over traces:

  cand       = prev_scores[:, None] + tr          # (K_prev, K_cur)
  best, bp   = max/argmax over K_prev
  new_scores = where(case == RESTART, max(prev_scores) + em, best + em)
  prev_best  = argmax(prev_scores)                # restart backtrace anchor

Emission/transition scoring reuses ``emission_scores`` /
``transition_scores`` verbatim (time axis of length 1), so RESTART /
SKIP / unreachable semantics are *definitionally* identical to the batch
kernel — and because the only reductions involved are max/argmax (exact
in f32, order-independent) and the adds are elementwise, the scores this
step produces are bit-identical to the same step inside the batch scan.
That equivalence is what lets the windowed decode serve as the byte-exact
parity oracle for the whole incremental path (tests/test_incremental.py).

SKIP rows double as the ragged-batch mask: a trace that has no appended
point in a dispatch round rides along as a SKIP step (identity
transition, zero emission), and the host discards its outputs — its
carried state is untouched either way.

Backpointers return to the host each step; the host keeps the bounded
(L, K) ring and owns fixed-lag commit (matcher/incremental.py) — the
ring is pure integer bookkeeping, and device round-trips per appended
point are O(K) payloads either way.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from ..matcher.hmm import RESTART, emission_scores, transition_scores

__all__ = ["incremental_step_batch"]


@functools.partial(jax.jit, static_argnames=())
def incremental_step_batch(dist_m: jnp.ndarray, valid: jnp.ndarray,
                           route_m: jnp.ndarray, gc_m: jnp.ndarray,
                           case: jnp.ndarray, prev_scores: jnp.ndarray,
                           sigma: jnp.ndarray, beta: jnp.ndarray):
    """Advance N carried traces by one appended kept point.

    Shapes: dist_m (N, K) f32/f16 point->edge distances of the appended
    point; valid (N, K) bool; route_m (N, K, K) f32/f16 route distances
    from each trace's previous kept point; gc_m (N,) f32/f16 great-circle
    distances; case (N,) i32 case code of the appended point;
    prev_scores (N, K) f32 carried last-step log-scores; sigma, beta
    scalars. Returns (new_scores (N, K) f32, bp (N, K) i32 backpointers,
    prev_best (N,) i32 restart backtrace anchors).

    A window's FIRST kept point is the same call with case=RESTART and
    prev_scores=0: ``max(0) + em == em``, exactly the scan's ``init``.
    """
    def one(d, v, r, g, c, prev):
        em = emission_scores(d[None], v[None], c[None], sigma)[0]       # (K,)
        tr = transition_scores(r[None], g[None], c[None], beta)[0]      # (K,K)
        cand = prev[:, None] + tr
        best = jnp.max(cand, axis=0)
        bp = jnp.argmax(cand, axis=0).astype(jnp.int32)
        stepped = best + em
        restarted = jnp.max(prev) + em
        new_scores = jnp.where(c == RESTART, restarted, stepped)
        prev_best = jnp.argmax(prev).astype(jnp.int32)
        return new_scores, bp, prev_best

    return jax.vmap(one)(dist_m, valid, route_m, gc_m, case, prev_scores)
