"""Core value types with the reference's exact wire/CSV layouts.

Point: 20-byte fixed binary (f32 lat, f32 lon, i32 accuracy, i64 time)
  (reference: Point.java:18,50-58)
Segment: 40-byte fixed binary (i64 id, i64 next_id, f64 min, f64 max,
  i32 length, i32 queue) and the 10-column tile CSV row
  (reference: Segment.java:22,55-74)
TimeQuantisedTile: 16-byte key (i64 time_range_start, i64 tile_id)
  (reference: TimeQuantisedTile.java:19,49-88)
"""
from __future__ import annotations

import math
import struct
from dataclasses import dataclass
from typing import List, Optional

from .osmlr import (
    INVALID_SEGMENT_ID,
    LEVEL_BITS,
    LEVEL_MASK,
    TILE_INDEX_MASK,
    tile_id_of_segment,
)


def _fmt_float(v: float) -> str:
    """Format a float with up to 6 fractional digits, no trailing zeros.

    Mirrors the reference's DecimalFormat("###.######") used when emitting
    point JSON (reference: Point.java:49,59-65).
    """
    s = f"{float(v):.6f}".rstrip("0").rstrip(".")
    return s if s not in ("", "-0") else "0"


_POINT_STRUCT = struct.Struct(">ffiq")  # big-endian like java.nio ByteBuffer


_F32 = struct.Struct(">ff")


@dataclass
class Point:
    lat: float
    lon: float
    accuracy: int
    time: int

    SIZE = _POINT_STRUCT.size  # 20

    def __post_init__(self):
        # the f32 wire format IS the value domain: quantise at
        # construction so every serde roundtrip (Kafka frame, state
        # snapshot) is the identity. Before this, a point restored from
        # a crash snapshot differed from its never-snapshotted twin in
        # the f32-truncated digits — enough to flip a rounded report
        # duration and break crash/restore output parity (the chaos
        # harness's kill_restore scenario caught exactly that).
        self.lat, self.lon = _F32.unpack(_F32.pack(self.lat, self.lon))

    def to_bytes(self) -> bytes:
        return _POINT_STRUCT.pack(self.lat, self.lon, self.accuracy, self.time)

    @classmethod
    def from_bytes(cls, raw: bytes, offset: int = 0) -> "Point":
        lat, lon, accuracy, time = _POINT_STRUCT.unpack_from(raw, offset)
        return cls(lat, lon, accuracy, time)

    def to_json_obj(self) -> dict:
        return {
            "lat": round(float(self.lat), 6),
            "lon": round(float(self.lon), 6),
            "time": int(self.time),
            "accuracy": int(self.accuracy),
        }

    def to_json_str(self) -> str:
        return (
            '{"lat":' + _fmt_float(self.lat)
            + ',"lon":' + _fmt_float(self.lon)
            + ',"time":' + str(int(self.time))
            + ',"accuracy":' + str(int(self.accuracy)) + "}"
        )


_SEGMENT_STRUCT = struct.Struct(">qqddii")  # 40 bytes


@dataclass
class Segment:
    """A single observation of a (segment, next segment) pair — one histogram
    entry in a traffic tile (reference: Segment.java:11-31)."""

    id: int
    next_id: Optional[int]
    min: float   # epoch seconds at segment start
    max: float   # epoch seconds at next-segment start (or segment end)
    length: int  # meters
    queue: int   # meters

    SIZE = _SEGMENT_STRUCT.size  # 40

    def __post_init__(self):
        if self.next_id is None:
            self.next_id = INVALID_SEGMENT_ID

    def tile_id(self) -> int:
        """3-bit level + 22-bit tile index (reference: Segment.java:33-36)."""
        return tile_id_of_segment(self.id)

    def valid(self) -> bool:
        # reference: Segment.java:38-40
        return self.min > 0 and self.max > 0 and self.max > self.min \
            and self.length > 0 and self.queue >= 0

    def sort_key(self):
        # reference: Segment.java:50-53 (id, then next_id)
        return (self.id, self.next_id)

    def csv_row(self, mode: str, source: str) -> str:
        """One tile CSV row (reference: Segment.java:59-74). ``next_id`` is
        left empty when invalid; duration is round(max-min); count always 1."""
        next_str = "" if self.next_id == INVALID_SEGMENT_ID else str(self.next_id)
        # half-up rounding to match Java Math.round (Python round() is banker's)
        duration = int(math.floor((self.max - self.min) + 0.5))
        return ",".join([
            str(self.id),
            next_str,
            str(duration),
            "1",
            str(int(self.length)),
            str(int(self.queue)),
            str(int(math.floor(self.min))),
            str(int(math.ceil(self.max))),
            source,
            mode,
        ])

    @staticmethod
    def column_layout() -> str:
        # reference: Segment.java:55-57 / simple_reporter.py:252
        return ("segment_id,next_segment_id,duration,count,length,queue_length,"
                "minimum_timestamp,maximum_timestamp,source,vehicle_type")

    def to_bytes(self) -> bytes:
        return _SEGMENT_STRUCT.pack(
            self.id, self.next_id, self.min, self.max, self.length, self.queue)

    @classmethod
    def from_bytes(cls, raw: bytes, offset: int = 0) -> "Segment":
        sid, nid, mn, mx, ln, q = _SEGMENT_STRUCT.unpack_from(raw, offset)
        return cls(sid, nid, mn, mx, ln, q)


_TILE_STRUCT = struct.Struct(">qq")


@dataclass(frozen=True)
class TimeQuantisedTile:
    """Key for the anonymiser's accumulation map: (time bucket start, graph
    tile id) (reference: TimeQuantisedTile.java:16-24)."""

    time_range_start: int
    tile_id: int

    SIZE = _TILE_STRUCT.size  # 16

    @staticmethod
    def tiles_for(segment: Segment, quantisation: int) -> List["TimeQuantisedTile"]:
        """All time buckets a segment observation spans
        (reference: TimeQuantisedTile.java:26-35)."""
        lo = int(segment.min)
        hi = int(segment.max)
        return [
            TimeQuantisedTile(b * quantisation, segment.tile_id())
            for b in range(lo // quantisation, hi // quantisation + 1)
        ]

    def tile_index(self) -> int:
        return (self.tile_id >> LEVEL_BITS) & TILE_INDEX_MASK

    def tile_level(self) -> int:
        return self.tile_id & LEVEL_MASK

    def __str__(self) -> str:
        return f"{self.time_range_start}_{self.tile_id}"

    def to_bytes(self) -> bytes:
        return _TILE_STRUCT.pack(self.time_range_start, self.tile_id)

    @classmethod
    def from_bytes(cls, raw: bytes, offset: int = 0) -> "TimeQuantisedTile":
        start, tid = _TILE_STRUCT.unpack_from(raw, offset)
        return cls(start, tid)
