"""Geodesy helpers shared by host code and the device matcher.

The reference measures probe separation with an equirectangular approximation
(reference: Batch.java:34-41); we keep the identical constant so streaming
report thresholds trip at the same distances.
"""
from __future__ import annotations

import math

import numpy as np

RAD_PER_DEG = math.pi / 180.0
# Half the WGS84-ish circumference used by the reference, per degree.
METERS_PER_DEG = 20037581.187 / 180.0


def equirectangular_m(lat_a, lon_a, lat_b, lon_b):
    """Equirectangular-approximation distance in meters.

    Works on scalars or numpy arrays (broadcasting). Matches the streaming
    worker's separation metric (reference: Batch.java:37-41).
    """
    x = (np.asarray(lon_a) - np.asarray(lon_b)) * METERS_PER_DEG * np.cos(
        0.5 * (np.asarray(lat_a) + np.asarray(lat_b)) * RAD_PER_DEG
    )
    y = (np.asarray(lat_a) - np.asarray(lat_b)) * METERS_PER_DEG
    d = np.sqrt(x * x + y * y)
    if np.ndim(d) == 0:
        return float(d)
    return d


def local_meters_projection(lat0: float, lon0: float):
    """Return (to_xy, to_ll) converting lat/lon degrees <-> local meters.

    A flat equirectangular chart anchored at (lat0, lon0); accurate to well
    under GPS noise over a metro-area extent, and cheap enough to run per
    probe batch on the host.
    """
    coslat = math.cos(lat0 * RAD_PER_DEG)

    def to_xy(lat, lon):
        x = (np.asarray(lon) - lon0) * METERS_PER_DEG * coslat
        y = (np.asarray(lat) - lat0) * METERS_PER_DEG
        return x, y

    def to_ll(x, y):
        lon = np.asarray(x) / (METERS_PER_DEG * coslat) + lon0
        lat = np.asarray(y) / METERS_PER_DEG + lat0
        return lat, lon

    return to_xy, to_ll
