"""Valhalla-compatible 3-level geographic tile hierarchy.

Level 2 = local (0.25°), level 1 = arterial (1°), level 0 = highway (4°),
over the whole-world bounding box; tile ids are row-major
(reference: py/get_tiles.py:30-102). File paths group the decimal id into
3-digit directories: ``{level}/{nnn}/{nnn}/{nnn}.{suffix}``.
"""
from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Iterator, List, Tuple

WORLD_MIN_X, WORLD_MIN_Y, WORLD_MAX_X, WORLD_MAX_Y = -180.0, -90.0, 180.0, 90.0

LEVEL_SIZES = {2: 0.25, 1: 1.0, 0: 4.0}


@dataclass(frozen=True)
class BoundingBox:
    minx: float
    miny: float
    maxx: float
    maxy: float


class Tiles:
    """Row/column math for one hierarchy level
    (reference: get_tiles.py:41-102)."""

    def __init__(self, bbox: BoundingBox, size: float):
        self.bbox = bbox
        self.tilesize = size
        self.ncolumns = int(math.ceil((bbox.maxx - bbox.minx) / size))
        self.nrows = int(math.ceil((bbox.maxy - bbox.miny) / size))
        self.max_tile_id = self.ncolumns * self.nrows - 1

    def row(self, y: float) -> int:
        if y < self.bbox.miny or y > self.bbox.maxy:
            return -1
        if y == self.bbox.maxy:
            return self.nrows - 1
        return int((y - self.bbox.miny) / self.tilesize)

    def col(self, x: float) -> int:
        if x < self.bbox.minx or x > self.bbox.maxx:
            return -1
        if x == self.bbox.maxx:
            return self.ncolumns - 1
        c = (x - self.bbox.minx) / self.tilesize
        return int(c) if c >= 0.0 else int(c - 1)

    def tile_id(self, lat: float, lon: float) -> int:
        r, c = self.row(lat), self.col(lon)
        if r < 0 or c < 0:
            return -1
        return r * self.ncolumns + c

    def tile_bbox(self, tile_id: int) -> BoundingBox:
        """Bounding box of one tile — the inverse of :meth:`tile_id`
        (any interior point maps back to the same id; the shared max
        edge belongs to the neighbour except at the world boundary)."""
        if tile_id < 0 or tile_id > self.max_tile_id:
            raise ValueError(f"tile id {tile_id} out of range "
                             f"[0, {self.max_tile_id}]")
        r, c = divmod(tile_id, self.ncolumns)
        minx = self.bbox.minx + c * self.tilesize
        miny = self.bbox.miny + r * self.tilesize
        return BoundingBox(minx, miny,
                           min(minx + self.tilesize, self.bbox.maxx),
                           min(miny + self.tilesize, self.bbox.maxy))

    def _digits(self, number: int) -> int:
        digits = 1 if number < 0 else 0
        while number:
            number //= 10
            digits += 1
        return digits

    def file_path(self, tile_id: int, level: int, suffix: str) -> str:
        """``{level}/{nnn}/{nnn}/{nnn}.{suffix}`` grouping the decimal tile id
        into 3-digit directories (reference: get_tiles.py:82-102)."""
        max_length = self._digits(self.max_tile_id)
        if max_length % 3:
            max_length += 3 - max_length % 3
        # prepend the level digit, then group by thousands
        combined = level * 10 ** max_length + tile_id
        grouped = f"{combined:,}".replace(",", "/")
        if level == 0:
            # a leading "1" placeholder keeps the zero-padding; swap it back
            grouped_full = f"{10 ** max_length + tile_id:,}".replace(",", "/")
            grouped = "0" + grouped_full[1:]
        return f"{grouped}.{suffix}"


class TileHierarchy:
    def __init__(self):
        world = BoundingBox(WORLD_MIN_X, WORLD_MIN_Y, WORLD_MAX_X, WORLD_MAX_Y)
        self.levels = {lvl: Tiles(world, size) for lvl, size in LEVEL_SIZES.items()}

    def tiles(self, level: int) -> Tiles:
        return self.levels[level]


def _split_antimeridian(bbox: List[float]) -> List[BoundingBox]:
    """Split a (minx,miny,maxx,maxy) box crossing ±180 into two boxes
    (reference: get_tiles.py:139-157)."""
    minx, miny, maxx, maxy = bbox
    if minx >= maxx:
        minx -= 360
    span = WORLD_MAX_X - WORLD_MIN_X
    if minx < WORLD_MIN_X and maxx > WORLD_MIN_X:
        return [BoundingBox(WORLD_MIN_X, miny, maxx, maxy),
                BoundingBox(minx + span, miny, WORLD_MAX_X, maxy)]
    if minx < WORLD_MAX_X and maxx > WORLD_MAX_X:
        return [BoundingBox(minx, miny, WORLD_MAX_X, maxy),
                BoundingBox(WORLD_MIN_X, miny, maxx - span, maxy)]
    return [BoundingBox(minx, miny, maxx, maxy)]


def tiles_for_bbox(bbox_lonlat: List[float], suffix: str = "gph",
                   levels: Tuple[int, ...] = (0, 1, 2)) -> Iterator[str]:
    """Yield tile file paths intersecting a lon/lat bbox
    (min_lon, min_lat, max_lon, max_lat), splitting at the antimeridian
    (reference: get_tiles.py:130-172)."""
    hierarchy = TileHierarchy()
    for box in _split_antimeridian(list(bbox_lonlat)):
        if box.miny < WORLD_MIN_Y or box.maxy > WORLD_MAX_Y:
            raise ValueError(f"latitude out of range in bbox {bbox_lonlat}")
        for level in levels:
            t = hierarchy.tiles(level)
            min_col, max_col = t.col(box.minx), t.col(box.maxx)
            min_row, max_row = t.row(box.miny), t.row(box.maxy)
            if -1 in (min_col, max_col, min_row, max_row):
                raise ValueError(f"bbox {bbox_lonlat} outside tile system")
            for r in range(min_row, max_row + 1):
                for c in range(min_col, max_col + 1):
                    yield t.file_path(r * t.ncolumns + c, level, suffix)
