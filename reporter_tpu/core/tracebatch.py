"""Columnar trace batches: the zero-dict wire format of the host pipeline.

The reference moves traces through every layer as JSON-shaped point dicts
(``[{lat, lon, time, accuracy}, ...]``, reference: reporter_service.py:240)
and the first rounds here did the same — BENCH_r05 measured the cost: with
the batched device decode down to ~10% of the wall, per-point Python (dict
construction at the ingestion edges, dict re-reads in ``prepare_batch``'s
``np.fromiter`` scatter) dominated host prep at 62% of batch time.

:class:`TraceBatch` is the fix: one flat float64 column per coordinate
(``lat``/``lon``/``time``, optional ``accuracy``) over ALL traces, with a
``(B+1,)`` offsets array marking trace boundaries — the classic columnar
layout of data-parallel input pipelines (PAPERS.md: MapReduce/Kafka
Streams). Every ingestion edge (HTTP service, streaming worker, batch
pipeline, bench synthesis) converts to columns ONCE at the wire, and the
matcher consumes the columns directly; point dicts are only materialised
on demand for the few consumers that want JSON back (HTTP split
deployments, error paths).

``TraceView`` / ``PointsView`` keep the old request-dict surface alive
(``trace["trace"][-1]["time"]`` etc.) so ``report()`` and the tile
emitters work unchanged on either representation.
"""
from __future__ import annotations

from typing import Sequence

import numpy as np


def points_to_columns(points: Sequence[dict]):
    """One pass over a point-dict list -> (lat, lon, time, accuracy) f64/f32
    arrays. The only place a request's point dicts are ever read."""
    n = len(points)
    lat = np.fromiter((p["lat"] for p in points), np.float64, n)
    lon = np.fromiter((p["lon"] for p in points), np.float64, n)
    time = np.fromiter((p["time"] for p in points), np.float64, n)
    if points and "accuracy" in points[0]:
        try:
            acc = np.fromiter((p.get("accuracy", 0) for p in points),
                              np.float32, n)
        except (TypeError, ValueError):
            acc = None
    else:
        acc = None
    return lat, lon, time, acc


class PointsView:
    """Sequence view over one trace's points in a :class:`TraceBatch`.

    Materialises a dict per *accessed* point only — consumers like
    ``report()`` touch two points per trace, not all of them.
    """

    __slots__ = ("_tb", "_lo", "_hi")

    def __init__(self, tb: "TraceBatch", lo: int, hi: int):
        self._tb = tb
        self._lo = lo
        self._hi = hi

    def __len__(self) -> int:
        return self._hi - self._lo

    def _point(self, j: int) -> dict:
        tb = self._tb
        p = {"lat": float(tb.lat[j]), "lon": float(tb.lon[j]),
             "time": float(tb.time[j])}
        if tb.accuracy is not None:
            p["accuracy"] = int(tb.accuracy[j])
        return p

    def __getitem__(self, i):
        n = len(self)
        if isinstance(i, slice):
            return [self._point(self._lo + j) for j in range(*i.indices(n))]
        if i < 0:
            i += n
        if not 0 <= i < n:
            raise IndexError(i)
        return self._point(self._lo + i)

    def __iter__(self):
        for j in range(self._lo, self._hi):
            yield self._point(j)


class TraceView:
    """Dict-shaped view of one trace in a :class:`TraceBatch` — quacks like
    the reference's request dict ({"uuid", "trace", "match_options"}) for
    ``report()`` and the tile emitters, without materialising points."""

    __slots__ = ("_tb", "_i")

    def __init__(self, tb: "TraceBatch", i: int):
        self._tb = tb
        self._i = i

    def __getitem__(self, key):
        v = self.get(key, _MISSING)
        if v is _MISSING:
            raise KeyError(key)
        return v

    def get(self, key, default=None):
        tb = self._tb
        if key == "uuid":
            u = tb.uuid(self._i)
            return u if u is not None else default
        if key == "trace":
            lo, hi = int(tb.offsets[self._i]), int(tb.offsets[self._i + 1])
            return PointsView(tb, lo, hi)
        if key == "match_options":
            o = tb.option(self._i)
            return o if o is not None else default
        return default

    def __contains__(self, key) -> bool:
        return self.get(key, _MISSING) is not _MISSING

    def end_time(self) -> float:
        """Last probe's epoch seconds (the report holdback anchor)."""
        return float(self._tb.time[int(self._tb.offsets[self._i + 1]) - 1])

    def to_request(self) -> dict:
        """Materialise the plain request dict (HTTP split deployments)."""
        out = {"trace": list(self["trace"])}
        u = self.get("uuid")
        if u is not None:
            out["uuid"] = u
        o = self.get("match_options")
        if o is not None:
            out["match_options"] = o
        return out


_MISSING = object()


class TraceBatch:
    """B traces as flat columns + offsets; the matcher's native currency.

    ``options`` is either one shared match_options dict for every trace
    (the service steady state — lets the matcher skip per-trace param
    resolution entirely) or a per-trace list; ``uuids`` is optional.
    """

    __slots__ = ("offsets", "lat", "lon", "time", "accuracy", "uuids",
                 "options")

    def __init__(self, offsets, lat, lon, time, accuracy=None, uuids=None,
                 options=None):
        self.offsets = np.ascontiguousarray(offsets, dtype=np.int64)
        self.lat = np.ascontiguousarray(lat, dtype=np.float64)
        self.lon = np.ascontiguousarray(lon, dtype=np.float64)
        self.time = np.ascontiguousarray(time, dtype=np.float64)
        self.accuracy = accuracy
        self.uuids = uuids
        self.options = options

    # ---- construction ----------------------------------------------------
    @classmethod
    def from_requests(cls, reqs: Sequence[dict]) -> "TraceBatch":
        """Convert request dicts once, at the edge. Accepts anything whose
        elements support ["trace"]/.get — including TraceViews."""
        counts = [len(r["trace"]) for r in reqs]
        offsets = np.zeros(len(reqs) + 1, dtype=np.int64)
        np.cumsum(counts, out=offsets[1:])
        n = int(offsets[-1])
        lat = np.fromiter(
            (p["lat"] for r in reqs for p in r["trace"]), np.float64, n)
        lon = np.fromiter(
            (p["lon"] for r in reqs for p in r["trace"]), np.float64, n)
        time = np.fromiter(
            (p["time"] for r in reqs for p in r["trace"]), np.float64, n)
        uuids = [r.get("uuid") for r in reqs]
        options = [r.get("match_options") for r in reqs]
        return cls(offsets, lat, lon, time, uuids=uuids, options=options)

    @classmethod
    def concat(cls, parts: Sequence[tuple]) -> "TraceBatch":
        """Build from per-trace pieces: (uuid, lat, lon, time, accuracy,
        options) with array coordinates — the dispatcher path, where each
        request thread columnarised its own trace already."""
        counts = [len(p[1]) for p in parts]
        offsets = np.zeros(len(parts) + 1, dtype=np.int64)
        np.cumsum(counts, out=offsets[1:])
        lat = np.concatenate([np.asarray(p[1], np.float64) for p in parts]) \
            if parts else np.zeros(0)
        lon = np.concatenate([np.asarray(p[2], np.float64) for p in parts]) \
            if parts else np.zeros(0)
        time = np.concatenate([np.asarray(p[3], np.float64) for p in parts]) \
            if parts else np.zeros(0)
        accs = [p[4] for p in parts]
        acc = np.concatenate([np.asarray(a, np.float32) for a in accs]) \
            if parts and all(a is not None for a in accs) else None
        opts = [p[5] for p in parts]
        if opts and all(o is opts[0] for o in opts):
            # one shared options object collapses so the matcher resolves
            # params once for the whole batch
            opts = opts[0]
        return cls(offsets, lat, lon, time, accuracy=acc,
                   uuids=[p[0] for p in parts], options=opts)

    # ---- per-trace access ------------------------------------------------
    def __len__(self) -> int:
        return len(self.offsets) - 1

    def lengths(self) -> np.ndarray:
        return np.diff(self.offsets)

    def uuid(self, i: int):
        return self.uuids[i] if self.uuids is not None else None

    def option(self, i: int):
        if self.options is None or isinstance(self.options, dict):
            return self.options
        return self.options[i]

    def __getitem__(self, i: int) -> TraceView:
        return TraceView(self, i)

    def __iter__(self):
        for i in range(len(self)):
            yield TraceView(self, i)

    def trace_columns(self, i: int):
        """(lat, lon, time) slices of one trace — zero copy."""
        lo, hi = int(self.offsets[i]), int(self.offsets[i + 1])
        return self.lat[lo:hi], self.lon[lo:hi], self.time[lo:hi]

    # ---- batch restructuring (the matcher's chunking) --------------------
    def gather(self, idx) -> "TraceBatch":
        """New TraceBatch of the traces at ``idx``, in that order — one
        vectorised ragged gather, no per-point work."""
        idx = np.asarray(idx, dtype=np.int64)
        counts = self.lengths()[idx]
        offsets = np.zeros(len(idx) + 1, dtype=np.int64)
        np.cumsum(counts, out=offsets[1:])
        total = int(offsets[-1])
        if total:
            starts = self.offsets[idx]
            if len(idx) and int(idx[-1]) - int(idx[0]) == len(idx) - 1 \
                    and bool((np.diff(idx) == 1).all()):
                # contiguous run of traces (the steady-state chunking):
                # zero-copy views instead of a fancy gather
                lo = int(starts[0])
                hi = lo + total
                lat, lon, time = (self.lat[lo:hi], self.lon[lo:hi],
                                  self.time[lo:hi])
                acc = self.accuracy[lo:hi] \
                    if self.accuracy is not None else None
            else:
                # ragged range gather: arange per trace, offset to source
                flat = np.arange(total, dtype=np.int64) + np.repeat(
                    starts - offsets[:-1], counts)
                lat, lon, time = (self.lat[flat], self.lon[flat],
                                  self.time[flat])
                acc = self.accuracy[flat] \
                    if self.accuracy is not None else None
        else:
            lat = lon = time = np.zeros(0)
            acc = None
        opts = self.options if self.options is None \
            or isinstance(self.options, dict) \
            else [self.options[int(i)] for i in idx]
        uu = None if self.uuids is None else [self.uuids[int(i)] for i in idx]
        return TraceBatch(offsets, lat, lon, time, accuracy=acc,
                          uuids=uu, options=opts)


def as_trace_batch(traces) -> TraceBatch:
    """Normalise a match_many input: TraceBatch passes through, request
    dicts convert once."""
    if isinstance(traces, TraceBatch):
        return traces
    return TraceBatch.from_requests(traces)


__all__ = ["TraceBatch", "TraceView", "PointsView", "as_trace_batch",
           "points_to_columns"]
