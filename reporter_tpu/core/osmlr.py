"""OSMLR 64-bit segment-id bit layout.

An OSMLR traffic segment id packs, from the low bits up: a 3-bit hierarchy
level, a 22-bit tile index within that level, and a 21-bit segment index
within the tile (reference: py/simple_reporter.py:36-49; mirrored in Java at
src/main/java/io/opentraffic/reporter/Segment.java:33-36 and
TimeQuantisedTile.java:37-43).

The all-ones 46-bit value is the INVALID sentinel used for "no next segment"
(reference: Segment.java:16, simple_reporter.py:43).
"""

LEVEL_BITS = 3
TILE_INDEX_BITS = 22
SEGMENT_INDEX_BITS = 21

LEVEL_MASK = (1 << LEVEL_BITS) - 1
TILE_INDEX_MASK = (1 << TILE_INDEX_BITS) - 1
SEGMENT_INDEX_MASK = (1 << SEGMENT_INDEX_BITS) - 1

INVALID_SEGMENT_ID = (
    (SEGMENT_INDEX_MASK << (TILE_INDEX_BITS + LEVEL_BITS))
    | (TILE_INDEX_MASK << LEVEL_BITS)
    | LEVEL_MASK
)  # == 0x3fffffffffff


def make_segment_id(level: int, tile_idx: int, seg_idx: int) -> int:
    """Pack (level, tile index, segment index) into a 64-bit OSMLR id."""
    if not 0 <= level <= LEVEL_MASK:
        raise ValueError(f"level {level} out of range")
    if not 0 <= tile_idx <= TILE_INDEX_MASK:
        raise ValueError(f"tile index {tile_idx} out of range")
    if not 0 <= seg_idx <= SEGMENT_INDEX_MASK:
        raise ValueError(f"segment index {seg_idx} out of range")
    return (seg_idx << (TILE_INDEX_BITS + LEVEL_BITS)) | (tile_idx << LEVEL_BITS) | level


def tile_level(segment_id: int) -> int:
    """Hierarchy level (0=highway, 1=arterial, 2=local) from the low 3 bits."""
    return segment_id & LEVEL_MASK


def tile_index(segment_id: int) -> int:
    return (segment_id >> LEVEL_BITS) & TILE_INDEX_MASK


def segment_index(segment_id: int) -> int:
    return (segment_id >> (LEVEL_BITS + TILE_INDEX_BITS)) & SEGMENT_INDEX_MASK


def tile_id_of_segment(segment_id: int) -> int:
    """Level + tile-index bits only — the 25-bit graph tile id.

    (reference: Segment.java:34-36 ``id & 0x1FFFFFF``)
    """
    return segment_id & ((1 << (LEVEL_BITS + TILE_INDEX_BITS)) - 1)
