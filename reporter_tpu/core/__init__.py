from .osmlr import (
    LEVEL_BITS,
    TILE_INDEX_BITS,
    SEGMENT_INDEX_BITS,
    LEVEL_MASK,
    TILE_INDEX_MASK,
    SEGMENT_INDEX_MASK,
    INVALID_SEGMENT_ID,
    make_segment_id,
    tile_level,
    tile_index,
    segment_index,
    tile_id_of_segment,
)
from .geo import equirectangular_m, METERS_PER_DEG
from .types import Point, Segment, TimeQuantisedTile
from .tiles import TileHierarchy, Tiles, BoundingBox, tiles_for_bbox

__all__ = [
    "LEVEL_BITS", "TILE_INDEX_BITS", "SEGMENT_INDEX_BITS",
    "LEVEL_MASK", "TILE_INDEX_MASK", "SEGMENT_INDEX_MASK",
    "INVALID_SEGMENT_ID",
    "make_segment_id", "tile_level", "tile_index", "segment_index",
    "tile_id_of_segment",
    "equirectangular_m", "METERS_PER_DEG",
    "Point", "Segment", "TimeQuantisedTile",
    "TileHierarchy", "Tiles", "BoundingBox", "tiles_for_bbox",
]
