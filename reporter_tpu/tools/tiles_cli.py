"""Tile tooling: list / download / tar graph tiles for a bounding box.

Equivalent of the reference's ``py/get_tiles.py`` CLI (bbox -> tile file
paths, get_tiles.py:104-172) and ``py/download_tiles.sh`` (parallel curl
download + optional tar, download_tiles.sh:55-77), built on the tile
hierarchy math in :mod:`reporter_tpu.core.tiles`.

``download`` fetches over HTTP with a thread pool (this image has no
network egress — the code path is exercised in tests against a local
server). Missing tiles are warned about, not fatal, matching the
reference's behavior (download_tiles.sh:62-69).
"""
from __future__ import annotations

import argparse
import concurrent.futures
import logging
import os
import sys
import tarfile
import time
import urllib.error
import urllib.request

from ..core.tiles import tiles_for_bbox

logger = logging.getLogger("reporter_tpu.tiles")


def list_tiles(bbox: list[float], suffix: str = "gph",
               levels=(0, 1, 2)) -> list[str]:
    return list(tiles_for_bbox(bbox, suffix=suffix, levels=levels))


def fetch_one(url: str, dest: str, timeout: float = 30.0) -> bool:
    os.makedirs(os.path.dirname(dest) or ".", exist_ok=True)
    try:
        with urllib.request.urlopen(url, timeout=timeout) as resp:
            with open(dest, "wb") as out:
                out.write(resp.read())
        return True
    except (urllib.error.URLError, OSError) as e:
        logger.warning("%s was not found! (%s)", url, e)
        return False


def download_tiles(bbox: list[float], base_url: str, out_dir: str,
                   processes: int = 5, suffix: str = "gph",
                   levels=(0, 1, 2), tar_output: bool = False) -> list[str]:
    """Download every tile in the bbox; returns the list of missing paths."""
    paths = list_tiles(bbox, suffix=suffix, levels=levels)
    base = base_url.rstrip("/")
    with concurrent.futures.ThreadPoolExecutor(max_workers=processes) as ex:
        ok = list(ex.map(
            lambda p: fetch_one(f"{base}/{p}", os.path.join(out_dir, p)),
            paths))
    missing = [p for p, good in zip(paths, ok) if not good]
    if tar_output:
        # sorted, no-recursion member list like the reference's tar invocation
        stamp = time.strftime("%Y_%m_%d-%H_%M_%S")
        tar_path = os.path.join(out_dir, f"tiles_{stamp}.tar")
        with tarfile.open(tar_path, "w") as tar:
            for p in sorted(set(paths) - set(missing)):
                tar.add(os.path.join(out_dir, p), arcname=p, recursive=False)
        logger.info("Wrote %s", tar_path)
    return missing


def _levels(arg: str):
    return tuple(int(x) for x in arg.split(","))


def main(argv=None):
    parser = argparse.ArgumentParser(
        prog="reporter-tiles", description="Graph tile tooling")
    sub = parser.add_subparsers(dest="cmd", required=True)

    p_list = sub.add_parser("list", help="print tile paths for a bbox")
    p_list.add_argument("--bbox", required=True,
                        help="min_lon,min_lat,max_lon,max_lat")
    p_list.add_argument("--suffix", default="gph")
    p_list.add_argument("--levels", type=_levels, default=(0, 1, 2))

    p_dl = sub.add_parser("download", help="download tiles for a bbox")
    p_dl.add_argument("--bbox", required=True)
    p_dl.add_argument("--url", required=True)
    p_dl.add_argument("--output-dir", required=True)
    p_dl.add_argument("--processes", type=int, default=5)
    p_dl.add_argument("--suffix", default="gph")
    p_dl.add_argument("--levels", type=_levels, default=(0, 1, 2))
    p_dl.add_argument("--tar", action="store_true")

    args = parser.parse_args(argv)
    logging.basicConfig(level=logging.INFO,
                        format="%(asctime)s %(levelname)s %(message)s")
    bbox = [float(x) for x in args.bbox.split(",")]

    if args.cmd == "list":
        for path in list_tiles(bbox, args.suffix, args.levels):
            print(path)
        return 0

    missing = download_tiles(bbox, args.url, args.output_dir,
                             processes=args.processes, suffix=args.suffix,
                             levels=args.levels, tar_output=args.tar)
    if missing:
        logger.warning("%d tiles missing", len(missing))
    return 0


if __name__ == "__main__":
    sys.exit(main())
