"""Operational CLIs: replay producer, debug consumer, tile tooling.

These mirror the reference's ops scripts (py/cat_to_kafka.py,
py/make_requests.sh, py/get_tiles.py + py/download_tiles.sh,
PrintConsumer.java) as first-class framework commands under
``python -m reporter_tpu``.
"""
