"""Synthetic-trace generator CLI.

Equivalent of the reference's ``py/generate_test_trace.py`` (route ->
per-second interpolation -> Gaussian noise -> POST /report,
generate_test_trace.py:181-203), against this framework's synthetic road
networks instead of a live Valhalla server. Emits, per trace:

  sv      one ``uuid|lat|lon|time|accuracy`` line per probe point — pipe
          into ``python -m reporter_tpu stream -f '|sv|\\|,0,1,2,3,4'``
  json    one /report request body per line (Batch.java:56-66 shape)
  post    POST each body to --url and print the datastore response
          (generate_test_trace.py:192-199)
"""
from __future__ import annotations

import argparse
import json
import sys
import urllib.request

import numpy as np


def emit_sv(trace, out):
    for p in trace.points:
        out.write(f"{trace.uuid}|{p['lat']}|{p['lon']}|{p['time']}"
                  f"|{p['accuracy']}\n")


def main(argv=None):
    parser = argparse.ArgumentParser(
        prog="reporter-synth",
        description="Generate noisy synthetic GPS traces with ground truth")
    parser.add_argument("--traces", type=int, default=10)
    parser.add_argument("--noise-m", type=float, default=5.0)
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument("--rows", type=int, default=20)
    parser.add_argument("--cols", type=int, default=20)
    parser.add_argument("--spacing-m", type=float, default=200.0)
    parser.add_argument("--graph", help="RoadNetwork file; omit for a "
                        "generated grid city")
    parser.add_argument("--format", choices=("sv", "json", "post"),
                        default="sv")
    parser.add_argument("--url", help="reporter /report url (format=post)")
    parser.add_argument("--mode", default="auto")
    args = parser.parse_args(argv)

    from ..synth import build_grid_city, generate_trace
    if args.graph:
        from ..graph.network import RoadNetwork
        net = RoadNetwork.load(args.graph)
    else:
        net = build_grid_city(rows=args.rows, cols=args.cols,
                              spacing_m=args.spacing_m, seed=args.seed)

    rng = np.random.default_rng(args.seed)
    made = 0
    while made < args.traces:
        tr = generate_trace(net, f"synth-{made}", rng, noise_m=args.noise_m)
        if tr is None:
            continue
        made += 1
        if args.format == "sv":
            emit_sv(tr, sys.stdout)
        elif args.format == "json":
            print(json.dumps(tr.request_json(mode=args.mode)))
        else:
            if not args.url:
                parser.error("--url is required with --format post")
            body = json.dumps(tr.request_json(mode=args.mode)).encode()
            req = urllib.request.Request(
                args.url, data=body,
                headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(req, timeout=30) as resp:
                print(resp.read().decode())
    return 0


if __name__ == "__main__":
    sys.exit(main())
