"""Datastore CLI: ingest / compact / query / stats over a histogram store.

  python -m reporter_tpu datastore ingest  <store> <results-dir> [--delete]
  python -m reporter_tpu datastore compact <store> [--level L] [--index I]
  python -m reporter_tpu datastore query   <store> --segment ID
                                           [--hours 7-9|7,8,9]
                                           [--t0 EPOCH --t1 EPOCH]
                                           [--percentiles 25,50,75,95]
  python -m reporter_tpu datastore stats   <store>

``ingest`` replays any directory in the anonymiser's flush layout — a
results dir OR its ``.deadletter`` spool; ``--delete`` removes each tile
file after a successful append (the dead-letter replay contract). All
output is one JSON object per line, metrics timers included, so the
commands compose in scripts the way bench.py's artifact lines do.
"""
from __future__ import annotations

import argparse
import json
import sys

from ..datastore import LocalDatastore, parse_hours_spec
from ..utils import metrics


def main(argv=None):
    parser = argparse.ArgumentParser(
        prog="reporter-datastore", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    sub = parser.add_subparsers(dest="cmd", required=True)

    p_ing = sub.add_parser("ingest", help="replay flushed tiles into the store")
    p_ing.add_argument("store")
    p_ing.add_argument("source", help="results or dead-letter directory")
    p_ing.add_argument("--delete", action="store_true",
                       help="remove each tile file after a successful "
                            "append (dead-letter replay)")
    p_ing.add_argument("--limit", type=int, default=None)

    p_cmp = sub.add_parser("compact", help="merge partition deltas")
    p_cmp.add_argument("store")
    p_cmp.add_argument("--level", type=int, default=None)
    p_cmp.add_argument("--index", type=int, default=None)
    p_cmp.add_argument("--max-deltas", type=int, default=None,
                       help="automatic policy: only compact partitions "
                            "with more than N uncompacted deltas")
    p_cmp.add_argument("--max-delta-bytes", type=int, default=None,
                       help="automatic policy: only compact partitions "
                            "whose uncompacted deltas exceed B bytes")

    p_qry = sub.add_parser("query", help="one segment's speed histogram")
    p_qry.add_argument("store")
    p_qry.add_argument("--segment", type=int, required=True)
    p_qry.add_argument("--hours", default=None,
                       help="hour-of-week subset: '7-9' or '7,8,9'")
    p_qry.add_argument("--t0", type=int, default=None,
                       help="epoch range start (with --t1; alternative "
                            "to --hours)")
    p_qry.add_argument("--t1", type=int, default=None)
    p_qry.add_argument("--percentiles", default=None,
                       help="comma-separated, e.g. 25,50,75,95")

    p_sts = sub.add_parser("stats", help="partition/segment/byte totals")
    p_sts.add_argument("store")

    args = parser.parse_args(argv)
    ds = LocalDatastore(args.store)

    if args.cmd == "ingest":
        out = ds.ingest_dir(args.source, delete=args.delete,
                            limit=args.limit)
        out["metrics"] = metrics.snapshot()["timers"]
    elif args.cmd == "compact":
        out = ds.compact(level=args.level, index=args.index,
                         max_deltas=args.max_deltas,
                         max_delta_bytes=args.max_delta_bytes)
    elif args.cmd == "query":
        hours = parse_hours_spec(args.hours)
        if hours is None and args.t0 is not None and args.t1 is not None:
            from ..datastore import hours_for_range
            hours = hours_for_range(args.t0, args.t1).tolist()
        kwargs = {}
        if args.percentiles:
            kwargs["percentiles"] = [
                float(p) for p in args.percentiles.split(",") if p]
        out = ds.query(args.segment, hours=hours, **kwargs)
    else:
        out = ds.stats()

    print(json.dumps(out, separators=(",", ":")))
    return 0


if __name__ == "__main__":
    sys.exit(main())
