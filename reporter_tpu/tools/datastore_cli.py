"""Datastore CLI: ingest / compact / query / profile over a histogram store.

  python -m reporter_tpu datastore ingest  <store> <results-dir> [--delete]
  python -m reporter_tpu datastore compact <store> [--level L] [--index I]
  python -m reporter_tpu datastore query   <store> --segment ID
                                           [--segments A,B,C]
                                           [--bbox MINLON,MINLAT,MAXLON,MAXLAT
                                            --bbox-level L]
                                           [--hours 7-9|7,8,9]
                                           [--t0 EPOCH --t1 EPOCH]
                                           [--percentiles 25,50,75,95]
                                           [--window 5m|90s|inf]
  python -m reporter_tpu datastore feed    <store> [--bbox ... --level L]
                                           [--cursor N] [--timeout S]
                                           [--max-polls N]
  python -m reporter_tpu datastore profile <store> [--graph city.npz
                                           --replay traces.jsonl]
                                           [--cap N] [--city NAME]
  python -m reporter_tpu datastore stats   <store>

``ingest`` replays any directory in the anonymiser's flush layout — a
results dir OR its ``.deadletter`` spool; ``--delete`` removes each tile
file after a successful append (the dead-letter replay contract).
``--segments`` / ``--bbox`` serve many segments through ONE
``query_many`` sweep per partition (datastore/query.py); ``--window``
answers from the freshness tier's recent-delta overlay (``5m``-style
specs; ``inf`` merges overlay + compacted — see "Freshness tier" in
the README). ``feed`` tails a change-feed cursor over the store: each
long-poll prints one JSON line (events + next cursor) and the next
poll resumes from it, so ``--max-polls N`` makes it scriptable the way
the query commands are; cross-process commits surface via the store
watcher, which the command forces once per poll. ``profile``
with ``--replay`` runs the request JSONs (one per line) through a
matcher on ``--graph`` and commits the native route memo's resident
pairs as the store's ``.profile`` pre-warm artifact; without
``--replay`` it prints the committed artifact's summary. All output is
one JSON object per line, metrics timers included, so the commands
compose in scripts the way bench.py's artifact lines do.
"""
from __future__ import annotations

import argparse
import json
import sys

from ..datastore import LocalDatastore, parse_hours_spec
from ..utils import metrics


def _profile(ds, args) -> dict:
    """``profile`` subcommand body: export (with --replay) or show."""
    from ..datastore.profile import (
        export_profile,
        load_profile,
        profile_path,
    )
    path = args.out or profile_path(ds.root)
    if args.replay is None:
        art = load_profile(path)
        if art is None:
            return {"path": path, "present": False}
        return {"path": path, "present": True, "city": art.get("city"),
                "n_pairs": art.get("n_pairs"),
                "memo_stats": art.get("memo_stats")}
    if not args.graph:
        raise SystemExit("profile --replay needs --graph")
    from ..graph.network import RoadNetwork
    from ..matcher import SegmentMatcher
    matcher = SegmentMatcher(net=RoadNetwork.load(args.graph))
    reqs = []
    with open(args.replay, encoding="utf-8") as f:
        for line in f:
            if line.strip():
                reqs.append(json.loads(line))
    # chunked replay: warm the memo the way serving traffic would
    for i in range(0, len(reqs), 256):
        matcher.match_many(reqs[i:i + 256])
    art = export_profile(matcher, path, cap=args.cap, city=args.city)
    return {"path": path, "n_pairs": art["n_pairs"],
            "replayed": len(reqs), "memo_stats": art["memo_stats"]}


def main(argv=None):
    parser = argparse.ArgumentParser(
        prog="reporter-datastore", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    sub = parser.add_subparsers(dest="cmd", required=True)

    p_ing = sub.add_parser("ingest", help="replay flushed tiles into the store")
    p_ing.add_argument("store")
    p_ing.add_argument("source", help="results or dead-letter directory")
    p_ing.add_argument("--delete", action="store_true",
                       help="remove each tile file after a successful "
                            "append (dead-letter replay)")
    p_ing.add_argument("--limit", type=int, default=None)

    p_cmp = sub.add_parser("compact", help="merge partition deltas")
    p_cmp.add_argument("store")
    p_cmp.add_argument("--level", type=int, default=None)
    p_cmp.add_argument("--index", type=int, default=None)
    p_cmp.add_argument("--max-deltas", type=int, default=None,
                       help="automatic policy: only compact partitions "
                            "with more than N uncompacted deltas")
    p_cmp.add_argument("--max-delta-bytes", type=int, default=None,
                       help="automatic policy: only compact partitions "
                            "whose uncompacted deltas exceed B bytes")

    p_qry = sub.add_parser("query", help="segment speed histograms "
                           "(single, batched list, or bbox)")
    p_qry.add_argument("store")
    p_qry.add_argument("--segment", type=int, default=None)
    p_qry.add_argument("--segments", default=None,
                       help="comma-separated ids served through one "
                            "query_many sweep")
    p_qry.add_argument("--bbox", default=None,
                       help="min_lon,min_lat,max_lon,max_lat — every "
                            "resident segment of --bbox-level inside")
    p_qry.add_argument("--bbox-level", type=int, default=2)
    p_qry.add_argument("--max-segments", type=int, default=None,
                       help="bbox fan-out bound (explicit truncation)")
    p_qry.add_argument("--hours", default=None,
                       help="hour-of-week subset: '7-9' or '7,8,9'")
    p_qry.add_argument("--t0", type=int, default=None,
                       help="epoch range start (with --t1; alternative "
                            "to --hours)")
    p_qry.add_argument("--t1", type=int, default=None)
    p_qry.add_argument("--percentiles", default=None,
                       help="comma-separated, e.g. 25,50,75,95")
    p_qry.add_argument("--window", default=None,
                       help="freshness window: '5m'/'90s'/seconds for "
                            "recent-overlay-only answers, 'inf' for "
                            "overlay+compacted merge; omit for the "
                            "compacted store only")

    p_fed = sub.add_parser("feed", help="tail a change-feed cursor "
                           "(one JSON line per long-poll)")
    p_fed.add_argument("store")
    p_fed.add_argument("--bbox", default=None,
                       help="min_lon,min_lat,max_lon,max_lat viewport "
                            "filter (needs --level)")
    p_fed.add_argument("--level", type=int, default=None)
    p_fed.add_argument("--cursor", type=int, default=-1,
                       help="resume cursor; -1 = from now")
    p_fed.add_argument("--timeout", type=float, default=25.0,
                       help="seconds each long-poll blocks")
    p_fed.add_argument("--max-polls", type=int, default=0,
                       help="stop after N polls (0 = forever)")

    p_prf = sub.add_parser("profile", help="route-memo pre-warm "
                           "artifact: export from a replay, or show")
    p_prf.add_argument("store")
    p_prf.add_argument("--graph", default=None,
                       help="RoadNetwork .npz to replay against")
    p_prf.add_argument("--replay", default=None,
                       help="request JSONs, one per line (the /report "
                            "body shape); replayed through match_many "
                            "to warm the memo before export")
    p_prf.add_argument("--cap", type=int, default=1 << 16,
                       help="max pairs exported")
    p_prf.add_argument("--city", default=None,
                       help="city name stamped into the artifact")
    p_prf.add_argument("--out", default=None,
                       help="artifact path (default <store>/.profile)")

    p_sts = sub.add_parser("stats", help="partition/segment/byte totals")
    p_sts.add_argument("store")

    args = parser.parse_args(argv)
    ds = LocalDatastore(args.store)

    if args.cmd == "ingest":
        out = ds.ingest_dir(args.source, delete=args.delete,
                            limit=args.limit)
        out["metrics"] = metrics.snapshot()["timers"]
        # clean exit hands the writer lease back (a successor acquires
        # a vacant lease instead of logging a dead-pid steal)
        ds.lease.release()
    elif args.cmd == "compact":
        out = ds.compact(level=args.level, index=args.index,
                         max_deltas=args.max_deltas,
                         max_delta_bytes=args.max_delta_bytes)
        ds.lease.release()
    elif args.cmd == "query":
        hours = parse_hours_spec(args.hours)
        if hours is None and args.t0 is not None and args.t1 is not None:
            from ..datastore import hours_for_range
            hours = hours_for_range(args.t0, args.t1).tolist()
        kwargs = {}
        if args.percentiles:
            kwargs["percentiles"] = [
                float(p) for p in args.percentiles.split(",") if p]
        if args.window is not None:
            from ..datastore.freshness import parse_window
            try:
                parse_window(args.window)
            except ValueError as e:
                parser.error(str(e))
            kwargs["window"] = args.window
        if args.bbox is not None:
            bbox = [float(v) for v in args.bbox.split(",")]
            if args.max_segments is not None:
                kwargs["max_segments"] = args.max_segments
            out = ds.query_bbox(bbox, args.bbox_level, hours=hours,
                                **kwargs)
        elif args.segments is not None:
            ids = [int(s) for s in args.segments.split(",") if s]
            out = {"results": ds.query_many(ids, hours=hours, **kwargs)}
        elif args.segment is not None:
            out = ds.query(args.segment, hours=hours, **kwargs)
        else:
            parser.error("query needs --segment, --segments or --bbox")
    elif args.cmd == "feed":
        tier = ds.enable_freshness()
        if tier is None:
            raise SystemExit("freshness tier disabled "
                             "(REPORTER_TPU_FRESHNESS=0)")
        bbox = None
        if args.bbox is not None:
            bbox = [float(v) for v in args.bbox.split(",")]
        cursor, polls = args.cursor, 0
        while args.max_polls <= 0 or polls < args.max_polls:
            # surface commits other processes made since the last poll
            # (the in-poll watcher is paced; a CLI tail wants each poll
            # to see the store's latest state)
            tier.feed.watch_store(force=True)
            out = tier.feed.poll(bbox=bbox, level=args.level,
                                 cursor=cursor, timeout_s=args.timeout)
            cursor = out["cursor"]
            polls += 1
            print(json.dumps(out, separators=(",", ":")), flush=True)
        return 0
    elif args.cmd == "profile":
        out = _profile(ds, args)
    else:
        out = ds.stats()

    print(json.dumps(out, separators=(",", ":")))
    return 0


if __name__ == "__main__":
    sys.exit(main())
