"""Graph tooling: build, tile, and inspect road networks.

The build-side analog of the reference's tile tooling (its graphs are
built externally by valhalla_build_config/valhalla tooling and consumed
read-only — Dockerfile:42-49): here the framework owns the format, so it
also owns construction.

  build-synth   generate a synthetic grid city -> monolithic .npz
  import-osm    parse raw OSM XML -> monolithic .npz (graph/osm.py)
  tile          partition a monolithic .npz into an RGT tile tree
  untile        compose a tile tree (optionally bbox-scoped) -> .npz
  info          counts for a .npz or tile tree
"""
from __future__ import annotations

import argparse
import sys


def main(argv=None):
    parser = argparse.ArgumentParser(
        prog="reporter-graph", description="Road-network tooling")
    sub = parser.add_subparsers(dest="cmd", required=True)

    p_b = sub.add_parser("build-synth", help="generate a grid city")
    p_b.add_argument("--rows", type=int, default=20)
    p_b.add_argument("--cols", type=int, default=20)
    p_b.add_argument("--spacing-m", type=float, default=200.0)
    p_b.add_argument("--seed", type=int, default=0)
    p_b.add_argument("--out", required=True, help=".npz path")

    p_o = sub.add_parser("import-osm", help="parse OSM XML into a graph")
    p_o.add_argument("--in", dest="osm_in", required=True,
                     help="OSM XML file (.osm / .xml)")
    p_o.add_argument("--out", required=True, help=".npz path")

    p_t = sub.add_parser("tile", help="partition a .npz into RGT tiles")
    p_t.add_argument("--graph", required=True)
    p_t.add_argument("--out-dir", required=True)

    p_u = sub.add_parser("untile", help="compose RGT tiles into a .npz")
    p_u.add_argument("--tile-dir", required=True)
    p_u.add_argument("--bbox", help="min_lon,min_lat,max_lon,max_lat; "
                     "omit for all tiles")
    p_u.add_argument("--out", required=True)

    p_i = sub.add_parser("info", help="print graph counts")
    p_i.add_argument("target", help=".npz file or tile tree dir")

    args = parser.parse_args(argv)

    from ..graph.network import RoadNetwork
    from ..graph.tilestore import GraphTileStore, write_tiles

    if args.cmd == "build-synth":
        from ..synth import build_grid_city
        net = build_grid_city(rows=args.rows, cols=args.cols,
                              spacing_m=args.spacing_m, seed=args.seed)
        net.save(args.out)
        print(f"wrote {args.out}: {net.num_nodes} nodes, "
              f"{net.num_edges} edges")
    elif args.cmd == "import-osm":
        from ..graph.osm import network_from_osm_xml
        net = network_from_osm_xml(args.osm_in)
        net.save(args.out)
        print(f"wrote {args.out}: {net.num_nodes} nodes, "
              f"{net.num_edges} edges, "
              f"{len(net.segment_length_m)} OSMLR segments")
    elif args.cmd == "tile":
        net = RoadNetwork.load(args.graph)
        written = write_tiles(net, args.out_dir)
        print(f"wrote {len(written)} tiles under {args.out_dir}")
        for rel in written:
            print(rel)
    elif args.cmd == "untile":
        store = GraphTileStore(args.tile_dir)
        if args.bbox:
            bbox = [float(x) for x in args.bbox.split(",")]
            net = store.load_bbox(bbox)
        else:
            net = store.load_all()
        net.save(args.out)
        print(f"wrote {args.out}: {net.num_nodes} nodes, "
              f"{net.num_edges} edges")
    else:  # info
        import os
        if os.path.isdir(args.target):
            store = GraphTileStore(args.target)
            paths = store.tile_paths()
            net = store.load_all()
            print(f"{len(paths)} tiles, {net.num_nodes} nodes, "
                  f"{net.num_edges} edges, "
                  f"{len(net.segment_length_m)} OSMLR segments")
        else:
            net = RoadNetwork.load(args.target)
            print(f"{net.num_nodes} nodes, {net.num_edges} edges, "
                  f"{len(net.segment_length_m)} OSMLR segments")
    return 0


if __name__ == "__main__":
    sys.exit(main())
