"""Deterministic OSM-XML city fixture: a realistic non-grid road network.

The accuracy gate needs a map that exercises the REAL import path
(graph/osm.py: way classification, one-way semantics, multi-node curved
ways, per-way OSMLR synthesis with in-way segment offsets) rather than
the synthetic grid whose edges are axis-aligned and one-per-segment.
This image has no network egress, so a genuine planet extract cannot be
fetched (the reference fetches one at build time,
load-historical-data/setup.sh:49-53); instead this module *generates*
an OSM XML document of a plausible mid-size town, deterministically —
same bytes every run, no checked-in binary blob:

- a jittered street net (sinusoidal node displacement: no two streets
  parallel or axis-aligned, varied block sizes and edge lengths);
- every street a single multi-node curved way (so one OSMLR segment
  spans many edges, with nonzero in-segment offsets — the assembly
  boundary-interpolation path the grid never exercises);
- mixed classes (primary diagonals, secondary arterials, residential
  infill), alternating one-way residentials, a motorway stub with
  ``_link`` ramps (internal edges), service alleys (unassociated);
- mixed ``maxspeed`` tag formats (kph, "N mph", absent).

Usage: python -m reporter_tpu.tools.osm_fixture --out city.osm.xml
"""
from __future__ import annotations

import argparse
import math
import sys
from typing import Dict, List, Tuple

LAT0, LON0 = 47.6000, -122.3300   # anchor; ~3 km x 3 km town
M_PER_DEG = 20037581.187 / 180.0
COS0 = math.cos(LAT0 * math.pi / 180.0)

GRID_N = 9          # major street grid
SPACING = 350.0     # meters between arterials


def _ll(x_m: float, y_m: float) -> Tuple[float, float]:
    return (LAT0 + y_m / M_PER_DEG,
            LON0 + x_m / (M_PER_DEG * COS0))


def _jitter(i: int, j: int) -> Tuple[float, float]:
    """Deterministic per-intersection displacement, up to ~±45 m — bends
    every street so ways are genuinely curved."""
    dx = 45.0 * math.sin(1.7 * i + 0.9 * j) * math.cos(0.6 * j)
    dy = 45.0 * math.sin(1.3 * j - 0.7 * i) * math.cos(0.8 * i)
    return dx, dy


def build_city_xml() -> str:
    nodes: Dict[Tuple[str, int, int], int] = {}
    node_ll: List[Tuple[int, float, float]] = []
    ways: List[Tuple[int, List[int], Dict[str, str]]] = []
    next_node = [1000]
    next_way = [9000]

    def node(kind: str, i: int, j: int, x: float, y: float) -> int:
        key = (kind, i, j)
        if key in nodes:
            return nodes[key]
        nid = next_node[0]
        next_node[0] += 1
        lat, lon = _ll(x, y)
        nodes[key] = nid
        node_ll.append((nid, lat, lon))
        return nid

    def grid_node(i: int, j: int) -> int:
        dx, dy = _jitter(i, j)
        return node("g", i, j, i * SPACING + dx, j * SPACING + dy)

    def vmid_node(i: int, j: int) -> int:
        """Midpoint of avenue i between rows j and j+1 (shared between
        the avenue and the residential mid-row crossing it)."""
        dx, dy = _jitter(i, j)
        dx2, dy2 = _jitter(i, j + 1)
        return node("vm", i, j, i * SPACING + 0.5 * (dx + dx2),
                    (j + 0.5) * SPACING + 0.5 * (dy + dy2))

    def way(node_ids: List[int], tags: Dict[str, str]) -> None:
        wid = next_way[0]
        next_way[0] += 1
        ways.append((wid, node_ids, tags))

    # arterials: each full row/column one curved multi-node way; avenues
    # thread through midpoint nodes so residential mid-rows intersect them
    for j in range(GRID_N):
        way([grid_node(i, j) for i in range(GRID_N)],
            {"highway": "secondary", "name": f"East Street {j}",
             **({"maxspeed": "50"} if j % 3 == 0 else {})})
    for i in range(GRID_N):
        nds = []
        for j in range(GRID_N):
            nds.append(grid_node(i, j))
            if j < GRID_N - 1:
                nds.append(vmid_node(i, j))
        way(nds, {"highway": "secondary", "name": f"North Avenue {i}",
                  **({"maxspeed": "35 mph"} if i % 3 == 1 else {})})

    # two primary diagonals weaving through grid intersections
    diag = []
    for k in range(GRID_N):
        diag.append(grid_node(k, k))
        if k < GRID_N - 1:
            dx, dy = _jitter(k, k)
            diag.append(node("d1", k, k,
                             (k + 0.5) * SPACING + dx + 40.0,
                             (k + 0.5) * SPACING + dy - 35.0))
    way(diag, {"highway": "primary", "name": "Grand Diagonal",
               "maxspeed": "60"})
    diag2 = []
    for k in range(GRID_N):
        i, j = k, GRID_N - 1 - k
        diag2.append(grid_node(i, j))
        if k < GRID_N - 1:
            dx, dy = _jitter(i, j)
            diag2.append(node("d2", i, j,
                              (i + 0.5) * SPACING + dx - 30.0,
                              (j - 0.5) * SPACING + dy + 25.0))
    way(diag2, {"highway": "primary", "name": "Counter Diagonal"})

    # residential infill: midblock streets between arterial rows,
    # alternating one-way, intersecting every avenue at its midpoint node
    for j in range(GRID_N - 1):
        mids = []
        for i in range(GRID_N):
            mids.append(vmid_node(i, j))
            if i < GRID_N - 1:
                dx, dy = _jitter(i, j)
                mids.append(node("r", i, j,
                                 (i + 0.5) * SPACING + dx + 15.0,
                                 (j + 0.5) * SPACING + dy
                                 + 25.0 * math.sin(1.1 * i + j)))
        tags = {"highway": "residential", "name": f"Mid Row {j}"}
        if j % 2 == 0:
            tags["oneway"] = "yes"
        way(mids, tags)

    # motorway stub north of town with link ramps (internal edges)
    mw = []
    for i in range(GRID_N):
        mw.append(node("m", i, 0, i * SPACING,
                       GRID_N * SPACING + 240.0 + 30.0 * math.sin(0.9 * i)))
    way(mw, {"highway": "motorway", "oneway": "yes",
             "name": "Bypass", "maxspeed": "100"})
    for i in (2, 6):
        way([mw[i], grid_node(i, GRID_N - 1)],
            {"highway": "motorway_link", "oneway": "yes"})
        way([grid_node(i + 1, GRID_N - 1), mw[i + 1]],
            {"highway": "motorway_link", "oneway": "yes"})

    # service alleys (unassociated edges)
    for i in (1, 4, 7):
        a = grid_node(i, 1)
        dx, dy = _jitter(i, 1)
        b = node("s", i, 1, i * SPACING + dx + 90.0,
                 1 * SPACING + dy + 110.0)
        way([a, b], {"highway": "service"})

    out = ['<?xml version="1.0" encoding="UTF-8"?>',
           '<osm version="0.6" generator="reporter_tpu-fixture">']
    for nid, lat, lon in node_ll:
        out.append(f'  <node id="{nid}" lat="{lat:.7f}" lon="{lon:.7f}"/>')
    for wid, nds, tags in ways:
        out.append(f'  <way id="{wid}">')
        out.extend(f'    <nd ref="{n}"/>' for n in nds)
        out.extend(f'    <tag k="{k}" v="{v}"/>' for k, v in tags.items())
        out.append('  </way>')
    out.append('</osm>')
    return "\n".join(out) + "\n"


def main(argv=None):
    parser = argparse.ArgumentParser(
        prog="reporter_tpu osm-fixture", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    parser.add_argument("--out", required=True, help="output .osm.xml path")
    args = parser.parse_args(argv)
    xml = build_city_xml()
    with open(args.out, "w") as f:
        f.write(xml)
    print(f"wrote {args.out}: {xml.count('<node')} nodes, "
          f"{xml.count('<way')} ways")
    return 0


if __name__ == "__main__":
    sys.exit(main())
