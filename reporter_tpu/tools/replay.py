"""Replay producer: flat file / stdin -> Kafka topic or stream worker.

The framework's equivalent of the reference's ``py/cat_to_kafka.py`` and the
``py/make_requests.sh`` replay driver: every input line passes through
user-supplied ``--key-with`` / ``--value-with`` / ``--send-if`` lambda
strings (reference: cat_to_kafka.py:30-40), with throughput logged every
10k lines (cat_to_kafka.py:59-61). make_requests.sh's bbox gate and
per-uuid keying (make_requests.sh:38-46) are expressible as lambdas, but
``--bbox`` + ``--key-index`` shortcuts cover the common case without one.

Sinks: a Kafka topic (when the client library is installed), stdout
(default — pipe into ``python -m reporter_tpu stream``), or /dev/null
(``--sink null`` for rate testing).
"""
from __future__ import annotations

import argparse
import logging
import sys
import time

logger = logging.getLogger("reporter_tpu.replay")

LOG_EVERY = 10000  # reference: cat_to_kafka.py:59


def _compile_lambda(src: str | None, what: str):
    if not src:
        return None
    fn = eval(src)  # the reference accepts arbitrary lambdas the same way
    if not callable(fn):
        raise argparse.ArgumentTypeError(f"--{what} must be a lambda")
    return fn


def bbox_send_if(bbox: list[float], sep: str, lat_i: int, lon_i: int):
    """A --send-if shortcut: keep separated-value lines whose lat/lon fall
    inside (min_lon, min_lat, max_lon, max_lat)
    (reference: make_requests.sh:38-44)."""
    min_lon, min_lat, max_lon, max_lat = bbox

    def send_if(line: str) -> bool:
        cols = line.rstrip("\n").split(sep)
        try:
            lat, lon = float(cols[lat_i]), float(cols[lon_i])
        except (IndexError, ValueError):
            return False
        return min_lat <= lat <= max_lat and min_lon <= lon <= max_lon

    return send_if


def replay(lines, sink, key_with=None, value_with=None, send_if=None,
           rate: float | None = None) -> tuple[int, int]:
    """Pump lines through the lambda gauntlet into ``sink(key, value)``.

    Returns (sent, total). Per-line failures are logged and skipped
    (reference: cat_to_kafka.py:62-65).
    """
    sent = total = 0
    interval = 1.0 / rate if rate else 0.0
    next_at = time.monotonic()
    for line in lines:
        total += 1
        try:
            stripped = line.rstrip("\n")
            if send_if is not None and not send_if(stripped):
                continue
            key = key_with(stripped) if key_with else None
            value = value_with(stripped) if value_with else stripped
            if rate:
                now = time.monotonic()
                if now < next_at:
                    time.sleep(next_at - now)
                next_at = max(next_at + interval, now - 1.0)
            sink(key, value)
            sent += 1
            if sent % LOG_EVERY == 0:
                logger.info("Sent %d messages of %d total", sent, total)
        except Exception:
            logger.exception("With line: %s", line.rstrip("\n"))
    logger.info("Finished sending %d messages of %d total", sent, total)
    return sent, total


def main(argv=None):
    parser = argparse.ArgumentParser(
        prog="reporter-replay",
        description="Replay a flat file (or stdin) into a Kafka topic or "
                    "stdout, with key/value/filter lambdas")
    parser.add_argument("file", help="file to read, '-' for stdin")
    parser.add_argument("--bootstrap",
                        help="Kafka bootstrap servers; omit for stdout")
    parser.add_argument("--topic", default="raw")
    parser.add_argument("--key-with",
                        help='e.g. \'lambda line: line.split("|")[0]\'')
    parser.add_argument("--value-with")
    parser.add_argument("--send-if")
    parser.add_argument("--bbox", help="min_lon,min_lat,max_lon,max_lat "
                        "shortcut filter for separated-value input")
    parser.add_argument("--separator", default="|")
    parser.add_argument("--lat-index", type=int, default=2)
    parser.add_argument("--lon-index", type=int, default=3)
    parser.add_argument("--rate", type=float,
                        help="max messages/sec (soak testing)")
    parser.add_argument("--sink", choices=("auto", "stdout", "null"),
                        default="auto")
    args = parser.parse_args(argv)

    logging.basicConfig(level=logging.INFO,
                        format="%(asctime)s %(levelname)s %(message)s")

    key_with = _compile_lambda(args.key_with, "key-with")
    value_with = _compile_lambda(args.value_with, "value-with")
    send_if = _compile_lambda(args.send_if, "send-if")
    if args.bbox:
        if send_if is not None:
            parser.error("--bbox and --send-if are mutually exclusive")
        send_if = bbox_send_if([float(x) for x in args.bbox.split(",")],
                               args.separator, args.lat_index, args.lon_index)

    if args.bootstrap and args.sink == "auto":
        from ..streaming.broker import KafkaBroker
        broker = KafkaBroker(args.bootstrap)

        def sink(key, value):
            broker.produce(args.topic, key, value.encode())
    elif args.sink == "null":
        def sink(key, value):
            pass
    else:
        def sink(key, value):
            sys.stdout.write(value + "\n")

    handle = sys.stdin if args.file == "-" else open(args.file)
    try:
        replay(handle, sink, key_with, value_with, send_if, rate=args.rate)
    finally:
        if handle is not sys.stdin:
            handle.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
