"""Accuracy gate: point-level segment agreement vs synthetic ground truth.

The reference had no automated accuracy gate — its synthetic-trace
harness (reference: py/generate_test_trace.py:181-203) produced traces
for *manual* inspection against a live stack. Here the same idea is an
executable gate: synthesise noisy traces whose true edge/segment sequence
is known, batch-match them on device, and score per-point segment-id
agreement. BASELINE.md's north star requires >=99% agreement; CI runs
this with ``--min-agreement 0.99`` (ci.yml).

Usage:
  python -m reporter_tpu accuracy [--graph g.npz] [--traces N]
      [--noise-m 4.0] [--min-agreement 0.99] [--seed 0]

Prints one JSON line with the agreement stats; exits 1 below the gate.
"""
from __future__ import annotations

import argparse
import json
import sys

import numpy as np


def score(net, matcher, traces) -> dict:
    """Match all traces in one device batch and score two agreements:

    - ``point_agreement``: per-probe-point segment-id attribution vs truth
      (strict; counts the inherently ambiguous ±1-point boundary cases)
    - ``segment_*``: the reported segment stream — the datastore contract.
      Precision over emitted *complete* segments (length > 0), recall over
      the truth path's end-to-end traversals
      (SyntheticTrace.truth_complete_segments). This is the metric
      BASELINE.md's >=99% north star is about: clients consume
      (segment_id, next_id, duration) rows, not per-point paths.
    """
    matches = matcher.match_many([tr.request_json() for tr in traces])
    agree = total = 0
    emitted = spurious = 0
    truth_full = truth_found = 0
    boundary_misses = interior_misses = 0
    per_trace = []
    for match, tr in zip(matches, traces):
        truth_pts = [int(net.edge_segment_id[e]) for e in tr.point_edges]
        decoded = {}
        for s in match["segments"]:
            sid = s.get("segment_id")
            for i in range(s["begin_shape_index"], s["end_shape_index"] + 1):
                decoded[i] = sid
        t_agree = t_total = 0
        for i, true_sid in enumerate(truth_pts):
            if true_sid < 0:  # point on an unassociated (no-OSMLR) edge
                continue
            t_total += 1
            if decoded.get(i) == true_sid:
                t_agree += 1
            else:
                # a miss whose decoded id matches the NEIGHBORING truth
                # point is the inherent +/-1-point attribution ambiguity
                # at a segment boundary (the probe sits within noise of
                # it; either side is defensible); anything else is a real
                # matching error
                got = decoded.get(i)
                off_by_one = (
                    (i > 0 and got == truth_pts[i - 1])
                    or (i + 1 < len(truth_pts)
                        and got == truth_pts[i + 1]))
                if off_by_one:
                    boundary_misses += 1
                else:
                    interior_misses += 1
        agree += t_agree
        total += t_total
        per_trace.append(t_agree / t_total if t_total else 1.0)

        # the datastore contract is about COMPLETE traversals (length > 0
        # only when the segment was covered end to end — reference
        # README.md "Reporter Output"): precision = emitted completes the
        # truth really did traverse fully; recall = truth's full
        # traversals the matcher reported complete
        truth_complete = tr.truth_complete_segments(net)
        complete = [s["segment_id"] for s in match["segments"]
                    if s.get("segment_id") is not None
                    and s.get("length", -1) > 0]
        tset = set(truth_complete)
        emitted += len(complete)
        spurious += sum(1 for sid in complete if sid not in tset)
        truth_full += len(truth_complete)
        got = set(complete)
        truth_found += sum(1 for sid in truth_complete if sid in got)
    seg_precision = 1.0 - spurious / emitted if emitted else 0.0
    seg_recall = truth_found / truth_full if truth_full else 1.0
    return {
        "traces": len(traces),
        "points_scored": total,
        "point_agreement": round(agree / total, 5) if total else 0.0,
        # decomposition of the strict misses: boundary-adjacent ones are
        # the inherent +/-1-point attribution ambiguity at segment
        # transitions; interior ones are real matching errors
        "point_misses_boundary": boundary_misses,
        "point_misses_interior": interior_misses,
        "worst_trace": round(min(per_trace), 5) if per_trace else 0.0,
        "segments_emitted": emitted,
        "segment_precision": round(seg_precision, 5),
        "segment_recall": round(seg_recall, 5),
        "agreement": round(min(seg_precision, seg_recall), 5),
    }


def main(argv=None):
    parser = argparse.ArgumentParser(
        prog="reporter_tpu accuracy", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    parser.add_argument("--graph", help="RoadNetwork .npz; omit for a "
                        "default synthetic city")
    parser.add_argument("--osm", help="OSM XML file to import and match "
                        "on (the real import path: graph/osm.py)")
    parser.add_argument("--osm-fixture", action="store_true",
                        help="use the deterministic non-grid OSM city "
                        "(tools/osm_fixture.py) through the real OSM "
                        "import path")
    parser.add_argument("--rows", type=int, default=16)
    parser.add_argument("--cols", type=int, default=16)
    parser.add_argument("--spacing-m", type=float, default=200.0)
    parser.add_argument("--traces", type=int, default=64)
    parser.add_argument("--noise-m", type=float, default=4.0)
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument("--min-agreement", type=float, default=0.0,
                        help="exit 1 if (segment) agreement falls below "
                        "this")
    parser.add_argument("--min-point-agreement", type=float, default=0.0,
                        help="exit 1 if STRICT per-point agreement falls "
                        "below this")
    parser.add_argument("--turn-penalty-factor", type=float, default=500.0,
                        help="matcher turn penalty; the reference's own "
                        "accuracy harness uses 500 "
                        "(generate_test_trace.py:172)")
    args = parser.parse_args(argv)

    from ..matcher import SegmentMatcher
    from ..synth import build_grid_city, generate_trace
    from ..utils.runtime import ensure_backend

    # pin the JAX platform before the first decode (probe + CPU fallback;
    # REPORTER_TPU_PLATFORM=cpu skips the probe entirely)
    ensure_backend()

    if args.graph:
        from ..graph.network import RoadNetwork
        net = RoadNetwork.load(args.graph)
    elif args.osm or args.osm_fixture:
        import io

        from ..graph.osm import network_from_osm_xml
        if args.osm:
            net = network_from_osm_xml(args.osm)
        else:
            from .osm_fixture import build_city_xml
            net = network_from_osm_xml(io.BytesIO(
                build_city_xml().encode()))
    else:
        # no service/internal edges: ground truth on those is ambiguous
        # by design (the matcher must *not* report them)
        net = build_grid_city(rows=args.rows, cols=args.cols,
                              spacing_m=args.spacing_m, seed=args.seed,
                              service_road_fraction=0.0,
                              internal_fraction=0.0)
    from ..matcher import MatchParams
    matcher = SegmentMatcher(net=net, params=MatchParams(
        turn_penalty_factor=args.turn_penalty_factor))

    rng = np.random.default_rng(args.seed)
    traces = []
    attempts = 0
    while len(traces) < args.traces:
        attempts += 1
        if attempts > 50 * args.traces:
            print(f"FAIL: could only generate {len(traces)}/{args.traces} "
                  "traces on this graph (too small/disconnected for "
                  "min_route_edges=8?)", file=sys.stderr)
            return 1
        tr = generate_trace(net, f"acc-{len(traces)}", rng,
                            noise_m=args.noise_m, min_route_edges=8)
        if tr is not None:
            traces.append(tr)

    result = score(net, matcher, traces)
    result["noise_m"] = args.noise_m
    print(json.dumps(result))
    if result["agreement"] < args.min_agreement:
        print(f"FAIL: agreement {result['agreement']} < "
              f"{args.min_agreement}", file=sys.stderr)
        return 1
    if result["point_agreement"] < args.min_point_agreement:
        print(f"FAIL: point_agreement {result['point_agreement']} < "
              f"{args.min_point_agreement}", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
