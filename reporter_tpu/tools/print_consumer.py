"""Debug consumer: print a topic's (key, value) stream to stdout.

Equivalent of the reference's PrintConsumer (PrintConsumer.java:24-51,
consumer group "verbose_reporters"): attach to a Kafka topic and print
every record, decoding the framework's binary value types when the topic
carries them (formatted -> Point, segments -> Segment list).
"""
from __future__ import annotations

import argparse
import sys

GROUP = "verbose_reporters"  # reference: PrintConsumer.java:27


def render(topic: str, key, value) -> str:
    """Human-readable record; binary Point/Segment values are decoded."""
    from ..core.types import Point, Segment

    if isinstance(value, (bytes, bytearray)):
        raw = bytes(value)
        if topic.startswith("formatted") and len(raw) == Point.SIZE:
            value = Point.from_bytes(raw)
        elif topic.startswith("segments") and raw and \
                len(raw) % Segment.SIZE == 0:
            value = [Segment.from_bytes(raw, off)
                     for off in range(0, len(raw), Segment.SIZE)]
        else:
            try:
                value = raw.decode()
            except UnicodeDecodeError:
                value = raw.hex()
    return f"{key}={value}"


def main(argv=None):
    parser = argparse.ArgumentParser(
        prog="reporter-print-consumer",
        description="Print every record on a topic (debugging)")
    parser.add_argument("--bootstrap", required=True)
    parser.add_argument("--topic", required=True)
    parser.add_argument("--group", default=GROUP)
    args = parser.parse_args(argv)

    from ..streaming.broker import KafkaBroker
    broker = KafkaBroker(args.bootstrap)
    for key, value in broker.consume(args.topic, group=args.group):
        print(render(args.topic, key, value))
    return 0


if __name__ == "__main__":
    sys.exit(main())
