"""Road network: the framework's replacement for Valhalla routing tiles.

The reference consumes Valhalla ``.gph`` tiles read-only through the C++
matcher (reference: Dockerfile:42-49, py/reporter_service.py:21); this
framework owns its graph format instead: a columnar, numpy-backed directed
graph with OSMLR segment associations, stored as ``.npz`` tiles keyed by the
3-level geographic tile hierarchy in :mod:`reporter_tpu.core.tiles`.

Columnar layout (structure-of-arrays) is deliberate: candidate lookup and
route-distance queries touch millions of edges per probe batch, and flat
arrays let both the numpy fallback and the C++ host runtime iterate without
pointer chasing — and hand fixed-width tensors straight to the device.

Edges are directed; geometry is the straight segment between end nodes
(synthetic networks are built at block granularity so this is exact; dense
polyline shapes can be added by splitting edges).

OSMLR association: each edge belongs to at most one OSMLR traffic segment
(``edge_segment_id``; -1 when unassociated, e.g. service roads), entering it
at ``edge_segment_offset_m`` from the segment start. A segment is a chain of
edges; ``segment_length_m`` maps segment id -> full length, which reporting
needs to distinguish complete from partial traversals
(reference: README.md "Reporter Output", length=-1 semantics).
"""
from __future__ import annotations

import io
from dataclasses import dataclass, field
from typing import Dict, Optional

import numpy as np

from ..core.geo import local_meters_projection


@dataclass
class EdgeAttr:
    """Convenience view of one edge's attributes."""
    edge_id: int
    start_node: int
    end_node: int
    length_m: float
    speed_kph: float
    segment_id: int          # -1 if no OSMLR association
    segment_offset_m: float  # distance from segment start at edge begin
    internal: bool           # turn channel / internal intersection / roundabout


@dataclass
class RoadNetwork:
    # nodes
    node_lat: np.ndarray  # (N,) f64 degrees
    node_lon: np.ndarray  # (N,) f64
    # directed edges
    edge_start: np.ndarray        # (E,) i32 node index
    edge_end: np.ndarray          # (E,) i32
    edge_length_m: np.ndarray     # (E,) f32
    edge_speed_kph: np.ndarray    # (E,) f32
    edge_segment_id: np.ndarray   # (E,) i64, -1 = unassociated
    edge_segment_offset_m: np.ndarray  # (E,) f32
    edge_internal: np.ndarray     # (E,) bool
    # OSMLR segment id -> total segment length (meters)
    segment_length_m: Dict[int, float] = field(default_factory=dict)

    # derived, built lazily
    _csr_offsets: Optional[np.ndarray] = None   # (N+1,) out-edge CSR
    _csr_edges: Optional[np.ndarray] = None     # (E,) edge ids sorted by start node
    _node_x: Optional[np.ndarray] = None        # projected meters
    _node_y: Optional[np.ndarray] = None
    _proj: Optional[tuple] = None               # (to_xy, to_ll)
    _anchor: Optional[tuple] = None             # (lat0, lon0)
    _headings: Optional[np.ndarray] = None      # (E, 2) unit headings

    @property
    def num_nodes(self) -> int:
        return len(self.node_lat)

    @property
    def num_edges(self) -> int:
        return len(self.edge_start)

    # ---- projection ------------------------------------------------------
    def projection_anchor(self):
        """(lat0, lon0) the local projection is anchored at — the network
        centroid. Exposed so the native batched prep can project points
        with the identical chart (native/__init__.py prepare_batch)."""
        if self._anchor is None:
            self._anchor = (float(np.mean(self.node_lat)),
                            float(np.mean(self.node_lon)))
        return self._anchor

    def projection(self):
        """Local equirectangular meters projection anchored at the network
        centroid; built once and shared by spatial index and matcher."""
        if self._proj is None:
            self._proj = local_meters_projection(*self.projection_anchor())
        return self._proj

    def node_xy(self):
        if self._node_x is None:
            to_xy, _ = self.projection()
            self._node_x, self._node_y = to_xy(self.node_lat, self.node_lon)
        return self._node_x, self._node_y

    def headings(self) -> np.ndarray:
        """(E, 2) unit heading per edge in projected meters
        (straight-segment geometry, matching the native runtime's
        head_x/head_y); cached — turn-penalty pricing and its removal in
        assembly both read this per decoded transition."""
        if self._headings is None:
            nx, ny = self.node_xy()
            dx = nx[self.edge_end] - nx[self.edge_start]
            dy = ny[self.edge_end] - ny[self.edge_start]
            n = np.maximum(np.hypot(dx, dy), 1e-9)
            self._headings = np.stack([dx / n, dy / n], axis=1)
        return self._headings

    # ---- adjacency -------------------------------------------------------
    def csr(self):
        """Out-edge adjacency in CSR form: (offsets[N+1], edge_ids[E])."""
        if self._csr_offsets is None:
            order = np.argsort(self.edge_start, kind="stable")
            counts = np.bincount(self.edge_start, minlength=self.num_nodes)
            offsets = np.zeros(self.num_nodes + 1, dtype=np.int64)
            np.cumsum(counts, out=offsets[1:])
            self._csr_offsets = offsets
            self._csr_edges = order.astype(np.int32)
        return self._csr_offsets, self._csr_edges

    def edge(self, edge_id: int) -> EdgeAttr:
        return EdgeAttr(
            edge_id=edge_id,
            start_node=int(self.edge_start[edge_id]),
            end_node=int(self.edge_end[edge_id]),
            length_m=float(self.edge_length_m[edge_id]),
            speed_kph=float(self.edge_speed_kph[edge_id]),
            segment_id=int(self.edge_segment_id[edge_id]),
            segment_offset_m=float(self.edge_segment_offset_m[edge_id]),
            internal=bool(self.edge_internal[edge_id]),
        )

    # ---- persistence (our .npz tile format) ------------------------------
    def save(self, path: str) -> None:
        seg_ids = np.array(sorted(self.segment_length_m), dtype=np.int64)
        seg_lens = np.array([self.segment_length_m[s] for s in seg_ids],
                            dtype=np.float32)
        np.savez_compressed(
            path,
            node_lat=self.node_lat, node_lon=self.node_lon,
            edge_start=self.edge_start, edge_end=self.edge_end,
            edge_length_m=self.edge_length_m,
            edge_speed_kph=self.edge_speed_kph,
            edge_segment_id=self.edge_segment_id,
            edge_segment_offset_m=self.edge_segment_offset_m,
            edge_internal=self.edge_internal,
            seg_ids=seg_ids, seg_lens=seg_lens,
        )

    @classmethod
    def load(cls, path) -> "RoadNetwork":
        data = np.load(path)
        seg = dict(zip(data["seg_ids"].tolist(), data["seg_lens"].tolist()))
        return cls(
            node_lat=data["node_lat"], node_lon=data["node_lon"],
            edge_start=data["edge_start"], edge_end=data["edge_end"],
            edge_length_m=data["edge_length_m"],
            edge_speed_kph=data["edge_speed_kph"],
            edge_segment_id=data["edge_segment_id"],
            edge_segment_offset_m=data["edge_segment_offset_m"],
            edge_internal=data["edge_internal"],
            segment_length_m=seg,
        )

    def to_bytes(self) -> bytes:
        buf = io.BytesIO()
        self.save(buf)
        return buf.getvalue()

    @classmethod
    def from_bytes(cls, raw: bytes) -> "RoadNetwork":
        return cls.load(io.BytesIO(raw))
