"""Bounded shortest-path route distances for HMM transition costs.

Meili's transition probability compares the network route distance between
consecutive candidate pairs against the great-circle distance between the
probes (reference: SURVEY.md §2.3; knobs ``max-route-distance-factor`` and
``beta`` at Dockerfile:14-17). Graph search is inherently sequential, so it
stays on the host: a bounded Dijkstra over the CSR adjacency, with a
per-source-node cache so a batch of traces over the same city amortises the
searches. The device only ever sees the resulting (T-1, K, K) cost tensors.

UNREACHABLE marks pairs with no route within the bound; the device matcher
turns those into -inf transition scores.
"""
from __future__ import annotations

import heapq
import math
import os
from collections import OrderedDict
from typing import Dict, Optional

import numpy as np

from .network import RoadNetwork
from .spatial import CandidateSet, PAD_EDGE

UNREACHABLE = np.float32(1.0e9)

# LRU capacities (env-tunable). Node entries hold whole bounded-Dijkstra
# result dicts (big, few); pair entries are 3-tuples (tiny, many).
_ENV_NODE_CAP = "REPORTER_TPU_ROUTE_CACHE_NODES"
_ENV_PAIR_CAP = "REPORTER_TPU_ROUTE_CACHE_PAIRS"


def _env_cap(name: str, default: int) -> int:
    from ..utils.runtime import _env_int
    return max(1, _env_int(name, default))


def _edge_secs(net: RoadNetwork, e: int, meters: float) -> float:
    """Travel seconds for ``meters`` of edge ``e`` at its speed (floored at
    1 kph, matching the native runtime's edge_secs)."""
    v = max(float(net.edge_speed_kph[e]), 1.0) / 3.6
    return meters / v


def _dijkstra_bounded(net: RoadNetwork, source_node: int, max_dist: float,
                      ) -> Dict[int, tuple]:
    """Single-source shortest paths out to ``max_dist``; each entry is
    ``(distance_m, travel_time_s)`` along the shortest-DISTANCE path.

    Time rides along for the max_route_time_factor admissibility bound —
    it does not drive the search (matching Meili: routes by distance, then
    bounds the route's travel time against the probes' elapsed time).
    """
    offsets, edge_ids = net.csr()
    lengths = net.edge_length_m
    ends = net.edge_end
    dist: Dict[int, tuple] = {source_node: (0.0, 0.0)}
    heap = [(0.0, source_node)]
    while heap:
        d, u = heapq.heappop(heap)
        du = dist.get(u)
        if du is not None and d > du[0]:
            continue
        if d > max_dist:
            break
        tu = dist[u][1]
        for idx in range(offsets[u], offsets[u + 1]):
            e = edge_ids[idx]
            v = int(ends[e])
            nd = d + float(lengths[e])
            dv = dist.get(v)
            if nd <= max_dist and (dv is None or nd < dv[0]):
                dist[v] = (nd, tu + _edge_secs(net, e, float(lengths[e])))
                heapq.heappush(heap, (nd, v))
    return dist


def shortest_path_edges(net: RoadNetwork, src_node: int, dst_node: int,
                        max_dist: float = 1.0e8):
    """Edge-id path from ``src_node`` to ``dst_node`` (Dijkstra with
    predecessor tracking), or None if unreachable. Used by the synthetic
    trace generator, not the matcher hot path."""
    offsets, edge_ids = net.csr()
    lengths = net.edge_length_m
    ends = net.edge_end
    dist = {src_node: 0.0}
    pred: Dict[int, int] = {}  # node -> incoming edge id
    heap = [(0.0, src_node)]
    while heap:
        d, u = heapq.heappop(heap)
        if u == dst_node:
            break
        if d > dist.get(u, np.inf) or d > max_dist:
            continue
        for idx in range(offsets[u], offsets[u + 1]):
            e = int(edge_ids[idx])
            v = int(ends[e])
            nd = d + float(lengths[e])
            if nd <= max_dist and nd < dist.get(v, np.inf):
                dist[v] = nd
                pred[v] = e
                heapq.heappush(heap, (nd, v))
    if dst_node not in dist or (dst_node != src_node and dst_node not in pred):
        return None
    path = []
    node = dst_node
    while node != src_node:
        e = pred[node]
        path.append(e)
        node = int(net.edge_start[e])
    return path[::-1]


class RouteCache:
    """Two-level LRU route cache, shared across batches and requests.

    Level 1 (``distances_from``) caches bounded single-source Dijkstra
    result dicts by source node — a batch of traces over one city
    amortises the searches. A cached entry is only reused when its bound
    covers the requested bound; otherwise it is recomputed at the larger
    bound. Entries map ``node -> (distance_m, travel_time_s)``.

    Level 2 (``pair_get``/``pair_put``) caches the node-to-node route
    kernel per ``(edge_from, edge_to)`` — the same urban edge pairs
    recur on every batch and every service request, and the pair hit
    skips not just the Dijkstra but the whole result-dict probe. The
    cached value is the raw (bound, distance_m, travel_time_s) triple;
    offset arithmetic, turn penalties and the time-admissibility check
    are reapplied per query from the live dt, so a hit is bit-identical
    to a recompute (pinned by tests/test_route_cache.py) and the key
    deliberately does NOT include dt: the cached kernel is
    dt-independent, and keying on it would only fragment the LRU across
    sampling-gap buckets.

    Both levels are LRU-bounded so a long-running service cannot grow
    without bound; hit/miss counts feed utils.metrics via
    ``flush_metrics`` (surfaced on the service /stats endpoint).

    Concurrency: shared across threads under CPython's GIL. Each dict
    operation is atomic, but a get can race a concurrent eviction, so
    the LRU bookkeeping (``move_to_end``/``popitem``) tolerates the key
    having vanished — a lost LRU bump or a double-evict costs a
    redundant recompute, never corruption and never an exception (the
    SegmentMatcher concurrent-Match contract).
    """

    def __init__(self, net: RoadNetwork, max_nodes: Optional[int] = None,
                 max_pairs: Optional[int] = None):
        self.net = net
        self._cache: "OrderedDict[int, tuple]" = OrderedDict()
        self._pairs: "OrderedDict[tuple, tuple]" = OrderedDict()
        self.max_nodes = max_nodes if max_nodes is not None \
            else _env_cap(_ENV_NODE_CAP, 1 << 16)
        self.max_pairs = max_pairs if max_pairs is not None \
            else _env_cap(_ENV_PAIR_CAP, 1 << 20)
        self.hits = 0
        self.misses = 0
        self.pair_hits = 0
        self.pair_misses = 0
        self._flushed = (0, 0, 0, 0)

    @staticmethod
    def _bump(lru: OrderedDict, key) -> None:
        try:
            lru.move_to_end(key)
        except KeyError:  # concurrently evicted; the fetched value stands
            pass

    @staticmethod
    def _evict(lru: OrderedDict, cap: int) -> None:
        while len(lru) > cap:
            try:
                lru.popitem(last=False)
            except KeyError:  # concurrent evictor got there first
                break

    def distances_from(self, node: int, max_dist: float) -> Dict[int, tuple]:
        entry = self._cache.get(node)
        if entry is not None and entry[0] >= max_dist:
            self.hits += 1
            self._bump(self._cache, node)
            return entry[1]
        self.misses += 1
        dist = _dijkstra_bounded(self.net, node, max_dist)
        self._cache[node] = (max_dist, dist)
        self._bump(self._cache, node)
        self._evict(self._cache, self.max_nodes)
        return dist

    # ---- pair level ------------------------------------------------------
    def pair_get(self, edge_a: int, edge_b: int):
        """Cached (bound_m, node_dist_m, node_secs) for the general route
        from edge_a's end node to edge_b's start node, or None. node_dist
        is inf when the pair was unreachable within bound_m."""
        got = self._pairs.get((edge_a, edge_b))
        if got is not None:
            self.pair_hits += 1
            self._bump(self._pairs, (edge_a, edge_b))
        else:
            self.pair_misses += 1
        return got

    def pair_put(self, edge_a: int, edge_b: int,
                 bound: float, node_dist: float, node_secs: float) -> None:
        self._pairs[(edge_a, edge_b)] = (bound, node_dist, node_secs)
        self._evict(self._pairs, self.max_pairs)

    def flush_metrics(self) -> None:
        """Publish counter deltas since the last flush to utils.metrics
        (route.cache.* counters). Called once per prepared trace/batch —
        per-pair metric increments would cost a lock op per (t, i, j)."""
        from ..utils import metrics

        now = (self.hits, self.misses, self.pair_hits, self.pair_misses)
        names = ("route.cache.node_hits", "route.cache.node_misses",
                 "route.cache.pair_hits", "route.cache.pair_misses")
        for name, cur, old in zip(names, now, self._flushed):
            if cur > old:
                metrics.count(name, cur - old)
        self._flushed = now


def route_distance(net: RoadNetwork, edge_a: int, offset_a: float,
                   edge_b: int, offset_b: float, max_dist: float,
                   cache: Optional[RouteCache] = None,
                   backward_tolerance_m: float = 0.0,
                   time_cap_s: float = -1.0,
                   turn_penalty_m: float = 0.0) -> float:
    """Network distance from a point ``offset_a`` along ``edge_a`` to a point
    ``offset_b`` along ``edge_b``; UNREACHABLE beyond ``max_dist``.

    ``backward_tolerance_m`` forgives small *apparent* backward movement on
    the same directed edge (along-track GPS noise): without it a few meters
    of backward jitter prices the same-edge transition as a full loop around
    the block, which makes a one-point flicker onto the co-located reverse
    edge the cheaper Viterbi path — exactly the segment-flapping the matcher
    must not emit.

    ``time_cap_s`` >= 0 additionally requires the route's travel time at
    edge speeds to fit the cap (Meili's ``max-route-time-factor`` bound);
    ``turn_penalty_m`` is added to general routes after admissibility (the
    caller prices the heading change between the two candidate edges).
    Semantics mirror the native runtime's rt_route_matrices exactly.
    """
    if edge_a == edge_b and offset_b >= offset_a:
        if time_cap_s >= 0 and _edge_secs(net, edge_a,
                                          offset_b - offset_a) > time_cap_s:
            return float(UNREACHABLE)
        return offset_b - offset_a
    if edge_a == edge_b and offset_a - offset_b <= backward_tolerance_m:
        return 0.0
    remaining = float(net.edge_length_m[edge_a]) - offset_a
    via = remaining + offset_b
    if via > max_dist:
        return float(UNREACHABLE)
    src = int(net.edge_end[edge_a])
    dst = int(net.edge_start[edge_b])
    node_dt = None
    if cache is not None:
        # pair level first: a bounded-Dijkstra dict entry is always the
        # EXACT shortest distance (relaxation never inserts past the
        # bound), so a cached finite pair is reusable at any query bound;
        # a cached unreachable only proves unreachability up to the bound
        # it was searched at
        got = cache.pair_get(edge_a, edge_b)
        sub = max_dist - via
        if got is not None and math.isinf(got[1]) and got[0] < sub:
            got = None  # unreachable verdict from a shallower search
        if got is not None:
            node_dt = None if math.isinf(got[1]) else (got[1], got[2])
        else:
            node_dt = cache.distances_from(src, sub).get(dst)
            cache.pair_put(edge_a, edge_b, sub,
                           node_dt[0] if node_dt is not None else math.inf,
                           node_dt[1] if node_dt is not None else 0.0)
    else:
        node_dt = _dijkstra_bounded(net, src, max_dist - via).get(dst)
    # a reused cache entry may have been computed at a larger bound and
    # contain nodes beyond this query's cap — re-check the total
    if node_dt is None or via + node_dt[0] > max_dist:
        return float(UNREACHABLE)
    if time_cap_s >= 0:
        secs = (_edge_secs(net, edge_a, remaining)
                + _edge_secs(net, edge_b, offset_b) + node_dt[1])
        if secs > time_cap_s:
            return float(UNREACHABLE)
    return via + node_dt[0] + turn_penalty_m


def _edge_headings(net: RoadNetwork) -> np.ndarray:
    """(E, 2) unit heading per edge (cached on the network)."""
    return net.headings()


def candidate_route_matrices(net: RoadNetwork, cands: CandidateSet,
                             gc_dist: np.ndarray,
                             max_route_distance_factor: float = 5.0,
                             min_bound_m: float = 500.0,
                             cache: Optional[RouteCache] = None,
                             backward_tolerance_m: float = 0.0,
                             dt: Optional[np.ndarray] = None,
                             max_route_time_factor: float = 0.0,
                             min_time_bound_s: float = 15.0,
                             turn_penalty_factor: float = 0.0) -> np.ndarray:
    """(T-1, K, K) route-distance tensor between consecutive candidates.

    ``gc_dist`` is the (T-1,) great-circle distance between consecutive
    probes; the search bound per step is
    ``max(min_bound_m, factor * gc_dist)`` mirroring the reference's
    ``max-route-distance-factor`` cap (reference: Dockerfile:14-17).

    ``dt`` (T-1,) probe time deltas + ``max_route_time_factor`` > 0 enable
    Meili's time-admissibility bound: a transition whose travel time at
    edge speeds exceeds ``max(min_time_bound_s, factor * dt[t])`` is
    unreachable (the floor parallels ``min_bound_m`` on the distance side —
    at 1 Hz sampling factor*dt is ~2 s, which GPS noise alone overruns).
    ``turn_penalty_factor`` adds ``factor * 0.5 * (1 - cos(theta))`` meters
    for the heading change between the two candidate edges (0 straight,
    ``factor`` for a U-turn) — the penalised route distance Meili feeds its
    transition cost. Mirrors the native rt_route_matrices exactly.
    """
    T, K = cands.edge_ids.shape
    if cache is None:
        cache = RouteCache(net)
    heads = _edge_headings(net) if turn_penalty_factor > 0 else None
    out = np.full((max(T - 1, 0), K, K), UNREACHABLE, dtype=np.float32)
    for t in range(T - 1):
        bound = max(min_bound_m, max_route_distance_factor * float(gc_dist[t]))
        time_cap = -1.0
        if dt is not None and max_route_time_factor > 0 and float(dt[t]) > 0:
            time_cap = max(min_time_bound_s,
                           max_route_time_factor * float(dt[t]))
        for i in range(K):
            ea = int(cands.edge_ids[t, i])
            if ea == PAD_EDGE:
                continue
            oa = float(cands.offset_m[t, i])
            for j in range(K):
                eb = int(cands.edge_ids[t + 1, j])
                if eb == PAD_EDGE:
                    continue
                ob = float(cands.offset_m[t + 1, j])
                penalty = 0.0
                if heads is not None:
                    cos_th = float(heads[ea] @ heads[eb])
                    penalty = turn_penalty_factor * 0.5 * (1.0 - cos_th)
                out[t, i, j] = route_distance(
                    net, ea, oa, eb, ob, bound, cache,
                    backward_tolerance_m=backward_tolerance_m,
                    time_cap_s=time_cap, turn_penalty_m=penalty)
    cache.flush_metrics()
    return out
