"""Bounded shortest-path route distances for HMM transition costs.

Meili's transition probability compares the network route distance between
consecutive candidate pairs against the great-circle distance between the
probes (reference: SURVEY.md §2.3; knobs ``max-route-distance-factor`` and
``beta`` at Dockerfile:14-17). Graph search is inherently sequential, so it
stays on the host: a bounded Dijkstra over the CSR adjacency, with a
per-source-node cache so a batch of traces over the same city amortises the
searches. The device only ever sees the resulting (T-1, K, K) cost tensors.

UNREACHABLE marks pairs with no route within the bound; the device matcher
turns those into -inf transition scores.
"""
from __future__ import annotations

import heapq
from typing import Dict, Optional

import numpy as np

from .network import RoadNetwork
from .spatial import CandidateSet, PAD_EDGE

UNREACHABLE = np.float32(1.0e9)


def _edge_secs(net: RoadNetwork, e: int, meters: float) -> float:
    """Travel seconds for ``meters`` of edge ``e`` at its speed (floored at
    1 kph, matching the native runtime's edge_secs)."""
    v = max(float(net.edge_speed_kph[e]), 1.0) / 3.6
    return meters / v


def _dijkstra_bounded(net: RoadNetwork, source_node: int, max_dist: float,
                      ) -> Dict[int, tuple]:
    """Single-source shortest paths out to ``max_dist``; each entry is
    ``(distance_m, travel_time_s)`` along the shortest-DISTANCE path.

    Time rides along for the max_route_time_factor admissibility bound —
    it does not drive the search (matching Meili: routes by distance, then
    bounds the route's travel time against the probes' elapsed time).
    """
    offsets, edge_ids = net.csr()
    lengths = net.edge_length_m
    ends = net.edge_end
    dist: Dict[int, tuple] = {source_node: (0.0, 0.0)}
    heap = [(0.0, source_node)]
    while heap:
        d, u = heapq.heappop(heap)
        du = dist.get(u)
        if du is not None and d > du[0]:
            continue
        if d > max_dist:
            break
        tu = dist[u][1]
        for idx in range(offsets[u], offsets[u + 1]):
            e = edge_ids[idx]
            v = int(ends[e])
            nd = d + float(lengths[e])
            dv = dist.get(v)
            if nd <= max_dist and (dv is None or nd < dv[0]):
                dist[v] = (nd, tu + _edge_secs(net, e, float(lengths[e])))
                heapq.heappush(heap, (nd, v))
    return dist


def shortest_path_edges(net: RoadNetwork, src_node: int, dst_node: int,
                        max_dist: float = 1.0e8):
    """Edge-id path from ``src_node`` to ``dst_node`` (Dijkstra with
    predecessor tracking), or None if unreachable. Used by the synthetic
    trace generator, not the matcher hot path."""
    offsets, edge_ids = net.csr()
    lengths = net.edge_length_m
    ends = net.edge_end
    dist = {src_node: 0.0}
    pred: Dict[int, int] = {}  # node -> incoming edge id
    heap = [(0.0, src_node)]
    while heap:
        d, u = heapq.heappop(heap)
        if u == dst_node:
            break
        if d > dist.get(u, np.inf) or d > max_dist:
            continue
        for idx in range(offsets[u], offsets[u + 1]):
            e = int(edge_ids[idx])
            v = int(ends[e])
            nd = d + float(lengths[e])
            if nd <= max_dist and nd < dist.get(v, np.inf):
                dist[v] = nd
                pred[v] = e
                heapq.heappush(heap, (nd, v))
    if dst_node not in dist or (dst_node != src_node and dst_node not in pred):
        return None
    path = []
    node = dst_node
    while node != src_node:
        e = pred[node]
        path.append(e)
        node = int(net.edge_start[e])
    return path[::-1]


class RouteCache:
    """Caches bounded single-source Dijkstra results by (source node).

    A cached entry is only reused when its bound covers the requested bound;
    otherwise it is recomputed at the larger bound. Entries map
    ``node -> (distance_m, travel_time_s)``.
    """

    def __init__(self, net: RoadNetwork):
        self.net = net
        self._cache: Dict[int, tuple] = {}  # node -> (bound, dist dict)
        self.hits = 0
        self.misses = 0

    def distances_from(self, node: int, max_dist: float) -> Dict[int, tuple]:
        entry = self._cache.get(node)
        if entry is not None and entry[0] >= max_dist:
            self.hits += 1
            return entry[1]
        self.misses += 1
        dist = _dijkstra_bounded(self.net, node, max_dist)
        self._cache[node] = (max_dist, dist)
        return dist


def route_distance(net: RoadNetwork, edge_a: int, offset_a: float,
                   edge_b: int, offset_b: float, max_dist: float,
                   cache: Optional[RouteCache] = None,
                   backward_tolerance_m: float = 0.0,
                   time_cap_s: float = -1.0,
                   turn_penalty_m: float = 0.0) -> float:
    """Network distance from a point ``offset_a`` along ``edge_a`` to a point
    ``offset_b`` along ``edge_b``; UNREACHABLE beyond ``max_dist``.

    ``backward_tolerance_m`` forgives small *apparent* backward movement on
    the same directed edge (along-track GPS noise): without it a few meters
    of backward jitter prices the same-edge transition as a full loop around
    the block, which makes a one-point flicker onto the co-located reverse
    edge the cheaper Viterbi path — exactly the segment-flapping the matcher
    must not emit.

    ``time_cap_s`` >= 0 additionally requires the route's travel time at
    edge speeds to fit the cap (Meili's ``max-route-time-factor`` bound);
    ``turn_penalty_m`` is added to general routes after admissibility (the
    caller prices the heading change between the two candidate edges).
    Semantics mirror the native runtime's rt_route_matrices exactly.
    """
    if edge_a == edge_b and offset_b >= offset_a:
        if time_cap_s >= 0 and _edge_secs(net, edge_a,
                                          offset_b - offset_a) > time_cap_s:
            return float(UNREACHABLE)
        return offset_b - offset_a
    if edge_a == edge_b and offset_a - offset_b <= backward_tolerance_m:
        return 0.0
    remaining = float(net.edge_length_m[edge_a]) - offset_a
    via = remaining + offset_b
    if via > max_dist:
        return float(UNREACHABLE)
    src = int(net.edge_end[edge_a])
    dst = int(net.edge_start[edge_b])
    if cache is not None:
        node_dt = cache.distances_from(src, max_dist - via).get(dst)
    else:
        node_dt = _dijkstra_bounded(net, src, max_dist - via).get(dst)
    # a reused cache entry may have been computed at a larger bound and
    # contain nodes beyond this query's cap — re-check the total
    if node_dt is None or via + node_dt[0] > max_dist:
        return float(UNREACHABLE)
    if time_cap_s >= 0:
        secs = (_edge_secs(net, edge_a, remaining)
                + _edge_secs(net, edge_b, offset_b) + node_dt[1])
        if secs > time_cap_s:
            return float(UNREACHABLE)
    return via + node_dt[0] + turn_penalty_m


def _edge_headings(net: RoadNetwork) -> np.ndarray:
    """(E, 2) unit heading per edge (cached on the network)."""
    return net.headings()


def candidate_route_matrices(net: RoadNetwork, cands: CandidateSet,
                             gc_dist: np.ndarray,
                             max_route_distance_factor: float = 5.0,
                             min_bound_m: float = 500.0,
                             cache: Optional[RouteCache] = None,
                             backward_tolerance_m: float = 0.0,
                             dt: Optional[np.ndarray] = None,
                             max_route_time_factor: float = 0.0,
                             min_time_bound_s: float = 60.0,
                             turn_penalty_factor: float = 0.0) -> np.ndarray:
    """(T-1, K, K) route-distance tensor between consecutive candidates.

    ``gc_dist`` is the (T-1,) great-circle distance between consecutive
    probes; the search bound per step is
    ``max(min_bound_m, factor * gc_dist)`` mirroring the reference's
    ``max-route-distance-factor`` cap (reference: Dockerfile:14-17).

    ``dt`` (T-1,) probe time deltas + ``max_route_time_factor`` > 0 enable
    Meili's time-admissibility bound: a transition whose travel time at
    edge speeds exceeds ``max(min_time_bound_s, factor * dt[t])`` is
    unreachable (the floor parallels ``min_bound_m`` on the distance side —
    at 1 Hz sampling factor*dt is ~2 s, which GPS noise alone overruns).
    ``turn_penalty_factor`` adds ``factor * 0.5 * (1 - cos(theta))`` meters
    for the heading change between the two candidate edges (0 straight,
    ``factor`` for a U-turn) — the penalised route distance Meili feeds its
    transition cost. Mirrors the native rt_route_matrices exactly.
    """
    T, K = cands.edge_ids.shape
    if cache is None:
        cache = RouteCache(net)
    heads = _edge_headings(net) if turn_penalty_factor > 0 else None
    out = np.full((max(T - 1, 0), K, K), UNREACHABLE, dtype=np.float32)
    for t in range(T - 1):
        bound = max(min_bound_m, max_route_distance_factor * float(gc_dist[t]))
        time_cap = -1.0
        if dt is not None and max_route_time_factor > 0 and float(dt[t]) > 0:
            time_cap = max(min_time_bound_s,
                           max_route_time_factor * float(dt[t]))
        for i in range(K):
            ea = int(cands.edge_ids[t, i])
            if ea == PAD_EDGE:
                continue
            oa = float(cands.offset_m[t, i])
            for j in range(K):
                eb = int(cands.edge_ids[t + 1, j])
                if eb == PAD_EDGE:
                    continue
                ob = float(cands.offset_m[t + 1, j])
                penalty = 0.0
                if heads is not None:
                    cos_th = float(heads[ea] @ heads[eb])
                    penalty = turn_penalty_factor * 0.5 * (1.0 - cos_th)
                out[t, i, j] = route_distance(
                    net, ea, oa, eb, ob, bound, cache,
                    backward_tolerance_m=backward_tolerance_m,
                    time_cap_s=time_cap, turn_penalty_m=penalty)
    return out
