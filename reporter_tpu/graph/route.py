"""Bounded shortest-path route distances for HMM transition costs.

Meili's transition probability compares the network route distance between
consecutive candidate pairs against the great-circle distance between the
probes (reference: SURVEY.md §2.3; knobs ``max-route-distance-factor`` and
``beta`` at Dockerfile:14-17). Graph search is inherently sequential, so it
stays on the host: a bounded Dijkstra over the CSR adjacency, with a
per-source-node cache so a batch of traces over the same city amortises the
searches. The device only ever sees the resulting (T-1, K, K) cost tensors.

UNREACHABLE marks pairs with no route within the bound; the device matcher
turns those into -inf transition scores.
"""
from __future__ import annotations

import heapq
from typing import Dict, Optional

import numpy as np

from .network import RoadNetwork
from .spatial import CandidateSet, PAD_EDGE

UNREACHABLE = np.float32(1.0e9)


def _dijkstra_bounded(net: RoadNetwork, source_node: int, max_dist: float,
                      ) -> Dict[int, float]:
    """Single-source shortest path lengths (meters) out to ``max_dist``."""
    offsets, edge_ids = net.csr()
    lengths = net.edge_length_m
    ends = net.edge_end
    dist = {source_node: 0.0}
    heap = [(0.0, source_node)]
    while heap:
        d, u = heapq.heappop(heap)
        if d > dist.get(u, np.inf):
            continue
        if d > max_dist:
            break
        for idx in range(offsets[u], offsets[u + 1]):
            e = edge_ids[idx]
            v = int(ends[e])
            nd = d + float(lengths[e])
            if nd <= max_dist and nd < dist.get(v, np.inf):
                dist[v] = nd
                heapq.heappush(heap, (nd, v))
    return dist


def shortest_path_edges(net: RoadNetwork, src_node: int, dst_node: int,
                        max_dist: float = 1.0e8):
    """Edge-id path from ``src_node`` to ``dst_node`` (Dijkstra with
    predecessor tracking), or None if unreachable. Used by the synthetic
    trace generator, not the matcher hot path."""
    offsets, edge_ids = net.csr()
    lengths = net.edge_length_m
    ends = net.edge_end
    dist = {src_node: 0.0}
    pred: Dict[int, int] = {}  # node -> incoming edge id
    heap = [(0.0, src_node)]
    while heap:
        d, u = heapq.heappop(heap)
        if u == dst_node:
            break
        if d > dist.get(u, np.inf) or d > max_dist:
            continue
        for idx in range(offsets[u], offsets[u + 1]):
            e = int(edge_ids[idx])
            v = int(ends[e])
            nd = d + float(lengths[e])
            if nd <= max_dist and nd < dist.get(v, np.inf):
                dist[v] = nd
                pred[v] = e
                heapq.heappush(heap, (nd, v))
    if dst_node not in dist or (dst_node != src_node and dst_node not in pred):
        return None
    path = []
    node = dst_node
    while node != src_node:
        e = pred[node]
        path.append(e)
        node = int(net.edge_start[e])
    return path[::-1]


class RouteCache:
    """Caches bounded single-source Dijkstra results by (source node).

    A cached entry is only reused when its bound covers the requested bound;
    otherwise it is recomputed at the larger bound.
    """

    def __init__(self, net: RoadNetwork):
        self.net = net
        self._cache: Dict[int, tuple] = {}  # node -> (bound, dist dict)
        self.hits = 0
        self.misses = 0

    def distances_from(self, node: int, max_dist: float) -> Dict[int, float]:
        entry = self._cache.get(node)
        if entry is not None and entry[0] >= max_dist:
            self.hits += 1
            return entry[1]
        self.misses += 1
        dist = _dijkstra_bounded(self.net, node, max_dist)
        self._cache[node] = (max_dist, dist)
        return dist


def route_distance(net: RoadNetwork, edge_a: int, offset_a: float,
                   edge_b: int, offset_b: float, max_dist: float,
                   cache: Optional[RouteCache] = None,
                   backward_tolerance_m: float = 0.0) -> float:
    """Network distance from a point ``offset_a`` along ``edge_a`` to a point
    ``offset_b`` along ``edge_b``; UNREACHABLE beyond ``max_dist``.

    ``backward_tolerance_m`` forgives small *apparent* backward movement on
    the same directed edge (along-track GPS noise): without it a few meters
    of backward jitter prices the same-edge transition as a full loop around
    the block, which makes a one-point flicker onto the co-located reverse
    edge the cheaper Viterbi path — exactly the segment-flapping the matcher
    must not emit.
    """
    if edge_a == edge_b and offset_b >= offset_a:
        return offset_b - offset_a
    if edge_a == edge_b and offset_a - offset_b <= backward_tolerance_m:
        return 0.0
    remaining = float(net.edge_length_m[edge_a]) - offset_a
    via = remaining + offset_b
    if via > max_dist:
        return float(UNREACHABLE)
    src = int(net.edge_end[edge_a])
    dst = int(net.edge_start[edge_b])
    if cache is not None:
        node_d = cache.distances_from(src, max_dist - via).get(dst)
    else:
        node_d = _dijkstra_bounded(net, src, max_dist - via).get(dst)
    # a reused cache entry may have been computed at a larger bound and
    # contain nodes beyond this query's cap — re-check the total
    if node_d is None or via + node_d > max_dist:
        return float(UNREACHABLE)
    return via + node_d


def candidate_route_matrices(net: RoadNetwork, cands: CandidateSet,
                             gc_dist: np.ndarray,
                             max_route_distance_factor: float = 5.0,
                             min_bound_m: float = 500.0,
                             cache: Optional[RouteCache] = None,
                             backward_tolerance_m: float = 0.0) -> np.ndarray:
    """(T-1, K, K) route-distance tensor between consecutive candidates.

    ``gc_dist`` is the (T-1,) great-circle distance between consecutive
    probes; the search bound per step is
    ``max(min_bound_m, factor * gc_dist)`` mirroring the reference's
    ``max-route-distance-factor`` cap (reference: Dockerfile:14-17).
    """
    T, K = cands.edge_ids.shape
    if cache is None:
        cache = RouteCache(net)
    out = np.full((max(T - 1, 0), K, K), UNREACHABLE, dtype=np.float32)
    for t in range(T - 1):
        bound = max(min_bound_m, max_route_distance_factor * float(gc_dist[t]))
        for i in range(K):
            ea = int(cands.edge_ids[t, i])
            if ea == PAD_EDGE:
                continue
            oa = float(cands.offset_m[t, i])
            for j in range(K):
                eb = int(cands.edge_ids[t + 1, j])
                if eb == PAD_EDGE:
                    continue
                ob = float(cands.offset_m[t + 1, j])
                out[t, i, j] = route_distance(
                    net, ea, oa, eb, ob, bound, cache,
                    backward_tolerance_m=backward_tolerance_m)
    return out
