"""Content-derived graph versions: the identity of a map build.

The reference fleet sits on a continuously refreshed OSMLR/OSM tile
substrate; ours treated the loaded :class:`RoadNetwork` as immutable
and anonymous. Every layer that outlives a graph — carried incremental
decode state, histogram partitions, change-feed cursors — needs a way
to say *which* map produced a value, or a hot swap silently mixes two
road networks' segment ids.

``map_version(net)`` hashes the persisted graph columns (the same
arrays ``RoadNetwork.save`` writes — derived caches are excluded, so a
reloaded graph hashes identically) into a short stable token. The
optional ``extra`` bytes fold the committed ``.profile`` artifact in,
so a re-profiled build is a *new* version even when the geometry is
unchanged (the route memo it pre-warms is part of the serving
contract). The token is cached on the network object: every call after
the first is an attribute read, cheap enough for per-request paths.
"""
from __future__ import annotations

import hashlib
from typing import Optional

import numpy as np

# the persisted columns, in a fixed order (matching RoadNetwork.save);
# hashing vars() would pick up lazily-built derived caches and make the
# version depend on which queries ran first
_HASHED_FIELDS = (
    "node_lat", "node_lon",
    "edge_start", "edge_end",
    "edge_length_m", "edge_speed_kph",
    "edge_segment_id", "edge_segment_offset_m",
    "edge_internal",
)

#: hex digits kept: 12 (48 bits) — collision-safe for any realistic
#: number of map builds while staying readable in /health and manifests
VERSION_LEN = 12


def map_version(net, extra: Optional[bytes] = None) -> str:
    """The content-derived version token of ``net``.

    Stable across save/load round trips and process restarts; cached on
    the network object (``net._map_version``) after the first call.
    ``extra`` (e.g. the raw bytes of the city's ``.profile`` artifact)
    is folded in WITHOUT being cached — callers mixing in an artifact
    get a fresh digest each call.
    """
    cached = getattr(net, "_map_version", None)
    if cached is None:
        h = hashlib.sha256()
        for name in _HASHED_FIELDS:
            col = getattr(net, name, None)
            if col is None:
                continue
            arr = np.ascontiguousarray(col)
            h.update(name.encode())
            h.update(str(arr.dtype).encode())
            h.update(arr.tobytes())
        # segment_length_m is a dict; hash it in sorted-key order the
        # way save() serialises it
        seg = getattr(net, "segment_length_m", None) or {}
        for sid in sorted(seg):
            h.update(b"%d:%a" % (int(sid), float(seg[sid])))
        cached = h.hexdigest()[:VERSION_LEN]
        try:
            net._map_version = cached
        except Exception:
            pass  # slotted / frozen stand-ins: just recompute next time
    if extra:
        h = hashlib.sha256(cached.encode())
        h.update(extra)
        return h.hexdigest()[:VERSION_LEN]
    return cached


__all__ = ["map_version", "VERSION_LEN"]
