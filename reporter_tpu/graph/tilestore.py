"""Tiled on-disk graph storage — the framework's ``.gph`` analog.

The reference's routing graph arrives as Valhalla tiles in a 3-level
geographic hierarchy consumed read-only by the native matcher
(reference: Dockerfile:42-49, py/get_tiles.py:82-102, setup.sh:49-53).
This module gives the framework the same deployment shape for its own
graphs: a :class:`RoadNetwork` is partitioned into per-tile binary files
under ``{level}/{nnn}/{nnn}/{nnn}.rgt`` (same path scheme, same 3-level
hierarchy), any bbox-worth of tiles can be composed back into a network,
and tile files can be shipped/downloaded individually with the tiles CLI.

Partitioning rule: an edge lives in the tile containing its *start node*
(so a tile is self-contained for candidate lookup) at the hierarchy level
of its OSMLR segment id when associated — highway segments land in the
4° level-0 tiles, arterials in level 1, locals in level 2 — and level 2
when unassociated. End nodes referenced across the boundary are carried
in the tile's node table, deduplicated by global id at load time.

Binary layout (RGT1, little-endian), parsed by the C++ host runtime when
available (the reference's native tile parser analog) and numpy otherwise:

  magic   b"RGT1"
  u32     version (=1)
  i64     n_nodes, n_edges, n_segments
  i64[N]  node_gid          global node id
  f64[N]  node_lat, node_lon
  i32[E]  edge_start, edge_end          (local node indices)
  f32[E]  edge_length_m, edge_speed_kph
  i64[E]  edge_segment_id               (-1 = unassociated)
  f32[E]  edge_segment_offset_m
  u8[E]   edge_internal
  i64[S]  seg_ids
  f32[S]  seg_lens
"""
from __future__ import annotations

import os
import struct
from typing import Dict, Iterable, List, Optional, Tuple

import numpy as np

from ..core.osmlr import tile_level
from ..core.tiles import TileHierarchy, tiles_for_bbox
from .network import RoadNetwork

MAGIC = b"RGT1"
VERSION = 1
SUFFIX = "rgt"
_HEADER = struct.Struct("<4sIqqq")


def tile_to_bytes(node_gid: np.ndarray, node_lat: np.ndarray,
                  node_lon: np.ndarray, edge_start: np.ndarray,
                  edge_end: np.ndarray, edge_length_m: np.ndarray,
                  edge_speed_kph: np.ndarray, edge_segment_id: np.ndarray,
                  edge_segment_offset_m: np.ndarray,
                  edge_internal: np.ndarray, seg_ids: np.ndarray,
                  seg_lens: np.ndarray) -> bytes:
    parts = [_HEADER.pack(MAGIC, VERSION, len(node_gid), len(edge_start),
                          len(seg_ids))]
    for arr, dtype in (
            (node_gid, "<i8"), (node_lat, "<f8"), (node_lon, "<f8"),
            (edge_start, "<i4"), (edge_end, "<i4"),
            (edge_length_m, "<f4"), (edge_speed_kph, "<f4"),
            (edge_segment_id, "<i8"), (edge_segment_offset_m, "<f4"),
            (edge_internal, "u1"), (seg_ids, "<i8"), (seg_lens, "<f4")):
        parts.append(np.ascontiguousarray(arr, dtype=dtype).tobytes())
    return b"".join(parts)


def tile_from_bytes(raw: bytes) -> dict:
    """Parse one RGT1 blob into its column arrays. Uses the C++ host
    runtime's parser when built; numpy slicing otherwise (same output)."""
    from .. import native
    if native.available():
        parsed = native.parse_tile(raw)
        if parsed is not None:
            return parsed
    return tile_from_bytes_np(raw)


def tile_from_bytes_np(raw: bytes) -> dict:
    magic, version, n_nodes, n_edges, n_segs = _HEADER.unpack_from(raw, 0)
    if magic != MAGIC:
        raise ValueError("not an RGT tile (bad magic)")
    if version != VERSION:
        raise ValueError(f"unsupported RGT version {version}")
    out: dict = {}
    off = _HEADER.size
    for name, dtype, count in (
            ("node_gid", "<i8", n_nodes), ("node_lat", "<f8", n_nodes),
            ("node_lon", "<f8", n_nodes),
            ("edge_start", "<i4", n_edges), ("edge_end", "<i4", n_edges),
            ("edge_length_m", "<f4", n_edges),
            ("edge_speed_kph", "<f4", n_edges),
            ("edge_segment_id", "<i8", n_edges),
            ("edge_segment_offset_m", "<f4", n_edges),
            ("edge_internal", "u1", n_edges),
            ("seg_ids", "<i8", n_segs), ("seg_lens", "<f4", n_segs)):
        arr = np.frombuffer(raw, dtype=dtype, count=count, offset=off)
        out[name] = arr
        off += arr.nbytes
    if off != len(raw):
        raise ValueError(f"RGT tile has {len(raw) - off} trailing bytes")
    out["edge_internal"] = out["edge_internal"].astype(bool)
    return out


def edge_tile_assignment(net: RoadNetwork) -> Tuple[np.ndarray, np.ndarray]:
    """(level, tile_id) per edge: OSMLR level when associated (else local
    level 2), geographic tile of the start node at that level."""
    E = net.num_edges
    levels = np.full(E, 2, dtype=np.int32)
    assoc = net.edge_segment_id >= 0
    if assoc.any():
        levels[assoc] = [tile_level(int(s))
                         for s in net.edge_segment_id[assoc]]
    hierarchy = TileHierarchy()
    tile_ids = np.empty(E, dtype=np.int64)
    start_lat = net.node_lat[net.edge_start]
    start_lon = net.node_lon[net.edge_start]
    for lvl in np.unique(levels):
        t = hierarchy.tiles(int(lvl))
        sel = levels == lvl
        rows = ((start_lat[sel] - t.bbox.miny) / t.tilesize).astype(np.int64)
        cols = ((start_lon[sel] - t.bbox.minx) / t.tilesize).astype(np.int64)
        rows = np.clip(rows, 0, t.nrows - 1)
        cols = np.clip(cols, 0, t.ncolumns - 1)
        tile_ids[sel] = rows * t.ncolumns + cols
    return levels, tile_ids


def write_tiles(net: RoadNetwork, root: str) -> List[str]:
    """Partition ``net`` into RGT tile files under ``root``; returns the
    relative paths written."""
    levels, tile_ids = edge_tile_assignment(net)
    hierarchy = TileHierarchy()
    written: List[str] = []
    # group edges by (level, tile_id) via one lexsort
    order = np.lexsort((tile_ids, levels))
    groups: Dict[Tuple[int, int], np.ndarray] = {}
    if len(order):
        key_change = np.flatnonzero(
            (np.diff(levels[order]) != 0) | (np.diff(tile_ids[order]) != 0))
        starts = np.concatenate([[0], key_change + 1])
        ends = np.concatenate([key_change + 1, [len(order)]])
        for s, e in zip(starts, ends):
            idx = order[s:e]
            groups[(int(levels[idx[0]]), int(tile_ids[idx[0]]))] = idx

    for (lvl, tid), edge_idx in sorted(groups.items()):
        node_gids = np.unique(np.concatenate(
            [net.edge_start[edge_idx], net.edge_end[edge_idx]]))
        local_of = {int(g): i for i, g in enumerate(node_gids)}
        remap = np.vectorize(local_of.__getitem__, otypes=[np.int32])
        seg_ids_here = np.unique(
            net.edge_segment_id[edge_idx][net.edge_segment_id[edge_idx] >= 0])
        seg_lens_here = np.array(
            [net.segment_length_m.get(int(s), 0.0) for s in seg_ids_here],
            dtype=np.float32)
        blob = tile_to_bytes(
            node_gid=node_gids,
            node_lat=net.node_lat[node_gids],
            node_lon=net.node_lon[node_gids],
            edge_start=remap(net.edge_start[edge_idx]),
            edge_end=remap(net.edge_end[edge_idx]),
            edge_length_m=net.edge_length_m[edge_idx],
            edge_speed_kph=net.edge_speed_kph[edge_idx],
            edge_segment_id=net.edge_segment_id[edge_idx],
            edge_segment_offset_m=net.edge_segment_offset_m[edge_idx],
            edge_internal=net.edge_internal[edge_idx],
            seg_ids=seg_ids_here, seg_lens=seg_lens_here)
        rel = hierarchy.tiles(lvl).file_path(tid, lvl, SUFFIX)
        path = os.path.join(root, rel)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        with open(path, "wb") as f:
            f.write(blob)
        written.append(rel)
    return written


def merge_tiles(parsed: Iterable[dict]) -> RoadNetwork:
    """Compose parsed tile dicts into one RoadNetwork, deduplicating
    boundary nodes by global id."""
    parsed = list(parsed)
    if not parsed:
        raise ValueError("no tiles to merge")
    all_gids = np.unique(np.concatenate([p["node_gid"] for p in parsed]))
    index_of = {int(g): i for i, g in enumerate(all_gids)}
    N = len(all_gids)
    node_lat = np.zeros(N, dtype=np.float64)
    node_lon = np.zeros(N, dtype=np.float64)
    cols: Dict[str, list] = {k: [] for k in (
        "edge_start", "edge_end", "edge_length_m", "edge_speed_kph",
        "edge_segment_id", "edge_segment_offset_m", "edge_internal")}
    segment_length: Dict[int, float] = {}
    for p in parsed:
        merged_idx = np.array([index_of[int(g)] for g in p["node_gid"]],
                              dtype=np.int32)
        node_lat[merged_idx] = p["node_lat"]
        node_lon[merged_idx] = p["node_lon"]
        cols["edge_start"].append(merged_idx[p["edge_start"]])
        cols["edge_end"].append(merged_idx[p["edge_end"]])
        for k in ("edge_length_m", "edge_speed_kph", "edge_segment_id",
                  "edge_segment_offset_m", "edge_internal"):
            cols[k].append(p[k])
        segment_length.update(zip(p["seg_ids"].tolist(),
                                  p["seg_lens"].tolist()))
    return RoadNetwork(
        node_lat=node_lat, node_lon=node_lon,
        edge_start=np.concatenate(cols["edge_start"]).astype(np.int32),
        edge_end=np.concatenate(cols["edge_end"]).astype(np.int32),
        edge_length_m=np.concatenate(cols["edge_length_m"]).astype(np.float32),
        edge_speed_kph=np.concatenate(
            cols["edge_speed_kph"]).astype(np.float32),
        edge_segment_id=np.concatenate(
            cols["edge_segment_id"]).astype(np.int64),
        edge_segment_offset_m=np.concatenate(
            cols["edge_segment_offset_m"]).astype(np.float32),
        edge_internal=np.concatenate(cols["edge_internal"]).astype(bool),
        segment_length_m=segment_length,
    )


class GraphTileStore:
    """Read side: compose a RoadNetwork from a tile tree on disk."""

    def __init__(self, root: str):
        self.root = root

    def tile_paths(self) -> List[str]:
        out = []
        for r, _d, fs in os.walk(self.root):
            for f in fs:
                if f.endswith("." + SUFFIX):
                    out.append(os.path.relpath(os.path.join(r, f), self.root))
        return sorted(out)

    def read_tile(self, rel_path: str) -> dict:
        with open(os.path.join(self.root, rel_path), "rb") as f:
            return tile_from_bytes(f.read())

    def load_all(self) -> RoadNetwork:
        paths = self.tile_paths()
        return merge_tiles(self.read_tile(p) for p in paths)

    def load_bbox(self, bbox_lonlat: List[float],
                  levels: Tuple[int, ...] = (0, 1, 2)) -> RoadNetwork:
        """Network covering a (min_lon, min_lat, max_lon, max_lat) bbox —
        only the intersecting tiles are read, like the reference's
        bbox-scoped tile downloads (download_tiles.sh)."""
        wanted = set(tiles_for_bbox(bbox_lonlat, suffix=SUFFIX,
                                    levels=levels))
        present = [p for p in self.tile_paths() if p in wanted]
        if not present:
            raise FileNotFoundError(
                f"no tiles under {self.root} intersect bbox {bbox_lonlat}")
        return merge_tiles(self.read_tile(p) for p in present)
