"""DeviceRouteKernel: the chunk-batched device route-cost stage.

Owns the device-resident graph columns for one :class:`RoadNetwork` and
turns a native-prepared chunk's candidate tensors into its (B, T-1, K, K)
route tensor with ONE multi-source bounded relaxation + ONE gather/
scatter assembly (ops/route_relax.py) instead of per-pair host Dijkstra
searches. The host path (graph/route.py, native route_step) stays the
byte-identical fallback and parity oracle; the matcher guards this path
with its own circuit domain (``route.device``) and re-runs the native
prep with routes on any failure here, so a broken device can never
change report bytes.

Per chunk the kernel:

1. collects the live candidate edges' end nodes (the relaxation
   sources), deduplicated and padded to a power of two (bounding the
   compiled-shape count the way batchpad's row padding does);
2. relaxes them all at the chunk-global bound — the max over every live
   step's ``max(min_bound, factor * gc)`` — which is exactness-safe: a
   bounded search at a larger bound settles a superset of the same exact
   distances, and the assembly re-applies each step's own bound;
3. assembles the route tensor and writes it into the prep dict's
   ``route_m`` rows ``[:B, :T-1]`` (row T-1 is the dead trailing step the
   native tail fill already covered), folding the device finite max into
   ``max_finite`` so the f16 wire decision sees device-written values.

A relaxation that fails to converge within the sweep cap raises instead
of returning a partially-relaxed tensor; so does a chunk whose padded
(sources x nodes) state would exceed the memory budget — both are
ordinary circuit failures to the caller.

On small graphs (``2 * N * N`` float32 elements within the cache
budget) the kernel keeps a device-resident node-kernel cache: one
(N, N) distance/time row pair per relaxed source node, tagged with the
bound it was relaxed at. A row relaxed at bound ``b`` is EXACT for any
query bound ``<= b`` (every admissible path's prefixes are admissible,
so the settled values — and the equal-distance tie set the time min
runs over — are identical), which is the same monotone-bound reuse rule
the host RouteCache applies. Steady-state chunks over a warm city then
skip the relaxation entirely and run only the gather/scatter assembly —
the fill drops from O(sweeps x E x S) to O(pairs). Rows are committed
only after a converged sweep, so a fallback chunk never poisons the
cache.

The per-city ``.profile`` artifact (datastore/profile.py) can carry the
observed ``route_hops``/``route_bound_m`` of a serving run; ``seed_hint``
consumes them so a freshly warmed city starts with a tight sweep cap
instead of the worst-case node count.
"""
from __future__ import annotations

import os
from typing import Optional

import numpy as np

from ..utils import faults, metrics
from .network import RoadNetwork
from .route import UNREACHABLE

#: sweep cap override; 0/unset = auto (profile hint or the node count)
ENV_HOPS = "REPORTER_TPU_ROUTE_HOPS"

#: ceiling on the padded relaxation state (sources x max(nodes, edges)
#: float32 elements, two states) — a chunk that would exceed it raises
#: (-> host fallback) rather than OOM the device. 64M elements = 512 MB.
_STATE_BUDGET_ELEMS = 64 * 1024 * 1024

#: ceiling on the dense (nodes x nodes) node-kernel cache (two float32
#: states); graphs over it (N > ~2.8k nodes) serve uncached, per-chunk.
_CACHE_BUDGET_ELEMS = 16 * 1024 * 1024


def _next_pow2(n: int) -> int:
    return 1 << max(n - 1, 0).bit_length()


class DeferredRoutes:
    """A chunk's dispatched-but-unsynced device route tensor.

    ``fill_prep(defer=True)`` returns one of these instead of blocking on
    the device→host copy: ``route`` is the in-flight (B, T-1, K, K)
    float32 array, ``max_finite`` its finite-max scalar. The prep stage
    stays dispatch-only; the first consumer that needs host bytes (the
    decode stage's wire finalisation, the lazy per-trace views) calls
    :meth:`write_back`, which blocks there — overlapping the device
    assembly with the next chunk's native prep. Every failure mode
    (budget, non-convergence, faults) still raises at dispatch time
    inside ``fill_prep``, so the caller's circuit semantics are
    unchanged; the assembly itself is pure arithmetic.

    On a fully warm node-kernel cache even the *dispatch* (blob packing
    + two transfers + the jit call) leaves the prep thread: ``fut`` is
    then a future resolving to ``(route, max_finite)``, submitted to the
    kernel's single dispatch worker. That path has no relax, hence no
    convergence check — nothing left that the circuit needs to see at
    prep time."""

    __slots__ = ("route", "max_finite", "_B", "_T", "_lock", "_done",
                 "_fut")

    def __init__(self, route, max_finite, B: int, T: int, fut=None):
        import threading
        self.route = route
        self.max_finite = max_finite
        self._B = B
        self._T = T
        self._lock = threading.RLock()  # write_back resolves under it
        self._done = False
        self._fut = fut

    def resolve(self):
        """Block until the device arrays are in hand (idempotent);
        returns ``(route, max_finite)`` — still device-resident."""
        with self._lock:
            if self._fut is not None:
                self.route, self.max_finite = self._fut.result()
                self._fut = None
            return self.route, self.max_finite

    def write_back(self, out: dict) -> None:
        """Materialise into the prep dict (idempotent, thread-safe):
        route bytes into ``route_m[:B, :T-1]``, finite max folded into
        ``max_finite`` — byte-identical to the non-deferred path."""
        with self._lock:
            if self._done:
                return
            route, dev_max = self.resolve()
            out["route_m"][:self._B, :self._T - 1] = np.asarray(route)
            out["max_finite"][0] = max(float(out["max_finite"][0]),
                                       float(dev_max))
            self._done = True


class DeviceRouteKernel:
    """Batched device route costs for one road network."""

    def __init__(self, net: RoadNetwork):
        import jax.numpy as jnp  # deferred: graph/ stays numpy-importable

        self.net = net
        self.n_nodes = int(net.num_nodes)
        self.n_edges = int(net.num_edges)
        # float32 edge columns in the C++ runtime's exact arithmetic:
        # m/s = max(kph, 1) * (1/3.6) as float32, secs = meters / v
        speed = np.asarray(net.edge_speed_kph, dtype=np.float32)
        v = np.maximum(speed, np.float32(1.0)) \
            * (np.float32(1.0) / np.float32(3.6))
        e_len = np.asarray(net.edge_length_m, dtype=np.float32)
        heads = np.asarray(net.headings(), dtype=np.float32)
        self._e_start = jnp.asarray(net.edge_start.astype(np.int32))
        self._e_end = jnp.asarray(net.edge_end.astype(np.int32))
        self._e_len = jnp.asarray(e_len)
        self._e_v = jnp.asarray(v)
        self._e_secs = jnp.asarray(e_len / v)
        self._head_x = jnp.asarray(heads[:, 0])
        self._head_y = jnp.asarray(heads[:, 1])
        # host copy for source gathering (no device round-trip per chunk)
        self._end_np = np.asarray(net.edge_end, dtype=np.int32)
        # sweep-cap seed (profile hint) + observed stats for export
        self._hops_hint = 0
        self.max_iters_seen = 0
        self.max_bound_seen = 0.0
        # device-resident node-kernel cache (see module docstring):
        # (N, N) relaxed rows, row i = source node i, valid while
        # _row_bound[i] >= the query bound; -1 = never relaxed
        self._cache_ok = 2 * self.n_nodes * self.n_nodes \
            <= _CACHE_BUDGET_ELEMS
        self._cache_dist = None
        self._cache_time = None
        self._row_bound = np.full(self.n_nodes, -1.0, dtype=np.float32)
        self._pool = None  # lazy: see _dispatch_pool()

    # -- profile plumbing --------------------------------------------------
    def seed_hint(self, route_hops: int) -> None:
        """Seed the sweep cap from a committed ``.profile`` artifact's
        observed hop count (datastore/profile.py warm_matcher)."""
        if route_hops > 0:
            self._hops_hint = int(route_hops)

    def stats(self) -> dict:
        """Observed relaxation stats for the profile export."""
        return {"route_hops": int(self.max_iters_seen),
                "route_bound_m": float(self.max_bound_seen)}

    def _iter_cap(self) -> int:
        raw = os.environ.get(ENV_HOPS, "").strip()
        if raw:
            try:
                forced = int(raw)
                if forced > 0:
                    return forced
            except ValueError:
                import logging
                logging.getLogger("reporter_tpu.graph").warning(
                    "%s=%r not an integer; using the auto cap",
                    ENV_HOPS, raw)
        if self._hops_hint > 0:
            # headroom over the recorded depth: a trace family slightly
            # deeper than the profile's still converges (and re-records)
            return max(self._hops_hint * 2, 16)
        return max(self.n_nodes, 2)

    # -- the chunk hot path ------------------------------------------------
    def fill_prep(self, out: dict, params, B: int,
                  min_bound_m: float = 500.0,
                  defer: bool = False) -> "Optional[DeferredRoutes]":
        """Compute and write ``out['route_m'][:B, :T-1]`` for a native
        ``prepare_batch(..., skip_routes=True)`` result dict, updating
        ``out['max_finite']``. Raises on non-convergence or a
        budget-exceeding chunk (the caller's circuit fallback re-runs
        the native prep with routes).

        ``defer=True`` skips the device→host sync: the assembly is
        dispatched and a :class:`DeferredRoutes` handle returned (None
        when the chunk had nothing to route and the prep dict is already
        complete). All circuit-visible failure modes (budget, faults,
        relax non-convergence) still raise HERE: on a fully warm cache
        — the only case where the dispatch itself is handed to the
        background worker — no relax runs, so nothing checkable is
        deferred past this frame."""
        faults.failpoint("route.device")
        edge = np.asarray(out["edge_ids"][:B])
        T = edge.shape[1]
        if T < 2:
            return
        nk = np.asarray(out["num_kept"][:B])
        gc = np.asarray(out["gc_m"][:B, :T - 1])
        dt = np.asarray(out["dt"][:B, :T - 1])

        # per-step bounds/caps in the C++ double->float32 expression
        bounds = np.maximum(
            np.float64(min_bound_m),
            np.float64(params.max_route_distance_factor)
            * gc.astype(np.float64)).astype(np.float32)
        tf = float(params.max_route_time_factor)
        caps = np.where(
            (tf > 0) & (dt > 0),
            np.maximum(np.float64(params.min_time_bound_s),
                       np.float64(tf) * dt),
            np.float64(-1.0)).astype(np.float32)

        steps = np.arange(T - 1)
        live_step = steps[None, :] < (nk[:, None] - 1)
        ea_live = live_step[:, :, None] & (edge[:, :T - 1, :] >= 0)
        if not bool(ea_live.any()):
            # no live transitions anywhere: the native tail fill already
            # wrote every route row of these traces
            metrics.count("route.device.empty_chunks")
            return
        chunk_bound = np.float32(bounds[live_step].max())

        # unique source nodes via a flag scan over the node-id space:
        # O(pairs + N) with no sort (np.unique was the costliest host
        # op left on the warm path), same sorted result
        flags = np.zeros(self.n_nodes, dtype=bool)
        flags[self._end_np[edge[:, :T - 1, :][ea_live]]] = True
        srcs = np.flatnonzero(flags).astype(np.int32)
        S = _next_pow2(len(srcs))
        if S * max(self.n_nodes, self.n_edges) * 2 > _STATE_BUDGET_ELEMS:
            metrics.count("route.device.budget_exceeded")
            raise RuntimeError(
                f"route relax state over budget: {len(srcs)} sources x "
                f"{self.n_nodes} nodes")
        btol = float(params.backward_tolerance_m)
        tpen = float(params.turn_penalty_factor)
        offset = np.asarray(out["offset_m"][:B])
        metrics.count("route.device.chunks")
        metrics.count("route.device.pairs",
                      int(ea_live.sum()) * edge.shape[2])
        metrics.count("route.device.sources", int(len(srcs)))
        if (defer and self._cache_ok and self._cache_dist is not None
                and bool(np.all(self._row_bound[srcs] >= chunk_bound))):
            # fully warm cache: no relax, hence no convergence check —
            # nothing left that can raise for circuit purposes, so even
            # the dispatch leaves the prep critical path
            metrics.count("route.device.deferred_chunks")
            metrics.count("route.device.async_dispatch_chunks")
            fut = self._dispatch_pool().submit(
                self._run, edge, offset, nk, bounds, caps, srcs,
                chunk_bound, btol, tpen)
            return DeferredRoutes(None, None, B, T, fut=fut)
        route, dev_max = self._run(edge, offset, nk, bounds, caps, srcs,
                                   chunk_bound, btol, tpen)
        if defer:
            metrics.count("route.device.deferred_chunks")
            return DeferredRoutes(route, dev_max, B, T)
        # synchronous path: materialise through the same declared sync
        # point as the deferred one (registry.SYNC_POINTS write_back) —
        # one d2h site, byte-identical either way
        DeferredRoutes(route, dev_max, B, T).write_back(out)
        return None

    def _relax(self, srcs: np.ndarray, chunk_bound) -> tuple:
        """Relax the padded source set at ``chunk_bound``; raises on
        non-convergence (before any cache commit). Returns the (S, N)
        distance/time kernels, S = len(srcs) padded to a power of two."""
        import jax
        import jax.numpy as jnp

        from ..ops import route_relax

        S = _next_pow2(len(srcs))
        pad = np.empty(S, dtype=np.int32)
        pad[:len(srcs)] = srcs
        pad[len(srcs):] = srcs[0]  # duplicate rows are redundant, not wrong

        src_dev = jnp.asarray(pad)
        mesh = self._mesh()
        if mesh is not None and S % mesh.devices.size == 0:
            from jax.sharding import NamedSharding, PartitionSpec
            src_dev = jax.device_put(
                src_dev, NamedSharding(mesh, PartitionSpec("data")))
            metrics.count("route.device.sharded_chunks")

        cap = self._iter_cap()
        dist, time, iters, converged = route_relax.relax_csr(
            self._e_start, self._e_end, self._e_len, self._e_secs,
            src_dev, jnp.float32(chunk_bound),
            n_nodes=self.n_nodes, max_iters=cap)
        if not bool(converged):
            metrics.count("route.device.nonconverged")
            raise RuntimeError(
                f"route relax did not converge within {cap} sweeps "
                f"(bound {float(chunk_bound):.0f} m)")
        self.max_iters_seen = max(self.max_iters_seen, int(iters))
        self.max_bound_seen = max(self.max_bound_seen, float(chunk_bound))
        return dist, time

    def _kernels_cached(self, srcs: np.ndarray, chunk_bound) -> tuple:
        """Serve (dist_sn, time_sn, node_row) from the node-kernel cache,
        relaxing only the rows whose cached bound does not cover this
        chunk's. Rows commit only after a converged sweep."""
        import jax.numpy as jnp

        missing = srcs[self._row_bound[srcs] < np.float32(chunk_bound)]
        if len(missing):
            dist, time = self._relax(missing, chunk_bound)
            if self._cache_dist is None:
                inf = jnp.full((self.n_nodes, self.n_nodes),
                               jnp.inf, jnp.float32)
                self._cache_dist = inf
                self._cache_time = inf
            rows = jnp.asarray(missing)
            self._cache_dist = self._cache_dist.at[rows] \
                .set(dist[:len(missing)])
            self._cache_time = self._cache_time.at[rows] \
                .set(time[:len(missing)])
            self._row_bound[missing] = np.float32(chunk_bound)
            metrics.count("route.device.cache_miss_rows", int(len(missing)))
        metrics.count("route.device.cache_hit_rows",
                      int(len(srcs) - len(missing)))
        # cache row i belongs to node i: node_row is the identity on the
        # nodes this chunk needs (all just proven covered), -1 elsewhere
        node_row = np.full(self.n_nodes, -1, dtype=np.int32)
        node_row[srcs] = srcs
        return self._cache_dist, self._cache_time, node_row

    def _run(self, edge, offset, nk, bounds, caps, srcs, chunk_bound,
             btol, tpen):
        """Relax (or cache-serve) + assemble; returns the DEVICE
        (B, T-1, K, K) float32 route array and finite-max scalar,
        dispatched but not synced. Split out so route_matrices shares
        it."""
        import jax.numpy as jnp

        from ..ops import route_relax

        if self._cache_ok:
            dist, time, node_row = self._kernels_cached(srcs, chunk_bound)
        else:
            dist, time = self._relax(srcs, chunk_bound)
            node_row = np.full(self.n_nodes, -1, dtype=np.int32)
            node_row[srcs] = np.arange(len(srcs), dtype=np.int32)

        # two packed blobs instead of eight small transfers: on a warm
        # cache the per-chunk device_put overhead IS the dispatch cost
        B, T, K = edge.shape
        ints = np.concatenate([
            np.ascontiguousarray(edge, dtype=np.int32).ravel(),
            nk.astype(np.int32, copy=False),
            node_row])
        f32s = np.concatenate([
            np.ascontiguousarray(offset, dtype=np.float32).ravel(),
            bounds.ravel(), caps.ravel(),
            np.array([btol, tpen], dtype=np.float32)])
        route, max_finite = route_relax.pair_costs_packed(
            jnp.asarray(ints), jnp.asarray(f32s), dist, time,
            self._e_start, self._e_end, self._e_len, self._e_v,
            self._head_x, self._head_y,
            B=B, T=T, K=K, N=self.n_nodes)
        # still device arrays: the caller decides when (and whether on
        # this thread) to pay the sync — fill_prep(defer=True) never does
        return route, max_finite

    @staticmethod
    def _mesh():
        from ..parallel import mesh as pmesh
        m = pmesh.decode_mesh()
        if m is None:
            return None
        data, _seq = pmesh.mesh_axes(m)
        return m if data > 1 else None

    def _dispatch_pool(self):
        """The single-worker executor for warm-cache async dispatch.
        One thread: chunk dispatches stay ordered and the node-kernel
        cache is only ever mutated by the (serial) prep thread."""
        if self._pool is None:
            from concurrent.futures import ThreadPoolExecutor
            self._pool = ThreadPoolExecutor(
                max_workers=1, thread_name_prefix="route-dispatch")
        return self._pool

    # -- standalone matrices (tests / bench parity legs) -------------------
    def route_matrices(self, cands, gc,
                       max_route_distance_factor: float = 5.0,
                       min_bound_m: float = 500.0,
                       backward_tolerance_m: float = 25.0,
                       dt=None, max_route_time_factor: float = 0.0,
                       min_time_bound_s: float = 60.0,
                       turn_penalty_factor: float = 0.0) -> np.ndarray:
        """(T-1, K, K) route tensor for one trace's candidate set — the
        device twin of NativeRuntime.route_matrices / graph.route.
        candidate_route_matrices, for the parity legs."""
        edge = np.asarray(cands.edge_ids, dtype=np.int32)[None]
        offset = np.asarray(cands.offset_m, dtype=np.float32)[None]
        T = edge.shape[1]
        if T < 2:
            return np.zeros((0, edge.shape[2], edge.shape[2]),
                            dtype=np.float32)
        gc = np.asarray(gc, dtype=np.float32).reshape(1, T - 1)
        bounds = np.maximum(
            np.float64(min_bound_m),
            np.float64(max_route_distance_factor)
            * gc.astype(np.float64)).astype(np.float32)
        if dt is not None and max_route_time_factor > 0:
            d64 = np.asarray(dt, dtype=np.float64).reshape(1, T - 1)
            caps = np.where(
                d64 > 0,
                np.maximum(np.float64(min_time_bound_s),
                           np.float64(max_route_time_factor) * d64),
                np.float64(-1.0)).astype(np.float32)
        else:
            caps = np.full((1, T - 1), -1.0, dtype=np.float32)
        nk = np.array([T], dtype=np.int32)
        live = edge[:, :T - 1, :] >= 0
        if not bool(live.any()):
            return np.full((T - 1, edge.shape[2], edge.shape[2]),
                           UNREACHABLE, dtype=np.float32)
        srcs = np.unique(self._end_np[edge[:, :T - 1, :][live]])
        route, _ = self._run(edge, offset, nk, bounds, caps, srcs,
                             np.float32(bounds.max()),
                             float(backward_tolerance_m),
                             float(turn_penalty_factor))
        return np.asarray(route)[0]
