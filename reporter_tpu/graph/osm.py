"""OSM XML importer: build a RoadNetwork from raw OpenStreetMap data.

The reference never parses OSM itself — it consumes Valhalla tiles built
elsewhere from OSM extracts (reference: Dockerfile:9-11,
load-historical-data/setup.sh:49-53). This framework owns its graph format
(graph/network.py), so real-map support means importing OSM directly:

- stdlib ``xml.etree.iterparse`` streaming parse (no osmium/pyosmium in the
  image), two passes over the file: ways first (to learn which nodes are
  referenced), then nodes.
- drivable ways only, classified onto the reference's 3-level hierarchy
  (0 = highway, 1 = arterial, 2 = local — reference: py/get_tiles.py:30-39).
- one directed edge per consecutive node pair; two-way roads emit both
  directions; ``oneway``/roundabout semantics honoured.
- speeds from ``maxspeed`` (kph or "N mph"), else per-class defaults.
- OSMLR association synthesised per (way, direction), SPLIT at decision
  points the way real OSMLR segments are: a new segment starts at every
  interior node shared with another drivable way (an intersection) and
  whenever the running length passes ~1 km — so a 3 km avenue through
  town becomes a chain of block-to-block segments, not one monolith, and
  complete-traversal semantics (length=-1 otherwise, reference
  README.md "Reporter Output") are meaningful. Each segment's 64-bit id
  packs the hierarchy level, the level's geographic tile of the
  segment's first node, and a per-tile running index (core/osmlr.py bit
  layout). ``service`` roads and internal edges (``*_link`` ramps,
  roundabouts) stay unassociated, mirroring how the reference treats
  no-OSMLR and internal edges in report()
  (reference: py/reporter_service.py:119-127,161-162).

Remaining simplification vs real OSMLR: segments never merge ACROSS ways
(real OSMLR chains same-road ways). Ids are valid, level/tile bits are
geographically correct, and every reporting code path (levels, tile
bucketing, privacy, CSV, complete-traversal reporting) behaves as with
authentic ids.
"""
from __future__ import annotations

import xml.etree.ElementTree as ET
from typing import Dict, IO, List, Union

import numpy as np

from ..core.geo import equirectangular_m
from ..core.osmlr import SEGMENT_INDEX_MASK, make_segment_id
from ..core.tiles import TileHierarchy
from .network import RoadNetwork

# highway=* values we import, with (hierarchy level, default speed kph).
# Levels follow the reference's tile hierarchy: 0 highway, 1 arterial,
# 2 local (py/get_tiles.py:30-39).
_HIGHWAY_CLASSES: Dict[str, tuple] = {
    "motorway": (0, 100.0), "motorway_link": (0, 60.0),
    "trunk": (0, 90.0), "trunk_link": (0, 50.0),
    "primary": (1, 60.0), "primary_link": (1, 40.0),
    "secondary": (1, 50.0), "secondary_link": (1, 40.0),
    "tertiary": (2, 40.0), "tertiary_link": (2, 30.0),
    "unclassified": (2, 40.0), "residential": (2, 30.0),
    "living_street": (2, 10.0), "service": (2, 20.0),
}
# classes that never get an OSMLR association (reference treats service
# roads as unassociated and ramps/roundabouts as internal)
_UNASSOCIATED = {"service"}
_INTERNAL_SUFFIX = "_link"
# OSMLR segments cap out around a kilometre; longer stretches between
# intersections split so complete-traversal reporting stays fine-grained
_MAX_SEGMENT_LEN_M = 1000.0


def _parse_speed(val: str, default: float) -> float:
    val = (val or "").strip().lower()
    if not val:
        return default
    try:
        if val.endswith("mph"):
            return float(val[:-3].strip()) * 1.609344
        return float(val.split()[0])
    except ValueError:
        return default


def _is_oneway(tags: Dict[str, str]) -> int:
    """0 = two-way, 1 = forward only, -1 = reverse only."""
    ow = tags.get("oneway", "").strip().lower()
    if ow in ("yes", "true", "1"):
        return 1
    if ow == "-1":
        return -1
    if ow in ("no", "false", "0"):
        return 0
    if tags.get("junction") in ("roundabout", "circular"):
        return 1
    return 0


# top-level OSM elements; cleared once fully processed. Children (nd/tag)
# must NOT be cleared early — their parent way's end event needs them.
_TOP_LEVEL = {"node", "way", "relation", "bounds"}


def _iter_elements(source: Union[str, IO[bytes]], tag: str):
    root = None
    for event, elem in ET.iterparse(source, events=("start", "end")):
        if event == "start":
            if root is None:
                root = elem
            continue
        if elem.tag == tag:
            yield elem
        if elem.tag in _TOP_LEVEL:
            elem.clear()
            # detach completed children from the root too, or country-scale
            # extracts accumulate one empty Element per node/way
            if root is not None and len(root) > 1024:
                root.clear()


def network_from_osm_xml(source: Union[str, IO[bytes]]) -> RoadNetwork:
    """Parse an OSM XML file (path or binary file object) into a
    RoadNetwork. Two streaming passes; memory is O(referenced nodes)."""
    # pass 1: drivable ways + the node ids they reference
    ways: List[tuple] = []  # (tags, [node ids])
    needed: Dict[int, int] = {}  # osm node id -> dense index (insertion order)
    for elem in _iter_elements(source, "way"):
        tags = {t.get("k"): t.get("v", "") for t in elem.findall("tag")}
        cls = tags.get("highway", "")
        if cls not in _HIGHWAY_CLASSES:
            continue
        refs = [int(nd.get("ref")) for nd in elem.findall("nd")]
        if len(refs) < 2:
            continue
        ways.append((tags, refs))
        for r in refs:
            needed.setdefault(r, len(needed))
    if not ways:
        raise ValueError("no drivable ways found in OSM input")

    # pass 2: coordinates for referenced nodes
    lat = np.full(len(needed), np.nan)
    lon = np.full(len(needed), np.nan)
    if isinstance(source, str):
        node_src: Union[str, IO[bytes]] = source
    else:
        source.seek(0)
        node_src = source
    for elem in _iter_elements(node_src, "node"):
        idx = needed.get(int(elem.get("id")))
        if idx is not None:
            lat[idx] = float(elem.get("lat"))
            lon[idx] = float(elem.get("lon"))
    missing = np.isnan(lat)
    if missing.any():
        # drop ways touching nodes absent from the extract (clipped bbox)
        bad = {osm_id for osm_id, i in needed.items() if missing[i]}
        ways = [(t, refs) for t, refs in ways
                if not any(r in bad for r in refs)]
        if not ways:
            raise ValueError("all ways reference nodes missing from input")

    hierarchy = TileHierarchy()
    seg_counters: Dict[int, int] = {}  # (level<<22|tile) -> next seg index

    e_start: List[int] = []
    e_end: List[int] = []
    e_len: List[float] = []
    e_speed: List[float] = []
    e_seg: List[int] = []
    e_off: List[float] = []
    e_internal: List[bool] = []
    segment_length: Dict[int, float] = {}

    def next_segment_id(level: int, first_node: int) -> int:
        tile_idx = hierarchy.tiles(level).tile_id(
            float(lat[first_node]), float(lon[first_node]))
        key = (level << 22) | tile_idx
        idx = seg_counters.get(key, 0)
        if idx > SEGMENT_INDEX_MASK:
            raise ValueError(f"tile {tile_idx} level {level} overflows "
                             "the 21-bit segment index")
        seg_counters[key] = idx + 1
        return make_segment_id(level, tile_idx, idx)

    # decision points: nodes referenced by more than one drivable way (or
    # more than once by the same way — a self-loop junction). Real OSMLR
    # segments break at these; segment splitting below follows suit.
    way_count: Dict[int, int] = {}
    for _tags, refs in ways:
        local: Dict[int, int] = {}
        for r in refs:
            local[r] = local.get(r, 0) + 1
        for r, c in local.items():
            # a node referenced twice by ONE way (closed ring) is a
            # decision point too: count it as two uses so the split
            # triggers at the loop-closure node
            way_count[r] = way_count.get(r, 0) + (2 if c > 1 else 1)

    for tags, refs in ways:
        cls = tags.get("highway", "")
        level, cls_speed = _HIGHWAY_CLASSES[cls]
        speed = _parse_speed(tags.get("maxspeed", ""), cls_speed)
        internal = cls.endswith(_INTERNAL_SUFFIX) \
            or tags.get("junction") in ("roundabout", "circular")
        associated = cls not in _UNASSOCIATED and not internal
        oneway = _is_oneway(tags)

        nodes = [needed[r] for r in refs]
        is_junction = [way_count.get(r, 0) > 1 for r in refs]
        seg_len = [equirectangular_m(lat[a], lon[a], lat[b], lon[b])
                   for a, b in zip(nodes[:-1], nodes[1:])]
        total = float(sum(seg_len))
        if total <= 0.0:
            continue

        directions = []
        if oneway >= 0:
            directions.append((nodes, seg_len, is_junction))
        if oneway <= 0:
            directions.append((nodes[::-1], seg_len[::-1],
                               is_junction[::-1]))
        for chain, lens, junction in directions:
            # split the way into OSMLR segments at interior decision
            # points and at the ~1 km length cap; offsets restart at 0
            # within each segment
            seg_id = next_segment_id(level, chain[0]) if associated else -1
            off = 0.0
            for step, ((a, b), L) in enumerate(
                    zip(zip(chain[:-1], chain[1:]), lens)):
                e_start.append(a)
                e_end.append(b)
                e_len.append(float(L))
                e_speed.append(speed)
                e_seg.append(seg_id)
                e_off.append(off if seg_id >= 0 else 0.0)
                e_internal.append(internal)
                off += float(L)
                interior = step + 1 < len(chain) - 1
                if seg_id >= 0 and interior and (
                        junction[step + 1] or off >= _MAX_SEGMENT_LEN_M):
                    segment_length[seg_id] = off
                    seg_id = next_segment_id(level, chain[step + 1])
                    off = 0.0
            if seg_id >= 0:
                segment_length[seg_id] = off

    # compact to nodes actually used by surviving edges: dropped/clipped
    # ways leave orphans (and NaN coords for nodes absent from the
    # extract) that would poison the centroid projection and spatial grid
    starts = np.asarray(e_start, dtype=np.int32)
    ends = np.asarray(e_end, dtype=np.int32)
    used = np.zeros(len(needed), dtype=bool)
    used[starts] = True
    used[ends] = True
    remap = np.cumsum(used) - 1
    lat = lat[used]
    lon = lon[used]

    return RoadNetwork(
        node_lat=lat, node_lon=lon,
        edge_start=remap[starts].astype(np.int32),
        edge_end=remap[ends].astype(np.int32),
        edge_length_m=np.asarray(e_len, dtype=np.float32),
        edge_speed_kph=np.asarray(e_speed, dtype=np.float32),
        edge_segment_id=np.asarray(e_seg, dtype=np.int64),
        edge_segment_offset_m=np.asarray(e_off, dtype=np.float32),
        edge_internal=np.asarray(e_internal, dtype=bool),
        segment_length_m=segment_length,
    )
