"""Spatial candidate lookup: probe point -> K nearest road edges.

This is the host-side front half of the matcher. The reference delegates it
to Valhalla's candidate search inside ``SegmentMatcher.Match``
(reference: py/reporter_service.py:240); here it is a uniform grid over
projected meters that emits **fixed-width (T, K) candidate tensors** ready to
ship to the device — padded with sentinel values so every trace in a batch
has identical shape.

A numpy implementation lives here; the C++ host runtime (reporter_tpu.native)
implements the same contract for throughput.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, Tuple

import numpy as np

from .network import RoadNetwork

PAD_EDGE = -1
PAD_DIST = 1.0e9


@dataclass
class CandidateSet:
    """Fixed-width candidates for one trace of T points, K per point.

    Padding: ``edge_ids == PAD_EDGE`` marks unused slots; their ``dist_m``
    is PAD_DIST so Gaussian emission scores underflow to ~-inf on device.
    """
    edge_ids: np.ndarray   # (T, K) i32
    dist_m: np.ndarray     # (T, K) f32 point->edge distance
    offset_m: np.ndarray   # (T, K) f32 along-edge offset of projection
    proj_x: np.ndarray     # (T, K) f32 projected-point coords, meters
    proj_y: np.ndarray     # (T, K) f32

    @property
    def T(self) -> int:
        return self.edge_ids.shape[0]

    @property
    def K(self) -> int:
        return self.edge_ids.shape[1]

    def valid(self) -> np.ndarray:
        return self.edge_ids != PAD_EDGE


class SpatialGrid:
    """Uniform grid over projected meters mapping cells -> edge ids."""

    def __init__(self, net: RoadNetwork, cell_m: float = 250.0):
        self.net = net
        self.cell_m = float(cell_m)
        nx, ny = net.node_xy()
        self.ax = nx[net.edge_start]
        self.ay = ny[net.edge_start]
        self.bx = nx[net.edge_end]
        self.by = ny[net.edge_end]
        # segment direction and squared length, precomputed for projection
        self.dx = self.bx - self.ax
        self.dy = self.by - self.ay
        self.len2 = np.maximum(self.dx * self.dx + self.dy * self.dy, 1e-9)

        self.cells: Dict[Tuple[int, int], np.ndarray] = {}
        lo_i = np.floor(np.minimum(self.ax, self.bx) / self.cell_m).astype(np.int64)
        hi_i = np.floor(np.maximum(self.ax, self.bx) / self.cell_m).astype(np.int64)
        lo_j = np.floor(np.minimum(self.ay, self.by) / self.cell_m).astype(np.int64)
        hi_j = np.floor(np.maximum(self.ay, self.by) / self.cell_m).astype(np.int64)
        buckets: Dict[Tuple[int, int], list] = {}
        for e in range(net.num_edges):
            for i in range(lo_i[e], hi_i[e] + 1):
                for j in range(lo_j[e], hi_j[e] + 1):
                    buckets.setdefault((i, j), []).append(e)
        for key, ids in buckets.items():
            self.cells[key] = np.asarray(ids, dtype=np.int32)

    def _edges_near(self, x: float, y: float, radius_m: float) -> np.ndarray:
        reach = int(np.ceil(radius_m / self.cell_m))
        ci = int(np.floor(x / self.cell_m))
        cj = int(np.floor(y / self.cell_m))
        found = [
            self.cells[(i, j)]
            for i in range(ci - reach, ci + reach + 1)
            for j in range(cj - reach, cj + reach + 1)
            if (i, j) in self.cells
        ]
        if not found:
            return np.empty(0, dtype=np.int32)
        return np.unique(np.concatenate(found))

    def candidates(self, lat: np.ndarray, lon: np.ndarray, k: int,
                   search_radius_m: float = 50.0) -> CandidateSet:
        """K nearest edges within ``search_radius_m`` for each probe point.

        ``search_radius_m`` mirrors the matcher knob of the same name
        (reference: Dockerfile:14-17, generate_test_trace.py:51).
        """
        to_xy, _ = self.net.projection()
        px, py = to_xy(np.asarray(lat, dtype=np.float64),
                       np.asarray(lon, dtype=np.float64))
        px = np.atleast_1d(px).astype(np.float64)
        py = np.atleast_1d(py).astype(np.float64)
        T = len(px)

        edge_ids = np.full((T, k), PAD_EDGE, dtype=np.int32)
        dist_m = np.full((T, k), PAD_DIST, dtype=np.float32)
        offset_m = np.zeros((T, k), dtype=np.float32)
        proj_x = np.zeros((T, k), dtype=np.float32)
        proj_y = np.zeros((T, k), dtype=np.float32)

        for t in range(T):
            near = self._edges_near(px[t], py[t], search_radius_m)
            if near.size == 0:
                continue
            # project the point on each nearby edge segment
            ax, ay = self.ax[near], self.ay[near]
            frac = ((px[t] - ax) * self.dx[near] + (py[t] - ay) * self.dy[near]) \
                / self.len2[near]
            frac = np.clip(frac, 0.0, 1.0)
            qx = ax + frac * self.dx[near]
            qy = ay + frac * self.dy[near]
            d = np.hypot(px[t] - qx, py[t] - qy)
            inside = d <= search_radius_m
            if not inside.any():
                continue
            near, frac, qx, qy, d = (arr[inside] for arr in (near, frac, qx, qy, d))
            take = np.argsort(d, kind="stable")[:k]
            n = len(take)
            edge_ids[t, :n] = near[take]
            dist_m[t, :n] = d[take]
            offset_m[t, :n] = frac[take] * self.net.edge_length_m[near[take]]
            proj_x[t, :n] = qx[take]
            proj_y[t, :n] = qy[take]

        return CandidateSet(edge_ids, dist_m, offset_m, proj_x, proj_y)
