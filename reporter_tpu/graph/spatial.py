"""Spatial candidate lookup: probe point -> K nearest road edges.

This is the host-side front half of the matcher. The reference delegates it
to Valhalla's candidate search inside ``SegmentMatcher.Match``
(reference: py/reporter_service.py:240); here it is a uniform grid over
projected meters that emits **fixed-width (T, K) candidate tensors** ready to
ship to the device — padded with sentinel values so every trace in a batch
has identical shape.

A numpy implementation lives here; the C++ host runtime (reporter_tpu.native)
implements the same contract for throughput.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, Tuple

import numpy as np

from .network import RoadNetwork

PAD_EDGE = -1
PAD_DIST = 1.0e9


@dataclass
class CandidateSet:
    """Fixed-width candidates for one trace of T points, K per point.

    Padding: ``edge_ids == PAD_EDGE`` marks unused slots; their ``dist_m``
    is PAD_DIST so Gaussian emission scores underflow to ~-inf on device.
    """
    edge_ids: np.ndarray   # (T, K) i32
    dist_m: np.ndarray     # (T, K) f32 point->edge distance
    offset_m: np.ndarray   # (T, K) f32 along-edge offset of projection
    proj_x: np.ndarray     # (T, K) f32 projected-point coords, meters
    proj_y: np.ndarray     # (T, K) f32

    @property
    def T(self) -> int:
        return self.edge_ids.shape[0]

    @property
    def K(self) -> int:
        return self.edge_ids.shape[1]

    def valid(self) -> np.ndarray:
        return self.edge_ids != PAD_EDGE


# cell key encoding: one int64 per (i, j) grid cell. |i|,|j| stay far
# below 2**30 for any terrestrial network at >=1 m cells
_KEY_M = np.int64(1) << np.int64(31)


class SpatialGrid:
    """Uniform grid over projected meters mapping cells -> edge ids.

    The cell map is stored as a CSR over SORTED int64 cell keys
    (``_cell_keys`` / ``_cell_off`` / ``_cell_edges``) so a whole batch of
    probe points resolves its neighborhoods with one ``searchsorted`` —
    the grid query itself is columnar, no Python per point. This is the
    numpy half of the whole-batch candidate search; the C++ runtime
    implements the same contract for the native path.
    """

    def __init__(self, net: RoadNetwork, cell_m: float = 250.0):
        self.net = net
        self.cell_m = float(cell_m)
        nx, ny = net.node_xy()
        self.ax = nx[net.edge_start]
        self.ay = ny[net.edge_start]
        self.bx = nx[net.edge_end]
        self.by = ny[net.edge_end]
        # segment direction and squared length, precomputed for projection
        self.dx = self.bx - self.ax
        self.dy = self.by - self.ay
        self.len2 = np.maximum(self.dx * self.dx + self.dy * self.dy, 1e-9)

        lo_i = np.floor(np.minimum(self.ax, self.bx) / self.cell_m).astype(np.int64)
        hi_i = np.floor(np.maximum(self.ax, self.bx) / self.cell_m).astype(np.int64)
        lo_j = np.floor(np.minimum(self.ay, self.by) / self.cell_m).astype(np.int64)
        hi_j = np.floor(np.maximum(self.ay, self.by) / self.cell_m).astype(np.int64)
        buckets: Dict[Tuple[int, int], list] = {}
        for e in range(net.num_edges):
            for i in range(lo_i[e], hi_i[e] + 1):
                for j in range(lo_j[e], hi_j[e] + 1):
                    buckets.setdefault((i, j), []).append(e)

        # CSR over sorted cell keys — the grid's ONLY runtime structure
        keys = np.array([np.int64(i) * _KEY_M + np.int64(j)
                         for i, j in buckets], dtype=np.int64)
        order = np.argsort(keys)
        self._cell_keys = keys[order]
        groups = [np.asarray(ids, dtype=np.int32)
                  for ids in buckets.values()]
        counts = np.array([len(groups[o]) for o in order], dtype=np.int64)
        self._cell_off = np.zeros(len(order) + 1, dtype=np.int64)
        np.cumsum(counts, out=self._cell_off[1:])
        self._cell_edges = (np.concatenate([groups[o] for o in order])
                            if len(order) else np.zeros(0, np.int32))

    def _pair_candidates(self, px: np.ndarray, py: np.ndarray,
                         radius_m: float):
        """All (point, edge) pairs whose grid neighborhoods intersect:
        returns (pt, edge) index arrays, deduplicated and sorted by
        (pt, edge). Fully vectorised — the per-point Python loop this
        replaces was 62% of host prep on the fallback path."""
        T = len(px)
        reach = int(np.ceil(radius_m / self.cell_m))
        ci = np.floor(px / self.cell_m).astype(np.int64)
        cj = np.floor(py / self.cell_m).astype(np.int64)
        span = np.arange(-reach, reach + 1, dtype=np.int64)
        di = np.repeat(span, len(span))
        dj = np.tile(span, len(span))
        # (T, C) neighborhood cell keys -> CSR slots via one searchsorted
        keys = ((ci[:, None] + di[None, :]) * _KEY_M
                + (cj[:, None] + dj[None, :])).ravel()
        pos = np.searchsorted(self._cell_keys, keys)
        pos_c = np.minimum(pos, len(self._cell_keys) - 1) \
            if len(self._cell_keys) else pos
        hit = (pos < len(self._cell_keys))
        if len(self._cell_keys):
            hit &= self._cell_keys[pos_c] == keys
        if not hit.any():
            return (np.zeros(0, np.int64), np.zeros(0, np.int64))
        slot = pos[hit]
        pt_of_cell = np.repeat(np.arange(T, dtype=np.int64),
                               len(span) * len(span))[hit]
        starts = self._cell_off[slot]
        counts = self._cell_off[slot + 1] - starts
        total = int(counts.sum())
        # ragged gather of every occupied cell's edge list
        off = np.zeros(len(counts), dtype=np.int64)
        np.cumsum(counts[:-1], out=off[1:])
        flat = np.arange(total, dtype=np.int64) + np.repeat(starts - off,
                                                            counts)
        e = self._cell_edges[flat].astype(np.int64)
        pt = np.repeat(pt_of_cell, counts)
        # dedup (pt, edge): an edge spans several neighborhood cells. The
        # unique sort also fixes the tie order (ascending edge id within a
        # point), matching the old per-point np.unique exactly.
        pair = pt * np.int64(self.net.num_edges) + e
        pair = np.unique(pair)
        return (pair // np.int64(self.net.num_edges),
                pair % np.int64(self.net.num_edges))

    def candidates(self, lat: np.ndarray, lon: np.ndarray, k: int,
                   search_radius_m: float = 50.0) -> CandidateSet:
        """K nearest edges within ``search_radius_m`` for each probe point.

        ``search_radius_m`` mirrors the matcher knob of the same name
        (reference: Dockerfile:14-17, generate_test_trace.py:51). One call
        serves any number of points — of one trace or a whole batch of
        traces (flat columns) — in a fixed set of numpy ops.
        """
        to_xy, _ = self.net.projection()
        px, py = to_xy(np.asarray(lat, dtype=np.float64),
                       np.asarray(lon, dtype=np.float64))
        px = np.atleast_1d(px).astype(np.float64)
        py = np.atleast_1d(py).astype(np.float64)
        T = len(px)

        edge_ids = np.full((T, k), PAD_EDGE, dtype=np.int32)
        dist_m = np.full((T, k), PAD_DIST, dtype=np.float32)
        offset_m = np.zeros((T, k), dtype=np.float32)
        proj_x = np.zeros((T, k), dtype=np.float32)
        proj_y = np.zeros((T, k), dtype=np.float32)

        pt, e = self._pair_candidates(px, py, search_radius_m)
        if not len(pt):
            return CandidateSet(edge_ids, dist_m, offset_m, proj_x, proj_y)

        # project every (point, edge) pair at once
        ax, ay = self.ax[e], self.ay[e]
        frac = ((px[pt] - ax) * self.dx[e] + (py[pt] - ay) * self.dy[e]) \
            / self.len2[e]
        frac = np.clip(frac, 0.0, 1.0)
        qx = ax + frac * self.dx[e]
        qy = ay + frac * self.dy[e]
        d = np.hypot(px[pt] - qx, py[pt] - qy)
        inside = d <= search_radius_m
        if not inside.any():
            return CandidateSet(edge_ids, dist_m, offset_m, proj_x, proj_y)
        pt, e, frac, qx, qy, d = (a[inside]
                                  for a in (pt, e, frac, qx, qy, d))

        # top-k per point: sort by (point, distance, edge) — the stable
        # per-point argsort over ascending-edge pairs this replaces broke
        # distance ties by edge id, so the tertiary key preserves it —
        # then rank within each point's group and keep ranks < k
        order = np.lexsort((e, d, pt))
        pt, e, frac, qx, qy, d = (a[order]
                                  for a in (pt, e, frac, qx, qy, d))
        first = np.r_[True, pt[1:] != pt[:-1]]
        group_start = np.maximum.accumulate(
            np.where(first, np.arange(len(pt)), 0))
        rank = np.arange(len(pt)) - group_start
        keep = rank < k
        rows = pt[keep]
        cols = rank[keep]
        e, frac, qx, qy, d = (a[keep] for a in (e, frac, qx, qy, d))
        edge_ids[rows, cols] = e
        dist_m[rows, cols] = d
        offset_m[rows, cols] = frac * self.net.edge_length_m[e]
        proj_x[rows, cols] = qx
        proj_y[rows, cols] = qy

        return CandidateSet(edge_ids, dist_m, offset_m, proj_x, proj_y)
