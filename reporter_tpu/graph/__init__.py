from .network import RoadNetwork, EdgeAttr
from .spatial import SpatialGrid, CandidateSet
from .route import route_distance, candidate_route_matrices
from .version import map_version

__all__ = [
    "RoadNetwork", "EdgeAttr",
    "SpatialGrid", "CandidateSet",
    "route_distance", "candidate_route_matrices",
    "map_version",
]
