from .network import RoadNetwork, EdgeAttr
from .spatial import SpatialGrid, CandidateSet
from .route import route_distance, candidate_route_matrices

__all__ = [
    "RoadNetwork", "EdgeAttr",
    "SpatialGrid", "CandidateSet",
    "route_distance", "candidate_route_matrices",
]
