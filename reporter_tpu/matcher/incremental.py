"""Carried per-trace decode state: the incremental Viterbi matcher.

The streaming batcher trims only the consumed prefix of a session's
window (``streaming/batcher.py``), so windows overlap and every
mid-stream report used to re-decode its WHOLE window — O(T·K^2) per
report, forever, per long-lived uuid. This module carries the decode
forward instead: per uuid it keeps the last-step log-scores (K,), a
bounded backpointer ring of the **uncommitted** tail, and the compact
per-step scalars segment assembly actually reads for the committed
prefix; an appended point then costs one candidate lookup, one route
row, and one batched device step (``ops/incremental.py``) — flat in T.

Byte-exact parity with the windowed batch path is the design
constraint, not an aspiration:

- scoring reuses ``hmm.emission_scores``/``transition_scores`` through
  the incremental kernel, and the only reductions involved (max /
  argmax) are exact in f32 — the carried scores are bit-identical to
  the batch scan's running scores at the same step;
- the f16 wire policy mirrors ``batchpad.pack_batches`` per trace: a
  window that would ship f16 quantises every appended step through the
  same f16 round-trip; a window that goes out of f16 range falls back
  to the batch path (the pack would flip the whole window to f32);
- **fixed-lag commit** finalises a ring step only when every current
  state's backtrace converges to the same ancestor there — the
  committed choice provably equals what the final full backtrace would
  pick, whatever is appended later. A window whose ambiguity outlives
  the lag bound falls back to the batch path rather than guess;
- host prep replicates ``batchpad`` semantics step-by-step (kept-point
  selection against the last kept anchor, per-point candidate pruning,
  f32 great-circle casts, breakage RESTARTs, trailing-jitter dwell),
  and assembly runs the same ``assemble_segments`` over a synthesised
  ``PreparedTrace``.

Anything the incremental path cannot reproduce byte-for-byte — bucket
truncation, wire-dtype flips, non-convergent lag windows, state-table
eviction — is a *fallback to the batch path for that trace*, never an
approximation. The windowed decode stays the parity oracle: the shadow
sampler (``REPORTER_TPU_SHADOW_SAMPLE``, PR 8) re-decodes sampled
incremental traces through the full window and compares match bytes.

Knobs: ``REPORTER_TPU_INCREMENTAL`` (kill switch, on by default where
wired), ``REPORTER_TPU_INCREMENTAL_LAG`` (max uncommitted ring steps),
``REPORTER_TPU_INCREMENTAL_MB`` (carried-state byte budget; LRU
eviction beyond it). The table is pressure-ladder-sheddable like the
PR 14 shadow state: the ``shed_trace`` rung suspends the incremental
path and releases its state bytes.
"""
from __future__ import annotations

import json
import logging
import os
import struct
import threading
import time
from typing import Dict, List, Optional

import numpy as np

from ..core.geo import equirectangular_m
from ..graph.route import UNREACHABLE, candidate_route_matrices
from ..graph.spatial import PAD_DIST, PAD_EDGE, CandidateSet
from ..utils import faults, metrics
from .assemble import assemble_segments
from .batchpad import (LENGTH_BUCKETS, PreparedTrace, _prune_candidates,
                       _route_prune_margin, _wire_f16)
from .hmm import NORMAL, RESTART, UNREACHABLE_THRESHOLD, WIRE_MAX_M

logger = logging.getLogger("reporter_tpu.matcher.incremental")

ENV_INCREMENTAL = "REPORTER_TPU_INCREMENTAL"
ENV_LAG = "REPORTER_TPU_INCREMENTAL_LAG"
ENV_BUDGET_MB = "REPORTER_TPU_INCREMENTAL_MB"

DEFAULT_LAG = 32
DEFAULT_BUDGET_MB = 64.0


def incremental_enabled() -> bool:
    """The ``REPORTER_TPU_INCREMENTAL`` kill switch (same grammar as the
    REPORTER_TPU_NATIVE matcher.circuit switch: off|0|false disables)."""
    return os.environ.get(ENV_INCREMENTAL, "").strip().lower() \
        not in ("0", "off", "false")


def lag_bound() -> int:
    """Max uncommitted ring steps per trace before fixed-lag commit must
    land (non-convergence past it falls back to the batch path)."""
    try:
        v = int(os.environ.get(ENV_LAG, "").strip() or DEFAULT_LAG)
        return max(2, v)
    except ValueError:
        return DEFAULT_LAG


def budget_bytes() -> int:
    """Carried-state table byte budget (LRU eviction beyond it)."""
    try:
        v = float(os.environ.get(ENV_BUDGET_MB, "").strip()
                  or DEFAULT_BUDGET_MB)
    except ValueError:
        v = DEFAULT_BUDGET_MB
    return int(max(0.0, v) * 1024 * 1024)


# pressure-ladder rung flag (service/admission.py shed_trace): one global
# load on the hot path, set only on ladder transitions
_pressure_shed = False


def set_pressure_shed(on: bool) -> None:
    global _pressure_shed
    _pressure_shed = bool(on)


def pressure_shed() -> bool:
    return _pressure_shed


class _Fallback(Exception):
    """This trace must be served by the batch path (reason in args[0]).
    Not an error: raised whenever incremental cannot reproduce the batch
    bytes (truncation, wire flip, non-convergent lag window)."""


class _Ring:
    """One uncommitted kept step: full candidate row (assembly needs the
    chosen one, unknown until backtrace), backpointers, and the raw f32
    route row from the previous kept step (assembly reads transition
    scalars; pre-wire values, exactly what ``prepare`` would store)."""

    __slots__ = ("kept_idx", "case", "edge_ids", "offset_m", "bp",
                 "prev_best", "route_in")

    def __init__(self, kept_idx, case, edge_ids, offset_m, bp, prev_best,
                 route_in):
        self.kept_idx = int(kept_idx)
        self.case = int(case)
        self.edge_ids = edge_ids      # (K,) i32
        self.offset_m = offset_m      # (K,) f32
        self.bp = bp                  # (K,) i32 | None (window-first step)
        self.prev_best = int(prev_best)
        self.route_in = route_in      # (K, K) f32 | None (window-first)

    def nbytes(self, K: int) -> int:
        return 4 * K * K + 3 * 4 * K + 64


class _Step:
    """Host-prepped inputs for one appended kept point, queued for the
    batched device step."""

    __slots__ = ("kept_idx", "case", "dist_w", "valid", "route_w", "gc_w",
                 "edge_ids", "offset_m", "route_raw")

    def __init__(self, kept_idx, case, dist_w, valid, route_w, gc_w,
                 edge_ids, offset_m, route_raw):
        self.kept_idx = kept_idx
        self.case = case
        self.dist_w = dist_w          # (K,) f32, wire round-tripped
        self.valid = valid            # (K,) bool
        self.route_w = route_w        # (K,K) f32, wire round-tripped
        self.gc_w = gc_w              # f32 scalar, wire round-tripped
        self.edge_ids = edge_ids      # (K,) i32 (pruned)
        self.offset_m = offset_m      # (K,) f32 (pruned)
        self.route_raw = route_raw    # (K,K) f32 pre-wire | None (first)


class CarriedState:
    """Everything one uuid's decode carries between appended points."""

    __slots__ = ("params_key", "f16", "K", "map_version",
                 "t0", "last_time", "n_raw",
                 "has_cands", "last_kept_raw", "last_lat", "last_lon",
                 "tail_ok", "prev_cand", "scores",
                 "c_kept", "c_case", "c_col", "c_edge", "c_off", "c_route",
                 "ring")

    def __init__(self, params_key, f16: bool, K: int,
                 map_version: Optional[str] = None):
        self.params_key = params_key
        self.f16 = bool(f16)
        self.K = int(K)
        # the graph build this state's edge ids/backpointers belong to
        # (graph/version.py); part of the cache identity — a hot swap
        # must never serve segment ids decoded against a dead graph
        self.map_version = map_version
        self.t0 = 0.0                 # first raw time of the window
        self.last_time = 0.0          # last processed raw time
        self.n_raw = 0                # raw points processed
        self.has_cands: List[bool] = []
        self.last_kept_raw = -1       # raw index of the last kept point
        self.last_lat = 0.0
        self.last_lon = 0.0
        self.tail_ok = True           # raw tail since last kept is jitter
        self.prev_cand = None         # pruned (K,) candidate row arrays
        self.scores: Optional[np.ndarray] = None  # (K,) f32 carried
        # committed prefix: the scalars assembly reads, one per step
        self.c_kept: List[int] = []   # raw index
        self.c_case: List[int] = []
        self.c_col: List[int] = []    # chosen candidate column
        self.c_edge: List[int] = []
        self.c_off: List[float] = []
        self.c_route: List[float] = []  # route to NEXT committed step
        self.ring: List[_Ring] = []

    @property
    def n_kept(self) -> int:
        return len(self.c_kept) + len(self.ring)

    def nbytes(self) -> int:
        K = self.K
        return (256 + len(self.has_cands)
                + 40 * len(self.c_kept)
                + sum(e.nbytes(K) for e in self.ring)
                + 5 * 4 * K)

    # -- snapshot serde (state snapshot v3) --------------------------------
    _HEAD = struct.Struct("<BBHddiiq??dd")

    def to_bytes(self) -> bytes:
        """Self-contained blob for the v3 state snapshot. Scalars are
        struct-packed, arrays raw ``tobytes`` with shapes implied by K
        and the packed counts."""
        K = self.K
        key = np.asarray(self.params_key, dtype=np.float64)
        out = [self._HEAD.pack(2, int(self.f16), K, self.t0,
                               self.last_time, self.n_raw,
                               self.last_kept_raw, len(self.c_kept),
                               self.tail_ok, self.prev_cand is not None,
                               self.last_lat, self.last_lon),
               struct.pack("<HH", len(key), len(self.ring)),
               key.tobytes(),
               np.packbits(np.asarray(self.has_cands, dtype=bool)
                           ).tobytes()]
        if self.prev_cand is not None:
            e, d, o, px, py = self.prev_cand
            out += [e.tobytes(), d.tobytes(), o.tobytes(),
                    px.tobytes(), py.tobytes()]
        sc = self.scores if self.scores is not None \
            else np.zeros(0, dtype=np.float32)
        out.append(struct.pack("<H", len(sc)))
        out.append(sc.tobytes())
        out.append(np.asarray(self.c_kept, dtype=np.int32).tobytes())
        out.append(np.asarray(self.c_case, dtype=np.int8).tobytes())
        out.append(np.asarray(self.c_col, dtype=np.int16).tobytes())
        out.append(np.asarray(self.c_edge, dtype=np.int32).tobytes())
        out.append(np.asarray(self.c_off, dtype=np.float32).tobytes())
        out.append(np.asarray(self.c_route, dtype=np.float32).tobytes())
        for r in self.ring:
            first = r.bp is None
            out.append(struct.pack("<iiB?", r.kept_idx, r.case,
                                   r.prev_best, first))
            out += [r.edge_ids.tobytes(), r.offset_m.tobytes()]
            if not first:
                out += [r.bp.tobytes(), r.route_in.tobytes()]
        # v2 trailer: the graph version the state was decoded against
        mv = (self.map_version or "").encode()
        out.append(struct.pack("<H", len(mv)))
        out.append(mv)
        return b"".join(out)

    @classmethod
    def from_bytes(cls, blob: bytes) -> "CarriedState":
        off = 0

        def take(n):
            nonlocal off
            if off + n > len(blob):
                raise ValueError("truncated carried-state blob")
            b = blob[off:off + n]
            off += n
            return b

        (ver, f16, K, t0, last_time, n_raw, last_kept, n_c, tail_ok,
         has_prev, last_lat, last_lon) = cls._HEAD.unpack(
            take(cls._HEAD.size))
        if ver not in (1, 2):
            raise ValueError(f"carried-state version {ver} unsupported")
        n_key, n_ring = struct.unpack("<HH", take(4))
        key = tuple(np.frombuffer(take(8 * n_key), dtype=np.float64)
                    .tolist())
        st = cls(key, bool(f16), K)
        st.t0, st.last_time, st.n_raw = t0, last_time, n_raw
        st.last_kept_raw = last_kept
        st.tail_ok = bool(tail_ok)
        st.last_lat, st.last_lon = last_lat, last_lon
        bits = np.frombuffer(take((n_raw + 7) // 8), dtype=np.uint8)
        st.has_cands = np.unpackbits(bits, count=n_raw).astype(bool) \
            .tolist()
        if has_prev:
            e = np.frombuffer(take(4 * K), dtype=np.int32)
            d = np.frombuffer(take(4 * K), dtype=np.float32)
            o = np.frombuffer(take(4 * K), dtype=np.float32)
            px = np.frombuffer(take(4 * K), dtype=np.float32)
            py = np.frombuffer(take(4 * K), dtype=np.float32)
            st.prev_cand = (e, d, o, px, py)
        (n_sc,) = struct.unpack("<H", take(2))
        sc = np.frombuffer(take(4 * n_sc), dtype=np.float32)
        st.scores = sc.copy() if n_sc else None
        st.c_kept = np.frombuffer(take(4 * n_c), np.int32).tolist()
        st.c_case = np.frombuffer(take(1 * n_c), np.int8).tolist()
        st.c_col = np.frombuffer(take(2 * n_c), np.int16).tolist()
        st.c_edge = np.frombuffer(take(4 * n_c), np.int32).tolist()
        st.c_off = np.frombuffer(take(4 * n_c), np.float32).tolist()
        st.c_route = np.frombuffer(take(4 * n_c), np.float32).tolist()
        for _ in range(n_ring):
            kept_idx, case, prev_best, first = struct.unpack(
                "<iiB?", take(10))
            edge = np.frombuffer(take(4 * K), dtype=np.int32)
            offm = np.frombuffer(take(4 * K), dtype=np.float32)
            bp = route_in = None
            if not first:
                bp = np.frombuffer(take(4 * K), dtype=np.int32)
                route_in = np.frombuffer(take(4 * K * K), dtype=np.float32
                                         ).reshape(K, K)
            st.ring.append(_Ring(kept_idx, case, edge, offm, bp,
                                 prev_best, route_in))
        if ver >= 2:
            (n_mv,) = struct.unpack("<H", take(2))
            mv = take(n_mv).decode()
            st.map_version = mv or None
        # ver 1 blobs predate graph versioning: map_version stays None,
        # which a versioned table treats as a mismatch — the trace
        # re-decodes from its window rather than trusting edge ids of
        # unknown provenance
        return st


def _wire_roundtrip(arr: np.ndarray) -> np.ndarray:
    """The f16 wire quantisation pack_batches applies, as a value map:
    f32 -> f16 -> f32 (sentinels overflow to +inf, upcast intact —
    exactly what the device decode sees after the wire)."""
    with np.errstate(over="ignore"):
        return arr.astype(np.float16).astype(np.float32)


class IncrementalTable:
    """uuid -> :class:`CarriedState`, byte-budgeted with LRU eviction.

    Owned by a :class:`SegmentMatcher` (``matcher.incremental_table``);
    all device work goes through ``ops.incremental_step_batch`` so N
    traces advance per dispatch. Mutations run under one lock — the
    streaming worker advances from its flush thread while /health and
    the heartbeat read the gauge from theirs.
    """

    def __init__(self, matcher):
        self.matcher = matcher
        # cache identity includes the graph build (graph/version.py):
        # a city hot swap rebuilds the matcher around a new net, and
        # every carried state minted against the old one must reset
        # instead of serving segment ids from a dead graph
        try:
            from ..graph.version import map_version
            self.map_version: Optional[str] = map_version(matcher.net)
        except Exception:
            self.map_version = None
        self._states: Dict[str, CarriedState] = {}
        self._order: List[str] = []   # LRU, oldest first
        self._lock = threading.Lock()
        self._bytes = 0
        self.evictions = 0
        self.fallbacks = 0
        self.resets = 0

    # -- gauges ------------------------------------------------------------
    def gauge(self) -> dict:
        with self._lock:
            return {"traces": len(self._states),
                    "map_version": self.map_version,
                    "state_bytes": self._bytes,
                    "budget_bytes": budget_bytes(),
                    "lag": lag_bound(),
                    "evictions": self.evictions,
                    "fallbacks": self.fallbacks,
                    "resets": self.resets}

    def _recount(self) -> None:
        self._bytes = sum(s.nbytes() for s in self._states.values())

    def _touch(self, uuid: str) -> None:
        try:
            self._order.remove(uuid)
        except ValueError:
            pass
        self._order.append(uuid)

    def evict(self, uuid: str, reason: str = "evicted") -> None:
        with self._lock:
            if self._states.pop(uuid, None) is not None:
                try:
                    self._order.remove(uuid)
                except ValueError:
                    pass
                self.evictions += 1
                metrics.count("match.incremental.evictions")
                self._recount()
                logger.debug("carried state for %s %s", uuid, reason)

    def clear(self) -> None:
        """Drop every carried state (pressure shed / kill switch)."""
        with self._lock:
            n = len(self._states)
            self._states.clear()
            self._order.clear()
            self._bytes = 0
            if n:
                self.evictions += n
                metrics.count("match.incremental.evictions", n)

    def _enforce_budget(self, keep: Optional[str] = None) -> None:
        """LRU-evict until under budget (called with the lock held)."""
        budget = budget_bytes()
        while self._bytes > budget and self._order:
            victim = None
            for u in self._order:
                if u != keep:
                    victim = u
                    break
            if victim is None:
                victim = self._order[0]  # even the active trace goes
            self._states.pop(victim, None)
            self._order.remove(victim)
            self.evictions += 1
            metrics.count("match.incremental.evictions")
            self._recount()

    # -- snapshot serde ----------------------------------------------------
    def to_blobs(self) -> List[tuple]:
        """[(uuid, blob)] for the v3 state snapshot."""
        with self._lock:
            return [(u, s.to_bytes()) for u, s in self._states.items()]

    def restore_blobs(self, blobs) -> int:
        """Load [(uuid, blob)] from a v3 snapshot; returns count loaded.
        A blob that fails to parse is skipped (that trace re-decodes
        from its window on the next report — correctness is unaffected,
        the snapshot only buys work avoidance)."""
        n = 0
        with self._lock:
            for uuid, blob in blobs:
                try:
                    self._states[uuid] = CarriedState.from_bytes(blob)
                    self._touch(uuid)
                    n += 1
                except Exception as e:
                    logger.warning("carried state for %s failed to "
                                   "restore (%s); it will re-decode",
                                   uuid, e)
            self._recount()
        return n

    # -- the advance + match path ------------------------------------------
    def match_many(self, tb, per_trace_params, results) -> int:
        """Advance carried state for every trace of ``tb`` with a uuid
        and fill ``results[i]`` with a match dict; slots left None fall
        back to the batch path. Returns the number of per-trace
        failures (real errors, not parity fallbacks)."""
        lag = lag_bound()
        jobs = []   # [i, uuid, state, steps, params, alive]
        failures = 0
        with self._lock:
            try:
                # decode cost — prep of the appended points, the kernel
                # rounds, and the fixed-lag commits — timed apart from
                # the serve assembly below: the O(K)-per-point claim
                # (and the bench gate on it) is about THIS span, while
                # assembly is the O(window) report-emission cost the
                # batch path pays identically
                t_dec = time.perf_counter()
                for i in range(len(tb)):
                    uuid = tb.uuid(i)
                    if not uuid:
                        continue
                    params = per_trace_params[i]
                    lat, lon, times = tb.trace_columns(i)
                    if len(times) == 0:
                        continue
                    try:
                        state = self._state_for(uuid, params, times)
                        steps = self._prep_appended(state, params, lat,
                                                    lon, times)
                    except _Fallback as fb:
                        self.fallbacks += 1
                        metrics.count("match.incremental.fallbacks")
                        logger.debug("trace %s falls back to the batch "
                                     "path (%s)", uuid, fb)
                        self._drop(uuid)
                        continue
                    jobs.append([i, uuid, state, steps, params, True])

                failures += self._run_rounds(jobs, lag)
                metrics.observe("match.incremental.decode",
                                time.perf_counter() - t_dec)

                for i, uuid, state, steps, params, alive in jobs:
                    if not alive:
                        continue
                    _lat, _lon, times = tb.trace_columns(i)
                    results[i] = self._build_match(state, times, params)
                    self._touch(uuid)
                    metrics.count("match.incremental.matches")
            except Exception:
                # a mid-advance error leaves SOME state half-stepped
                # (n_raw past the scores) — drop every state this call
                # touched so nothing stale survives to the next report
                failures += 1
                metrics.count("match.incremental.errors")
                for job in jobs:
                    self._drop(job[1])
                self._recount()
                raise
            delta = -self._bytes
            self._recount()
            delta += self._bytes
            if delta:
                metrics.count("match.incremental.state_bytes", delta)
            keep = jobs[-1][1] if jobs else None
            self._enforce_budget(keep=keep)
        # shadow parity sampling runs outside the lock (it re-preps and
        # re-decodes the full window)
        for i, uuid, state, steps, params, alive in jobs:
            if alive and results[i] is not None:
                lat, lon, times = tb.trace_columns(i)
                _maybe_shadow(self.matcher, lat, lon, times, params,
                              results[i])
        return failures

    def _drop(self, uuid: str) -> None:
        """Lock-held eviction (fallback/error paths)."""
        if self._states.pop(uuid, None) is not None:
            try:
                self._order.remove(uuid)
            except ValueError:
                pass

    def _state_for(self, uuid, params, times) -> CarriedState:
        key = tuple(
            float(getattr(params, f))
            for f in type(self.matcher)._PREP_KEY_FIELDS)
        f16 = _wire_f16()
        n = len(times)
        st = self._states.get(uuid)
        if st is not None:
            ok = (st.params_key == key and st.f16 == f16
                  and st.map_version == self.map_version
                  and 0 < st.n_raw <= n
                  and st.t0 == float(times[0])
                  and st.last_time == float(times[st.n_raw - 1]))
            if not ok:
                # window identity changed: the batcher trimmed at
                # shape_used (or a new session reused the uuid) — the
                # batch oracle frames the new window with RESTART at its
                # first kept point, so the carried chain resets and the
                # short surviving window replays incrementally
                self._drop(uuid)
                self.resets += 1
                metrics.count("match.incremental.resets")
                st = None
        if st is None:
            st = CarriedState(key, f16, int(params.max_candidates),
                              map_version=self.map_version)
            self._states[uuid] = st
            self._touch(uuid)
        return st

    def _prep_appended(self, state: CarriedState, params, lat, lon,
                       times) -> List[_Step]:
        """Host prep for raw points [state.n_raw, len(times)): kept-point
        selection, candidate lookup + pruning, the route row from the
        previous kept point — mirroring batchpad semantics exactly.
        Mutates selection state as it goes (any later failure evicts)."""
        m = self.matcher
        K = state.K
        lookup = m.runtime if m.runtime is not None else m.grid
        margin = _route_prune_margin(params)
        steps: List[_Step] = []
        n = len(times)
        if state.n_raw == 0:
            state.t0 = float(times[0])
        for j in range(state.n_raw, n):
            row = lookup.candidates(lat[j:j + 1], lon[j:j + 1], K,
                                    params.search_radius)
            has = bool((row.edge_ids != PAD_EDGE).any())
            state.has_cands.append(has)
            state.n_raw = j + 1
            state.last_time = float(times[j])
            if not has:
                state.tail_ok = False   # off-network tail: no dwell
                continue
            gc64 = None
            if state.last_kept_raw >= 0:
                gc64 = equirectangular_m(state.last_lat, state.last_lon,
                                         float(lat[j]), float(lon[j]))
                if gc64 < params.interpolation_distance:
                    continue            # jitter drop; tail stays ok
            if state.n_kept + 1 > LENGTH_BUCKETS[-1]:
                # the batch path truncates at the largest bucket; that
                # semantics is window-global, not per-step
                raise _Fallback("window exceeds the largest bucket")
            pruned = _prune_candidates(
                CandidateSet(edge_ids=row.edge_ids, dist_m=row.dist_m,
                             offset_m=row.offset_m, proj_x=row.proj_x,
                             proj_y=row.proj_y), margin)
            steps.append(self._make_step(state, params, pruned, gc64,
                                         times, j))
            state.last_kept_raw = j
            state.last_lat = float(lat[j])
            state.last_lon = float(lon[j])
            state.tail_ok = True
            state.prev_cand = (
                np.ascontiguousarray(pruned.edge_ids[0]),
                np.ascontiguousarray(pruned.dist_m[0]),
                np.ascontiguousarray(pruned.offset_m[0]),
                np.ascontiguousarray(pruned.proj_x[0]),
                np.ascontiguousarray(pruned.proj_y[0]))
        return steps

    def _make_step(self, state, params, pruned, gc64, times, j) -> _Step:
        """Route row + case code + wire cast for one appended kept point."""
        m = self.matcher
        K = state.K
        dist = np.ascontiguousarray(pruned.dist_m[0])
        valid = pruned.edge_ids[0] != PAD_EDGE
        if gc64 is None:        # first kept point of the window
            case = RESTART
            gc32 = np.float32(0.0)
            route_raw = None
            route_in = np.full((K, K), UNREACHABLE, dtype=np.float32)
        else:
            gc32 = np.float32(gc64)
            case = RESTART if gc32 > params.breakage_distance else NORMAL
            pe, pd, po, ppx, ppy = state.prev_cand
            pair = CandidateSet(
                edge_ids=np.stack([pe, pruned.edge_ids[0]]),
                dist_m=np.stack([pd, dist]),
                offset_m=np.stack([po, pruned.offset_m[0]]),
                proj_x=np.stack([ppx, pruned.proj_x[0]]),
                proj_y=np.stack([ppy, pruned.proj_y[0]]))
            gc_arr = np.asarray([gc32], dtype=np.float32)
            dt = None
            if params.max_route_time_factor > 0:
                dt = np.asarray(
                    [times[j] - times[state.last_kept_raw]])
            if m.runtime is not None:
                route = m.runtime.route_matrices(
                    pair, gc_arr,
                    max_route_distance_factor=params
                    .max_route_distance_factor,
                    backward_tolerance_m=params.backward_tolerance_m,
                    dt=dt,
                    max_route_time_factor=params.max_route_time_factor,
                    min_time_bound_s=params.min_time_bound_s,
                    turn_penalty_factor=params.turn_penalty_factor)
            else:
                route = candidate_route_matrices(
                    m.net, pair, gc_arr,
                    max_route_distance_factor=params
                    .max_route_distance_factor,
                    cache=m.route_cache,
                    backward_tolerance_m=params.backward_tolerance_m,
                    dt=dt,
                    max_route_time_factor=params.max_route_time_factor,
                    min_time_bound_s=params.min_time_bound_s,
                    turn_penalty_factor=params.turn_penalty_factor)
            route_raw = np.ascontiguousarray(route[0], dtype=np.float32)
            route_in = route_raw
        dist_w, route_w, gc_w = dist, route_in, gc32
        if state.f16:
            # per-trace mirror of the pack_batches wire decision: a
            # finite value out of f16 range would flip the WHOLE window
            # to the f32 wire in the batch path — history the carried
            # f16 scores can't rewrite, so fall back instead
            fin_d = float(np.amax(dist, initial=0.0,
                                  where=dist < UNREACHABLE_THRESHOLD))
            fin_r = float(np.amax(route_in, initial=0.0,
                                  where=route_in < UNREACHABLE_THRESHOLD))
            if max(fin_d, fin_r, float(gc32)) > WIRE_MAX_M:
                raise _Fallback("finite distance beyond the f16 wire")
            dist_w = _wire_roundtrip(dist)
            route_w = _wire_roundtrip(route_in)
            gc_w = _wire_roundtrip(np.asarray(gc32))[()]
        return _Step(j, case, dist_w, valid, route_w, gc_w,
                     np.ascontiguousarray(pruned.edge_ids[0]),
                     np.ascontiguousarray(pruned.offset_m[0]),
                     route_raw)

    def _run_rounds(self, jobs, lag: int) -> int:
        """Advance every job's queued steps through the batched kernel,
        one dispatch per round (round r = each trace's r-th step); ring
        rows pad to a power of two so the jit shape count stays
        logarithmic. Returns per-round failure count."""
        from ..ops import incremental_step_batch
        failures = 0
        r = 0
        while True:
            rows = [job for job in jobs
                    if job[5] and r < len(job[3])]
            if not rows:
                break
            # group rows by the device scalars (one kernel call each);
            # the steady state is a single shared params object
            groups: Dict[tuple, list] = {}
            for job in rows:
                p = job[4]
                gkey = (float(p.effective_sigma), float(p.beta),
                        int(p.max_candidates))
                groups.setdefault(gkey, []).append(job)
            for (sigma, beta, K), grp in groups.items():
                self._round(grp, r, K, sigma, beta,
                            incremental_step_batch, lag)
            r += 1
        return failures

    def _round(self, grp, r, K, sigma, beta, kernel, lag) -> None:
        n = len(grp)
        rows = 1 << max(n - 1, 0).bit_length()   # pow2 pad
        dist = np.full((rows, K), PAD_DIST, dtype=np.float32)
        valid = np.zeros((rows, K), dtype=bool)
        route = np.full((rows, K, K), UNREACHABLE, dtype=np.float32)
        gc = np.zeros(rows, dtype=np.float32)
        case = np.full(rows, RESTART, dtype=np.int32)
        prev = np.zeros((rows, K), dtype=np.float32)
        for b, job in enumerate(grp):
            step = job[3][r]
            st = job[2]
            dist[b] = step.dist_w
            valid[b] = step.valid
            route[b] = step.route_w
            gc[b] = step.gc_w
            case[b] = step.case
            if st.scores is not None:
                prev[b] = st.scores
        new_scores, bp, prev_best = kernel(
            dist, valid, route, gc, case, prev,
            np.float32(sigma), np.float32(beta))
        new_scores = np.asarray(new_scores)
        bp = np.asarray(bp)
        prev_best = np.asarray(prev_best)
        metrics.count("match.incremental.steps", n)
        for b, job in enumerate(grp):
            step = job[3][r]
            st = job[2]
            first = st.scores is None
            st.scores = new_scores[b].copy()
            st.ring.append(_Ring(
                step.kept_idx, step.case, step.edge_ids, step.offset_m,
                None if first else bp[b].copy(),
                0 if first else int(prev_best[b]),
                None if first else step.route_raw))
            try:
                while len(st.ring) > lag:
                    self._commit_one(st)
            except _Fallback as fb:
                self.fallbacks += 1
                metrics.count("match.incremental.fallbacks")
                logger.debug("trace %s falls back to the batch path "
                             "(%s)", job[1], fb)
                job[5] = False
                self._drop(job[1])

    def _commit_one(self, st: CarriedState) -> None:
        """Fixed-lag commit of the oldest ring step: finalise its choice
        iff every current state's backtrace converges there. The
        converged ancestor provably equals what the final backtrace
        will pick — whatever gets appended later enters ABOVE these
        steps, so the pointer chase below them never changes."""
        K = st.K
        cur = np.arange(K, dtype=np.int32)
        for e in reversed(st.ring[1:]):
            if e.case == RESTART:
                cur = np.full(K, e.prev_best, dtype=np.int32)
            else:
                cur = e.bp[cur]
        c = int(cur[0])
        if not bool((cur == c).all()):
            raise _Fallback("lag window did not converge")
        faults.failpoint("match.incremental.commit")
        e0 = st.ring.pop(0)
        if st.c_kept and e0.route_in is not None:
            # the transition INTO this step, at the now-known choice
            # pair, becomes the previous committed step's outgoing
            # route scalar (what assembly reads)
            st.c_route[-1] = float(e0.route_in[st.c_col[-1], c])
        st.c_kept.append(e0.kept_idx)
        st.c_case.append(e0.case)
        st.c_col.append(c)
        st.c_edge.append(int(e0.edge_ids[c]))
        st.c_off.append(float(e0.offset_m[c]))
        st.c_route.append(float(UNREACHABLE))   # until the next commit
        metrics.count("match.incremental.commits")

    def _build_match(self, st: CarriedState, times, params) -> dict:
        """Synthesise a PreparedTrace + decoded path from the carried
        state and run the SAME scalar assembly as the batch fallback
        path — byte-identical match dicts by construction."""
        K = st.K
        nc = len(st.c_kept)
        n = st.n_kept
        # live-tail backtrace (the batch backward pass over the ring)
        ring_path: List[int] = []
        if st.ring:
            cur = int(np.argmax(st.scores))
            ring_path = [cur]
            for e in reversed(st.ring[1:]):
                cur = e.prev_best if e.case == RESTART else int(e.bp[cur])
                ring_path.append(cur)
            ring_path.reverse()
        path = np.zeros(max(n, 1), dtype=np.int32)
        path[nc:n] = ring_path

        edge_ids = np.full((n, K), PAD_EDGE, dtype=np.int32)
        offset = np.zeros((n, K), dtype=np.float32)
        case = np.zeros(n, dtype=np.int32)
        kept_idx = np.zeros(n, dtype=np.int32)
        route_m = np.full((max(n - 1, 0), K, K), UNREACHABLE,
                          dtype=np.float32)
        if nc:
            kept_idx[:nc] = st.c_kept
            case[:nc] = st.c_case
            edge_ids[:nc, 0] = st.c_edge
            offset[:nc, 0] = st.c_off
            # committed->committed transitions live at the (0, 0) cell
            # the all-zero committed path indexes
            route_m[:max(nc - 1, 0), 0, 0] = st.c_route[:nc - 1] \
                if nc > 1 else []
        for t, e in enumerate(st.ring):
            kept_idx[nc + t] = e.kept_idx
            case[nc + t] = e.case
            edge_ids[nc + t] = e.edge_ids
            offset[nc + t] = e.offset_m
            if e.route_in is None:
                continue
            if t == 0 and nc:
                # last committed -> first ring step: the committed side
                # sits in column 0, the ring side keeps its true index
                route_m[nc - 1, 0, :] = e.route_in[st.c_col[-1], :]
            elif t > 0:
                route_m[nc + t - 1] = e.route_in
        dwell = 0.0
        if n and st.last_kept_raw < st.n_raw - 1 and st.tail_ok:
            dwell = float(times[st.n_raw - 1] - times[st.last_kept_raw])
        prepared = PreparedTrace(
            num_raw=st.n_raw, num_kept=n, kept_idx=kept_idx,
            times=np.asarray(times), edge_ids=edge_ids, dist_m=offset * 0,
            offset_m=offset, route_m=route_m,
            gc_m=np.zeros(max(n - 1, 0), dtype=np.float32), case=case,
            trailing_jitter_dwell_s=dwell,
            has_cands=np.asarray(st.has_cands, dtype=bool))
        return assemble_segments(
            self.matcher.net, prepared, path, mode=params.mode,
            queue_threshold_kph=params.queue_speed_threshold_kph,
            interpolation_distance_m=params.interpolation_distance,
            backward_tolerance_m=params.backward_tolerance_m,
            turn_penalty_factor=params.turn_penalty_factor)


# -- shadow parity oracle (the PR 8 sampler, generalised) -------------------

_shadow_lock = threading.Lock()
_shadow_acc = 0.0


def _maybe_shadow(matcher, lat, lon, times, params, match) -> None:
    """Deterministic-accumulator sampling (REPORTER_TPU_SHADOW_SAMPLE,
    shared with the decode shadow): re-decode this trace's FULL window
    through the batch oracle (prepare -> wire cast -> numpy Viterbi ->
    scalar assembly) and compare match bytes. A mismatch is a parity
    bug, counted and logged — the incremental result still serves (the
    sampler observes, the circuit + fallbacks act)."""
    from ..obs import profiler
    frac = profiler.shadow_fraction()
    if frac <= 0.0:
        return
    global _shadow_acc
    with _shadow_lock:
        _shadow_acc += min(frac, 1.0)
        if _shadow_acc < 1.0:
            return
        _shadow_acc -= 1.0
    try:
        oracle = _oracle_match(matcher, lat, lon, times, params)
        a = json.dumps(match, sort_keys=True)
        b = json.dumps(oracle, sort_keys=True)
        metrics.count("match.incremental.shadow_checks")
        if a != b:
            metrics.count("match.incremental.shadow_mismatches")
            logger.warning(
                "incremental/batch parity mismatch on a %d-point window "
                "(incremental %d bytes, oracle %d bytes)",
                len(times), len(a), len(b))
    except Exception as e:   # the sampler must never take down serving
        metrics.count("match.incremental.shadow_errors")
        logger.warning("incremental shadow check failed: %s", e)


def _oracle_match(matcher, lat, lon, times, params) -> dict:
    """The windowed batch path for one trace, end to end on the host:
    prepare -> pack (wire dtype decision included) -> numpy Viterbi
    oracle -> scalar assembly. This is the parity definition the bench
    and tests hold the incremental path to."""
    from .batchpad import pack_batches
    from .cpu_ref import viterbi_decode_numpy
    points = [{"lat": float(lat[j]), "lon": float(lon[j]),
               "time": float(times[j])} for j in range(len(times))]
    prep = matcher.prepare(points, params)
    batch = pack_batches([prep])[0]
    T = batch.dist_m.shape[1]
    path, _score = viterbi_decode_numpy(
        np.asarray(batch.dist_m[0], dtype=np.float32),
        np.asarray(batch.valid[0]),
        np.asarray(batch.route_m[0, :max(T - 1, 0)], dtype=np.float32),
        np.asarray(batch.gc_m[0, :max(T - 1, 0)], dtype=np.float32),
        np.asarray(batch.case[0]),
        np.float32(params.effective_sigma), np.float32(params.beta))
    return assemble_segments(
        matcher.net, prep, path, mode=params.mode,
        queue_threshold_kph=params.queue_speed_threshold_kph,
        interpolation_distance_m=params.interpolation_distance,
        backward_tolerance_m=params.backward_tolerance_m,
        turn_penalty_factor=params.turn_penalty_factor)


__all__ = ["IncrementalTable", "CarriedState", "incremental_enabled",
           "lag_bound", "budget_bytes", "set_pressure_shed",
           "pressure_shed"]
