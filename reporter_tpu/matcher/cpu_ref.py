"""Pure-numpy single-trace Viterbi — the reference-architecture analog.

Two jobs:

1. **Bench baseline.** The reference decodes one trace at a time on one
   CPU thread inside C++ Meili (reference: py/reporter_service.py:240,
   Batch.java:66-68). This module is the closest in-repo analog of that
   one Meili thread: same emission/transition semantics as the device
   kernels, no XLA, no batching — what bench.py's ``vs_baseline`` ratio
   is measured against (BASELINE.md's ">=50x over single-process Meili").
2. **Oracle.** An implementation independent of lax.scan/associative-scan
   for the equivalence tests.

Semantics mirror matcher/hmm.py exactly: emission ``-0.5*(d/sigma)^2``
(invalid candidates -inf), transition ``-|route-gc|/beta`` (unreachable
-inf), SKIP steps carry state through the identity, RESTART steps start a
new chain carrying the finished chain's best score as a constant offset.
"""
from __future__ import annotations

import numpy as np

from .hmm import NEG_INF, RESTART, SKIP, UNREACHABLE_THRESHOLD


def viterbi_decode_numpy(dist_m, valid, route_m, gc_m, case, sigma, beta):
    """Decode ONE trace; shapes (T,K), (T,K), (T-1,K,K), (T-1,), (T,).

    Returns (path (T,) i32, score f32) with the same contract as one row
    of hmm.viterbi_decode_batch.
    """
    dist_m = np.asarray(dist_m, dtype=np.float32)
    route_m = np.asarray(route_m, dtype=np.float32)
    gc_m = np.asarray(gc_m, dtype=np.float32)
    case = np.asarray(case)
    T, K = dist_m.shape

    em = np.where(valid, -0.5 * (dist_m / np.float32(sigma)) ** 2, NEG_INF)
    em[case == SKIP] = 0.0

    identity = np.where(np.eye(K, dtype=bool), 0.0, NEG_INF).astype(np.float32)

    scores = em[0].copy()
    bps = np.empty((T - 1, K), dtype=np.int32)
    prev_bests = np.empty(T - 1, dtype=np.int32)
    for t in range(1, T):
        if case[t] == SKIP:
            tr_t = identity
        elif case[t] == RESTART:
            tr_t = np.zeros((K, K), dtype=np.float32)
        else:
            dev = np.abs(route_m[t - 1] - gc_m[t - 1])
            tr_t = np.where(route_m[t - 1] < UNREACHABLE_THRESHOLD,
                            -dev / np.float32(beta), NEG_INF)
        cand = scores[:, None] + tr_t
        best = cand.max(axis=0)
        bps[t - 1] = cand.argmax(axis=0)
        prev_bests[t - 1] = int(scores.argmax())
        stepped = best + em[t]
        if case[t] == RESTART:
            scores = scores.max() + em[t]
        else:
            scores = stepped

    path = np.empty(T, dtype=np.int32)
    path[-1] = int(scores.argmax())
    for t in range(T - 1, 0, -1):
        if case[t] == RESTART:
            path[t - 1] = prev_bests[t - 1]
        else:
            path[t - 1] = bps[t - 1][path[t]]
    return path, np.float32(scores.max())
