"""Host-side trace preparation: candidates, route tensors, padding buckets.

Two pieces of irregularity are resolved here so the device program stays
fixed-shape and branch-free (SURVEY.md §7 "Hard parts: raggedness"):

1. **Point filtering.** Probe points closer than ``interpolation_distance``
   to the last kept point (GPS jitter while slow/stopped) and points with no
   candidate edges are *excluded* from the HMM; the Viterbi runs over the
   kept subsequence only, and excluded jitter points are attributed to the
   decoded runs afterwards (candidate-less probes — off-network — stay
   unattributed wherever they occur; see assemble.py's span fix-up). This
   mirrors Meili's interpolation behavior and is what keeps
   backward-jitter from reading as a u-turn.

2. **Bucketed padding.** Kept subsequences are padded to the smallest bucket
   in ``LENGTH_BUCKETS`` so XLA compiles a handful of shapes, not thousands.
"""
from __future__ import annotations

import bisect
import os as _os
from dataclasses import dataclass
from typing import List, Sequence

import numpy as np

from ..core.geo import equirectangular_m
from ..core.tracebatch import TraceBatch, points_to_columns
from ..graph.network import RoadNetwork
from ..graph.route import RouteCache, candidate_route_matrices, UNREACHABLE
from ..graph.spatial import CandidateSet, SpatialGrid, PAD_EDGE, PAD_DIST
from .hmm import (
    NORMAL, RESTART, SKIP, UNREACHABLE_THRESHOLD, WIRE_MAX_M)
from .params import MatchParams

LENGTH_BUCKETS = (16, 64, 256, 1024)

#: FLASH-style candidate pruning margin, in multiples of the HMM's
#: effective sigma: after the distance-sorted candidate gather, a
#: point's candidates beyond ``dist[0] + sigma_mult * effective_sigma``
#: are dropped BEFORE any route between them is requested — their
#: emission probability is already vanishing relative to the best
#: candidate, so the route columns they'd occupy are near-certain
#: Viterbi losers. 0 (default) disables pruning; the shadow-accuracy
#: sampler (obs/shadow.py) is the guard rail when arming it.
ENV_PRUNE = "REPORTER_TPU_ROUTE_PRUNE_SIGMA"

#: runtime bucket-ladder override: "16,64,256,1024" (ascending ints),
#: with an optional "@<waste>" suffix setting the occupancy-driven
#: split threshold ("@1" / "@off" disables splitting). Default: the
#: fixed LENGTH_BUCKETS ladder with splitting at DEFAULT_SPLIT_WASTE.
ENV_BUCKETS = "REPORTER_TPU_BUCKETS"

#: padding-waste ratio above which the native dispatcher breaks a
#: mixed-length chunk into per-pow2-bucket sub-batches (matcher.py
#: SegmentMatcher._split_bucket) — high enough that the exact-fill steady state
#: (BENCH_DEV_r07 recorded 0.21 whole-run, mostly jitter drops and
#: pow2 row padding a finer T can't reclaim) never splits, low enough
#: that a 17-point trace padding to T=64 (waste ~0.73) always does
DEFAULT_SPLIT_WASTE = 0.35

_ladder_cache: "dict[str, tuple]" = {}

#: pressure-ladder rung (service/admission.py "coarse_buckets"): under
#: sustained overload the adaptive splitter is disabled — fewer, larger
#: decode shapes, no split dispatches and no fresh compile episodes
#: mid-storm. The ladder flips it; bucket_ladder() reports threshold
#: 1.0 (never split) while it holds.
_pressure_coarse = False


def set_pressure_coarse(on: bool) -> None:
    global _pressure_coarse
    _pressure_coarse = bool(on)


def bucket_ladder() -> "tuple[tuple, float]":
    """(ladder, split_threshold) from REPORTER_TPU_BUCKETS; the default
    fixed ladder with the default threshold when unset. A malformed
    spec logs and keeps the default (a typo'd ladder must degrade to
    the shipped shapes, never to an unbounded shape zoo)."""
    spec = _os.environ.get(ENV_BUCKETS, "").strip()
    if not spec:
        # the default is NOT cached: LENGTH_BUCKETS is read live, so
        # tests that monkeypatch the module ladder keep working
        return (LENGTH_BUCKETS,
                1.0 if _pressure_coarse else DEFAULT_SPLIT_WASTE)
    got = _ladder_cache.get(spec)
    if got is not None:
        return (got[0], 1.0) if _pressure_coarse else got
    ladder, thresh = LENGTH_BUCKETS, DEFAULT_SPLIT_WASTE
    if spec:
        body, _, tail = spec.partition("@")
        try:
            if tail.strip().lower() in ("off", "no", "false"):
                thresh = 1.0
            elif tail.strip():
                thresh = float(tail)
            vals = tuple(int(v) for v in body.split(",") if v.strip())
            if body.strip():
                if not vals or any(v <= 0 for v in vals) or \
                        list(vals) != sorted(set(vals)):
                    raise ValueError("ladder must be ascending positive")
                ladder = vals
            if not 0.0 < thresh:
                raise ValueError("threshold must be positive")
        except ValueError as e:
            import logging
            logging.getLogger("reporter_tpu.matcher").warning(
                "%s=%r not understood (%s); keeping the default ladder",
                ENV_BUCKETS, spec, e)
            ladder, thresh = LENGTH_BUCKETS, DEFAULT_SPLIT_WASTE
    _ladder_cache[spec] = (ladder, thresh)
    return (ladder, 1.0) if _pressure_coarse else (ladder, thresh)


def bucket_length(n: int) -> int:
    """Smallest bucket >= n (the last bucket caps the trace length).
    Reads the runtime ladder (REPORTER_TPU_BUCKETS; default unchanged)."""
    ladder, _ = bucket_ladder()
    idx = bisect.bisect_left(ladder, n)
    return ladder[min(idx, len(ladder) - 1)]


def kept_point_count(batch: "PaddedBatch") -> int:
    """Kept (non-SKIP) probe points across a padded batch — the
    occupancy numerator of the profiler's wide events. One whole-tensor
    count over the (B, T) case codes: pad rows and padding tails are
    all-SKIP by construction, so no per-trace view materialises."""
    return int(np.count_nonzero(np.asarray(batch.case) != SKIP))


def occupancy_stats(kept_points: int, rows: int, T: int
                    ) -> "tuple[int, float, float]":
    """(padded point cells, occupancy, padding-waste ratio) for a batch
    padded to ``rows`` traces of bucket length ``T``. The waste ratio
    is the fraction of decoded point slots that carry no real probe —
    what variable-length (FLASH-style) bucketing would reclaim; the
    candidate width K scales both sides, so it cancels."""
    cells = rows * T
    occ = kept_points / cells if cells else 0.0
    return cells, occ, 1.0 - occ


@dataclass
class PreparedTrace:
    """One trace's fixed-width tensors, padded to bucket length T.

    Tensor rows 0..num_kept-1 correspond to the *kept* points;
    ``kept_idx`` maps them back to indices in the original trace.
    """
    num_raw: int           # points in the original trace
    num_kept: int          # points included in the HMM
    kept_idx: np.ndarray   # (num_kept,) i32 original indices
    times: np.ndarray      # (num_raw,) f64 epoch seconds
    edge_ids: np.ndarray   # (T, K) i32
    dist_m: np.ndarray     # (T, K) f32
    offset_m: np.ndarray   # (T, K) f32
    route_m: np.ndarray    # (T-1, K, K) f32
    gc_m: np.ndarray       # (T-1,) f32
    case: np.ndarray       # (T,) i32
    # seconds the raw tail verifiably dwelt at the last kept point (jitter
    # drops only; 0 when the tail was off-network or bucket-truncated)
    trailing_jitter_dwell_s: float = 0.0
    # (num_raw,) u8/bool: raw point had any candidate edge; None on
    # hand-built preps (assembler then treats every drop as jitter)
    has_cands: "np.ndarray | None" = None

    @property
    def T(self) -> int:
        return self.edge_ids.shape[0]


def _select_kept(lat, lon, has_cands, interpolation_distance):
    """Indices of points that enter the HMM: drop candidate-less points and
    points within ``interpolation_distance`` of the last kept point.

    Vectorised common case: when every consecutive pair of candidate-
    bearing points is at least the interpolation distance apart (a moving
    vehicle — the overwhelming majority of traces), the anchor never
    skips a point and the answer is one array op. The sequential scan
    only runs from the first violation onward (a slow/stopped stretch),
    where the moving-anchor semantics are irreducibly order-dependent.
    """
    has = np.asarray(has_cands, dtype=bool)
    idx = np.flatnonzero(has)
    if idx.size <= 1:
        return idx.astype(np.int32)
    lat = np.asarray(lat)
    lon = np.asarray(lon)
    gc = np.atleast_1d(equirectangular_m(lat[idx[:-1]], lon[idx[:-1]],
                                         lat[idx[1:]], lon[idx[1:]]))
    viol = np.flatnonzero(gc < interpolation_distance)
    if viol.size == 0:
        return idx.astype(np.int32)
    j = int(viol[0])  # pairs before the first violation are all kept
    kept = idx[:j + 1].tolist()
    for i in idx[j + 1:].tolist():
        gc_i = equirectangular_m(lat[kept[-1]], lon[kept[-1]],
                                 lat[i], lon[i])
        if gc_i < interpolation_distance:
            continue
        kept.append(i)
    return np.asarray(kept, dtype=np.int32)


def prepare_trace(net: RoadNetwork, grid: SpatialGrid | None,
                  points: Sequence[dict], params: MatchParams,
                  cache: RouteCache | None = None,
                  runtime=None) -> PreparedTrace:
    """Candidates + route tensors + case codes for one trace, padded.

    ``runtime`` (reporter_tpu.native.NativeRuntime) supplies C++ candidate
    lookup and route matrices when available; the numpy ``grid`` + ``cache``
    path is the fallback with identical semantics. ``points`` is a point-
    dict sequence (converted to columns once, here at the edge).
    """
    lat, lon, times, _acc = points_to_columns(points)
    lookup = runtime if runtime is not None else grid
    all_cands = lookup.candidates(lat, lon, params.max_candidates,
                                  params.search_radius)
    has_cands = (all_cands.edge_ids != PAD_EDGE).any(axis=1)
    return _prepare_from_candidates(net, lat, lon, times, all_cands,
                                    has_cands, params, cache, runtime)


def prepare_traces_numpy(net: RoadNetwork, grid: SpatialGrid,
                         tb: TraceBatch, params: MatchParams,
                         cache: RouteCache | None = None,
                         ) -> List[PreparedTrace]:
    """Whole-chunk numpy host prep (the fallback hot path): ONE vectorised
    candidate search over every point of every trace in the chunk, then
    per-trace route tensors through the shared cross-batch route cache.
    Same per-trace semantics as :func:`prepare_trace` — the candidate
    tensors sliced out of the batch lookup are identical to a per-trace
    lookup because the grid query is a pure per-point function."""
    K = params.max_candidates
    all_c = grid.candidates(tb.lat, tb.lon, K, params.search_radius)
    has_all = (all_c.edge_ids != PAD_EDGE).any(axis=1)
    out = []
    offsets = tb.offsets
    for b in range(len(tb)):
        lo, hi = int(offsets[b]), int(offsets[b + 1])
        sub = CandidateSet(
            edge_ids=all_c.edge_ids[lo:hi], dist_m=all_c.dist_m[lo:hi],
            offset_m=all_c.offset_m[lo:hi], proj_x=all_c.proj_x[lo:hi],
            proj_y=all_c.proj_y[lo:hi])
        out.append(_prepare_from_candidates(
            net, tb.lat[lo:hi], tb.lon[lo:hi], tb.time[lo:hi], sub,
            has_all[lo:hi], params, cache, None))
    return out


def _prepare_from_candidates(net, lat, lon, times, all_cands, has_cands,
                             params: MatchParams, cache, runtime
                             ) -> PreparedTrace:
    """Kept-point selection, route tensors, case codes and padding for one
    trace whose candidate lookup already happened (shared by the
    per-trace and whole-batch prep paths)."""
    num_raw = len(lat)
    K = params.max_candidates
    kept = _select_kept(lat, lon, has_cands, params.interpolation_distance)
    n = len(kept)
    T = bucket_length(max(n, 1))
    truncated = n > T
    if truncated:  # cap at the largest bucket
        kept = kept[:T]
        n = T

    # dwell time of a *jitter-only* trailing tail: every raw point after the
    # last kept one must have candidates and sit within the interpolation
    # distance of that kept point — i.e. the vehicle verifiably stayed put.
    # Tails dropped for lacking candidates (off-network driving) or by
    # bucket truncation carry no such guarantee and count no dwell. Used by
    # segment assembly to detect a vehicle queued at trace end.
    trailing_jitter_dwell_s = 0.0
    if n and not truncated and int(kept[-1]) < num_raw - 1:
        lk = int(kept[-1])
        tail = np.arange(lk + 1, num_raw)
        tail_gc = equirectangular_m(lat[lk], lon[lk], lat[tail], lon[tail])
        if bool(has_cands[tail].all()) and \
                bool((np.atleast_1d(tail_gc)
                      < params.interpolation_distance).all()):
            trailing_jitter_dwell_s = float(times[num_raw - 1] - times[lk])

    cands = CandidateSet(
        edge_ids=all_cands.edge_ids[kept], dist_m=all_cands.dist_m[kept],
        offset_m=all_cands.offset_m[kept], proj_x=all_cands.proj_x[kept],
        proj_y=all_cands.proj_y[kept])
    cands = _prune_candidates(cands, _route_prune_margin(params))

    gc = equirectangular_m(lat[kept[:-1]], lon[kept[:-1]],
                           lat[kept[1:]], lon[kept[1:]]) if n > 1 else np.zeros(0)
    gc = np.atleast_1d(np.asarray(gc, dtype=np.float32))

    # probe time deltas between consecutive KEPT points feed Meili's
    # max_route_time_factor admissibility bound (reference: Dockerfile:16);
    # None disables the bound entirely (factor <= 0)
    dt = None
    if params.max_route_time_factor > 0 and n > 1:
        dt = np.diff(times[kept])

    if runtime is not None:
        route = runtime.route_matrices(
            cands, gc,
            max_route_distance_factor=params.max_route_distance_factor,
            backward_tolerance_m=params.backward_tolerance_m,
            dt=dt, max_route_time_factor=params.max_route_time_factor,
            min_time_bound_s=params.min_time_bound_s,
            turn_penalty_factor=params.turn_penalty_factor)
    else:
        route = candidate_route_matrices(
            net, cands, gc,
            max_route_distance_factor=params.max_route_distance_factor,
            cache=cache,
            backward_tolerance_m=params.backward_tolerance_m,
            dt=dt, max_route_time_factor=params.max_route_time_factor,
            min_time_bound_s=params.min_time_bound_s,
            turn_penalty_factor=params.turn_penalty_factor)

    # case codes over kept points: RESTART at the first point and after
    # breakage-sized gaps; SKIP only in the padding tail
    case = np.full(T, SKIP, dtype=np.int32)
    if n:
        case[:n] = NORMAL
        case[0] = RESTART
        if n > 1:
            case[1:n][gc[:n - 1] > params.breakage_distance] = RESTART

    # pad to bucket
    edge_ids = np.full((T, K), PAD_EDGE, dtype=np.int32)
    dist = np.full((T, K), PAD_DIST, dtype=np.float32)
    offset = np.zeros((T, K), dtype=np.float32)
    route_p = np.full((max(T - 1, 0), K, K), UNREACHABLE, dtype=np.float32)
    gc_p = np.zeros(max(T - 1, 0), dtype=np.float32)

    edge_ids[:n] = cands.edge_ids
    dist[:n] = cands.dist_m
    offset[:n] = cands.offset_m
    if n > 1:
        route_p[:n - 1] = route
        gc_p[:n - 1] = gc

    return PreparedTrace(num_raw=num_raw, num_kept=n, kept_idx=kept,
                         times=times, edge_ids=edge_ids, dist_m=dist,
                         offset_m=offset, route_m=route_p, gc_m=gc_p,
                         case=case,
                         trailing_jitter_dwell_s=trailing_jitter_dwell_s,
                         has_cands=np.asarray(has_cands))


class _LazyTraceViews:
    """Sequence of PreparedTrace views built on first element access.

    The native hot path (SegmentMatcher._drain_stage with batched
    assembly) only ever needs ``len()`` — building 512 dataclass views
    with 8 numpy slices each cost ~3 ms per chunk for nothing. Tests
    and the fallback assembler index/iterate, which materialises."""

    def __init__(self, n: int, build):
        self._n = n
        self._build = build
        self._views: List[PreparedTrace] | None = None

    def _mat(self) -> List[PreparedTrace]:
        if self._views is None:
            self._views = self._build()
        return self._views

    def __len__(self) -> int:
        return self._n

    def __getitem__(self, i):
        return self._mat()[i]

    def __iter__(self):
        return iter(self._mat())


@dataclass
class PaddedBatch:
    """A device-ready batch of same-bucket traces."""
    traces: "List[PreparedTrace] | _LazyTraceViews"
    dist_m: np.ndarray   # (B, T, K) f32
    valid: np.ndarray    # (B, T, K) bool
    # route/gc time rows: T-1 on the numpy pack_batches path, T on the
    # native prepare_batch path (dead trailing step so the dominant
    # tensor shards along seq with zero pad copies); the decode kernels
    # accept either and slice inside jit (matcher/hmm.py trim_time_pad)
    route_m: np.ndarray  # (B, T-1 | T, K, K) f32
    gc_m: np.ndarray     # (B, T-1 | T) f32
    case: np.ndarray     # (B, T) i32
    # native batched-prep extras (None on the per-trace fallback path):
    # the raw prepare_batch tensors + flat point arrays, consumed by the
    # native batched assembler (NativeRuntime.assemble_batch)
    prep: dict | None = None
    pt_off: np.ndarray | None = None     # (B+1,) i64
    times_flat: np.ndarray | None = None  # flat f64 raw probe times
    # deferred wire finalisation (the device-resident route path of
    # prepare_batch(defer_routes=True)): the decode stage runs it once
    # before reading the batch tensors, paying the device sync there —
    # overlapped with the next chunk's native prep — instead of in prep
    finalize: "object | None" = None

    def finalize_wire(self) -> None:
        """Run the deferred route write-back + wire-dtype cast; no-op
        when the batch was built synchronously."""
        f, self.finalize = self.finalize, None
        if f is not None:
            f(self)


def prepare_batch(runtime, traces_points: Sequence[Sequence[dict]],
                  params: MatchParams, T: int,
                  pad_rows: int | None = None,
                  n_threads: int = 0,
                  route_kernel=None,
                  route_circuit=None,
                  defer_routes: bool = False) -> PaddedBatch:
    """Whole-chunk host prep through ONE native call (the hot path).

    Same per-trace semantics as :func:`prepare_trace` — the C++ side
    (host_runtime.cpp rt_prepare_batch) mirrors candidate search, jitter/
    no-candidate selection, case codes and route bounds exactly, and the
    parity is pinned by tests/test_native.py — but with zero per-trace
    Python: one ctypes round-trip prepares the whole chunk straight into
    padded (B, T, ...) tensors, fanned out across C++ threads. This is
    what replaces the reference's one-C++-Match-per-trace architecture
    (reference: py/reporter_service.py:240) on the host side; BENCH_r03
    measured per-trace Python as the end-to-end ceiling.

    ``traces_points``: a columnar :class:`TraceBatch` (the zero-dict hot
    path — flat coordinate arrays pass straight through to the native
    call) or one list of point dicts per trace (converted here, once).
    ``T``: the padding bucket (all traces in a chunk share it — callers
    bucket by raw length first). ``pad_rows`` >= B adds all-SKIP filler
    rows (mesh divisibility / pow2 shape bounding). Float tensors ship on
    the f16 wire when every finite distance fits (same policy as
    pack_batches).

    ``route_kernel`` (graph/route_device.py DeviceRouteKernel) moves the
    route-cost stage onto the device: the native call runs with
    ``skip_routes`` and the kernel fills ``route_m`` from one batched
    bounded relaxation. Any device failure (or an open ``route_circuit``)
    falls back to a native re-prep WITH routes — byte-identical output,
    just slower — and records the outcome on the circuit so a sick
    device stops being retried per-chunk.

    ``defer_routes=True`` (the pipelined matcher's mode) keeps the
    device route tensor DEVICE-RESIDENT: the assembly is dispatched in
    prep but never synced here — ``route_m`` on the returned batch is
    the in-flight device array (padded to the native wire layout) and
    the batch carries a ``finalize`` closure the decode stage runs
    before reading tensors, which pays the sync + wire-f16 decision
    there, overlapped with the next chunk's native prep. Every device
    failure still raises at dispatch time, inside this call, so circuit
    and fallback semantics are identical to the synchronous path.

    Returns a PaddedBatch whose ``traces`` are PreparedTrace *views* over
    the batch tensors (rows of the pre-cast f32 arrays), usable by
    assemble_segments unchanged.
    """
    if isinstance(traces_points, TraceBatch):
        B = len(traces_points)
        pt_off = traces_points.offsets
        counts = np.diff(pt_off)
        lat, lon, times = (traces_points.lat, traces_points.lon,
                           traces_points.time)
    else:
        B = len(traces_points)
        counts = [len(pts) for pts in traces_points]
        pt_off = np.zeros(B + 1, dtype=np.int64)
        np.cumsum(counts, out=pt_off[1:])
        n_pts = int(pt_off[-1])
        lat = np.fromiter((p["lat"] for pts in traces_points for p in pts),
                          np.float64, n_pts)
        lon = np.fromiter((p["lon"] for pts in traces_points for p in pts),
                          np.float64, n_pts)
        times = np.fromiter((p["time"] for pts in traces_points for p in pts),
                            np.float64, n_pts)

    use_device = route_kernel is not None and \
        (route_circuit is None or route_circuit.allow())
    if route_kernel is not None and not use_device:
        from ..utils import metrics
        metrics.count("route.device.circuit_skipped_chunks")

    def native_prep(skip_routes: bool) -> dict:
        return runtime.prepare_batch(
            pt_off, lat, lon, times, T, params.max_candidates,
            search_radius=params.search_radius,
            interpolation_distance=params.interpolation_distance,
            breakage_distance=params.breakage_distance,
            max_route_distance_factor=params.max_route_distance_factor,
            backward_tolerance_m=params.backward_tolerance_m,
            max_route_time_factor=params.max_route_time_factor,
            min_time_bound_s=params.min_time_bound_s,
            turn_penalty_factor=params.turn_penalty_factor,
            prune_margin_m=_route_prune_margin(params),
            skip_routes=skip_routes,
            n_threads=n_threads, n_rows=pad_rows)

    out = native_prep(skip_routes=use_device)
    pending = None
    if use_device:
        from ..obs import trace as obs_trace
        from ..utils import metrics
        try:
            with obs_trace.span("prep.routes_device"):
                pending = route_kernel.fill_prep(out, params, B,
                                                 defer=defer_routes)
        except Exception:
            if route_circuit is not None:
                route_circuit.record_failure()
            metrics.count("route.device.errors")
            metrics.count("route.device.fallback_chunks")
            import logging
            logging.getLogger("reporter_tpu.matcher").warning(
                "device route kernel failed; re-prepping chunk with host "
                "routes", exc_info=True)
            out = native_prep(skip_routes=False)
        else:
            if route_circuit is not None:
                route_circuit.record_success()

    def build_views() -> List[PreparedTrace]:
        if pending is not None:
            pending.write_back(out)
        edge_ids, kept, num_kept = out["edge_ids"], out["kept_idx"], \
            out["num_kept"]
        views = []
        for b in range(B):
            nk = int(num_kept[b])
            views.append(PreparedTrace(
                num_raw=int(counts[b]), num_kept=nk, kept_idx=kept[b, :nk],
                times=times[pt_off[b]:pt_off[b + 1]],
                edge_ids=edge_ids[b], dist_m=out["dist_m"][b],
                offset_m=out["offset_m"][b],
                # the batch tensors carry T time rows (dead trailing
                # step, for seq sharding); the per-trace view keeps the
                # documented (T-1, ...) contract — a contiguous slice,
                # no copy
                route_m=out["route_m"][b, :max(T - 1, 0)],
                gc_m=out["gc_m"][b, :max(T - 1, 0)], case=out["case"][b],
                trailing_jitter_dwell_s=float(out["dwell"][b]),
                has_cands=out["has_cands"][pt_off[b]:pt_off[b + 1]]))
        return views

    # wire dtype: one vectorised decision + cast for the whole batch
    # (sentinels overflow f16 to +inf, which device scoring treats
    # identically — matcher/hmm.py). The cast runs in native code
    # (F16C); numpy's f16 astype was the top host cost after batching.
    dist, route, gc = out["dist_m"], out["route_m"], out["gc_m"]
    finalize = None
    if pending is not None:
        # device-resident: route_m is installed by finalize (the
        # deferred handle may still be a dispatch future on a warm
        # cache); the wire dtype is decided at decode time from the
        # SAME total max the sync path folds (device route bytes are
        # host-identical, so the decision — and therefore the f16
        # quantisation — matches exactly)
        rows = int(dist.shape[0])
        route = None

        def finalize(batch, _p=pending, _rows=rows):
            import jax.numpy as jnp

            from ..utils import metrics
            try:
                route_dev, _mx = _p.resolve()
            except Exception:
                # a warm-cache async dispatch died off-thread (device
                # lost mid-flight); the decode lane surfaces it — the
                # chunk has no route bytes to degrade onto anyway
                metrics.count("route.device.finalize_errors")
                raise
            batch.route_m = _device_route_full(route_dev, _rows, T)
            _p.write_back(out)
            if _wire_f16() and float(out["max_finite"][0]) <= WIRE_MAX_M:
                batch.dist_m = runtime.to_f16(out["dist_m"])
                batch.gc_m = runtime.to_f16(out["gc_m"])
                batch.route_m = batch.route_m.astype(jnp.float16)
    elif _wire_f16() and float(out["max_finite"][0]) <= WIRE_MAX_M:
        dist = runtime.to_f16(dist)
        route = runtime.to_f16(route)
        gc = runtime.to_f16(gc)
    return PaddedBatch(traces=_LazyTraceViews(B, build_views), dist_m=dist,
                       valid=out["edge_ids"] != PAD_EDGE, route_m=route,
                       gc_m=gc, case=out["case"], prep=out,
                       pt_off=pt_off, times_flat=times, finalize=finalize)


def _device_route_full(route_dev, rows: int, T: int):
    """Pad a deferred (B, T-1, K, K) device route tensor out to the
    native wire layout (rows, T, K, K): filler rows and the dead
    trailing time step carry the UNREACHABLE sentinel — the same bytes
    the native tail fill writes — so every decode shape and SKIP-row
    behavior is identical to the host-materialised path. Runs as an
    async device op; nothing here blocks."""
    import jax.numpy as jnp
    B = int(route_dev.shape[0])
    return jnp.pad(route_dev, ((0, rows - B), (0, 1), (0, 0), (0, 0)),
                   constant_values=np.float32(UNREACHABLE))


def _route_prune_margin(params: MatchParams) -> float:
    """Candidate pruning margin in meters (0 = pruning off), from
    REPORTER_TPU_ROUTE_PRUNE_SIGMA x the params' effective sigma. A
    malformed or negative value logs and disables pruning — a typo must
    degrade to the exact (unpruned) semantics, never to surprise drops."""
    spec = _os.environ.get(ENV_PRUNE, "").strip()
    if not spec:
        return 0.0
    try:
        mult = float(spec)
        if mult < 0:
            raise ValueError("must be >= 0")
    except ValueError as e:
        import logging
        logging.getLogger("reporter_tpu.matcher").warning(
            "%s=%r not understood (%s); candidate pruning stays off",
            ENV_PRUNE, spec, e)
        return 0.0
    return mult * float(params.effective_sigma)


def _prune_candidates(cands: CandidateSet, margin: float) -> CandidateSet:
    """Numpy mirror of the native prune block: per point, drop the
    distance-sorted suffix beyond ``dist[0] + margin``. The best
    candidate always survives; pad slots stay pad."""
    if margin <= 0 or cands.edge_ids.size == 0:
        return cands
    live = cands.edge_ids != PAD_EDGE
    cut = (cands.dist_m > cands.dist_m[:, :1] + np.float32(margin)) & live
    if not cut.any():
        return cands
    return CandidateSet(
        edge_ids=np.where(cut, PAD_EDGE, cands.edge_ids),
        dist_m=np.where(cut, PAD_DIST, cands.dist_m),
        offset_m=np.where(cut, np.float32(0.0), cands.offset_m),
        proj_x=cands.proj_x, proj_y=cands.proj_y)


def _wire_f16() -> bool:
    import logging
    import os
    val = os.environ.get("REPORTER_TPU_WIRE", "f16").strip().lower()
    if val not in ("f16", "f32"):
        logging.getLogger("reporter_tpu.matcher").warning(
            "REPORTER_TPU_WIRE=%r not recognised (use f16|f32); keeping f16",
            val)
        return True
    return val != "f32"


def _f16_safe(p: PreparedTrace) -> bool:
    """True when every finite distance in the trace fits the f16 wire
    undistorted (sentinel values >= UNREACHABLE_THRESHOLD travel as +inf;
    the native batched path decides from the C++-computed max_finite
    scalar instead of re-scanning)."""
    if p.gc_m.size and float(np.amax(p.gc_m)) > WIRE_MAX_M:
        return False
    for arr in (p.route_m, p.dist_m):
        if arr.size and float(np.amax(
                arr, initial=0.0,
                where=arr < UNREACHABLE_THRESHOLD)) > WIRE_MAX_M:
            return False
    return True


def _next_pow2(n: int) -> int:
    return 1 << max(n - 1, 0).bit_length()


def padded_batch_rows(B: int, pad: "int | None", pow2: bool = True) -> int:
    """Batch rows after mesh-multiple + pow2 padding — the ONE padding
    policy shared by pack_batches and the native dispatch (pow2 bounds
    the compiled-shape count per bucket; it never breaks mesh
    divisibility)."""
    rows = B
    if pad:
        rows = ((rows + pad - 1) // pad) * pad
    if pow2:
        p2 = _next_pow2(rows)
        if not pad or p2 % pad == 0:
            rows = p2
    return rows


def pack_batches(prepared: Sequence[PreparedTrace],
                 pad_batch_to: int | None = None,
                 max_batch: int | None = None,
                 pad_pow2: bool = False) -> List[PaddedBatch]:
    """Group prepared traces by bucket length and stack into batches.

    ``pad_batch_to`` optionally rounds the batch dimension up to a multiple
    (useful to keep the compiled-shape count low in a long-running service);
    filler rows are all-SKIP traces that decode to nothing. ``max_batch``
    splits a group into chunks of at most that many traces so host->device
    transfer, decode, and host post-processing of successive chunks can
    overlap (the dispatch pipeline in SegmentMatcher.match_many).
    ``pad_pow2`` additionally rounds the batch dimension up to a power of
    two (after the multiple), bounding the compiled-shape count per bucket
    to log2(max_batch) instead of max_batch — a micro-batching service
    sees every B from 1 to its flush cap over a long run, and each
    distinct B is otherwise a fresh XLA compile stall.

    By default the float tensors are built in the f16 wire format — the
    cast happens inside the copy the pack already performs, halving
    host->device bytes; the unreachable/pad sentinels overflow to +inf,
    which the device scoring treats identically (matcher/hmm.py). A batch
    containing any trace with finite distances beyond f16 range (extreme
    breakage_distance overrides) falls back to f32, as does setting
    REPORTER_TPU_WIRE=f32.
    """
    by_T: dict[int, List[PreparedTrace]] = {}
    for p in prepared:
        by_T.setdefault(p.T, []).append(p)

    # pad and dtype decisions are per T-bucket (one compiled (shape, dtype)
    # per bucket): only buckets actually split by max_batch pad their tail
    # up to the chunk size; small buckets keep their exact B (or the
    # caller's rounding); one out-of-range trace anywhere in a bucket puts
    # the whole bucket on the f32 wire rather than mixing dtypes mid-request
    f16 = _wire_f16()
    chunked: List[tuple] = []  # (T, group, pad, dtype)
    for T, group in sorted(by_T.items()):
        dtype = np.float16 if f16 and all(map(_f16_safe, group)) \
            else np.float32
        if max_batch and len(group) > max_batch:
            chunked.extend((T, group[i:i + max_batch], max_batch, dtype)
                           for i in range(0, len(group), max_batch))
        else:
            chunked.append((T, group, pad_batch_to, dtype))

    batches = []
    for T, group, pad, dtype in chunked:
        B = padded_batch_rows(len(group), pad, pow2=pad_pow2)
        K = group[0].edge_ids.shape[1]
        with np.errstate(over="ignore"):  # sentinels overflow f16 to +inf
            dist = np.full((B, T, K), PAD_DIST, dtype=dtype)
            valid = np.zeros((B, T, K), dtype=bool)
            route = np.full((B, max(T - 1, 0), K, K), UNREACHABLE,
                            dtype=dtype)
            gc = np.zeros((B, max(T - 1, 0)), dtype=dtype)
            case = np.full((B, T), SKIP, dtype=np.int32)
            for b, p in enumerate(group):
                dist[b] = p.dist_m
                valid[b] = p.edge_ids != PAD_EDGE
                route[b] = p.route_m
                gc[b] = p.gc_m
                case[b] = p.case
        batches.append(PaddedBatch(traces=group, dist_m=dist, valid=valid,
                                   route_m=route, gc_m=gc, case=case))
    return batches
