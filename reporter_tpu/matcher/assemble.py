"""Decoded candidate path -> OSMLR segment sequence (the match output).

Produces the ``segment_matcher`` schema the reference's clients consume
(reference: README.md "Reporter Output"; consumed by report() at
py/reporter_service.py:103-162):

  segments: [{segment_id?, way_ids, start_time, end_time, length,
              queue_length, internal, begin_shape_index, end_shape_index}]

Semantics preserved:
- ``start_time == -1``  — the path got onto the segment mid-segment
- ``end_time == -1``    — the path left the segment mid-segment
- ``length == -1``      — the segment was not completely traversed
- ``internal`` entries (turn channels etc.) carry no segment_id
- entry/exit times are interpolated along the route between the two probe
  points straddling the segment boundary.

This walk is pure host-side post-processing over the device's decoded
(T,) candidate indices; it runs per trace after the batched Viterbi.
"""
from __future__ import annotations

from typing import List, Optional

import numpy as np

from ..graph.network import RoadNetwork
from ..graph.route import UNREACHABLE
from ..graph.spatial import PAD_EDGE
from .hmm import RESTART

# how close (meters) an observation must be to a segment boundary to count
# as having been observed at the boundary itself
_BOUNDARY_EPS = 1.0

# queue_length extrapolates from the queue's observed back edge to the
# segment end (reference README.md:283 anchors the field at the end); a
# stall observed further than this from the end says nothing about the end
# of the segment, so no queue is reported
_QUEUE_END_PROXIMITY_M = 100.0


def _interp_time(pos: float, pos_a: float, pos_b: float,
                 time_a: float, time_b: float) -> float:
    if pos_b <= pos_a:
        return float(time_a)
    frac = (pos - pos_a) / (pos_b - pos_a)
    frac = min(max(frac, 0.0), 1.0)
    return float(time_a + frac * (time_b - time_a))


class _Run:
    """Consecutive decoded points on the same OSMLR segment (or the same
    non-associated stretch)."""

    __slots__ = ("segment_id", "internal", "first_idx", "last_idx",
                 "first_pos", "last_pos", "first_time", "last_time",
                 "first_cum", "last_cum", "edges",
                 "start_time", "end_time", "queue_start")

    def __init__(self, segment_id: Optional[int], internal: bool, idx: int,
                 pos: float, time: float, cum: float, edge: int):
        self.segment_id = segment_id
        self.internal = internal
        self.first_idx = self.last_idx = idx
        self.first_pos = self.last_pos = pos
        self.first_time = self.last_time = time
        self.first_cum = self.last_cum = cum
        self.edges = [edge]
        self.start_time: float = -1.0
        self.end_time: float = -1.0
        # segment position where the current trailing slow stretch began;
        # None while traffic is moving (reference: README.md:283 —
        # queue_length is the slow tail measured from the segment end)
        self.queue_start: Optional[float] = None

    def queue_length(self, seg_len: float) -> int:
        if self.segment_id is None or self.queue_start is None \
                or seg_len <= 0.0:
            return 0
        # only extrapolate to the segment end when the queue was actually
        # observed near it (last observation within the proximity bound)
        if seg_len - self.last_pos > _QUEUE_END_PROXIMITY_M:
            return 0
        return int(round(max(seg_len - self.queue_start, 0.0)))


def assemble_segments(net: RoadNetwork, prepared, path: np.ndarray,
                      mode: str = "auto",
                      queue_threshold_kph: float = 10.0,
                      interpolation_distance_m: float = 10.0,
                      backward_tolerance_m: float = 25.0,
                      turn_penalty_factor: float = 0.0) -> dict:
    """Build the match dict for one trace.

    ``prepared`` is a PreparedTrace (host tensors incl. times);
    ``path`` is the device-decoded (T,) candidate index per point.
    ``turn_penalty_factor`` must echo the matcher's: route_m prices
    heading changes INTO its distances for Viterbi ranking (Meili
    semantics), but cumulative route positions here must be geometric —
    the penalty is subtracted back out along the decoded path, else
    boundary interpolation and the traversal-consistency checks read
    penalty meters as road meters.
    """
    n = int(prepared.num_kept)
    if n == 0:
        return {"segments": [], "mode": mode}

    # one vectorised gather pass, then plain-scalar control flow: per-element
    # numpy indexing/int()/float() dominates this walk otherwise
    ks = np.asarray(path[:n], dtype=np.int64)
    rows = np.arange(n)
    edges = prepared.edge_ids[rows, ks].astype(np.int64)
    pad = edges == PAD_EDGE
    safe = np.where(pad, 0, edges)
    seg_ids = net.edge_segment_id[safe]
    seg_pos = net.edge_segment_offset_m[safe].astype(np.float64) + \
        prepared.offset_m[rows, ks]
    internal = net.edge_internal[safe]
    kept = np.asarray(prepared.kept_idx[:n], dtype=np.int64)
    times_kept = np.asarray(prepared.times)[kept]
    restarts = prepared.case[:n] == RESTART
    steps = prepared.route_m[np.arange(n - 1), ks[:-1], ks[1:]] if n > 1 \
        else np.zeros(0, dtype=np.float32)
    if turn_penalty_factor > 0 and n > 1:
        # strip the ranking-only turn penalty from the decoded steps
        # (reachable ones; same-edge transitions price no penalty and
        # their cos term is 1, so the correction is uniformly safe)
        heads = net.headings()
        cos_th = np.einsum("ij,ij->i", heads[safe[:-1]], heads[safe[1:]])
        penalty = turn_penalty_factor * 0.5 * (1.0 - cos_th)
        steps = np.where(steps < UNREACHABLE / 2,
                         np.maximum(steps - penalty, 0.0), steps)

    segments: List[dict] = []

    # a vehicle stalled at trace end emits points the jitter filter drops
    # (all within interpolation_distance of the last kept point), so the
    # kept-point speeds never see the stall; the dwell time of that raw
    # tail bounds its speed and marks the queue instead. batchpad computes
    # the dwell only for verifiably-jitter tails (0 for off-network or
    # bucket-truncated tails, which carry no stay-put guarantee). Mid-trace
    # stalls need no special case: dropped points stretch dt between kept
    # points.
    trailing_dwell_s = float(getattr(prepared, "trailing_jitter_dwell_s",
                                     0.0))

    # chains of kept points, split at RESTART boundaries, decoded-pad
    # points and unroutable decoded transitions; excluded points BETWEEN
    # runs are attributed to spans by the fix-up after the walk (dropped
    # points inside one run's span need nothing). The scan is a fixed set
    # of array ops: a chain is a maximal run of consecutive non-pad
    # points with no break flag, so boundaries fall out of one mask and
    # each chain is a contiguous slice of the gathered columns.
    nonpad_idx = np.flatnonzero(~pad)
    if nonpad_idx.size:
        break_before = np.ones(n, dtype=bool)
        if n > 1:
            break_before[1:] = (restarts[1:] | pad[:-1]
                                | (steps >= UNREACHABLE / 2))
        chain_pos = np.flatnonzero(break_before[nonpad_idx])
        chain_lo = nonpad_idx[chain_pos]
        chain_hi = np.r_[nonpad_idx[chain_pos[1:] - 1] + 1,
                         nonpad_idx[-1] + 1]
        # within-chain cumulative route position: sequential f64
        # accumulation (np.cumsum), matching the scalar walk bit-for-bit;
        # chains reset to 0 (only intra-chain differences are consumed)
        steps64 = np.asarray(steps, dtype=np.float64)
        last_chain = len(chain_lo) - 1
        # the trailing dwell belongs to the chain still open at trace end
        dwell_ok = int(nonpad_idx[-1]) == n - 1
        for k in range(len(chain_lo)):
            lo, hi = int(chain_lo[k]), int(chain_hi[k])
            cum = np.zeros(hi - lo, dtype=np.float64)
            if hi - lo > 1:
                np.cumsum(steps64[lo:hi - 1], out=cum[1:])
            final = k == last_chain and dwell_ok
            segments.extend(_chain_to_segments(
                net,
                (kept[lo:hi], edges[lo:hi], seg_ids[lo:hi],
                 seg_pos[lo:hi], times_kept[lo:hi], cum, internal[lo:hi]),
                queue_threshold_kph,
                trailing_dwell_s=trailing_dwell_s if final else 0.0,
                interpolation_distance_m=interpolation_distance_m,
                backward_tolerance_m=backward_tolerance_m))

    # attribute the jitter points the HMM excluded: gap points between
    # runs join the FOLLOWING run (keeping the preceding run's end at
    # its last kept probe — the shape_used trim anchor), and a
    # verifiably-jitter trailing tail joins the final run. Candidate-
    # less probes — off-network — stay unattributed wherever they occur:
    # leading ones, and any in a between-run gap together with the
    # jitter points BEFORE them (spans are contiguous and cannot
    # hole-punch). Without this fix-up, every dropped point between
    # runs reads as unmatched to consumers walking the spans.
    hc = getattr(prepared, "has_cands", None)
    for prev, cur in zip(segments, segments[1:]):
        lo = prev["end_shape_index"] + 1
        hi = cur["begin_shape_index"]
        start = lo
        if hc is not None:
            # candidate-less (off-network) gap points stay unattributed;
            # spans are contiguous, so attribution reaches back only to
            # just after the last off-network point in the gap
            for j in range(hi - 1, lo - 1, -1):
                if not hc[j]:
                    start = j + 1
                    break
        cur["begin_shape_index"] = start
    if segments and trailing_dwell_s > 0.0:
        segments[-1]["end_shape_index"] = int(prepared.num_raw) - 1

    return {"segments": segments, "mode": mode}


def _chain_to_segments(net: RoadNetwork, chain: tuple,
                       queue_threshold_kph: float = 10.0,
                       trailing_dwell_s: float = 0.0,
                       interpolation_distance_m: float = 10.0,
                       backward_tolerance_m: float = 25.0) -> List[dict]:
    """``chain``: column arrays (idx, edge, seg_id, seg_pos, time, cum,
    internal) for one contiguous chain of decoded points."""
    idxs, edges_a, sids_raw, poss, times_a, cums, internals = chain
    m = len(idxs)
    # a re-entry onto the same segment starts a new run — but apparent
    # backward movement within the matcher's backward tolerance is
    # along-track GPS noise (the same phenomenon route_distance prices as
    # staying put), not a loop back onto the segment; splitting on it
    # shatters one traversal into several partial runs and loses the
    # complete-traversal report
    reentry_tol = max(_BOUNDARY_EPS, backward_tolerance_m)
    # run boundaries in one vector pass: every negative segment id means
    # "unassociated", so they collapse to one sentinel before comparing
    sids = np.where(sids_raw < 0, np.int64(-1), sids_raw)
    new_run = np.ones(m, dtype=bool)
    if m > 1:
        new_run[1:] = ((sids[1:] != sids[:-1])
                       | (internals[1:] != internals[:-1])
                       | ((sids[1:] >= 0)
                          & (poss[1:] < poss[:-1] - reentry_tol)))
    run_lo = np.flatnonzero(new_run)
    run_hi = np.r_[run_lo[1:], m]
    runs: List[_Run] = []
    for a, b in zip(run_lo.tolist(), run_hi.tolist()):
        sid_v = int(sids[a])
        r = _Run(sid_v if sid_v >= 0 else None, bool(internals[a]),
                 int(idxs[a]), float(poss[a]), float(times_a[a]),
                 float(cums[a]), int(edges_a[a]))
        if b - a > 1:
            r.last_idx = int(idxs[b - 1])
            r.last_pos = float(poss[b - 1])
            r.last_time = float(times_a[b - 1])
            r.last_cum = float(cums[b - 1])
            e = edges_a[a:b]
            r.edges = e[np.r_[True, e[1:] != e[:-1]]].tolist()
            # queue detection: the trailing maximal streak of slow
            # intervals (dt > 0) anchors queue_start at the position
            # where the streak began; any fast interval resets it
            dts = times_a[a + 1:b] - times_a[a:b - 1]
            act = dts > 0.0
            with np.errstate(divide="ignore", invalid="ignore"):
                speed = (poss[a + 1:b] - poss[a:b - 1]) / dts * 3.6
            slow = act & (speed < queue_threshold_kph)
            fast = act & ~slow
            lf = np.flatnonzero(fast)
            start_j = int(lf[-1]) + 1 if lf.size else 0
            sl = np.flatnonzero(slow[start_j:])
            if sl.size:
                r.queue_start = float(poss[a + start_j + int(sl[0])])
        runs.append(r)

    # trailing raw-point dwell (see assemble_segments): the dropped tail
    # stayed within interpolation_distance for dwell seconds — if even the
    # upper-bound speed is below the queue threshold, the vehicle is queued
    # at its last decoded position
    if trailing_dwell_s > 0.0 and runs:
        last_run = runs[-1]
        # tail points sit anywhere in a disc of one interpolation distance
        # around the last kept point, so net displacement is bounded by the
        # disc's diameter (2r), not its radius
        bound_kph = 2.0 * interpolation_distance_m / trailing_dwell_s * 3.6
        if bound_kph < queue_threshold_kph and last_run.queue_start is None:
            last_run.queue_start = last_run.last_pos

    # interpolate boundary times between adjacent runs. The boundary
    # crossing must actually lie on the route between the two straddling
    # probes: a claimed exit (segment end) beyond the next probe's route
    # position, or a claimed entry (segment start) before the previous
    # probe's, means the route never traversed that part of the segment —
    # a one-point flicker onto a crossing way at an intersection would
    # otherwise read as a COMPLETE traversal of the whole crossing
    # segment (clamped interpolation hid the contradiction). The
    # reference's native matcher derives completeness from actual edge
    # traversal (starts/ends flags); this check is the time-domain
    # equivalent.
    for a, b in zip(runs[:-1], runs[1:]):
        # time as a function of cumulative route position between the two
        # probes straddling the boundary
        pos_a, pos_b = a.last_cum, b.first_cum
        ta, tb = a.last_time, b.first_time
        if a.segment_id is not None:
            seg_len = net.segment_length_m.get(a.segment_id, 0.0)
            exit_cum = a.last_cum + max(seg_len - a.last_pos, 0.0)
            if exit_cum <= pos_b + _BOUNDARY_EPS:
                a.end_time = _interp_time(exit_cum, pos_a, pos_b, ta, tb)
            # else: exit unobserved; end_time stays -1
        else:
            a.end_time = ta
        if b.segment_id is not None:
            entry_cum = b.first_cum - b.first_pos
            if entry_cum >= pos_a - _BOUNDARY_EPS:
                b.start_time = _interp_time(entry_cum, pos_a, pos_b, ta, tb)
            # else: entry unobserved; start_time stays -1
        else:
            b.start_time = tb

    # chain endpoints: partial entry/exit => -1 sentinels. The "at the
    # boundary" test tolerates THREE interpolation distances: a trace
    # that genuinely starts/ends at a segment node projects a few meters
    # inside it (candidate projection carries the GPS noise), the jitter
    # filter may have dropped the true final probe (anything within one
    # interpolation distance of the last kept point), and sampling stops
    # up to a probe interval before the physical route end — a 1 m eps
    # would mark nearly every genuine end-to-end traversal partial
    end_tol = max(_BOUNDARY_EPS, 3.0 * interpolation_distance_m)
    if runs:
        # a single-point run that is BOTH chain endpoints gets no grants:
        # one probe cannot witness a traversal, and with the widened
        # tolerance a short segment's lone re-fed straddling probe (the
        # shape_used overlap) would otherwise read as a second complete
        # traversal at every window boundary
        lone_point = (len(runs) == 1
                      and runs[0].first_idx == runs[0].last_idx)
        first = runs[0]
        if first.segment_id is not None and first.first_pos <= end_tol:
            if not lone_point:
                first.start_time = first.first_time
        elif first.segment_id is None:
            first.start_time = first.first_time
        # else stays -1 (got on mid-segment)
        last = runs[-1]
        if last.segment_id is not None:
            seg_len = net.segment_length_m.get(last.segment_id, 0.0)
            if last.last_pos >= seg_len - end_tol and not lone_point:
                last.end_time = last.last_time
            # else stays -1 (still on the segment when the trace ended)
        else:
            last.end_time = last.last_time

    out = []
    for r in runs:
        complete = r.segment_id is not None \
            and r.start_time != -1.0 and r.end_time != -1.0
        seg_len = net.segment_length_m.get(r.segment_id, -1.0) \
            if r.segment_id is not None else -1.0
        entry = {
            "way_ids": [int(e) for e in r.edges],
            "start_time": round(r.start_time, 3),
            "end_time": round(r.end_time, 3),
            "length": int(round(seg_len)) if complete else -1,
            "queue_length": r.queue_length(max(seg_len, 0.0)),
            "internal": r.internal,
            "begin_shape_index": int(r.first_idx),
            "end_shape_index": int(r.last_idx),
        }
        if r.segment_id is not None:
            entry["segment_id"] = int(r.segment_id)
        out.append(entry)
    return out
