"""The batched HMM map matcher: emission/transition scoring + Viterbi on device.

This replaces the reference's per-trace C++ Meili matcher
(reference: py/reporter_service.py:52,240 — ``valhalla.SegmentMatcher.Match``,
one trace per call, one C++ instance per service thread). Here the whole
batch decodes in one XLA program:

- emission score of candidate k at point t: log N(dist | 0, sigma_z)
  with constants dropped -> ``-0.5 * (d / sigma)^2``
- transition score between candidates (i, j) of consecutive points:
  ``-|route_dist - great_circle| / beta`` (exponential deviation model)
- Viterbi decode as a ``lax.scan`` over time, ``vmap`` over the batch.

Everything is fixed-shape: traces padded to T points, K candidates. Control
flow that depends on data (probe gaps > breakage_distance, points with no
candidates, padding) is encoded host-side as a per-point ``case`` tensor:

  NORMAL  — standard Viterbi step
  RESTART — chain restarts here (first kept point, or after a breakage
            split; reference knob ``breakage_distance``, Dockerfile:14-17)
  SKIP    — padding tail; state passes through untouched

(points with no candidates, and jitter points under the interpolation
distance, are filtered out host-side before tensors are built — see
``batchpad.prepare_trace``)

so the scan body is branch-free ``jnp.where`` selects — XLA-friendly, no
data-dependent Python control flow.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

# plain float, not a jnp scalar: a module-level jnp constant would
# initialise the XLA backend at import time, which breaks
# jax.distributed.initialize (parallel/multihost.py) — it must run first
NEG_INF = -1.0e30
NORMAL, RESTART, SKIP = 0, 1, 2
# route distances at/above this threshold are "no route found within bound"
UNREACHABLE_THRESHOLD = 0.5e9
# largest finite distance the f16 wire format ships (sentinels above
# UNREACHABLE_THRESHOLD travel as +inf). Bounded at 4096 m so the f16 ulp
# stays <= 2 m (<= 1 m rounding) — noise well under the metre-scale
# deviations the transition scores discriminate on; consecutive-probe
# route/great-circle distances are typically tens of metres. Batches with
# finite distances beyond this ship f32 instead (pack_batches fallback).
WIRE_MAX_M = 4.096e3


def emission_scores(dist_m: jnp.ndarray, valid: jnp.ndarray,
                    case: jnp.ndarray, sigma: jnp.ndarray) -> jnp.ndarray:
    """(T, K) emission log-scores.

    ``dist_m`` point->edge distances, ``valid`` candidate mask, ``case``
    per-point case codes, ``sigma`` scalar effective sigma_z.
    SKIP rows become all-zero so they never poison the running scores.
    """
    # scoring always runs in f32: callers may ship the wire tensors as f16
    # to halve host->device transfer (ops.decode_batch)
    dist_m = dist_m.astype(jnp.float32)
    z = dist_m / sigma
    scores = jnp.where(valid, -0.5 * z * z, NEG_INF)
    return jnp.where((case == SKIP)[:, None], 0.0, scores)


def transition_scores(route_m: jnp.ndarray, gc_m: jnp.ndarray,
                      case_to: jnp.ndarray, beta: jnp.ndarray) -> jnp.ndarray:
    """(T-1, K, K) transition log-scores for steps into points 1..T-1.

    Steps into a SKIP point use the identity matrix (0 on the diagonal,
    -inf off it) so the chain state is carried through unchanged. Steps into
    a RESTART point are zeroed (the scan ignores them). Unreachable route
    distances become -inf.
    """
    K = route_m.shape[-1]
    # f16 wire tensors (ops.decode_batch) carry unreachable as +inf, which
    # upcasts cleanly and still fails the reachability test below
    route_m = route_m.astype(jnp.float32)
    gc_m = gc_m.astype(jnp.float32)
    dev = jnp.abs(route_m - gc_m[:, None, None])
    scores = jnp.where(route_m < UNREACHABLE_THRESHOLD, -dev / beta, NEG_INF)
    # both branches must carry an explicit dtype: with two weak Python
    # scalars no array operand pins the result, so under jax_enable_x64
    # this would silently widen to f64 (lint TC003)
    identity = jnp.where(jnp.eye(K, dtype=bool),
                         jnp.float32(0.0), jnp.float32(NEG_INF))
    scores = jnp.where((case_to == SKIP)[:, None, None], identity[None], scores)
    return jnp.where((case_to == RESTART)[:, None, None], 0.0, scores)


def _viterbi_single(em: jnp.ndarray, tr: jnp.ndarray, case: jnp.ndarray):
    """Viterbi forward + backtrace for one trace.

    em: (T, K) emission scores; tr: (T-1, K, K) transition scores;
    case: (T,) case codes. Returns (path (T,) i32, final score f32).
    """
    T, K = em.shape

    def forward(prev_scores, inp):
        em_t, tr_t, case_t = inp
        cand = prev_scores[:, None] + tr_t           # (K_prev, K_cur)
        best = jnp.max(cand, axis=0)
        bp = jnp.argmax(cand, axis=0).astype(jnp.int32)
        stepped = best + em_t
        # a restart carries the finished chain's best score as a constant
        # offset (argmax-invariant) so the final score is the total over
        # all chains — and matches the associative formulation exactly
        restarted = jnp.max(prev_scores) + em_t
        new_scores = jnp.where(case_t == RESTART, restarted, stepped)
        # argmax of the chain state *before* this step, for restart backtrace
        prev_best = jnp.argmax(prev_scores).astype(jnp.int32)
        return new_scores, (bp, prev_best)

    init = em[0]
    final_scores, (bps, prev_bests) = jax.lax.scan(
        forward, init, (em[1:], tr, case[1:]))

    last = jnp.argmax(final_scores).astype(jnp.int32)

    def backward(cur, inp):
        bp_t, prev_best_t, case_t = inp
        prev = jnp.where(case_t == RESTART, prev_best_t, bp_t[cur])
        return prev, cur

    first, rest = jax.lax.scan(
        backward, last, (bps, prev_bests, case[1:]), reverse=True)
    path = jnp.concatenate([first[None], rest])
    return path, jnp.max(final_scores)


def trim_time_pad(dist_m, route_m, gc_m):
    """Accept route/gc shipped with T time rows (a dead trailing step —
    the native batched prep pads so the dominant tensor shards along the
    seq mesh axis with zero host copies) or the classic T-1 rows; return
    (T-1)-row views. Shape-static, so free under jit."""
    Tm1 = dist_m.shape[-2] - 1
    if route_m.shape[-3] == Tm1 + 1:
        route_m = route_m[..., :Tm1, :, :]
        gc_m = gc_m[..., :Tm1]
    return route_m, gc_m


@functools.partial(jax.jit, static_argnames=())
def viterbi_decode_batch(dist_m: jnp.ndarray, valid: jnp.ndarray,
                         route_m: jnp.ndarray, gc_m: jnp.ndarray,
                         case: jnp.ndarray, sigma: jnp.ndarray,
                         beta: jnp.ndarray):
    """Decode a padded batch of traces.

    Shapes: dist_m (B,T,K) f32; valid (B,T,K) bool; route_m (B,T-1,K,K)
    f32 (or (B,T,K,K) with a dead last step — see trim_time_pad);
    gc_m (B,T-1) f32 (or (B,T)); case (B,T) i32; sigma, beta scalars.
    Returns (paths (B,T) i32 candidate indices, scores (B,) f32).
    """
    route_m, gc_m = trim_time_pad(dist_m, route_m, gc_m)

    def one(d, v, r, g, c):
        em = emission_scores(d, v, c, sigma)
        tr = transition_scores(r, g, c[1:], beta)
        return _viterbi_single(em, tr, c)

    return jax.vmap(one)(dist_m, valid, route_m, gc_m, case)
