from .params import MatchParams
from .hmm import viterbi_decode_batch, NORMAL, RESTART, SKIP, NEG_INF  # noqa: F401
from .assemble import assemble_segments
from .matcher import SegmentMatcher, Configure, pipeline_enabled

__all__ = [
    "MatchParams",
    "viterbi_decode_batch", "NORMAL", "RESTART", "SKIP", "NEG_INF",
    "assemble_segments",
    "SegmentMatcher", "Configure", "pipeline_enabled",
]
