"""Matcher tuning knobs, named after the reference's configuration keys.

Defaults mirror the reference deployment (reference: Dockerfile:14-17,
py/generate_test_trace.py:45-52): sigma_z 4.07, beta 3,
max-route-distance-factor 5, search_radius 50 m, breakage_distance 2000 m.
"""
from __future__ import annotations

from dataclasses import dataclass, replace


@dataclass(frozen=True)
class MatchParams:
    mode: str = "auto"
    sigma_z: float = 4.07              # emission Gaussian std, meters
    beta: float = 3.0                  # transition exponential scale
    max_route_distance_factor: float = 5.0
    max_route_time_factor: float = 2.0
    # floor on the time-admissibility cap max(floor, factor*dt), the time
    # analog of the 500 m floor on the distance bound: at 1 Hz sampling
    # factor*dt is ~2 s, which GPS projection noise alone overruns, so an
    # unfloored bound prunes honest transitions instead of absurd detours.
    # The floor is sized to NOISE-scale jumps, not the full distance
    # bound: a projection hop of ~100 m at a slow-but-moving 25 km/h
    # takes ~15 s, so 15 s keeps every honest noise-induced route while
    # pruning teleports (e.g. 250 m of 30 km/h road "travelled" between
    # 1 Hz probes). The previous 60 s floor — sized to the 500 m distance
    # floor at 30 km/h — made the bound nearly inert at defaults: it only
    # ever pruned sub-30 km/h crawls sustained for a full minute.
    # Observable in tests/test_knobs.py::test_time_floor_prunes_teleport.
    min_time_bound_s: float = 15.0
    breakage_distance: float = 2000.0  # meters; larger probe gaps split the HMM
    search_radius: float = 50.0        # meters candidate search radius
    turn_penalty_factor: float = 0.0
    gps_accuracy: float = 0.0          # >0 widens sigma to at least accuracy/1.96
    max_candidates: int = 8            # K, fixed width of candidate tensors
    # points closer than this to the last kept point are excluded from the
    # HMM and interpolated onto the decoded path afterwards — Meili's cure
    # for GPS jitter flipping the matched direction of travel
    interpolation_distance: float = 10.0
    # apparent backward movement along the same directed edge up to this
    # many meters is priced as staying put rather than as a loop around the
    # block; suppresses one-point flickers onto the co-located reverse edge
    # (see graph/route.py route_distance)
    backward_tolerance_m: float = 25.0
    # observed speeds below this mark queued traffic: queue_length is the
    # distance from the segment end occupied by the slow tail (reference:
    # README.md:283 defines the field; the C++ matcher's threshold constant
    # is not published, so it is a knob here)
    queue_speed_threshold_kph: float = 10.0

    def with_options(self, options: dict) -> "MatchParams":
        """Apply per-request ``match_options`` overrides by reference name
        (reference: generate_test_trace.py:45-52).

        Returns ``self`` when every override already equals the current
        value — the common case (e.g. mode=auto on every request), and
        what lets match_many group such traces into one prep/decode batch
        without building 512 identical frozen dataclasses per call."""
        fields = {}
        for key in ("mode", "sigma_z", "beta", "breakage_distance",
                    "search_radius", "turn_penalty_factor", "gps_accuracy",
                    "max_route_distance_factor", "max_route_time_factor"):
            if key in options and options[key] != getattr(self, key):
                fields[key] = options[key]
        return replace(self, **fields) if fields else self

    @property
    def effective_sigma(self) -> float:
        if self.gps_accuracy and self.gps_accuracy > 0:
            return max(self.sigma_z, self.gps_accuracy / 1.96)
        return self.sigma_z
