"""SegmentMatcher: the framework's matcher facade.

API-compatible with the surface the reference uses from the ``valhalla``
extension module (reference: py/reporter_service.py:21,52,240 and
py/simple_reporter.py:132-133):

    Configure(config_path_or_dict)
    m = SegmentMatcher()
    match_json = m.Match(trace_json_str)

plus the batched entry point the reference lacks — ``match_many`` — which is
the TPU hot path: many traces prepared on host, decoded in one vmapped
Viterbi per padding bucket.
"""
from __future__ import annotations

import json
import logging
import os
from concurrent.futures import ThreadPoolExecutor
from typing import List, Optional, Sequence

import numpy as np

from ..core.tracebatch import TraceBatch, as_trace_batch
from ..graph.network import RoadNetwork
from ..graph.route import RouteCache
from ..graph.spatial import SpatialGrid
from ..obs import profiler
from ..obs import trace as obs_trace
from ..utils import faults, metrics
from ..utils import locks as _locks
from ..utils.circuit import CircuitBreaker
from .assemble import assemble_segments
from .batchpad import (bucket_ladder, kept_point_count, pack_batches,
                       padded_batch_rows, prepare_batch, prepare_trace,
                       prepare_traces_numpy)
from .params import MatchParams

# process-wide configuration, mirroring valhalla.Configure's module-level
# behavior (reference: reporter_service.py:284)
_global_config: dict = {}

logger = logging.getLogger("reporter_tpu.matcher")


def _route_cache_counters() -> dict:
    """Numpy route-cache hit snapshot for a chunk's wide event (the
    fallback-path twin of the native route-pair memo stats)."""
    c = metrics.default.counter
    return {"pair_hits": c("route.cache.pair_hits"),
            "pair_misses": c("route.cache.pair_misses"),
            "node_hits": c("route.cache.node_hits"),
            "node_misses": c("route.cache.node_misses")}


def _circuit_knobs() -> tuple:
    """(threshold, cooldown_s) for the native-prep circuit breaker."""
    from ..utils.runtime import _env_float, _env_int
    return (_env_int("REPORTER_TPU_CIRCUIT_THRESHOLD", 5),
            _env_float("REPORTER_TPU_CIRCUIT_COOLDOWN_S", 30.0))


def _native_disabled() -> bool:
    """REPORTER_TPU_NATIVE=off|0|false|numpy is the matcher.circuit
    kill switch: force the numpy prep fallback even when the C++ host
    runtime is importable (incident lever; default auto-detect)."""
    return os.environ.get("REPORTER_TPU_NATIVE", "").strip().lower() \
        in ("0", "off", "false", "numpy")


def _route_device_enabled() -> bool:
    """REPORTER_TPU_ROUTE_DEVICE opts the device route kernel in (off by
    default: the host path is the battle-tested oracle, and the kernel
    only pays off where a real accelerator backs jax)."""
    return os.environ.get("REPORTER_TPU_ROUTE_DEVICE", "").strip().lower() \
        in ("1", "on", "true", "yes")


def _decode_chunk() -> int:
    """Traces per decode dispatch. REPORTER_TPU_DECODE_CHUNK forces it;
    the default follows the pipeline mode: 128 when the device lanes
    are on AND there is more than one core to overlap across (chunks
    ARE the overlap granularity), 512 otherwise — chunking buys nothing
    without real overlap, so fewer dispatches win (+17% measured on one
    core at 512 vs 128) until per-chunk tensors (route_m: 16 MB f32 at
    512) outgrow cache and memory bandwidth takes it back (1024-row
    chunks measured ~10% SLOWER than 512). The default then scales by
    the decode mesh's data-axis width: a chunk is split across all M
    devices, so per-DEVICE rows (and therefore per-device utilisation)
    only hold steady if the chunk grows with the mesh."""
    from ..utils.runtime import _env_int
    val = _env_int("REPORTER_TPU_DECODE_CHUNK", 0)
    if val:
        return max(1, val)
    if pipeline_enabled() and (os.cpu_count() or 1) > 1:
        base = 128
    else:
        base = 512
    from ..ops import decode_mesh_size
    return base * max(1, decode_mesh_size())


def match_batch_default() -> int:
    """Default dispatcher flush cap (service MATCH_BATCH_MAX unset): at
    least TWO decode chunks per drained batch, so the dispatch lane
    keeps >=2 chunks in flight per device while the drain lane works —
    a chunk spans the whole data mesh, so 2x the chunk is 2 chunks per
    device. PR 8's queue-depth wide events are the sensor proving the
    devices stay fed under this depth. Unsharded hosts keep the
    shipped 256: the scaling rationale is mesh utilisation, and
    quadrupling the flush cap on a single lone-CPU device would only
    grow tail latency and peak memory."""
    from ..ops import decode_mesh_size
    if decode_mesh_size() <= 1:
        return 256
    return max(256, 2 * _decode_chunk())


def _prep_workers() -> int:
    """Host-prep thread count (env-tunable; 0 disables the pool)."""
    from ..utils.runtime import _env_int
    return _env_int("REPORTER_TPU_PREP_THREADS",
                    min(32, os.cpu_count() or 1))


#: pressure-ladder last rung (service/admission.py "oracle_decode"):
#: decode serves via the per-trace numpy oracle — the same degraded
#: path the decode circuit breaker uses — keeping the device queue
#: free for the drain backlog. One global load on the hot path.
_pressure_oracle = False


def set_pressure_oracle(on: bool) -> None:
    global _pressure_oracle
    _pressure_oracle = bool(on)


def pipeline_enabled() -> bool:
    """Overlap the device lanes (decode dispatch; d2h wait + assembly)
    with host prep of later chunks. REPORTER_TPU_PIPELINE forces on/off;
    the default is platform-aware: ON wherever there is device or IO
    time to hide (any accelerator, or a multi-core CPU host where the
    GIL-releasing native assembly genuinely parallelises), OFF on a
    single-core CPU-only host, where every stage contends for the same
    core and the thread hops are a measured ~5-12% end-to-end loss.
    Results are identical either way (pinned by TestDevicePipeline)."""
    val = os.environ.get("REPORTER_TPU_PIPELINE", "").strip().lower()
    if val:
        return val not in ("0", "off", "false")
    # cpu-count short-circuits first: jax.default_backend() initialises
    # the backend as a side effect, which on TPU attaches the
    # single-client chip — a multi-core host must not pay that just to
    # read this flag
    if (os.cpu_count() or 1) > 1:
        return True
    import jax
    return jax.default_backend() != "cpu"


class RunColumns:
    """One decoded chunk's run columns as Python lists — ONE bulk
    ``.tolist()`` per column (the approved conversion idiom), shared by
    every :class:`MatchRuns` view of the chunk. This replaced the old
    per-trace ``_runs_as_lists`` slice-and-convert, which paid ~4k tiny
    tolist calls per 512-trace chunk."""

    __slots__ = ("seg_id", "internal", "start", "end", "length", "queue",
                 "begin_idx", "end_idx", "way_off", "ways", "arrays")

    def __init__(self, runs: dict):
        self.seg_id = runs["seg_id"].tolist()
        self.internal = runs["internal"].astype(bool).tolist()
        # round HERE, whole column at once (reporter-lint HP002 sweep:
        # the dict-era formatter called round() twice per run)
        start_r = np.round(runs["start"], 3)
        end_r = np.round(runs["end"], 3)
        self.start = start_r.tolist()
        self.end = end_r.tolist()
        self.length = runs["length"].tolist()
        self.queue = runs["queue"].tolist()
        self.begin_idx = runs["begin_idx"].tolist()
        self.end_idx = runs["end_idx"].tolist()
        self.way_off = runs["way_off"].tolist()
        self.ways = runs["ways"].tolist()
        # the same columns as numpy arrays (start/end already rounded),
        # in the native wire writer's column order — rt_report_json /
        # rt_render_segments_json serialise straight from these buffers
        # (service/wire.py); hand-built RunColumns-shaped test doubles
        # without this attribute take the Python writer path
        self.arrays = {
            "seg_id": runs["seg_id"], "internal": runs["internal"],
            "start": start_r, "end": end_r, "length": runs["length"],
            "queue": runs["queue"], "begin_idx": runs["begin_idx"],
            "end_idx": runs["end_idx"], "way_off": runs["way_off"],
            "ways": runs["ways"]}


def _jnum(x) -> str:
    """One JSON scalar, byte-identical to ``json.dumps(x)``: floats via
    ``float.__repr__`` (with the Infinity/NaN spellings), bools/None as
    their JSON literals, ints via ``str``."""
    if x is True:
        return "true"
    if x is False:
        return "false"
    if x is None:
        return "null"
    if isinstance(x, float):
        if x != x:
            return "NaN"
        if x == float("inf"):
            return "Infinity"
        if x == float("-inf"):
            return "-Infinity"
        return repr(x)
    return str(x)


def render_segments_json(cols: RunColumns, lo: int, hi: int,
                         mode: str) -> str:
    """Serialise run columns [lo, hi) to the reference-schema
    ``{"segments":[...],"mode":...}`` JSON — a thin dispatcher over the
    wire backend knob (``REPORTER_TPU_WIRE_NATIVE``): the C-level
    writer (native/src/host_runtime.cpp rt_render_segments_json) when
    armed and the columns carry their arrays, else the Python columnar
    writer below. Both are byte-identical to ``json.dumps`` over the
    per-run dicts the old ``_format_runs`` materialised (pinned by
    tests/test_report_writer.py)."""
    arrays = getattr(cols, "arrays", None)
    if arrays is not None:
        from ..service import wire
        out = wire.maybe_native_segments(arrays, lo, hi, mode)
        if out is not None:
            return bytes(out).decode("utf-8")
    return render_segments_json_py(cols, lo, hi, mode)


def render_segments_json_py(cols: RunColumns, lo: int, hi: int,
                            mode: str) -> str:
    """The Python columnar segments writer — the wire dispatcher's
    fallback backend, and the oracle the native writer is pinned
    against. Emits bytes from the columns and never builds a per-run
    dict. Start/end times are always finite floats here (rounded probe
    epochs / -1.0 sentinels), so they format through bare ``repr`` —
    identical bytes to json.dumps's ``float.__repr__`` path, without
    the per-value type dispatch."""
    way_off, ways = cols.way_off, cols.ways
    start, end, length = cols.start, cols.end, cols.length
    queue, internal = cols.queue, cols.internal
    begin_idx, end_idx, seg_id = cols.begin_idx, cols.end_idx, cols.seg_id
    parts = []
    for r in range(lo, hi):
        w = ",".join(map(str, ways[way_off[r]:way_off[r + 1]]))
        sid = seg_id[r]
        parts.append(
            f'{{"way_ids":[{w}],'
            f'"start_time":{start[r]!r},'
            f'"end_time":{end[r]!r},'
            f'"length":{length[r]},'
            f'"queue_length":{queue[r]},'
            f'"internal":{"true" if internal[r] else "false"},'
            f'"begin_shape_index":{begin_idx[r]},'
            f'"end_shape_index":{end_idx[r]}'
            + (f',"segment_id":{sid}}}' if sid >= 0 else "}"))
    mode_json = '"auto"' if mode == "auto" else json.dumps(mode)
    return ('{"segments":[' + ",".join(parts) + '],"mode":'
            + mode_json + "}")


class MatchRuns:
    """One trace's match result as a lazy view over its chunk's shared
    :class:`RunColumns`.

    Dict-shaped consumers (tests, the numpy-fallback comparisons, the
    worker's structured report path) see the reference-schema match dict
    through the mapping protocol below — the per-run dicts materialise
    on first structural access, via one comprehension. The hot serving
    path (``Match()`` and service ``report_json``) serialises straight
    from the columns and never triggers it. Deliberately NOT a dict
    subclass: ``json.dumps`` on a lazy dict subclass would silently
    encode the un-materialised storage; here it fails loudly instead
    (use the writers)."""

    __slots__ = ("cols", "lo", "hi", "mode", "_dict")

    def __init__(self, cols: RunColumns, lo: int, hi: int, mode: str):
        self.cols = cols
        self.lo = lo
        self.hi = hi
        self.mode = mode
        self._dict = None

    def _materialise(self) -> dict:
        d = self._dict
        if d is None:
            c, lo, hi = self.cols, self.lo, self.hi
            wo, ways = c.way_off, c.ways
            segments = [
                {"way_ids": ways[wo[r]:wo[r + 1]],
                 "start_time": c.start[r],
                 "end_time": c.end[r],
                 "length": c.length[r],
                 "queue_length": c.queue[r],
                 "internal": c.internal[r],
                 "begin_shape_index": c.begin_idx[r],
                 "end_shape_index": c.end_idx[r],
                 **({"segment_id": c.seg_id[r]}
                    if c.seg_id[r] >= 0 else {})}
                for r in range(lo, hi)]
            d = self._dict = {"segments": segments, "mode": self.mode}
        return d

    def has_runs(self) -> bool:
        """True when the match produced any segment run — an emptiness
        probe that never materialises the per-run dicts (the streaming
        batcher's trim logic only needs this bit)."""
        return self.hi > self.lo

    # -- mapping protocol (materialises) -----------------------------------
    def __getitem__(self, key):
        return self._materialise()[key]

    def __setitem__(self, key, value):
        if key == "mode":
            # report() stamps mode without needing the segment dicts
            self.mode = value
            if self._dict is not None:
                self._dict["mode"] = value
            return
        self._materialise()[key] = value

    def get(self, key, default=None):
        return self._materialise().get(key, default)

    def __contains__(self, key):
        return key in self._materialise()

    def __iter__(self):
        return iter(self._materialise())

    def __len__(self):
        return len(self._materialise())

    def keys(self):
        return self._materialise().keys()

    def values(self):
        return self._materialise().values()

    def items(self):
        return self._materialise().items()

    def __eq__(self, other):
        if isinstance(other, MatchRuns):
            other = other._materialise()
        if isinstance(other, dict):
            return self._materialise() == other
        return NotImplemented

    __hash__ = None  # mutable mapping semantics, like dict

    def __bool__(self):
        return True  # a match result is always a non-empty mapping

    def __repr__(self):
        return repr(self._materialise())


def Configure(conf) -> None:
    """Load matcher configuration from a JSON file path or a dict.

    Recognised keys (all optional): ``graph`` (path to a RoadNetwork .npz),
    and any MatchParams field under ``matcher`` (sigma_z, beta, ...).
    """
    global _global_config
    if isinstance(conf, str):
        with open(conf) as f:
            _global_config = json.load(f)
    else:
        _global_config = dict(conf)


class SegmentMatcher:
    """Batched HMM matcher bound to one road network.

    One instance serves the whole process (the reference instead creates
    one C++ matcher per service thread, reporter_service.py:51-58). The
    service serialises device work through its BatchDispatcher thread;
    direct concurrent Match() calls are safe under CPython's GIL (the
    shared RouteCache may redundantly recompute but never corrupts).
    """

    def __init__(self, net: Optional[RoadNetwork] = None,
                 params: Optional[MatchParams] = None,
                 # ~1.5x the default 50 m search radius: reach stays 1 (a
                 # 3x3 cell scan) while each cell holds few edges — 2.5x
                 # faster candidate lookup than the old 250 m cells, with
                 # identical results (the grid is a pure index)
                 grid_cell_m: float = 75.0,
                 use_native: Optional[bool] = None):
        if net is None:
            graph_path = _global_config.get("graph")
            if graph_path is None:
                raise ValueError(
                    "no network: pass net= or Configure({'graph': path})")
            net = RoadNetwork.load(graph_path)
        self.net = net
        if params is None:
            params = MatchParams(**_global_config.get("matcher", {}))
        self.params = params
        self._grid_cell_m = grid_cell_m
        # the numpy structures are only built if the fallback path is used
        # (the native runtime owns its own grid and cache). Lazy-built
        # under a lock: with the circuit breaker, concurrent native-path
        # callers can reach the fallback simultaneously, and a bare
        # check-then-set would race duplicate SpatialGrid/RouteCache
        # builds (losing one copy's cache warmth exactly when degraded)
        self._grid: Optional[SpatialGrid] = None
        self._route_cache: Optional[RouteCache] = None
        self._fallback_lock = _locks.new_lock("matcher.fallback")
        # C++ host runtime when available (and not explicitly disabled);
        # numpy fallback otherwise — identical contract. The
        # REPORTER_TPU_NATIVE knob is the matcher.circuit kill switch:
        # "off" forces the numpy prep leg without rebuilding the server
        # (explicit use_native=True still wins — tests ask by hand).
        self.runtime = None
        if use_native is None and _native_disabled():
            use_native = False
        if use_native is not False:
            from .. import native
            if native.available():
                self.runtime = native.NativeRuntime(net, cell_m=grid_cell_m)
            elif use_native:
                raise RuntimeError("native host runtime requested but "
                                   "unavailable")
        # failure domains, one breaker per hot-path stage (shared
        # threshold/cooldown knobs):
        #   circuit           native prep -> numpy prep fallback
        #   circuit_decode    device decode -> per-trace numpy oracle
        #                     (cpu_ref.viterbi_decode_numpy)
        #   circuit_assemble  native batched assembly -> per-trace scalar
        #                     assembly with poisoned-trace quarantine
        #   circuit_route     device route kernel -> native re-prep with
        #                     host routes (batchpad.prepare_batch)
        #   circuit_incremental  carried-state incremental decode ->
        #                     whole-window batch re-decode (match_many)
        # Fallback outputs are pinned byte-identical (tests/
        # test_report_writer.py, TestDecodeDomain); a half-open probe
        # after the cooldown feels out recovery. The breakers exist even
        # without a runtime/device (they just never trip) so /health can
        # always report every domain's state.
        threshold, cooldown = _circuit_knobs()
        self.circuit = CircuitBreaker("matcher.circuit",
                                      threshold=threshold,
                                      cooldown_s=cooldown)
        self.circuit_decode = CircuitBreaker("matcher.circuit.decode",
                                             threshold=threshold,
                                             cooldown_s=cooldown)
        # assemble's breaker guards quarantine/shedding of poisoned
        # traces inside ONE implementation — there is no dual path to
        # pair, so no FALLBACK_PAIRS entry
        self.circuit_assemble = CircuitBreaker("matcher.circuit.assemble",  # lint: ignore[FB001]
                                               threshold=threshold,
                                               cooldown_s=cooldown)
        self.circuit_route = CircuitBreaker("matcher.circuit.route",
                                            threshold=threshold,
                                            cooldown_s=cooldown)
        self.circuit_incremental = CircuitBreaker(
            "matcher.circuit.incremental",
            threshold=threshold, cooldown_s=cooldown)
        # carried per-trace decode state for the incremental path
        # (matcher/incremental.py); built lazily — batch-only callers
        # never pay for the table
        self._incremental_table = None
        # device route kernel (REPORTER_TPU_ROUTE_DEVICE): built lazily
        # on the first native dispatch — jax import + column upload are
        # not a cost the numpy-only paths should pay. False = build
        # failed / disabled, None = not attempted yet.
        self._route_kernel = None
        self._route_kernel_tried = False
        # where a poisoned trace's request JSON lands when assembly
        # quarantines it (None -> the worker-registered trace spool via
        # utils.spool, else log-and-drop)
        self.quarantine_spool: Optional[str] = None
        # two single-worker device lanes, each FIFO: the dispatch lane
        # runs decode dispatch + async d2h so the device queue stays fed,
        # the drain lane runs the d2h wait + assembly — so chunk N's
        # decode overlaps both host prep of chunk N+1 (main thread) and
        # assembly of chunk N-1 (drain lane). Constructed here (worker
        # threads only spawn on first submit; GC of the matcher releases
        # them) so concurrent first calls can't race a lazy check-then-set
        # into duplicate lanes.
        self._dispatch_pool = ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="device-dispatch")
        self._drain_pool = ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="device-drain")
        # build the process-global decode mesh NOW (not on the first
        # request): device enumeration + the sharded jit wrappers are
        # one-time costs that belong at init, and a mis-sliced
        # REPORTER_TPU_DEVICE_SLICE should fail loudly here. None when
        # sharding is off or only one local device is visible
        # (REPORTER_TPU_DECODE_SHARD, default auto).
        from ..parallel import mesh as _pmesh
        self.decode_mesh = _pmesh.decode_mesh()

    @property
    def grid(self) -> SpatialGrid:
        if self._grid is None:
            with self._fallback_lock:
                if self._grid is None:
                    self._grid = SpatialGrid(self.net,
                                             cell_m=self._grid_cell_m)
        return self._grid

    @property
    def route_cache(self) -> RouteCache:
        if self._route_cache is None:
            with self._fallback_lock:
                if self._route_cache is None:
                    self._route_cache = RouteCache(self.net)
        return self._route_cache

    @property
    def incremental_table(self):
        """The carried per-trace decode state table (built on first use)."""
        if self._incremental_table is None:
            with self._fallback_lock:
                if self._incremental_table is None:
                    from .incremental import IncrementalTable
                    self._incremental_table = IncrementalTable(self)
        return self._incremental_table

    # -- failure-domain surface --------------------------------------------
    #: domain name -> breaker attribute; the /health "degraded" block,
    #: the worker heartbeat and the chaos assertions all read this map
    CIRCUIT_DOMAINS = (("native.prep", "circuit"),
                       ("decode.dispatch", "circuit_decode"),
                       ("matcher.assemble", "circuit_assemble"),
                       ("route.device", "circuit_route"),
                       ("match.incremental", "circuit_incremental"))

    def _device_route_kernel(self):
        """The lazily-built device route kernel, or None when disabled,
        unavailable, or its one-time build failed (logged once; the host
        route path then serves every chunk)."""
        if not self._route_kernel_tried:
            self._route_kernel_tried = True
            if _route_device_enabled() and self.runtime is not None:
                try:
                    from ..graph.route_device import DeviceRouteKernel
                    self._route_kernel = DeviceRouteKernel(self.net)
                except Exception as e:
                    metrics.count("route.device.build_errors")
                    logger.warning(
                        "REPORTER_TPU_ROUTE_DEVICE is set but the device "
                        "route kernel failed to build (%s); host routes "
                        "serve every chunk", e)
                    self._route_kernel = None
        return self._route_kernel

    def circuit_snapshots(self) -> dict:
        """{domain: breaker snapshot} for every guarded hot-path stage."""
        return {domain: getattr(self, attr).snapshot()
                for domain, attr in self.CIRCUIT_DOMAINS}

    def open_domains(self) -> List[str]:
        """Domains currently open (serving degraded) — [] when healthy."""
        return [domain for domain, attr in self.CIRCUIT_DOMAINS
                if getattr(self, attr).snapshot()["state"] == "open"]

    # -- single-trace, reference-shaped API --------------------------------
    def Match(self, trace_json: str) -> str:
        trace = json.loads(trace_json)
        result = self.match_many([trace])[0]
        if isinstance(result, MatchRuns):
            # columnar writer: JSON bytes straight from the run columns,
            # byte-identical to json.dumps of the materialised dict
            return render_segments_json(result.cols, result.lo, result.hi,
                                        result.mode)
        return json.dumps(result, separators=(",", ":"))

    # -- batched hot path --------------------------------------------------
    def prepare(self, points: Sequence[dict],
                params: Optional[MatchParams] = None):
        """Host prep (candidates + route tensors) for one trace — the
        single owner of the native-vs-numpy dispatch; bench and tests use
        this instead of re-implementing the branch."""
        params = params if params is not None else self.params
        if self.runtime is not None:
            return prepare_trace(self.net, None, points, params,
                                 runtime=self.runtime)
        return prepare_trace(self.net, self.grid, points, params,
                             self.route_cache)

    def match_many(self, traces) -> List[dict]:
        """Match a batch of traces; returns match dicts in order.

        ``traces`` is either a columnar :class:`TraceBatch` (the zero-dict
        hot path — the service, streaming worker, pipeline and bench all
        ingest straight into one) or a sequence of request dicts
        ({"uuid", "trace": [{lat, lon, time, ...}], "match_options"}),
        converted to columns once at this edge. Per-trace match_options
        may override params (reference: generate_test_trace.py:45-52); a
        TraceBatch with one shared options dict resolves params once for
        the whole batch.

        Chunked dispatch pipeline: the main thread runs host prep (one
        native call per chunk when the C++ runtime is present — zero
        per-trace Python) and hands each prepared chunk to two
        single-worker FIFO lanes: the dispatch lane runs decode dispatch
        + async d2h (so the device queue stays fed and, over a TPU
        tunnel, h2d transfers stream off the main thread), the drain
        lane runs the d2h wait + assembly. Chunk N's decode therefore
        overlaps prep of chunk N+1 AND assembly of chunk N-1.
        REPORTER_TPU_PIPELINE=0 runs both stages inline for a serialized
        per-stage breakdown.
        """
        tb = as_trace_batch(traces)
        ntr = len(tb)
        opts = tb.options
        if opts is None:
            per_trace_params = [self.params] * ntr
        elif isinstance(opts, dict):
            per_trace_params = [self.params.with_options(opts)] * ntr
        else:
            per_trace_params = [
                self.params.with_options(o) if o else self.params
                for o in opts]

        # deferred: importing at module level would cycle through
        # ops -> pallas_viterbi -> matcher.hmm -> matcher/__init__
        from ..ops import batch_pad_multiple, decode_batch

        chunk = _decode_chunk()
        # pad the batch dim to the mesh's data-axis size so decode_batch
        # takes the sharded multi-device path (filler rows are all-SKIP
        # traces that decode to nothing)
        pad = batch_pad_multiple()
        if pad:
            chunk = ((chunk + pad - 1) // pad) * pad

        results: List[Optional[dict]] = [None] * ntr
        futures = []
        if pipeline_enabled():
            def submit(batch, order, sigma, beta):
                # the device lanes run on their own threads: carry the
                # chunk's trace context over the hop so decode/assemble
                # spans parent to the chunk (None when disarmed)
                ctx = obs_trace.current()
                d_fut = self._dispatch_pool.submit(
                    self._lane_stage, ctx, self._dispatch_stage, batch,
                    sigma, beta, decode_batch)
                futures.append((d_fut, self._drain_pool.submit(
                    self._lane_stage, ctx, self._drain_stage, batch,
                    order, d_fut, per_trace_params, results, tb)))
        else:
            def submit(batch, order, sigma, beta):
                decoded = self._dispatch_stage(batch, sigma, beta,
                                               decode_batch)
                self._drain_stage(batch, order, decoded,
                                  per_trace_params, results, tb)

        try:
            if self.runtime is not None:
                self._dispatch_native(tb, per_trace_params, chunk, pad,
                                      submit)
            else:
                self._dispatch_fallback(tb, per_trace_params, chunk,
                                        pad, submit)
        except BaseException:
            # a prep-phase failure must quiesce the lanes before it
            # propagates: later chunks must not keep decoding discarded
            # work into the next call (shared FIFO lanes, shared timers).
            # Two passes: cancel EVERYTHING still queued first (waiting
            # pair-by-pair would let the single-worker lanes dequeue and
            # run later chunks to completion), then wait out whatever had
            # already started.
            running = [f for pair in futures for f in reversed(pair)
                       if not f.cancel()]
            for f in running:
                try:
                    f.result()
                except BaseException:
                    pass
            raise
        # drain EVERY chunk, then surface the first failure in
        # submission order (matches the inline path's raise point); a
        # dispatch-lane error re-raises out of its drain future, so the
        # drain futures cover both lanes
        first_err = None
        for _d_fut, a_fut in futures:
            try:
                a_fut.result()
            except BaseException as e:
                if first_err is None:
                    first_err = e
        if first_err is not None:
            raise first_err
        return results

    def match_incremental(self, traces) -> List[Optional[dict]]:
        """Match via carried per-trace decode state where possible.

        Same input contract as :meth:`match_many`, but each trace with a
        uuid advances its carried decode state by the points appended
        since its last report — O(K) device work per appended point
        instead of a whole-window re-decode. Returns match dicts in
        order with ``None`` for every trace the incremental path did not
        serve (no uuid, kill switch/pressure shed, open circuit, parity
        fallback, eviction, error) — callers route those through
        :meth:`match_many`, whose output is byte-identical by
        construction (tests/test_incremental.py pins this).
        """
        from . import incremental as _inc
        tb = as_trace_batch(traces)
        ntr = len(tb)
        results: List[Optional[dict]] = [None] * ntr
        if ntr == 0:
            return results
        if not _inc.incremental_enabled() or _inc.pressure_shed():
            if self._incremental_table is not None:
                self._incremental_table.clear()
            return results
        if not self.circuit_incremental.allow():
            metrics.count("match.incremental.circuit_skips")
            return results
        opts = tb.options
        if opts is None:
            per_trace_params = [self.params] * ntr
        elif isinstance(opts, dict):
            per_trace_params = [self.params.with_options(opts)] * ntr
        else:
            per_trace_params = [
                self.params.with_options(o) if o else self.params
                for o in opts]
        try:
            with metrics.timer("match.incremental.advance"):
                failures = self.incremental_table.match_many(
                    tb, per_trace_params, results)
        except Exception as e:
            self.circuit_incremental.record_failure()
            logger.warning("incremental match failed (%s); the batch "
                           "path serves this report", e)
            return [None] * ntr
        if failures:
            self.circuit_incremental.record_failure()
        else:
            self.circuit_incremental.record_success()
        return results

    @staticmethod
    def _lane_stage(ctx, fn, *args):
        """Run one device-lane stage under a captured trace context (the
        executor hop drops the submitter's contextvars)."""
        with obs_trace.attach(ctx):
            return fn(*args)

    def _dispatch_stage(self, batch, sigma, beta, decode_batch):
        """Dispatch lane: decode dispatch + async d2h for one chunk.
        Returns the in-flight device array without waiting on it, so the
        next chunk's dispatch isn't gated on this one's results. The
        profiler span attributes any XLA compile this dispatch pays to
        the chunk's (B, T, K) shape — the compile-telemetry tap.

        Failure domain: a dispatch that raises (device lost, compile
        failure, injected ``decode.dispatch`` fault) degrades THAT chunk
        to the per-trace numpy oracle and counts a ``circuit_decode``
        failure; enough consecutive failures open the circuit and later
        chunks skip the device entirely until a half-open probe
        succeeds — the decode twin of the native-prep breaker."""
        B, T, K = batch.dist_m.shape
        with metrics.timer("matcher.decode_dispatch"), \
                profiler.dispatch_span(B, T, K):
            # deferred device routes (prepare_batch defer_routes): sync
            # the in-flight tensor + settle the wire dtype HERE, on the
            # decode lane, so the prep stage stayed dispatch-only. Every
            # consumer below (device decode, numpy oracle, pressure
            # ladder) reads the finalised tensors.
            batch.finalize_wire()
            if _pressure_oracle:
                # the ladder's last rung: identical results (the oracle
                # is the breaker's fallback, bit-identical on scan),
                # device left to the recovery drain
                metrics.count("pressure.oracle_chunks")
                return self._decode_numpy_chunk(batch, sigma, beta)
            if not self.circuit_decode.allow():
                metrics.count("matcher.circuit.decode.fallback_chunks")
                return self._decode_numpy_chunk(batch, sigma, beta)
            try:
                faults.failpoint("decode.dispatch")
                decoded, _scores = decode_batch(
                    batch.dist_m, batch.valid, batch.route_m,
                    batch.gc_m, batch.case, sigma, beta)
                if hasattr(decoded, "copy_to_host_async"):
                    decoded.copy_to_host_async()
            except Exception as e:
                self.circuit_decode.record_failure()
                metrics.count("matcher.circuit.decode.errors")
                logger.warning(
                    "device decode failed for a (%d, %d, %d) chunk (%s); "
                    "decoding it via the numpy oracle", B, T, K, e)
                return self._decode_numpy_chunk(batch, sigma, beta)
            self.circuit_decode.record_success()
        return decoded

    def _decode_numpy_chunk(self, batch, sigma, beta) -> np.ndarray:
        """Degraded decode: the per-trace numpy Viterbi oracle
        (cpu_ref.viterbi_decode_numpy — the same implementation the
        shadow-accuracy sampler scores the device against) over every
        row of the chunk. Consumes the SAME prepared tensors as the
        device kernels, so on the scan backend (the single-device CPU
        default) the paths — and therefore the report bytes — are
        bit-identical (pinned by TestDecodeDomain); tie-breaks may
        differ only vs the associative-scan backend, where equal-score
        paths already diverge between device backends."""
        from .cpu_ref import viterbi_decode_numpy
        dist = np.asarray(batch.dist_m, dtype=np.float32)
        valid = np.asarray(batch.valid)
        T = dist.shape[1]
        # the native prep path carries a dead trailing time row (T rows,
        # for seq sharding); the oracle wants the documented T-1
        route = np.asarray(batch.route_m[:, :max(T - 1, 0)],
                           dtype=np.float32)
        gc = np.asarray(batch.gc_m[:, :max(T - 1, 0)], dtype=np.float32)
        case = np.asarray(batch.case)
        # rows past len(batch.traces) are all-SKIP pow2/mesh padding the
        # device batch carries; assembly never reads them (decoded[:B]),
        # so the oracle must not pay a full Viterbi per filler row —
        # degraded mode is exactly when throughput is scarcest
        out = np.zeros(dist.shape[:2], dtype=np.int32)
        for b in range(len(batch.traces)):
            out[b], _score = viterbi_decode_numpy(
                dist[b], valid[b], route[b], gc[b], case[b], sigma, beta)
        return out

    def _drain_stage(self, batch, order, decoded, per_trace_params,
                     results, tb=None) -> None:
        """Drain lane: d2h wait + assembly + result formatting for one
        chunk. ``decoded`` is the dispatch stage's device array, or a
        Future of it on the pipelined path; writes into ``results`` slots
        owned exclusively by this chunk's ``order``. ``tb`` is the call's
        TraceBatch — the source the poisoned-trace quarantine rebuilds a
        replayable request body from."""
        if hasattr(decoded, "result"):
            decoded = decoded.result()
        with metrics.timer("matcher.decode_wait"):
            decoded = np.asarray(decoded)
        # shadow-accuracy tap: maybe re-decode this chunk through the
        # numpy oracle on the profiler's background thread (sampled,
        # REPORTER_TPU_SHADOW_SAMPLE; one flag-cheap call when off)
        p0 = per_trace_params[order[0]]
        profiler.maybe_shadow(batch, decoded, len(order),
                              p0.effective_sigma, p0.beta)
        if batch.prep is not None:
            # native batched assembly: ONE call walks every decoded
            # path of this batch into run records; the results are lazy
            # MatchRuns views over ONE shared RunColumns — no per-run
            # dicts here, the serving path serialises straight from the
            # columns (render_segments_json / service report_json).
            # Failure domain: one poisoned trace used to fail the WHOLE
            # chunk here; now a failed batch call counts a
            # ``circuit_assemble`` failure and the chunk degrades to the
            # per-trace scalar assembler below, which isolates the
            # poison to its own trace.
            if self.circuit_assemble.allow():
                B = len(batch.traces)
                gp = per_trace_params[order[0]]
                try:
                    with metrics.timer("matcher.assemble"):
                        faults.failpoint("matcher.assemble")
                        runs = self.runtime.assemble_batch(
                            decoded[:B], batch.prep, batch.pt_off,
                            batch.times_flat,
                            queue_threshold_kph=gp.queue_speed_threshold_kph,
                            interpolation_distance_m=gp.interpolation_distance,
                            backward_tolerance_m=gp.backward_tolerance_m,
                            turn_penalty_factor=gp.turn_penalty_factor)
                        ro = runs["run_off"].tolist()
                        cols = RunColumns(runs)
                        # chunk wire layout for the batch writer
                        # (native.write_report_json_batch): per-trace
                        # run spans + last point times, so the FIRST
                        # /report serialisation of this chunk can emit
                        # every trace's body in one C call and the
                        # rest slice it (service/wire.py memo)
                        pt_off = np.ascontiguousarray(batch.pt_off,
                                                      dtype=np.int64)
                        cols.arrays["_run_off"] = np.ascontiguousarray(
                            runs["run_off"], dtype=np.int64)
                        cols.arrays["_trace_end"] = np.ascontiguousarray(
                            np.asarray(batch.times_flat,
                                       dtype=np.float64)[pt_off[1:] - 1])
                        for b, i in enumerate(order):
                            results[i] = MatchRuns(
                                cols, ro[b], ro[b + 1],
                                per_trace_params[i].mode)
                except Exception as e:
                    self.circuit_assemble.record_failure()
                    metrics.count("matcher.circuit.assemble.native_errors")
                    logger.warning(
                        "batched assembly failed for a %d-trace chunk "
                        "(%s); assembling it per trace", len(order), e)
                else:
                    self.circuit_assemble.record_success()
                    return
            else:
                metrics.count("matcher.circuit.assemble.fallback_chunks")
        # per-trace scalar assembly — the numpy-path default AND the
        # assemble-domain degraded mode: each trace assembles in its own
        # try, so a poisoned trace quarantines alone instead of failing
        # the chunk. order is elementwise-aligned with batch.traces (the
        # dispatchers build it that way), so row b IS trace order[b].
        with metrics.timer("matcher.assemble"):
            for b, i in enumerate(order):
                params = per_trace_params[i]
                try:
                    faults.failpoint("matcher.assemble")
                    results[i] = assemble_segments(
                        self.net, batch.traces[b], decoded[b],
                        mode=params.mode,
                        queue_threshold_kph=params.queue_speed_threshold_kph,
                        interpolation_distance_m=params.interpolation_distance,
                        backward_tolerance_m=params.backward_tolerance_m,
                        turn_penalty_factor=params.turn_penalty_factor)
                except Exception as e:
                    self._quarantine_trace(tb, int(i), e)
                    # the caller still gets a well-formed (empty) match
                    # for the poisoned slot; every other trace's bytes
                    # are unchanged (pinned by TestAssembleDomain).
                    # Dict-per-poisoned-trace is the cold quarantine
                    # path, not the per-trace steady state.
                    results[i] = {"segments": [],  # lint: ignore[HP002]
                                  "mode": params.mode}

    def _quarantine_trace(self, tb, i: int, err: Exception) -> None:
        """Spool a poisoned trace's request JSON (/report-ready — the
        dead-letter replayer re-submits it verbatim) to the trace
        dead-letter spool; best-effort, counted either way."""
        metrics.count("matcher.assemble.quarantined")
        from ..utils import spool
        root = self.quarantine_spool or spool.trace_dir()
        uuid = tb.uuid(i) if tb is not None else None
        if root is None or tb is None:
            logger.error("quarantined poisoned trace %s (%s) with no "
                         "dead-letter spool configured", uuid, err)
            return
        try:
            body = tb[i].to_request()
            # deterministic per-uuid name: when the dead-letter REPLAY
            # of this body poisons again, the re-quarantine overwrites
            # this entry instead of minting a fresh one — the drainer's
            # shared uuid budget can then converge it to .quarantine
            # rather than chase an ever-growing family of copies
            name = f"poison.{uuid or 'anon'}.json"
            path = spool.write(root, name,
                               json.dumps(body, separators=(",", ":")))
            logger.warning("quarantined poisoned trace %s -> %s (%s)",
                           uuid, path, err)
        except Exception as spool_err:  # never fail the chunk for this
            logger.error("poisoned-trace quarantine failed for %s: %s "
                         "(original error: %s)", uuid, spool_err, err)

    # every param that shapes the prepared tensors or the batched
    # assembly: traces may only share one native prep call (and one device
    # batch) when all of these agree; sigma/beta ride along because they
    # are batch-wide scalars on device
    _PREP_KEY_FIELDS = (
        "effective_sigma", "beta", "max_candidates", "search_radius",
        "interpolation_distance", "breakage_distance",
        "max_route_distance_factor", "backward_tolerance_m",
        "max_route_time_factor", "min_time_bound_s", "turn_penalty_factor",
        "queue_speed_threshold_kph")

    def _param_groups(self, per_trace_params):
        """[(params, index array)] — one group per distinct prep-param
        key, insertion-ordered. The steady state (one shared options
        dict, so one params object for the whole batch) is an identity
        scan, no per-trace key tuples."""
        ntr = len(per_trace_params)
        if ntr == 0:
            return []
        p0 = per_trace_params[0]
        if all(p is p0 for p in per_trace_params):
            return [(p0, np.arange(ntr, dtype=np.int64))]
        keyed: dict[tuple, tuple] = {}
        for i, p in enumerate(per_trace_params):
            key = tuple(getattr(p, f) for f in self._PREP_KEY_FIELDS)
            got = keyed.get(key)
            if got is None:
                keyed[key] = (p, [i])
            else:
                got[1].append(i)
        return [(p, np.asarray(idxs, dtype=np.int64))
                for p, idxs in keyed.values()]

    def _dispatch_native(self, tb: TraceBatch, per_trace_params, chunk,
                         pad, submit):
        """Hot path: group by prep params, bucket by raw length
        (vectorised), then ONE rt_prepare_batch call per chunk on this
        thread — the chunk's flat coordinate columns pass straight from
        the TraceBatch to the native call, zero per-point Python —
        handing each prepared batch to ``submit`` (the device lanes).

        Failure domain: each chunk consults the circuit breaker. A
        native prep error degrades THAT chunk to the numpy path (the
        caller still gets every result) and counts a breaker failure;
        enough consecutive failures open the circuit and subsequent
        chunks skip native entirely until a half-open probe succeeds.
        """
        workers = max(1, _prep_workers())
        buckets = np.asarray(bucket_ladder()[0], dtype=np.int64)
        raw_counts = np.diff(tb.offsets)  # per-trace raw point counts
        # bucket by RAW length (kept length is only known after the
        # native prep; raw is an upper bound, so a jitter-heavy trace
        # may decode in a larger bucket — same decoded path, the SKIP
        # tail is inert)
        Ts = buckets[np.minimum(
            np.searchsorted(buckets, np.maximum(tb.lengths(), 1)),
            len(buckets) - 1)]
        ci = 0  # chunk index across the whole call, a span attribute
        for params, idxs in self._param_groups(per_trace_params):
            sigma = np.float32(params.effective_sigma)
            beta = np.float32(params.beta)
            for T0 in np.unique(Ts[idxs]).tolist():
                group = idxs[Ts[idxs] == T0]
                for T, bucket in self._split_bucket(int(T0), group,
                                                    raw_counts, pad,
                                                    chunk):
                    for lo in range(0, len(bucket), chunk):
                        part = bucket[lo:lo + chunk]
                        # part itself is the order: _drain_stage only
                        # enumerates it, so no per-chunk list conversion
                        # (reporter-lint HP003)
                        order = part
                        rows = padded_batch_rows(len(part), pad)
                        with obs_trace.span("matcher.chunk", chunk=ci,
                                            traces=len(part), T=int(T)):
                            ci += 1
                            if not self.circuit.allow():
                                metrics.count(
                                    "matcher.circuit.fallback_chunks")
                                self._submit_numpy_chunk(
                                    tb, part, params, pad, submit,
                                    sigma, beta)
                                continue
                            try:
                                with metrics.timer("matcher.prep"):
                                    faults.failpoint("native.prep")
                                    batch = prepare_batch(
                                        self.runtime, tb.gather(part),
                                        params, int(T), pad_rows=rows,
                                        n_threads=workers,
                                        route_kernel=self
                                        ._device_route_kernel(),
                                        route_circuit=self.circuit_route,
                                        # device-resident route tensor:
                                        # the decode stage pays the sync
                                        # (finalize_wire), overlapped
                                        # with the next chunk's prep
                                        defer_routes=True)
                            except Exception as e:
                                self.circuit.record_failure()
                                metrics.count(
                                    "matcher.circuit.native_errors")
                                logger.warning(
                                    "native prep failed for a %d-trace "
                                    "chunk (%s); serving it via the "
                                    "numpy fallback", len(part), e)
                                self._submit_numpy_chunk(
                                    tb, part, params, pad, submit,
                                    sigma, beta)
                                continue
                            self.circuit.record_success()
                            # the chunk's wide event: occupancy vs the
                            # padded (rows, T) grid, memo state, queue
                            # depth — one call per CHUNK, not per trace
                            profiler.chunk_event(
                                bucket_T=int(T), K=params.max_candidates,
                                traces=len(part),
                                rows=int(batch.case.shape[0]),
                                kept_points=kept_point_count(batch),
                                raw_points=int(raw_counts[part].sum()),
                                cache=self.runtime.route_memo_stats(),
                                path="native")
                            submit(batch, order, sigma, beta)

    @staticmethod
    def _padded_cells(n: int, pad, T: int, chunk) -> int:
        """Point cells ``n`` traces of bucket ``T`` actually decode as,
        chunked exactly as the dispatch loop chunks them — each chunk
        re-pays its own mesh-multiple + pow2 row padding."""
        cells = 0
        while n > 0:
            take = min(n, chunk) if chunk else n
            cells += padded_batch_rows(take, pad) * T
            n -= take
        return cells

    @staticmethod
    def _split_bucket(T: int, group, raw_counts, pad=None, chunk=None):
        """The occupancy-driven adaptive splitter: ``[(sub_T, index
        array)]`` for one ladder-bucket group, ``[(T, group)]`` when no
        split pays. A split breaks a mixed-length group into per-pow2-
        bucket sub-batches (per-trace smallest power of two >= raw
        length, clipped to [ladder floor, T]) when the padding waste of
        decoding everything at T exceeds the ladder's threshold —
        consulting the RECORDED per-bucket waste from PR 8's wide
        events (profiler.bucket_waste) once chunks of this T have been
        measured, and a projection from this group's raw lengths before
        that (kept <= raw, so the projection under-states waste and
        never over-splits). ``pad`` is the mesh row multiple: a split
        only happens when the total padded point cells ACROSS the
        sub-batches — each re-paying mesh-multiple + pow2 ROW padding —
        actually drop, so splitting can never trade tail pad for worse
        filler-row pad (a 4-trace sub-batch on an 8-wide mesh pads
        right back to 8 rows). Decoded paths are unchanged — the SKIP
        tail is inert, pinned byte-identical by
        tests/test_sharded_decode.py — and the shape cost is bounded:
        sub-buckets are powers of two, each new (rows, T) pair is ONE
        compile episode, and a second compile of the same shape still
        trips the storm counter."""
        ladder, thresh = bucket_ladder()
        if thresh >= 1.0 or len(group) < 2 or T <= int(ladder[0]):
            return [(T, group)]
        raws = np.minimum(raw_counts[group], T)
        # decision waste = max(projected, recorded). The projection
        # uses the same denominator the recorded waste does — PADDED
        # rows chunked exactly as dispatch will chunk them (mesh
        # multiple + pow2 filler counts as waste there too) — with
        # kept <= raw in the numerator, so it under-states and never
        # over-splits; the recorded per-bucket number (PR 8's wide
        # events) catches what the projection can't see (kept << raw
        # on jitter-heavy streams). max, not recorded-first: after a
        # split, the low-waste SUB-chunks record under this same T
        # and a recorded-first read would oscillate
        # (split -> record low -> stop splitting -> record high -> ...)
        cells_unsplit = SegmentMatcher._padded_cells(len(group), pad, T,
                                                     chunk)
        waste = 1.0 - float(raws.sum()) / cells_unsplit
        recorded = profiler.bucket_waste(T)
        if recorded is not None:
            waste = max(waste, recorded)
        if waste <= thresh:
            return [(T, group)]
        subTs = np.minimum(np.maximum(
            np.exp2(np.ceil(np.log2(np.maximum(raws, 1))))
            .astype(np.int64), int(ladder[0])), T)
        uniq, counts = np.unique(subTs, return_counts=True)
        if uniq.tolist() == [T]:
            return [(T, group)]
        cells_split = int(sum(
            SegmentMatcher._padded_cells(int(c), pad, int(s), chunk)
            for s, c in zip(uniq.tolist(), counts.tolist())))
        if cells_split >= cells_unsplit:
            return [(T, group)]
        metrics.count("decode.bucket.split")
        return [(int(s), group[subTs == s]) for s in uniq.tolist()]

    def _submit_numpy_chunk(self, tb: TraceBatch, part, params, pad,
                            submit, sigma, beta) -> None:
        """Prep ONE chunk through the numpy path and hand its packed
        batches to the device lanes — the degraded lane the circuit
        breaker routes native chunks through, and the inner step of
        ``_dispatch_fallback``. Contract identical to native prep
        (results pinned byte-equal by tests/test_report_writer.py)."""
        with metrics.timer("matcher.prep"):
            prepped = prepare_traces_numpy(
                self.net, self.grid, tb.gather(part), params,
                self.route_cache)
        # chunk-granular identity bookkeeping on the numpy fallback
        # path (one small dict per chunk, not per point)
        idx_of = {id(p): i for p, i in zip(prepped, part)}
        for batch in pack_batches(prepped, pad_batch_to=pad,
                                  pad_pow2=True):
            # rows of a packed batch align with its traces list, so
            # order[b] is the global index of batch.traces[b]
            order = [idx_of[id(p)] for p in batch.traces]
            profiler.chunk_event(
                bucket_T=int(batch.case.shape[1]),
                K=params.max_candidates, traces=len(order),
                rows=int(batch.case.shape[0]),
                kept_points=kept_point_count(batch),
                raw_points=int(sum(p.num_raw for p in batch.traces)),
                cache=_route_cache_counters(), path="numpy")
            submit(batch, order, sigma, beta)

    def _dispatch_fallback(self, tb: TraceBatch, per_trace_params, chunk,
                           pad, submit):
        """numpy prep path (no native library): whole-chunk vectorised
        candidate search + per-trace route tensors through the shared
        cross-batch route cache, then pack_batches — same contract as the
        native path, slower."""
        ci = 0
        for params, idxs in self._param_groups(per_trace_params):
            sigma = np.float32(params.effective_sigma)
            beta = np.float32(params.beta)
            for lo in range(0, len(idxs), chunk):
                part = idxs[lo:lo + chunk]
                with obs_trace.span("matcher.chunk", chunk=ci,
                                    traces=len(part)):
                    ci += 1
                    self._submit_numpy_chunk(tb, part, params, pad,
                                             submit, sigma, beta)
